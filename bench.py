"""Benchmark: Reed-Solomon parity encode + decode throughput per chip.

Measures the BASELINE.md target metric: parity-encode GiB/s (and
decode-with-4-erasures GiB/s) at d=10, p=4, 1 MiB chunks, batch=128 parts
per dispatch, on the default JAX device (the real TPU chip under the
driver).  Device-resident sustained throughput is measured with an
on-device fori_loop so per-dispatch RPC/transfer overhead of the tunneled
dev environment does not pollute the kernel number; the end-to-end
dispatch rate is reported alongside on stderr.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "GiB/s", "vs_baseline": N/5.0}
vs_baseline is against the 5 GiB/s single-chip north star (BASELINE.md;
the reference's CPU SIMD crate does ~1-6 GiB/s/core and publishes no
numbers).
"""

import json
import sys
import time

import numpy as np


# Last driver-captured device record (BENCH_r03.json): lets an outage
# record distinguish "environment down" from "perf regression".
_LAST_GOOD = {"round": 3, "encode_gibps": 54.66, "decode_gibps": 54.47}


def _outage_record(metric: str) -> str:
    """The structured line emitted when the tunnel never answers: keeps
    the driver-parsed fields (metric/value/unit/vs_baseline) AND marks
    the failure as an environment outage with the last authoritative
    number, so a 0.0 here is never mistaken for a regression."""
    return json.dumps({
        "metric": metric,
        "value": 0.0, "unit": "GiB/s", "vs_baseline": 0.0,
        "error": "device init timeout (tpu tunnel unreachable)",
        "tunnel_down": True,
        "last_good": _LAST_GOOD,
    })


def nproc() -> int:
    import os

    return os.cpu_count() or 1


def _env_shrink(name: str, default: float) -> float:
    """Test-seam env override that can only SHRINK ``default``:
    malformed, non-positive, or larger values fall back, so inherited
    variables can't break the bench's timing/output contract."""
    import os

    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        v = float(raw)
    except ValueError:
        return default
    return v if 0 < v < default else default


def _probe_device(timeout_s: float) -> str:
    """PJRT init probe in a throwaway subprocess: when the tunnel is
    down, jax.devices() blocks forever and cannot be interrupted
    in-process, so the only safe pre-flight (and the only way retries
    can exist at all) is a killable child.  On a healthy tunnel the
    probe costs one extra init (~20-40 s) per bench run — accepted:
    a round's device record is worth more (VERDICT r4).

    Returns "" on success, "timeout" on a hang, else the child's
    stderr tail — a crash (broken install, PJRT abort) must surface as
    itself, not be recorded as a tunnel outage.

    Test seams (tests/test_bench_outage.py): the child's program and
    the per-probe timeout are env-overridable so the hang/crash paths
    can be exercised in milliseconds without a real tunnel.  The seams
    can only SHRINK budgets (and a malformed value is ignored), so an
    inherited variable can neither crash the one-JSON-line contract
    nor push the worst case past the 405s the driver cap is sized
    for."""
    import os
    import subprocess

    prog = os.environ.get("CHUNKY_BITS_TPU_BENCH_PROBE_PY",
                          "import jax; jax.devices()")
    timeout_s = _env_shrink("CHUNKY_BITS_TPU_BENCH_PROBE_SECS",
                            timeout_s)
    try:
        r = subprocess.run(
            [sys.executable, "-c", prog],
            timeout=timeout_s, capture_output=True)
    except subprocess.TimeoutExpired:
        return "timeout"
    if r.returncode == 0:
        return ""
    return "probe rc=%d: %s" % (
        r.returncode, r.stderr.decode(errors="replace")[-500:])


def _device_init_watchdog(metric: str):
    """Device-init guard: the tunneled dev chip's PJRT client blocks
    indefinitely when the tunnel endpoint is down (observed rounds 3-4:
    multi-hour outages; even jax.devices() hangs).

    Two layers: (1) bounded subprocess probes with backoff — a
    transient blip costs a retry, not the round's device record;
    (2) the in-process backstop watchdog, because the tunnel can die
    between a green probe and the main process's own init.  Both exits
    emit the structured outage record.  Returns the Event the caller
    must ``set()`` once the device has answered (first compile/dispatch
    done); every bench path that can touch a device must arm this."""
    import os
    import threading

    # Bench owns outage handling: the library's bounded degrade-to-CPU
    # (ops/jax_backend.py; both the init wait and the per-dispatch
    # guard) would silently record CPU throughput as the device metric,
    # so force both off — even an inherited env value (e.g. the
    # SKILL.md e2e recipe's 15s) must not re-enable them — and let THIS
    # watchdog's structured record fire instead.
    from chunky_bits_tpu.ops.jax_backend import (DEVICE_INIT_TIMEOUT_ENV,
                                                 DISPATCH_TIMEOUT_ENV)

    os.environ[DEVICE_INIT_TIMEOUT_ENV] = "0"
    os.environ[DISPATCH_TIMEOUT_ENV] = "0"

    # Probe budget: 3 x 120s + 15s + 30s backoff = 405s worst case —
    # deliberately under the old watchdog's 600s so the structured
    # outage record always lands inside any driver-side cap sized for
    # the previous behavior.  120s comfortably covers a healthy cold
    # init (~20-40s).
    fail = ""
    for attempt in range(3):
        fail = _probe_device(120)
        if not fail:
            break
        if fail != "timeout":
            # a crashing child is a deterministic code/env defect, not a
            # transient tunnel outage — surface it now, don't backoff
            print(json.dumps({
                "metric": metric, "value": 0.0, "unit": "GiB/s",
                "vs_baseline": 0.0, "error": fail}), flush=True)
            sys.exit(3)
        if attempt < 2:
            delay = 15 * (attempt + 1) * _env_shrink(
                "CHUNKY_BITS_TPU_BENCH_BACKOFF_SCALE", 1.0)
            print(f"# device probe {attempt + 1}/3 timed out; retrying "
                  f"in {delay:g}s", file=sys.stderr, flush=True)
            time.sleep(delay)
    else:
        print(_outage_record(metric), flush=True)
        sys.exit(3)

    ready = threading.Event()

    def watchdog() -> None:
        if not ready.wait(600):
            print(_outage_record(metric), flush=True)
            os._exit(3)

    threading.Thread(target=watchdog, daemon=True).start()
    return ready


def _arm_if_device_backend(backend, metric: str):
    """Arm the init watchdog when the effective backend spec resolves to
    a device backend ("jax"/"jax:...", explicitly or via
    $CHUNKY_BITS_TPU_BACKEND) — those are the paths that can block
    forever in PJRT init.  Returns the armed Event, or None (CPU
    backends can't hang on device init)."""
    import os

    effective = backend or os.environ.get("CHUNKY_BITS_TPU_BACKEND") or ""
    if effective.split(":", 1)[0] != "jax":
        return None
    return _device_init_watchdog(metric)


def marginal_seconds(body_fn, x, iters: int) -> float:
    """Marginal per-iteration device time of ``body_fn`` inside an
    on-device loop, measured as a difference across loop lengths so
    constant per-dispatch overhead (and anything XLA hoists) cancels.
    The loop body is made iteration-dependent by XORing the scalar
    carry into the input — a cheap, unhoistable pass whose cost the
    caller measures once with ``body_fn=lambda y: y`` and subtracts.
    Returns -1.0 when the two slopes disagree (non-linear scaling —
    the measurement is invalid).  Shared by bench.py and exp_packed.py
    so A/B numbers from the two scripts stay comparable."""
    import jax
    import jax.numpy as jnp

    iters = max(2, iters)  # n1 == n2 at iters=1 -> zero-division below

    def make(n):
        def loop(x):
            def body(i, acc):
                y = x ^ (acc & 0xFF).astype(jnp.uint8)
                out = body_fn(y)
                return acc + out[i % x.shape[0], 0, ::4096].astype(
                    jnp.uint32).sum()
            return jax.lax.fori_loop(0, n, body, jnp.uint32(0))
        return jax.jit(loop)

    def best_time(f):
        int(f(x))  # compile + warm
        best = float("inf")
        for _ in range(3):
            t0 = time.time()
            int(f(x))
            best = min(best, time.time() - t0)
        return best

    n1, n2, n3 = max(1, iters // 5), iters, 2 * iters
    t1, t2, t3 = (best_time(make(n)) for n in (n1, n2, n3))
    m12 = (t2 - t1) / (n2 - n1)
    m23 = (t3 - t2) / (n3 - n2)
    if m12 <= 0 or m23 <= 0 or not (0.4 <= m12 / m23 <= 2.5):
        print(f"# warning: non-linear loop scaling "
              f"(m12={m12 * 1e3:.3f}ms m23={m23 * 1e3:.3f}ms)",
              file=sys.stderr)
        return -1.0
    return (t3 - t1) / (n3 - n1)


def main() -> None:
    ready = _device_init_watchdog("rs_parity_encode_gibps")

    import jax
    import jax.numpy as jnp

    from chunky_bits_tpu.ops import matrix
    from chunky_bits_tpu.ops.backend import ErasureCoder, NumpyBackend
    from chunky_bits_tpu.ops.jax_backend import JaxBackend

    d, p = 10, 4
    size = 1 << 20  # 1 MiB chunks
    on_accel = jax.default_backend() != "cpu"
    ready.set()  # backends initialized; the tunnel answered
    batch = 128 if on_accel else 4
    iters = 10 if on_accel else 2

    backend = JaxBackend()
    enc = matrix.build_encode_matrix(d, p)
    parity_rows = enc[d:]
    # decode: shards 0,1 (data) and 12,13 (parity) erased
    present = list(range(2, 12))
    dec_rows = matrix.decode_matrix(enc, present, [0, 1, 12, 13])

    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (batch, d, size), dtype=np.uint8)

    def device_apply(mat):
        if on_accel:
            from chunky_bits_tpu.ops.pallas_kernels import \
                apply_matrix_pallas

            return lambda x: apply_matrix_pallas(mat, x)
        from chunky_bits_tpu.ops import gf256
        from chunky_bits_tpu.ops.bitplane import apply_bitplane

        m2 = jnp.asarray(
            gf256.expand_to_bit_matrix(mat).astype(np.float32),
            dtype=jnp.bfloat16)
        return lambda x: apply_bitplane(m2, x)

    def _marginal_seconds(body_fn, x) -> float:
        return marginal_seconds(body_fn, x, iters)

    _xor_cost_cache: dict[tuple, float] = {}

    def sustained_gibps(apply_fn, x) -> float:
        """Marginal throughput of ``apply_fn`` over ``x[B, K, S]`` with
        the XOR-loop carrier cost (measured once per input shape)
        subtracted; 0.0 when either measurement is invalid."""
        shape = tuple(x.shape)
        if shape not in _xor_cost_cache:
            _xor_cost_cache[shape] = _marginal_seconds(lambda y: y, x)
        xor_cost = _xor_cost_cache[shape]
        total = _marginal_seconds(apply_fn, x)
        if total < 0 or xor_cost < 0 or total <= xor_cost:
            return 0.0
        kernel = total - xor_cost
        b, k, s = shape
        return b * k * s / kernel / (1 << 30)

    x = jnp.asarray(data)

    # correctness gate: the benched kernel must match the CPU oracle
    small = data[:1, :, :8192]
    want = ErasureCoder(d, p, NumpyBackend()).encode_batch(small)
    got = backend.apply_matrix(parity_rows, small)
    if not np.array_equal(want, got):
        print(json.dumps({"metric": "rs_parity_encode_gibps",
                          "value": 0.0, "unit": "GiB/s",
                          "vs_baseline": 0.0,
                          "error": "byte-identity failed"}))
        sys.exit(1)

    encode_gibps = sustained_gibps(device_apply(parity_rows), x)

    # decode-with-4-erasures: x [B, 10, S] stands in for the survivors
    decode_gibps = sustained_gibps(device_apply(dec_rows), x)

    # Wide geometry d=16 p=8: the occupancy model
    # (pallas_kernels.py:34-43) says d=10 p=4's [K8, R8] = [80, 32]
    # weight tile caps MXU cell occupancy at 15.6% and predicts the
    # fix is geometry, not kernel: K8 = 128 and (with the kernel's two
    # parts per grid cell) 2*R8 = 128 fill the array -> ~3.2x the
    # per-cell-streaming throughput if the model is right.  Measured
    # here on-chip to confirm or correct it (accel only: the CPU
    # fallback would double an already-slow run for no signal).
    wide_gibps = None  # None = not attempted/invalid -> key omitted
    if on_accel:
        d16, p8, b16 = 16, 8, 64
        enc16 = matrix.build_encode_matrix(d16, p8)
        data16 = rng.integers(0, 256, (b16, d16, size), dtype=np.uint8)
        small16 = data16[:1, :, :8192]
        want16 = ErasureCoder(d16, p8, NumpyBackend()).encode_batch(
            small16)
        got16 = backend.apply_matrix(enc16[d16:], small16)
        if not np.array_equal(want16, got16):
            print("# wide-geometry byte-identity FAILED; skipping",
                  file=sys.stderr)
        else:
            from chunky_bits_tpu.ops.pallas_kernels import \
                apply_matrix_pallas

            rows16 = enc16[d16:]
            # the 1 GiB transfer happens only after the gate passed
            x16 = jnp.asarray(data16)
            wide_gibps = sustained_gibps(
                lambda y: apply_matrix_pallas(rows16, y), x16) or None
            del x16  # free HBM before the e2e dispatch measurement
        del data16

    # end-to-end dispatch rate (includes per-call host overhead)
    apply_fn = device_apply(parity_rows)
    f1 = jax.jit(lambda x: apply_fn(x).astype(jnp.uint32).sum())
    int(f1(x))
    t0 = time.time()
    vals = [f1(x) for _ in range(4)]
    _ = [int(v) for v in vals]
    e2e = 4 * batch * d * size / (time.time() - t0) / (1 << 30)

    if wide_gibps is not None and encode_gibps > 0:
        wide_note = (f" | wide d16p8 encode: {wide_gibps:.1f} GiB/s "
                     f"({wide_gibps / encode_gibps:.2f}x vs d10p4)")
    else:
        wide_note = ""
    print(
        f"# d={d} p={p} chunk=1MiB batch={batch} device="
        f"{jax.devices()[0]}\n"
        f"# encode sustained: {encode_gibps:.1f} GiB/s | decode(4 erasures)"
        f" sustained: {decode_gibps:.1f} GiB/s | e2e dispatch: "
        f"{e2e:.1f} GiB/s{wide_note}",
        file=sys.stderr,
    )
    # if the loop measurement refused to report (hoist suspicion), fall
    # back to the conservative dispatch-rate number
    value = encode_gibps if encode_gibps > 0 else e2e
    print(json.dumps({
        "metric": "rs_parity_encode_gibps_d10p4_1mib_b" + str(batch),
        "value": round(value, 2),
        "unit": "GiB/s",
        "vs_baseline": round(value / 5.0, 2),
        "decode_4_erasures_gibps": round(decode_gibps, 2),
        "e2e_dispatch_gibps": round(e2e, 2),
        # omitted (not 0.0) when skipped or invalid, so a CPU-fallback
        # run can't read as a wide-geometry perf collapse
        **({"wide_encode_gibps_d16p8_b64": round(wide_gibps, 2)}
           if wide_gibps is not None else {}),
    }))


def bench_cpu_reference() -> None:
    """BASELINE.md config 1: the CPU oracle on the reference's default
    geometry (d=3 p=2, 1 MiB chunks) — the number the TPU path is
    compared against.  Single JSON line on stdout."""
    from chunky_bits_tpu.ops import matrix
    from chunky_bits_tpu.ops.backend import get_backend

    d, p, size, batch = 3, 2, 1 << 20, 64
    backend = get_backend("native")
    enc = matrix.build_encode_matrix(d, p)
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (batch, d, size), dtype=np.uint8)
    backend.apply_matrix(enc[d:], data)  # warm (thread pool, tables)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        backend.apply_matrix(enc[d:], data)
        best = min(best, time.perf_counter() - t0)
    gib = batch * d * size / best / (1 << 30)
    print(json.dumps({
        "metric": "cpu_native_parity_encode_gibps_d3p2_1mib",
        "value": round(gib, 2), "unit": "GiB/s",
        "vs_baseline": round(gib / 5.0, 2),
    }))


def bench_cp_pipeline(argv: list) -> None:
    """BASELINE.md config 2 as written: a multi-GiB stream through the
    real ``FileWriteBuilder`` pipeline (staging, batched encode+hash,
    ordered part assembly) with VoidDestination, batch=256 parts/step,
    d=10 p=4, 1 MiB chunks.  Reports e2e GiB/s and parts/dispatch.

    Flags: ``--gib N`` stream size (default 1), ``--backend X`` (default
    jax), ``--batch N`` (default 256 per BASELINE.md:32), ``--no-hash``
    to skip per-shard SHA-256 — on a 1-core host the hash caps the
    full pipeline at ~1.8 GiB/s (a host-core artifact, not a design
    signal), so --no-hash isolates the staging + device-encode pipeline
    the config exists to measure.  ``--threads N`` pins the host plane
    to N total threads (``native:N`` codec + an N-worker HostPipeline);
    ``--sweep-threads 1,2[,4...]`` runs the whole measurement once per N
    and prints one JSON line each — the host-scaling harness for the
    full streamed-ingest pipeline (the config-4 sweep covers only the
    batcher's compute core).  NOTE: under the tunneled dev chip,
    host->device bandwidth is ~25 MiB/s, so the jax backend here is
    tunnel-bound (see BASELINE.md "tunnel ceiling"); on co-located TPU
    hardware the same path rides PCIe/ICI."""
    import asyncio

    from chunky_bits_tpu.file.writer import FileWriteBuilder
    from chunky_bits_tpu.ops.batching import EncodeHashBatcher

    def flag(name, default, cast):
        if name in argv:
            return cast(argv[argv.index(name) + 1])
        return default

    gib = flag("--gib", 1.0, float)
    backend = flag("--backend", "jax", str)
    batch = flag("--batch", 256, int)
    stage = flag("--stage", 8, int)
    no_hash = "--no-hash" in argv
    threads = flag("--threads", None, str)
    sweep = flag("--sweep-threads", None, str)
    if threads and sweep:
        print("--threads and --sweep-threads conflict; pick one",
              file=sys.stderr)
        sys.exit(2)
    # each sweep entry pins TOTAL host threads: the native:N codec cap
    # plus an N-worker HostPipeline, so N=1 really is one host thread
    # and the N=1 vs N=2 A/B measures core scaling, not oversubscription
    thread_list = ([int(x) for x in sweep.split(",")] if sweep
                   else [int(threads)] if threads else [None])
    # --src file: materialize the stream to a temp file and ingest via
    # aio.FileReader — engages the writer's zero-copy mmap view path,
    # i.e. the real `cp local-file cluster#x` shape.  Default "cyclic"
    # streams synthetic bytes through readinto (socket/pipe shape).
    src = flag("--src", "cyclic", str)

    d, p, chunk = 10, 4, 1 << 20
    part_bytes = d * chunk
    total = int(gib * (1 << 30)) // part_bytes * part_bytes

    blob = np.random.default_rng(0).integers(
        0, 256, 16 * part_bytes, dtype=np.uint8).tobytes()

    blob_view = memoryview(blob)

    class CyclicReader:
        """Constant-memory synthetic stream: serves views of one blob.
        ``readinto`` lands bytes straight in the writer's staging block
        (one source copy), like a real file/socket reader would."""

        def __init__(self, total_bytes: int):
            self.remaining = total_bytes
            self.off = 0

        async def read(self, n: int = -1) -> bytes:
            if self.remaining <= 0:
                return b""
            if n < 0:
                n = 1 << 20
            n = min(n, self.remaining, len(blob) - self.off)
            data = blob[self.off:self.off + n]
            self.off = (self.off + n) % len(blob)
            self.remaining -= n
            return data

        async def readinto(self, mem) -> int:
            if self.remaining <= 0:
                return 0
            n = min(len(mem), self.remaining, len(blob) - self.off)
            mem[:n] = blob_view[self.off:self.off + n]
            self.off = (self.off + n) % len(blob)
            self.remaining -= n
            return n

    class NoHashBatcher(EncodeHashBatcher):
        """Parity on the device, zero digests: isolates the pipeline
        from the 1-core host SHA bound.  Only the per-dispatch codec
        call is replaced, so the parent's merge policy and dispatch
        counting stay byte-for-byte comparable to the hash-on run."""

        def _encode(self, coder, stacked):
            parity = coder.encode_batch(stacked)
            digests = np.zeros(
                (stacked.shape[0], coder.data + coder.parity, 32),
                dtype=np.uint8)
            return parity, digests

    batcher_cls = NoHashBatcher if no_hash else EncodeHashBatcher
    batcher_box = {}

    # --threads/--sweep-threads pins native:N, which cannot hang on
    # device init — skip the watchdog entirely (passing None would fall
    # back to $CHUNKY_BITS_TPU_BACKEND and probe a device the sweep
    # never touches)
    ready = (None if thread_list != [None] else _arm_if_device_backend(
        backend,
        "cp_pipeline_encode_gibps_d10p4_1mib_b" + str(batch)
        + ("_nohash" if no_hash else "")
        + ("_mmap" if src == "file" else "")))

    async def run(run_backend, pipeline) -> tuple:
        def make_batcher():
            batcher_box["b"] = batcher_cls(backend=run_backend,
                                           max_batch=batch,
                                           host_pipeline=pipeline)
            return batcher_box["b"]

        builder = (FileWriteBuilder()
                   .with_destination(None)  # VoidDestination
                   .with_chunk_size(chunk)
                   .with_data_chunks(d).with_parity_chunks(p)
                   .with_concurrency(batch + 4)
                   .with_batch_parts(batch)
                   .with_stage_parts(stage)
                   .with_backend(run_backend)
                   .with_encode_batcher(make_batcher))
        if pipeline is not None:
            builder = builder.with_host_pipeline(pipeline)
        # warm (compile, thread pools) with one small batch
        await (builder.with_batch_parts(2).with_concurrency(6)
               .write(CyclicReader(2 * part_bytes)))
        if ready is not None:
            ready.set()  # device answered the warm-up dispatch
        t0 = time.perf_counter()
        ref = await builder.write(make_reader())
        dt = time.perf_counter() - t0
        # each write() resolves a fresh batcher, so the box holds the
        # measured run's instance and its count is exact
        return ref, dt, batcher_box["b"].dispatches

    import contextlib
    import tempfile

    with contextlib.ExitStack() as stack:
        if src == "file":
            from chunky_bits_tpu.utils import aio

            tmp = stack.enter_context(
                tempfile.NamedTemporaryFile(suffix=".cb-bench"))
            written = 0
            while written < total:
                n = min(len(blob), total - written)
                tmp.write(blob[:n])
                written += n
            tmp.flush()

            def make_reader():
                return aio.FileReader(tmp.name)
        elif src == "cyclic":
            def make_reader():
                return CyclicReader(total)
        else:
            print(f"usage: bench.py --config 2 --src {{cyclic,file}} "
                  f"(got {src!r})", file=sys.stderr)
            sys.exit(2)

        for n_threads in thread_list:
            if n_threads is None:
                run_backend, pipeline, suffix = backend, None, ""
            else:
                # pin TOTAL host threads: native:N codec cap + an
                # N-worker pipeline (writer compute rides the pipeline)
                from chunky_bits_tpu.parallel.host_pipeline import \
                    HostPipeline

                run_backend = f"native:{n_threads}"
                pipeline = HostPipeline(threads=n_threads)
                suffix = f"_host{n_threads}"
            ref, dt, dispatches = asyncio.run(run(run_backend, pipeline))
            if pipeline is not None:
                stats = pipeline.stats()
                pipeline.close()
            else:
                stats = None
            n_parts = len(ref.parts)
            assert n_parts == total // part_bytes
            gibps = total / dt / (1 << 30)
            per_dispatch = n_parts / max(dispatches, 1)
            print(f"# config 2: {total / (1 << 30):.1f} GiB through "
                  f"FileWriteBuilder, backend={run_backend}, "
                  f"batch={batch}, src={src}, "
                  f"hash={'off' if no_hash else 'on'}; {n_parts} "
                  f"parts in {dispatches} dispatches "
                  f"({per_dispatch:.1f} parts/dispatch)"
                  + (f"; {stats}" if stats is not None else ""),
                  file=sys.stderr)
            print(json.dumps({
                "metric": "cp_pipeline_encode_gibps_d10p4_1mib_b"
                          + str(batch)
                          + ("_nohash" if no_hash else "")
                          + ("_mmap" if src == "file" else "") + suffix,
                "value": round(gibps, 2), "unit": "GiB/s",
                "vs_baseline": round(gibps / 5.0, 2),
                "parts_per_dispatch": round(per_dispatch, 1),
                **({"host_threads": n_threads, "host_cores": nproc()}
                   if n_threads is not None else {}),
            }))


def bench_batched_repair(argv=()) -> None:
    """BASELINE.md config 3's host-path shape: many degraded parts
    sharing one erasure pattern (the common node-loss case) rebuilt
    through the ReconstructBatcher's coalesced dispatches — the repair
    analogue of config 4.  One JSON line on stdout per run.

    ``--threads N`` pins the decode to N host threads (the ``native:N``
    codec spec bounding the batched GF matmul's std::thread fan-out);
    ``--sweep-threads 1,2[,4...]`` runs the measurement once per N, one
    JSON line each — the decode-side host-scaling harness, mirroring
    configs 2 and 4."""
    import asyncio

    argv = list(argv)

    def flag_val(name):
        if name in argv:
            idx = argv.index(name) + 1
            if idx >= len(argv):
                print(f"usage: bench.py --config 3 [{name} N[,N...]]",
                      file=sys.stderr)
                sys.exit(2)
            return argv[idx]
        return None

    threads = flag_val("--threads")
    sweep = flag_val("--sweep-threads")
    if threads and sweep:
        print("--threads and --sweep-threads conflict; pick one",
              file=sys.stderr)
        sys.exit(2)
    specs = ([f"native:{n}" for n in sweep.split(",")] if sweep
             else [f"native:{threads}" if threads else None])

    from chunky_bits_tpu.ops.backend import ErasureCoder, get_backend
    from chunky_bits_tpu.ops.batching import ReconstructBatcher

    d, p, size = 10, 4, 1 << 20
    # armed before the prep encodes below — they hit the device too when
    # $CHUNKY_BITS_TPU_BACKEND selects a jax backend (an explicit
    # --threads/--sweep-threads run pins native:N, which cannot hang)
    ready = (None if (threads or sweep) else _arm_if_device_backend(
        None, "batched_repair_reconstruct_gibps_d10p4_4erasures"))
    n_parts = 40
    rng = np.random.default_rng(0)
    coder = ErasureCoder(d, p, get_backend(specs[0]))
    parts = []
    for _ in range(n_parts):
        data = rng.integers(0, 256, (1, d, size), dtype=np.uint8)
        parity = coder.encode_batch(data)
        rows = [data[0, i] for i in range(d)] + [parity[0, i]
                                                 for i in range(p)]
        for i in (0, 3, 11, 13):  # the same 4 erasures on every part
            rows[i] = None
        parts.append(rows)

    async def run(backend) -> float:
        batcher = ReconstructBatcher(backend=backend)
        sem = asyncio.Semaphore(10)  # resilver's in-flight bound

        async def one(rows):
            async with sem:
                return await batcher.reconstruct(d, p, list(rows))

        await one(parts[0])  # warm
        if ready is not None:
            ready.set()  # device answered the warm-up dispatch
        t0 = time.perf_counter()
        await asyncio.gather(*[one(r) for r in parts[1:]])
        dt = time.perf_counter() - t0
        coalesce = (n_parts - 1) / max(batcher.dispatches - 1, 1)
        print(f"# coalescing factor: {coalesce:.1f} parts/dispatch",
              file=sys.stderr)
        return (n_parts - 1) * d * size / dt / (1 << 30)

    for backend in specs:
        gib = asyncio.run(run(backend))
        print(json.dumps({
            "metric": "batched_repair_reconstruct_gibps_d10p4_4erasures"
                      + (f"_{backend.replace(':', '')}" if backend
                         else ""),
            "value": round(gib, 2), "unit": "GiB/s",
            "vs_baseline": round(gib / 5.0, 2),
            **({"host_cores": nproc()} if sweep else {}),
        }))


def bench_hot_read(argv=()) -> None:
    """Hot-read serve path: repeated reads of ONE object through the full
    cluster read pipeline (metadata -> FileReadBuilder -> chunk fetch +
    verify), cache off vs on (`tunables.cache_bytes`).  The off run pays
    fetch + SHA-256 verify per chunk every time; the on run serves
    verified buffers out of the content-addressed cache.  CPU-backend,
    no device, no watchdog.  Single JSON line: value = cached GiB/s,
    with the uncached number and the speedup alongside.

    Flags: ``--mib N`` object size (default 64), ``--reads N`` timed
    reads per mode (default 5), ``--backend X`` (default auto)."""
    import asyncio
    import contextlib
    import tempfile

    argv = list(argv)

    def flag(name, default, cast):
        if name in argv:
            return cast(argv[argv.index(name) + 1])
        return default

    mib = flag("--mib", 64, int)
    reads = flag("--reads", 5, int)
    backend = flag("--backend", None, str)

    from chunky_bits_tpu.cluster import Cluster
    from chunky_bits_tpu.utils import aio

    payload = np.random.default_rng(0).integers(
        0, 256, mib << 20, dtype=np.uint8).tobytes()

    def make_cluster(root: str, cache_bytes: int) -> Cluster:
        import os

        dirs = []
        for i in range(5):
            d = os.path.join(root, f"disk{i}")
            os.makedirs(d, exist_ok=True)
            dirs.append(d)
        meta = os.path.join(root, "meta")
        os.makedirs(meta, exist_ok=True)
        tunables = {"cache_bytes": cache_bytes}
        if backend:
            tunables["backend"] = backend
        return Cluster.from_obj({
            "destinations": [{"location": d} for d in dirs],
            "metadata": {"type": "path", "format": "yaml", "path": meta},
            "profiles": {"default": {"data": 3, "parity": 2,
                                     "chunk_size": 20}},
            "tunables": tunables,
        })

    async def read_once(cluster: Cluster) -> int:
        # the gateway GET core: metadata ref (cached or parsed) then the
        # serve-path builder's stream
        ref = await cluster.get_file_ref("obj")
        total = 0
        async for chunk in cluster.file_read_builder(ref).stream():
            total += len(chunk)
        return total

    async def run_mode(root: str, cache_bytes: int) -> float:
        cluster = make_cluster(root, cache_bytes)
        profile = cluster.get_profile(None)
        await cluster.write_file("obj", aio.BytesReader(payload), profile)
        # warm pass doubles as the byte-identity gate for this mode
        ref = await cluster.get_file_ref("obj")
        got = await cluster.file_read_builder(ref).read_all()
        assert got == payload, "hot-read byte identity failed"
        best = float("inf")
        for _ in range(reads):
            t0 = time.perf_counter()
            n = await read_once(cluster)
            best = min(best, time.perf_counter() - t0)
            assert n == len(payload)
        await cluster.tunables.location_context().aclose()
        return len(payload) / best / (1 << 30)

    with contextlib.ExitStack() as stack:
        cold_root = stack.enter_context(tempfile.TemporaryDirectory())
        hot_root = stack.enter_context(tempfile.TemporaryDirectory())
        uncached = asyncio.run(run_mode(cold_root, 0))
        cached = asyncio.run(run_mode(hot_root, max(4 * len(payload),
                                                    64 << 20)))
    speedup = cached / uncached if uncached > 0 else 0.0
    print(f"# config 6: hot-read of one {mib} MiB object, d=3 p=2, "
          f"backend={backend or 'auto'}; uncached {uncached:.2f} GiB/s, "
          f"cached {cached:.2f} GiB/s ({speedup:.1f}x)", file=sys.stderr)
    print(json.dumps({
        "metric": "hot_read_cached_gibps_d3p2_1mib",
        "value": round(cached, 2), "unit": "GiB/s",
        "vs_baseline": round(cached / 5.0, 2),
        "uncached_gibps": round(uncached, 2),
        "cache_speedup": round(speedup, 2),
    }))


def bench_gateway_put(argv=()) -> None:
    """Gateway PUT ingest: a multi-GiB body streamed through a REAL
    aiohttp server into the full encode+hash+place pipeline (the
    BASELINE "CLI host plane" row's gateway PUT shape, measurable and
    re-runnable instead of hand-driven curl).  CPU-only — no device, no
    watchdog.  One JSON line per run.

    The A/B this config exists for: ``--threads N`` pins the cluster's
    host plane to N total threads (``tunables.host_threads`` + the
    ``native:N`` codec spec), so N=1 vs N=2 measures whether socket
    receive and encode+hash actually overlap across cores.
    ``--sweep-threads 1,2[,...]`` emits one line per N.

    Flags: ``--gib N`` body size (default 1), ``--trials N`` (default 3,
    best-of reported), ``--threads N`` / ``--sweep-threads N,N``."""
    import asyncio
    import contextlib
    import os
    import tempfile

    argv = list(argv)

    def flag(name, default, cast):
        if name in argv:
            return cast(argv[argv.index(name) + 1])
        return default

    gib = flag("--gib", 1.0, float)
    trials = flag("--trials", 3, int)
    threads = flag("--threads", None, str)
    sweep = flag("--sweep-threads", None, str)
    if threads and sweep:
        print("--threads and --sweep-threads conflict; pick one",
              file=sys.stderr)
        sys.exit(2)
    thread_list = ([int(x) for x in sweep.split(",")] if sweep
                   else [int(threads)] if threads else [0])

    from aiohttp import ClientSession, ClientTimeout
    from aiohttp.test_utils import TestServer

    from chunky_bits_tpu.cluster import Cluster
    from chunky_bits_tpu.gateway import make_app

    total = int(gib * (1 << 30))
    blob = np.random.default_rng(0).integers(
        0, 256, 8 << 20, dtype=np.uint8).tobytes()

    def make_cluster(root: str, n_threads: int) -> Cluster:
        dirs = []
        for i in range(5):
            d = os.path.join(root, f"disk{i}")
            os.makedirs(d, exist_ok=True)
            dirs.append(d)
        meta = os.path.join(root, "meta")
        os.makedirs(meta, exist_ok=True)
        tunables = {"backend": f"native:{n_threads}" if n_threads
                    else "native"}
        if n_threads:
            tunables["host_threads"] = n_threads
        return Cluster.from_obj({
            "destinations": [{"location": d} for d in dirs],
            "metadata": {"type": "path", "format": "yaml", "path": meta},
            # the reference's default geometry (writer.rs:50-59): d=3
            # p=2, 1 MiB chunks — the BASELINE round-5 PUT row's shape
            "profiles": {"default": {"data": 3, "parity": 2,
                                     "chunk_size": 20}},
            "tunables": tunables,
        })

    async def body():
        sent = 0
        view = memoryview(blob)
        while sent < total:
            n = min(len(blob), total - sent)
            yield view[:n]
            sent += n

    async def run_one(n_threads: int) -> float:
        best = float("inf")
        with contextlib.ExitStack() as stack:
            root = stack.enter_context(tempfile.TemporaryDirectory())
            cluster = make_cluster(root, n_threads)
            server = TestServer(make_app(cluster))
            await server.start_server()
            try:
                timeout = ClientTimeout(total=3600)
                async with ClientSession(timeout=timeout) as session:
                    # warm: thread pools, first-dispatch codec resolution
                    resp = await session.put(server.make_url("/warm"),
                                             data=blob[:1 << 20])
                    assert resp.status == 200, resp.status
                    for t in range(trials):
                        t0 = time.perf_counter()
                        resp = await session.put(
                            server.make_url(f"/obj{t}"), data=body())
                        dt = time.perf_counter() - t0
                        assert resp.status == 200, resp.status
                        best = min(best, dt)
            finally:
                await server.close()
                await cluster.tunables.location_context().aclose()
                if n_threads:
                    # cluster-pinned pipeline: stop its workers so a
                    # sweep doesn't accumulate thread sets across runs
                    cluster.host_pipeline().close()
        return total / best / (1 << 30)

    for n_threads in thread_list:
        gibps = asyncio.run(run_one(n_threads))
        label = n_threads if n_threads else "auto"
        print(f"# config 7: gateway PUT {gib:g} GiB, d=3 p=2 native, "
              f"host_threads={label}, best of {trials}: "
              f"{gibps:.3f} GiB/s", file=sys.stderr)
        print(json.dumps({
            "metric": "gateway_put_ingest_gibps_d3p2_1mib"
                      + (f"_host{n_threads}" if n_threads else ""),
            "value": round(gibps, 3), "unit": "GiB/s",
            "vs_baseline": round(gibps / 5.0, 3),
            "host_cores": nproc(),
        }))


def bench_hedged_read(argv=()) -> None:
    """BASELINE.md config 8: hedged-read tail-latency A/B (CPU-only, no
    device, no watchdog).  A d=3 p=2 object is written to five
    in-process HTTP storage nodes, every chunk gets a replica on a
    second (fast) node, then node 0 is wrapped with injected
    latency+jitter on every GET — the classic one-slow-replica shape.
    Reads run once with hedging off (`tunables.hedge_ms = 0`, the
    default: byte-for-byte the pre-scoreboard location walk) and once
    with it on; per-part p50/p99 latency, throughput, and request
    amplification (extra GETs from hedges, budget-capped at ~5%) are
    reported.  The headline number is the p99 collapse.

    Flags: ``--parts N`` (default 4), ``--chunk-log2 N`` (default 15 =
    32 KiB), ``--reads N`` timed passes per leg (default 40),
    ``--delay-ms N`` slow-node injected latency (default 100, +/-25%
    jitter), ``--hedge-ms N`` hedge delay floor for the ON leg
    (default 10).

    Failure contract (tests/test_bench_outage.py): ANY failure still
    emits exactly one parseable JSON line and exits 3."""
    import asyncio
    import contextlib
    import random as _random
    import tempfile

    argv = list(argv)

    def flag(name, default, cast):
        if name in argv:
            return cast(argv[argv.index(name) + 1])
        return default

    metric = "hedged_read_p99_collapse_d3p2_1slow"
    try:
        parts = flag("--parts", 4, int)
        chunk_log2 = flag("--chunk-log2", 15, int)
        reads = flag("--reads", 40, int)
        delay_ms = flag("--delay-ms", 100.0, float)
        hedge_ms = flag("--hedge-ms", 10.0, float)
        if parts <= 0 or reads <= 0:
            raise ValueError("--parts and --reads must be positive")
        if not (10 <= chunk_log2 <= 24):
            raise ValueError("--chunk-log2 out of range [10, 24]")
        if delay_ms < 0 or hedge_ms <= 0:
            raise ValueError("--delay-ms must be >= 0, --hedge-ms > 0")

        from aiohttp import web

        from chunky_bits_tpu.cluster import Cluster
        from chunky_bits_tpu.file.location import Location
        from chunky_bits_tpu.utils import aio

        d, p = 3, 2
        chunk_bytes = 1 << chunk_log2
        payload = np.random.default_rng(0).integers(
            0, 256, parts * d * chunk_bytes, dtype=np.uint8).tobytes()

        class Node:
            """In-memory HTTP storage node with injectable GET latency
            (stall, not fail) — the straggler the scheduler must beat."""

            def __init__(self) -> None:
                self.store: dict[str, bytes] = {}
                self.gets = 0
                self.delay_s = 0.0
                self._rng = _random.Random(1)
                self._runner = None
                self.url = ""

            async def _get(self, request):
                key = request.match_info["key"]
                self.gets += 1
                if self.delay_s > 0:
                    await asyncio.sleep(
                        self.delay_s * self._rng.uniform(0.75, 1.25))
                data = self.store.get(key)
                if data is None:
                    return web.Response(status=404)
                return web.Response(body=data)

            async def _put(self, request):
                self.store[request.match_info["key"]] = \
                    await request.read()
                return web.Response()

            async def start(self) -> "Node":
                app = web.Application()
                app.router.add_get("/{key:.*}", self._get)
                app.router.add_put("/{key:.*}", self._put)
                self._runner = web.AppRunner(app)
                await self._runner.setup()
                site = web.TCPSite(self._runner, "127.0.0.1", 0)
                await site.start()
                port = site._server.sockets[0].getsockname()[1]
                self.url = f"http://127.0.0.1:{port}"
                return self

            async def stop(self) -> None:
                if self._runner is not None:
                    await self._runner.cleanup()

        async def run() -> dict:
            nodes = [await Node().start() for _ in range(5)]
            try:
                with contextlib.ExitStack() as stack:
                    meta = stack.enter_context(
                        tempfile.TemporaryDirectory())

                    def make_cluster(hedge: float) -> Cluster:
                        return Cluster.from_obj({
                            "destinations": [{"location": n.url + "/"}
                                             for n in nodes],
                            "metadata": {"type": "path",
                                         "format": "yaml", "path": meta},
                            "profiles": {"default": {
                                "data": d, "parity": p,
                                "chunk_size": chunk_log2}},
                            "tunables": {"hedge_ms": hedge},
                        })

                    writer_cluster = make_cluster(0)
                    await writer_cluster.write_file(
                        "obj", aio.BytesReader(payload),
                        writer_cluster.get_profile())
                    ref = await writer_cluster.get_file_ref("obj")
                    await writer_cluster.tunables.location_context() \
                        .aclose()

                    # replica pass: every chunk gets a second location
                    # on a FAST node (never node 0 — ONE slow replica
                    # per chunk is the scenario), so the hedged leg
                    # always has somewhere to race
                    fast_i = 1
                    for part in ref.parts:
                        for chunk in part.data + part.parity:
                            key = str(chunk.hash)
                            owner = next(
                                n for n in nodes
                                if str(chunk.locations[0])
                                .startswith(n.url))
                            while (nodes[fast_i] is owner
                                   or fast_i == 0):
                                fast_i = (fast_i + 1) % len(nodes)
                            target = nodes[fast_i]
                            fast_i = (fast_i + 1) % len(nodes)
                            target.store[key] = owner.store[key]
                            chunk.locations.append(Location.http(
                                f"{target.url}/{key}"))

                    nodes[0].delay_s = delay_ms / 1000.0

                    async def leg(hedge: float) -> dict:
                        cluster = make_cluster(hedge)
                        cx = cluster.tunables.location_context()
                        # warm connections (and the first-read breaker
                        # samples) outside the timed window
                        for part in ref.parts:
                            await part.read(cx)
                        for n in nodes:
                            n.gets = 0
                        lat: list[float] = []
                        t0 = time.perf_counter()
                        for _ in range(reads):
                            for part in ref.parts:
                                s = time.perf_counter()
                                bufs = await part.read_buffers(cx)
                                lat.append(time.perf_counter() - s)
                                del bufs
                        total_s = time.perf_counter() - t0
                        requests = sum(n.gets for n in nodes)
                        # byte-identity gate: whichever location or
                        # reconstruct path won each race, the object
                        # must read back exactly
                        got = await cluster.file_read_builder(ref) \
                            .read_all()
                        assert got == payload, \
                            "hedged-read byte identity failed"
                        stats = cluster.health_scoreboard().stats()
                        await cx.aclose()
                        arr = np.array(lat)
                        return {
                            "p50_ms": float(np.percentile(arr, 50))
                            * 1000.0,
                            "p99_ms": float(np.percentile(arr, 99))
                            * 1000.0,
                            "gibps": reads * len(payload) / total_s
                            / (1 << 30),
                            "requests": requests,
                            "hedges": (stats.hedges_fired,
                                       stats.hedges_won,
                                       stats.hedges_cancelled),
                        }

                    off = await leg(0)
                    on = await leg(hedge_ms)
                    return {"off": off, "on": on}
            finally:
                for n in nodes:
                    await n.stop()

        res = asyncio.run(run())
        off, on = res["off"], res["on"]
        collapse = (off["p99_ms"] / on["p99_ms"]
                    if on["p99_ms"] > 0 else 0.0)
        amplification = (on["requests"] / off["requests"] - 1.0
                         if off["requests"] else 0.0)
        fired, won, cancelled = on["hedges"]
        print(f"# config 8: {parts} parts d={d} p={p} "
              f"chunk={chunk_bytes >> 10} KiB, slow node "
              f"{delay_ms:g}ms, hedge {hedge_ms:g}ms, {reads} reads: "
              f"off p50/p99 {off['p50_ms']:.1f}/{off['p99_ms']:.1f} ms "
              f"{off['gibps']:.3f} GiB/s | on p50/p99 "
              f"{on['p50_ms']:.1f}/{on['p99_ms']:.1f} ms "
              f"{on['gibps']:.3f} GiB/s | p99 collapse "
              f"{collapse:.1f}x | amplification "
              f"{amplification * 100:.1f}% | hedges fired/won/"
              f"cancelled {fired}/{won}/{cancelled}",
              file=sys.stderr)
        print(json.dumps({
            "metric": metric,
            "value": round(collapse, 2), "unit": "x",
            # the acceptance target is a >= 5x p99 collapse with one
            # slow replica; vs_baseline >= 1.0 means criterion met
            "vs_baseline": round(collapse / 5.0, 2),
            "p50_off_ms": round(off["p50_ms"], 2),
            "p99_off_ms": round(off["p99_ms"], 2),
            "p50_on_ms": round(on["p50_ms"], 2),
            "p99_on_ms": round(on["p99_ms"], 2),
            "gibps_off": round(off["gibps"], 3),
            "gibps_on": round(on["gibps"], 3),
            "hedge_amplification": round(amplification, 4),
            "hedges_fired": fired,
            "hedges_won": won,
            "hedges_cancelled": cancelled,
        }))
    # lint: broad-except-ok the driver contract (ONE parseable JSON
    # line, always) outranks the traceback; the error text carries it
    except Exception as err:
        print(json.dumps({
            "metric": metric, "value": 0.0, "unit": "x",
            "vs_baseline": 0.0,
            "error": f"{type(err).__name__}: {err}",
        }))
        sys.exit(3)


def bench_gateway_scaleout(argv=()) -> None:
    """BASELINE.md config 9: the gateway scale-out A/B (CPU-only, no
    device, no watchdog).  Hundreds of concurrent keep-alive clients
    hammer a REAL multi-process gateway fleet (gateway/workers.py:
    SO_REUSEPORT workers under the supervisor) with the mixed read
    traffic of a serving frontend — full-body hot reads, within-chunk
    ranges (the sendfile fast path), conditional GETs (If-None-Match →
    304), and periodic large objects — once per worker count in the
    sweep.  Reports RPS and p50/p99/p999 client-side latency per leg
    (percentiles via file/profiler.percentile — the SAME code the
    gateway access log uses, so bench and production numbers agree by
    construction), plus the 304-vs-full-body hot-read comparison.
    Every body is compared against the source payload, so the run is
    also the sendfile-vs-reassembly byte-identity gate.

    Flags: ``--clients N`` concurrent keep-alive clients (default
    200), ``--rounds N`` request rounds per client (default 5),
    ``--sweep-workers 1,2[,4]`` worker counts (default "1,2" — the 1 vs
    N A/B; both legs run under the supervisor so the comparison is
    pure worker count), ``--no-sendfile`` forces the reassembly path in
    every worker (the sendfile A/B leg), ``--smoke`` shrinks everything
    to a seconds-scale contract check (8 clients, 2 rounds, 1 worker).

    Failure contract (tests/test_bench_outage.py): ANY failure still
    emits exactly one parseable JSON line and exits 3."""
    import asyncio
    import contextlib
    import os
    import random as _random
    import tempfile

    argv = list(argv)

    def flag(name, default, cast):
        if name in argv:
            return cast(argv[argv.index(name) + 1])
        return default

    metric_base = "gateway_scaleout_rps_d3p2_mixed"
    try:
        smoke = "--smoke" in argv
        clients = flag("--clients", 8 if smoke else 200, int)
        rounds = flag("--rounds", 2 if smoke else 5, int)
        sweep = flag("--sweep-workers", "1" if smoke else "1,2", str)
        no_sendfile = "--no-sendfile" in argv
        worker_counts = [int(x) for x in sweep.split(",")]
        if clients <= 0 or rounds <= 0 or not worker_counts \
                or any(w <= 0 for w in worker_counts):
            raise ValueError("--clients/--rounds/--sweep-workers must "
                             "be positive")

        import aiohttp

        from chunky_bits_tpu.cluster import Cluster
        from chunky_bits_tpu.cluster.tunables import GATEWAY_SENDFILE_ENV
        from chunky_bits_tpu.file.profiler import percentile
        from chunky_bits_tpu.gateway.workers import GatewaySupervisor
        from chunky_bits_tpu.utils import aio

        if no_sendfile:
            # the one sanctioned env handoff shape (a WRITE, like the
            # CLI's backend flag): workers inherit it at spawn
            os.environ[GATEWAY_SENDFILE_ENV] = "0"

        rng = np.random.default_rng(0)
        sizes = ({"small": 4 << 10, "med": 32 << 10, "large": 64 << 10}
                 if smoke else
                 {"small": 16 << 10, "med": 256 << 10,
                  "large": 1 << 20})
        payloads = {name: rng.integers(0, 256, n, dtype=np.uint8)
                    .tobytes() for name, n in sizes.items()}
        # the cold tier: more bytes than the cache budget below, so
        # these reads always pay fetch+verify on the server (the
        # host-compute-bound half of the mix; hot reads are the
        # loop-bound half)
        n_cold = 2 if smoke else 24
        cold_bytes = (16 << 10) if smoke else (256 << 10)
        for i in range(n_cold):
            payloads[f"cold{i}"] = rng.integers(
                0, 256, cold_bytes, dtype=np.uint8).tobytes()
        chunk_log2 = 12 if smoke else 16
        chunk_bytes = 1 << chunk_log2

        def make_cluster_obj(root: str) -> dict:
            dirs = []
            for i in range(5):
                d = os.path.join(root, f"disk{i}")
                os.makedirs(d, exist_ok=True)
                dirs.append(d)
            meta = os.path.join(root, "meta")
            os.makedirs(meta, exist_ok=True)
            return {
                "destinations": [{"location": d} for d in dirs],
                "metadata": {"type": "path", "format": "yaml",
                             "path": meta},
                # the reference's default geometry at gateway-friendly
                # chunk sizes: ranges inside one chunk exercise the
                # sendfile path, whole objects span chunks
                "profiles": {"default": {"data": 3, "parity": 2,
                                         "chunk_size": chunk_log2}},
                # cache sized to hold the hot tier but NOT the cold
                # tier: the mix stays genuinely mixed (hot reads serve
                # from memory, cold reads re-fetch + re-verify)
                "tunables": {"backend": "native",
                             "cache_bytes": 2 << 20},
            }

        class MiniConn:
            """Minimal raw-socket keep-alive HTTP/1.1 GET client (the
            wrk role).  aiohttp's client costs more CPU per request
            than the gateway spends serving a hot object — load driven
            through it measures the generator, not the fleet.  This
            parser handles exactly what the gateway sends (status line,
            Content-Length-delimited bodies, body-less 304s) and keeps
            the client's per-request cost far below the server's."""

            def __init__(self, host: str, port: int):
                self.host = host
                self.port = port
                self.reader = None
                self.writer = None

            async def open(self):
                self.reader, self.writer = await asyncio.open_connection(
                    self.host, self.port)
                return self

            async def get(self, path: str, extra: str = "") -> tuple:
                """(status, body) over the persistent connection."""
                self.writer.write(
                    (f"GET {path} HTTP/1.1\r\n"
                     f"Host: {self.host}\r\n{extra}\r\n").encode())
                await self.writer.drain()
                status_line = await self.reader.readline()
                status = int(status_line.split(b" ", 2)[1])
                length = 0
                while True:
                    line = await self.reader.readline()
                    if line in (b"\r\n", b""):
                        break
                    if line[:15].lower() == b"content-length:":
                        length = int(line[15:])
                body = b""
                if status not in (204, 304) and length:
                    body = await self.reader.readexactly(length)
                return status, body

            async def close(self):
                if self.writer is not None:
                    self.writer.close()
                    # bounded: closing a local socket
                    try:
                        await asyncio.wait_for(
                            self.writer.wait_closed(), timeout=5)
                    except (asyncio.TimeoutError, OSError):
                        pass

        async def run_leg(cluster_obj: dict, n_workers: int) -> dict:
            sup = GatewaySupervisor(cluster_obj, "127.0.0.1", 0,
                                    workers=n_workers,
                                    ready_timeout=120.0)
            await sup.start()
            try:
                url = f"http://127.0.0.1:{sup.port}"
                connector = aiohttp.TCPConnector(limit=clients)
                timeout = aiohttp.ClientTimeout(total=600)
                async with aiohttp.ClientSession(
                        connector=connector,
                        timeout=timeout) as session:
                    # warm pass, untimed: fills every worker's hot-tier
                    # cache + sendfile memo (SO_REUSEPORT spreads the
                    # connections) AND is the whole-object byte-
                    # identity gate against the source payloads
                    async def warm(i):
                        for name in ("small", "med", "large"):
                            r = await session.get(f"{url}/{name}")
                            assert r.status == 200, r.status
                            body = await r.read()
                            assert body == payloads[name], \
                                "warm byte identity"
                    await asyncio.gather(*[warm(i)
                                           for i in range(clients)])
                    # identity pass, untimed: in-chunk ranges (the
                    # sendfile path when on), a cross-chunk range and a
                    # suffix (always reassembly), compared to the
                    # numpy-oracle payload slices
                    med = payloads["med"]
                    for start, end in ((5, chunk_bytes - 1),
                                       (chunk_bytes, 2 * chunk_bytes - 1),
                                       (chunk_bytes // 2,
                                        chunk_bytes + 99),
                                       (len(med) - 50, len(med) - 1)):
                        r = await session.get(
                            f"{url}/med",
                            headers={"Range": f"bytes={start}-{end}"})
                        assert r.status == 206, r.status
                        assert await r.read() == med[start:end + 1], \
                            "range byte identity"
                    r = await session.get(f"{url}/small")
                    await r.read()
                    etag = r.headers["ETag"]
                    r = await session.get(f"{url}/med")
                    await r.read()
                    med_etag = r.headers["ETag"]

                lat: dict = {"full": [], "range": [], "cond": [],
                             "large": [], "cold": []}

                async def one_client(ci: int) -> int:
                    # ONE keep-alive raw connection per client; the
                    # timed loop checks status + length only (identity
                    # is pinned untimed above) so the SERVER stays the
                    # measured resource
                    done = 0
                    crng = _random.Random(ci)
                    conn = await MiniConn("127.0.0.1",
                                          sup.port).open()
                    try:
                        for r_i in range(rounds):
                            t0 = time.perf_counter()
                            status, body = await conn.get("/small")
                            lat["full"].append(time.perf_counter() - t0)
                            assert status == 200
                            assert len(body) == len(payloads["small"])
                            done += 1

                            start = crng.randrange(
                                0, len(med) - chunk_bytes)
                            start -= start % chunk_bytes
                            end = start + chunk_bytes - 1
                            t0 = time.perf_counter()
                            status, body = await conn.get(
                                "/med",
                                f"Range: bytes={start}-{end}\r\n")
                            lat["range"].append(
                                time.perf_counter() - t0)
                            assert status == 206
                            assert len(body) == chunk_bytes
                            done += 1

                            t0 = time.perf_counter()
                            status, body = await conn.get(
                                "/small",
                                f"If-None-Match: {etag}\r\n")
                            lat["cond"].append(
                                time.perf_counter() - t0)
                            assert status == 304
                            done += 1

                            # cold tier: the set outsizes the cache.
                            # Alternate two shapes — a range CROSSING a
                            # chunk boundary (never sendfile-eligible:
                            # the server fetches + SHA-verifies a whole
                            # d-chunk part to ship 4 KiB), and a range
                            # INSIDE one chunk (the sendfile fast path
                            # when enabled: one verify, memoized, then
                            # page-cache -> socket; with --no-sendfile
                            # it pays the whole-part fetch instead —
                            # THE on/off A/B class)
                            name = f"cold{(ci + r_i * 7) % n_cold}"
                            span = min(4096, chunk_bytes // 2)
                            if (ci + r_i) % 2:
                                start = chunk_bytes - span // 2
                            else:
                                start = chunk_bytes // 4
                            t0 = time.perf_counter()
                            status, body = await conn.get(
                                f"/{name}",
                                f"Range: bytes={start}-"
                                f"{start + span - 1}\r\n")
                            lat["cold"].append(
                                time.perf_counter() - t0)
                            assert status == 206
                            assert len(body) == span
                            done += 1

                            if ci % 8 == 0:
                                t0 = time.perf_counter()
                                status, body = await conn.get("/large")
                                lat["large"].append(
                                    time.perf_counter() - t0)
                                assert status == 200
                                assert len(body) == \
                                    len(payloads["large"])
                                done += 1
                    finally:
                        await conn.close()
                    return done

                t0 = time.perf_counter()
                counts = await asyncio.gather(
                    *[one_client(i) for i in range(clients)])
                wall = time.perf_counter() - t0

                # unqueued phase: ONE sequential connection measures
                # the per-request cost of a hot full-body read vs a
                # 304 — the "repeat readers cost zero bytes" claim,
                # uncontaminated by the saturation phase's queueing
                seq = 20 if smoke else 100
                conn = await MiniConn("127.0.0.1", sup.port).open()
                try:
                    seq_full: list = []
                    seq_cond: list = []
                    status, body = await conn.get("/med")
                    assert status == 200  # hot again post-saturation
                    for _ in range(seq):
                        t0s = time.perf_counter()
                        status, body = await conn.get("/med")
                        seq_full.append(time.perf_counter() - t0s)
                        assert status == 200
                    for _ in range(seq):
                        t0s = time.perf_counter()
                        status, body = await conn.get(
                            "/med", f"If-None-Match: {med_etag}\r\n")
                        seq_cond.append(time.perf_counter() - t0s)
                        assert status == 304
                finally:
                    await conn.close()

                all_lat = sorted(v for vs in lat.values() for v in vs)
                return {
                    "requests": sum(counts),
                    "wall": wall,
                    "rps": sum(counts) / wall,
                    "p50_ms": percentile(all_lat, 50) * 1e3,
                    "p99_ms": percentile(all_lat, 99) * 1e3,
                    "p999_ms": percentile(all_lat, 99.9) * 1e3,
                    "full_p50_ms":
                        percentile(sorted(seq_full), 50) * 1e3,
                    "cond_p50_ms":
                        percentile(sorted(seq_cond), 50) * 1e3,
                }
            finally:
                await sup.stop()

        async def run() -> list:
            results = []
            with contextlib.ExitStack() as stack:
                root = stack.enter_context(
                    tempfile.TemporaryDirectory())
                cluster_obj = make_cluster_obj(root)
                cluster = Cluster.from_obj(cluster_obj)
                profile = cluster.get_profile(None)
                for name, data in payloads.items():
                    await cluster.write_file(
                        name, aio.BytesReader(data), profile)
                await cluster.tunables.location_context().aclose()
                for n_workers in worker_counts:
                    results.append(
                        (n_workers,
                         await run_leg(cluster_obj, n_workers)))
            return results

        results = asyncio.run(run())
        base_rps = results[0][1]["rps"]
        for n_workers, res in results:
            cond_speedup = (res["full_p50_ms"] / res["cond_p50_ms"]
                            if res["cond_p50_ms"] > 0 else 0.0)
            print(f"# config 9: workers={n_workers} clients={clients} "
                  f"rounds={rounds} sendfile="
                  f"{'off' if no_sendfile else 'on'}: "
                  f"{res['requests']} reqs in {res['wall']:.2f}s = "
                  f"{res['rps']:.0f} RPS | p50/p99/p999 "
                  f"{res['p50_ms']:.1f}/{res['p99_ms']:.1f}/"
                  f"{res['p999_ms']:.1f} ms | sequential hot full p50 "
                  f"{res['full_p50_ms']:.2f} ms vs 304 p50 "
                  f"{res['cond_p50_ms']:.2f} ms ({cond_speedup:.1f}x)",
                  file=sys.stderr)
            print(json.dumps({
                "metric": (metric_base + f"_w{n_workers}"
                           + ("_nosendfile" if no_sendfile else "")
                           + ("_smoke" if smoke else "")),
                "value": round(res["rps"], 1),
                "unit": "req/s",
                # the A/B this config exists for: this leg's RPS over
                # the sweep's first (single-worker) leg
                "vs_baseline": round(res["rps"] / base_rps, 2)
                if base_rps > 0 else 0.0,
                "workers": n_workers,
                "clients": clients,
                "requests": res["requests"],
                "p50_ms": round(res["p50_ms"], 2),
                "p99_ms": round(res["p99_ms"], 2),
                "p999_ms": round(res["p999_ms"], 2),
                "hot_full_p50_ms": round(res["full_p50_ms"], 3),
                "cond_304_p50_ms": round(res["cond_p50_ms"], 3),
                "cond_304_speedup": round(cond_speedup, 2),
                "host_cores": nproc(),
            }))
    # lint: broad-except-ok the driver contract (ONE parseable JSON
    # line, always) outranks the traceback; the error text carries it
    except Exception as err:
        print(json.dumps({
            "metric": metric_base, "value": 0.0, "unit": "req/s",
            "vs_baseline": 0.0,
            "error": f"{type(err).__name__}: {err}",
        }))
        sys.exit(3)


def bench_small_objects(argv=()) -> None:
    """BASELINE.md config 4's compute core: many concurrent small-object
    encodes (d=8 p=3, 4 MiB objects => [1, 8, S] batches) coalescing
    through the shared EncodeHashBatcher.  Reports aggregate ingest-side
    encode+hash throughput and the achieved coalescing factor.

    ``--threads N`` caps the native engine's host threads ("native:N");
    ``--sweep-threads 1,2,4,8`` runs the whole measurement once per N
    and prints one JSON line each — THE one-command scaling harness for
    the host-SHA row (run it on a multi-core host to turn BASELINE.md's
    projected SHA scaling into data; on a 1-core host the same command
    records the thread-contention overhead curve)."""
    import asyncio
    import os

    argv = list(argv)

    def flag_val(name):
        if name in argv:
            idx = argv.index(name) + 1
            if idx >= len(argv):
                print(f"usage: bench.py --config 4 [{name} N[,N...]]",
                      file=sys.stderr)
                sys.exit(2)
            return argv[idx]
        return None

    threads = flag_val("--threads")
    sweep = flag_val("--sweep-threads")
    if threads and sweep:
        print("--threads and --sweep-threads conflict; pick one",
              file=sys.stderr)
        sys.exit(2)
    specs = ([f"native:{n}" for n in sweep.split(",")] if sweep
             else [f"native:{threads}" if threads else None])

    from chunky_bits_tpu.ops.batching import EncodeHashBatcher

    d, p = 8, 3
    obj_bytes = 4 << 20
    size = obj_bytes // d
    n_objects = 96
    rng = np.random.default_rng(0)
    objs = [rng.integers(0, 256, (1, d, size), dtype=np.uint8)
            for _ in range(n_objects)]
    ready = _arm_if_device_backend(
        specs[0], "bulk_ingest_encode_hash_gibps_d8p3_4mib_objs")

    async def run(backend) -> float:
        batcher = EncodeHashBatcher(backend=backend)
        sem = asyncio.Semaphore(16)  # gateway-like request concurrency

        async def one(stacked):
            async with sem:
                await batcher.encode_hash(d, p, stacked)

        await one(objs[0])  # warm
        if ready is not None:
            ready.set()  # device answered the warm-up dispatch
        t0 = time.perf_counter()
        await asyncio.gather(*[one(o) for o in objs[1:]])
        dt = time.perf_counter() - t0
        # grouping factor: requests per coalesced group (merge-preferring
        # device backends additionally turn each group into ONE dispatch;
        # CPU backends run the group's batches back-to-back unmerged)
        coalesce = (n_objects - 1) / max(batcher.groups - 1, 1)
        print(f"# coalescing factor: {coalesce:.1f} objects/group "
              f"({batcher.dispatches} codec dispatches); "
              f"host cores: {os.cpu_count()} (per-shard SHA-256 is "
              f"host-side and scales with cores)", file=sys.stderr)
        return (n_objects - 1) * obj_bytes / dt / (1 << 30)

    for backend in specs:
        gib = asyncio.run(run(backend))
        print(json.dumps({
            "metric": "bulk_ingest_encode_hash_gibps_d8p3_4mib_objs"
                      + (f"_{backend.replace(':', '')}" if backend
                         else ""),
            "value": round(gib, 2), "unit": "GiB/s",
            "vs_baseline": round(gib / 5.0, 2),
            **({"host_cores": os.cpu_count()} if sweep else {}),
        }))


def bench_slab_store(argv=()) -> None:
    """BASELINE.md config 10: packed slab store vs file-per-chunk A/B
    (CPU-only, no device, no watchdog).  Many small objects are written
    and read back through two otherwise-identical clusters — one with
    plain path destinations (one chunk file per shard), one with
    ``slab:`` packed destinations (file/slab.py) — and the GC candidate
    enumeration is timed for both layouts: the dirent walk + per-file
    stat that find-unused-hashes pays on path destinations vs the slab
    index scan.  Byte identity between the legs is asserted in-run.

    Flags: ``--objects N`` (default 150), ``--obj-kib N`` object size
    (default 16), ``--smoke`` (CI-scale: 30 objects).

    Failure contract (tests/test_bench_outage.py): ANY failure still
    emits exactly one parseable JSON line and exits 3."""
    import asyncio
    import contextlib
    import os
    import tempfile

    argv = list(argv)

    def flag(name, default, cast):
        if name in argv:
            return cast(argv[argv.index(name) + 1])
        return default

    metric = "slab_small_object_get_ops_d3p2"
    try:
        objects = flag("--objects", 150, int)
        obj_kib = flag("--obj-kib", 16, int)
        if "--smoke" in argv:
            objects = min(objects, 30)
        if objects <= 0 or obj_kib <= 0:
            raise ValueError("--objects and --obj-kib must be positive")

        from chunky_bits_tpu.cluster import Cluster
        from chunky_bits_tpu.file import slab as slab_mod
        from chunky_bits_tpu.utils import aio

        rng = np.random.default_rng(0)
        payloads = [rng.integers(0, 256, obj_kib << 10,
                                 dtype=np.uint8).tobytes()
                    for _ in range(objects)]

        def make_cluster(root: str, packed: bool) -> Cluster:
            dirs = []
            for i in range(5):
                d = os.path.join(root, f"disk{i}")
                os.makedirs(d, exist_ok=True)
                dirs.append(f"slab:{d}" if packed else d)
            meta = os.path.join(root, "meta")
            os.makedirs(meta, exist_ok=True)
            return Cluster.from_obj({
                "destinations": [{"location": d} for d in dirs],
                "metadata": {"type": "path", "format": "yaml",
                             "path": meta},
                # small-object shape: d=3 p=2, 4 KiB chunks — the
                # regime where per-chunk open/stat overhead dominates
                "profiles": {"default": {"data": 3, "parity": 2,
                                         "chunk_size": 12}},
            })

        def walk_candidates_dirents(root: str) -> int:
            """The legacy GC enumeration: every dirent listed, every
            file stat'ed (the --grace-seconds age check)."""
            n = 0
            for dirpath, _dirs, files in os.walk(root):
                if os.path.basename(dirpath) == "meta":
                    continue
                for name in files:
                    os.stat(os.path.join(dirpath, name))
                    n += 1
            return n

        def walk_candidates_index(root: str) -> int:
            """The packed enumeration: one index scan per store."""
            n = 0
            for i in range(5):
                store = slab_mod.get_store(
                    os.path.join(root, f"disk{i}"))
                n += len(store.live_names())
            return n

        async def run_leg(root: str, packed: bool) -> dict:
            cluster = make_cluster(root, packed)
            profile = cluster.get_profile(None)
            t0 = time.perf_counter()
            for i, payload in enumerate(payloads):
                await cluster.write_file(
                    f"o{i:04d}", aio.BytesReader(payload), profile)
            put_s = time.perf_counter() - t0
            bodies = []
            t0 = time.perf_counter()
            for i in range(objects):
                ref = await cluster.get_file_ref(f"o{i:04d}")
                bodies.append(
                    await cluster.file_read_builder(ref).read_all())
            get_s = time.perf_counter() - t0
            for i, body in enumerate(bodies):
                assert body == payloads[i], \
                    f"byte identity failed (packed={packed}, obj {i})"
            walk = (walk_candidates_index if packed
                    else walk_candidates_dirents)
            t0 = time.perf_counter()
            chunks = walk(root)
            gc_s = time.perf_counter() - t0
            await cluster.tunables.location_context().aclose()
            return {"put_ops": objects / put_s,
                    "get_ops": objects / get_s,
                    "gc_walk_ms": gc_s * 1000.0,
                    "chunks": chunks}

        async def run() -> tuple:
            with contextlib.ExitStack() as stack:
                files_root = stack.enter_context(
                    tempfile.TemporaryDirectory())
                slab_root = stack.enter_context(
                    tempfile.TemporaryDirectory())
                files = await run_leg(files_root, packed=False)
                packed = await run_leg(slab_root, packed=True)
            return files, packed

        files, packed = asyncio.run(run())
        get_ab = (packed["get_ops"] / files["get_ops"]
                  if files["get_ops"] > 0 else 0.0)
        gc_ab = (files["gc_walk_ms"] / packed["gc_walk_ms"]
                 if packed["gc_walk_ms"] > 0 else 0.0)
        print(f"# config 10: {objects} x {obj_kib} KiB objects d=3 p=2 "
              f"4 KiB chunks over 5 nodes — files PUT/GET "
              f"{files['put_ops']:.1f}/{files['get_ops']:.1f} obj/s, "
              f"slab PUT/GET {packed['put_ops']:.1f}/"
              f"{packed['get_ops']:.1f} obj/s ({get_ab:.2f}x GET) | "
              f"GC walk {files['gc_walk_ms']:.1f} ms "
              f"({files['chunks']} dirents) vs "
              f"{packed['gc_walk_ms']:.1f} ms index ({gc_ab:.1f}x)",
              file=sys.stderr)
        print(json.dumps({
            "metric": metric,
            "value": round(packed["get_ops"], 1), "unit": "obj/s",
            # the A/B verdict: >= 1.0 means the packed layout serves
            # small-object GETs at least as fast as file-per-chunk
            "vs_baseline": round(get_ab, 3),
            "put_files_ops": round(files["put_ops"], 1),
            "put_slab_ops": round(packed["put_ops"], 1),
            "get_files_ops": round(files["get_ops"], 1),
            "gc_walk_files_ms": round(files["gc_walk_ms"], 2),
            "gc_walk_slab_ms": round(packed["gc_walk_ms"], 2),
            "gc_walk_speedup": round(gc_ab, 2),
            "chunks": files["chunks"],
        }))
    # lint: broad-except-ok the driver contract (ONE parseable JSON
    # line, always) outranks the traceback; the error text carries it
    except Exception as err:
        print(json.dumps({
            "metric": metric, "value": 0.0, "unit": "obj/s",
            "vs_baseline": 0.0,
            "error": f"{type(err).__name__}: {err}",
        }))
        sys.exit(3)


def bench_meta_log(argv=()) -> None:
    """BASELINE.md config 18: indexed meta-log vs file-per-ref
    metadata-plane A/B (CPU-only, no device, no watchdog).  The same
    namespace of d=3 p=2-shaped file references, laid out
    hierarchically (32 x 16 directories), is published through two
    stores behind the same MetadataStore surface — ``type: path``
    (one file per ref, the reference's shape) and ``type: meta-log``
    (cluster/meta_log.py: append-only ref log + journal-committed
    index) — and every namespace-scale operation the PR moved onto
    the index is timed through the surface each store actually
    serves:

    - recursive listing (the walk ``Cluster.list_files`` callers pay:
      one ``list()`` round-trip per directory, vs ONE index scan via
      ``list_files_recursive``),
    - prefix scan (one subtree),
    - scrub-pass metadata cost (the priority pre-scan: the legacy
      store must walk the namespace AND read+parse every ref before
      it can order the pass — ``ScrubDaemon._namespace_refs`` — while
      the meta-log scores the whole namespace from one index scan of
      publish-time node keys, ``namespace_nodes``, reading zero ref
      bytes: ``_index_prescan``),
    - GC live-hash candidate walk (the ``find-unused-hashes`` liveness
      set: per-file ref reads + hash extraction vs a pure index scan
      of publish-time hash projections, ``namespace_hashes``),
    - verify-walk fetch (meta-log only, informational: one batched
      ``namespace_snapshot`` — the grouped-read cost the paged verify
      walk pays across a whole pass),
    - cold-start index build (meta-log only: journal replay into a
      fresh index — the restart cost the path store does not have but
      also cannot amortize).

    Ref payloads are asserted byte-identical across the stores in-run
    (sampled every ~97th name; the golden ``meta_log_placement``
    fixture pins the same property for real cluster writes).

    Flags: ``--objects N`` (default 10000), ``--smoke`` (CI-scale:
    1000 objects).

    Failure contract (tests/test_bench_outage.py): ANY failure still
    emits exactly one parseable JSON line and exits 3."""
    import asyncio
    import contextlib
    import hashlib
    import os
    import tempfile

    argv = list(argv)

    def flag(name, default, cast):
        if name in argv:
            return cast(argv[argv.index(name) + 1])
        return default

    metric = "meta_log_scrub_meta_speedup_10k"
    try:
        objects = flag("--objects", 10_000, int)
        if "--smoke" in argv:
            objects = min(objects, 1_000)
        if objects <= 0:
            raise ValueError("--objects must be positive")

        from chunky_bits_tpu.cluster.meta_log import MetadataLog, MetaLogStore
        from chunky_bits_tpu.cluster.metadata import (MetadataFormat,
                                                      MetadataPath)

        def name_of(i: int) -> str:
            return f"ns{i % 32:02d}/g{(i // 32) % 16:02d}/o{i:06d}"

        def ref_obj(i: int) -> dict:
            """One d=3 p=2 single-part ref in the exact to_obj layout
            a real write produces (see the golden fixtures), hashes
            deterministic per object."""

            def chunk(j: int) -> dict:
                digest = hashlib.sha256(f"{i}:{j}".encode()).hexdigest()
                return {"sha256": digest,
                        "locations": [f"d{j}/sha256-{digest}"]}

            return {"length": 12_288,
                    "parts": [{"chunksize": 4096,
                               "data": [chunk(j) for j in range(3)],
                               "parity": [chunk(j) for j in (3, 4)]}]}

        refs = [ref_obj(i) for i in range(objects)]
        names = [name_of(i) for i in range(objects)]

        async def walk_paths(store) -> list:
            """The legacy recursive file enumeration: one ``list()``
            round-trip per directory (ScrubDaemon._list_file_paths's
            shape)."""
            out, stack = [], ["."]
            while stack:
                path = stack.pop()
                for entry in await store.list(path):
                    if str(entry.path) in (".", path):
                        continue
                    if entry.is_directory():
                        stack.append(entry.path)
                    elif entry.is_file():
                        out.append(entry.path)
            return out

        def extract_hashes(obj, into: set) -> None:
            # display form, matching the index projection's str(hash)
            for part in obj["parts"]:
                for chunk in part["data"] + part["parity"]:
                    into.add("sha256-" + chunk["sha256"])

        async def run_leg(root: str, kind: str) -> dict:
            meta = os.path.join(root, "meta")
            os.makedirs(meta, exist_ok=True)
            # json-strict: the one format that parses via json.loads —
            # keeps the shared parse cost from drowning the I/O delta
            # either leg (both legs pay it identically).  Constructed
            # directly, NOT via metadata_from_obj: the A/B must stay
            # path-vs-log even when $CHUNKY_BITS_TPU_METADATA_KIND
            # would rebuild the path leg fleet-wide.
            fmt = MetadataFormat("json-strict")
            if kind == "path":
                store: object = MetadataPath(path=meta, format=fmt)
            else:
                store = MetadataLog(path=meta, format=fmt)
            t0 = time.perf_counter()
            for name, obj in zip(names, refs):
                await store.write(name, obj)
            put_s = time.perf_counter() - t0
            recursive = getattr(store, "list_files_recursive", None)
            t0 = time.perf_counter()
            if recursive is not None:
                files = await recursive("")
            else:
                files = await walk_paths(store)
            list_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            if recursive is not None:
                subtree = await recursive("ns07")
            else:
                out, stack = [], ["ns07"]
                while stack:
                    path = stack.pop()
                    for entry in await store.list(path):
                        if str(entry.path) in (".", path):
                            continue
                        if entry.is_directory():
                            stack.append(entry.path)
                        elif entry.is_file():
                            out.append(entry.path)
                subtree = out
            prefix_s = time.perf_counter() - t0
            # scrub-pass metadata cost — the priority pre-scan: the
            # legacy store cannot order a pass without walking the
            # namespace and reading+parsing EVERY ref (the refs it
            # then scrubs come from this same snapshot); the meta-log
            # scores the whole namespace from one index scan of
            # publish-time node keys, zero ref reads
            # (scrub._index_prescan's shape, here against an empty
            # degraded set — the set test costs the same either way)
            index_nodes = getattr(store, "namespace_nodes", None)
            degraded: frozenset = frozenset()
            t0 = time.perf_counter()
            if index_nodes is not None:
                rows = await index_nodes()
                assert rows is not None, "index projection missing"
                scanned = [
                    (0 if degraded and any(k in degraded for k in nk)
                     else 2, name)
                    for name, nk in rows]
            else:
                scanned = [(p, await store.read(p))
                           for p in await walk_paths(store)]
            scrub_s = time.perf_counter() - t0
            # GC live-hash walk (find-unused-hashes' liveness set): a
            # separate pass with its own listing (GC runs in its own
            # process per batch) — per-file ref reads + extraction on
            # the legacy store, a pure index scan of publish-time hash
            # projections on the meta-log (_get_hashes_snapshot's
            # shape)
            live: set = set()
            index_hashes = getattr(store, "namespace_hashes", None)
            t0 = time.perf_counter()
            if index_hashes is not None:
                hrows = await index_hashes()
                assert hrows is not None, "hash projection missing"
                for _name, hs in hrows:
                    live.update(hs)
            else:
                for p in await walk_paths(store):
                    extract_hashes(await store.read(p), live)
            gc_s = time.perf_counter() - t0
            snapshot_ms = 0.0
            cold_ms = 0.0
            if kind == "meta-log":
                # verify-walk fetch, informational: one batched
                # snapshot = the grouped-read+parse cost the paged
                # verify walk spreads across a whole pass
                t0 = time.perf_counter()
                fetched = await store.namespace_snapshot()
                snapshot_ms = (time.perf_counter() - t0) * 1000.0
                assert len(fetched) == objects, \
                    f"snapshot {len(fetched)} != {objects}"
                del fetched
                # cold-start index build: journal replay into a FRESH
                # store (deliberately not get_store's warm instance)
                t0 = time.perf_counter()
                cold = MetaLogStore(meta)
                n_cold = len(cold.live_names())
                cold_ms = (time.perf_counter() - t0) * 1000.0
                assert n_cold == objects, \
                    f"cold index {n_cold} != {objects}"
            assert len(files) == objects, \
                f"{kind} listed {len(files)} != {objects}"
            assert len(scanned) == objects, \
                f"{kind} scanned {len(scanned)} != {objects}"
            assert len(subtree) == sum(
                1 for n in names if n.startswith("ns07/")), \
                f"{kind} prefix scan miscounted"
            return {"put_ops": objects / put_s,
                    "list_ms": list_s * 1000.0,
                    "prefix_ms": prefix_s * 1000.0,
                    "scrub_ms": scrub_s * 1000.0,
                    "gc_ms": gc_s * 1000.0,
                    "snapshot_ms": snapshot_ms,
                    "cold_ms": cold_ms,
                    "live_hashes": live,
                    "meta_dir": meta}

        async def run() -> tuple:
            with contextlib.ExitStack() as stack:
                path_root = stack.enter_context(
                    tempfile.TemporaryDirectory())
                log_root = stack.enter_context(
                    tempfile.TemporaryDirectory())
                path_leg = await run_leg(path_root, "path")
                log_leg = await run_leg(log_root, "meta-log")
                # byte identity across stores, asserted in-run on a
                # sample (every ~97th name, first and last included)
                log_store = MetaLogStore(log_leg["meta_dir"])
                step = max(1, objects // 97)
                compared = 0
                for i in list(range(0, objects, step)) + [objects - 1]:
                    fpath = os.path.join(
                        path_leg["meta_dir"],
                        *names[i].split("/"))
                    with open(fpath, "rb") as f:
                        path_bytes = f.read()
                    log_bytes = log_store.read_bytes(names[i])
                    assert path_bytes == log_bytes, \
                        f"ref {names[i]} differs across stores"
                    compared += 1
            return path_leg, log_leg, compared

        path_leg, log_leg, compared = asyncio.run(run())
        # full SET equality: the index projection and the parsed refs
        # must agree on every live hash, or GC would delete live data
        assert path_leg["live_hashes"] == log_leg["live_hashes"], \
            "GC liveness sets differ across stores"

        def speedup(key: str) -> float:
            return (path_leg[key] / log_leg[key]
                    if log_leg[key] > 0 else 0.0)

        list_ab = speedup("list_ms")
        prefix_ab = speedup("prefix_ms")
        scrub_ab = speedup("scrub_ms")
        gc_ab = speedup("gc_ms")
        print(f"# config 18: {objects} refs over 32x16 dirs — PUT "
              f"path/log {path_leg['put_ops']:.0f}/"
              f"{log_leg['put_ops']:.0f} obj/s | list "
              f"{path_leg['list_ms']:.1f} vs {log_leg['list_ms']:.1f} "
              f"ms ({list_ab:.1f}x) | prefix "
              f"{path_leg['prefix_ms']:.1f} vs "
              f"{log_leg['prefix_ms']:.1f} ms ({prefix_ab:.1f}x) | "
              f"scrub-meta {path_leg['scrub_ms']:.0f} vs "
              f"{log_leg['scrub_ms']:.0f} ms ({scrub_ab:.1f}x) | GC "
              f"{path_leg['gc_ms']:.0f} vs {log_leg['gc_ms']:.0f} ms "
              f"({gc_ab:.1f}x) | snapshot "
              f"{log_leg['snapshot_ms']:.0f} ms | cold index "
              f"{log_leg['cold_ms']:.1f} ms | {compared} refs "
              f"byte-identical", file=sys.stderr)
        print(json.dumps({
            "metric": metric,
            # the headline: how much cheaper a scrub pass's metadata
            # side got (>= 1.0 means the index wins)
            "value": round(scrub_ab, 2), "unit": "x",
            "vs_baseline": round(scrub_ab, 3),
            "objects": objects,
            "put_path_ops": round(path_leg["put_ops"], 1),
            "put_log_ops": round(log_leg["put_ops"], 1),
            "list_path_ms": round(path_leg["list_ms"], 2),
            "list_log_ms": round(log_leg["list_ms"], 2),
            "list_speedup": round(list_ab, 2),
            "prefix_path_ms": round(path_leg["prefix_ms"], 2),
            "prefix_log_ms": round(log_leg["prefix_ms"], 2),
            "prefix_speedup": round(prefix_ab, 2),
            "scrub_meta_path_ms": round(path_leg["scrub_ms"], 2),
            "scrub_meta_log_ms": round(log_leg["scrub_ms"], 2),
            "scrub_meta_speedup": round(scrub_ab, 2),
            "gc_live_path_ms": round(path_leg["gc_ms"], 2),
            "gc_live_log_ms": round(log_leg["gc_ms"], 2),
            "gc_live_speedup": round(gc_ab, 2),
            "snapshot_log_ms": round(log_leg["snapshot_ms"], 2),
            "cold_index_ms": round(log_leg["cold_ms"], 2),
            "refs_byte_identical": compared,
        }))
    # lint: broad-except-ok the driver contract (ONE parseable JSON
    # line, always) outranks the traceback; the error text carries it
    except Exception as err:
        print(json.dumps({
            "metric": metric, "value": 0.0, "unit": "x",
            "vs_baseline": 0.0,
            "error": f"{type(err).__name__}: {err}",
        }))
        sys.exit(3)


def bench_repair_bandwidth(argv=()) -> None:
    """BASELINE.md config 11: repair-bandwidth A/B (CPU-only, no
    device, no watchdog).  Many small objects are written with
    per-chunk block-digest trees (``repair_block_bytes``) onto MIXED
    ``slab:`` and plain-path destinations, a localized single-block bit
    flip is injected into one chunk replica of a corrupt subset, and a
    scrub/repair pass runs once per leg: OFF = the legacy shape
    (``ScrubDaemon(planner=False)``: part-granular resilver re-reads
    every replica of a damaged part), ON = the targeted repair planner
    (cluster/repair.py: block-localized ranged reads off the
    healthiest d helpers, batched rebuild, in-place rewrite).  Reported
    per leg: repair bytes read per rebuilt chunk byte (the headline —
    the planner's structural win), scrub wall time, and the per-node
    I/O-completion distribution (health-scoreboard completions over the
    pass: verification + helper reads AND repair writes — the node
    balance view, not a pure read count); repaired objects are
    asserted byte-identical to their payloads in-run.  Repair reads are measured from the metrics
    registry's ``cb_io_bytes_total{op=read}`` deltas (a profiler rides
    the pass, so every location read is recorded) minus the scrub
    stats' verification bytes — actual I/O, not estimates.

    Flags: ``--objects N`` (default 200), ``--corrupt N`` damaged
    objects (default 40), ``--chunk-log2 N`` (default 16 = 64 KiB),
    ``--block-kib N`` digest-tree block (default 4), ``--smoke``
    (CI-scale: 30 objects, 8 corrupt).

    Failure contract (tests/test_bench_outage.py): ANY failure still
    emits exactly one parseable JSON line and exits 3."""
    import asyncio
    import contextlib
    import os
    import random as _random
    import tempfile

    argv = list(argv)

    def flag(name, default, cast):
        if name in argv:
            return cast(argv[argv.index(name) + 1])
        return default

    metric = "repair_bytes_reduction_d3p2_localized"
    try:
        objects = flag("--objects", 200, int)
        corrupt = flag("--corrupt", 40, int)
        chunk_log2 = flag("--chunk-log2", 16, int)
        block_kib = flag("--block-kib", 4, int)
        if "--smoke" in argv:
            objects = min(objects, 30)
            corrupt = min(corrupt, 8)
        if objects <= 0 or corrupt <= 0 or corrupt > objects:
            raise ValueError(
                "--objects and --corrupt must be positive, "
                "corrupt <= objects")
        if not (12 <= chunk_log2 <= 22):
            raise ValueError("--chunk-log2 out of range [12, 22]")
        if block_kib <= 0 or (block_kib << 10) >= (1 << chunk_log2):
            raise ValueError(
                "--block-kib must be positive and smaller than a chunk")

        from chunky_bits_tpu.cluster import Cluster
        from chunky_bits_tpu.cluster.scrub import ScrubDaemon
        from chunky_bits_tpu.file.profiler import new_profiler
        from chunky_bits_tpu.obs.metrics import get_registry
        from chunky_bits_tpu.utils import aio

        d, p = 3, 2
        chunk_bytes = 1 << chunk_log2
        block_bytes = block_kib << 10
        rng = np.random.default_rng(0)
        payloads = [rng.integers(0, 256, d * chunk_bytes,
                                 dtype=np.uint8).tobytes()
                    for _ in range(objects)]
        picks = _random.Random(7)
        # (object index, damaged chunk slot, byte offset) per victim —
        # identical corruption for both legs
        damage = [(i, picks.randrange(d),
                   picks.randrange(chunk_bytes))
                  for i in picks.sample(range(objects), corrupt)]

        def make_cluster(root: str) -> Cluster:
            dirs = []
            for i in range(5):
                disk = os.path.join(root, f"disk{i}")
                os.makedirs(disk, exist_ok=True)
                # the mixed-operations shape: packed slab stores AND
                # file-per-chunk path destinations in one cluster
                dirs.append(f"slab:{disk}" if i < 3 else disk)
            meta = os.path.join(root, "meta")
            os.makedirs(meta, exist_ok=True)
            return Cluster.from_obj({
                "destinations": [{"location": x} for x in dirs],
                "metadata": {"type": "path", "format": "yaml",
                             "path": meta},
                "profiles": {"default": {
                    "data": d, "parity": p,
                    "chunk_size": chunk_log2}},
                "tunables": {"repair_block_bytes": block_bytes},
            })

        def flip_byte(location, offset: int) -> None:
            """One-byte bit flip inside a replica, path or slab."""
            if location.is_slab():
                path, base, length = location.slab_extent()
                pos = base + min(offset, length - 1)
            else:
                path = location.target
                pos = offset
            with open(path, "r+b") as f:
                f.seek(pos)
                byte = f.read(1)
                f.seek(pos)
                f.write(bytes([byte[0] ^ 0xFF]))

        def read_bytes_total() -> float:
            """cb_io_bytes_total{op=read} from the process registry —
            every profiled location read in the process so far."""
            for fam in get_registry().snapshot()["families"]:
                if fam["name"] == "cb_io_bytes_total":
                    return sum(s["value"] for s in fam["samples"]
                               if s["labels"].get("op") == "read")
            return 0.0

        async def run_leg(root: str, planner: bool) -> dict:
            cluster = make_cluster(root)
            profile = cluster.get_profile(None)
            for i, payload in enumerate(payloads):
                await cluster.write_file(
                    f"o{i:04d}", aio.BytesReader(payload), profile)
            for i, slot, offset in damage:
                ref = await cluster.get_file_ref(f"o{i:04d}")
                flip_byte(ref.parts[0].data[slot].locations[0], offset)
            before_nodes = {
                row.key: row.completions
                for row in cluster.health_scoreboard().stats().locations}
            profiler, _reporter = new_profiler()
            daemon = ScrubDaemon(cluster, bytes_per_sec=0,
                                 planner=planner, profiler=profiler)
            read_before = read_bytes_total()
            stats = await daemon.run_once()
            read_after = read_bytes_total()
            if stats.corrupt != corrupt or stats.repaired < corrupt:
                raise RuntimeError(
                    f"leg planner={planner}: corrupt={stats.corrupt} "
                    f"repaired={stats.repaired}, expected {corrupt}")
            for i, _slot, _offset in damage:
                ref = await cluster.get_file_ref(f"o{i:04d}")
                body = await cluster.file_read_builder(ref).read_all()
                assert body == payloads[i], \
                    f"byte identity failed (planner={planner}, obj {i})"
            repair_read = (read_after - read_before
                           - stats.bytes_verified)
            io_per_node = sorted(
                row.completions - before_nodes.get(row.key, 0)
                for row in cluster.health_scoreboard().stats().locations)
            out = {
                "repair_read_b": repair_read,
                "bytes_per_rebuilt":
                    repair_read / float(corrupt * chunk_bytes),
                "wall_s": stats.last_pass_seconds,
                "io_per_node": io_per_node,
            }
            if stats.repair is not None:
                out["repair"] = stats.repair
            await cluster.tunables.location_context().aclose()
            return out

        async def run() -> tuple:
            with contextlib.ExitStack() as stack:
                off_root = stack.enter_context(
                    tempfile.TemporaryDirectory())
                on_root = stack.enter_context(
                    tempfile.TemporaryDirectory())
                off = await run_leg(off_root, planner=False)
                on = await run_leg(on_root, planner=True)
            return off, on

        off, on = asyncio.run(run())
        reduction = (off["bytes_per_rebuilt"] / on["bytes_per_rebuilt"]
                     if on["bytes_per_rebuilt"] > 0 else 0.0)
        rep = on.get("repair", {})
        print(f"# config 11: {objects} x {d}x{chunk_bytes >> 10} KiB "
              f"objects d={d} p={p}, {corrupt} with one flipped byte, "
              f"{block_kib} KiB blocks, mixed slab/path — repair reads "
              f"{off['repair_read_b'] / 1024:.0f} KiB off vs "
              f"{on['repair_read_b'] / 1024:.0f} KiB on "
              f"({off['bytes_per_rebuilt']:.2f} vs "
              f"{on['bytes_per_rebuilt']:.2f} B/rebuilt B, "
              f"{reduction:.1f}x less) | scrub pass "
              f"{off['wall_s']:.2f}s vs {on['wall_s']:.2f}s | plans "
              f"copy/decode/fallback {rep.get('plans_copy', 0)}/"
              f"{rep.get('plans_decode', 0)}/"
              f"{rep.get('plans_fallback', 0)}", file=sys.stderr)
        print(json.dumps({
            "metric": metric,
            "value": round(reduction, 2), "unit": "x",
            # the acceptance target is a >= 3x reduction in repair
            # bytes read per rebuilt byte; vs_baseline >= 1.0 = met
            "vs_baseline": round(reduction / 3.0, 2),
            "objects": objects, "corrupt": corrupt,
            "chunk_kib": chunk_bytes >> 10, "block_kib": block_kib,
            "repair_read_off_b": int(off["repair_read_b"]),
            "repair_read_on_b": int(on["repair_read_b"]),
            "bytes_per_rebuilt_off": round(
                off["bytes_per_rebuilt"], 3),
            "bytes_per_rebuilt_on": round(on["bytes_per_rebuilt"], 3),
            "wall_off_s": round(off["wall_s"], 3),
            "wall_on_s": round(on["wall_s"], 3),
            "helper_b_replica_on": rep.get("helper_bytes_replica", 0),
            "helper_b_decode_on": rep.get("helper_bytes_decode", 0),
            "plans_copy": rep.get("plans_copy", 0),
            "plans_decode": rep.get("plans_decode", 0),
            "plans_fallback": rep.get("plans_fallback", 0),
            "io_per_node_off": off["io_per_node"],
            "io_per_node_on": on["io_per_node"],
        }))
    # lint: broad-except-ok the driver contract (ONE parseable JSON
    # line, always) outranks the traceback; the error text carries it
    except Exception as err:
        print(json.dumps({
            "metric": metric, "value": 0.0, "unit": "x",
            "vs_baseline": 0.0,
            "error": f"{type(err).__name__}: {err}",
        }))
        sys.exit(3)


def bench_pm_msr_repair(argv=()) -> None:
    """BASELINE.md config 13: product-matrix MSR regenerating code vs
    Reed-Solomon repair bandwidth (CPU-only, no device, no watchdog).

    The config-11-style single-chunk-loss workload: many one-part
    objects at d=5 p=4, one data chunk's ONLY replica deleted on a
    corrupt subset (whole-chunk loss — the regime regenerating codes
    exist for), one scrub/repair pass per leg.  The ``rs`` leg repairs
    through the planner's decode plan at the classic information-
    theoretic floor (d whole-chunk helper reads per rebuilt chunk);
    the ``pm-msr`` leg (ops/pm_msr.py) regenerates from d' = 2(d-1)
    β-sized helper projections — d'·β = 2·chunksize helper bytes, i.e.
    d/2 = 2.5x below the rs floor at this geometry.

    Reported per leg: helper bytes read per rebuilt byte (the headline
    — the planner's per-code counters, exactly the repair-plane bytes
    a distributed deployment would move), scrub wall time, and the
    disk-side read delta (``cb_io_bytes_total{op=read}`` minus
    verification bytes — the local-helper full reads the projections
    are computed from, reported honestly alongside).  In-run asserts:
    repaired objects byte-identical to their payloads; pm-msr encode
    and repair byte-identical between the numpy and native backends;
    exact bucket-sum equality per plan (rs: plans·d·chunk; pm-msr:
    plans·d'·β — the config-11 accounting discipline).

    Flags: ``--objects N`` (default 120), ``--corrupt N`` (default 30),
    ``--chunk-log2 N`` (default 14 = 16 KiB), ``--smoke`` (CI-scale:
    20 objects, 6 corrupt).

    Failure contract (tests/test_bench_outage.py): ANY failure still
    emits exactly one parseable JSON line and exits 3."""
    import asyncio
    import contextlib
    import os
    import random as _random
    import tempfile

    argv = list(argv)

    def flag(name, default, cast):
        if name in argv:
            return cast(argv[argv.index(name) + 1])
        return default

    metric = "pm_msr_repair_bytes_reduction_d5p4"
    try:
        objects = flag("--objects", 120, int)
        corrupt = flag("--corrupt", 30, int)
        chunk_log2 = flag("--chunk-log2", 14, int)
        if "--smoke" in argv:
            objects = min(objects, 20)
            corrupt = min(corrupt, 6)
        if objects <= 0 or corrupt <= 0 or corrupt > objects:
            raise ValueError(
                "--objects and --corrupt must be positive, "
                "corrupt <= objects")
        if not (12 <= chunk_log2 <= 22):
            raise ValueError("--chunk-log2 out of range [12, 22]")

        from chunky_bits_tpu.cluster import Cluster
        from chunky_bits_tpu.cluster.scrub import ScrubDaemon
        from chunky_bits_tpu.file.profiler import new_profiler
        from chunky_bits_tpu.obs.metrics import get_registry
        from chunky_bits_tpu.ops.backend import NumpyBackend, get_coder
        from chunky_bits_tpu.ops.pm_msr import PMMSRCoder
        from chunky_bits_tpu.utils import aio

        d, p = 5, 4
        alpha, dh = d - 1, 2 * (d - 1)
        chunk_bytes = 1 << chunk_log2
        beta = chunk_bytes // alpha
        rng = np.random.default_rng(0)
        payloads = [rng.integers(0, 256, d * chunk_bytes,
                                 dtype=np.uint8).tobytes()
                    for _ in range(objects)]
        picks = _random.Random(7)
        # (object index, lost data-chunk slot) per victim — identical
        # whole-chunk loss for both legs
        damage = [(i, picks.randrange(d))
                  for i in picks.sample(range(objects), corrupt)]

        # in-run backend identity: the pm-msr matrices must produce
        # byte-identical parity AND regenerations on numpy and native
        # (the same invariant the conformance fuzz pins; asserted here
        # so a bench round can never report a win off divergent math)
        c_np = PMMSRCoder(d, p, NumpyBackend())
        c_nat = get_coder(d, p, "native", code="pm-msr")
        sample = rng.integers(0, 256, (2, d, 8 * alpha), dtype=np.uint8)
        par_np = c_np.encode_batch(sample)
        if not np.array_equal(par_np, c_nat.encode_batch(sample)):
            raise RuntimeError("pm-msr parity differs numpy vs native")
        full = np.concatenate([sample, par_np], axis=1)
        helpers = [i for i in range(d + p) if i != 1][:dh]
        projs = np.stack([c_np.project_batch(1, full[:, h, :])
                          for h in helpers], axis=1)
        regen_np = c_np.repair_batch(1, helpers, projs)
        if not (np.array_equal(regen_np,
                               c_nat.repair_batch(1, helpers, projs))
                and np.array_equal(regen_np, full[:, 1, :])):
            raise RuntimeError("pm-msr regeneration differs or is wrong")

        def make_cluster(root: str, code: str) -> Cluster:
            dirs = []
            for i in range(d + p):
                disk = os.path.join(root, f"disk{i}")
                os.makedirs(disk, exist_ok=True)
                dirs.append(disk)
            meta = os.path.join(root, "meta")
            os.makedirs(meta, exist_ok=True)
            return Cluster.from_obj({
                "destinations": [{"location": x} for x in dirs],
                "metadata": {"type": "path", "format": "yaml",
                             "path": meta},
                "profiles": {"default": {
                    "data": d, "parity": p,
                    "chunk_size": chunk_log2, "code": code}},
            })

        def read_bytes_total() -> float:
            for fam in get_registry().snapshot()["families"]:
                if fam["name"] == "cb_io_bytes_total":
                    return sum(s["value"] for s in fam["samples"]
                               if s["labels"].get("op") == "read")
            return 0.0

        async def run_leg(root: str, code: str) -> dict:
            cluster = make_cluster(root, code)
            profile = cluster.get_profile(None)
            for i, payload in enumerate(payloads):
                await cluster.write_file(
                    f"o{i:04d}", aio.BytesReader(payload), profile)
            for i, slot in damage:
                ref = await cluster.get_file_ref(f"o{i:04d}")
                os.remove(ref.parts[0].data[slot].locations[0].target)
            profiler, _reporter = new_profiler()
            daemon = ScrubDaemon(cluster, bytes_per_sec=0,
                                 planner=True, profiler=profiler)
            read_before = read_bytes_total()
            stats = await daemon.run_once()
            read_after = read_bytes_total()
            rep = stats.repair or {}
            leg = rep.get("by_code", {}).get(code, {})
            if stats.repaired < corrupt:
                raise RuntimeError(
                    f"leg code={code}: repaired={stats.repaired}, "
                    f"expected {corrupt}")
            # exact per-plan helper-byte accounting (the config-11
            # bucket-sum discipline): every counted helper byte is a
            # byte the plan shape predicts, no estimates
            if code == "pm-msr":
                if leg.get("plans_msr") != corrupt:
                    raise RuntimeError(f"pm-msr plans: {leg}")
                want = corrupt * dh * beta
                if leg.get("helper_bytes_msr") != want:
                    raise RuntimeError(
                        f"helper_bytes_msr {leg.get('helper_bytes_msr')}"
                        f" != plans*d'*beta {want}")
                helper_b = leg.get("helper_bytes_msr", 0)
            else:
                if leg.get("plans_decode") != corrupt:
                    raise RuntimeError(f"rs plans: {leg}")
                want = corrupt * d * chunk_bytes
                if leg.get("helper_bytes_decode") != want:
                    raise RuntimeError(
                        f"helper_bytes_decode "
                        f"{leg.get('helper_bytes_decode')} != "
                        f"plans*d*chunk {want}")
                helper_b = leg.get("helper_bytes_decode", 0)
            for i, _slot in damage:
                ref = await cluster.get_file_ref(f"o{i:04d}")
                body = await cluster.file_read_builder(ref).read_all()
                assert body == payloads[i], \
                    f"byte identity failed (code={code}, obj {i})"
            rebuilt_b = leg.get("bytes_rebuilt", 0)
            out = {
                "helper_b": helper_b,
                "bytes_per_rebuilt": helper_b / float(rebuilt_b or 1),
                "disk_read_b": read_after - read_before
                - stats.bytes_verified,
                "wall_s": stats.last_pass_seconds,
                "repair": rep,
            }
            await cluster.tunables.location_context().aclose()
            return out

        async def run() -> tuple:
            with contextlib.ExitStack() as stack:
                rs_root = stack.enter_context(
                    tempfile.TemporaryDirectory())
                pm_root = stack.enter_context(
                    tempfile.TemporaryDirectory())
                rs = await run_leg(rs_root, "rs")
                pm = await run_leg(pm_root, "pm-msr")
            return rs, pm

        rs, pm = asyncio.run(run())
        reduction = (rs["bytes_per_rebuilt"] / pm["bytes_per_rebuilt"]
                     if pm["bytes_per_rebuilt"] > 0 else 0.0)
        print(f"# config 13: {objects} x {d}x{chunk_bytes >> 10} KiB "
              f"objects d={d} p={p}, {corrupt} single-chunk losses — "
              f"helper bytes {rs['helper_b'] / 1024:.0f} KiB rs vs "
              f"{pm['helper_b'] / 1024:.0f} KiB pm-msr "
              f"({rs['bytes_per_rebuilt']:.2f} vs "
              f"{pm['bytes_per_rebuilt']:.2f} B/rebuilt B, "
              f"{reduction:.2f}x less; rs floor is d={d}, pm-msr is "
              f"d'/alpha={dh}/{alpha}) | disk reads "
              f"{rs['disk_read_b'] / 1024:.0f} vs "
              f"{pm['disk_read_b'] / 1024:.0f} KiB | scrub pass "
              f"{rs['wall_s']:.2f}s vs {pm['wall_s']:.2f}s",
              file=sys.stderr)
        print(json.dumps({
            "metric": metric,
            "value": round(reduction, 3), "unit": "x",
            # acceptance: pm-msr >= 1.5x below the rs d x damage floor
            "vs_baseline": round(reduction / 1.5, 3),
            "objects": objects, "corrupt": corrupt,
            "chunk_kib": chunk_bytes >> 10,
            "data": d, "parity": p, "alpha": alpha, "helpers": dh,
            "helper_b_rs": int(rs["helper_b"]),
            "helper_b_pm": int(pm["helper_b"]),
            "bytes_per_rebuilt_rs": round(rs["bytes_per_rebuilt"], 3),
            "bytes_per_rebuilt_pm": round(pm["bytes_per_rebuilt"], 3),
            "disk_read_rs_b": int(rs["disk_read_b"]),
            "disk_read_pm_b": int(pm["disk_read_b"]),
            "plans_msr": pm["repair"].get("plans_msr", 0),
            "plans_decode_rs": rs["repair"].get("plans_decode", 0),
            "wall_rs_s": round(rs["wall_s"], 3),
            "wall_pm_s": round(pm["wall_s"], 3),
        }))
    # lint: broad-except-ok the driver contract (ONE parseable JSON
    # line, always) outranks the traceback; the error text carries it
    except Exception as err:
        print(json.dumps({
            "metric": metric, "value": 0.0, "unit": "x",
            "vs_baseline": 0.0,
            "error": f"{type(err).__name__}: {err}",
        }))
        sys.exit(3)


def bench_sim_scenarios(argv=()) -> None:
    """BASELINE.md config 14: the deterministic cluster simulator's
    scenario-suite runner (CPU-only, no device, no watchdog).

    Runs every library scenario (chunky_bits_tpu/sim/scenario.py: AZ
    outage mid-scrub, rolling restart, pm-msr repair under helper
    churn, thundering herd, correlated in-zone disk failures, flapping
    node, slow-leak corruption) at fleet scale — N simulated nodes
    behind the production Location/Cluster/scrub/repair machinery on
    the virtual-time loop — and reports the virtual-vs-wall
    compression ratio (the headline: virtual seconds lived per wall
    second spent) plus per-scenario invariant verdicts.

    In-run asserts: every scenario passes ALL its verdicts (namespace
    converges to Valid, reads clean outside fault windows, hedge
    amplification within budget, repair bytes within the config-11/13
    structural bounds), and the AZ-outage scenario re-run with the
    same seed produces a byte-identical event trace and equal metrics
    snapshot (the determinism contract tests/test_sim.py pins at unit
    scale, observed here at fleet scale).

    Flags: ``--nodes N`` (default 100), ``--seed N`` (default 0),
    ``--scenarios a,b,...`` (default: the whole library), ``--smoke``
    (CI-scale: 12 nodes, 6 objects, 3 scenarios).

    Failure contract (tests/test_bench_outage.py): ANY failure —
    including a scenario failing an invariant — still emits exactly
    one parseable JSON line and exits 3."""
    import tempfile

    argv = list(argv)

    def flag(name, default, cast):
        if name in argv:
            return cast(argv[argv.index(name) + 1])
        return default

    metric = "sim_scenario_suite_compression"
    try:
        nodes = flag("--nodes", 100, int)
        seed = flag("--seed", 0, int)
        objects = flag("--objects", 0, int)  # 0 = scenario default
        picked = flag("--scenarios", "", str)
        smoke = "--smoke" in argv

        from chunky_bits_tpu.sim.scenario import (
            SCENARIOS,
            fresh_workdir,
            run_scenario,
        )

        if smoke:
            nodes = min(nodes, 12)
            objects = objects or 6  # an explicit --objects wins
            names = ["az_outage", "pm_msr_restart_repair",
                     "flapping_node"]
        else:
            names = sorted(SCENARIOS)
        if picked:
            names = [n.strip() for n in picked.split(",") if n.strip()]
        unknown = [n for n in names if n not in SCENARIOS]
        if unknown:
            raise ValueError(f"unknown scenario(s) {unknown} "
                             f"(know {sorted(SCENARIOS)})")
        if nodes <= 0:
            raise ValueError("--nodes must be positive")

        rows = []
        failed: list[str] = []
        with tempfile.TemporaryDirectory(prefix="cb_sim14_") as tmp:
            for name in names:
                workdir = fresh_workdir(f"{tmp}/{name}")
                result = run_scenario(
                    name, nodes=nodes, seed=seed, workdir=workdir,
                    objects=objects or None)
                row = result.to_obj()
                rows.append(row)
                if not result.ok():
                    failed.append(name)
                print(f"# config 14: {name}: "
                      f"{row['virtual_s']:.0f}s virtual in "
                      f"{row['wall_s']:.2f}s wall "
                      f"({row['compression_x']:.0f}x), verdicts "
                      f"{row['verdicts']}", file=sys.stderr)
            if failed:
                # fail fast: the exit-3 record must not wait out two
                # more full determinism runs
                raise AssertionError(
                    f"scenario invariants failed: {failed}; "
                    f"rows={rows}")
            # the determinism contract at fleet scale: same seed ⇒
            # byte-identical trace + equal metrics (two runs of the
            # acceptance scenario over one reused workdir)
            det_name = "az_outage" if "az_outage" in names else names[0]
            det_dir = f"{tmp}/det"
            fresh_workdir(det_dir)
            first = run_scenario(det_name, nodes=nodes, seed=seed,
                                 workdir=det_dir,
                                 objects=objects or None)
            fresh_workdir(det_dir)
            second = run_scenario(det_name, nodes=nodes, seed=seed,
                                  workdir=det_dir,
                                  objects=objects or None)
            deterministic = (first.trace == second.trace
                             and first.metrics == second.metrics)
        if not deterministic:
            raise AssertionError(
                f"{det_name} determinism violated: same seed produced "
                "differing traces/metrics")

        virtual_total = sum(r["virtual_s"] for r in rows)
        wall_total = sum(r["wall_s"] for r in rows)
        compression = (virtual_total / wall_total
                       if wall_total > 0 else 0.0)
        print(f"# config 14: {len(rows)} scenarios x {nodes} nodes: "
              f"{virtual_total:.0f}s virtual in {wall_total:.1f}s wall "
              f"= {compression:.0f}x compression; deterministic "
              f"({det_name} twice: trace+metrics identical)",
              file=sys.stderr)
        print(json.dumps({
            "metric": metric,
            "value": round(compression, 1), "unit": "x",
            # acceptance floor: the 100-node AZ-outage criterion (>= 30
            # virtual minutes inside 60 s wall) is 30x — the suite
            # should clear it with orders of margin
            "vs_baseline": round(compression / 30.0, 1),
            "nodes": nodes, "seed": seed,
            "scenarios": len(rows),
            # recomputed from the rows so the CI assert
            # scenarios_ok == scenarios stays a real check, not a
            # tautology, should the fail-fast above ever be relaxed
            "scenarios_ok": sum(1 for r in rows if r["ok"]),
            "virtual_s": round(virtual_total, 1),
            "wall_s": round(wall_total, 2),
            "deterministic": deterministic,
            "rows": rows,
        }))
    # lint: broad-except-ok the driver contract (ONE parseable JSON
    # line, always) outranks the traceback; the error text carries it
    except Exception as err:
        print(json.dumps({
            "metric": metric, "value": 0.0, "unit": "x",
            "vs_baseline": 0.0,
            "error": f"{type(err).__name__}: {err}"[:2000],
        }))
        sys.exit(3)


def bench_slo_detection(argv=()) -> None:
    """BASELINE.md config 15: SLO-engine detection quality + engine-off
    overhead (CPU-only, no device, no watchdog).

    Three legs, all asserted in-run:

    1. **Detection** — the simulator scenario suite (config 14's
       library) with the SLO engine's per-scenario verdicts: every
       expected alert fires within its virtual-time detection bound of
       the scripted fault and resolves after convergence, and the
       TOTAL false-positive count across the suite is zero (the
       engine runs in every scenario, including the silent controls).
       Reported per rule: virtual detection latency seconds.
    2. **Determinism** — one detection scenario re-run with the same
       seed must produce a byte-identical event trace (alert
       transitions included) and equal metrics + detection report.
    3. **Overhead A/B** — an in-process single-worker gateway serving
       sequential hot GETs with the engine OFF (the default) vs ON at
       a fast tick, interleaved both orderings: the engine must land
       within noise, because it is default-off and touches no hot
       path (its cost is one registry snapshot per tick).

    Flags: ``--nodes N`` (default 100), ``--seed N``, ``--scenarios
    a,b,...``, ``--reads N`` (overhead GETs per leg), ``--smoke``
    (CI-scale: 12 nodes, 3 scenarios, fewer reads).

    Failure contract (tests/test_bench_outage.py): ANY failure still
    emits exactly one parseable JSON line and exits 3."""
    import tempfile

    argv = list(argv)

    def flag(name, default, cast):
        if name in argv:
            return cast(argv[argv.index(name) + 1])
        return default

    metric = "slo_detection_latency"
    try:
        nodes = flag("--nodes", 100, int)
        seed = flag("--seed", 0, int)
        objects = flag("--objects", 0, int)
        reads = flag("--reads", 400, int)
        picked = flag("--scenarios", "", str)
        smoke = "--smoke" in argv

        from chunky_bits_tpu.sim.scenario import (
            SCENARIOS,
            fresh_workdir,
            run_scenario,
        )

        if smoke:
            nodes = min(nodes, 12)
            objects = objects or 6
            reads = min(reads, 120)
            names = ["thundering_herd", "fleet_partition",
                     "rolling_restart"]
        else:
            names = sorted(SCENARIOS)
        if picked:
            names = [n.strip() for n in picked.split(",") if n.strip()]
        unknown = [n for n in names if n not in SCENARIOS]
        if unknown:
            raise ValueError(f"unknown scenario(s) {unknown} "
                             f"(know {sorted(SCENARIOS)})")
        if nodes <= 0 or reads <= 0:
            raise ValueError("--nodes and --reads must be positive")

        # ---- leg 1: detection quality over the scenario suite ----
        rows = []
        failed: list[str] = []
        latencies: dict[str, float] = {}
        bounds: dict[str, float] = {}
        false_positives = 0
        with tempfile.TemporaryDirectory(prefix="cb_slo15_") as tmp:
            for name in names:
                workdir = fresh_workdir(f"{tmp}/{name}")
                result = run_scenario(
                    name, nodes=nodes, seed=seed, workdir=workdir,
                    objects=objects or None)
                slo = result.details.get("slo", {})
                row = {"name": name, "ok": result.ok(),
                       "verdicts": dict(sorted(
                           result.verdicts.items())),
                       **slo}
                rows.append(row)
                if not result.ok():
                    failed.append(name)
                false_positives += slo.get("false_positives", 0)
                for rule, lat in slo.get("detect_latency_s",
                                         {}).items():
                    key = f"{name}.{rule}"
                    latencies[key] = lat
                    bounds[key] = SCENARIOS[name].slo["expected"][
                        rule]["within_s"]
                print(f"# config 15: {name}: detect="
                      f"{slo.get('detect_latency_s', {})} "
                      f"fp={slo.get('false_positives', 0)}",
                      file=sys.stderr)
            if failed:
                raise AssertionError(
                    f"scenario verdicts failed: {failed}; rows={rows}")
            if false_positives:
                raise AssertionError(
                    f"false positives across the suite: "
                    f"{false_positives}; rows={rows}")
            if not latencies:
                raise AssertionError(
                    "no expected alerts in the selected scenarios — "
                    "detection quality unmeasured")

            # ---- leg 2: determinism (alert trace included) ----
            det_name = ("thundering_herd"
                        if "thundering_herd" in names else names[0])
            det_dir = f"{tmp}/det"
            fresh_workdir(det_dir)
            first = run_scenario(det_name, nodes=nodes, seed=seed,
                                 workdir=det_dir,
                                 objects=objects or None)
            fresh_workdir(det_dir)
            second = run_scenario(det_name, nodes=nodes, seed=seed,
                                  workdir=det_dir,
                                  objects=objects or None)
            deterministic = (
                first.trace == second.trace
                and first.metrics == second.metrics
                and first.details.get("slo") == second.details.get(
                    "slo"))
            if not deterministic:
                raise AssertionError(
                    f"{det_name} detection determinism violated")

        # ---- leg 3: engine-off overhead A/B ----
        overhead = _slo_overhead_ab(reads)
        print(f"# config 15: overhead A/B: off={overhead['rps_off']:.0f}"
              f" rps, on={overhead['rps_on']:.0f} rps, ratio="
              f"{overhead['on_off_ratio']:.3f} "
              f"(ticks={overhead['evaluations']})", file=sys.stderr)
        if overhead["on_off_ratio"] < 0.5:
            # a LOOSE in-run floor (2x would mean the engine somehow
            # entered the hot path); the within-noise claim is the
            # BASELINE.md record's job, not a CI coin-flip's
            raise AssertionError(
                f"engine-on gateway lost >2x throughput: {overhead}")

        worst_key = max(latencies, key=lambda k: latencies[k])
        worst = latencies[worst_key]
        margin = min(bounds[k] / max(latencies[k], 1e-9)
                     for k in latencies)
        print(f"# config 15: {len(rows)} scenarios x {nodes} nodes: "
              f"{len(latencies)} expected alerts all detected, "
              f"worst latency {worst:.0f}s virtual ({worst_key}), "
              f"0 false positives, deterministic", file=sys.stderr)
        print(json.dumps({
            "metric": metric,
            # the headline: worst virtual detection latency across
            # every expected alert in the suite
            "value": round(worst, 1), "unit": "s",
            # margin to the tightest detection bound (>1 = inside)
            "vs_baseline": round(margin, 2),
            "nodes": nodes, "seed": seed,
            "scenarios": len(rows),
            "alerts_expected": len(latencies),
            "alerts_detected": len(latencies),
            "false_positives": false_positives,
            "deterministic": deterministic,
            "detect_latency_s": {k: round(v, 1)
                                 for k, v in sorted(latencies.items())},
            **overhead,
            "rows": rows,
        }))
    # lint: broad-except-ok the driver contract (ONE parseable JSON
    # line, always) outranks the traceback; the error text carries it
    except Exception as err:
        print(json.dumps({
            "metric": metric, "value": 0.0, "unit": "s",
            "vs_baseline": 0.0,
            "error": f"{type(err).__name__}: {err}"[:2000],
        }))
        sys.exit(3)


def _slo_overhead_ab(reads: int) -> dict:
    """Config 15's leg 3: sequential keep-alive GETs against an
    in-process single-worker gateway, engine OFF vs ON (fast tick),
    interleaved both orderings (off,on,on,off) so drift cancels.
    Returns rps per mode + the on/off ratio."""
    import asyncio
    import os as _os
    import tempfile

    payload_kib = 64

    async def run_leg(slo_on: bool) -> tuple[float, int]:
        import aiohttp
        from aiohttp.test_utils import TestServer

        from chunky_bits_tpu.cluster import Cluster
        from chunky_bits_tpu.gateway import make_app

        with tempfile.TemporaryDirectory(prefix="cb_slo_ab_") as tmp:
            dirs = []
            for i in range(5):
                d = _os.path.join(tmp, f"disk{i}")
                _os.makedirs(d)
                dirs.append(d)
            meta = _os.path.join(tmp, "meta")
            _os.makedirs(meta)
            tunables: dict = {"cache_bytes": 8 << 20}
            if slo_on:
                tunables["slo_eval_s"] = 0.05  # ~20 ticks/s: far
                # denser than any production cadence, so the measured
                # delta UPPER-bounds the real engine-on cost
            cluster = Cluster.from_obj({
                "destinations": [{"location": d} for d in dirs],
                "metadata": {"type": "path", "format": "yaml",
                             "path": meta},
                "profiles": {"default": {"data": 3, "parity": 2,
                                         "chunk_size": 16}},
                "tunables": tunables,
            })
            server = TestServer(make_app(cluster))
            await server.start_server()
            evaluations = 0
            try:
                url = f"http://127.0.0.1:{server.port}"
                body = _os.urandom(payload_kib << 10)
                async with aiohttp.ClientSession() as session:
                    resp = await session.put(f"{url}/hot", data=body)
                    assert resp.status == 200, resp.status
                    # warm (fills the read cache on the cache path)
                    resp = await session.get(f"{url}/hot")
                    assert await resp.read() == body
                    t0 = time.monotonic()
                    for _ in range(reads):
                        resp = await session.get(f"{url}/hot")
                        data = await resp.read()
                        assert len(data) == len(body)
                    wall = time.monotonic() - t0
                    if slo_on:
                        resp = await session.get(f"{url}/alerts")
                        alerts = await resp.json()
                        assert alerts.get("enabled") is True, alerts
                        evaluations = alerts.get("evaluations", 0)
            finally:
                await server.close()
            await cluster.tunables.location_context().aclose()
            return reads / wall, evaluations

    async def run() -> dict:
        rps: dict[bool, list] = {False: [], True: []}
        evaluations = 0
        for slo_on in (False, True, True, False):
            leg_rps, evals = await run_leg(slo_on)
            rps[slo_on].append(leg_rps)
            evaluations = max(evaluations, evals)
        off = sum(rps[False]) / len(rps[False])
        on = sum(rps[True]) / len(rps[True])
        return {
            "rps_off": round(off, 1),
            "rps_on": round(on, 1),
            "on_off_ratio": round(on / off, 4),
            "evaluations": evaluations,
        }

    return asyncio.run(run())


def bench_crash_matrix(argv=()) -> None:
    """BASELINE.md config 16: the crash-consistency matrix suite
    (CPU-only, no device, no watchdog).

    Three legs, all asserted in-run (chunky_bits_tpu/sim/crash.py):

    1. **Matrix** — every storage-plane mutation (slab append +
       journal commit, GC mark-dead, compaction, atomic chunk
       publication, metadata publication, the repair planner's
       in-place rewrite) is recorded through the filesystem seam
       (file/fsio.py), then EVERY prefix "crash at op k" is replayed
       into a cloned directory under the kill / flush / torn-write /
       power-cut (per-file writeback masks) / power-cut-with-lost-
       renames failure models, and a cold restart is verified against
       the recovery invariants: durable data byte-exact, the mutated
       name absent|exact|detectably-damaged (powercut only),
       compaction leaves old or new journal (never neither),
       acknowledged metadata publications survive every model, the
       stale-temp reaper never eats a live file, and the store
       accepts new work.  ANY red image fails the run.
    2. **Scrub recovery** — a real erasure-coded cluster (five
       ``slab:`` destinations) ingests an object while one
       destination records; selected crash images of that node —
       including the journal-line-without-slab-bytes power-cut image
       slab.py documents — are spliced back and ``scrub --once``
       (production daemon + repair planner) must converge the
       namespace to Valid with byte-identical reads.
    3. **Determinism** — the whole matrix re-run with the same seed
       must produce the identical normalized op-stream + verdict
       digest.

    Flags: ``--seed N`` (default 0), ``--mutations a,b,...`` (default:
    the whole library), ``--smoke`` (CI-scale: three mutations, the
    power-cut scrub image only).

    Failure contract (tests/test_bench_outage.py): ANY failure still
    emits exactly one parseable JSON line and exits 3."""
    import tempfile
    import time as _time

    argv = list(argv)

    def flag(name, default, cast):
        if name in argv:
            return cast(argv[argv.index(name) + 1])
        return default

    metric = "crash_matrix_images"
    try:
        seed = flag("--seed", 0, int)
        picked = flag("--mutations", "", str)
        smoke = "--smoke" in argv

        from chunky_bits_tpu.sim import crash

        if smoke:
            names = ["slab_append", "slab_compact", "metadata_publish"]
            points = "smoke"
        else:
            names = sorted(crash.MUTATIONS)
            points = "full"
        if picked:
            names = [n.strip() for n in picked.split(",") if n.strip()]
        unknown = [n for n in names if n not in crash.MUTATIONS]
        if unknown:
            raise ValueError(f"unknown mutation(s) {unknown} "
                             f"(know {sorted(crash.MUTATIONS)})")

        t0 = _time.monotonic()
        with tempfile.TemporaryDirectory(prefix="cb_crash16_") as tmp:
            result = crash.run_matrix(f"{tmp}/m1", seed=seed,
                                      mutations=names)
            if not result.ok():
                raise AssertionError(
                    "crash images failed recovery: "
                    f"{[v.to_obj() for v in result.failed()[:6]]}")
            for row in result.rows():
                print(f"# config 16: {row['mutation']}: "
                      f"{row['ops']} ops, {row['images']} images, "
                      f"all recovered", file=sys.stderr)
            cluster_verdicts = crash.run_cluster_recovery(
                f"{tmp}/cluster", seed=seed, points=points)
            cluster_failed = [v for v in cluster_verdicts if not v.ok]
            if cluster_failed:
                raise AssertionError(
                    "scrub --once failed to converge crash images: "
                    f"{[v.to_obj() for v in cluster_failed[:6]]}")
            print(f"# config 16: scrub recovery: "
                  f"{len(cluster_verdicts)} cluster images (incl. the "
                  f"journal-line-without-slab-bytes power cut) all "
                  f"converged to Valid", file=sys.stderr)
            second = crash.run_matrix(f"{tmp}/m2", seed=seed,
                                      mutations=names)
            deterministic = (second.digest == result.digest
                             and second.ok())
            if not deterministic:
                raise AssertionError(
                    "crash matrix determinism violated: same seed "
                    f"produced digest {second.digest[:16]} vs "
                    f"{result.digest[:16]}")
        wall = _time.monotonic() - t0

        images = len(result.verdicts)
        images_ok = sum(1 for v in result.verdicts if v.ok)
        cluster_ok = sum(1 for v in cluster_verdicts if v.ok)
        print(f"# config 16: {len(names)} mutations, "
              f"{result.crash_points()} crash points, {images} images "
              f"+ {len(cluster_verdicts)} cluster images, all "
              f"recovered, deterministic, {wall:.1f}s wall",
              file=sys.stderr)
        print(json.dumps({
            "metric": metric,
            # the headline: how many distinct crash images were
            # verified invariant-clean this run
            "value": images_ok + cluster_ok, "unit": "images",
            # acceptance floor: every enumerated image recovers —
            # ratio of verified-clean to enumerated (must be 1.0)
            "vs_baseline": round(
                (images_ok + cluster_ok)
                / max(images + len(cluster_verdicts), 1), 3),
            "seed": seed,
            "mutations": len(names),
            "crash_points": result.crash_points(),
            "images": images,
            "images_ok": images_ok,
            "cluster_images": len(cluster_verdicts),
            "cluster_images_ok": cluster_ok,
            "deterministic": deterministic,
            "digest": result.digest,
            "wall_s": round(wall, 2),
            "rows": result.rows(),
        }))
    # lint: broad-except-ok the driver contract (ONE parseable JSON
    # line, always) outranks the traceback; the error text carries it
    except Exception as err:
        print(json.dumps({
            "metric": metric, "value": 0.0, "unit": "images",
            "vs_baseline": 0.0,
            "error": f"{type(err).__name__}: {err}"[:2000],
        }))
        sys.exit(3)


def bench_xor_schedule(argv=()) -> None:
    """BASELINE.md config 12: scheduled-XOR erasure engine vs the
    byte-table kernels (CPU-only, no tunnel, no gateway).

    A chunk-size x geometry grid, encode AND decode-with-p-erasures
    legs.  Each cell measures three engines on identical data, with
    in-run byte-identity asserts between them:

    * ``table``        — the current native path at its best runtime
      tier (GFNI > AVX2 pshufb > scalar on this build+CPU): the A/B's
      OFF leg and the headline ``speedup`` denominator;
    * ``table_scalar`` — the same kernels forced to the scalar table
      (``cb_gf_set_level(0)``): what a build/CPU without SIMD table
      kernels runs — the deployment the XOR engine exists for;
    * ``xor``          — the scheduled-XOR engine
      (``CHUNKY_BITS_TPU_XOR_SCHEDULE`` path, ops/xor_schedule.py).

    Flags: ``--sizes-kib 64,1024,4096`` / ``--geoms 3x2,10x4,20x6`` /
    ``--iters 3`` (best-of) / ``--mib 64`` (per-cell working set) /
    ``--smoke`` (one 64 KiB d=3 p=2 cell, seconds-scale — the CI
    step).  One JSON line always; failures exit 3 with the same
    contract as configs 8-11.  ``value`` is the best cell's speedup of
    xor over the CURRENT native path — the keep-the-winner rule: the
    flag stays opt-in unless this exceeds 1.0 on the deployment's own
    grid."""
    import time as _time

    metric = "cpu_xor_schedule_vs_native_speedup"
    try:
        from chunky_bits_tpu.ops import matrix, xor_schedule
        from chunky_bits_tpu.ops.cpu_backend import (NativeBackend,
                                                     gf_force_level)

        def flag(name, default, cast):
            argv_l = list(argv)
            if name in argv_l:
                return cast(argv_l[argv_l.index(name) + 1])
            return default

        smoke = "--smoke" in argv
        sizes = flag("--sizes-kib", "64" if smoke else "64,1024,4096",
                     str)
        geoms = flag("--geoms", "3x2" if smoke else "3x2,10x4,20x6",
                     str)
        iters = flag("--iters", 1 if smoke else 3, int)
        mib = flag("--mib", 8 if smoke else 64, int)
        size_list = [int(x) << 10 for x in sizes.split(",")]
        geom_list = []
        for g in geoms.split(","):
            d_s, p_s = g.lower().split("x")
            geom_list.append((int(d_s), int(p_s)))
        if iters < 1 or mib < 1 or not size_list or not geom_list:
            raise ValueError("need --iters >= 1, --mib >= 1 and "
                             "non-empty --sizes-kib/--geoms")
        for s in size_list:
            if s % 8 or s < 8:
                raise ValueError(f"--sizes-kib entries must be "
                                 f"multiples of 8 bytes, got {s}")
        for d, p in geom_list:
            if d < 1 or p < 1:
                raise ValueError(f"bad geometry d={d} p={p}")

        rng = np.random.default_rng(0)
        table = NativeBackend(xor_schedule=False)
        xor = NativeBackend(xor_schedule=True)

        def best_s(apply_fn):
            best = float("inf")
            for _ in range(iters):
                t0 = _time.perf_counter()
                apply_fn()
                best = min(best, _time.perf_counter() - t0)
            return best

        grid = []
        sched_meta = {}
        for d, p in geom_list:
            enc = matrix.build_encode_matrix(d, p)
            t0 = _time.perf_counter()
            sched = xor_schedule.get_schedule(enc[d:])
            sched_meta[f"{d}x{p}"] = {
                "build_ms": round((_time.perf_counter() - t0) * 1e3, 1),
                "raw_xors": sched.raw_xors,
                "ops": int(sched.ops.shape[0]),
                "planes": sched.n_planes,
            }
            for size in size_list:
                batch = max(1, (mib << 20) // (d * size))
                data = rng.integers(0, 256, (batch, d, size),
                                    dtype=np.uint8)
                nbytes = batch * d * size
                for leg in ("encode", "decode"):
                    if leg == "encode":
                        mat = enc[d:]
                        src = data
                    else:
                        parity = table.apply_matrix(enc[d:], data)
                        full = np.concatenate([data, parity], axis=1)
                        erased = sorted(
                            rng.choice(d + p, size=p,
                                       replace=False).tolist())
                        present = [i for i in range(d + p)
                                   if i not in erased]
                        mat = matrix.decode_matrix(enc, present, erased)
                        src = np.ascontiguousarray(
                            full[:, np.array(present[:d]), :])
                    # identity between the engines on this cell's data
                    want = table.apply_matrix(mat, src)
                    got = xor.apply_matrix(mat, src)
                    if not np.array_equal(want, got):
                        raise RuntimeError(
                            f"byte identity broke at d={d} p={p} "
                            f"size={size} {leg}")
                    del want, got
                    t_best = best_s(lambda: table.apply_matrix(mat, src))
                    gf_force_level(0)
                    try:
                        t_scalar = best_s(
                            lambda: table.apply_matrix(mat, src))
                    finally:
                        gf_force_level(2)
                    x_best = best_s(lambda: xor.apply_matrix(mat, src))
                    cell = {
                        "size_kib": size >> 10, "d": d, "p": p,
                        "leg": leg,
                        "table_gibps": round(
                            nbytes / t_best / (1 << 30), 2),
                        "table_scalar_gibps": round(
                            nbytes / t_scalar / (1 << 30), 2),
                        "xor_gibps": round(
                            nbytes / x_best / (1 << 30), 2),
                        "speedup": round(t_best / x_best, 2),
                        "speedup_vs_scalar": round(
                            t_scalar / x_best, 2),
                    }
                    grid.append(cell)
                    print(f"# config 12: d{d}p{p} {size >> 10}KiB "
                          f"{leg}: table {cell['table_gibps']} "
                          f"(scalar {cell['table_scalar_gibps']}) vs "
                          f"xor {cell['xor_gibps']} GiB/s -> "
                          f"{cell['speedup']}x "
                          f"(vs scalar {cell['speedup_vs_scalar']}x)",
                          file=sys.stderr)
        best_cell = max(grid, key=lambda c: c["speedup"])
        wins = sum(1 for c in grid if c["speedup"] > 1.0)
        wins_scalar = sum(1 for c in grid
                          if c["speedup_vs_scalar"] > 1.0)
        print(json.dumps({
            "metric": metric,
            # the keep-the-winner gate: > 1.0 anywhere on the grid is
            # the only thing that would justify defaulting the flag on
            "value": best_cell["speedup"], "unit": "x",
            "vs_baseline": best_cell["speedup"],
            "wins": wins, "cells": len(grid),
            "wins_vs_scalar": wins_scalar,
            "best_cell": {k: best_cell[k]
                          for k in ("size_kib", "d", "p", "leg")},
            "schedules": sched_meta,
            "grid": grid,
        }))
    # lint: broad-except-ok the driver contract (ONE parseable JSON
    # line, always) outranks the traceback; the error text carries it
    except Exception as err:
        print(json.dumps({
            "metric": metric, "value": 0.0, "unit": "x",
            "vs_baseline": 0.0,
            "error": f"{type(err).__name__}: {err}",
        }))
        sys.exit(3)


def bench_mesh_pipeline(argv=()) -> None:
    """BASELINE.md config 17: multi-device ``mesh`` erasure backend vs
    the single-device jax backend, plus dispatch-pipeline on/off legs.

    Three backends encode and decode identical data, byte-identity
    asserted in-run against the numpy oracle:

    * ``single``      — ops/jax_backend.JaxBackend on one device (the
      current device path: the A/B's OFF leg);
    * ``mesh``        — ops/mesh_backend.MeshBackend at the default
      dispatch depth (2, the double buffer): sharded dispatch + the
      feed-ahead window;
    * ``mesh_nopipe`` — the same mesh with depth 0 (every dispatch
      materializes synchronously): isolates the pipeline's contribution
      from the sharding's.

    Overlap is proven in-run from the pipeline's own counters, not
    wall-clock (which a loaded host would make flaky): the ``mesh`` leg
    must stage submits while the window is busy (``submits_while_busy``
    > 0, ``max_inflight`` >= 2) with host callback time recorded inside
    the in-flight window (``host_overlap_s`` > 0 — host staging hidden
    behind device dispatch), and the ``mesh_nopipe`` leg must show NO
    overlap (``max_inflight`` <= 1, ``submits_while_busy`` == 0).

    Runs on whatever devices jax exposes; with no args on this repo's
    dev box that is the 8-device virtual CPU mesh (provisioned in-env
    below, the same recipe as tests/conftest.py — CPU numbers gauge
    WIRING, not the chip: record them as virtual-mesh rows).  On-chip
    rows come from ``./tpu_session.sh`` when the tunnel cooperates.
    Both library degrade timeouts are forced off so a degraded CPU
    fallback can never be silently recorded as the device number
    (identity asserts would still catch wrong bytes; the stats asserts
    catch a dead mesh).

    Flags: ``--geom 10x4`` / ``--size-kib 256`` / ``--parts 16`` /
    ``--batches 4`` / ``--iters 3`` / ``--devices 8`` / ``--smoke``
    (tiny shapes, seconds-scale — the CI step).  One JSON line always;
    failures exit 3 with the same contract as configs 8-16."""
    import os

    metric = "mesh_pipeline_encode_gibps"
    try:
        # Provision BEFORE any jax import: drop the axon tunnel pinning,
        # force the CPU platform and a virtual device mesh — identical
        # to conftest.  A tpu_session.sh run sets
        # $CHUNKY_BITS_TPU_BENCH_MESH_ONCHIP=1 to keep the real chips.
        n_devices_flag = None
        argv_l = list(argv)

        def flag(name, default, cast):
            if name in argv_l:
                return cast(argv_l[argv_l.index(name) + 1])
            return default

        smoke = "--smoke" in argv_l
        geom = flag("--geom", "10x4", str)
        size_kib = flag("--size-kib", 64 if smoke else 256, int)
        parts = flag("--parts", 8 if smoke else 16, int)
        batches = flag("--batches", 2 if smoke else 4, int)
        iters = flag("--iters", 1 if smoke else 3, int)
        n_devices_flag = flag("--devices", 8, int)
        d_s, p_s = geom.lower().split("x")
        d, p = int(d_s), int(p_s)
        if (d < 1 or p < 1 or size_kib < 1 or parts < 1 or batches < 2
                or iters < 1 or n_devices_flag < 2):
            raise ValueError(
                "need d,p >= 1, --size-kib/--parts >= 1, --batches >= 2 "
                "(the feed-ahead proof), --iters >= 1, --devices >= 2")

        from chunky_bits_tpu.cluster import tunables as _tunables

        if not _tunables.env_flag("CHUNKY_BITS_TPU_BENCH_MESH_ONCHIP"):
            from chunky_bits_tpu.utils.virtualmesh import (
                provision_virtual_mesh,
            )

            provision_virtual_mesh(os.environ, n_devices_flag)
            import jax

            jax.config.update("jax_platforms", "cpu")
        # Bench owns outage handling (see _device_init_watchdog): force
        # the library's bounded degrade-to-CPU off so a sticky-CPU
        # fallback can never be recorded as the device number.
        from chunky_bits_tpu.ops.jax_backend import (
            DEVICE_INIT_TIMEOUT_ENV,
            DISPATCH_TIMEOUT_ENV,
            JaxBackend,
        )

        os.environ[DEVICE_INIT_TIMEOUT_ENV] = "0"
        os.environ[DISPATCH_TIMEOUT_ENV] = "0"

        import jax

        from chunky_bits_tpu.ops import matrix
        from chunky_bits_tpu.ops.backend import ErasureCoder, NumpyBackend
        from chunky_bits_tpu.ops.mesh_backend import MeshBackend

        platform = jax.devices()[0].platform
        n_devices = len(jax.devices())

        rng = np.random.default_rng(0)
        size = size_kib << 10
        enc = matrix.build_encode_matrix(d, p)
        data = [rng.integers(0, 256, (parts, d, size), dtype=np.uint8)
                for _ in range(batches)]
        nbytes = batches * parts * d * size

        single = JaxBackend()
        mesh_on = MeshBackend()  # depth from tunables (default 2)
        mesh_off = MeshBackend(depth=0)
        legs = {"single": single, "mesh": mesh_on,
                "mesh_nopipe": mesh_off}

        # decode inputs: p erasures, host-inverted matrix, picked rows
        oracle_par = [NumpyBackend().apply_matrix(enc[d:], b)
                      for b in data]
        erased = sorted(rng.choice(d + p, size=p, replace=False).tolist())
        present = [i for i in range(d + p) if i not in erased]
        dec = matrix.decode_matrix(enc, present, list(erased))
        picked = [np.ascontiguousarray(
            np.concatenate([b, o], axis=1)[:, np.array(present[:d])])
            for b, o in zip(data, oracle_par)]
        oracle_dec = [NumpyBackend().apply_matrix(dec, pk)
                      for pk in picked]

        def run_encode(be):
            coder = ErasureCoder(d, p, be)
            return [pr for pr, _dg in coder.encode_hash_batches(data)]

        def run_decode(be):
            submit = getattr(be, "submit_apply", None)
            if submit is None:
                return [be.apply_matrix(dec, pk) for pk in picked]
            # feed-ahead: stage every batch before collecting any
            tickets = [submit(dec, pk) for pk in picked]
            return [t.result() for t in tickets]

        def best_s(fn):
            best = float("inf")
            for _ in range(iters):
                t0 = time.perf_counter()
                fn()
                best = min(best, time.perf_counter() - t0)
            return best

        results = {}
        identical = True
        for name, be in legs.items():
            enc_out = run_encode(be)
            dec_out = run_decode(be)
            for got, want in zip(enc_out, oracle_par):
                if not np.array_equal(got, want):
                    raise RuntimeError(f"{name} encode != numpy oracle")
            for got, want in zip(dec_out, oracle_dec):
                if not np.array_equal(got, want):
                    raise RuntimeError(f"{name} decode != numpy oracle")
            e_best = best_s(lambda be=be: run_encode(be))
            d_best = best_s(lambda be=be: run_decode(be))
            results[name] = {
                "encode_gibps": round(nbytes / e_best / (1 << 30), 3),
                "decode_gibps": round(nbytes / d_best / (1 << 30), 3),
            }
            print(f"# config 17: {name}: encode "
                  f"{results[name]['encode_gibps']} GiB/s, decode "
                  f"{results[name]['decode_gibps']} GiB/s", file=sys.stderr)

        # overlap proof from the pipeline's own counters (cumulative
        # over every dispatch above)
        on = vars(mesh_on.pipeline.stats())
        off = vars(mesh_off.pipeline.stats())
        proof = (on["submits_while_busy"] > 0 and on["max_inflight"] >= 2
                 and on["host_overlap_s"] > 0.0 and on["cancelled"] == 0
                 and on["completed"] == on["submitted"]
                 and off["submits_while_busy"] == 0
                 and off["max_inflight"] <= 1 and off["cancelled"] == 0)
        if not proof:
            raise RuntimeError(
                f"pipeline overlap not proven: on={on} off={off}")
        on["host_overlap_s"] = round(on["host_overlap_s"], 6)
        off["host_overlap_s"] = round(off["host_overlap_s"], 6)

        mesh_e = results["mesh"]["encode_gibps"]
        single_e = results["single"]["encode_gibps"]
        print(json.dumps({
            "metric": metric,
            "value": mesh_e, "unit": "GiB/s",
            # >1.0 means the mesh beat one device; on the virtual CPU
            # mesh this gauges wiring overhead, not chip scaling
            "vs_baseline": round(mesh_e / single_e, 3) if single_e else 0.0,
            "platform": platform, "devices": n_devices,
            "geom": f"{d}x{p}", "size_kib": size_kib,
            "parts": parts, "batches": batches,
            "legs": results,
            "pipeline": {"on": on, "off": off},
            "overlap_proven": proof, "identical": identical,
            "smoke": smoke,
        }))
    # lint: broad-except-ok the driver contract (ONE parseable JSON
    # line, always) outranks the traceback; the error text carries it
    except Exception as err:
        print(json.dumps({
            "metric": metric, "value": 0.0, "unit": "GiB/s",
            "vs_baseline": 0.0,
            "error": f"{type(err).__name__}: {err}",
        }))
        sys.exit(3)


def bench_qos_isolation(argv=()) -> None:
    """BASELINE.md config 19: the multi-tenant QoS noisy-neighbor A/B
    (CPU-only, single-process — this box's ~1.35 effective cores make
    multi-process A/Bs environment-gated, config-9 BASELINE note).

    One in-process gateway (``make_app`` on an AppRunner, tiny
    ``max_concurrent_gets`` so admission is the contended resource)
    serves cold-tier reads to TWO tenants telling themselves apart by
    ``X-Api-Key``: an antagonist fleet that floods continuous GETs,
    and a victim issuing periodic GETs.  Leg OFF (``qos.enabled:
    false`` — the pre-QoS gateway) sheds past the bound, so the victim
    pays 503+retry against the whole flood; leg ON (``qos.enabled:
    true``, victim weight 4) admits through the weighted-fair
    scheduler, so the victim queues for roughly one DRR rotation.
    Reported value: victim time-to-success p99 OFF over ON (>= 1 means
    QoS helped; the acceptance bar is 5x).  Aggregate throughput of
    both legs rides along — isolation must not tax total RPS (within
    10%).  Per-tenant byte identity (every victim body, sampled
    antagonist bodies, against the numpy source payload) is asserted
    in-run.

    Flags: ``--antagonists N`` flood size (default 16),
    ``--reads N`` victim reads per leg (default 40), ``--cap N``
    max_concurrent_gets (default 4), ``--smoke`` shrinks to a
    seconds-scale contract check.

    Failure contract (tests/test_bench_outage.py): ANY failure still
    emits exactly one parseable JSON line and exits 3."""
    import asyncio
    import contextlib
    import os
    import tempfile

    argv = list(argv)

    def flag(name, default, cast):
        if name in argv:
            return cast(argv[argv.index(name) + 1])
        return default

    metric = "qos_isolation_victim_p99_improvement_d3p2"
    try:
        smoke = "--smoke" in argv
        antagonists = flag("--antagonists", 6 if smoke else 16, int)
        victim_reads = flag("--reads", 6 if smoke else 40, int)
        gets_cap = flag("--cap", 4, int)
        if antagonists <= 0 or victim_reads <= 0 or gets_cap <= 0:
            raise ValueError(
                "--antagonists/--reads/--cap must be positive")

        from aiohttp import web

        from chunky_bits_tpu.cluster import Cluster
        from chunky_bits_tpu.file.profiler import percentile
        from chunky_bits_tpu.gateway.http import make_app
        from chunky_bits_tpu.utils import aio

        rng = np.random.default_rng(0)
        obj_bytes = (64 << 10) if smoke else (512 << 10)
        chunk_log2 = 12 if smoke else 14
        payload = rng.integers(0, 256, obj_bytes,
                               dtype=np.uint8).tobytes()
        retry_s = 0.02  # victim/antagonist backoff after a 503

        def make_cluster_obj(root: str, qos_on: bool) -> dict:
            dirs = []
            for i in range(5):
                d = os.path.join(root, f"disk{i}")
                os.makedirs(d, exist_ok=True)
                dirs.append(d)
            meta = os.path.join(root, "meta")
            os.makedirs(meta, exist_ok=True)
            return {
                "destinations": [{"location": d} for d in dirs],
                "metadata": {"type": "path", "format": "yaml",
                             "path": meta},
                "profiles": {"default": {"data": 3, "parity": 2,
                                         "chunk_size": chunk_log2}},
                # cache far below the object size: every GET pays
                # fetch+verify, so a slot is held long enough for
                # admission to be the contended resource
                "tunables": {
                    "backend": "native",
                    "cache_bytes": 1 << 14,
                    "qos": {
                        "enabled": qos_on,
                        "tenants": {
                            "victim": {"weight": 4,
                                       "keys": ["victim-key"]},
                            "antagonist": {
                                "keys": ["antagonist-key"]},
                        },
                    },
                },
            }

        class MiniConn:
            """Raw-socket keep-alive GET client (the config-9 shape):
            client-side cost stays far below the server's, so the
            gateway is the measured resource."""

            def __init__(self, port: int):
                self.port = port
                self.reader = None
                self.writer = None

            async def open(self):
                self.reader, self.writer = \
                    await asyncio.open_connection("127.0.0.1",
                                                  self.port)
                return self

            async def get(self, path: str, extra: str = "") -> tuple:
                self.writer.write(
                    (f"GET {path} HTTP/1.1\r\n"
                     f"Host: 127.0.0.1\r\n{extra}\r\n").encode())
                await self.writer.drain()
                status_line = await self.reader.readline()
                status = int(status_line.split(b" ", 2)[1])
                length = 0
                while True:
                    line = await self.reader.readline()
                    if line in (b"\r\n", b""):
                        break
                    if line[:15].lower() == b"content-length:":
                        length = int(line[15:])
                body = b""
                if status not in (204, 304) and length:
                    body = await self.reader.readexactly(length)
                return status, body

            async def close(self):
                if self.writer is not None:
                    self.writer.close()
                    try:
                        await asyncio.wait_for(
                            self.writer.wait_closed(), timeout=5)
                    except (asyncio.TimeoutError, OSError):
                        pass

        async def run_leg(qos_on: bool) -> dict:
            with tempfile.TemporaryDirectory() as root:
                cluster_obj = make_cluster_obj(root, qos_on)
                seed_cluster = Cluster.from_obj(cluster_obj)
                profile = seed_cluster.get_profile(None)
                await seed_cluster.write_file(
                    "obj", aio.BytesReader(payload), profile)
                await seed_cluster.tunables.location_context().aclose()

                cluster = Cluster.from_obj(cluster_obj)
                app = make_app(cluster,
                               max_concurrent_gets=gets_cap)
                runner = web.AppRunner(app)
                await runner.setup()
                site = web.TCPSite(runner, "127.0.0.1", 0)
                await site.start()
                port = site._server.sockets[0].getsockname()[1]
                stop = False
                counts = {"ok": 0, "shed": 0}

                async def fetch_ok(conn, key: str) -> tuple:
                    """GET until success; returns (wall_s, body)."""
                    t0 = time.perf_counter()
                    while True:
                        status, body = await conn.get(
                            "/obj", f"X-Api-Key: {key}\r\n")
                        if status == 200:
                            return time.perf_counter() - t0, body
                        if status != 503:
                            raise RuntimeError(
                                f"unexpected status {status}")
                        counts["shed"] += 1
                        await asyncio.sleep(retry_s)

                async def antagonist(i: int) -> None:
                    conn = await MiniConn(port).open()
                    try:
                        j = 0
                        while not stop:
                            _, body = await fetch_ok(
                                conn, "antagonist-key")
                            counts["ok"] += 1
                            j += 1
                            if j % 8 == 0:
                                # sampled antagonist byte identity
                                assert body == payload, \
                                    "antagonist byte identity"
                    finally:
                        await conn.close()

                tasks = [asyncio.ensure_future(antagonist(i))
                         for i in range(antagonists)]
                # let the flood saturate admission first
                await asyncio.sleep(1.0 if smoke else 2.0)
                lat: list = []
                victim_conn = await MiniConn(port).open()
                t_open = time.perf_counter()
                try:
                    for _ in range(victim_reads):
                        wall, body = await fetch_ok(
                            victim_conn, "victim-key")
                        # per-tenant byte identity: every victim body
                        assert body == payload, "victim byte identity"
                        counts["ok"] += 1
                        lat.append(wall)
                        await asyncio.sleep(0.05)
                finally:
                    t_window = time.perf_counter() - t_open
                    # graceful drain: antagonists finish their
                    # in-flight request (a mid-request cancel would
                    # abort server writes into closed sockets)
                    stop = True
                    await asyncio.gather(*tasks,
                                         return_exceptions=True)
                    await victim_conn.close()
                    await runner.cleanup()
                    await cluster.tunables.location_context().aclose()
                return {
                    "victim_p50_ms":
                        percentile(sorted(lat), 50) * 1e3,
                    "victim_p99_ms":
                        percentile(sorted(lat), 99) * 1e3,
                    "ok": counts["ok"],
                    "shed_503": counts["shed"],
                    "rps": counts["ok"] / t_window
                    if t_window > 0 else 0.0,
                }

        async def run() -> tuple:
            off = await run_leg(qos_on=False)
            on = await run_leg(qos_on=True)
            return off, on

        off, on = asyncio.run(run())
        improvement = (off["victim_p99_ms"] / on["victim_p99_ms"]
                       if on["victim_p99_ms"] > 0 else 0.0)
        rps_ratio = (on["rps"] / off["rps"] if off["rps"] > 0
                     else 0.0)
        print(f"# config 19: cap={gets_cap} "
              f"antagonists={antagonists} reads={victim_reads}: "
              f"victim p99 OFF {off['victim_p99_ms']:.1f} ms "
              f"(sheds={off['shed_503']}) vs ON "
              f"{on['victim_p99_ms']:.1f} ms "
              f"(sheds={on['shed_503']}) = {improvement:.1f}x; "
              f"aggregate RPS {off['rps']:.0f} -> {on['rps']:.0f} "
              f"({rps_ratio:.2f}x)", file=sys.stderr)
        print(json.dumps({
            "metric": metric + ("_smoke" if smoke else ""),
            # the number this config exists for: victim tail latency
            # with isolation ON vs OFF under the same flood
            "value": round(improvement, 2),
            "unit": "x",
            "vs_baseline": round(improvement, 2),
            "antagonists": antagonists,
            "victim_reads": victim_reads,
            "max_concurrent_gets": gets_cap,
            "object_bytes": obj_bytes,
            "off": {k: round(v, 3) if isinstance(v, float) else v
                    for k, v in off.items()},
            "on": {k: round(v, 3) if isinstance(v, float) else v
                   for k, v in on.items()},
            "aggregate_rps_ratio": round(rps_ratio, 3),
            "host_cores": nproc(),
        }))
    # lint: broad-except-ok the driver contract (ONE parseable JSON
    # line, always) outranks the traceback; the error text carries it
    except Exception as err:
        print(json.dumps({
            "metric": metric, "value": 0.0, "unit": "x",
            "vs_baseline": 0.0,
            "error": f"{type(err).__name__}: {err}",
        }))
        sys.exit(3)


if __name__ == "__main__":
    # Bench measures the product defaults: the runtime concurrency
    # sanitizer (analysis/sanitizer.py) must stay OFF here even when an
    # inherited $CHUNKY_BITS_TPU_SANITIZE would turn it on — its
    # instrumentation is a correctness tool whose overhead would
    # pollute every recorded number (write, not read: the one
    # sanctioned env handoff, like the CLI's backend write).
    import os as _os

    _os.environ["CHUNKY_BITS_TPU_SANITIZE"] = "0"
    # Default (no args): BASELINE config 2/3 on the device — the driver's
    # recorded metric.  --config 1|4 run the auxiliary BASELINE.md configs.
    if "--config" in sys.argv:
        configs = {"1": bench_cpu_reference,
                   "2": lambda: bench_cp_pipeline(sys.argv),
                   "3": lambda: bench_batched_repair(sys.argv),
                   "4": lambda: bench_small_objects(sys.argv),
                   "6": lambda: bench_hot_read(sys.argv),
                   "7": lambda: bench_gateway_put(sys.argv),
                   "8": lambda: bench_hedged_read(sys.argv),
                   "9": lambda: bench_gateway_scaleout(sys.argv),
                   "10": lambda: bench_slab_store(sys.argv),
                   "11": lambda: bench_repair_bandwidth(sys.argv),
                   "12": lambda: bench_xor_schedule(sys.argv),
                   "13": lambda: bench_pm_msr_repair(sys.argv),
                   "14": lambda: bench_sim_scenarios(sys.argv),
                   "15": lambda: bench_slo_detection(sys.argv),
                   "16": lambda: bench_crash_matrix(sys.argv),
                   "17": lambda: bench_mesh_pipeline(sys.argv),
                   "18": lambda: bench_meta_log(sys.argv),
                   "19": lambda: bench_qos_isolation(sys.argv)}
        idx = sys.argv.index("--config") + 1
        which = sys.argv[idx] if idx < len(sys.argv) else ""
        if which not in configs:
            print(f"usage: bench.py [--config "
                  f"{{1,2,3,4,6,7,8,9,10,11,12,13,14,15,16,17,18,19}}]"
                  f" — the device kernel metric (configs 2+3's compute "
                  f"core) is the default no-arg run (got {which!r}); 6 "
                  f"is the hot-read cache A/B, 7 the gateway PUT ingest "
                  f"A/B, 8 the hedged-read tail-latency A/B, 9 the "
                  f"gateway scale-out multi-worker A/B, 10 the packed "
                  f"slab store vs file-per-chunk A/B, 11 the "
                  f"repair-bandwidth planner A/B, 12 the scheduled-XOR "
                  f"erasure engine vs byte-table grid, 13 the pm-msr "
                  f"regenerating-code vs rs repair-bandwidth A/B, 14 "
                  f"the simulator scenario-suite runner, 15 the SLO "
                  f"detection-quality + engine-off overhead suite, 16 "
                  f"the crash-consistency matrix suite (all CPU-only), "
                  f"17 the multi-device mesh backend + dispatch-"
                  f"pipeline A/B (virtual CPU mesh by default), 18 the "
                  f"indexed meta-log vs file-per-ref metadata-plane "
                  f"A/B, 19 the multi-tenant QoS noisy-neighbor A/B",
                  file=sys.stderr)
            sys.exit(2)
        configs[which]()
    else:
        main()
