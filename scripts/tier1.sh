#!/usr/bin/env bash
# The tier-1 verification gate — THE command builders and CI run.  The
# static-analysis pre-step runs first; the pytest invocation is kept
# byte-identical to the ROADMAP.md "Tier-1 verify" line so nobody gates
# on a subtly different invocation:
#   - CPU-only jax (never touches the flaky TPU tunnel),
#   - `not slow` marker cut,
#   - leak-strict plugins-off run (no cacheprovider/xdist/randomly),
#   - a DOTS_PASSED count parsed from the progress lines, and the
#     pytest exit code as the script's own.
# Log lands in /tmp/_t1.log for postmortems.
#
# Sanitize leg: CHUNKY_BITS_TPU_SANITIZE=1 bash scripts/tier1.sh runs
# the identical suite under the runtime concurrency sanitizer
# (chunky_bits_tpu/analysis/sanitizer.py) — tests/conftest.py installs
# it before any event loop exists and fails the session on leaked
# tasks, swallowed task exceptions, or cross-plane handoff violations
# (loop stalls are reported but advisory).  CI runs this as its own
# matrix entry.
set -o pipefail
cd "$(dirname "$0")/.."

# Static-analysis gate first (scripts/check.sh: the invariant linter +
# mypy when installed).  Fast, CPU-only, no jax import — runs even when
# the device tunnel is down.  The pytest invocation below additionally
# re-runs the linter via tests/test_analysis.py::test_shipped_tree_is_clean,
# so drivers invoking the ROADMAP.md pytest line directly still gate on it.
bash scripts/check.sh || exit $?

rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)
exit $rc
