#!/usr/bin/env bash
# The static-analysis gate — tier-1 (scripts/tier1.sh) and CI both run
# this.  Two halves:
#
#   1. the project-native invariant linter (chunky_bits_tpu/analysis):
#      pure stdlib AST rules, NO jax/numpy/aiohttp import, so it runs
#      even when the device tunnel is down and on bare runners.  Always
#      BLOCKING.  Covers all four families: CB1xx single-function
#      invariants, the CB2xx concurrency-hazard rules (blocking calls
#      in async defs, locks across awaits, leaked tasks, the
#      cross-plane call-graph pass, loop-shared state), the CB3xx
#      whole-program reachability rules, and the CB4xx resource-
#      lifetime/deadline rules (CFG + dataflow: fd/lock/task leaks on
#      exception and cancellation paths, interprocedural deadline and
#      scrub-metering proofs); run one family alone with
#      `python -m chunky_bits_tpu.analysis --select CB4`.
#   2. mypy over the strict-typed surfaces ([tool.mypy] in
#      pyproject.toml) — only when mypy is installed, and ADVISORY by
#      default (MYPY_STRICT=1 makes it blocking; CI's mypy step sets
#      it and is a blocking job, so the typed surfaces DO gate merges
#      — the env default only spares dev boxes that happen to carry a
#      mismatched mypy).  The dev image cannot install mypy at all, so
#      there this half skips with a note.  Lint rule CB106 enforces
#      annotation presence on the same modules regardless, so the
#      typing floor never silently disappears with the tool.
#
# Exit code: non-zero when the linter fails (or mypy fails under
# MYPY_STRICT=1).
set -o pipefail
cd "$(dirname "$0")/.."

python -m chunky_bits_tpu.analysis || exit $?

if python -c "import mypy" >/dev/null 2>&1; then
    if python -m mypy chunky_bits_tpu/ops chunky_bits_tpu/file \
        chunky_bits_tpu/cluster chunky_bits_tpu/parallel; then
        echo "check.sh: mypy half green"
    elif [ "${MYPY_STRICT:-0}" = "1" ]; then
        exit 1
    else
        echo "check.sh: WARNING mypy half failed (ADVISORY — set" \
             "MYPY_STRICT=1 to make it blocking)" >&2
    fi
else
    echo "check.sh: mypy not installed; skipped the mypy half" \
         "(CB106 above still enforced annotation presence)"
fi
