#!/usr/bin/env python
"""CI metrics-scrape smoke: boot a throwaway gateway (SLO engine ON at
a fast tick) on a temp cluster, drive one PUT/GET, scrape /metrics +
/healthz + /stats + /alerts, validate the exposition against the
strict line grammar (chunky_bits_tpu.obs.metrics.parse_exposition —
the same parser the tests and `chunky-bits stats` use), and
schema-check the /alerts and /stats payloads (closed rule set, the
slo stanza, the build-info identity gauge).  Exit 0 with "metrics
smoke OK" on success; any grammar violation, missing family, or
schema miss fails the step.

Run: python scripts/metrics_smoke.py
"""

from __future__ import annotations

import asyncio
import os
import sys
import tempfile

# runnable as `python scripts/metrics_smoke.py` from the repo root (the
# CI invocation): script mode puts scripts/ on sys.path, not the root
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: families a fresh single-worker gateway must expose after one
#: PUT + one GET (cache families need cache_bytes on; node/pipeline
#: families need actual I/O — the roundtrip provides both)
REQUIRED_FAMILIES = (
    "cb_request_seconds",
    "cb_request_total",
    "cb_request_bytes_total",
    "cb_worker_up",
    "cb_build_info",
    "cb_cache_hits_total",
    "cb_pipeline_jobs_total",
    "cb_node_completions_total",
    "cb_eventloop_lag_seconds",
    "cb_gateway_gets_in_flight",
    "cb_alerts_state",
    "cb_slo_evaluations_total",
)


async def main() -> int:
    import aiohttp
    from aiohttp.test_utils import TestServer

    from chunky_bits_tpu.cluster import Cluster
    from chunky_bits_tpu.gateway import make_app
    from chunky_bits_tpu.obs.metrics import parse_exposition

    with tempfile.TemporaryDirectory() as tmp:
        dirs = []
        for i in range(5):
            d = os.path.join(tmp, f"disk{i}")
            os.makedirs(d)
            dirs.append(d)
        meta = os.path.join(tmp, "meta")
        os.makedirs(meta)
        cluster = Cluster.from_obj({
            "destinations": [{"location": d} for d in dirs],
            "metadata": {"type": "path", "format": "yaml",
                         "path": meta},
            "profiles": {"default": {"data": 3, "parity": 2,
                                     "chunk_size": 16}},
            # engine ON at a fast tick so /alerts answers with live
            # state and the cb_slo_*/cb_alerts_* families are scraped
            "tunables": {"cache_bytes": 4 << 20, "slo_eval_s": 0.2},
        })
        server = TestServer(make_app(cluster))
        await server.start_server()
        try:
            url = f"http://127.0.0.1:{server.port}"
            async with aiohttp.ClientSession() as session:
                payload = os.urandom(200000)
                resp = await session.put(f"{url}/obj", data=payload)
                assert resp.status == 200, resp.status
                resp = await session.get(f"{url}/obj")
                assert await resp.read() == payload
                resp = await session.get(f"{url}/healthz")
                assert resp.status == 200, resp.status
                await asyncio.sleep(0.5)  # at least one engine tick
                resp = await session.get(f"{url}/stats")
                stats = await resp.json()
                assert stats["requests"]["count"] >= 2, stats
                # /stats slo stanza schema
                slo = stats.get("slo", {})
                assert slo.get("enabled") is True, stats
                for key in ("evaluations", "firing", "pending",
                            "resolved_total"):
                    assert key in slo, slo
                assert slo["evaluations"] >= 1, slo
                # /alerts schema: the closed rule set, every row shaped
                resp = await session.get(f"{url}/alerts")
                assert resp.status == 200, resp.status
                alerts = await resp.json()
                assert alerts.get("enabled") is True, alerts
                from chunky_bits_tpu.obs.slo import ALERT_STATES, RULES
                rows = {a["rule"]: a for a in alerts["alerts"]}
                assert set(rows) == set(RULES), sorted(rows)
                for a in rows.values():
                    assert a["state"] in ALERT_STATES, a
                    for key in ("since", "threshold", "fired_count"):
                        assert key in a, a
                assert alerts["firing"] == [], alerts["firing"]
                resp = await session.get(f"{url}/metrics")
                assert resp.status == 200, resp.status
                parsed = parse_exposition(await resp.text())
        finally:
            await server.close()
        await cluster.tunables.location_context().aclose()
    missing = [f for f in REQUIRED_FAMILIES if f not in parsed]
    if missing:
        print(f"metrics smoke FAILED: missing families {missing}",
              file=sys.stderr)
        return 1
    print(f"metrics smoke OK ({len(parsed)} families, "
          "exposition grammar valid)")
    return 0


if __name__ == "__main__":
    sys.exit(asyncio.run(main()))
