"""Regenerate the golden file-reference fixtures.

Run from the repo root:  python tests/golden/generate.py

The fixtures freeze bytes -> exact YAML (structure, sha256 content
addresses, parity hashes, and for the cluster fixture the hash-seeded
placement) as cross-version conformance anchors: a future kernel or
layout change that silently breaks wire compatibility fails
tests/test_golden.py.  Regenerating is a deliberate act — do it only for
an intentional, documented format change.
"""

import asyncio
import os
import sys
import tempfile

import numpy as np
import yaml

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

from chunky_bits_tpu.cluster import Cluster  # noqa: E402
from chunky_bits_tpu.file import FileWriteBuilder  # noqa: E402
from chunky_bits_tpu.utils import aio  # noqa: E402

GOLDEN_DIR = os.path.dirname(os.path.abspath(__file__))


def payload(n: int, seed: int) -> bytes:
    return np.random.default_rng(seed).integers(
        0, 256, n, dtype=np.uint8).tobytes()


def cluster_spec(meta_path: str) -> dict:
    """Relative-path destinations with unequal weights; placement is
    deterministic because the cluster Destination seeds its RNG from the
    first shard hash (reference: src/cluster/destination.rs:73-84)."""
    return {
        "destinations": [
            {"location": "d0", "weight": 2000},
            {"location": "d1", "weight": 500},
            {"location": "d2"},
            {"location": "d3"},
            {"location": "d4", "repeat": 1},
        ],
        "metadata": {"type": "path", "format": "yaml", "path": meta_path},
        # code pinned to "rs" in YAML (which wins over any inherited
        # $CHUNKY_BITS_TPU_CODE — the CI pm-msr matrix leg): fixtures
        # 3/4 freeze the CLASSIC wire format; fixture 6 freezes pm-msr
        "profiles": {"default": {"data": 3, "parity": 2,
                                 "chunk_size": 12, "code": "rs"}},
        # pinned OFF in YAML (which wins over any inherited
        # $CHUNKY_BITS_TPU_REPAIR_BLOCK_BYTES): these fixtures freeze
        # the CLASSIC wire format; fixture 5 freezes the tree format
        "tunables": {"repair_block_bytes": 0},
    }


async def build_refs() -> dict[str, dict]:
    refs: dict[str, dict] = {}

    # 1. structure + content addressing, short final part (d=3 p=2)
    ref = await (FileWriteBuilder()
                 .with_chunk_size(1 << 14)
                 .with_data_chunks(3).with_parity_chunks(2)
                 .write(aio.BytesReader(payload(100_000, 1))))
    refs["void_small"] = ref.to_obj()

    # 2. the benchmark geometry d=10 p=4: parity hashes pin the GF(2^8)
    # matrix convention byte-for-byte across backends
    ref = await (FileWriteBuilder()
                 .with_chunk_size(1 << 12)
                 .with_data_chunks(10).with_parity_chunks(4)
                 .write(aio.BytesReader(payload(3 * 10 * (1 << 12) + 777,
                                               2))))
    refs["void_wide"] = ref.to_obj()

    # 3. hash-seeded weighted placement over relative-path destinations
    with tempfile.TemporaryDirectory() as tmp:
        cwd = os.getcwd()
        os.chdir(tmp)
        try:
            for i in range(5):
                os.mkdir(f"d{i}")
            os.mkdir("meta")
            cluster = Cluster.from_obj(cluster_spec("meta"))
            profile = cluster.get_profile()
            ref = await (cluster.get_file_writer(profile)
                         .write(aio.BytesReader(payload(30_000, 3))))
            refs["cluster_placement"] = ref.to_obj()
        finally:
            os.chdir(cwd)

    # 4. the same payload/weights over PACKED (slab:) destinations:
    # pins the slab location serialization AND that the packed layout
    # reproduces fixture 3's hash-seeded placement draw and content
    # addresses exactly — the store changes where bytes live, never
    # which bytes or which node
    with tempfile.TemporaryDirectory() as tmp:
        cwd = os.getcwd()
        os.chdir(tmp)
        try:
            for i in range(5):
                os.mkdir(f"d{i}")
            os.mkdir("meta")
            spec = cluster_spec("meta")
            for node in spec["destinations"]:
                node["location"] = "slab:" + node["location"]
            cluster = Cluster.from_obj(spec)
            profile = cluster.get_profile()
            ref = await (cluster.get_file_writer(profile)
                         .write(aio.BytesReader(payload(30_000, 3))))
            refs["slab_placement"] = ref.to_obj()
        finally:
            os.chdir(cwd)

    # 5. fixture 1's exact payload with per-chunk block-digest trees
    # (the `repair_block_bytes` tunable, file/chunk.py BlockDigests):
    # pins the tree wire format AND that the trees are strictly
    # additive — stripping every `blocks` key must reproduce fixture 1
    # byte-for-byte (tests/test_golden.py asserts both directions)
    ref = await (FileWriteBuilder()
                 .with_chunk_size(1 << 14)
                 .with_data_chunks(3).with_parity_chunks(2)
                 .with_repair_block_bytes(4096)
                 .write(aio.BytesReader(payload(100_000, 1))))
    refs["block_digests"] = ref.to_obj()

    # 6. fixture 1's exact payload under the product-matrix MSR code
    # (ops/pm_msr.py): pins the `code: pm-msr` wire format BOTH ways —
    # data chunks stay byte-identical to fixture 1 (the code is
    # systematic and the shard split is unchanged at this geometry,
    # alpha=2 | every shard length), parity chunks pin the pm-msr
    # GF(2^8) generator matrix through their content addresses, and
    # the `code` key is the ONLY structural delta (tests assert
    # stripping it reproduces a classic-parseable ref)
    ref = await (FileWriteBuilder()
                 .with_chunk_size(1 << 14)
                 .with_data_chunks(3).with_parity_chunks(2)
                 .with_code("pm-msr")
                 .write(aio.BytesReader(payload(100_000, 1))))
    refs["pm_msr_placement"] = ref.to_obj()

    # 7. fixture 3's exact payload/weights with the metadata published
    # through the indexed meta-log store (cluster/meta_log.py) and read
    # back from the log before freezing: pins that the append-only
    # store round-trips refs byte-identically to file-per-ref — the
    # store changes where METADATA lives, never its bytes (the mirror
    # test asserts this fixture equals fixture 3 exactly)
    with tempfile.TemporaryDirectory() as tmp:
        cwd = os.getcwd()
        os.chdir(tmp)
        try:
            for i in range(5):
                os.mkdir(f"d{i}")
            spec = cluster_spec("meta")
            spec["metadata"] = {"type": "meta-log", "format": "yaml",
                                "path": "meta"}
            cluster = Cluster.from_obj(spec)
            profile = cluster.get_profile()
            ref = await (cluster.get_file_writer(profile)
                         .write(aio.BytesReader(payload(30_000, 3))))
            await cluster.metadata.write("golden/ref", ref.to_obj())
            refs["meta_log_placement"] = await cluster.metadata.read(
                "golden/ref")
        finally:
            os.chdir(cwd)
    return refs


def dump(obj: dict) -> str:
    return yaml.safe_dump(obj, sort_keys=False)


def main() -> None:
    refs = asyncio.run(build_refs())
    for name, obj in refs.items():
        path = os.path.join(GOLDEN_DIR, f"{name}.yaml")
        with open(path, "w") as f:
            f.write(dump(obj))
        print(f"wrote {path}")


if __name__ == "__main__":
    main()
