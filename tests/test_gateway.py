"""HTTP gateway tests — the reference leaves src/http.rs untested
(SURVEY §4); full coverage here: GET/HEAD/PUT, Range semantics
(206/416/Content-Range), content-type, 404."""

import asyncio
import os

import pytest
import yaml

from chunky_bits_tpu.cluster import Cluster
from chunky_bits_tpu.gateway import make_app, parse_http_range
from chunky_bits_tpu.gateway.http import HttpRangeError


def make_cluster(tmp_path) -> Cluster:
    dirs = []
    for i in range(5):
        d = tmp_path / f"disk{i}"
        d.mkdir()
        dirs.append(str(d))
    meta = tmp_path / "meta"
    meta.mkdir()
    return Cluster.from_obj({
        "destinations": [{"location": d} for d in dirs],
        "metadata": {"type": "path", "format": "yaml", "path": str(meta)},
        "profiles": {"default": {"data": 3, "parity": 2,
                                 "chunk_size": 16}},
    })


def test_parse_http_range():
    assert parse_http_range("bytes=0-99") == ("range", 0, 99)
    assert parse_http_range("bytes=500-") == ("prefix", 500)
    assert parse_http_range("bytes=-300") == ("suffix", 300)
    for bad in ("bytes=5-2", "chars=0-5", "bytes=0-5,10-20", "bytes=-",
                "bytes=a-b", "garbage"):
        with pytest.raises(HttpRangeError):
            parse_http_range(bad)


def test_gateway_end_to_end(tmp_path):
    payload = os.urandom(300000)

    async def main():
        from aiohttp.test_utils import TestClient, TestServer

        cluster = make_cluster(tmp_path)
        app = make_app(cluster)
        async with TestClient(TestServer(app)) as client:
            # PUT with content-type
            resp = await client.put(
                "/objects/data.bin", data=payload,
                headers={"Content-Type": "application/x-demo"})
            assert resp.status == 200
            # metadata written with content_type
            meta = yaml.safe_load(
                (tmp_path / "meta" / "objects" / "data.bin").read_text())
            assert meta["content_type"] == "application/x-demo"
            # GET whole
            resp = await client.get("/objects/data.bin")
            assert resp.status == 200
            assert resp.headers["Content-Type"] == "application/x-demo"
            body = await resp.read()
            assert body == payload
            # HEAD
            resp = await client.head("/objects/data.bin")
            assert resp.status == 200
            assert int(resp.headers["Content-Length"]) == len(payload)
            # Range: inclusive slice
            resp = await client.get(
                "/objects/data.bin", headers={"Range": "bytes=100-199"})
            assert resp.status == 206
            body = await resp.read()
            assert body == payload[100:200]
            assert resp.headers["Content-Range"] == \
                f"bytes 100-199/{len(payload)}"
            # prefix range (from offset to EOF)
            resp = await client.get(
                "/objects/data.bin",
                headers={"Range": f"bytes={len(payload) - 50}-"})
            assert resp.status == 206
            assert await resp.read() == payload[-50:]
            # suffix range (last N bytes)
            resp = await client.get(
                "/objects/data.bin", headers={"Range": "bytes=-77"})
            assert resp.status == 206
            assert await resp.read() == payload[-77:]
            # unsatisfiable
            resp = await client.get(
                "/objects/data.bin",
                headers={"Range": f"bytes={len(payload) + 10}-"})
            assert resp.status == 416
            resp = await client.get(
                "/objects/data.bin",
                headers={"Range": f"bytes=-{len(payload) + 10}"})
            assert resp.status == 416
            # unparseable / multi-range / unknown-unit Range headers are
            # ignored per RFC 9110, not rejected
            for header in ("bytes=0-5,10-20", "chars=0-5", "garbage"):
                resp = await client.get(
                    "/objects/data.bin", headers={"Range": header})
                assert resp.status == 200, header
                assert await resp.read() == payload
            # 404 for unknown object
            resp = await client.get("/missing")
            assert resp.status == 404
        await cluster.tunables.location_context().aclose()

    asyncio.run(main())


def test_gateway_roundtrip_through_read_path(tmp_path):
    """PUT then GET with a degraded cluster (one chunk deleted)."""
    payload = os.urandom(150000)

    async def main():
        from aiohttp.test_utils import TestClient, TestServer

        cluster = make_cluster(tmp_path)
        app = make_app(cluster)
        async with TestClient(TestServer(app)) as client:
            assert (await client.put("/f", data=payload)).status == 200
            ref = await cluster.get_file_ref("f")
            os.remove(ref.parts[0].data[0].locations[0].target)
            resp = await client.get("/f")
            assert await resp.read() == payload
        await cluster.tunables.location_context().aclose()

    asyncio.run(main())


def test_gateway_concurrent_puts_coalesce(tmp_path):
    """Parallel small-object PUTs into a jax-backend cluster share encode
    dispatches through the cluster's per-loop batcher (BASELINE config 4's
    many-small-objects regime) and every object reads back identical."""
    import asyncio as aio_mod

    import numpy as np

    from tests.test_tpu_cluster import make_jax_cluster

    rng = np.random.default_rng(17)
    payloads = {f"o{i}": rng.integers(0, 256, 50000, dtype=np.uint8)
                .tobytes() for i in range(8)}

    async def main():
        from aiohttp.test_utils import TestClient, TestServer

        cluster = make_jax_cluster(tmp_path, d=3, p=2)
        app = make_app(cluster)
        async with TestClient(TestServer(app)) as client:
            results = await asyncio.gather(*[
                client.put(f"/objects/{name}", data=data)
                for name, data in payloads.items()])
            assert all(r.status == 200 for r in results)
            batcher = cluster._encode_batchers.get(
                aio_mod.get_running_loop())
            assert batcher is not None and batcher.dispatches > 0
            total_parts = 0
            for name in payloads:
                total_parts += len(
                    (await cluster.get_file_ref(f"objects/{name}")).parts)
            assert batcher.dispatches < total_parts
            for name, data in payloads.items():
                resp = await client.get(f"/objects/{name}")
                assert await resp.read() == data
        await cluster.tunables.location_context().aclose()

    asyncio.run(main())
