"""HTTP gateway tests — the reference leaves src/http.rs untested
(SURVEY §4); full coverage here: GET/HEAD/PUT, Range semantics
(206/416/Content-Range), content-type, 404."""

import asyncio
import os

import pytest
import yaml

from chunky_bits_tpu.cluster import Cluster
from chunky_bits_tpu.gateway import make_app, parse_http_range
from chunky_bits_tpu.gateway.http import HttpRangeError


def make_cluster(tmp_path) -> Cluster:
    dirs = []
    for i in range(5):
        d = tmp_path / f"disk{i}"
        d.mkdir()
        dirs.append(str(d))
    meta = tmp_path / "meta"
    meta.mkdir()
    return Cluster.from_obj({
        "destinations": [{"location": d} for d in dirs],
        "metadata": {"type": "path", "format": "yaml", "path": str(meta)},
        "profiles": {"default": {"data": 3, "parity": 2,
                                 "chunk_size": 16}},
    })


def test_get_aborts_cleanly_when_degraded_beyond_repair(tmp_path, caplog):
    """A mid-stream read failure (>p chunks of a later part gone) must
    abort the connection — never deliver a truncated body as a clean
    200 EOF, never kill the server: follow-up requests still work."""
    import aiohttp

    # make_cluster: chunk_size 2^16, d=3 => 192 KiB parts; 4 parts
    part_bytes = 3 * (1 << 16)
    payload = os.urandom(3 * part_bytes + 5000)

    async def main():
        from aiohttp.test_utils import TestClient, TestServer

        cluster = make_cluster(tmp_path)
        app = make_app(cluster)
        # no connection pooling: the server force-closes aborted streams'
        # connections, which would poison pooled reuse
        async with TestClient(
                TestServer(app),
                connector=aiohttp.TCPConnector(force_close=True)) as client:
            assert (await client.put("/obj/x", data=payload)).status == 200
            ref = await cluster.get_file_ref("obj/x")
            # destroy all 5 chunks of the SECOND part: the stream serves
            # part 0 fine, then hits an unreconstructable part
            for chunk in ref.parts[1].all_chunks():
                os.remove(chunk.locations[0].target)

            # unranged GET: headers flow, then the connection aborts
            with pytest.raises(aiohttp.ClientError):
                resp = await client.get("/obj/x")
                assert resp.status == 200
                body = await resp.read()
                # if the transport delivered everything buffered before
                # the abort, it must still be short, not a clean body
                assert len(body) < len(payload)
                raise aiohttp.ClientPayloadError("short body")

            # ranged GET over the broken part aborts too
            lo, hi = part_bytes, 2 * part_bytes - 1
            with pytest.raises(aiohttp.ClientError):
                resp = await client.get(
                    "/obj/x",
                    headers={"Range": f"bytes={lo}-{hi}"})
                assert resp.status == 206
                body = await resp.read()
                assert len(body) < hi - lo + 1
                raise aiohttp.ClientPayloadError("short body")

            # a range entirely inside the intact first part still works
            resp = await client.get(
                "/obj/x", headers={"Range": "bytes=1000-2999"})
            assert resp.status == 206
            assert await resp.read() == payload[1000:3000]

            # ...and cleanly: a take-limited stream must not read (or
            # abort on) broken parts PAST its window
            with caplog.at_level("ERROR", "chunky_bits_tpu.gateway"):
                caplog.clear()
                resp = await client.get(
                    "/obj/x", headers={"Range": "bytes=0-999"})
                assert resp.status == 206
                assert await resp.read() == payload[:1000]
                assert not [r for r in caplog.records
                            if "aborted mid-stream" in r.message]

            # the server survives: an unrelated full roundtrip succeeds
            assert (await client.put("/obj/y",
                                     data=b"still alive")).status == 200
            resp = await client.get("/obj/y")
            assert await resp.read() == b"still alive"

    asyncio.run(main())


def test_parse_http_range():
    assert parse_http_range("bytes=0-99") == ("range", 0, 99)
    assert parse_http_range("bytes=500-") == ("prefix", 500)
    assert parse_http_range("bytes=-300") == ("suffix", 300)
    for bad in ("bytes=5-2", "chars=0-5", "bytes=0-5,10-20", "bytes=-",
                "bytes=a-b", "garbage"):
        with pytest.raises(HttpRangeError):
            parse_http_range(bad)


def test_gateway_end_to_end(tmp_path):
    payload = os.urandom(300000)

    async def main():
        from aiohttp.test_utils import TestClient, TestServer

        cluster = make_cluster(tmp_path)
        app = make_app(cluster)
        async with TestClient(TestServer(app)) as client:
            # PUT with content-type
            resp = await client.put(
                "/objects/data.bin", data=payload,
                headers={"Content-Type": "application/x-demo"})
            assert resp.status == 200
            # metadata written with content_type (through the store
            # surface — the meta-log CI leg changes the disk layout)
            meta = await cluster.metadata.read("objects/data.bin")
            assert meta["content_type"] == "application/x-demo"
            # GET whole
            resp = await client.get("/objects/data.bin")
            assert resp.status == 200
            assert resp.headers["Content-Type"] == "application/x-demo"
            body = await resp.read()
            assert body == payload
            # HEAD
            resp = await client.head("/objects/data.bin")
            assert resp.status == 200
            assert int(resp.headers["Content-Length"]) == len(payload)
            # Range: inclusive slice
            resp = await client.get(
                "/objects/data.bin", headers={"Range": "bytes=100-199"})
            assert resp.status == 206
            body = await resp.read()
            assert body == payload[100:200]
            assert resp.headers["Content-Range"] == \
                f"bytes 100-199/{len(payload)}"
            # prefix range (from offset to EOF)
            resp = await client.get(
                "/objects/data.bin",
                headers={"Range": f"bytes={len(payload) - 50}-"})
            assert resp.status == 206
            assert await resp.read() == payload[-50:]
            # suffix range (last N bytes)
            resp = await client.get(
                "/objects/data.bin", headers={"Range": "bytes=-77"})
            assert resp.status == 206
            assert await resp.read() == payload[-77:]
            # unsatisfiable
            resp = await client.get(
                "/objects/data.bin",
                headers={"Range": f"bytes={len(payload) + 10}-"})
            assert resp.status == 416
            # oversized suffix selects the entire representation
            # (RFC 9110 14.1.2 — satisfiable, not 416)
            resp = await client.get(
                "/objects/data.bin",
                headers={"Range": f"bytes=-{len(payload) + 10}"})
            assert resp.status == 206
            assert await resp.read() == payload
            assert resp.headers["Content-Range"] == \
                f"bytes 0-{len(payload) - 1}/{len(payload)}"
            # unparseable / multi-range / unknown-unit Range headers are
            # ignored per RFC 9110, not rejected
            for header in ("bytes=0-5,10-20", "chars=0-5", "garbage"):
                resp = await client.get(
                    "/objects/data.bin", headers={"Range": header})
                assert resp.status == 200, header
                assert await resp.read() == payload
            # 404 for unknown object
            resp = await client.get("/missing")
            assert resp.status == 404
        await cluster.tunables.location_context().aclose()

    asyncio.run(main())


def test_gateway_roundtrip_through_read_path(tmp_path):
    """PUT then GET with a degraded cluster (one chunk deleted)."""
    payload = os.urandom(150000)

    async def main():
        from aiohttp.test_utils import TestClient, TestServer

        cluster = make_cluster(tmp_path)
        app = make_app(cluster)
        async with TestClient(TestServer(app)) as client:
            assert (await client.put("/f", data=payload)).status == 200
            ref = await cluster.get_file_ref("f")
            os.remove(ref.parts[0].data[0].locations[0].target)
            resp = await client.get("/f")
            assert await resp.read() == payload
        await cluster.tunables.location_context().aclose()

    asyncio.run(main())


def test_gateway_concurrent_puts_coalesce(tmp_path):
    """Parallel small-object PUTs into a jax-backend cluster share encode
    dispatches through the cluster's per-loop batcher (BASELINE config 4's
    many-small-objects regime) and every object reads back identical."""
    import asyncio as aio_mod

    import numpy as np

    from tests.test_tpu_cluster import make_jax_cluster

    rng = np.random.default_rng(17)
    payloads = {f"o{i}": rng.integers(0, 256, 50000, dtype=np.uint8)
                .tobytes() for i in range(8)}

    async def main():
        from aiohttp.test_utils import TestClient, TestServer

        cluster = make_jax_cluster(tmp_path, d=3, p=2)
        app = make_app(cluster)
        async with TestClient(TestServer(app)) as client:
            results = await asyncio.gather(*[
                client.put(f"/objects/{name}", data=data)
                for name, data in payloads.items()])
            assert all(r.status == 200 for r in results)
            batcher = cluster._encode_batchers.get(
                aio_mod.get_running_loop())
            assert batcher is not None and batcher.dispatches > 0
            total_parts = 0
            for name in payloads:
                total_parts += len(
                    (await cluster.get_file_ref(f"objects/{name}")).parts)
            assert batcher.dispatches < total_parts
            for name, data in payloads.items():
                resp = await client.get(f"/objects/{name}")
                assert await resp.read() == data
        await cluster.tunables.location_context().aclose()

    asyncio.run(main())


def test_gateway_put_limits_and_errors(tmp_path):
    """Hardening beyond the reference (http.rs:97-118 maps every failure
    to a bare 500): 413 on oversized bodies (declared or streamed), error
    bodies on 500s, and the concurrent-PUT bound holds under load."""
    async def main():
        from aiohttp.test_utils import TestClient, TestServer

        cluster = make_cluster(tmp_path)
        app = make_app(cluster, max_put_bytes=100000, max_concurrent_puts=4)
        async with TestClient(TestServer(app)) as client:
            # declared oversize: rejected from the header, before the
            # streaming ingest starts
            resp = await client.put("/big", data=b"x" * 200000)
            assert resp.status == 413
            # undeclared oversize: chunked stream, caught mid-body
            async def gen():
                for _ in range(30):
                    yield b"y" * 10000
            resp = await client.put("/big2", data=gen())
            assert resp.status == 413
            assert "too large" in await resp.text()
            # within the limit: accepted
            resp = await client.put("/ok", data=b"z" * 50000)
            assert resp.status == 200
            # no metadata was durably written for the rejected bodies
            from chunky_bits_tpu.cluster.metadata import MetadataReadError
            for rejected in ("big", "big2"):
                with pytest.raises(MetadataReadError):
                    await cluster.metadata.read(rejected)

    asyncio.run(main())


def test_gateway_put_concurrency_bound(tmp_path, monkeypatch):
    """At most max_concurrent_puts ingests run at once; the rest queue
    and complete."""
    async def main():
        from aiohttp.test_utils import TestClient, TestServer

        cluster = make_cluster(tmp_path)
        in_flight = {"now": 0, "peak": 0}
        real_write = Cluster.write_file

        async def counting_write(self, path, reader, profile,
                                 content_type=None, **kw):
            in_flight["now"] += 1
            in_flight["peak"] = max(in_flight["peak"], in_flight["now"])
            try:
                await asyncio.sleep(0.01)
                return await real_write(self, path, reader, profile,
                                        content_type, **kw)
            finally:
                in_flight["now"] -= 1

        monkeypatch.setattr(Cluster, "write_file", counting_write)
        app = make_app(cluster, max_concurrent_puts=3)
        async with TestClient(TestServer(app)) as client:
            resps = await asyncio.gather(*[
                client.put(f"/obj{i}", data=os.urandom(20000))
                for i in range(12)
            ])
            assert all(r.status == 200 for r in resps)
        assert in_flight["peak"] <= 3
        assert in_flight["peak"] > 1  # genuinely concurrent

    asyncio.run(main())


def test_gateway_concurrent_puts_and_ranged_gets_stress(tmp_path):
    """Mixed load: concurrent PUTs of distinct objects while ranged GETs
    stream an existing object; every byte must come back right."""
    payload = os.urandom(400000)

    async def main():
        from aiohttp.test_utils import TestClient, TestServer

        cluster = make_cluster(tmp_path)
        app = make_app(cluster)
        async with TestClient(TestServer(app)) as client:
            resp = await client.put("/base", data=payload)
            assert resp.status == 200

            async def put_one(i):
                body = os.urandom(60000 + i * 1000)
                r = await client.put(f"/stress{i}", data=body)
                assert r.status == 200
                return (i, body)

            async def get_range(i):
                start = (i * 37003) % (len(payload) - 5000)
                end = start + 4999
                r = await client.get(
                    "/base", headers={"Range": f"bytes={start}-{end}"})
                assert r.status == 206
                assert await r.read() == payload[start:end + 1]

            puts, _ = await asyncio.gather(
                asyncio.gather(*[put_one(i) for i in range(8)]),
                asyncio.gather(*[get_range(i) for i in range(16)]),
            )
            for i, body in puts:
                r = await client.get(f"/stress{i}")
                assert await r.read() == body

    asyncio.run(main())


def test_gateway_oversize_put_orphans_are_gc_collectable(tmp_path):
    """A mid-stream 413 leaves no metadata; shards already written stay
    (they are content-addressed and possibly shared with other files, so
    blind deletion would be a data-destruction primitive) and the
    reference-checking find-unused-hashes GC reclaims them."""
    async def main():
        from aiohttp.test_utils import TestClient, TestServer

        from chunky_bits_tpu.cli.main import main as cli_main

        cluster = make_cluster(tmp_path)
        app = make_app(cluster, max_put_bytes=200000)
        async with TestClient(TestServer(app)) as client:
            # a durable object first
            resp = await client.put("/keep", data=b"k" * 150000)
            assert resp.status == 200

            async def gen():
                for _ in range(60):  # 600 KB chunked, no Content-Length
                    yield b"y" * 10000
            resp = await client.put("/leak", data=gen())
            assert resp.status == 413
        assert not (tmp_path / "meta" / "leak").exists()
        # write the cluster spec out so the GC CLI can run against it
        spec = tmp_path / "cluster.yaml"
        spec.write_text(yaml.safe_dump(cluster.to_obj()))
        rc = await asyncio.to_thread(
            cli_main,
            ["find-unused-hashes", "--remove", f"{spec}#.", "--",
             *[str(tmp_path / f"disk{i}") for i in range(5)]])
        assert rc == 0
        # orphans gone, durable object intact
        ref = await cluster.get_file_ref("keep")
        report = await ref.verify()
        assert report.is_ideal()
        referenced = {str(c.hash) for p in ref.parts
                      for c in (*p.data, *p.parity)}
        remaining = {p.name for i in range(5)
                     for p in (tmp_path / f"disk{i}").iterdir()}
        assert remaining == referenced

    asyncio.run(main())


def test_guarded_body_rate_floor(monkeypatch):
    """The minimum-ingest-rate guard aborts a trickling body once past
    the grace window (slow-loris cannot pin a PUT slot forever)."""
    from chunky_bits_tpu.gateway import http as gw

    class Trickle:
        async def read(self, n=-1):
            return b"z"

    clock = {"now": 0.0}
    monkeypatch.setattr(gw.time, "monotonic", lambda: clock["now"])
    body = gw._GuardedBody(Trickle(), max_bytes=None, min_rate=256)

    async def main():
        # inside the grace window: slow reads are tolerated
        clock["now"] = gw._RATE_GRACE_SECONDS - 1
        assert await body.read(1024) == b"z"
        # past the grace window at ~0 B/s average: aborted before even
        # waiting on the client
        clock["now"] = gw._RATE_GRACE_SECONDS + 10
        with pytest.raises(gw._BodyTooSlow):
            await body.read(1024)
        # min_rate=0 disables the floor entirely
        fast = gw._GuardedBody(Trickle(), max_bytes=None, min_rate=0)
        clock["now"] = 10_000.0
        assert await fast.read(1024) == b"z"

    asyncio.run(main())


def test_guarded_body_silent_client_times_out(monkeypatch):
    """A client that sends headers and then *nothing* is also aborted:
    the rate floor is a read deadline, not a post-read check."""
    from chunky_bits_tpu.gateway import http as gw

    class Silent:
        async def read(self, n=-1):
            await asyncio.Future()  # never resolves

    monkeypatch.setattr(gw, "_RATE_GRACE_SECONDS", 0.05)

    async def main():
        body = gw._GuardedBody(Silent(), max_bytes=None, min_rate=256)
        with pytest.raises(gw._BodyTooSlow):
            await body.read(1024)

    asyncio.run(main())


def test_guarded_body_burst_then_stall_cannot_bank_credit(monkeypatch):
    """Bytes already sent must not buy an unbounded stall: a read can
    never wait longer than the grace window, however fast the client
    burst beforehand."""
    from chunky_bits_tpu.gateway import http as gw

    monkeypatch.setattr(gw, "_RATE_GRACE_SECONDS", 0.05)

    class Silent:
        async def read(self, n=-1):
            await asyncio.Future()  # never resolves

    async def main():
        body = gw._GuardedBody(Silent(), max_bytes=None, min_rate=1)
        body.total = 10 ** 9  # credit banked by a line-speed burst
        with pytest.raises(gw._BodyTooSlow):
            await body.read(1024)

    asyncio.run(main())
