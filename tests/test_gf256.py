"""KATs and algebraic checks for the GF(2^8) core and the RS matrix
convention (mirrors the role of the reference crate's own field tests;
the geometry grid mirrors tests/file.rs:26-56)."""

import numpy as np
import pytest

from chunky_bits_tpu.errors import ErasureError
from chunky_bits_tpu.ops import gf256, matrix
from chunky_bits_tpu.ops.backend import ErasureCoder, NumpyBackend


def test_field_known_values():
    # Known values of the 0x11d / generator-2 field (same field as the
    # reference's galois_8 and the Linux RAID6 tables).
    assert gf256.EXP_TABLE[0] == 1
    assert gf256.EXP_TABLE[1] == 2
    assert gf256.EXP_TABLE[8] == 29  # 2^8 = 0x100 ^ 0x11d = 29
    assert gf256.LOG_TABLE[3] == 25
    assert gf256.gf_mul(0x80, 2) == 29
    assert gf256.gf_mul(0, 123) == 0
    assert gf256.gf_mul(1, 123) == 123


def test_field_axioms_sampled():
    rng = np.random.default_rng(0)
    for _ in range(200):
        a, b, c = (int(x) for x in rng.integers(0, 256, 3))
        assert gf256.gf_mul(a, b) == gf256.gf_mul(b, a)
        assert gf256.gf_mul(a, gf256.gf_mul(b, c)) == gf256.gf_mul(
            gf256.gf_mul(a, b), c
        )
        # distributive over XOR (field addition)
        assert gf256.gf_mul(a, b ^ c) == gf256.gf_mul(a, b) ^ gf256.gf_mul(a, c)
        if b:
            assert gf256.gf_mul(gf256.gf_div(a, b), b) == a
    for a in range(1, 256):
        assert gf256.gf_mul(a, gf256.gf_inv(a)) == 1


def test_mul_bit_matrix_matches_scalar():
    rng = np.random.default_rng(1)
    for c in [0, 1, 2, 3, 29, 128, 255]:
        m = gf256.mul_bit_matrix(c)
        for x in rng.integers(0, 256, 16):
            x = int(x)
            bits = np.array([(x >> k) & 1 for k in range(8)], dtype=np.uint8)
            out_bits = (m @ bits) % 2
            out = sum(int(v) << k for k, v in enumerate(out_bits))
            assert out == gf256.gf_mul(c, x)


def test_invert_roundtrip():
    rng = np.random.default_rng(2)
    for n in (1, 2, 5, 10):
        while True:
            m = rng.integers(0, 256, (n, n)).astype(np.uint8)
            try:
                inv = matrix.gf_invert(m)
                break
            except ErasureError:
                continue
        assert np.array_equal(matrix.gf_matmul(m, inv), matrix.gf_identity(n))


def test_encode_matrix_convention():
    # Hand-derived for d=2, p=1: V rows [1,0],[1,1],[1,2]; top is
    # self-inverse; parity row = [1^2, 2] = [3, 2].
    e = matrix.build_encode_matrix(2, 1)
    assert e.tolist() == [[1, 0], [0, 1], [3, 2]]
    # d=1: every parity row is [1] => parity shards replicate the data shard.
    e1 = matrix.build_encode_matrix(1, 3)
    assert e1.tolist() == [[1], [1], [1], [1]]
    # Systematic top for a larger geometry.
    e2 = matrix.build_encode_matrix(10, 4)
    assert np.array_equal(e2[:10], matrix.gf_identity(10))


@pytest.mark.parametrize("d", [1, 2, 3])
@pytest.mark.parametrize("p", [0, 1, 2, 3])
def test_encode_reconstruct_grid(d, p):
    rng = np.random.default_rng(d * 10 + p)
    size = 257
    coder = ErasureCoder(d, p, NumpyBackend())
    data = rng.integers(0, 256, (4, d, size)).astype(np.uint8)
    parity = coder.encode_batch(data)
    assert parity.shape == (4, p, size)
    full = np.concatenate([data, parity], axis=1)
    if p == 0:
        return
    # Erase up to p shards, reconstruct, compare byte-for-byte.
    for erased_count in range(1, p + 1):
        erased = list(
            rng.choice(d + p, size=erased_count, replace=False).astype(int)
        )
        shards = [None if i in erased else full[0, i].copy()
                  for i in range(d + p)]
        out = coder.reconstruct(shards)
        for i in range(d + p):
            assert np.array_equal(out[i], full[0, i]), (i, erased)


def test_reconstruct_data_only():
    coder = ErasureCoder(3, 2, NumpyBackend())
    rng = np.random.default_rng(7)
    data = rng.integers(0, 256, (1, 3, 64)).astype(np.uint8)
    parity = coder.encode_batch(data)
    full = np.concatenate([data, parity], axis=1)[0]
    shards = [None, full[1].copy(), None, full[3].copy(), full[4].copy()]
    out = coder.reconstruct_data(shards)
    assert np.array_equal(out[0], full[0])
    assert np.array_equal(out[2], full[2])


def test_too_few_shards():
    coder = ErasureCoder(3, 2, NumpyBackend())
    shards = [np.zeros(8, dtype=np.uint8), None, None, None,
              np.zeros(8, dtype=np.uint8)]
    with pytest.raises(ErasureError):
        coder.reconstruct(shards)


def test_bad_geometry():
    with pytest.raises(ErasureError):
        ErasureCoder(0, 2, NumpyBackend())
    with pytest.raises(ErasureError):
        matrix.build_encode_matrix(200, 200)
