"""Multi-device mesh tests: dp/sp-sharded erasure transforms on the
virtual 8-device CPU mesh, byte-identical to the numpy oracle."""

import numpy as np
import pytest

from chunky_bits_tpu.ops import matrix
from chunky_bits_tpu.ops.backend import ErasureCoder, NumpyBackend


@pytest.fixture(scope="module")
def eight_devices():
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return jax.devices()


@pytest.mark.parametrize("dp,sp", [(8, 1), (4, 2), (2, 4), (1, 8)])
def test_sharded_apply_identity(eight_devices, dp, sp):
    from chunky_bits_tpu.parallel import make_mesh, sharded_apply

    d, p = 10, 4
    enc = matrix.build_encode_matrix(d, p)
    rng = np.random.default_rng(dp * 10 + sp)
    data = rng.integers(0, 256, (dp * 2, d, 128 * sp), dtype=np.uint8)
    mesh = make_mesh(8, dp=dp, sp=sp)
    got = np.asarray(sharded_apply(mesh, enc[d:], data))
    want = ErasureCoder(d, p, NumpyBackend()).encode_batch(data)
    assert np.array_equal(got, want)


def test_encode_step_with_collective(eight_devices):
    from chunky_bits_tpu.parallel import encode_step_sharded, make_mesh

    d, p = 3, 2
    enc = matrix.build_encode_matrix(d, p)
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (8, d, 512), dtype=np.uint8)
    mesh = make_mesh(8, dp=4, sp=2)
    parity, checksum = encode_step_sharded(mesh, enc, data)
    want = ErasureCoder(d, p, NumpyBackend()).encode_batch(data)
    assert np.array_equal(np.asarray(parity), want)
    assert int(checksum) == int(want.astype(np.uint64).sum() % (1 << 32))


def test_sharded_decode(eight_devices):
    """Reconstruction rows through the sharded path."""
    from chunky_bits_tpu.parallel import make_mesh, sharded_apply

    d, p = 10, 4
    coder = ErasureCoder(d, p, NumpyBackend())
    enc = coder.encode_matrix
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, (8, d, 256), dtype=np.uint8)
    parity = coder.encode_batch(data)
    full = np.concatenate([data, parity], axis=1)
    present = [1, 2, 3, 4, 5, 6, 7, 8, 9, 10]  # shard 0 and 11-13 lost
    wanted = [0]
    dec = matrix.decode_matrix(enc, present, wanted)
    mesh = make_mesh(8, dp=8, sp=1)
    picked = full[:, np.array(present[:d]), :]
    got = np.asarray(sharded_apply(mesh, dec, picked))
    assert np.array_equal(got[:, 0, :], data[:, 0, :])


@pytest.mark.parametrize("dp,tp", [(4, 2), (2, 4), (1, 8), (8, 1)])
def test_wide_stripe_encode(eight_devices, dp, tp):
    """BASELINE.md config 5: wide stripe d=16..20 with the contraction
    axis split over 'tp' and partial popcounts psum'd across chips."""
    from chunky_bits_tpu.parallel import encode_wide_sharded, \
        make_stripe_mesh

    d, p = 16, 6
    enc = matrix.build_encode_matrix(d, p)
    rng = np.random.default_rng(dp * 100 + tp)
    data = rng.integers(0, 256, (max(dp, 2), d, 384), dtype=np.uint8)
    mesh = make_stripe_mesh(8, dp=dp, tp=tp)
    got = np.asarray(encode_wide_sharded(mesh, enc, data))
    want = ErasureCoder(d, p, NumpyBackend()).encode_batch(data)
    assert np.array_equal(got, want)


def test_wide_stripe_d20_p6(eight_devices):
    """The exact BASELINE config-5 geometry (d=20 divisible by tp=4)."""
    from chunky_bits_tpu.parallel import encode_wide_sharded, \
        make_stripe_mesh

    d, p = 20, 6
    enc = matrix.build_encode_matrix(d, p)
    rng = np.random.default_rng(5)
    data = rng.integers(0, 256, (4, d, 256), dtype=np.uint8)
    mesh = make_stripe_mesh(8, dp=2, tp=4)
    got = np.asarray(encode_wide_sharded(mesh, enc, data))
    want = ErasureCoder(d, p, NumpyBackend()).encode_batch(data)
    assert np.array_equal(got, want)


def test_wide_stripe_decode(eight_devices):
    """Decode rows through the contraction-sharded path: reconstruct 4
    erased data shards of a d=20 stripe from 20 survivors."""
    from chunky_bits_tpu.parallel import make_stripe_mesh, \
        wide_apply_sharded

    d, p = 20, 6
    coder = ErasureCoder(d, p, NumpyBackend())
    enc = coder.encode_matrix
    rng = np.random.default_rng(6)
    data = rng.integers(0, 256, (4, d, 256), dtype=np.uint8)
    parity = coder.encode_batch(data)
    full = np.concatenate([data, parity], axis=1)
    erased = [0, 5, 11, 19]
    present = [i for i in range(d + p) if i not in erased][:d]
    dec = matrix.decode_matrix(enc, present, erased)
    mesh = make_stripe_mesh(8, dp=2, tp=4)
    got = np.asarray(
        wide_apply_sharded(mesh, dec, full[:, np.array(present), :]))
    assert np.array_equal(got, data[:, np.array(erased), :])


def test_wide_stripe_rejects_indivisible(eight_devices):
    from chunky_bits_tpu.parallel import make_stripe_mesh, \
        wide_apply_sharded

    d, p = 10, 4
    enc = matrix.build_encode_matrix(d, p)
    mesh = make_stripe_mesh(8, dp=2, tp=4)
    data = np.zeros((2, d, 128), dtype=np.uint8)
    with pytest.raises(ValueError):
        wide_apply_sharded(mesh, enc[d:], data)


def test_graft_entry():
    """The driver's entry points must keep working."""
    import sys

    sys.path.insert(0, "/root/repo")
    import __graft_entry__
    import jax

    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[1] == 4
    __graft_entry__.dryrun_multichip(len(jax.devices()))


def test_pallas_kernel_interpret_identity():
    """The fused pallas kernel, in interpret mode on CPU, must match the
    oracle byte-for-byte (the TPU path runs the same kernel compiled)."""
    from chunky_bits_tpu.ops.pallas_kernels import apply_matrix_pallas

    d, p = 10, 4
    enc = matrix.build_encode_matrix(d, p)
    rng = np.random.default_rng(2)
    oracle = ErasureCoder(d, p, NumpyBackend())
    for batch in (2, 3):  # even -> two parts per grid cell, odd -> one
        data = rng.integers(0, 256, (batch, d, 256), dtype=np.uint8)
        got = np.asarray(apply_matrix_pallas(enc[d:], data,
                                             interpret=True))
        want = oracle.encode_batch(data)
        assert np.array_equal(got, want), batch


def test_packed_kernel_interpret_identity():
    """The field-multiplexed kernel (two data columns per int8 MXU
    element, contraction split so per-field popcounts never collide)
    must match the oracle byte-for-byte at every gated geometry,
    including the gate's edges (p=8 doubles output rows to the full MXU
    tile; d=15 puts ceil(K8/2)=60 popcounts one step under the 6-bit
    field ceiling)."""
    import jax.numpy as jnp

    from chunky_bits_tpu.ops.pallas_kernels import (
        apply_m2_bitmajor_packed,
        bitmajor_device_matrix,
        packed_geometry_ok,
    )

    rng = np.random.default_rng(5)
    # gate corners (d and p extremes) + interior geometries + decode-
    # shaped rows; a 120-geometry sweep of the whole gated grid (d 1..15
    # x p 1..8, encode + decode rows) passed as a one-off with the same
    # oracle — this subset keeps the corners pinned in the suite
    for d, p, batch, s in [(10, 4, 2, 512), (10, 4, 3, 256), (3, 2, 2, 256),
                           (15, 8, 2, 256), (8, 8, 2, 256), (1, 1, 2, 256),
                           (15, 1, 2, 512), (1, 8, 3, 256), (12, 6, 4, 768)]:
        assert packed_geometry_ok(p, d, s)
        enc = matrix.build_encode_matrix(d, p)
        data = rng.integers(0, 256, (batch, d, s), dtype=np.uint8)
        m2 = bitmajor_device_matrix(enc[d:])
        got = np.asarray(apply_m2_bitmajor_packed(
            m2, jnp.asarray(data), interpret=True))
        want = ErasureCoder(d, p, NumpyBackend()).encode_batch(data)
        assert np.array_equal(got, want), (d, p, batch, s)
        if d >= 2 and p >= 2:
            # decode-shaped rows: reconstruct r (= #erased, <= p) rows
            erased = [0, d]
            present = [i for i in range(d + p) if i not in erased][:d]
            dec = matrix.decode_matrix(enc, present, erased)
            full = np.concatenate([data, want], axis=1)
            got = np.asarray(apply_m2_bitmajor_packed(
                bitmajor_device_matrix(dec),
                jnp.asarray(np.ascontiguousarray(full[:, np.array(present)])),
                interpret=True))
            assert np.array_equal(got, full[:, np.array(erased)]), (d, p)

    # outside the gate: p>8 (two weight tiles), d>15 (field overflow),
    # and lane-misaligned tile halves must all be refused
    for r, k, s in [(9, 10, 512), (4, 16, 512), (4, 10, 128)]:
        assert not packed_geometry_ok(r, k, s)


def test_packed_kernel_env_selection(monkeypatch):
    """$CHUNKY_BITS_TPU_PACKED_KERNEL=1 routes gated geometries through the
    field-multiplexed kernel from the shared entry point (and therefore
    from apply_matrix_pallas and every mesh impl) with identical bytes;
    ungated geometries must keep falling back to the standard kernel."""
    import jax.numpy as jnp

    from chunky_bits_tpu.ops.pallas_kernels import (
        apply_m2_bitmajor,
        bitmajor_device_matrix,
    )

    monkeypatch.setenv("CHUNKY_BITS_TPU_PACKED_KERNEL", "1")
    rng = np.random.default_rng(11)
    calls = []
    import chunky_bits_tpu.ops.pallas_kernels as pk
    real_packed = pk.apply_m2_bitmajor_packed
    monkeypatch.setattr(
        pk, "apply_m2_bitmajor_packed",
        lambda *a, **kw: calls.append(a[0].shape) or real_packed(*a, **kw))
    # gated (d=10,p=4) and ungated (s=128 lane-misaligned halves)
    for d, p, s in [(10, 4, 512), (10, 4, 128)]:
        enc = matrix.build_encode_matrix(d, p)
        data = rng.integers(0, 256, (2, d, s), dtype=np.uint8)
        m2 = bitmajor_device_matrix(enc[d:])
        got = np.asarray(apply_m2_bitmajor(m2, jnp.asarray(data),
                                           interpret=True))
        want = ErasureCoder(d, p, NumpyBackend()).encode_batch(data)
        assert np.array_equal(got, want), (d, p, s)
    # identical bytes from both kernels would mask broken routing: the
    # packed path must have been taken exactly once (the gated call)
    assert calls == [(32, 80)]


def test_sharded_apply_pallas_impl_identity(eight_devices):
    """The fused-kernel mesh impl (what TPU meshes auto-select), run in
    interpret mode on the virtual CPU mesh, matches the oracle through
    every sharded path: dp/sp apply, the checksum encode step, and the
    contraction-sharded wide stripe with its post-psum bit-major pack."""
    from chunky_bits_tpu.parallel import (
        encode_step_sharded,
        encode_wide_sharded,
        make_mesh,
        make_stripe_mesh,
        sharded_apply,
    )
    from chunky_bits_tpu.parallel.mesh import wide_apply_sharded

    d, p = 10, 4
    enc = matrix.build_encode_matrix(d, p)
    oracle = ErasureCoder(d, p, NumpyBackend())
    rng = np.random.default_rng(7)
    data = rng.integers(0, 256, (8, d, 512), dtype=np.uint8)
    want = oracle.encode_batch(data)

    mesh = make_mesh(8, dp=4, sp=2)
    got = np.asarray(sharded_apply(mesh, enc[d:], data,
                                   impl="pallas_interpret"))
    assert np.array_equal(got, want)

    parity, checksum = encode_step_sharded(mesh, enc, data,
                                           impl="pallas_interpret")
    assert np.array_equal(np.asarray(parity), want)
    assert int(checksum) == int(want.astype(np.uint64).sum() % (1 << 32))

    smesh = make_stripe_mesh(8, dp=4, tp=2)
    got = np.asarray(encode_wide_sharded(smesh, enc, data,
                                         impl="pallas_interpret"))
    assert np.array_equal(got, want)

    # decode rows through the pallas wide path
    full = np.concatenate([data, want], axis=1)
    erased = [0, 5, 9, 13]
    present = [i for i in range(d + p) if i not in erased][:d]
    dec = matrix.decode_matrix(enc, present, erased)
    got = np.asarray(wide_apply_sharded(
        smesh, dec, full[:, np.array(present), :], impl="pallas_interpret"))
    assert np.array_equal(got, full[:, np.array(erased), :])


def test_acc_kernel_int16_contract():
    """The pack-free accumulator narrows to int16 after the exact int32
    MXU accumulation (global popcount <= K8 <= 2048): dtype is part of
    the mesh contract — it halves the tp psum's ICI bytes — and the
    post-psum bit-major pack must reproduce the oracle from it."""
    import jax.numpy as jnp

    from chunky_bits_tpu.ops.pallas_kernels import (
        acc_m2_bitmajor,
        bitmajor_device_matrix,
        pack_acc_bitmajor,
    )

    d, p = 20, 6
    enc = matrix.build_encode_matrix(d, p)
    rng = np.random.default_rng(13)
    data = rng.integers(0, 256, (2, d, 256), dtype=np.uint8)
    m2 = bitmajor_device_matrix(enc[d:])
    acc = acc_m2_bitmajor(m2, jnp.asarray(data), interpret=True)
    assert acc.dtype == jnp.int16
    want = ErasureCoder(d, p, NumpyBackend()).encode_batch(data)
    assert np.array_equal(np.asarray(pack_acc_bitmajor(acc)), want)
    # the contraction-split sum of two half-stripe accumulators equals
    # the full accumulator (the psum identity, minus the mesh)
    half = d // 2
    m2a = bitmajor_device_matrix(np.ascontiguousarray(enc[d:, :half]))
    m2b = bitmajor_device_matrix(np.ascontiguousarray(enc[d:, half:]))
    acc2 = (acc_m2_bitmajor(m2a, jnp.asarray(data[:, :half]),
                            interpret=True)
            + acc_m2_bitmajor(m2b, jnp.asarray(data[:, half:]),
                              interpret=True))
    assert np.array_equal(np.asarray(pack_acc_bitmajor(acc2)), want)


def test_mesh_auto_impl_einsum_on_cpu(eight_devices):
    """Virtual CPU meshes must keep auto-selecting the einsum impl (the
    pallas Mosaic kernel only compiles on TPU)."""
    from chunky_bits_tpu.parallel import make_mesh
    from chunky_bits_tpu.parallel.mesh import _auto_impl

    mesh = make_mesh(8, dp=4, sp=2)
    assert _auto_impl(mesh, 4, 10, 512) == "einsum"


def test_mesh_backend_spec_parsing():
    from chunky_bits_tpu.errors import ErasureError
    from chunky_bits_tpu.parallel.backend import parse_mesh_spec

    assert parse_mesh_spec("dp4,sp2") == {"dp": 4, "sp": 2}
    assert parse_mesh_spec("tp4") == {"tp": 4}
    assert parse_mesh_spec("dp=2, sp=4") == {"dp": 2, "sp": 4}
    for bad in ("", "xp3", "dp4,tp2,sp2", "tp2,sp2", "dp4,dp2", "dp0"):
        with pytest.raises(ErasureError):
            parse_mesh_spec(bad)


def test_mesh_backend_dp_sp_identity(eight_devices):
    """jax:dpN,spM backend matches the numpy oracle, including ragged
    batch/byte sizes that need dispatch padding."""
    from chunky_bits_tpu.ops.backend import get_backend

    backend = get_backend("jax:dp4,sp2")
    d, p = 5, 3
    enc = matrix.build_encode_matrix(d, p)
    oracle = ErasureCoder(d, p, NumpyBackend())
    rng = np.random.default_rng(0)
    for b, s in ((8, 512), (3, 512), (5, 300), (1, 77)):
        data = rng.integers(0, 256, (b, d, s), dtype=np.uint8)
        got = backend.apply_matrix(enc[d:], data)
        want = oracle.encode_batch(data)
        assert np.array_equal(got, want), (b, s)


def test_mesh_backend_wide_stripe_identity(eight_devices):
    from chunky_bits_tpu.errors import ErasureError
    from chunky_bits_tpu.ops.backend import get_backend

    backend = get_backend("jax:tp4")
    d, p = 20, 6
    enc = matrix.build_encode_matrix(d, p)
    oracle = ErasureCoder(d, p, NumpyBackend())
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, (3, d, 384), dtype=np.uint8)
    got = backend.apply_matrix(enc[d:], data)
    assert np.array_equal(got, oracle.encode_batch(data))
    # decode through the same backend: erase 6 shards, rebuild
    coder = ErasureCoder(d, p, backend)
    full = np.concatenate([data, oracle.encode_batch(data)], axis=1)
    shards = [None if i in (0, 5, 9, 21, 23, 25) else full[0, i]
              for i in range(d + p)]
    out = coder.reconstruct(shards)
    for i in range(d + p):
        assert np.array_equal(out[i], full[0, i])
    # indivisible stripe rejected
    with pytest.raises(ErasureError):
        backend.apply_matrix(enc[d:][:, :18], data[:, :18])


def test_mesh_backend_cluster_lifecycle(tmp_path, eight_devices):
    """cluster.yaml tunables can put the erasure plane on a device mesh:
    write through jax:dp4,sp2, read back, shards byte-identical."""
    import asyncio as aio_mod

    from chunky_bits_tpu.cluster import Cluster
    from chunky_bits_tpu.utils import aio

    dirs = []
    for i in range(6):
        dd = tmp_path / f"disk{i}"
        dd.mkdir()
        dirs.append(str(dd))
    meta = tmp_path / "meta"
    meta.mkdir()
    cluster = Cluster.from_obj({
        "destinations": [{"location": x} for x in dirs],
        "metadata": {"type": "path", "format": "yaml", "path": str(meta)},
        "tunables": {"backend": "jax:dp4,sp2"},
        "profiles": {"default": {"data": 4, "parity": 2,
                                 "chunk_size": 14}},
    })
    payload = np.random.default_rng(5).integers(
        0, 256, 200000, dtype=np.uint8).tobytes()

    async def main():
        await cluster.write_file("f", aio.BytesReader(payload),
                                 cluster.get_profile())
        got = await (await cluster.get_file_ref("f")) \
            .read_builder().read_all()
        assert got == payload
        # mesh backend clusters engage the shared encode batcher
        assert cluster._encode_batchers.get(
            aio_mod.get_running_loop()) is not None

    aio_mod.run(main())


def test_mesh_backend_name_normalization(eight_devices):
    from chunky_bits_tpu.ops.backend import get_backend

    a = get_backend("jax:dp=4, sp=2")
    b = get_backend("jax:dp4,sp2")
    assert a is b
    assert a.name == "jax:dp4,sp2"
    # too-many-devices specs fail with a clear message
    from chunky_bits_tpu.errors import ErasureError
    with pytest.raises(ErasureError, match="devices"):
        get_backend("jax:dp64,sp2")


def test_multihost_single_process_is_noop():
    """init_multihost without a coordinator is a clean single-process
    setup; local meshes span exactly this process's devices and run the
    sharded step."""
    import jax

    from chunky_bits_tpu.ops import matrix
    from chunky_bits_tpu.ops.backend import ErasureCoder, NumpyBackend
    from chunky_bits_tpu.parallel import (
        encode_step_sharded,
        init_multihost,
        local_mesh,
        partition_parts,
    )

    idx, count = init_multihost()
    assert (idx, count) == (0, 1)
    idx, count = init_multihost()  # idempotent
    assert (idx, count) == (0, 1)

    mesh = local_mesh(sp=2)
    assert mesh.devices.size == len(jax.local_devices())

    d, p = 4, 2
    enc = matrix.build_encode_matrix(d, p)
    rng = np.random.default_rng(5)
    data = rng.integers(0, 256, (8, d, 512), dtype=np.uint8)
    lo, hi = partition_parts(len(data))
    assert (lo, hi) == (0, len(data))  # one process owns everything
    parity, _ = encode_step_sharded(mesh, enc, data[lo:hi])
    want = ErasureCoder(d, p, NumpyBackend()).encode_batch(data)
    assert np.array_equal(np.asarray(parity), want)


def test_partition_parts_deals_balanced_contiguous_slices():
    from chunky_bits_tpu.parallel import partition_parts

    for total, n in [(10, 4), (8, 8), (3, 8), (0, 4), (257, 16)]:
        slices = [partition_parts(total, i, n) for i in range(n)]
        # contiguous, ordered, covering exactly [0, total)
        assert slices[0][0] == 0 and slices[-1][1] == total
        for (a, b), (c, e) in zip(slices, slices[1:]):
            assert b == c
        sizes = [b - a for a, b in slices]
        assert max(sizes) - min(sizes) <= 1  # balanced

    with pytest.raises(ValueError):
        partition_parts(10, 5, 4)


def test_local_mesh_uses_local_devices():
    """The local meshes are built from jax.local_devices(), not a count
    sliced off the global list — on a process_index>0 host those differ
    and collectives would otherwise cross DCN."""
    import jax

    from chunky_bits_tpu.parallel import local_mesh, local_stripe_mesh

    local = set(jax.local_devices())
    for mesh in (local_mesh(sp=2), local_stripe_mesh(tp=2)):
        assert set(mesh.devices.flat) == local


def test_two_process_distributed_encode(tmp_path):
    """The explicit-args main path of init_multihost, exercised for real:
    two CPU processes join one jax.distributed job over a localhost
    coordinator, deal the part batch with partition_parts, encode their
    slices on local meshes, and the parent verifies the concatenation is
    oracle-identical (multi-host analogue of the reference's one-process
    pipeline; SURVEY distributed-backend row)."""
    import os
    import socket
    import subprocess
    import sys

    # pick a free port for the coordinator
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("JAX_COORDINATOR_ADDRESS", None)
    env.pop("COORDINATOR_ADDRESS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")

    nprocs = 2
    outs = [str(tmp_path / f"w{i}.npz") for i in range(nprocs)]
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(repo, "tests", "mh_worker.py"),
             str(port), str(i), str(nprocs), outs[i]],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        for i in range(nprocs)
    ]
    results = []
    try:
        for p in procs:
            stdout, stderr = p.communicate(timeout=180)
            results.append((p.returncode, stdout, stderr))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rc, stdout, stderr in results:
        assert rc == 0, stderr.decode(errors="replace")[-2000:]

    d, p_, size, total = 4, 2, 256, 12
    data = np.random.default_rng(77).integers(
        0, 256, (total, d, size), dtype=np.uint8)
    want = ErasureCoder(d, p_, NumpyBackend()).encode_batch(data)

    pieces = [np.load(o) for o in outs]
    # contiguous balanced cover of [0, total)
    assert int(pieces[0]["lo"]) == 0
    assert int(pieces[0]["hi"]) == int(pieces[1]["lo"])
    assert int(pieces[1]["hi"]) == total
    got = np.concatenate([pc["parity"] for pc in pieces], axis=0)
    assert np.array_equal(got, want)
    # each worker's psum checksum covers exactly its slice
    for pc in pieces:
        lo, hi = int(pc["lo"]), int(pc["hi"])
        assert int(pc["checksum"]) == \
            int(want[lo:hi].astype(np.uint64).sum() % (1 << 32))


def test_init_multihost_rejects_late_explicit_args():
    """Explicit coordinator args after the process was finalized
    single-host must raise, not be silently ignored."""
    import chunky_bits_tpu.parallel.multihost as mh

    mh.init_multihost()  # finalize single-process
    with pytest.raises(RuntimeError, match="already finalized"):
        mh.init_multihost("router:1234", num_processes=4, process_id=1)
    with pytest.raises(RuntimeError, match="already finalized"):
        mh.init_multihost(process_id=2)  # lone process_id is explicit too
