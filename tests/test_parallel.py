"""Multi-device mesh tests: dp/sp-sharded erasure transforms on the
virtual 8-device CPU mesh, byte-identical to the numpy oracle."""

import numpy as np
import pytest

from chunky_bits_tpu.ops import matrix
from chunky_bits_tpu.ops.backend import ErasureCoder, NumpyBackend


@pytest.fixture(scope="module")
def eight_devices():
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return jax.devices()


@pytest.mark.parametrize("dp,sp", [(8, 1), (4, 2), (2, 4), (1, 8)])
def test_sharded_apply_identity(eight_devices, dp, sp):
    from chunky_bits_tpu.parallel import make_mesh, sharded_apply

    d, p = 10, 4
    enc = matrix.build_encode_matrix(d, p)
    rng = np.random.default_rng(dp * 10 + sp)
    data = rng.integers(0, 256, (dp * 2, d, 128 * sp), dtype=np.uint8)
    mesh = make_mesh(8, dp=dp, sp=sp)
    got = np.asarray(sharded_apply(mesh, enc[d:], data))
    want = ErasureCoder(d, p, NumpyBackend()).encode_batch(data)
    assert np.array_equal(got, want)


def test_encode_step_with_collective(eight_devices):
    from chunky_bits_tpu.parallel import encode_step_sharded, make_mesh

    d, p = 3, 2
    enc = matrix.build_encode_matrix(d, p)
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (8, d, 512), dtype=np.uint8)
    mesh = make_mesh(8, dp=4, sp=2)
    parity, checksum = encode_step_sharded(mesh, enc, data)
    want = ErasureCoder(d, p, NumpyBackend()).encode_batch(data)
    assert np.array_equal(np.asarray(parity), want)
    assert int(checksum) == int(want.astype(np.uint64).sum() % (1 << 32))


def test_sharded_decode(eight_devices):
    """Reconstruction rows through the sharded path."""
    from chunky_bits_tpu.parallel import make_mesh, sharded_apply

    d, p = 10, 4
    coder = ErasureCoder(d, p, NumpyBackend())
    enc = coder.encode_matrix
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, (8, d, 256), dtype=np.uint8)
    parity = coder.encode_batch(data)
    full = np.concatenate([data, parity], axis=1)
    present = [1, 2, 3, 4, 5, 6, 7, 8, 9, 10]  # shard 0 and 11-13 lost
    wanted = [0]
    dec = matrix.decode_matrix(enc, present, wanted)
    mesh = make_mesh(8, dp=8, sp=1)
    picked = full[:, np.array(present[:d]), :]
    got = np.asarray(sharded_apply(mesh, dec, picked))
    assert np.array_equal(got[:, 0, :], data[:, 0, :])


@pytest.mark.parametrize("dp,tp", [(4, 2), (2, 4), (1, 8), (8, 1)])
def test_wide_stripe_encode(eight_devices, dp, tp):
    """BASELINE.md config 5: wide stripe d=16..20 with the contraction
    axis split over 'tp' and partial popcounts psum'd across chips."""
    from chunky_bits_tpu.parallel import encode_wide_sharded, \
        make_stripe_mesh

    d, p = 16, 6
    enc = matrix.build_encode_matrix(d, p)
    rng = np.random.default_rng(dp * 100 + tp)
    data = rng.integers(0, 256, (max(dp, 2), d, 384), dtype=np.uint8)
    mesh = make_stripe_mesh(8, dp=dp, tp=tp)
    got = np.asarray(encode_wide_sharded(mesh, enc, data))
    want = ErasureCoder(d, p, NumpyBackend()).encode_batch(data)
    assert np.array_equal(got, want)


def test_wide_stripe_d20_p6(eight_devices):
    """The exact BASELINE config-5 geometry (d=20 divisible by tp=4)."""
    from chunky_bits_tpu.parallel import encode_wide_sharded, \
        make_stripe_mesh

    d, p = 20, 6
    enc = matrix.build_encode_matrix(d, p)
    rng = np.random.default_rng(5)
    data = rng.integers(0, 256, (4, d, 256), dtype=np.uint8)
    mesh = make_stripe_mesh(8, dp=2, tp=4)
    got = np.asarray(encode_wide_sharded(mesh, enc, data))
    want = ErasureCoder(d, p, NumpyBackend()).encode_batch(data)
    assert np.array_equal(got, want)


def test_wide_stripe_decode(eight_devices):
    """Decode rows through the contraction-sharded path: reconstruct 4
    erased data shards of a d=20 stripe from 20 survivors."""
    from chunky_bits_tpu.parallel import make_stripe_mesh, \
        wide_apply_sharded

    d, p = 20, 6
    coder = ErasureCoder(d, p, NumpyBackend())
    enc = coder.encode_matrix
    rng = np.random.default_rng(6)
    data = rng.integers(0, 256, (4, d, 256), dtype=np.uint8)
    parity = coder.encode_batch(data)
    full = np.concatenate([data, parity], axis=1)
    erased = [0, 5, 11, 19]
    present = [i for i in range(d + p) if i not in erased][:d]
    dec = matrix.decode_matrix(enc, present, erased)
    mesh = make_stripe_mesh(8, dp=2, tp=4)
    got = np.asarray(
        wide_apply_sharded(mesh, dec, full[:, np.array(present), :]))
    assert np.array_equal(got, data[:, np.array(erased), :])


def test_wide_stripe_rejects_indivisible(eight_devices):
    from chunky_bits_tpu.parallel import make_stripe_mesh, \
        wide_apply_sharded

    d, p = 10, 4
    enc = matrix.build_encode_matrix(d, p)
    mesh = make_stripe_mesh(8, dp=2, tp=4)
    data = np.zeros((2, d, 128), dtype=np.uint8)
    with pytest.raises(ValueError):
        wide_apply_sharded(mesh, enc[d:], data)


def test_graft_entry():
    """The driver's entry points must keep working."""
    import sys

    sys.path.insert(0, "/root/repo")
    import __graft_entry__
    import jax

    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[1] == 4
    __graft_entry__.dryrun_multichip(len(jax.devices()))


def test_pallas_kernel_interpret_identity():
    """The fused pallas kernel, in interpret mode on CPU, must match the
    oracle byte-for-byte (the TPU path runs the same kernel compiled)."""
    from chunky_bits_tpu.ops.pallas_kernels import apply_matrix_pallas

    d, p = 10, 4
    enc = matrix.build_encode_matrix(d, p)
    rng = np.random.default_rng(2)
    data = rng.integers(0, 256, (2, d, 256), dtype=np.uint8)
    got = np.asarray(apply_matrix_pallas(enc[d:], data, interpret=True))
    want = ErasureCoder(d, p, NumpyBackend()).encode_batch(data)
    assert np.array_equal(got, want)
