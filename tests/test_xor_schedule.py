"""The scheduled-XOR erasure engine (ops/xor_schedule.py + cb_xor_exec).

Pins the engine's three contracts:

* **byte identity** — schedules executed by the numpy reference
  executor AND the native engine (at every forced kernel tier,
  including the pinned scalar fallback) produce exactly the table
  codec's bytes, for encode, decode-with-erasures, and the fused
  ingest path, flag on or off;
* **bounded schedule cache** — LRU by matrix digest, capacity
  respected, eviction observable;
* **program well-formedness** — every temp defined before use, every
  output seeded by a copy/zero, CSE never above the raw XOR count.
"""

import asyncio
import os
import subprocess
import sys

import numpy as np
import pytest

from chunky_bits_tpu.ops import matrix, xor_schedule
from chunky_bits_tpu.ops.backend import (ErasureCoder, NumpyBackend,
                                         register_backend)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _native(**kwargs):
    try:
        from chunky_bits_tpu.ops.cpu_backend import NativeBackend

        return NativeBackend(**kwargs)
    except Exception as err:  # pragma: no cover - no compiler in env
        pytest.skip(f"native backend unavailable: {err}")


@pytest.fixture
def force_impl():
    """Force the XOR engine's kernel tier for one test, restoring the
    detected best afterwards (the toggle is process-wide)."""
    from chunky_bits_tpu.ops import cpu_backend

    forced = []

    def force(level: int) -> int:
        eff = cpu_backend.xor_force_impl(level)
        forced.append(eff)
        return eff

    yield force
    cpu_backend.xor_force_impl(2)


# ---- schedule structure ----

def test_schedule_well_formed_and_cse_reduces():
    enc = matrix.build_encode_matrix(10, 4)
    sched = xor_schedule.build_schedule(enc[10:])
    assert sched.k == 10 and sched.r == 4
    n_in, out_base = 8 * sched.k, sched.out_base
    defined = set(range(n_in))
    seeded = set()
    for dst, src, kind in sched.ops.tolist():
        assert 0 <= dst < sched.n_planes
        if kind == xor_schedule.OP_ZERO:
            assert dst >= out_base
        else:
            assert src in defined, "use before def"
        if kind == xor_schedule.OP_XOR and dst >= out_base:
            assert dst in seeded, "output XOR before its seeding copy"
        if kind in (xor_schedule.OP_COPY, xor_schedule.OP_ZERO):
            seeded.add(dst)
        defined.add(dst)
    # every output plane is produced
    assert set(range(out_base, sched.n_planes)) <= seeded | defined
    # CSE strictly reduces plane ops vs the raw one-XOR-per-set-bit
    # program (8r of which become the seeding copies)
    assert len(sched.ops) < sched.raw_xors
    assert sched.n_xors < sched.raw_xors - 8 * sched.r


def test_identity_and_zero_rows_schedule():
    """Decode matrices contain identity rows (pass-through shards) and
    the builder must handle all-zero rows without emitting garbage."""
    mat = np.zeros((2, 3), dtype=np.uint8)
    mat[0, 1] = 1  # identity row: out0 = shard1
    sched = xor_schedule.build_schedule(mat)
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (2, 3, 64), dtype=np.uint8)
    out = xor_schedule.apply_numpy(sched, data)
    assert np.array_equal(out[:, 0], data[:, 1])
    assert not out[:, 1].any()


def test_planes_roundtrip():
    rng = np.random.default_rng(1)
    rows = rng.integers(0, 256, (5, 128), dtype=np.uint8)
    planes = xor_schedule.planes_of(rows)
    assert planes.shape == (40, 16)
    assert np.array_equal(xor_schedule.bytes_of(planes), rows)
    # convention anchor: plane v, byte t8, bit b = bit v of byte 8*t8+b
    one = np.zeros((1, 8), dtype=np.uint8)
    one[0, 3] = 1 << 5  # bit 5 of byte 3
    p = xor_schedule.planes_of(one)
    assert p[5, 0] == 1 << 3 and p.sum() == (1 << 3)


# ---- executor identity (numpy reference + native, all kernel tiers) ----

@pytest.mark.parametrize("seed", range(6))
def test_numpy_executor_matches_table_codec(seed):
    rng = np.random.default_rng(seed)
    d = int(rng.integers(1, 17))
    p = int(rng.integers(1, 9))
    size = int(rng.integers(1, 300)) * 8
    batch = int(rng.integers(1, 4))
    enc = matrix.build_encode_matrix(d, p)
    data = rng.integers(0, 256, (batch, d, size), dtype=np.uint8)
    want = NumpyBackend().apply_matrix(enc[d:], data)
    sched = xor_schedule.get_schedule(enc[d:])
    assert np.array_equal(xor_schedule.apply_numpy(sched, data), want)


@pytest.mark.parametrize("level", [0, 1, 2])
def test_native_engine_identity_per_tier(level, force_impl):
    """Encode AND decode byte identity at every kernel tier — level 0
    pins the scalar fallback (the forced-path discipline of the
    SHA-NI/GFNI fixes: the portable path is tested, not trusted)."""
    eff = force_impl(level)
    if eff != level:
        pytest.skip(f"tier {level} unavailable (clamped to {eff})")
    off = _native(xor_schedule=False)
    on = _native(xor_schedule=True)
    rng = np.random.default_rng(100 + level)
    for d, p, size, batch in ((3, 2, 64, 2), (10, 4, 1024, 2),
                              (1, 1, 8, 1), (16, 8, 1992, 1),
                              (20, 6, 8192, 1), (4, 4, 16, 3)):
        enc = matrix.build_encode_matrix(d, p)
        data = rng.integers(0, 256, (batch, d, size), dtype=np.uint8)
        want = off.apply_matrix(enc[d:], data)
        assert np.array_equal(on.apply_matrix(enc[d:], data), want), \
            (d, p, size)
        full = np.concatenate([data, want], axis=1)
        erased = rng.choice(d + p, size=p, replace=False)
        present = [i for i in range(d + p) if i not in erased]
        dec = matrix.decode_matrix(enc, present, sorted(erased))
        picked = np.ascontiguousarray(full[:, np.array(present[:d]), :])
        assert np.array_equal(on.apply_matrix(dec, picked),
                              off.apply_matrix(dec, picked)), (d, p, size)


def test_non_multiple_of_8_falls_back_to_table_path():
    on = _native(xor_schedule=True)
    off = _native(xor_schedule=False)
    rng = np.random.default_rng(7)
    enc = matrix.build_encode_matrix(3, 2)
    for size in (1, 7, 9, 1001):
        data = rng.integers(0, 256, (2, 3, size), dtype=np.uint8)
        assert np.array_equal(on.apply_matrix(enc[3:], data),
                              off.apply_matrix(enc[3:], data)), size


def test_encode_and_hash_into_identity_with_flag_on():
    """The fused ingest entry point — the shape the HostPipeline slices
    (nthreads=1 per stripe range) — must emit identical parity AND
    digests with the engine on."""
    off = _native(xor_schedule=False)
    on = _native(xor_schedule=True)
    rng = np.random.default_rng(8)
    for d, p, size, batch in ((3, 2, 4096, 4), (10, 4, 1 << 16, 2),
                              (2, 0, 512, 2)):
        enc = matrix.build_encode_matrix(d, p)
        data = rng.integers(0, 256, (batch, d, size), dtype=np.uint8)
        p1, h1 = off.encode_and_hash(enc[d:], data)
        p2, h2 = on.encode_and_hash(enc[d:], data)
        assert np.array_equal(p1, p2), (d, p, size)
        assert np.array_equal(h1, h2), (d, p, size)
        # and the sliced pipeline shape: caller-provided output rows
        par = np.zeros((batch, p, size), dtype=np.uint8)
        dig = np.zeros((batch, d + p, 32), dtype=np.uint8)
        on.encode_and_hash_into(enc[d:], data, par, dig, 1)
        assert np.array_equal(par, p1) and np.array_equal(dig, h1)


def test_host_pipeline_slicing_identity_with_flag_on():
    from chunky_bits_tpu.parallel.host_pipeline import HostPipeline

    on = _native(xor_schedule=True)
    coder = ErasureCoder(10, 4, on)
    rng = np.random.default_rng(9)
    stacked = rng.integers(0, 256, (8, 10, 4096), dtype=np.uint8)
    want_p, want_h = ErasureCoder(
        10, 4, _native(xor_schedule=False)).encode_hash_batch(stacked)
    pipe = HostPipeline(threads=3)
    try:
        got_p, got_h = pipe.encode_hash_sync(coder, stacked)
    finally:
        pipe.close()
    assert np.array_equal(got_p, want_p)
    assert np.array_equal(got_h, want_h)


def test_reconstruct_batcher_decode_path_with_flag_on():
    """The decode-plan route the read path, resilver and the
    RepairPlanner all share: ReconstructBatcher ->
    reconstruct_batch_picked -> NativeBackend.apply_matrix — schedules
    come out of the shared LRU keyed by the decode matrix digest."""
    from chunky_bits_tpu.ops.batching import ReconstructBatcher

    be = _native(xor_schedule=True)
    be.name = "native-xorsched-test"
    register_backend(be)
    rng = np.random.default_rng(10)
    d, p, size = 5, 3, 2048
    coder = ErasureCoder(d, p, NumpyBackend())
    data = rng.integers(0, 256, (1, d, size), dtype=np.uint8)
    full = np.concatenate([data, coder.encode_batch(data)], axis=1)

    async def run():
        batcher = ReconstructBatcher(backend="native-xorsched-test")
        erased = [1, 4, 6]
        arrays = [None if i in erased else full[0, i]
                  for i in range(d + p)]
        out = await batcher.reconstruct(d, p, arrays)
        await batcher.aclose()
        return out

    out = asyncio.run(run())
    for i in range(d + p):
        assert np.array_equal(out[i], full[0, i]), i


# ---- the bounded schedule LRU ----

def test_schedule_cache_bound_and_eviction():
    cache = xor_schedule.ScheduleCache(maxsize=3)
    rng = np.random.default_rng(11)
    mats = [rng.integers(1, 256, (2, 3), dtype=np.uint8)
            for _ in range(5)]
    scheds = [cache.get(m) for m in mats]
    assert len(cache) == 3
    info = cache.info()
    assert info["misses"] == 5 and info["evictions"] == 2
    # most-recent entries hit; the oldest was evicted and rebuilds
    assert cache.get(mats[-1]) is scheds[-1]
    assert cache.info()["hits"] == 1
    again = cache.get(mats[0])
    assert again is not scheds[0]
    assert np.array_equal(again.ops, scheds[0].ops)
    assert cache.info()["misses"] == 6


def test_schedule_cache_lru_order():
    cache = xor_schedule.ScheduleCache(maxsize=2)
    a = np.array([[1, 2]], dtype=np.uint8)
    b = np.array([[3, 4]], dtype=np.uint8)
    c = np.array([[5, 6]], dtype=np.uint8)
    sa = cache.get(a)
    cache.get(b)
    assert cache.get(a) is sa  # refresh a
    cache.get(c)               # evicts b, not a
    assert cache.get(a) is sa
    assert cache.info()["evictions"] == 1


def test_shared_cache_is_used_by_dispatch():
    on = _native(xor_schedule=True)
    rng = np.random.default_rng(12)
    mat = rng.integers(1, 256, (2, 4), dtype=np.uint8)
    data = rng.integers(0, 256, (1, 4, 64), dtype=np.uint8)
    before = xor_schedule.schedule_cache_info()
    on.apply_matrix(mat, data)
    on.apply_matrix(mat, data)
    after = xor_schedule.schedule_cache_info()
    assert after["misses"] == before["misses"] + 1
    assert after["hits"] >= before["hits"] + 1


# ---- flag plumbing ----

def test_tunables_accessor_parses_standard_flag_shapes(monkeypatch):
    from chunky_bits_tpu.cluster import tunables

    monkeypatch.delenv(tunables.XOR_SCHEDULE_ENV, raising=False)
    assert tunables.xor_schedule_enabled() is False
    for raw, want in (("1", True), ("on", True), ("0", False),
                      ("false", False), ("", False)):
        monkeypatch.setenv(tunables.XOR_SCHEDULE_ENV, raw)
        assert tunables.xor_schedule_enabled() is want, raw


def test_flag_read_at_first_dispatch(monkeypatch):
    from chunky_bits_tpu.cluster import tunables

    monkeypatch.setenv(tunables.XOR_SCHEDULE_ENV, "1")
    be = _native()
    assert be._xor is None  # not read at construction
    rng = np.random.default_rng(13)
    mat = rng.integers(1, 256, (1, 2), dtype=np.uint8)
    be.apply_matrix(mat, rng.integers(0, 256, (1, 2, 8), dtype=np.uint8))
    assert be._xor is True
    # baked: flipping the env after first dispatch changes nothing
    monkeypatch.setenv(tunables.XOR_SCHEDULE_ENV, "0")
    assert be._xor_enabled() is True


# ---- golden fixtures stay byte-identical with the flag on ----

def test_golden_fixtures_identical_with_flag_on():
    """End to end through the cluster write path in a fresh process
    with $CHUNKY_BITS_TPU_XOR_SCHEDULE=1: every golden fixture must
    reproduce byte-for-byte (content addresses pin the parity bytes),
    and the engine must actually have dispatched."""
    prog = (
        "import asyncio, os\n"
        "from tests.golden import generate as gen\n"
        "from chunky_bits_tpu.ops import xor_schedule\n"
        "refs = asyncio.run(gen.build_refs())\n"
        "for name, obj in refs.items():\n"
        "    with open(os.path.join(gen.GOLDEN_DIR, name + '.yaml')) as f:\n"
        "        assert gen.dump(obj) == f.read(), name\n"
        "info = xor_schedule.schedule_cache_info()\n"
        "assert info['misses'] > 0, 'xor engine never dispatched'\n"
        "print('golden ok', info['misses'])\n"
    )
    env = dict(os.environ, PYTHONPATH=REPO,
               CHUNKY_BITS_TPU_XOR_SCHEDULE="1",
               JAX_PLATFORMS="cpu")
    # the engine lives in the native backend: a fleet-wide backend
    # override (the CI mesh/jax matrix legs) would route every dispatch
    # around it and make the engine-dispatched assert vacuous
    env.pop("CHUNKY_BITS_TPU_BACKEND", None)
    r = subprocess.run([sys.executable, "-c", prog], cwd=REPO, env=env,
                       capture_output=True, timeout=300)
    assert r.returncode == 0, r.stderr.decode()[-800:]
    assert b"golden ok" in r.stdout


# ---- static verifier (compile-time byte-identity proof) ----

def test_verifier_rejects_corrupted_program():
    """Must-fail control: corrupt ONE op of a valid schedule and the
    symbolic GF(2) replay must refuse it — the proof actually checks
    the program, it is not a tautology over the builder's output."""
    from chunky_bits_tpu.errors import ErasureError

    mat = matrix.build_encode_matrix(4, 2)
    sched = xor_schedule.build_schedule(mat)  # verified on build
    xor_schedule.verify_schedule(sched, mat)  # and re-verifiable

    # flip one XOR's source plane to a different input plane
    bad_ops = np.array(sched.ops, copy=True)
    xors = np.nonzero(bad_ops[:, 2] == xor_schedule.OP_XOR)[0]
    assert len(xors), "encode schedule must contain XOR ops"
    i = int(xors[-1])
    bad_ops[i, 1] = (bad_ops[i, 1] + 1) % (8 * sched.k)
    bad = xor_schedule.XorSchedule(sched.k, sched.r, sched.n_temps,
                                   np.ascontiguousarray(bad_ops),
                                   sched.raw_xors, sched.digest)
    with pytest.raises(ErasureError, match="miscompile"):
        xor_schedule.verify_schedule(bad, mat)


def test_verifier_rejects_wrong_matrix():
    """A schedule verified against a DIFFERENT matrix must fail — the
    check ties the program to the exact bit expansion, so a cache
    serving a stale program for a new matrix cannot pass."""
    from chunky_bits_tpu.errors import ErasureError

    mat_a = matrix.build_encode_matrix(4, 2)
    mat_b = np.array(mat_a, copy=True)
    mat_b[0, 0] ^= 1
    sched = xor_schedule.build_schedule(mat_a)
    with pytest.raises(ErasureError, match="miscompile"):
        xor_schedule.verify_schedule(sched, mat_b)


def test_verifier_runs_on_every_build_before_caching():
    """build_schedule itself verifies (the always-on contract): a
    builder miscompilation can never escape into the ScheduleCache."""
    import unittest.mock as mock

    mat = matrix.build_encode_matrix(3, 2)
    with mock.patch.object(xor_schedule, "verify_schedule",
                           side_effect=AssertionError("called")) as v:
        with pytest.raises(AssertionError, match="called"):
            xor_schedule.build_schedule(mat)
    assert v.called
