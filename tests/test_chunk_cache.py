"""Content-addressed read cache + cluster-shared reconstruct pipeline.

The reference has no read-side caching (every GET re-fetches, re-verifies,
re-decodes; src/file/file_part.rs:73-135) so there is nothing to mirror —
these tests pin the TPU-repo extension's own contract: byte identity with
the cache on vs off (including reconstruct-from-erasure hits), singleflight
under concurrent readers, LRU eviction under a tiny byte budget, rejection
of corrupted pre-insert buffers, whole-chunk-only entries under ranged
gateway GETs, and the per-loop shared reconstruct batcher / FileReference
metadata cache on the cluster façade.
"""

import asyncio
import hashlib
import os
import random

import pytest

from chunky_bits_tpu.cluster import Cluster
from chunky_bits_tpu.cluster.tunables import CACHE_BYTES_ENV, Tunables
from chunky_bits_tpu.errors import SerdeError
from chunky_bits_tpu.file import (
    AnyHash,
    ChunkCache,
    FileReadBuilder,
    FileReference,
    LocationContext,
    new_profiler,
)
from chunky_bits_tpu.utils import aio

CHUNK_SIZE = 1 << 16


def synthetic_bytes(n: int, seed: int = 0) -> bytes:
    rng = random.Random(seed)
    return bytes(rng.getrandbits(8) for _ in range(n))


def make_cluster(tmp_path, cache_bytes: int = 0, backend=None) -> Cluster:
    dirs = []
    for i in range(5):
        d = tmp_path / f"disk{i}"
        d.mkdir(exist_ok=True)
        dirs.append(str(d))
    meta = tmp_path / "meta"
    meta.mkdir(exist_ok=True)
    tunables = {"cache_bytes": cache_bytes}
    if backend is not None:
        tunables["backend"] = backend
    return Cluster.from_obj({
        "destinations": [{"location": d} for d in dirs],
        "metadata": {"type": "path", "format": "yaml", "path": str(meta)},
        "profiles": {"default": {"data": 3, "parity": 2,
                                 "chunk_size": 16}},
        "tunables": tunables,
    })


async def read_all(cluster: Cluster, path: str) -> bytes:
    reader = await cluster.read_file(path)
    out = []
    while True:
        data = await reader.read(1 << 20)
        if not data:
            break
        out.append(data)
    return b"".join(out)


# ---- unit: the cache itself ----


def test_lru_eviction_under_byte_budget():
    cache = ChunkCache(100)
    bufs = {bytes([i]) * 32: bytes([i]) * 40 for i in range(3)}
    for digest, buf in bufs.items():
        assert cache._insert(digest, buf) is not None
    # 3 x 40 > 100: the first (LRU) entry was evicted
    assert cache.evictions == 1
    assert cache.size_bytes == 80
    assert len(cache) == 2
    digests = list(bufs)
    assert cache.get(digests[0]) is None
    # freshen #1, insert another: #2 (now LRU) is the one to go
    assert cache.get(digests[1]) == bufs[digests[1]]
    assert cache._insert(b"x" * 32, b"y" * 40) is not None
    assert cache.get(digests[2]) is None
    assert cache.get(digests[1]) is not None
    # an entry larger than the whole budget is refused outright
    assert cache._insert(b"z" * 32, b"w" * 101) is None
    assert cache.size_bytes <= 100


def test_oversize_budget_rejected():
    with pytest.raises(ValueError):
        ChunkCache(0)


def test_insert_verified_rejects_corruption():
    async def main():
        cache = ChunkCache(1 << 20)
        good = b"payload-bytes"
        hash_ = AnyHash.from_buf(good)
        # a corrupted buffer under a mismatching digest never enters
        assert not await cache.insert_verified(hash_, b"evil-bytes!!!")
        assert cache.rejects == 1
        assert len(cache) == 0
        # the genuine bytes do
        assert await cache.insert_verified(hash_, good)
        assert cache.get(hash_.value.digest) == good

    asyncio.run(main())


def test_singleflight_concurrent_readers():
    """N concurrent readers of one digest run ONE fetch; the losers are
    served the winner's verified buffer."""
    async def main():
        cache = ChunkCache(1 << 20)
        payload = b"c" * 1000
        digest = hashlib.sha256(payload).digest()
        fetches = {"n": 0}
        gate = asyncio.Event()

        async def fetch():
            fetches["n"] += 1
            await gate.wait()
            return payload

        tasks = [asyncio.ensure_future(cache.get_or_fetch(digest, fetch))
                 for _ in range(8)]
        await asyncio.sleep(0)  # all callers enqueue before the release
        gate.set()
        results = await asyncio.gather(*tasks)
        assert all(r == payload for r in results)
        assert fetches["n"] == 1
        assert cache.misses == 1
        assert cache.coalesced == 7
        # and the buffer is now cached
        assert cache.get(digest) == payload

    asyncio.run(main())


def test_singleflight_winner_death_does_not_doom_waiters():
    """A cancelled winner hands the flight over: a waiter retries,
    becomes the new winner, and completes the fetch."""
    async def main():
        cache = ChunkCache(1 << 20)
        payload = b"d" * 64
        digest = hashlib.sha256(payload).digest()
        started = asyncio.Event()

        async def hanging_fetch():
            started.set()
            await asyncio.Future()  # parked until cancelled

        async def good_fetch():
            return payload

        winner = asyncio.ensure_future(
            cache.get_or_fetch(digest, hanging_fetch))
        await started.wait()
        waiter = asyncio.ensure_future(
            cache.get_or_fetch(digest, good_fetch))
        await asyncio.sleep(0)
        winner.cancel()
        assert await waiter == payload
        with pytest.raises(asyncio.CancelledError):
            await winner

    asyncio.run(main())


def test_failed_fetch_propagates_none_to_waiters():
    """A fetch that finds no readable location resolves every waiter
    with None (chunk unreachable) — nobody re-fetches in a storm."""
    async def main():
        cache = ChunkCache(1 << 20)
        digest = b"q" * 32
        fetches = {"n": 0}
        gate = asyncio.Event()

        async def failing_fetch():
            fetches["n"] += 1
            await gate.wait()
            return None

        tasks = [asyncio.ensure_future(
            cache.get_or_fetch(digest, failing_fetch)) for _ in range(4)]
        await asyncio.sleep(0)
        gate.set()
        assert await asyncio.gather(*tasks) == [None] * 4
        assert fetches["n"] == 1
        assert len(cache) == 0

    asyncio.run(main())


# ---- conformance: byte identity with the cache in the loop ----


@pytest.mark.parametrize("backend", ["numpy", "native", "jax"])
def test_read_byte_identity_cache_on_vs_off(tmp_path, backend):
    """Cached, uncached, and reconstruct-from-erasure reads are all
    byte-identical across erasure backends — the cache can change
    timing, never bytes."""
    if backend == "native":
        from chunky_bits_tpu.errors import ErasureError
        from chunky_bits_tpu.ops.backend import get_backend

        try:
            get_backend("native")
        except ErasureError as err:
            pytest.skip(f"native backend unavailable: {err}")
    if backend == "jax":
        pytest.importorskip("jax")
    payload = synthetic_bytes(3 * CHUNK_SIZE + 12345, seed=31)

    async def main():
        cold = make_cluster(tmp_path, cache_bytes=0, backend=backend)
        profile = cold.get_profile(None)
        await cold.write_file("obj", aio.BytesReader(payload), profile)
        assert await read_all(cold, "obj") == payload

        hot = make_cluster(tmp_path, cache_bytes=64 << 20, backend=backend)
        assert await read_all(hot, "obj") == payload  # fill pass
        cache = hot._chunk_caches[asyncio.get_running_loop()]
        assert cache.misses > 0 and cache.inserts > 0
        hits_before = cache.hits
        assert await read_all(hot, "obj") == payload  # served hot
        assert cache.hits > hits_before

        # erase a data chunk: the cached read must still reconstruct
        # byte-identically, and the rebuilt row becomes a cache entry
        ref = await hot.get_file_ref("obj")
        victim = ref.parts[0].data[1]
        os.remove(victim.locations[0].target)
        degraded = make_cluster(tmp_path, cache_bytes=64 << 20,
                                backend=backend)
        assert await read_all(degraded, "obj") == payload
        dcache = degraded._chunk_caches[asyncio.get_running_loop()]
        assert dcache.get(victim.cache_key()) is not None
        # ...so the NEXT degraded read serves the lost chunk from cache
        hits = dcache.hits
        assert await read_all(degraded, "obj") == payload
        assert dcache.hits > hits
        for c in (cold, hot, degraded):
            await c.tunables.location_context().aclose()

    asyncio.run(main())


def test_cache_never_holds_trimmed_buffers(tmp_path):
    """Seek/take (range) reads fill the cache with WHOLE verified chunks
    only; the trim happens at the stream edge."""
    payload = synthetic_bytes(3 * CHUNK_SIZE + 5000, seed=5)

    async def main():
        cluster = make_cluster(tmp_path, cache_bytes=64 << 20)
        profile = cluster.get_profile(None)
        await cluster.write_file("obj", aio.BytesReader(payload), profile)
        ref = await cluster.get_file_ref("obj")
        builder = cluster.file_read_builder(ref)
        got = await builder.with_seek(100).with_take(1000).read_all()
        assert got == payload[100:1100]
        cache = cluster._chunk_caches[asyncio.get_running_loop()]
        sizes = {len(buf) for buf in cache._entries.values()}
        # every entry is a whole chunk of the first part, never a slice
        assert sizes == {ref.parts[0].chunksize}
        await cluster.tunables.location_context().aclose()

    asyncio.run(main())


# ---- cluster façade: shared batcher, metadata cache, profiler ----


def test_cluster_shared_reconstruct_batcher(tmp_path):
    """Concurrent degraded reads share the cluster's per-loop batcher
    (mirroring _encode_batcher) instead of one batcher per stream."""
    payload = synthetic_bytes(3 * CHUNK_SIZE, seed=11)

    async def main():
        cluster = make_cluster(tmp_path)
        profile = cluster.get_profile(None)
        for name in ("a", "b"):
            await cluster.write_file(name, aio.BytesReader(payload),
                                     profile)
            ref = await cluster.get_file_ref(name)
            os.remove(ref.parts[0].data[0].locations[0].target)
        loop = asyncio.get_running_loop()
        got = await asyncio.gather(read_all(cluster, "a"),
                                   read_all(cluster, "b"))
        assert got == [payload, payload]
        batcher = cluster._reconstruct_batchers.get(loop)
        assert batcher is not None and batcher.groups > 0
        # the same instance serves later reads on this loop
        await read_all(cluster, "a")
        assert cluster._reconstruct_batchers.get(loop) is batcher
        await cluster.tunables.location_context().aclose()

    asyncio.run(main())


def test_file_ref_metadata_cache_write_invalidation(tmp_path):
    """With the cache on, hot-object metadata parses once; a write-path
    invalidation makes the next GET see the new object immediately."""
    payload = synthetic_bytes(2000, seed=3)

    async def main():
        cluster = make_cluster(tmp_path, cache_bytes=1 << 20)
        profile = cluster.get_profile(None)
        await cluster.write_file("obj", aio.BytesReader(payload), profile)
        ref1 = await cluster.get_file_ref("obj")
        assert await cluster.get_file_ref("obj") is ref1  # cached parse
        new_payload = synthetic_bytes(3000, seed=4)
        await cluster.write_file("obj", aio.BytesReader(new_payload),
                                 profile)
        ref2 = await cluster.get_file_ref("obj")
        assert ref2 is not ref1
        assert await read_all(cluster, "obj") == new_payload

        # a get_file_ref in flight across the write must not re-install
        # the stale parse afterwards
        cluster._file_refs.clear()
        real_read = cluster.metadata.read
        release = asyncio.Event()

        async def slow_read(path):
            obj = await real_read(path)
            await release.wait()
            return obj

        cluster.metadata.read = slow_read
        try:
            stale = asyncio.ensure_future(cluster.get_file_ref("obj"))
            await asyncio.sleep(0.01)
            cluster.metadata.read = real_read
            await cluster.write_file_ref("obj", ref2)
            release.set()
            await stale
        finally:
            cluster.metadata.read = real_read
        assert "obj" not in cluster._file_refs

        # cache off: every call re-parses
        off = make_cluster(tmp_path)
        a = await off.get_file_ref("obj")
        b = await off.get_file_ref("obj")
        assert a is not b
        for c in (cluster, off):
            await c.tunables.location_context().aclose()

    asyncio.run(main())


def test_profiler_surfaces_cache_counters(tmp_path):
    """A fully hot read logs no I/O at all — the report carries the
    cache's own counters instead."""
    payload = synthetic_bytes(2 * CHUNK_SIZE, seed=9)

    async def main():
        cluster = make_cluster(tmp_path, cache_bytes=64 << 20)
        profile = cluster.get_profile(None)
        await cluster.write_file("obj", aio.BytesReader(payload), profile)
        await read_all(cluster, "obj")  # fill
        ref = await cluster.get_file_ref("obj")
        profiler, reporter = new_profiler()
        cx = cluster.tunables.location_context().but_with(
            profiler=profiler)
        builder = cluster.file_read_builder(ref).location_context(cx)
        assert await builder.read_all() == payload
        report = reporter.profile()
        assert report.cache_stats, "cache counters missing from report"
        stats = report.cache_stats[0]
        assert stats.hits > 0
        assert "Cache<" in str(report)
        await cluster.tunables.location_context().aclose()

    asyncio.run(main())


def test_tunables_cache_bytes_serde(monkeypatch):
    assert Tunables.from_obj(None).cache_bytes == 0
    assert Tunables.from_obj({}).cache_bytes == 0
    t = Tunables.from_obj({"cache_bytes": 1 << 20})
    assert t.cache_bytes == 1 << 20
    assert t.to_obj()["cache_bytes"] == 1 << 20
    assert "cache_bytes" not in Tunables.from_obj({}).to_obj()
    for bad in (-1, "lots", [1]):
        with pytest.raises(SerdeError):
            Tunables.from_obj({"cache_bytes": bad})
    # env default: enables without YAML, YAML wins, garbage reads as off
    monkeypatch.setenv(CACHE_BYTES_ENV, str(1 << 16))
    assert Tunables.from_obj({}).cache_bytes == 1 << 16
    assert Tunables.from_obj({"cache_bytes": 0}).cache_bytes == 0
    monkeypatch.setenv(CACHE_BYTES_ENV, "banana")
    assert Tunables.from_obj({}).cache_bytes == 0


def test_gateway_range_gets_through_cache(tmp_path):
    """Ranged GETs are served through the cache: whole chunks cached,
    trimmed at the edge, bytes identical, repeats hit."""
    payload = synthetic_bytes(3 * CHUNK_SIZE + 7777, seed=21)

    async def main():
        from aiohttp.test_utils import TestClient, TestServer

        from chunky_bits_tpu.gateway import make_app

        cluster = make_cluster(tmp_path, cache_bytes=64 << 20)
        app = make_app(cluster)
        async with TestClient(TestServer(app)) as client:
            assert (await client.put("/obj", data=payload)).status == 200
            ref = await cluster.get_file_ref("obj")
            chunksizes = {part.chunksize for part in ref.parts}
            # interleaved ranged + full GETs, twice each so the second
            # pass is served from the cache
            for _ in range(2):
                resp = await client.get(
                    "/obj", headers={"Range": "bytes=100-4099"})
                assert resp.status == 206
                assert await resp.read() == payload[100:4100]
                lo = 2 * CHUNK_SIZE - 100
                resp = await client.get(
                    "/obj", headers={"Range": f"bytes={lo}-"})
                assert resp.status == 206
                assert await resp.read() == payload[lo:]
                resp = await client.get("/obj")
                assert await resp.read() == payload
            cache = cluster._chunk_caches[asyncio.get_running_loop()]
            assert cache.hits > 0
            # every cached buffer is a whole chunk, never a trimmed range
            assert {len(b) for b in cache._entries.values()} <= chunksizes
        await cluster.tunables.location_context().aclose()

    asyncio.run(main())
