"""Seeded chaos soak: random op sequences against a live cluster.

The reference pins behavior with one scripted delete-and-resilver cycle
(tests/cluster.rs:145-231).  This drives a longer randomized sequence —
write, overwrite, read, corrupt, delete (bounded by p per part),
verify, resilver — asserting the system's core invariants after every
step:

* with at most p chunks damaged per part, reads stay byte-identical;
* resilver always returns an object to Valid and its content survives;
* listing reflects every object ever written.

The soaks run on the simulator's virtual-time loop (``sim.run``):
retry backoff, scrub intervals and convergence polling compress to
milliseconds of wall time, so they stay un-``slow``-marked in tier-1.
One real-clock soak remains as the ``slow``-marked canary
(``test_chaos_slow_location_hedged``) — it deliberately pays wall-clock
stalls so a regression in the REAL timer path can't hide behind the
virtual conversions.
"""

import asyncio
import os
import pathlib

import numpy as np
import pytest

from chunky_bits_tpu.cluster import Cluster
from chunky_bits_tpu.file import FileIntegrity
from chunky_bits_tpu.sim import run as sim_run
from chunky_bits_tpu.utils import aio


@pytest.mark.parametrize("seed", [1, 7])
def test_chaos_soak(tmp_path, seed):
    rng = np.random.default_rng(seed)
    root = tmp_path / f"s{seed}"
    dirs = []
    for i in range(6):
        d = root / f"disk{i}"
        d.mkdir(parents=True)
        dirs.append(str(d))
    meta = root / "meta"
    meta.mkdir()

    # built inside main(): every time-sensitive object (scoreboard,
    # retry backoff) must be born under the virtual clock sim.run
    # installs, not capture real timestamps before it
    cluster: Cluster = None  # type: ignore[assignment]

    contents: dict[str, bytes] = {}
    # chunks we have damaged since the last resilver, per object:
    # {name: set of (part_idx, chunk_idx)} — never exceeds p per part
    damaged: dict[str, set] = {}

    def chunk_path(part_obj, ci):
        chunks = part_obj["data"] + part_obj["parity"]
        t = chunks[ci]["locations"][0]
        return t[len("file://"):] if t.startswith("file://") else t

    async def read_meta(name):
        # through the store surface, not the raw path layout — the
        # meta-log CI leg rebuilds plain path stores fleet-wide
        return await cluster.metadata.read(name)

    async def op_write(name):
        size = int(rng.integers(1, 60000))
        payload = rng.integers(0, 256, size, dtype=np.uint8).tobytes()
        await cluster.write_file(name, aio.BytesReader(payload),
                                 cluster.get_profile())
        contents[name] = payload
        damaged[name] = set()

    async def op_read(name):
        got = await (await cluster.get_file_ref(name)) \
            .read_builder().read_all()
        assert got == contents[name], f"read mismatch for {name}"

    async def op_damage(name, corrupt):
        obj = await read_meta(name)
        part_idx = int(rng.integers(0, len(obj["parts"])))
        part_obj = obj["parts"][part_idx]
        n_chunks = len(part_obj["data"]) + len(part_obj["parity"])
        hurt_here = {c for (p_, c) in damaged[name] if p_ == part_idx}
        if len(hurt_here) >= 2:  # p == 2: stay reconstructible
            return
        choices = [c for c in range(n_chunks) if c not in hurt_here]
        ci = int(rng.choice(choices))
        path = chunk_path(part_obj, ci)
        if not os.path.exists(path):
            return  # shared content-addressed chunk already damaged
        if corrupt:
            raw = bytearray(pathlib.Path(path).read_bytes())
            raw[int(rng.integers(0, len(raw)))] ^= 0x01
            pathlib.Path(path).write_bytes(bytes(raw))
        else:
            os.remove(path)
        damaged[name].add((part_idx, ci))

    async def op_verify(name):
        report = await (await cluster.get_file_ref(name)).verify()
        if damaged[name]:
            assert report.integrity() != FileIntegrity.VALID, \
                f"damage to {name} not detected"
        else:
            assert report.integrity() == FileIntegrity.VALID

    async def op_resilver(name):
        ref = await cluster.get_file_ref(name)
        await ref.resilver(cluster.get_destination(cluster.get_profile()))
        await cluster.write_file_ref(name, ref)
        damaged[name] = set()
        report = await (await cluster.get_file_ref(name)).verify()
        assert report.integrity() == FileIntegrity.VALID
        await op_read(name)

    async def main():
        nonlocal cluster
        cluster = Cluster.from_obj({
            "destinations": [{"location": x} for x in dirs],
            "metadata": {"type": "path", "format": "yaml",
                         "path": str(meta)},
            "profiles": {"default": {"data": 3, "parity": 2,
                                     "chunk_size": 12}},
        })
        await op_write("obj0")
        for step in range(40):
            names = list(contents)
            name = names[int(rng.integers(0, len(names)))]
            op = rng.choice(
                ["write", "overwrite", "read", "corrupt", "delete",
                 "verify", "resilver"])
            if op == "write":
                await op_write(f"obj{len(contents)}")
            elif op == "overwrite":
                await op_write(name)
            elif op == "read":
                await op_read(name)
            elif op == "corrupt":
                await op_damage(name, corrupt=True)
                await op_read(name)
            elif op == "delete":
                await op_damage(name, corrupt=False)
                await op_read(name)
            elif op == "verify":
                await op_verify(name)
            elif op == "resilver":
                await op_resilver(name)
        # final sweep: repair everything, then everything is Valid
        for name in contents:
            await op_resilver(name)
        listed = await cluster.list_files("")
        listed_names = {str(x) for x in listed}
        for name in contents:
            assert any(name in x for x in listed_names), \
                f"{name} missing from listing {listed_names}"

    sim_run(main())


@pytest.mark.slow
def test_chaos_slow_location_hedged(tmp_path):
    """THE real-clock canary (slow-marked, excluded from tier-1):
    straggler chaos over real sockets with real stalls, asserting
    wall-clock hedge latency — the one soak that would catch a
    regression in the REAL timer path that the virtual-time
    conversions cannot see.

    Every chunk has two replicas and one node serves with a 500 ms
    stall.  A hedged read (`tunables.hedge_ms`) must complete near the
    FAST replica's latency — far under one stall — and bytes must be
    identical whichever location wins the race: slow-node-primary
    (replica wins), fast-primary (primary wins), and hedging-off (the
    stall is simply paid) must all agree."""
    import time

    from chunky_bits_tpu.file.location import Location
    from tests.http_node import FakeHttpNode

    rng = np.random.default_rng(11)
    meta = tmp_path / "meta"
    meta.mkdir()
    payload = rng.integers(0, 256, 150000, dtype=np.uint8).tobytes()

    async def main():
        nodes = [await FakeHttpNode().start() for _ in range(5)]
        try:
            def make_cluster(hedge_ms):
                return Cluster.from_obj({
                    "destinations": [{"location": n.url + "/"}
                                     for n in nodes],
                    "metadata": {"type": "path", "format": "yaml",
                                 "path": str(meta)},
                    "profiles": {"default": {"data": 3, "parity": 2,
                                             "chunk_size": 14}},
                    "tunables": {"hedge_ms": hedge_ms},
                })

            writer = make_cluster(0)
            await writer.write_file("obj", aio.BytesReader(payload),
                                    writer.get_profile())
            ref = await writer.get_file_ref("obj")
            # replicate every chunk onto a second node, never node 0:
            # node 0 is the one slow replica of the scenario
            pick = 1
            for part in ref.parts:
                for chunk in part.data + part.parity:
                    key = str(chunk.hash)
                    owner = next(n for n in nodes
                                 if str(chunk.locations[0])
                                 .startswith(n.url))
                    while nodes[pick] is owner or pick == 0:
                        pick = (pick + 1) % len(nodes)
                    nodes[pick].store[key] = owner.store[key]
                    chunk.locations.append(
                        Location.http(f"{nodes[pick].url}/{key}"))
                    pick = (pick + 1) % len(nodes)
            await writer.write_file_ref("obj", ref)

            async def read_all(cluster):
                r = await cluster.get_file_ref("obj")
                return await cluster.file_read_builder(r).read_all()

            # hedging OFF pays the stall but stays byte-identical
            nodes[0].get_delay = 0.5
            cold = make_cluster(0)
            t0 = time.monotonic()
            assert await read_all(cold) == payload
            off_elapsed = time.monotonic() - t0
            assert off_elapsed >= 0.5, \
                "expected the unhedged read to pay the stall"

            # hedging ON completes near the fast replica's latency:
            # every stalled primary is raced after ~25 ms
            hedged = make_cluster(25)
            t0 = time.monotonic()
            assert await read_all(hedged) == payload
            on_elapsed = time.monotonic() - t0
            assert on_elapsed < 0.5, (
                f"hedged read took {on_elapsed:.3f}s — it waited out "
                f"the 0.5s stall instead of racing the fast replica")
            # repeat reads ride the scoreboard's ordering (slow node
            # demoted) and stay identical
            assert await read_all(hedged) == payload

            # flip the slow side: now the REPLICA side added above is
            # never slow, node 0 is fast again and a different node
            # stalls — whichever location wins, bytes are identical
            nodes[0].get_delay = 0.0
            nodes[2].get_delay = 0.35
            flipped = make_cluster(25)
            assert await read_all(flipped) == payload
            stats = hedged.health_scoreboard().stats()
            assert stats.hedges_fired >= 1, \
                f"no hedges fired against a stalling node: {stats}"
            for cluster in (cold, hedged, flipped, writer):
                await cluster.tunables.location_context().aclose()
        finally:
            for n in nodes:
                await n.stop()

    asyncio.run(main())


def test_chaos_slow_location_hedged_virtual(tmp_path):
    """The straggler scenario in compressed virtual time (the tier-1
    face of the slow canary above): simulated nodes, one slowed by the
    fabric's fault state machine, durations measured on the virtual
    clock.  Hedging-off pays the straggler's latency; hedging-on
    completes near the fast replica's latency; bytes are identical
    either way."""
    from chunky_bits_tpu.file.location import Location
    from chunky_bits_tpu.sim import fabric as fabric_mod
    from chunky_bits_tpu.utils import clock as clock_mod

    rng = np.random.default_rng(11)
    meta = tmp_path / "meta"
    meta.mkdir()
    payload = rng.integers(0, 256, 150000, dtype=np.uint8).tobytes()

    async def main():
        fab = fabric_mod.SimFabric("hedge", 5, zones=("z",), seed=11)
        try:
            def make_cluster(hedge_ms):
                return Cluster.from_obj({
                    "destinations": fab.destination_objs(),
                    "metadata": {"type": "path", "format": "yaml",
                                 "path": str(meta)},
                    "profiles": {"default": {"data": 3, "parity": 2,
                                             "chunk_size": 14}},
                    "tunables": {"hedge_ms": hedge_ms},
                })

            writer = make_cluster(0)
            await writer.write_file("obj", aio.BytesReader(payload),
                                    writer.get_profile())
            ref = await writer.get_file_ref("obj")
            # replicate every chunk onto a second node, never n0000:
            # n0000 is the one slow replica of the scenario
            nodes = [fab.nodes[k] for k in sorted(fab.nodes)]
            pick = 1
            for part in ref.parts:
                for chunk in part.data + part.parity:
                    owner, key = fabric_mod.resolve(
                        chunk.locations[0].target)
                    while nodes[pick] is owner or pick == 0:
                        pick = (pick + 1) % len(nodes)
                    replica = nodes[pick]
                    replica.store[key] = owner.store[key]
                    chunk.locations.append(Location.sim(
                        f"{fab.fabric_id}/{replica.node_id}/{key}"))
                    pick = (pick + 1) % len(nodes)
            await writer.write_file_ref("obj", ref)

            async def read_all(cluster):
                r = await cluster.get_file_ref("obj")
                return await cluster.file_read_builder(r).read_all()

            # n0000 straggles: ~0.5 s of VIRTUAL latency per request
            # (median 2 ms x 250), the state machine's slow mode
            slow = fab.nodes["n0000"]
            slow.slow_factor = 250.0
            slow.set_state(fabric_mod.SLOW)

            # hedging OFF pays the straggler but stays byte-identical
            cold = make_cluster(0)
            t0 = clock_mod.monotonic()
            assert await read_all(cold) == payload
            off_elapsed = clock_mod.monotonic() - t0
            assert off_elapsed >= 0.2, (
                f"unhedged read took {off_elapsed:.3f}s virtual — "
                "never met the straggler?")

            # hedging ON completes near the fast replica's latency
            hedged = make_cluster(25)
            t0 = clock_mod.monotonic()
            assert await read_all(hedged) == payload
            on_elapsed = clock_mod.monotonic() - t0
            assert on_elapsed < 0.2, (
                f"hedged read took {on_elapsed:.3f}s virtual — it "
                "waited out the straggler instead of racing the "
                "fast replica")
            assert await read_all(hedged) == payload
            stats = hedged.health_scoreboard().stats()
            assert stats.hedges_fired >= 1, \
                f"no hedges fired against a straggler: {stats}"
            for cluster in (cold, hedged, writer):
                await cluster.tunables.location_context().aclose()
        finally:
            fab.close()

    sim_run(main())


def test_chaos_slab_store_churn(tmp_path):
    """The soak invariants over PACKED destinations (file/slab.py):
    random write/overwrite/read/corrupt/delete/verify/resilver churn
    with mid-churn compaction of every store.  Damage flips bytes
    inside live slab extents or marks them dead — never more than p
    per part — and reads must stay byte-identical throughout."""
    from chunky_bits_tpu.file import slab

    rng = np.random.default_rng(13)
    root = tmp_path / "slabs"
    dirs = []
    for i in range(6):
        d = root / f"disk{i}"
        d.mkdir(parents=True)
        dirs.append(str(d))
    meta = root / "meta"
    meta.mkdir()
    cluster = Cluster.from_obj({
        "destinations": [{"location": f"slab:{x}"} for x in dirs],
        "metadata": {"type": "path", "format": "yaml", "path": str(meta)},
        "profiles": {"default": {"data": 3, "parity": 2,
                                 "chunk_size": 12}},
    })

    contents: dict[str, bytes] = {}
    damaged: dict[str, set] = {}

    async def op_write(name):
        size = int(rng.integers(1, 50000))
        payload = rng.integers(0, 256, size, dtype=np.uint8).tobytes()
        await cluster.write_file(name, aio.BytesReader(payload),
                                 cluster.get_profile())
        contents[name] = payload
        damaged[name] = set()

    async def op_read(name):
        got = await cluster.file_read_builder(
            await cluster.get_file_ref(name)).read_all()
        assert got == contents[name], f"read mismatch for {name}"

    async def op_damage(name, corrupt):
        ref = await cluster.get_file_ref(name)
        pi = int(rng.integers(0, len(ref.parts)))
        part = ref.parts[pi]
        chunks = part.data + part.parity
        hurt = {c for (p_, c) in damaged[name] if p_ == pi}
        if len(hurt) >= 2:  # p == 2: stay reconstructible
            return
        ci = int(rng.choice(
            [c for c in range(len(chunks)) if c not in hurt]))
        location = chunks[ci].locations[0]
        ext = location.slab_extent()
        if ext is None:
            return  # shared content-addressed chunk already damaged
        path, off, ln = ext
        if corrupt:
            with open(path, "r+b") as f:
                at = off + int(rng.integers(0, ln))
                f.seek(at)
                byte = f.read(1)
                f.seek(at)
                f.write(bytes([byte[0] ^ 0x01]))
        else:
            await location.delete()
        damaged[name].add((pi, ci))

    async def op_resilver(name):
        ref = await cluster.get_file_ref(name)
        await ref.resilver(cluster.get_destination(cluster.get_profile()))
        await cluster.write_file_ref(name, ref)
        damaged[name] = set()
        report = await (await cluster.get_file_ref(name)).verify()
        assert report.integrity() == FileIntegrity.VALID
        await op_read(name)

    async def main():
        await op_write("obj0")
        for step in range(30):
            name = list(contents)[int(rng.integers(0, len(contents)))]
            op = rng.choice(["write", "overwrite", "read", "corrupt",
                             "delete", "resilver", "compact"])
            if op == "write":
                await op_write(f"obj{len(contents)}")
            elif op == "overwrite":
                await op_write(name)
            elif op == "read":
                await op_read(name)
            elif op == "corrupt":
                await op_damage(name, corrupt=True)
                await op_read(name)
            elif op == "delete":
                await op_damage(name, corrupt=False)
                await op_read(name)
            elif op == "resilver":
                await op_resilver(name)
            elif op == "compact":
                # mid-churn compaction must preserve every live extent
                # (dead ones are exactly the reclaimable set)
                for d in dirs:
                    await asyncio.to_thread(slab.get_store(d).compact)
                await op_read(name)
        for name in contents:
            await op_resilver(name)

    asyncio.run(main())


def test_chaos_scrub_daemon_under_concurrent_churn(tmp_path):
    """The scrub daemon runs (with rolling restarts) WHILE the cluster
    churns: concurrent writes, deletes, mid-write corruption, and
    resilver.  Afterwards every object reads byte-identical, a final
    scrub pass leaves everything Valid, and the daemon stops cleanly —
    under SANITIZE=1 the conftest additionally fails the session if
    any scrub task leaked.  Runs in virtual time: the daemon's
    interval sleeps and the convergence poll compress to nothing, so
    the soak can afford generous virtual deadlines."""
    from chunky_bits_tpu.cluster.scrub import ScrubDaemon

    rng = np.random.default_rng(17)
    root = tmp_path / "scrubbed"
    dirs = []
    for i in range(6):
        d = root / f"disk{i}"
        d.mkdir(parents=True)
        dirs.append(str(d))
    meta = root / "meta"
    meta.mkdir()
    cluster: Cluster = None  # type: ignore[assignment]
    contents: dict[str, bytes] = {}

    async def write(name):
        size = int(rng.integers(1, 30000))
        payload = rng.integers(0, 256, size, dtype=np.uint8).tobytes()
        await cluster.write_file(name, aio.BytesReader(payload),
                                 cluster.get_profile())
        contents[name] = payload

    async def corrupt_one(name):
        """Mid-churn corruption: flip a byte in one live extent (at
        most one damaged chunk per object between repairs — p=2 keeps
        it reconstructible even while the daemon races a resilver)."""
        ref = await cluster.get_file_ref(name)
        part = ref.parts[int(rng.integers(0, len(ref.parts)))]
        chunk = part.data[int(rng.integers(0, len(part.data)))]
        ext = chunk.locations[0].slab_extent()
        if ext is None:
            return
        path, off, ln = ext
        with open(path, "r+b") as f:
            at = off + int(rng.integers(0, ln))
            f.seek(at)
            byte = f.read(1)
            f.seek(at)
            f.write(bytes([byte[0] ^ 0x10]))

    async def main():
        nonlocal cluster
        cluster = Cluster.from_obj({
            "destinations": [{"location": f"slab:{x}"} for x in dirs],
            "metadata": {"type": "path", "format": "yaml",
                         "path": str(meta)},
            "profiles": {"default": {"data": 3, "parity": 2,
                                     "chunk_size": 12}},
        })
        daemon = ScrubDaemon(cluster, bytes_per_sec=50_000_000,
                             interval_seconds=0.01)
        daemon.start()
        try:
            await write("obj0")
            for step in range(14):
                name = list(contents)[
                    int(rng.integers(0, len(contents)))]
                op = rng.choice(["write", "read", "corrupt",
                                 "delete", "resilver", "restart"])
                if op == "write":
                    await write(f"obj{len(contents)}")
                elif op == "read":
                    got = await cluster.file_read_builder(
                        await cluster.get_file_ref(name)).read_all()
                    assert got == contents[name]
                elif op == "corrupt":
                    await corrupt_one(name)
                elif op == "delete":
                    ref = await cluster.get_file_ref(name)
                    part = ref.parts[0]
                    loc = part.parity[0].locations[0]
                    try:
                        await loc.delete()
                    except Exception:  # noqa: BLE001 — the daemon may
                        pass  # have repaired/deleted it concurrently
                elif op == "resilver":
                    ref = await cluster.get_file_ref(name)
                    await ref.resilver(cluster.get_destination(
                        cluster.get_profile()))
                    await cluster.write_file_ref(name, ref)
                elif op == "restart":
                    # rolling restart: stop AND await, then start anew
                    await daemon.stop()
                    daemon.start()
                await asyncio.sleep(0.005)
            # quiesce churn; let the daemon repair remaining damage
            deadline = asyncio.get_running_loop().time() + 30.0
            while True:
                ok = True
                for name in contents:
                    report = await (await cluster.get_file_ref(name)
                                    ).verify()
                    if report.integrity() != FileIntegrity.VALID:
                        ok = False
                if ok:
                    break
                assert asyncio.get_running_loop().time() < deadline, \
                    "scrub daemon never converged the namespace"
                await asyncio.sleep(0.05)
        finally:
            await daemon.stop()
        assert daemon.stats().passes >= 1
        for name, payload in contents.items():
            got = await cluster.file_read_builder(
                await cluster.get_file_ref(name)).read_all()
            assert got == payload, f"post-churn mismatch for {name}"

    sim_run(main())


def test_chaos_disk_full_on_one_slab_destination(tmp_path, monkeypatch):
    """One packed destination returns ENOSPC on every append: writes
    fail over to the surviving nodes (the writer invalidates the full
    node), reads stay byte-identical, and once space returns a
    resilver re-places onto the recovered node."""
    import errno

    from chunky_bits_tpu.file import slab

    rng = np.random.default_rng(19)
    root = tmp_path / "full"
    dirs = []
    for i in range(6):
        d = root / f"disk{i}"
        d.mkdir(parents=True)
        dirs.append(str(d))
    meta = root / "meta"
    meta.mkdir()
    cluster = Cluster.from_obj({
        "destinations": [{"location": f"slab:{x}"} for x in dirs],
        "metadata": {"type": "path", "format": "yaml", "path": str(meta)},
        "profiles": {"default": {"data": 3, "parity": 2,
                                 "chunk_size": 12}},
    })
    full_store = slab.get_store(dirs[0])

    def out_of_space(name, data):
        raise OSError(errno.ENOSPC, "No space left on device")

    monkeypatch.setattr(full_store, "append", out_of_space)
    payload = rng.integers(0, 256, 40000, dtype=np.uint8).tobytes()

    async def main():
        await cluster.write_file("obj", aio.BytesReader(payload),
                                 cluster.get_profile())
        ref = await cluster.get_file_ref("obj")
        # nothing landed on the full node
        for part in ref.parts:
            for chunk in part.data + part.parity:
                for location in chunk.locations:
                    assert not location.target.startswith(dirs[0]), \
                        f"chunk placed on the full node: {location}"
        got = await cluster.file_read_builder(ref).read_all()
        assert got == payload
        # space returns: the node takes writes again on resilver
        monkeypatch.undo()
        await ref.parts[0].data[0].locations[0].delete()
        report = await ref.resilver(
            cluster.get_destination(cluster.get_profile()))
        assert not report.failed_writes(), report.failed_writes()
        await cluster.write_file_ref("obj", ref)
        got = await cluster.file_read_builder(
            await cluster.get_file_ref("obj")).read_all()
        assert got == payload

    asyncio.run(main())


def test_chaos_soak_http_nodes(tmp_path):
    """The same invariants over in-process HTTP storage nodes: damage is
    dropped/corrupted in the node stores, repair re-places over HTTP."""
    from tests.http_node import FakeHttpNode

    rng = np.random.default_rng(3)
    meta = tmp_path / "meta"
    meta.mkdir()

    async def main():
        nodes = [await FakeHttpNode().start() for _ in range(6)]
        try:
            cluster = Cluster.from_obj({
                "destinations": [{"location": n.url + "/"} for n in nodes],
                "metadata": {"type": "path", "format": "yaml",
                             "path": str(meta)},
                "profiles": {"default": {"data": 3, "parity": 2,
                                         "chunk_size": 12}},
            })
            contents: dict[str, bytes] = {}
            damaged: dict[str, set] = {}

            def find_node(url: str):
                for n in nodes:
                    if url.startswith(n.url):
                        return n, url[len(n.url) + 1:]
                raise AssertionError(url)

            async def write(name):
                size = int(rng.integers(1, 40000))
                payload = rng.integers(0, 256, size,
                                       dtype=np.uint8).tobytes()
                await cluster.write_file(name, aio.BytesReader(payload),
                                         cluster.get_profile())
                contents[name] = payload
                damaged[name] = set()

            async def damage(name):
                ref = await cluster.get_file_ref(name)
                pi = int(rng.integers(0, len(ref.parts)))
                part = ref.parts[pi]
                chunks = part.data + part.parity
                hurt = {c for (p_, c) in damaged[name] if p_ == pi}
                if len(hurt) >= 2:
                    return
                ci = int(rng.choice(
                    [c for c in range(len(chunks)) if c not in hurt]))
                node, key = find_node(str(chunks[ci].locations[0]))
                if key not in node.store:
                    return
                if rng.random() < 0.5:
                    raw = bytearray(node.store[key])
                    raw[int(rng.integers(0, len(raw)))] ^= 1
                    node.store[key] = bytes(raw)
                else:
                    del node.store[key]
                damaged[name].add((pi, ci))

            async def repair(name):
                ref = await cluster.get_file_ref(name)
                await ref.resilver(
                    cluster.get_destination(cluster.get_profile()))
                await cluster.write_file_ref(name, ref)
                damaged[name] = set()
                report = await (await cluster.get_file_ref(name)).verify()
                assert report.integrity() == FileIntegrity.VALID

            await write("obj0")
            for _ in range(25):
                name = list(contents)[int(rng.integers(0, len(contents)))]
                op = rng.choice(["write", "read", "damage", "repair"])
                if op == "write":
                    await write(f"obj{len(contents)}")
                elif op == "read":
                    got = await (await cluster.get_file_ref(name)) \
                        .read_builder().read_all()
                    assert got == contents[name]
                elif op == "damage":
                    await damage(name)
                    got = await (await cluster.get_file_ref(name)) \
                        .read_builder().read_all()
                    assert got == contents[name]
                else:
                    await repair(name)
            for name in contents:
                await repair(name)
        finally:
            for n in nodes:
                await n.stop()

    asyncio.run(main())
