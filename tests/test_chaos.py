"""Seeded chaos soak: random op sequences against a live cluster.

The reference pins behavior with one scripted delete-and-resilver cycle
(tests/cluster.rs:145-231).  This drives a longer randomized sequence —
write, overwrite, read, corrupt, delete (bounded by p per part),
verify, resilver — asserting the system's core invariants after every
step:

* with at most p chunks damaged per part, reads stay byte-identical;
* resilver always returns an object to Valid and its content survives;
* listing reflects every object ever written.
"""

import asyncio
import os
import pathlib

import numpy as np
import pytest

from chunky_bits_tpu.cluster import Cluster
from chunky_bits_tpu.file import FileIntegrity
from chunky_bits_tpu.utils import aio


@pytest.mark.parametrize("seed", [1, 7])
def test_chaos_soak(tmp_path, seed):
    rng = np.random.default_rng(seed)
    root = tmp_path / f"s{seed}"
    dirs = []
    for i in range(6):
        d = root / f"disk{i}"
        d.mkdir(parents=True)
        dirs.append(str(d))
    meta = root / "meta"
    meta.mkdir()
    cluster = Cluster.from_obj({
        "destinations": [{"location": x} for x in dirs],
        "metadata": {"type": "path", "format": "yaml", "path": str(meta)},
        "profiles": {"default": {"data": 3, "parity": 2,
                                 "chunk_size": 12}},
    })

    contents: dict[str, bytes] = {}
    # chunks we have damaged since the last resilver, per object:
    # {name: set of (part_idx, chunk_idx)} — never exceeds p per part
    damaged: dict[str, set] = {}

    def chunk_path(part_obj, ci):
        chunks = part_obj["data"] + part_obj["parity"]
        t = chunks[ci]["locations"][0]
        return t[len("file://"):] if t.startswith("file://") else t

    async def read_meta(name):
        import yaml

        return yaml.safe_load((meta / name).read_text())

    async def op_write(name):
        size = int(rng.integers(1, 60000))
        payload = rng.integers(0, 256, size, dtype=np.uint8).tobytes()
        await cluster.write_file(name, aio.BytesReader(payload),
                                 cluster.get_profile())
        contents[name] = payload
        damaged[name] = set()

    async def op_read(name):
        got = await (await cluster.get_file_ref(name)) \
            .read_builder().read_all()
        assert got == contents[name], f"read mismatch for {name}"

    async def op_damage(name, corrupt):
        obj = await read_meta(name)
        part_idx = int(rng.integers(0, len(obj["parts"])))
        part_obj = obj["parts"][part_idx]
        n_chunks = len(part_obj["data"]) + len(part_obj["parity"])
        hurt_here = {c for (p_, c) in damaged[name] if p_ == part_idx}
        if len(hurt_here) >= 2:  # p == 2: stay reconstructible
            return
        choices = [c for c in range(n_chunks) if c not in hurt_here]
        ci = int(rng.choice(choices))
        path = chunk_path(part_obj, ci)
        if not os.path.exists(path):
            return  # shared content-addressed chunk already damaged
        if corrupt:
            raw = bytearray(pathlib.Path(path).read_bytes())
            raw[int(rng.integers(0, len(raw)))] ^= 0x01
            pathlib.Path(path).write_bytes(bytes(raw))
        else:
            os.remove(path)
        damaged[name].add((part_idx, ci))

    async def op_verify(name):
        report = await (await cluster.get_file_ref(name)).verify()
        if damaged[name]:
            assert report.integrity() != FileIntegrity.VALID, \
                f"damage to {name} not detected"
        else:
            assert report.integrity() == FileIntegrity.VALID

    async def op_resilver(name):
        ref = await cluster.get_file_ref(name)
        await ref.resilver(cluster.get_destination(cluster.get_profile()))
        await cluster.write_file_ref(name, ref)
        damaged[name] = set()
        report = await (await cluster.get_file_ref(name)).verify()
        assert report.integrity() == FileIntegrity.VALID
        await op_read(name)

    async def main():
        await op_write("obj0")
        for step in range(40):
            names = list(contents)
            name = names[int(rng.integers(0, len(names)))]
            op = rng.choice(
                ["write", "overwrite", "read", "corrupt", "delete",
                 "verify", "resilver"])
            if op == "write":
                await op_write(f"obj{len(contents)}")
            elif op == "overwrite":
                await op_write(name)
            elif op == "read":
                await op_read(name)
            elif op == "corrupt":
                await op_damage(name, corrupt=True)
                await op_read(name)
            elif op == "delete":
                await op_damage(name, corrupt=False)
                await op_read(name)
            elif op == "verify":
                await op_verify(name)
            elif op == "resilver":
                await op_resilver(name)
        # final sweep: repair everything, then everything is Valid
        for name in contents:
            await op_resilver(name)
        listed = await cluster.list_files("")
        listed_names = {str(x) for x in listed}
        for name in contents:
            assert any(name in x for x in listed_names), \
                f"{name} missing from listing {listed_names}"

    asyncio.run(main())


def test_chaos_slow_location_hedged(tmp_path):
    """Straggler chaos (stall, not fail): every chunk has two replicas
    and one node serves with a 500 ms stall.  A hedged read
    (`tunables.hedge_ms`) must complete near the FAST replica's
    latency — far under one stall — and bytes must be identical
    whichever location wins the race: slow-node-primary (replica wins),
    fast-primary (primary wins), and hedging-off (the stall is simply
    paid) must all agree."""
    import time

    from chunky_bits_tpu.file.location import Location
    from tests.http_node import FakeHttpNode

    rng = np.random.default_rng(11)
    meta = tmp_path / "meta"
    meta.mkdir()
    payload = rng.integers(0, 256, 150000, dtype=np.uint8).tobytes()

    async def main():
        nodes = [await FakeHttpNode().start() for _ in range(5)]
        try:
            def make_cluster(hedge_ms):
                return Cluster.from_obj({
                    "destinations": [{"location": n.url + "/"}
                                     for n in nodes],
                    "metadata": {"type": "path", "format": "yaml",
                                 "path": str(meta)},
                    "profiles": {"default": {"data": 3, "parity": 2,
                                             "chunk_size": 14}},
                    "tunables": {"hedge_ms": hedge_ms},
                })

            writer = make_cluster(0)
            await writer.write_file("obj", aio.BytesReader(payload),
                                    writer.get_profile())
            ref = await writer.get_file_ref("obj")
            # replicate every chunk onto a second node, never node 0:
            # node 0 is the one slow replica of the scenario
            pick = 1
            for part in ref.parts:
                for chunk in part.data + part.parity:
                    key = str(chunk.hash)
                    owner = next(n for n in nodes
                                 if str(chunk.locations[0])
                                 .startswith(n.url))
                    while nodes[pick] is owner or pick == 0:
                        pick = (pick + 1) % len(nodes)
                    nodes[pick].store[key] = owner.store[key]
                    chunk.locations.append(
                        Location.http(f"{nodes[pick].url}/{key}"))
                    pick = (pick + 1) % len(nodes)
            await writer.write_file_ref("obj", ref)

            async def read_all(cluster):
                r = await cluster.get_file_ref("obj")
                return await cluster.file_read_builder(r).read_all()

            # hedging OFF pays the stall but stays byte-identical
            nodes[0].get_delay = 0.5
            cold = make_cluster(0)
            t0 = time.monotonic()
            assert await read_all(cold) == payload
            off_elapsed = time.monotonic() - t0
            assert off_elapsed >= 0.5, \
                "expected the unhedged read to pay the stall"

            # hedging ON completes near the fast replica's latency:
            # every stalled primary is raced after ~25 ms
            hedged = make_cluster(25)
            t0 = time.monotonic()
            assert await read_all(hedged) == payload
            on_elapsed = time.monotonic() - t0
            assert on_elapsed < 0.5, (
                f"hedged read took {on_elapsed:.3f}s — it waited out "
                f"the 0.5s stall instead of racing the fast replica")
            # repeat reads ride the scoreboard's ordering (slow node
            # demoted) and stay identical
            assert await read_all(hedged) == payload

            # flip the slow side: now the REPLICA side added above is
            # never slow, node 0 is fast again and a different node
            # stalls — whichever location wins, bytes are identical
            nodes[0].get_delay = 0.0
            nodes[2].get_delay = 0.35
            flipped = make_cluster(25)
            assert await read_all(flipped) == payload
            stats = hedged.health_scoreboard().stats()
            assert stats.hedges_fired >= 1, \
                f"no hedges fired against a stalling node: {stats}"
            for cluster in (cold, hedged, flipped, writer):
                await cluster.tunables.location_context().aclose()
        finally:
            for n in nodes:
                await n.stop()

    asyncio.run(main())


def test_chaos_soak_http_nodes(tmp_path):
    """The same invariants over in-process HTTP storage nodes: damage is
    dropped/corrupted in the node stores, repair re-places over HTTP."""
    from tests.http_node import FakeHttpNode

    rng = np.random.default_rng(3)
    meta = tmp_path / "meta"
    meta.mkdir()

    async def main():
        nodes = [await FakeHttpNode().start() for _ in range(6)]
        try:
            cluster = Cluster.from_obj({
                "destinations": [{"location": n.url + "/"} for n in nodes],
                "metadata": {"type": "path", "format": "yaml",
                             "path": str(meta)},
                "profiles": {"default": {"data": 3, "parity": 2,
                                         "chunk_size": 12}},
            })
            contents: dict[str, bytes] = {}
            damaged: dict[str, set] = {}

            def find_node(url: str):
                for n in nodes:
                    if url.startswith(n.url):
                        return n, url[len(n.url) + 1:]
                raise AssertionError(url)

            async def write(name):
                size = int(rng.integers(1, 40000))
                payload = rng.integers(0, 256, size,
                                       dtype=np.uint8).tobytes()
                await cluster.write_file(name, aio.BytesReader(payload),
                                         cluster.get_profile())
                contents[name] = payload
                damaged[name] = set()

            async def damage(name):
                ref = await cluster.get_file_ref(name)
                pi = int(rng.integers(0, len(ref.parts)))
                part = ref.parts[pi]
                chunks = part.data + part.parity
                hurt = {c for (p_, c) in damaged[name] if p_ == pi}
                if len(hurt) >= 2:
                    return
                ci = int(rng.choice(
                    [c for c in range(len(chunks)) if c not in hurt]))
                node, key = find_node(str(chunks[ci].locations[0]))
                if key not in node.store:
                    return
                if rng.random() < 0.5:
                    raw = bytearray(node.store[key])
                    raw[int(rng.integers(0, len(raw)))] ^= 1
                    node.store[key] = bytes(raw)
                else:
                    del node.store[key]
                damaged[name].add((pi, ci))

            async def repair(name):
                ref = await cluster.get_file_ref(name)
                await ref.resilver(
                    cluster.get_destination(cluster.get_profile()))
                await cluster.write_file_ref(name, ref)
                damaged[name] = set()
                report = await (await cluster.get_file_ref(name)).verify()
                assert report.integrity() == FileIntegrity.VALID

            await write("obj0")
            for _ in range(25):
                name = list(contents)[int(rng.integers(0, len(contents)))]
                op = rng.choice(["write", "read", "damage", "repair"])
                if op == "write":
                    await write(f"obj{len(contents)}")
                elif op == "read":
                    got = await (await cluster.get_file_ref(name)) \
                        .read_builder().read_all()
                    assert got == contents[name]
                elif op == "damage":
                    await damage(name)
                    got = await (await cluster.get_file_ref(name)) \
                        .read_builder().read_all()
                    assert got == contents[name]
                else:
                    await repair(name)
            for name in contents:
                await repair(name)
        finally:
            for n in nodes:
                await n.stop()

    asyncio.run(main())
