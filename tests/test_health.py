"""Location-health scoreboard, breaker, hedge budget, and the wiring.

Unit-level pins for cluster/health.py (EWMA math, breaker transitions,
token-bucket exhaustion, ordering) plus the integration seams the
tentpole added: tunables serde for hedge_ms/read_retries, health-aware
writer placement, transient-HTTP retries on both planes, and the
profiler's per-location failure trail (a degraded cluster must be
diagnosable).  The end-to-end hedged-read race lives in
tests/test_chaos.py::test_chaos_slow_location_hedged; bench --config 8
is the measured A/B.
"""

import asyncio
import threading

import numpy as np
import pytest

from chunky_bits_tpu.cluster.health import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    HealthScoreboard,
    location_key,
)
from chunky_bits_tpu.errors import (
    HttpStatusError,
    LocationError,
    ShardError,
    is_transient_error,
)
from chunky_bits_tpu.file.location import Location


class Clock:
    """Deterministic injectable monotonic clock."""

    def __init__(self) -> None:
        self.now = 1000.0

    def __call__(self) -> float:
        return self.now


def loc(url: str) -> Location:
    return Location.parse(url)


# ---- identity ----

def test_location_key_groups_by_node():
    a = loc("http://10.0.0.1:8080/chunks/sha256-aa")
    b = loc("http://10.0.0.1:8080/chunks/sha256-bb")
    c = loc("http://10.0.0.2:8080/chunks/sha256-aa")
    assert location_key(a) == location_key(b)
    assert location_key(a) != location_key(c)
    d1 = loc("/disk0/sha256-aa")
    d2 = loc("/disk0/sha256-bb")
    d3 = loc("/disk1/sha256-aa")
    assert location_key(d1) == location_key(d2)
    assert location_key(d1) != location_key(d3)


# ---- EWMA / ordering ----

def test_ewma_latency_and_order():
    sb = HealthScoreboard()
    fast, slow = loc("http://fast:1/x"), loc("http://slow:1/x")
    for _ in range(10):
        sb.record(fast, True, 0.002)
        sb.record(slow, True, 0.200)
    ranked = sb.order([slow, fast])
    assert ranked == [fast, slow]
    rows = {r.key[1]: r for r in sb.stats().locations}
    assert rows["fast:1"].ewma_ms == pytest.approx(2.0, rel=0.2)
    assert rows["slow:1"].ewma_ms == pytest.approx(200.0, rel=0.3)


def test_order_is_stable_for_unknown_locations():
    """A fresh scoreboard must reproduce metadata order exactly — the
    hedging-off default walk is pinned byte-for-byte to the pre-PR
    (and reference, file_part.rs:83-101) behavior."""
    sb = HealthScoreboard()
    locs = [loc(f"http://n{i}:1/x") for i in range(6)]
    assert sb.order(locs) == locs


def test_error_rate_ranks_failing_node_last():
    sb = HealthScoreboard()
    ok, bad = loc("http://ok:1/x"), loc("http://bad:1/x")
    sb.record(ok, True, 0.01)
    for _ in range(3):
        sb.record(bad, False, 0.01)
    assert sb.order([bad, ok]) == [ok, bad]


# ---- breaker ----

def test_breaker_closed_open_halfopen_cycle():
    clock = Clock()
    sb = HealthScoreboard(clock=clock)
    node = loc("http://flaky:1/x")
    assert sb.breaker_state(node) == CLOSED
    for _ in range(sb.BREAKER_FAILURES - 1):
        sb.record(node, False)
    assert sb.breaker_state(node) == CLOSED  # one short of the trip
    sb.record(node, False)
    assert sb.breaker_state(node) == OPEN
    assert sb.degraded(node)
    # cooldown elapses -> half-open (one probe allowed)
    clock.now += sb.BREAKER_COOLDOWN + 0.1
    assert sb.breaker_state(node) == HALF_OPEN
    # a half-open failure re-opens immediately (no 5-strike grace)
    sb.record(node, False)
    assert sb.breaker_state(node) == OPEN
    clock.now += sb.BREAKER_COOLDOWN + 0.1
    assert sb.breaker_state(node) == HALF_OPEN
    # a successful probe closes
    sb.record(node, True, 0.01)
    assert sb.breaker_state(node) == CLOSED


def test_open_breaker_orders_last_but_stays_usable():
    clock = Clock()
    sb = HealthScoreboard(clock=clock)
    dead, fine = loc("http://dead:1/x"), loc("http://fine:1/x")
    for _ in range(sb.BREAKER_FAILURES):
        sb.record(dead, False)
    # dead first in metadata order, but ranked last — never dropped
    ranked = sb.order([dead, fine])
    assert ranked == [fine, dead]
    assert len(ranked) == 2


# ---- hedge budget ----

def test_hedge_budget_exhaustion_and_accrual():
    sb = HealthScoreboard(hedge_ms=10.0)
    assert sb.hedge_enabled
    # the bucket starts at the burst cap
    burst = int(sb._hedge_burst)
    for _ in range(burst):
        assert sb.try_fire_hedge()
    assert not sb.try_fire_hedge(), "budget should be exhausted"
    assert sb.hedges_fired == burst
    # accrual: 1/hedge_ratio primaries buy exactly one token
    for _ in range(int(1 / 0.05) - 1):
        sb.note_primary()
        assert not sb.try_fire_hedge()
    sb.note_primary()
    assert sb.try_fire_hedge()


def test_hedge_delay_clamps_to_floor_and_ceiling():
    sb = HealthScoreboard(hedge_ms=10.0)
    # no samples: the floor
    assert sb.hedge_delay() == pytest.approx(0.010)
    # tiny latencies: still the floor
    for _ in range(50):
        sb.record(loc("http://a:1/x"), True, 0.0001)
    assert sb.hedge_delay() == pytest.approx(0.010)
    # huge latencies: the ceiling (20x floor)
    for _ in range(200):
        sb.record(loc("http://a:1/x"), True, 5.0)
    assert sb.hedge_delay() == pytest.approx(0.200)
    # mid-range latencies: tracks the p95
    sb2 = HealthScoreboard(hedge_ms=10.0)
    for _ in range(100):
        sb2.record(loc("http://a:1/x"), True, 0.050)
    assert sb2.hedge_delay() == pytest.approx(0.050, rel=0.05)


def test_hedging_disabled_by_default():
    sb = HealthScoreboard()
    assert not sb.hedge_enabled


def test_latency_floor_learns_without_verdict():
    """A cancelled hedge loser's lower-bound sample must move the EWMA
    (so ordering demotes the straggler) but neither count as success
    nor failure — in particular it must NOT close an open breaker."""
    clock = Clock()
    sb = HealthScoreboard(clock=clock)
    slow, fast = loc("http://slow:1/x"), loc("http://fast:1/x")
    sb.record(fast, True, 0.002)
    sb.record_latency_floor(slow, 0.050)
    assert sb.order([slow, fast]) == [fast, slow]
    row = {r.key[1]: r for r in sb.stats().locations}["slow:1"]
    assert row.err_rate == pytest.approx(0.0)
    # an open breaker stays open through a floor sample
    for _ in range(sb.BREAKER_FAILURES):
        sb.record(slow, False)
    assert sb.breaker_state(slow) == OPEN
    sb.record_latency_floor(slow, 0.100)
    assert sb.breaker_state(slow) == OPEN


def test_inflight_pairing_and_cancel_verdict():
    sb = HealthScoreboard()
    node = loc("http://n:1/x")
    sb.begin(node)
    sb.begin(node)
    assert sb.stats().locations[0].inflight == 2
    sb.finish(node, True, 0.01)
    # ok=None (cancelled racer): in-flight closes, no err/latency sample
    sb.finish(node, None, None)
    row = sb.stats().locations[0]
    assert row.inflight == 0
    assert row.err_rate == pytest.approx(0.0)
    assert row.completions == 1


def test_scoreboard_is_thread_safe():
    """Completions arrive from loop callbacks AND pipeline worker
    threads; hammer from several threads and check totals."""
    sb = HealthScoreboard(hedge_ms=5.0)
    node = loc("http://n:1/x")
    n_threads, per = 4, 500

    def work():
        for i in range(per):
            sb.begin(node)
            sb.finish(node, i % 10 != 0, 0.001)
            sb.note_primary()

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    row = sb.stats().locations[0]
    assert row.completions == n_threads * per
    assert row.inflight == 0


# ---- transient classification ----

def test_transient_error_classification():
    assert is_transient_error(HttpStatusError(503, "http://n/x"))
    assert is_transient_error(HttpStatusError(429, "http://n/x"))
    assert not is_transient_error(HttpStatusError(404, "http://n/x"))
    assert not is_transient_error(HttpStatusError(507, "http://n/x")), \
        "a full disk is deterministic, not transient"
    assert not is_transient_error(LocationError("connection refused"))
    # ShardError wrapping a transient cause (the write plane's shape)
    err = ShardError("write failed")
    err.__cause__ = HttpStatusError(500, "http://n/x")
    assert is_transient_error(err)


# ---- tunables serde ----

def test_tunables_hedge_and_retry_serde(monkeypatch):
    from chunky_bits_tpu.cluster.tunables import Tunables

    # the CI hedge leg exports these globally; the serde defaults under
    # test are the no-env ones
    monkeypatch.delenv("CHUNKY_BITS_TPU_HEDGE_MS", raising=False)
    monkeypatch.delenv("CHUNKY_BITS_TPU_READ_RETRIES", raising=False)
    t = Tunables.from_obj({"hedge_ms": 15, "read_retries": 2})
    assert t.hedge_ms == 15.0
    assert t.read_retries == 2
    obj = t.to_obj()
    assert obj["hedge_ms"] == 15.0
    assert obj["read_retries"] == 2
    t2 = Tunables.from_obj(obj)
    assert (t2.hedge_ms, t2.read_retries) == (15.0, 2)
    # defaults: hedging off, one retry — and neither serialized
    t3 = Tunables.from_obj({})
    assert t3.hedge_ms == 0.0
    assert t3.read_retries == 1
    assert "hedge_ms" not in t3.to_obj()
    assert "read_retries" not in t3.to_obj()
    # context carries the retry bound to both planes
    assert t.location_context().read_retries == 2
    from chunky_bits_tpu.errors import SerdeError

    with pytest.raises(SerdeError):
        Tunables.from_obj({"hedge_ms": -1})
    with pytest.raises(SerdeError):
        Tunables.from_obj({"read_retries": "many"})


def test_tunables_env_defaults(monkeypatch):
    from chunky_bits_tpu.cluster import tunables

    monkeypatch.setenv("CHUNKY_BITS_TPU_HEDGE_MS", "12.5")
    monkeypatch.setenv("CHUNKY_BITS_TPU_READ_RETRIES", "3")
    t = tunables.Tunables.from_obj({})
    assert t.hedge_ms == 12.5
    assert t.read_retries == 3
    # YAML wins over env
    t2 = tunables.Tunables.from_obj({"hedge_ms": 0, "read_retries": 0})
    assert t2.hedge_ms == 0.0
    assert t2.read_retries == 0
    # malformed env values are lenient (perf knobs can't crash startup)
    monkeypatch.setenv("CHUNKY_BITS_TPU_HEDGE_MS", "fast")
    monkeypatch.setenv("CHUNKY_BITS_TPU_READ_RETRIES", "-2")
    assert tunables.hedge_ms() == 0.0
    assert tunables.read_retries() == 1
    monkeypatch.setenv("CHUNKY_BITS_TPU_STAGGER_SECONDS", "0.02")
    assert tunables.stagger_seconds() == 0.02
    monkeypatch.setenv("CHUNKY_BITS_TPU_STAGGER_SECONDS", "soon")
    assert tunables.stagger_seconds() == 0.1


# ---- health-aware writes ----

def test_next_writer_deprioritizes_open_breaker(tmp_path):
    """With node 0's breaker open, placement prefers the healthy nodes
    BEFORE node 0 hard-fails a write; with all nodes healthy the
    hash-seeded draw stays byte-identical to the reference's."""
    from chunky_bits_tpu.cluster.nodes import ClusterNodes
    from chunky_bits_tpu.cluster.profile import ClusterProfile
    from chunky_bits_tpu.cluster.destination import _WriterState
    from chunky_bits_tpu.file.hashing import AnyHash
    from chunky_bits_tpu.file.location import LocationContext

    dirs = []
    for i in range(4):
        d = tmp_path / f"disk{i}"
        d.mkdir()
        dirs.append(str(d))
    nodes = ClusterNodes.from_obj([{"location": x} for x in dirs])
    profile = ClusterProfile.from_obj({"data": 1, "parity": 0})
    hash_ = AnyHash.from_buf(b"seed")

    async def draw(health):
        cx = LocationContext()
        cx.health = health
        state = _WriterState(nodes, profile, cx)
        picked = set()
        for _ in range(4):
            index, _node = await state.next_writer(hash_)
            picked.add(index)
        return picked

    async def main():
        baseline = await draw(None)
        assert baseline == {0, 1, 2, 3}  # all slots drain eventually

        sb = HealthScoreboard()
        bad = Location.local(str(tmp_path / "disk0" / "chunk"))
        for _ in range(sb.BREAKER_FAILURES):
            sb.record(bad, False)
        assert sb.degraded(bad)
        # first three draws avoid the degraded node entirely...
        cx = LocationContext()
        cx.health = sb
        state = _WriterState(nodes, profile, cx)
        first_three = {(await state.next_writer(hash_))[0]
                       for _ in range(3)}
        assert 0 not in first_three
        # ...but it remains the last resort, not a hard failure
        index, _node = await state.next_writer(hash_)
        assert index == 0

    asyncio.run(main())


def test_write_shard_retries_transient_http(tmp_path):
    """A 503 on PUT gets one jittered retry against the SAME node
    before invalidation (tunables.read_retries); a 507 (full disk)
    stays an immediate invalidate+failover, pinning the pre-PR
    failover behavior."""
    from chunky_bits_tpu.cluster import Cluster
    from chunky_bits_tpu.utils import aio
    from tests.http_node import FakeHttpNode

    rng = np.random.default_rng(5)
    payload = rng.integers(0, 256, 30000, dtype=np.uint8).tobytes()
    meta = tmp_path / "meta"
    meta.mkdir()

    async def main():
        flaky = await FakeHttpNode().start()
        steady = [await FakeHttpNode().start() for _ in range(5)]
        try:
            flaky.put_fail_status = 503
            flaky.put_fail_remaining = 10**6  # every PUT 503s, for now
            cluster = Cluster.from_obj({
                "destinations": [{"location": n.url + "/"}
                                 for n in [flaky] + steady],
                "metadata": {"type": "path", "format": "yaml",
                             "path": str(meta)},
                "profiles": {"default": {"data": 3, "parity": 2,
                                         "chunk_size": 13}},
            })
            await cluster.write_file("obj", aio.BytesReader(payload),
                                     cluster.get_profile())
            got = await (await cluster.get_file_ref("obj")) \
                .read_builder().read_all()
            assert got == payload
            # the flaky node was retried at least once before failover:
            # >= 2 attempts for the one shard routed to it (stagger
            # serializes the first draws, so exactly one shard hits it)
            assert flaky.put_attempts >= 2, flaky.put_attempts
            await cluster.tunables.location_context().aclose()
        finally:
            await flaky.stop()
            for n in steady:
                await n.stop()

    asyncio.run(main())


def test_transient_put_succeeds_on_retry(tmp_path):
    """One 503 then service: the shard lands on the SAME node via the
    retry, no failover draw at all."""
    from chunky_bits_tpu.file.hashing import AnyHash
    from chunky_bits_tpu.cluster.nodes import ClusterNodes
    from chunky_bits_tpu.cluster.profile import ClusterProfile
    from chunky_bits_tpu.cluster.destination import (
        ClusterWriter,
        _WriterState,
    )
    from chunky_bits_tpu.file.location import LocationContext
    from tests.http_node import FakeHttpNode

    async def main():
        node = await FakeHttpNode().start()
        try:
            node.put_fail_status = 503
            node.put_fail_remaining = 1
            nodes = ClusterNodes.from_obj([{"location": node.url + "/"}])
            state = _WriterState(
                nodes, ClusterProfile.from_obj({"data": 1, "parity": 0}),
                LocationContext())
            writer = ClusterWriter(state, None, None)
            hash_ = AnyHash.from_buf(b"payload")
            locations = await writer.write_shard(hash_, b"payload")
            assert len(locations) == 1
            assert node.put_attempts == 2  # the 503, then the retry
            assert str(hash_) in node.store
        finally:
            await node.stop()

    asyncio.run(main())


# ---- diagnosability (the anonymous-swallow satellite) ----

def test_profiler_records_which_location_failed(tmp_path):
    """fetch_chunk used to swallow every LocationError anonymously;
    the profiler now carries (location, why) for each failed or
    corrupt location even though the read itself recovered."""
    from chunky_bits_tpu.file.chunk import Chunk
    from chunky_bits_tpu.file.file_part import FilePart
    from chunky_bits_tpu.file.hashing import AnyHash
    from chunky_bits_tpu.file.location import LocationContext
    from chunky_bits_tpu.file.profiler import new_profiler

    rng = np.random.default_rng(9)
    data = rng.integers(0, 256, 4096, dtype=np.uint8).tobytes()
    shard = data[:2048], data[2048:]
    chunks = []
    for i, payload in enumerate(shard):
        good = tmp_path / f"chunk{i}"
        good.write_bytes(payload)
        missing = str(tmp_path / "gone" / f"chunk{i}")
        chunks.append(Chunk(
            hash=AnyHash.from_buf(payload),
            # first location unreadable -> must be reported, not
            # silently skipped
            locations=[Location.local(missing),
                       Location.local(str(good))]))
    part = FilePart(chunksize=2048, data=chunks, parity=[])

    async def main():
        profiler, reporter = new_profiler()
        cx = LocationContext(profiler=profiler)
        got = await part.read(cx)
        assert got == data
        report = reporter.profile()
        assert len(report.location_failures) == 2
        failed_locations = {str(l) for l, _e in report.location_failures}
        assert all("/gone/" in s for s in failed_locations)
        assert "ReadFailures<" in str(report)

    asyncio.run(main())


def test_corrupt_location_is_reported_and_demerited(tmp_path):
    from chunky_bits_tpu.file.chunk import Chunk
    from chunky_bits_tpu.file.file_part import FilePart
    from chunky_bits_tpu.file.hashing import AnyHash
    from chunky_bits_tpu.file.location import LocationContext
    from chunky_bits_tpu.file.profiler import new_profiler

    payload = b"x" * 4096
    corrupt = tmp_path / "bad" / "chunk0"
    corrupt.parent.mkdir()
    corrupt.write_bytes(b"y" * 4096)
    good = tmp_path / "good" / "chunk0"
    good.parent.mkdir()
    good.write_bytes(payload)
    chunk = Chunk(hash=AnyHash.from_buf(payload),
                  locations=[Location.local(str(corrupt)),
                             Location.local(str(good))])
    part = FilePart(chunksize=4096, data=[chunk], parity=[])

    async def main():
        profiler, reporter = new_profiler()
        cx = LocationContext(profiler=profiler)
        cx.health = HealthScoreboard()
        got = await part.read(cx)
        assert got == payload
        report = reporter.profile()
        assert len(report.location_failures) == 1
        _loc, why = report.location_failures[0]
        assert "hash mismatch" in why
        # corruption is a health demerit for the serving node
        assert cx.health.stats().locations, "no health rows recorded"
        rows = {r.key: r for r in cx.health.stats().locations}
        bad_row = rows[location_key(chunk.locations[0])]
        assert bad_row.errors >= 1

    asyncio.run(main())


# ---- hedged read: byte identity under the race, scoreboard counters ----

def test_hedged_read_byte_identity_fuzz(tmp_path):
    """Conformance fuzz for the race: random objects, every chunk
    replicated, random per-read winner (no injected latency — both
    sides are live, so either may win); bytes must always be identical
    to hedging-off."""
    from chunky_bits_tpu.file.chunk import Chunk
    from chunky_bits_tpu.file.file_part import FilePart
    from chunky_bits_tpu.file.hashing import AnyHash
    from chunky_bits_tpu.file.location import LocationContext

    rng = np.random.default_rng(21)

    async def main():
        for trial in range(6):
            d = int(rng.integers(2, 5))
            chunksize = int(rng.integers(100, 5000))
            chunks = []
            want = []
            for ci in range(d):
                payload = rng.integers(
                    0, 256, chunksize, dtype=np.uint8).tobytes()
                want.append(payload)
                locations = []
                for rep in range(2):
                    f = tmp_path / f"t{trial}" / f"r{rep}" / f"c{ci}"
                    f.parent.mkdir(parents=True, exist_ok=True)
                    f.write_bytes(payload)
                    locations.append(Location.local(str(f)))
                chunks.append(Chunk(hash=AnyHash.from_buf(payload),
                                    locations=locations))
            part = FilePart(chunksize=chunksize, data=chunks, parity=[])
            # aggressive floor: hedges fire on essentially every fetch
            cx = LocationContext()
            cx.health = HealthScoreboard(hedge_ms=0.001)
            got = await part.read(cx)
            assert got == b"".join(want), f"trial {trial} mismatch"

    asyncio.run(main())
