"""Worker for the two-process jax.distributed smoke test
(test_parallel.py::test_two_process_distributed_encode).

Each worker joins a localhost coordinator via ``init_multihost``'s
explicit-args path, takes its ``partition_parts`` slice of a shared
deterministic part batch, encodes it on a mesh over its own local
devices, and writes parity + the psum checksum to an .npz for the parent
to verify against the oracle.  Run:

    python mh_worker.py <coordinator_port> <process_id> <n_procs> <out.npz>
"""

import sys


def main() -> None:
    port, pid, nprocs, out_path = (sys.argv[1], int(sys.argv[2]),
                                   int(sys.argv[3]), sys.argv[4])

    import numpy as np

    from chunky_bits_tpu.ops import matrix
    from chunky_bits_tpu.parallel import (
        encode_step_sharded,
        init_multihost,
        local_mesh,
        partition_parts,
    )

    idx, count = init_multihost(f"127.0.0.1:{port}", num_processes=nprocs,
                                process_id=pid)
    assert (idx, count) == (pid, nprocs), (idx, count)
    # idempotent re-entry must keep reporting the distributed topology
    assert init_multihost() == (pid, nprocs)

    d, p, size, total = 4, 2, 256, 12
    enc = matrix.build_encode_matrix(d, p)
    # same seed in every process: the global batch is shared state, each
    # process encodes only its dealt slice
    data = np.random.default_rng(77).integers(
        0, 256, (total, d, size), dtype=np.uint8)
    lo, hi = partition_parts(total)
    mesh = local_mesh(sp=2)
    parity, checksum = encode_step_sharded(mesh, enc, data[lo:hi])
    np.savez(out_path, lo=lo, hi=hi, parity=np.asarray(parity),
             checksum=int(checksum))
    print(f"worker {pid}: parts [{lo}, {hi}) ok", flush=True)


if __name__ == "__main__":
    main()
