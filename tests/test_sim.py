"""Deterministic cluster simulator tests (chunky_bits_tpu/sim).

Three layers, matching the simulator's three pieces:

* the **clock seam + virtual loop** — time compression (hours of
  virtual time in milliseconds of wall), zero-virtual-width thread
  work, seam install/restore hygiene, and the production-imports-
  nothing-from-sim guarantee (checked in a subprocess so this suite's
  own sim imports cannot pollute the verdict);
* the **fault-injection fabric** — state-machine semantics per verb,
  deterministic latency sampling, the one-shot FaultInjector scripts
  shared with tests/http_node.py, and the ``sim:`` Location surface
  (parse/str round-trip, read/write/exists/length/delete through the
  production Location verbs);
* the **scenario engine** — every library scenario passes its own
  invariant verdicts at small scale, the ISSUE-12 regression trio
  (AZ outage waits out the partition with no fallback storm; rolling
  restart during pm-msr repair keeps the ``cb_repair_*`` code labels
  correct; a breaker flap never strands a live node at zero traffic),
  and THE determinism pin: same seed ⇒ byte-identical event trace and
  equal metrics snapshot.

Everything runs un-``slow``-marked in tier-1: compressed virtual time
is the whole point.  The SANITIZE=1 leg runs these too — ``sim.run``
tears down asyncio.run-style, so 0 leaked tasks is part of the
contract under test.
"""

import asyncio
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from chunky_bits_tpu.errors import (
    HttpStatusError,
    LocationError,
    LocationParseError,
)
from chunky_bits_tpu.sim import fabric as fabric_mod
from chunky_bits_tpu.sim import run as sim_run
from chunky_bits_tpu.sim.scenario import (
    SCENARIOS,
    fresh_workdir,
    run_scenario,
)
from chunky_bits_tpu.utils import clock as clock_mod


# ---- clock seam + virtual loop ----

def test_virtual_loop_compresses_time():
    """An hour of virtual sleeping costs milliseconds of wall time,
    and the seam's monotonic() agrees with the loop's timebase."""
    real = clock_mod.system_clock()

    async def main():
        t0 = clock_mod.monotonic()
        await clock_mod.sleep(3600.0)
        await asyncio.sleep(1800.0)  # plain asyncio.sleep is virtual too
        return clock_mod.monotonic() - t0

    wall0 = real.monotonic()
    virtual = sim_run(main())
    wall = real.monotonic() - wall0
    assert virtual >= 5400.0
    assert wall < 10.0, f"virtual hour took {wall:.1f}s of wall time"


def test_clock_seam_restored_after_run():
    """sim.run brackets the clock swap: afterwards the active clock is
    the system clock again, even when the scenario raises."""
    assert clock_mod.active() is clock_mod.system_clock()

    async def boom():
        await clock_mod.sleep(60.0)
        raise RuntimeError("scenario failed")

    with pytest.raises(RuntimeError, match="scenario failed"):
        sim_run(boom())
    assert clock_mod.active() is clock_mod.system_clock()


def test_thread_work_completes_at_zero_virtual_width(tmp_path):
    """Real host-thread work (the disk hops asyncio.to_thread runs)
    still completes under the virtual loop — and takes zero virtual
    time: the loop refuses to advance while a thread is in flight."""
    path = tmp_path / "payload.bin"

    async def main():
        t0 = clock_mod.monotonic()
        await asyncio.to_thread(path.write_bytes, b"x" * 65536)
        data = await asyncio.to_thread(path.read_bytes)
        return data, clock_mod.monotonic() - t0

    data, virtual_width = sim_run(main())
    assert data == b"x" * 65536
    assert virtual_width == 0.0


def test_sim_run_rejects_nested_loop():
    async def outer():
        coro = asyncio.sleep(0)
        try:
            sim_run(coro)
        finally:
            coro.close()

    with pytest.raises(RuntimeError, match="running event loop"):
        asyncio.run(outer())


def test_production_imports_nothing_from_sim():
    """The acceptance criterion, checked in a clean interpreter: the
    cluster/file/gateway planes import with zero sim modules loaded
    (the ``sim:`` Location branches are lazy, like ``slab:``).

    Deliberately kept ALONGSIDE lint rule CB304 (sim-purity), not
    replaced by it: this pin proves the *runtime default import
    closure* is sim-free (catching dynamic/importlib paths static
    analysis cannot see), while CB304 proves it *statically* including
    lazy in-function imports this subprocess never executes."""
    code = (
        "import sys\n"
        "import chunky_bits_tpu.cluster\n"
        "import chunky_bits_tpu.file.location\n"
        "import chunky_bits_tpu.cluster.scrub\n"
        "import chunky_bits_tpu.cluster.repair\n"
        "import chunky_bits_tpu.gateway\n"
        "bad = [m for m in sys.modules"
        " if m.startswith('chunky_bits_tpu.sim')]\n"
        "assert not bad, f'production imports pulled in {bad}'\n"
    )
    subprocess.run([sys.executable, "-c", code], check=True,
                   cwd=REPO, env=dict(os.environ, PYTHONPATH=REPO),
                   timeout=120)


# ---- fault-injection fabric ----

def test_fabric_state_machine_semantics():
    """Each fault state produces the failure shape a real node in that
    state would: dead refuses, partitioned stalls then times out,
    erroring answers a transient status, recovering lapses healthy."""
    async def main():
        fab = fabric_mod.SimFabric("sm", 1, seed=5)
        node = fab.nodes["n0000"]
        await node.write("c", b"payload")

        with pytest.raises(ValueError, match="unknown node state"):
            node.set_state("zombie")

        node.set_state(fabric_mod.DEAD)
        with pytest.raises(LocationError, match="dead"):
            await node.read("c")

        node.set_state(fabric_mod.PARTITIONED)
        node.partition_stall_s = 7.5
        t0 = clock_mod.monotonic()
        with pytest.raises(LocationError, match="partitioned"):
            await node.read("c")
        assert clock_mod.monotonic() - t0 >= 7.5

        node.set_state(fabric_mod.ERRORING)
        with pytest.raises(HttpStatusError):
            await node.read("c")

        node.set_state(fabric_mod.RECOVERING)
        node.recover_s = 30.0
        assert await node.read("c") == b"payload"
        assert node.state == fabric_mod.RECOVERING
        await clock_mod.sleep(31.0)
        assert await node.read("c") == b"payload"
        assert node.state == fabric_mod.HEALTHY
        fab.close()

    sim_run(main())


def test_fabric_latency_is_seeded_deterministic():
    """Same fabric seed ⇒ identical per-node latency sample sequences;
    different nodes draw independent streams."""
    model = fabric_mod.LatencyModel(median_ms=3.0, tail_p=0.2)
    fab_a = fabric_mod.SimFabric("la", 3, seed=42, latency=model)
    fab_b = fabric_mod.SimFabric("lb", 3, seed=42, latency=model)
    try:
        for node_id in fab_a.nodes:
            a = [model.sample(fab_a.nodes[node_id].rng)
                 for _ in range(64)]
            b = [model.sample(fab_b.nodes[node_id].rng)
                 for _ in range(64)]
            assert a == b
        first = [model.sample(fabric_mod.SimFabric(
            "lc", 2, seed=42, latency=model).nodes["n0000"].rng)
            for _ in range(16)]
        second = [model.sample(fabric_mod.SimFabric(
            "ld", 2, seed=42, latency=model).nodes["n0001"].rng)
            for _ in range(16)]
        assert first != second
    finally:
        fab_a.close()
        fab_b.close()


def test_fault_injector_one_shot_and_broken_disk():
    """The knob surface tests/http_node.py forwards to: one-shot PUT
    statuses consume their budget then normal service resumes; the
    node-wide broken-disk mode answers 507 forever."""
    inj = fabric_mod.FaultInjector()
    inj.put_fail_status = 503
    inj.put_fail_remaining = 2
    assert inj.put_fault() == 503
    assert inj.put_fault() == 503
    assert inj.put_fault() == 0
    inj.fail_puts = True
    assert inj.put_fault() == 507
    inj.fail_puts = False
    assert inj.get_fault() == 0.0
    inj.get_delay = 0.25
    assert inj.get_fault() == 0.25


def test_sim_location_surface(tmp_path):
    """``sim:`` locations behind the production Location verbs: parse
    and str round-trip, write/read(range)/exists/length/delete hit the
    fabric node, and a dangling fabric id fails loudly."""
    from chunky_bits_tpu.file.location import Location

    loc = Location.parse("sim:fabX/n0000/chunk0")
    assert loc.is_sim() and str(loc) == "sim:fabX/n0000/chunk0"
    with pytest.raises(LocationParseError):
        Location.parse("sim:")
    with pytest.raises(LocationError, match="no live sim fabric"):
        fabric_mod.resolve("ghost/n0000/c")
    with pytest.raises(LocationError, match="does not name"):
        fabric_mod.resolve("only-two/parts")

    async def main():
        fab = fabric_mod.SimFabric("fabX", 2, seed=0)
        try:
            with pytest.raises(LocationError, match="no node"):
                fabric_mod.resolve("fabX/n9999/c")
            await loc.write(b"0123456789")
            assert await loc.file_exists()
            assert await loc.file_len() == 10
            assert await loc.read() == b"0123456789"
            from chunky_bits_tpu.file.location import Range
            ranged = loc.with_range(Range(start=2, length=5))
            assert await ranged.read() == b"23456"
            await loc.delete()
            assert not await loc.file_exists()
            with pytest.raises(LocationError, match="no chunk"):
                await loc.read()
        finally:
            fab.close()

    sim_run(main())


def test_fabric_zone_topology_and_stats():
    fab = fabric_mod.SimFabric("zt", 9, zones=("a", "b", "c"), seed=1)
    try:
        assert {n.zone for n in fab.nodes.values()} == {"a", "b", "c"}
        assert len(fab.nodes_in_zone("a")) == 3
        fab.set_zone_state("b", fabric_mod.DEAD)
        assert all(n.state == fabric_mod.DEAD
                   for n in fab.nodes_in_zone("b"))
        stats = fab.stats()
        assert stats["nodes"] == 9
        assert stats["by_state"] == {"dead": 3, "healthy": 6}
        with pytest.raises(ValueError, match="no nodes in zone"):
            fab.set_zone_state("nowhere", fabric_mod.DEAD)
        dests = fab.destination_objs()
        assert len(dests) == 9
        assert dests[0]["location"].startswith("sim:zt/")
        assert dests[0]["zones"] == ["a"]
    finally:
        fab.close()
    with pytest.raises(LocationError, match="no live sim fabric"):
        fabric_mod.get_fabric("zt")


# ---- scenario engine ----

#: per-scenario invariant verdicts that MUST appear and hold — the
#: regression surface for the ISSUE-12 trio, the rest of the library,
#: and (ISSUE 13) the SLO detection verdicts: every scenario carries
#: `slo_no_false_positives` (the engine runs everywhere, silence is a
#: tested property), and scenarios with scripted faults additionally
#: pin their expected alerts firing within the detection bound and
#: resolving after convergence
_KEY_VERDICTS = {
    # repair waits out the partition: zero classic-resilver fallbacks;
    # a third of the fleet degraded must trip the breaker-open alert
    "az_outage": ("converged", "no_fallback_storm",
                  "reads_clean_outside_fault",
                  "slo_detected_breaker_open",
                  "slo_no_false_positives"),
    # restarts are routine: the engine must stay SILENT throughout
    "rolling_restart": ("converged", "reads_clean_outside_fault",
                        "slo_no_false_positives"),
    # msr plan survives helper churn or falls back cleanly, and every
    # repair byte lands under the pm-msr code label
    "pm_msr_restart_repair": ("converged", "repair_labeled_pm_msr",
                              "slo_no_false_positives"),
    # the pinned hedge token bucket is an alert, inside the declared
    # straggler window only
    "thundering_herd": ("hedge_within_budget", "herd_reads_served",
                        "slo_detected_hedge_exhaustion",
                        "slo_no_false_positives"),
    # dead disks: the planner's re-placement escalation IS the
    # fallback-storm signal (and it resolves once re-placed)
    "correlated_failures": ("converged", "replaced_lost_chunks",
                            "slo_detected_repair_fallback_storm",
                            "slo_no_false_positives"),
    # an open breaker may never strand a live node at zero traffic:
    # the half-open probe recovers it once the flapping stops — and
    # one flapping node of many stays below every alert objective
    "flapping_node": ("breaker_recovered", "traffic_returned",
                      "slo_no_false_positives"),
    "slow_leak": ("converged", "corruption_detected",
                  "slo_no_false_positives"),
    # weighted-fair admission isolates the victim tenant from the
    # flood (and the FIFO control leg must actually have degraded it,
    # else both isolation verdicts pass trivially)
    "noisy_neighbor": ("victim_isolated_under_drr",
                       "victim_near_baseline_under_drr",
                       "fifo_leg_degraded",
                       "slo_no_false_positives"),
    # the disk-fault axis (ISSUE 14): a corruption burst plus silent
    # torn and ENOSPC-refused repair writes — all absorbed by
    # scrub/repair, never client-visible, the engine stays silent
    "disk_corruption_storm": ("converged", "corruption_detected",
                              "torn_writes_ridden_out",
                              "disk_full_ridden_out",
                              "reads_clean_outside_fault",
                              "slo_no_false_positives"),
    # total connectivity loss: scrub-stall + breaker + fallback-storm
    # all detected, all resolved after the heal
    "fleet_partition": ("converged",
                        "slo_detected_scrub_stall",
                        "slo_detected_breaker_open",
                        "slo_detected_repair_fallback_storm",
                        "slo_no_false_positives"),
}


@pytest.mark.parametrize("name", sorted(SCENARIOS))
def test_scenario_invariants(name, tmp_path):
    """Every library scenario passes all its verdicts at small scale —
    fleet semantics don't change with N, only coverage does (bench
    --config 14 runs the same library at N=100)."""
    result = run_scenario(name, nodes=12, seed=0,
                          workdir=str(tmp_path), objects=6)
    assert result.ok(), (
        f"{name} failed verdicts "
        f"{ {k: v for k, v in result.verdicts.items() if not v} }\n"
        f"trace tail:\n"
        + result.trace.decode()[-2000:])
    for verdict in _KEY_VERDICTS[name]:
        assert result.verdicts.get(verdict) is True, (
            f"{name} missing/failed key verdict {verdict!r}: "
            f"{result.verdicts}")
    # compressed virtual time is the point: wall must be a small
    # fraction of the virtual span on every scenario long enough to
    # measure (thundering_herd lives mostly in real hash work)
    if result.virtual_seconds >= 60.0:
        assert result.compression() > 10.0, result.to_obj()


def test_scenario_same_seed_byte_identical(tmp_path):
    """THE determinism pin: two runs of the same scenario, seed, and
    workdir produce byte-identical event traces and equal metrics
    snapshots.  ONE workdir path reused (reset between runs) so
    metadata paths are string-identical run to run."""
    workdir = str(tmp_path / "det")
    runs = []
    for _ in range(2):
        fresh_workdir(workdir)
        runs.append(run_scenario("az_outage", nodes=10, seed=7,
                                 workdir=workdir, objects=6))
    a, b = runs
    assert a.trace == b.trace, "event traces diverged across runs"
    assert a.metrics == b.metrics, "metrics snapshots diverged"
    assert a.virtual_seconds == b.virtual_seconds
    assert a.verdicts == b.verdicts
    assert a.trace.count(b"\n") > 20, "trace suspiciously empty"


def test_scenario_different_seed_diverges(tmp_path):
    """The pin's control: a different seed actually changes the world
    (latency draws, placement, damage choices) — byte-identity above
    is meaningful, not vacuous."""
    workdir = str(tmp_path / "ctl")
    fresh_workdir(workdir)
    a = run_scenario("az_outage", nodes=10, seed=7,
                     workdir=workdir, objects=6)
    fresh_workdir(workdir)
    b = run_scenario("az_outage", nodes=10, seed=8,
                     workdir=workdir, objects=6)
    assert a.trace != b.trace
    assert a.ok() and b.ok()


def test_scenario_result_shape(tmp_path):
    """The bench --config 14 row: to_obj() is JSON-serializable with
    the fields the driver contract reports."""
    import json

    workdir = str(tmp_path / "row")
    fresh_workdir(workdir)
    result = run_scenario("rolling_restart", nodes=10, seed=3,
                          workdir=workdir, objects=6)
    row = json.loads(json.dumps(result.to_obj()))
    for key in ("name", "seed", "nodes", "virtual_s", "wall_s",
                "compression_x", "ok", "verdicts", "trace_events"):
        assert key in row, f"missing {key} in {sorted(row)}"
    assert row["ok"] is True
    assert row["trace_events"] > 0


def test_unknown_scenario_fails_loudly(tmp_path):
    with pytest.raises(ValueError, match="unknown scenario"):
        run_scenario("heat_death", workdir=str(tmp_path))


# ---- SLO detection quality (ISSUE 13) ----

def test_every_scenario_reports_slo_verdicts(tmp_path):
    """The acceptance criterion's shape half: EVERY scenario runs the
    engine and reports `slo_no_false_positives`, scenarios with a spec
    report one `slo_detected_<rule>` per expected rule, and the result
    row carries the detection-latency report bench --config 15 emits.
    (The verdicts HOLDING is pinned per scenario in _KEY_VERDICTS.)"""
    result = run_scenario("fleet_partition", nodes=12, seed=0,
                          workdir=str(tmp_path), objects=6)
    assert "slo_no_false_positives" in result.verdicts
    spec = SCENARIOS["fleet_partition"].slo
    for rule in spec["expected"]:
        assert f"slo_detected_{rule}" in result.verdicts
    report = result.details["slo"]
    assert report["false_positives"] == 0
    for rule, bound in ((r, c["within_s"])
                        for r, c in spec["expected"].items()):
        assert 0.0 < report["detect_latency_s"][rule] <= bound
    # alert transitions are trace events — part of the determinism pin
    assert result.trace.count(b'"event":"alert"') \
        == report["transitions"]


def test_detection_latency_is_deterministic(tmp_path):
    """Same seed ⇒ identical detection latencies (the general trace
    pin covers this byte-for-byte; this pins the derived numbers the
    config-15 row reports, so a refactor of the report cannot silently
    decouple them from the trace)."""
    runs = []
    workdir = str(tmp_path / "det")
    for _ in range(2):
        fresh_workdir(workdir)
        runs.append(run_scenario("thundering_herd", nodes=12, seed=0,
                                 workdir=workdir, objects=6))
    a, b = runs
    assert a.details["slo"] == b.details["slo"]
    assert a.details["slo"]["detect_latency_s"], "expected a detection"
    assert a.trace == b.trace
