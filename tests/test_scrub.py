"""Continuous scrub/repair daemon (cluster/scrub.py).

Pins the acceptance criteria: the byte-rate bound is honored (measured),
an injected flipped bit is detected, the serving node is demerited in
the health scoreboard, the damaged part is repaired in place, the
daemon is off by default with zero overhead when off, and start/stop
leaks nothing (the SANITIZE=1 tier-1 leg re-runs this whole file under
the task-leak registry).
"""

import asyncio
import os
import time

import numpy as np
import pytest

from chunky_bits_tpu.cluster import Cluster
from chunky_bits_tpu.cluster.scrub import ScrubDaemon, TokenBucket, \
    maybe_build
from chunky_bits_tpu.utils import aio
from tests.test_slab import make_cluster_obj


def write_payload(cluster, name, nbytes, seed=0):
    payload = np.random.default_rng(seed).integers(
        0, 256, nbytes, dtype=np.uint8).tobytes()

    async def run():
        await cluster.write_file(name, aio.BytesReader(payload),
                                 cluster.get_profile())

    asyncio.run(run())
    return payload


def flip_bit_in_extent(location, at=11):
    path, off, ln = location.slab_extent()
    with open(path, "r+b") as f:
        f.seek(off + min(at, ln - 1))
        byte = f.read(1)
        f.seek(off + min(at, ln - 1))
        f.write(bytes([byte[0] ^ 1]))


# ---- token bucket ----

def test_token_bucket_honors_rate():
    bucket = TokenBucket(rate=40_000)

    async def main():
        t0 = time.monotonic()
        # 60 KB against a 40 KB/s rate with a 40 KB burst: at least
        # (60-40)/40 = 0.5 s must elapse
        for _ in range(6):
            await bucket.take(10_000)
        return time.monotonic() - t0

    elapsed = asyncio.run(main())
    assert elapsed >= 0.4, f"bucket let 60KB through in {elapsed:.3f}s"
    assert elapsed < 5.0


def test_token_bucket_zero_rate_is_unbounded():
    bucket = TokenBucket(rate=0)

    async def main():
        t0 = time.monotonic()
        for _ in range(100):
            await bucket.take(1 << 20)
        return time.monotonic() - t0

    assert asyncio.run(main()) < 0.5


# ---- off by default ----

def test_daemon_off_by_default(tmp_path):
    cluster = Cluster.from_obj(make_cluster_obj(tmp_path))
    assert cluster.tunables.scrub_bytes_per_sec == 0
    assert maybe_build(cluster) is None


def test_tunable_serde_and_env_default(tmp_path, monkeypatch):
    from chunky_bits_tpu.cluster.tunables import (
        SCRUB_BYTES_PER_SEC_ENV,
        Tunables,
    )

    t = Tunables.from_obj({"scrub_bytes_per_sec": 1048576})
    assert t.scrub_bytes_per_sec == 1048576
    assert t.to_obj()["scrub_bytes_per_sec"] == 1048576
    assert "scrub_bytes_per_sec" not in Tunables.from_obj(None).to_obj()
    with pytest.raises(Exception):
        Tunables.from_obj({"scrub_bytes_per_sec": -5})
    monkeypatch.setenv(SCRUB_BYTES_PER_SEC_ENV, "2048")
    assert Tunables.from_obj(None).scrub_bytes_per_sec == 2048
    monkeypatch.setenv(SCRUB_BYTES_PER_SEC_ENV, "garbage")
    assert Tunables.from_obj(None).scrub_bytes_per_sec == 0
    # YAML wins over the env default
    assert Tunables.from_obj(
        {"scrub_bytes_per_sec": 7}).scrub_bytes_per_sec == 7
    cluster = Cluster.from_obj(make_cluster_obj(
        tmp_path, tunables={"scrub_bytes_per_sec": 4096}))
    daemon = maybe_build(cluster)
    assert daemon is not None and daemon.rate == 4096


# ---- detection / demerit / repair ----

def test_scrub_detects_demerits_and_repairs(tmp_path):
    cluster = Cluster.from_obj(make_cluster_obj(tmp_path))
    payload = write_payload(cluster, "a/obj", 30000, seed=1)
    write_payload(cluster, "b", 9000, seed=2)

    async def main():
        ref = await cluster.get_file_ref("a/obj")
        bad_location = ref.parts[0].data[0].locations[0]
        flip_bit_in_extent(bad_location)
        daemon = ScrubDaemon(cluster, bytes_per_sec=10_000_000)
        stats = await daemon.run_once()
        assert stats.files_scanned == 2
        assert stats.corrupt >= 1
        assert stats.repaired >= 1
        assert stats.bytes_verified > 0
        # the node serving corrupt bytes took a health demerit
        health = cluster.health_scoreboard().stats()
        assert any(row.errors >= 1 for row in health.locations), health
        # repaired: the object verifies Valid and reads identical
        ref2 = await cluster.get_file_ref("a/obj")
        verify = await ref2.verify(cluster.tunables.location_context())
        assert str(verify.integrity()) == "Valid"
        got = await cluster.file_read_builder(ref2).read_all()
        assert got == payload
        # the Scrub<...> stanza renders through the profiler
        from chunky_bits_tpu.file.profiler import new_profiler

        profiler, reporter = new_profiler()
        profiler.attach_scrub(daemon)
        assert "Scrub<" in str(reporter.profile())

    asyncio.run(main())


def test_scrub_repairs_missing_extent(tmp_path):
    cluster = Cluster.from_obj(make_cluster_obj(tmp_path))
    payload = write_payload(cluster, "obj", 24000, seed=3)

    async def main():
        ref = await cluster.get_file_ref("obj")
        await ref.parts[0].parity[0].locations[0].delete()
        daemon = ScrubDaemon(cluster, bytes_per_sec=0)  # unthrottled
        stats = await daemon.run_once()
        assert stats.unavailable >= 1
        assert stats.repaired >= 1
        ref2 = await cluster.get_file_ref("obj")
        verify = await ref2.verify(cluster.tunables.location_context())
        assert str(verify.integrity()) == "Valid"
        got = await cluster.file_read_builder(ref2).read_all()
        assert got == payload

    asyncio.run(main())


def test_scrub_no_repair_mode_reports_only(tmp_path):
    cluster = Cluster.from_obj(make_cluster_obj(tmp_path))
    write_payload(cluster, "obj", 15000, seed=4)

    async def main():
        ref = await cluster.get_file_ref("obj")
        flip_bit_in_extent(ref.parts[0].data[0].locations[0])
        daemon = ScrubDaemon(cluster, bytes_per_sec=0, repair=False)
        stats = await daemon.run_once()
        assert stats.corrupt >= 1
        assert stats.repaired == 0
        # still corrupt: a second pass finds it again
        stats = await daemon.run_once()
        assert stats.corrupt >= 2

    asyncio.run(main())


def test_scrub_rate_bound_measured(tmp_path):
    """The acceptance measurement: with ~45 KB of replicas and a
    30 KB/s budget (30 KB burst), one pass cannot finish faster than
    (bytes - burst) / rate."""
    cluster = Cluster.from_obj(make_cluster_obj(tmp_path))
    write_payload(cluster, "obj", 27000, seed=5)

    async def main():
        daemon = ScrubDaemon(cluster, bytes_per_sec=30_000)
        t0 = time.monotonic()
        stats = await daemon.run_once()
        elapsed = time.monotonic() - t0
        floor = (stats.bytes_verified - 30_000) / 30_000
        assert floor > 0.1, \
            f"scenario too small to measure ({stats.bytes_verified}B)"
        assert elapsed >= floor * 0.9, (
            f"pass took {elapsed:.3f}s for {stats.bytes_verified}B — "
            f"the 30KB/s bound requires >= {floor:.3f}s")

    asyncio.run(main())


def test_scrub_prioritizes_degraded_nodes_first(tmp_path):
    cluster = Cluster.from_obj(make_cluster_obj(tmp_path))
    write_payload(cluster, "healthy", 12000, seed=6)
    write_payload(cluster, "atrisk", 12000, seed=7)

    async def main():
        ref = await cluster.get_file_ref("atrisk")
        victim = ref.parts[0].data[0].locations[0]
        health = cluster.health_scoreboard()
        for _ in range(6):  # trip the breaker: node degraded
            health.record(victim, False)
        assert health.degraded(victim)
        daemon = ScrubDaemon(cluster, bytes_per_sec=0)
        order = []
        original = daemon._scrub_ref

        async def spy(path, ref, cx, pipe, snapshot):
            order.append(path)
            return await original(path, ref, cx, pipe, snapshot)

        daemon._scrub_ref = spy
        await daemon.run_once()
        assert order[0] == "atrisk", order

    asyncio.run(main())


def test_scrub_rewrites_corrupt_replica_beside_healthy_one(tmp_path):
    """A corrupt replica with a healthy sibling is overwritten in
    place (resilver only rebuilds chunks with NO valid replica) — the
    namespace CONVERGES instead of re-detecting the same rot, and
    re-demeriting the node, every pass forever."""
    from chunky_bits_tpu.file.location import Location

    cluster = Cluster.from_obj(make_cluster_obj(tmp_path))
    payload = write_payload(cluster, "obj", 21000, seed=30)

    async def main():
        ref = await cluster.get_file_ref("obj")
        chunk = ref.parts[0].data[0]
        # replicate the chunk onto a second node, then corrupt the
        # original replica
        data = await chunk.locations[0].read()
        victim_root = os.path.dirname(chunk.locations[0].target)
        other = next(d for d in
                     (os.path.join(str(tmp_path), f"disk{i}")
                      for i in range(5))
                     if d != victim_root)
        replica = Location.parse(f"slab:{other}/{chunk.hash}")
        await replica.write(bytes(data))
        chunk.locations.append(replica)
        await cluster.write_file_ref("obj", ref)
        flip_bit_in_extent(chunk.locations[0])
        daemon = ScrubDaemon(cluster, bytes_per_sec=0)
        stats1 = await daemon.run_once()
        assert stats1.corrupt == 1
        assert stats1.repaired >= 1
        # converged: the next pass finds NOTHING new
        stats2 = await daemon.run_once()
        assert stats2.corrupt == stats1.corrupt, \
            "corrupt replica re-detected — scrub never converges"
        ref2 = await cluster.get_file_ref("obj")
        verify = await ref2.verify(cluster.tunables.location_context())
        assert str(verify.integrity()) == "Valid"
        got = await cluster.file_read_builder(ref2).read_all()
        assert got == payload

    asyncio.run(main())


def test_daemon_survives_a_failing_pass(tmp_path):
    """An unexpected exception inside one pass is logged and retried —
    it must not silently end continuous scrubbing, and stop() must
    still return cleanly afterwards."""
    cluster = Cluster.from_obj(make_cluster_obj(tmp_path))
    write_payload(cluster, "obj", 9000, seed=31)

    async def main():
        daemon = ScrubDaemon(cluster, bytes_per_sec=0,
                             interval_seconds=0.01)
        real_run_once = daemon.run_once
        calls = {"n": 0}

        async def flaky():
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("injected pass failure")
            return await real_run_once()

        daemon.run_once = flaky
        daemon.start()
        deadline = time.monotonic() + 10.0
        while daemon.stats().passes < 1:
            assert time.monotonic() < deadline, \
                "daemon died on the failing pass"
            await asyncio.sleep(0.02)
        assert calls["n"] >= 2
        await daemon.stop()
        assert not daemon.stats().running

    asyncio.run(main())


def test_scrub_repair_never_clobbers_concurrent_overwrite(tmp_path):
    """The republish fence: a client overwrite landing WHILE the
    (rate-bounded) scrub pass holds the old ref must win — the repair
    may fix old chunks, but stale metadata is never written back over
    the new version."""
    cluster = Cluster.from_obj(make_cluster_obj(tmp_path))
    write_payload(cluster, "obj", 27000, seed=20)

    async def main():
        ref = await cluster.get_file_ref("obj")
        flip_bit_in_extent(ref.parts[0].data[0].locations[0])
        # ~45 KB of replicas against 30 KB/s: the pass holds obj's
        # metadata snapshot for >= ~0.5 s after reading it
        daemon = ScrubDaemon(cluster, bytes_per_sec=30_000)
        pass_task = asyncio.ensure_future(daemon.run_once())
        await asyncio.sleep(0.15)
        new_payload = np.random.default_rng(21).integers(
            0, 256, 5000, dtype=np.uint8).tobytes()
        await cluster.write_file("obj", aio.BytesReader(new_payload),
                                 cluster.get_profile())
        await pass_task
        got = await cluster.file_read_builder(
            await cluster.get_file_ref("obj")).read_all()
        assert got == new_payload, \
            "scrub republished a stale ref over a concurrent overwrite"

    asyncio.run(main())


# ---- daemon lifetime ----

def test_daemon_start_stop_leaks_nothing(tmp_path):
    cluster = Cluster.from_obj(make_cluster_obj(tmp_path))
    write_payload(cluster, "obj", 9000, seed=8)

    async def main():
        daemon = ScrubDaemon(cluster, bytes_per_sec=10_000_000,
                             interval_seconds=0.02)
        daemon.start()
        assert daemon.stats().running
        daemon.start()  # idempotent while running
        deadline = time.monotonic() + 10.0
        while daemon.stats().passes < 2:
            assert time.monotonic() < deadline, "no passes completed"
            await asyncio.sleep(0.02)
        await daemon.stop()
        assert not daemon.stats().running
        await daemon.stop()  # idempotent when stopped
        passes = daemon.stats().passes
        await asyncio.sleep(0.1)
        assert daemon.stats().passes == passes, "daemon survived stop()"

    asyncio.run(main())


# ---- gateway integration ----

def test_gateway_scrub_status_and_autostart(tmp_path):
    """serve() with the tunable set starts the daemon and exposes its
    counters at /scrub/status; with the tunable off the endpoint says
    enabled: false (pinned separately in the gateway sendfile test)."""
    from aiohttp import ClientSession

    from chunky_bits_tpu.gateway import serve

    cluster = Cluster.from_obj(make_cluster_obj(
        tmp_path, tunables={"scrub_bytes_per_sec": 10_000_000}))
    payload = write_payload(cluster, "obj", 20000, seed=9)

    async def main():
        ready: asyncio.Future = asyncio.get_running_loop() \
            .create_future()
        serve_task = asyncio.ensure_future(serve(
            cluster, "127.0.0.1", 0,
            on_ready=lambda port: ready.set_result(port)))
        port = await asyncio.wait_for(ready, 30)
        try:
            async with ClientSession() as session:
                url = f"http://127.0.0.1:{port}"
                deadline = time.monotonic() + 15.0
                while True:
                    resp = await session.get(f"{url}/scrub/status")
                    assert resp.status == 200
                    status = await resp.json()
                    assert status["enabled"] is True
                    if status["passes"] >= 1:
                        break
                    assert time.monotonic() < deadline, status
                    await asyncio.sleep(0.05)
                assert status["files_scanned"] >= 1
                assert status["corrupt"] == 0
                # object reads ride alongside the scrub
                resp = await session.get(f"{url}/obj")
                assert await resp.read() == payload
        finally:
            serve_task.cancel()
            await asyncio.gather(serve_task, return_exceptions=True)

    asyncio.run(main())


# ---- CLI ----

def test_cli_scrub_once(tmp_path):
    import subprocess
    import sys

    import yaml

    REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    obj = make_cluster_obj(tmp_path)
    cluster_path = tmp_path / "cluster.yaml"
    cluster_path.write_text(yaml.safe_dump(obj))
    cluster = Cluster.from_obj(obj)
    write_payload(cluster, "obj", 18000, seed=10)

    async def corrupt():
        ref = await cluster.get_file_ref("obj")
        flip_bit_in_extent(ref.parts[0].data[0].locations[0])

    asyncio.run(corrupt())
    env = dict(os.environ)
    env.setdefault("PYTHONPATH", REPO)
    result = subprocess.run(
        [sys.executable, "-m", "chunky_bits_tpu.cli", "scrub",
         "--once", str(cluster_path)],
        capture_output=True, env=env, cwd=REPO, timeout=120)
    assert result.returncode == 0, result.stderr.decode()
    out = result.stdout.decode()
    assert "Scrub<" in out and "corrupt=1" in out, out
    assert "repaired=1" in out, out

    async def check():
        fresh = Cluster.from_obj(obj)
        ref = await fresh.get_file_ref("obj")
        verify = await ref.verify(fresh.tunables.location_context())
        assert str(verify.integrity()) == "Valid"

    asyncio.run(check())
