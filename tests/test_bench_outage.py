"""The bench outage contract (driver-facing, BENCH_r{N}.json).

When the device tunnel is down, ``python bench.py`` must emit ONE
parseable JSON line with ``tunnel_down: true`` and the last-good
numbers, exit 3, and do it fast enough to beat a driver-side cap; a
crashing probe child (broken env) must surface as itself, not as an
outage.  Exercised via the probe seams so no real tunnel (or hang) is
involved — the real-outage run was also verified live (BASELINE.md
round-5 state).
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_bench(probe_py: str, timeout=120):
    env = dict(os.environ,
               PYTHONPATH=REPO,
               CHUNKY_BITS_TPU_BENCH_PROBE_PY=probe_py,
               CHUNKY_BITS_TPU_BENCH_PROBE_SECS="0.3",
               CHUNKY_BITS_TPU_BENCH_BACKOFF_SCALE="0.01")
    return subprocess.run(
        [sys.executable, "bench.py"], cwd=REPO, env=env,
        capture_output=True, timeout=timeout)


def test_tunnel_down_emits_structured_record_fast():
    t0 = time.monotonic()
    r = _run_bench("import time; time.sleep(30)")
    assert r.returncode == 3, r.stderr.decode()[-500:]
    assert time.monotonic() - t0 < 60
    rec = json.loads(r.stdout.decode().strip().splitlines()[-1])
    assert rec["tunnel_down"] is True
    assert rec["value"] == 0.0
    assert rec["last_good"]["encode_gibps"] > 0
    # driver-parsed fields must all be present
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in rec


def test_probe_crash_is_not_an_outage():
    r = _run_bench("import sys; print('boom', file=sys.stderr); "
                   "sys.exit(7)")
    assert r.returncode == 3
    rec = json.loads(r.stdout.decode().strip().splitlines()[-1])
    assert "tunnel_down" not in rec
    assert "probe rc=7" in rec["error"]
    assert "boom" in rec["error"]


def test_config8_failure_emits_one_json_line():
    """--config 8 (hedged-read A/B, CPU-only) honors the same driver
    contract as the device configs: ANY failure still produces exactly
    one parseable JSON line on stdout and exit code 3."""
    env = dict(os.environ, PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, "bench.py", "--config", "8", "--reads", "0"],
        cwd=REPO, env=env, capture_output=True, timeout=120)
    assert r.returncode == 3, r.stderr.decode()[-500:]
    lines = [ln for ln in r.stdout.decode().splitlines() if ln.strip()]
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in rec
    assert rec["value"] == 0.0
    assert "error" in rec


def test_config9_smoke_emits_one_json_line():
    """--config 9 --smoke (gateway scale-out A/B at seconds-scale
    parameters, one supervisor-run worker) honors the driver contract:
    exactly one parseable JSON line on stdout with the required keys,
    exit 0."""
    env = dict(os.environ, PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, "bench.py", "--config", "9", "--smoke"],
        cwd=REPO, env=env, capture_output=True, timeout=300)
    assert r.returncode == 0, r.stderr.decode()[-800:]
    lines = [ln for ln in r.stdout.decode().splitlines() if ln.strip()]
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline", "workers",
                "p50_ms", "p99_ms", "p999_ms", "cond_304_speedup"):
        assert key in rec
    assert rec["value"] > 0
    assert rec["unit"] == "req/s"


def test_config9_failure_emits_one_json_line():
    """ANY --config 9 failure (here: invalid parameters) still
    produces exactly one parseable JSON line and exit 3 — the same
    contract as configs 8 and the device runs."""
    env = dict(os.environ, PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, "bench.py", "--config", "9",
         "--clients", "0"],
        cwd=REPO, env=env, capture_output=True, timeout=120)
    assert r.returncode == 3, r.stderr.decode()[-500:]
    lines = [ln for ln in r.stdout.decode().splitlines() if ln.strip()]
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in rec
    assert rec["value"] == 0.0
    assert "error" in rec


def test_config10_smoke_emits_one_json_line():
    """--config 10 --smoke (packed slab store vs file-per-chunk A/B at
    CI scale) honors the driver contract: exactly one parseable JSON
    line on stdout with the required keys plus the A/B fields, exit
    0 — and the run itself asserts byte identity between layouts."""
    env = dict(os.environ, PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, "bench.py", "--config", "10", "--smoke"],
        cwd=REPO, env=env, capture_output=True, timeout=300)
    assert r.returncode == 0, r.stderr.decode()[-800:]
    lines = [ln for ln in r.stdout.decode().splitlines() if ln.strip()]
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline",
                "put_files_ops", "put_slab_ops", "get_files_ops",
                "gc_walk_files_ms", "gc_walk_slab_ms",
                "gc_walk_speedup"):
        assert key in rec
    assert rec["value"] > 0
    assert rec["unit"] == "obj/s"


def test_config10_failure_emits_one_json_line():
    """ANY --config 10 failure (here: invalid parameters) still
    produces exactly one parseable JSON line and exit 3 — the same
    contract as configs 8/9 and the device runs."""
    env = dict(os.environ, PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, "bench.py", "--config", "10",
         "--objects", "0"],
        cwd=REPO, env=env, capture_output=True, timeout=120)
    assert r.returncode == 3, r.stderr.decode()[-500:]
    lines = [ln for ln in r.stdout.decode().splitlines() if ln.strip()]
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in rec
    assert rec["value"] == 0.0
    assert "error" in rec


def test_config11_smoke_emits_one_json_line():
    """--config 11 --smoke (repair-bandwidth planner A/B at CI scale)
    honors the driver contract: exactly one parseable JSON line on
    stdout with the required keys plus the A/B fields, exit 0 — and
    the run itself asserts repaired objects byte-identical to their
    payloads on both legs."""
    env = dict(os.environ, PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, "bench.py", "--config", "11", "--smoke"],
        cwd=REPO, env=env, capture_output=True, timeout=300)
    assert r.returncode == 0, r.stderr.decode()[-800:]
    lines = [ln for ln in r.stdout.decode().splitlines() if ln.strip()]
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline",
                "bytes_per_rebuilt_off", "bytes_per_rebuilt_on",
                "repair_read_off_b", "repair_read_on_b",
                "wall_off_s", "wall_on_s", "plans_decode",
                "io_per_node_off", "io_per_node_on"):
        assert key in rec
    assert rec["value"] > 0
    assert rec["unit"] == "x"
    # the planner's structural win: strictly fewer repair bytes read
    # per rebuilt byte than the part-granular legacy leg
    assert rec["bytes_per_rebuilt_on"] < rec["bytes_per_rebuilt_off"]


def test_config11_failure_emits_one_json_line():
    """ANY --config 11 failure (here: invalid parameters) still
    produces exactly one parseable JSON line and exit 3 — the same
    contract as configs 8/9/10 and the device runs."""
    env = dict(os.environ, PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, "bench.py", "--config", "11",
         "--corrupt", "0"],
        cwd=REPO, env=env, capture_output=True, timeout=120)
    assert r.returncode == 3, r.stderr.decode()[-500:]
    lines = [ln for ln in r.stdout.decode().splitlines() if ln.strip()]
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in rec
    assert rec["value"] == 0.0
    assert "error" in rec


def test_config12_smoke_emits_one_json_line():
    """--config 12 --smoke (scheduled-XOR engine vs byte-table grid at
    CI scale) honors the driver contract: exactly one parseable JSON
    line on stdout with the required keys plus the grid fields, exit
    0 — and the run itself asserts byte identity between the engines
    on every cell."""
    env = dict(os.environ, PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, "bench.py", "--config", "12", "--smoke"],
        cwd=REPO, env=env, capture_output=True, timeout=300)
    assert r.returncode == 0, r.stderr.decode()[-800:]
    lines = [ln for ln in r.stdout.decode().splitlines() if ln.strip()]
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline", "wins",
                "cells", "wins_vs_scalar", "best_cell", "schedules",
                "grid"):
        assert key in rec
    assert rec["value"] > 0
    assert rec["unit"] == "x"
    assert rec["cells"] == len(rec["grid"]) == 2  # encode + decode
    for cell in rec["grid"]:
        assert cell["table_gibps"] > 0 and cell["xor_gibps"] > 0


def test_config12_failure_emits_one_json_line():
    """ANY --config 12 failure (here: invalid parameters) still
    produces exactly one parseable JSON line and exit 3 — the same
    contract as configs 8-11 and the device runs."""
    env = dict(os.environ, PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, "bench.py", "--config", "12",
         "--iters", "0"],
        cwd=REPO, env=env, capture_output=True, timeout=120)
    assert r.returncode == 3, r.stderr.decode()[-500:]
    lines = [ln for ln in r.stdout.decode().splitlines() if ln.strip()]
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in rec
    assert rec["value"] == 0.0
    assert "error" in rec


def test_config13_smoke_emits_one_json_line():
    """--config 13 --smoke (pm-msr vs rs repair-bandwidth A/B at CI
    scale) honors the driver contract: exactly one parseable JSON line
    on stdout with the required keys plus the per-leg fields, exit 0 —
    and the run itself asserts repaired objects byte-identical to
    their payloads on both legs and pm-msr encode/repair byte-identical
    across backends."""
    env = dict(os.environ, PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, "bench.py", "--config", "13", "--smoke"],
        cwd=REPO, env=env, capture_output=True, timeout=300)
    assert r.returncode == 0, r.stderr.decode()[-800:]
    lines = [ln for ln in r.stdout.decode().splitlines() if ln.strip()]
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline",
                "bytes_per_rebuilt_rs", "bytes_per_rebuilt_pm",
                "helper_b_rs", "helper_b_pm", "alpha", "helpers",
                "disk_read_rs_b", "disk_read_pm_b", "plans_msr",
                "plans_decode_rs", "wall_rs_s", "wall_pm_s"):
        assert key in rec
    assert rec["value"] > 0
    assert rec["unit"] == "x"
    # the regenerating code's structural win: strictly fewer helper
    # bytes per rebuilt byte than the rs leg's d x damage floor
    assert rec["bytes_per_rebuilt_pm"] < rec["bytes_per_rebuilt_rs"]


def test_config13_failure_emits_one_json_line():
    """ANY --config 13 failure (here: invalid parameters) still
    produces exactly one parseable JSON line and exit 3 — the same
    contract as configs 8-12 and the device runs."""
    env = dict(os.environ, PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, "bench.py", "--config", "13",
         "--corrupt", "0"],
        cwd=REPO, env=env, capture_output=True, timeout=120)
    assert r.returncode == 3, r.stderr.decode()[-500:]
    lines = [ln for ln in r.stdout.decode().splitlines() if ln.strip()]
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in rec
    assert rec["value"] == 0.0
    assert "error" in rec


def test_config14_smoke_emits_one_json_line():
    """--config 14 --smoke (simulator scenario suite at CI scale: 12
    nodes, 3 scenarios) honors the driver contract: exactly one
    parseable JSON line on stdout with the required keys plus the
    per-scenario rows, exit 0 — and the run itself asserts every
    scenario's invariant verdicts AND the same-seed determinism
    double-run (byte-identical trace, equal metrics)."""
    env = dict(os.environ, PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, "bench.py", "--config", "14", "--smoke"],
        cwd=REPO, env=env, capture_output=True, timeout=300)
    assert r.returncode == 0, r.stderr.decode()[-800:]
    lines = [ln for ln in r.stdout.decode().splitlines() if ln.strip()]
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline", "nodes",
                "scenarios", "scenarios_ok", "virtual_s", "wall_s",
                "deterministic", "rows"):
        assert key in rec
    assert rec["unit"] == "x"
    # compressed virtual time is the metric: even at smoke scale the
    # suite must live orders of magnitude more virtual life than wall
    assert rec["value"] > 10
    assert rec["deterministic"] is True
    assert rec["scenarios_ok"] == rec["scenarios"] == len(rec["rows"])
    for row in rec["rows"]:
        assert row["ok"] is True, row


def test_config14_failure_emits_one_json_line():
    """ANY --config 14 failure (here: an unknown scenario name) still
    produces exactly one parseable JSON line and exit 3 — the same
    contract as configs 8-13 and the device runs."""
    env = dict(os.environ, PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, "bench.py", "--config", "14",
         "--scenarios", "heat_death"],
        cwd=REPO, env=env, capture_output=True, timeout=120)
    assert r.returncode == 3, r.stderr.decode()[-500:]
    lines = [ln for ln in r.stdout.decode().splitlines() if ln.strip()]
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in rec
    assert rec["value"] == 0.0
    assert "error" in rec


def test_config15_smoke_emits_one_json_line():
    """--config 15 --smoke (SLO detection quality + engine-off
    overhead A/B at CI scale) honors the driver contract: exactly one
    parseable JSON line on stdout with the required keys, exit 0 —
    and the run itself asserts every expected alert detected within
    its virtual-time bound, ZERO false positives across the suite,
    the same-seed determinism double-run (alert trace included), and
    the engine-on gateway within a loose throughput floor of
    engine-off."""
    env = dict(os.environ, PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, "bench.py", "--config", "15", "--smoke"],
        cwd=REPO, env=env, capture_output=True, timeout=300)
    assert r.returncode == 0, r.stderr.decode()[-800:]
    lines = [ln for ln in r.stdout.decode().splitlines() if ln.strip()]
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline", "nodes",
                "scenarios", "alerts_expected", "alerts_detected",
                "false_positives", "deterministic",
                "detect_latency_s", "rps_off", "rps_on",
                "on_off_ratio", "rows"):
        assert key in rec
    assert rec["unit"] == "s"
    # the detection-quality contract, observed live: every expected
    # alert detected (value = worst virtual latency, inside bounds ⇒
    # margin > 1), zero false positives, deterministic double-run
    assert rec["value"] > 0
    assert rec["vs_baseline"] > 1.0
    assert rec["alerts_detected"] == rec["alerts_expected"] > 0
    assert rec["false_positives"] == 0
    assert rec["deterministic"] is True
    assert rec["on_off_ratio"] > 0.5
    for row in rec["rows"]:
        assert row["ok"] is True, row


def test_config15_failure_emits_one_json_line():
    """ANY --config 15 failure (here: an unknown scenario name) still
    produces exactly one parseable JSON line and exit 3 — the same
    contract as configs 8-14 and the device runs."""
    env = dict(os.environ, PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, "bench.py", "--config", "15",
         "--scenarios", "heat_death"],
        cwd=REPO, env=env, capture_output=True, timeout=120)
    assert r.returncode == 3, r.stderr.decode()[-500:]
    lines = [ln for ln in r.stdout.decode().splitlines() if ln.strip()]
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in rec
    assert rec["value"] == 0.0
    assert "error" in rec


def test_config16_smoke_emits_one_json_line():
    """--config 16 --smoke (crash-consistency matrix at CI scale:
    three mutations plus the power-cut scrub-recovery images) honors
    the driver contract: exactly one parseable JSON line on stdout
    with the required keys, exit 0 — and the run itself asserts every
    enumerated crash image recovers invariant-clean, ``scrub --once``
    converges the journal-line-without-slab-bytes power-cut image to
    Valid, and the same-seed determinism double-run (identical
    op-stream + verdict digest)."""
    env = dict(os.environ, PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, "bench.py", "--config", "16", "--smoke"],
        cwd=REPO, env=env, capture_output=True, timeout=300)
    assert r.returncode == 0, r.stderr.decode()[-800:]
    lines = [ln for ln in r.stdout.decode().splitlines() if ln.strip()]
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline", "mutations",
                "crash_points", "images", "images_ok",
                "cluster_images", "cluster_images_ok",
                "deterministic", "digest", "rows"):
        assert key in rec
    assert rec["unit"] == "images"
    # the acceptance criterion, observed live: EVERY enumerated crash
    # point recovered clean (ratio pinned at 1.0), deterministically
    assert rec["value"] > 100
    assert rec["vs_baseline"] == 1.0
    assert rec["images_ok"] == rec["images"]
    assert rec["cluster_images_ok"] == rec["cluster_images"] > 0
    assert rec["deterministic"] is True
    for row in rec["rows"]:
        assert row["images_ok"] == row["images"], row


def test_config16_failure_emits_one_json_line():
    """ANY --config 16 failure (here: an unknown mutation name) still
    produces exactly one parseable JSON line and exit 3 — the same
    contract as configs 8-15 and the device runs."""
    env = dict(os.environ, PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, "bench.py", "--config", "16",
         "--mutations", "heat_death"],
        cwd=REPO, env=env, capture_output=True, timeout=120)
    assert r.returncode == 3, r.stderr.decode()[-500:]
    lines = [ln for ln in r.stdout.decode().splitlines() if ln.strip()]
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in rec
    assert rec["value"] == 0.0
    assert "error" in rec


def test_config17_smoke_emits_one_json_line():
    """--config 17 --smoke (mesh backend + dispatch-pipeline A/B on an
    in-process virtual CPU mesh) honors the driver contract: exactly
    one parseable JSON line on stdout with the required keys, exit 0 —
    and the run itself asserts every leg byte-identical to the numpy
    oracle (encode, hash, decode-with-erasures) and proves the
    double-buffer overlap from the pipeline's own counters
    (max_inflight >= 2, submits-while-busy > 0 on the pipelined leg;
    neither with depth 0) rather than wall-clock."""
    env = dict(os.environ, PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, "bench.py", "--config", "17", "--smoke"],
        cwd=REPO, env=env, capture_output=True, timeout=300)
    assert r.returncode == 0, r.stderr.decode()[-800:]
    lines = [ln for ln in r.stdout.decode().splitlines() if ln.strip()]
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline", "devices",
                "geom", "legs", "pipeline", "overlap_proven",
                "identical"):
        assert key in rec
    assert rec["unit"] == "GiB/s"
    assert rec["value"] > 0
    assert rec["identical"] is True
    # the acceptance criterion, observed live: overlap proven from the
    # pipeline counters, not timing — double buffer held two dispatches
    # in flight while the off leg never exceeded one
    assert rec["overlap_proven"] is True
    assert rec["pipeline"]["on"]["max_inflight"] >= 2
    assert rec["pipeline"]["on"]["submits_while_busy"] > 0
    assert rec["pipeline"]["on"]["cancelled"] == 0
    assert rec["pipeline"]["off"]["max_inflight"] <= 1
    assert rec["pipeline"]["off"]["submits_while_busy"] == 0


def test_config17_failure_emits_one_json_line():
    """ANY --config 17 failure (here: an unparseable geometry) still
    produces exactly one parseable JSON line and exit 3 — the same
    contract as configs 8-16 and the device runs."""
    env = dict(os.environ, PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, "bench.py", "--config", "17",
         "--geom", "bogus"],
        cwd=REPO, env=env, capture_output=True, timeout=120)
    assert r.returncode == 3, r.stderr.decode()[-500:]
    lines = [ln for ln in r.stdout.decode().splitlines() if ln.strip()]
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in rec
    assert rec["value"] == 0.0
    assert "error" in rec


def test_config18_smoke_emits_one_json_line():
    """--config 18 --smoke (indexed metadata plane A/B at CI scale:
    10^3 objects, file-per-ref vs meta-log) honors the driver
    contract: exactly one parseable JSON line on stdout with the
    required keys, exit 0 — and the run itself asserts sampled refs
    byte-identical between the stores, the GC liveness sets
    set-equal, and the scrub pre-scan / GC walk answered from the
    index projections with zero ref reads."""
    env = dict(os.environ, PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, "bench.py", "--config", "18", "--smoke"],
        cwd=REPO, env=env, capture_output=True, timeout=300)
    assert r.returncode == 0, r.stderr.decode()[-800:]
    lines = [ln for ln in r.stdout.decode().splitlines() if ln.strip()]
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline", "objects",
                "put_path_ops", "put_log_ops", "list_path_ms",
                "list_log_ms", "list_speedup", "prefix_speedup",
                "scrub_meta_speedup", "gc_live_speedup",
                "snapshot_log_ms", "cold_index_ms",
                "refs_byte_identical"):
        assert key in rec
    assert rec["unit"] == "x"
    assert rec["value"] > 0
    assert rec["objects"] == 1000
    assert rec["refs_byte_identical"] > 0
    # smoke scale pins correctness (identity + index answers), not
    # the >= 10x acceptance ratios — those are BASELINE.md's 10^4 rows
    assert rec["scrub_meta_speedup"] > 0
    assert rec["gc_live_speedup"] > 0


def test_config18_failure_emits_one_json_line():
    """ANY --config 18 failure (here: a non-positive object count)
    still produces exactly one parseable JSON line and exit 3 — the
    same contract as configs 8-17 and the device runs."""
    env = dict(os.environ, PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, "bench.py", "--config", "18",
         "--objects", "0"],
        cwd=REPO, env=env, capture_output=True, timeout=120)
    assert r.returncode == 3, r.stderr.decode()[-500:]
    lines = [ln for ln in r.stdout.decode().splitlines() if ln.strip()]
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in rec
    assert rec["value"] == 0.0
    assert "error" in rec


def test_config19_smoke_emits_one_json_line():
    """--config 19 --smoke (multi-tenant QoS noisy-neighbor A/B:
    antagonist flood vs victim, isolation off vs on through one
    in-process gateway) honors the driver contract: exactly one
    parseable JSON line on stdout with the required keys, exit 0 —
    and the run itself asserts per-tenant byte identity in both legs
    (every victim body, sampled antagonist bodies)."""
    env = dict(os.environ, PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, "bench.py", "--config", "19", "--smoke"],
        cwd=REPO, env=env, capture_output=True, timeout=300)
    assert r.returncode == 0, r.stderr.decode()[-800:]
    lines = [ln for ln in r.stdout.decode().splitlines() if ln.strip()]
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline",
                "antagonists", "victim_reads", "max_concurrent_gets",
                "off", "on", "aggregate_rps_ratio"):
        assert key in rec
    assert rec["unit"] == "x"
    # smoke scale pins the contract + per-tenant identity + the
    # direction of the win, not the 5x acceptance ratio — that is
    # BASELINE.md's full-scale row
    assert rec["value"] > 1.0
    for leg in ("off", "on"):
        assert rec[leg]["victim_p99_ms"] > 0
        assert rec[leg]["ok"] > 0
    # the OFF leg must actually shed (else the flood was no flood);
    # the ON leg queues fairly instead of shedding the victim
    assert rec["off"]["shed_503"] > 0


def test_config19_failure_emits_one_json_line():
    """ANY --config 19 failure (here: a non-positive flood size)
    still produces exactly one parseable JSON line and exit 3 — the
    same contract as configs 8-18 and the device runs."""
    env = dict(os.environ, PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, "bench.py", "--config", "19",
         "--antagonists", "0"],
        cwd=REPO, env=env, capture_output=True, timeout=120)
    assert r.returncode == 3, r.stderr.decode()[-500:]
    lines = [ln for ln in r.stdout.decode().splitlines() if ln.strip()]
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in rec
    assert rec["value"] == 0.0
    assert "error" in rec


def test_seams_only_shrink_and_tolerate_garbage():
    """Inherited env values must not break the contract: malformed or
    larger-than-default values fall back to the real budget."""
    import bench

    for raw, want in (("", 120.0), ("15s", 120.0), ("-3", 120.0),
                      ("900", 120.0), ("0.5", 0.5)):
        os.environ["CHUNKY_BITS_TPU_BENCH_PROBE_SECS"] = raw
        try:
            assert bench._env_shrink(
                "CHUNKY_BITS_TPU_BENCH_PROBE_SECS", 120.0) == want, raw
        finally:
            del os.environ["CHUNKY_BITS_TPU_BENCH_PROBE_SECS"]
