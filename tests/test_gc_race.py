"""GC vs concurrent ingest (VERDICT r4 item 5).

``find-unused-hashes --remove`` must never delete a chunk an in-flight
write is about to reference.  The danger sequence: a ``cp`` stages chunk
files BEFORE publishing its metadata, so a racing GC lists the chunk,
finds no reference, and removes it just ahead of the publish.  The
reference runs this scan with no guard and no test (main.rs:329-435);
here the grace window (--grace-seconds) plus the delete-time age
re-check make the interleaving safe, and this file pins that guarantee
with live writes racing GC batches on one event loop.
"""

import asyncio
import hashlib
import os
import random
import time
from types import SimpleNamespace

import pytest
import yaml

from chunky_bits_tpu.cli.config import Config
from chunky_bits_tpu.cli.main import find_unused_hashes
from chunky_bits_tpu.utils import aio


@pytest.fixture
def cluster(tmp_path):
    disks = []
    for i in range(5):
        d = tmp_path / f"disk{i}"
        d.mkdir()
        disks.append(str(d))
    (tmp_path / "metadata").mkdir()
    path = tmp_path / "cluster.yaml"
    path.write_text(yaml.safe_dump({
        "destinations": [{"location": d} for d in disks],
        "metadata": {"type": "path", "format": "yaml",
                     "path": str(tmp_path / "metadata")},
        "profiles": {"default": {"data": 3, "parity": 2,
                                 "chunk_size": 16}},
    }))
    return path, disks


def _gc_args(yaml_path, disks, **over):
    base = dict(source=[f"{yaml_path}#."], hashes=disks,
                batch_size=2, remove=True, grace_seconds=30.0)
    base.update(over)
    return SimpleNamespace(**base)


def _plant_orphan(disks, i):
    """An unreferenced chunk file old enough to be a GC candidate."""
    data = b"orphan-%d" % i
    name = "sha256-" + hashlib.sha256(data).hexdigest()
    path = os.path.join(disks[i % len(disks)], name)
    with open(path, "wb") as f:
        f.write(data)
    old = time.time() - 3600
    os.utime(path, (old, old))
    return path


def test_gc_never_eats_inflight_writes(cluster, capsys):
    yaml_path, disks = cluster
    rng = random.Random(1234)
    payloads = {f"f{i}": rng.randbytes(rng.randrange(2000, 10000))
                for i in range(6)}
    orphans = [_plant_orphan(disks, i) for i in range(3)]

    async def run() -> None:
        config = await Config.load_or_default(None)
        cluster_obj = await config.get_cluster(str(yaml_path))

        async def writer():
            for name, data in payloads.items():
                await cluster_obj.write_file(
                    name, aio.BytesReader(data),
                    cluster_obj.get_profile(None))
                # yield so GC batches interleave between publishes
                await asyncio.sleep(0)

        async def gc_loop():
            # several full GC passes while writes are in flight; tiny
            # batch size forces multiple list/subtract/delete rounds
            # per pass
            for _ in range(4):
                await find_unused_hashes(
                    config, _gc_args(yaml_path, disks))
                await asyncio.sleep(0)

        await asyncio.gather(writer(), gc_loop())
        # a final pass after the writes, still within the grace window
        await find_unused_hashes(config, _gc_args(yaml_path, disks))

        # every written file must read back intact — no live chunk was
        # collected at any interleaving point
        for name, data in payloads.items():
            reader = await cluster_obj.read_file(name)
            chunks = []
            while True:
                piece = await reader.read(1 << 16)
                if not piece:
                    break
                chunks.append(piece)
            assert b"".join(chunks) == data, f"{name} corrupted by GC"

    asyncio.run(run())
    # ...while genuinely orphaned, old chunks were collected
    for path in orphans:
        assert not os.path.exists(path)


def test_grace_window_shields_fresh_unreferenced_chunks(cluster):
    """A just-staged chunk with no reference yet (the mid-publish state)
    survives a --remove pass; with the window disabled it is collected —
    the reference's (unsafe) behavior, still available explicitly."""
    yaml_path, disks = cluster
    data = b"staged-but-not-yet-published"
    name = "sha256-" + hashlib.sha256(data).hexdigest()
    path = os.path.join(disks[0], name)
    with open(path, "wb") as f:
        f.write(data)

    async def run() -> None:
        config = await Config.load_or_default(None)
        await find_unused_hashes(config, _gc_args(yaml_path, disks))
        assert os.path.exists(path)  # shielded by the grace window
        await find_unused_hashes(
            config, _gc_args(yaml_path, disks, grace_seconds=0.0))
        assert not os.path.exists(path)  # explicit opt-out collects it

    asyncio.run(run())


def test_delete_time_recheck_spares_rewritten_chunk(cluster):
    """A chunk listed as an orphan but re-written (same content hash =>
    same path) before the delete fires must be spared: the delete-time
    age re-check sees the fresh mtime."""
    yaml_path, disks = cluster
    data = b"dedup-rewrite-target"
    name = "sha256-" + hashlib.sha256(data).hexdigest()
    path = os.path.join(disks[0], name)
    with open(path, "wb") as f:
        f.write(data)
    old = time.time() - 3600
    os.utime(path, (old, old))

    real_stat = os.stat
    bumped = {"done": False}

    def stat_with_rewrite(p, *a, **kw):
        # first age check passes (old mtime); then simulate the
        # concurrent re-write by freshening the file before the
        # delete-time re-check runs
        st = real_stat(p, *a, **kw)
        if p == path and not bumped["done"]:
            bumped["done"] = True
            os.utime(path, None)
        return st

    async def run() -> None:
        config = await Config.load_or_default(None)
        import unittest.mock as mock
        with mock.patch("chunky_bits_tpu.cli.main.os.stat",
                        side_effect=stat_with_rewrite):
            await find_unused_hashes(
                config, _gc_args(yaml_path, disks))

    asyncio.run(run())
    assert os.path.exists(path)


def test_stale_publish_temps_reaped_live_ones_spared(cluster):
    """A crashed writer's '<name>.tmp.<pid>.<hex>' file is reclaimed
    once aged past the grace window; a live writer's fresh temp — and
    non-matching unknown names — are left alone."""
    yaml_path, disks = cluster
    stale = os.path.join(disks[0], "sha256-" + "a" * 64 + ".tmp.1234.deadbeef")
    live = os.path.join(disks[1], "sha256-" + "b" * 64 + ".tmp.5678.cafebabe")
    unknown = os.path.join(disks[2], "notes.txt")
    for p in (stale, live, unknown):
        with open(p, "wb") as f:
            f.write(b"x")
    old = time.time() - 3600
    os.utime(stale, (old, old))
    os.utime(unknown, (old, old))

    async def run() -> None:
        config = await Config.load_or_default(None)
        await find_unused_hashes(config, _gc_args(yaml_path, disks))

    asyncio.run(run())
    assert not os.path.exists(stale)
    assert os.path.exists(live)
    assert os.path.exists(unknown)


def test_temp_predicate_matches_producer():
    """The GC's temp predicate and the publisher's naming can't drift:
    a name generated by the producer must match the predicate."""
    from chunky_bits_tpu.file.location import (is_publish_temp,
                                               publish_temp_name)

    name = publish_temp_name("/x/sha256-" + "a" * 64)
    assert is_publish_temp(os.path.basename(name))
    assert not is_publish_temp("sha256-" + "a" * 64)
    assert not is_publish_temp("notes.txt")
