"""Cross-backend byte-identity: numpy vs native C++ vs JAX bit-plane.

This is the analogue of the reference CI's sha256 encode-decode identity job
(.github/workflows/compile.yml) applied at the codec boundary: all backends
must produce identical shards for identical inputs.
"""

import numpy as np
import pytest

from chunky_bits_tpu.ops.backend import ErasureCoder, NumpyBackend, get_backend


def _backends():
    out = [NumpyBackend()]
    try:
        out.append(get_backend("native"))
    except Exception as err:  # pragma: no cover - build env missing g++
        pytest.skip(f"native backend unavailable: {err}")
    out.append(get_backend("jax"))
    return out


@pytest.mark.parametrize("d,p", [(1, 2), (3, 2), (10, 4), (20, 6)])
def test_encode_identity_across_backends(d, p):
    rng = np.random.default_rng(42)
    data = rng.integers(0, 256, (3, d, 1000)).astype(np.uint8)
    results = []
    for be in _backends():
        coder = ErasureCoder(d, p, be)
        results.append((be.name, coder.encode_batch(data)))
    ref_name, ref = results[0]
    for name, got in results[1:]:
        assert np.array_equal(ref, got), f"{name} != {ref_name}"


@pytest.mark.parametrize("d,p", [(3, 2), (10, 4)])
def test_reconstruct_identity_across_backends(d, p):
    rng = np.random.default_rng(7)
    data = rng.integers(0, 256, (2, d, 513)).astype(np.uint8)
    numpy_coder = ErasureCoder(d, p, NumpyBackend())
    parity = numpy_coder.encode_batch(data)
    full = np.concatenate([data, parity], axis=1)
    erased = list(rng.choice(d + p, size=p, replace=False).astype(int))
    present = [i for i in range(d + p) if i not in erased]
    for be in _backends():
        coder = ErasureCoder(d, p, be)
        rebuilt = coder.reconstruct_batch(full, present, erased)
        for row, idx in zip(np.moveaxis(rebuilt, 1, 0), erased):
            assert np.array_equal(row, full[:, idx, :]), (be.name, idx)


def test_native_large_batch_threads():
    try:
        be = get_backend("native")
    except Exception as err:  # pragma: no cover
        pytest.skip(f"native backend unavailable: {err}")
    rng = np.random.default_rng(3)
    data = rng.integers(0, 256, (64, 3, 4096)).astype(np.uint8)
    got = ErasureCoder(3, 2, be).encode_batch(data)
    want = ErasureCoder(3, 2, NumpyBackend()).encode_batch(data)
    assert np.array_equal(got, want)


def test_native_thread_knob_spec():
    """'native:N' caps the C++ engine's host threads (the cluster.yaml
    tunables surface for shared hosts); results stay byte-identical and
    bad specs fail with a clear message."""
    from chunky_bits_tpu.errors import ErasureError

    try:
        be2 = get_backend("native:2")
    except ErasureError:
        raise
    except Exception as err:  # pragma: no cover
        pytest.skip(f"native backend unavailable: {err}")
    assert be2.name == "native:2"
    assert be2.nthreads == 2
    assert get_backend("native:2") is be2  # registry round-trip
    assert get_backend("native").nthreads == 0  # plain spelling: auto

    d, p = 5, 3
    rng = np.random.default_rng(21)
    data = rng.integers(0, 256, (7, d, 2048), dtype=np.uint8)
    want = ErasureCoder(d, p, NumpyBackend()).encode_batch(data)
    assert np.array_equal(ErasureCoder(d, p, be2).encode_batch(data), want)
    parity, digests = ErasureCoder(d, p, be2).encode_hash_batch(data)
    assert np.array_equal(parity, want)
    import hashlib
    assert digests[3, 1].tobytes() == hashlib.sha256(data[3, 1]).digest()

    for bad in ("native:", "native:0", "native:-2", "native:x"):
        with pytest.raises(ErasureError, match="thread count"):
            get_backend(bad)


@pytest.mark.parametrize("s", [1, 31, 32, 33, 63, 64, 65, 127, 128, 129,
                               4095, 4096, 4097, 32768, 32769, 70000])
def test_native_vector_width_boundaries(s):
    """Shard sizes straddling the SIMD vector widths (32 B AVX2, 64 B
    GFNI/AVX-512 and the SHA block) and the 32 KiB fusion block must
    agree with the oracles exactly, on both the pure encode path and
    the block-interleaved encode+hash path (streaming SHA cursor:
    sub-64-byte tails, multi-range accumulation, blockless final
    range)."""
    import hashlib

    try:
        be = get_backend("native")
    except Exception as err:  # pragma: no cover
        pytest.skip(f"native backend unavailable: {err}")
    d, p = 5, 3
    rng = np.random.default_rng(s)
    data = rng.integers(0, 256, (3, d, s), dtype=np.uint8)
    want = ErasureCoder(d, p, NumpyBackend()).encode_batch(data)
    coder = ErasureCoder(d, p, be)
    assert np.array_equal(coder.encode_batch(data), want)
    parity, digests = coder.encode_hash_batch(data)
    assert np.array_equal(parity, want)
    for bi in range(data.shape[0]):
        for j in range(d):
            assert digests[bi, j].tobytes() == \
                hashlib.sha256(data[bi, j]).digest(), (bi, j)
        for j in range(p):
            assert digests[bi, d + j].tobytes() == \
                hashlib.sha256(want[bi, j]).digest(), (bi, j)
