"""Unit tests for the async byte substrate (utils/aio.py) — focused on
``read_exact_into``, the zero-restage ingest primitive the writer's
staging block depends on (readinto fast path, read() fallback, partial
fills, EOF)."""

import asyncio

import numpy as np
import pytest

from chunky_bits_tpu.utils import aio


class DribbleReader:
    """read()-only reader serving at most ``step`` bytes per call."""

    def __init__(self, data: bytes, step: int):
        self._data = data
        self._off = 0
        self._step = step
        self.calls = 0

    async def read(self, n: int = -1) -> bytes:
        self.calls += 1
        if self._off >= len(self._data):
            return b""
        n = min(n if n >= 0 else self._step, self._step,
                len(self._data) - self._off)
        out = self._data[self._off:self._off + n]
        self._off += n
        return out


class DribbleIntoReader(DribbleReader):
    """readinto-capable variant with the same dribble behavior."""

    async def readinto(self, mem: memoryview) -> int:
        self.calls += 1
        if self._off >= len(self._data):
            return 0
        n = min(len(mem), self._step, len(self._data) - self._off)
        mem[:n] = self._data[self._off:self._off + n]
        self._off += n
        return n


@pytest.mark.parametrize("cls", [DribbleReader, DribbleIntoReader])
@pytest.mark.parametrize("step", [1, 7, 64, 1000])
def test_read_exact_into_fills_exactly(cls, step):
    data = bytes(range(256)) * 4  # 1024 bytes
    buf = np.zeros(600, dtype=np.uint8)

    async def main():
        reader = cls(data, step)
        got = await aio.read_exact_into(reader, memoryview(buf))
        assert got == 600
        assert buf.tobytes() == data[:600]
        # second fill continues from where the reader left off
        buf2 = np.zeros(600, dtype=np.uint8)
        got = await aio.read_exact_into(reader, memoryview(buf2))
        assert got == len(data) - 600  # EOF short fill
        assert buf2.tobytes()[:got] == data[600:]
        # at EOF: zero filled
        assert await aio.read_exact_into(reader, memoryview(buf2)) == 0

    asyncio.run(main())


def test_read_exact_into_prefers_readinto():
    data = b"x" * 100

    async def main():
        reader = DribbleIntoReader(data, 1000)
        buf = np.zeros(100, dtype=np.uint8)
        await aio.read_exact_into(reader, memoryview(buf))
        assert buf.tobytes() == data

    asyncio.run(main())


def test_builtin_readers_readinto():
    """BytesReader and FileReader expose the zero-copy path."""

    async def main():
        data = bytes(range(200))
        buf = np.zeros(200, dtype=np.uint8)
        r = aio.BytesReader(data)
        assert await aio.read_exact_into(r, memoryview(buf)) == 200
        assert buf.tobytes() == data

    asyncio.run(main())


def test_file_reader_readinto(tmp_path):
    async def main():
        data = bytes(range(256)) * 3
        path = tmp_path / "f.bin"
        path.write_bytes(data)
        r = aio.FileReader(str(path), offset=100)
        buf = np.zeros(500, dtype=np.uint8)
        assert await aio.read_exact_into(r, memoryview(buf)) == 500
        assert buf.tobytes() == data[100:600]
        await r.close()

    asyncio.run(main())


def test_file_reader_view_parts(tmp_path, monkeypatch):
    """Zero-copy staging views: whole parts served as mmap views that
    advance the stream position, interleaving cleanly with readinto for
    the tail."""
    monkeypatch.delenv("CHUNKY_BITS_TPU_NO_MMAP", raising=False)
    part = 96
    data = bytes(range(256)) * 2  # 512 bytes = 5 parts + 32-byte tail

    async def main():
        path = tmp_path / "f.bin"
        path.write_bytes(data)
        r = aio.FileReader(str(path))
        mv = await r.view_parts(part, 3)
        assert mv is not None and len(mv) == 3 * part
        assert bytes(mv) == data[:3 * part]
        # view is zero-copy: frombuffer aliases the page cache
        arr = np.frombuffer(mv, dtype=np.uint8)
        assert not arr.flags.writeable
        mv2 = await r.view_parts(part, 3)
        assert len(mv2) == 2 * part  # only 2 full parts remain
        assert bytes(mv2) == data[3 * part:5 * part]
        assert await r.view_parts(part, 3) is None  # tail < one part
        buf = np.zeros(64, dtype=np.uint8)
        got = await aio.read_exact_into(r, memoryview(buf))
        assert got == 32  # the tail, exactly where the views left off
        assert buf[:32].tobytes() == data[5 * part:]
        await r.close()

    asyncio.run(main())


def test_file_reader_view_parts_offset_and_unmappable(tmp_path,
                                                      monkeypatch):
    monkeypatch.delenv("CHUNKY_BITS_TPU_NO_MMAP", raising=False)

    async def main():
        data = bytes(range(256))
        path = tmp_path / "f.bin"
        path.write_bytes(data)
        # seeked reader: views start at the offset
        r = aio.FileReader(str(path), offset=16)
        mv = await r.view_parts(80, 8)  # 240 bytes remain = 3 full parts
        assert len(mv) == 240 and bytes(mv) == data[16:]
        await r.close()
        # empty file can't mmap: view path declines, byte path sees EOF
        empty = tmp_path / "empty.bin"
        empty.write_bytes(b"")
        r = aio.FileReader(str(empty))
        assert await r.view_parts(64, 4) is None
        assert await r.read(10) == b""
        await r.close()

    asyncio.run(main())


def test_view_parts_opt_out(tmp_path, monkeypatch):
    """CHUNKY_BITS_TPU_NO_MMAP=1 keeps every part on the readinto copy
    path (for sources subject to concurrent truncation)."""
    monkeypatch.setenv("CHUNKY_BITS_TPU_NO_MMAP", "1")

    async def main():
        data = bytes(range(256))
        path = tmp_path / "f.bin"
        path.write_bytes(data)
        r = aio.FileReader(str(path))
        assert await r.view_parts(64, 2) is None
        assert r._mm is aio.FileReader._NO_MAP
        buf = np.zeros(256, dtype=np.uint8)
        assert await aio.read_exact_into(r, memoryview(buf)) == 256
        assert buf.tobytes() == data
        await r.close()

    asyncio.run(main())


def test_iter_reader_contract():
    """IterReader: read(-1) drains to EOF as joined bytes; read(n)
    passes whole chunks through uncopied (short reads allowed) and
    splits oversized chunks via views; b'' only at EOF."""

    async def chunks():
        yield b"aaaa"
        yield memoryview(b"bbbbbbbb")
        yield b"cc"

    async def main():
        # slurp drains everything as bytes
        r = aio.IterReader(chunks())
        assert await r.read() == b"aaaabbbbbbbbcc"
        assert await r.read() == b""
        # bounded reads: pass-through, then split, then drain
        r = aio.IterReader(chunks())
        assert bytes(await r.read(100)) == b"aaaa"  # short, not padded
        first = await r.read(3)
        assert bytes(first) == b"bbb"
        assert bytes(await r.read(100)) == b"bbbbb"  # pending remainder
        # slurp after bounded reads picks up pending + rest
        r = aio.IterReader(chunks())
        head = await r.read(2)
        assert bytes(head) == b"aa"
        assert await r.read() == b"aabbbbbbbbcc"
        assert await r.read(5) == b""

    asyncio.run(main())


def test_mmap_opt_out_env_parsing(monkeypatch):
    """Standard env-flag semantics: unset/empty/0/false/no/off keep the
    mmap paths ON; truthy values opt out."""
    for val in (None, "", "0", "false", "No", "OFF"):
        if val is None:
            monkeypatch.delenv("CHUNKY_BITS_TPU_NO_MMAP", raising=False)
        else:
            monkeypatch.setenv("CHUNKY_BITS_TPU_NO_MMAP", val)
        assert not aio.mmap_opted_out(), repr(val)
    for val in ("1", "true", "yes", "anything"):
        monkeypatch.setenv("CHUNKY_BITS_TPU_NO_MMAP", val)
        assert aio.mmap_opted_out(), repr(val)


def test_open_in_thread_cancel_reaps_orphan():
    """Cancelling the awaiting task while the open hop is mid-thread
    must close the orphaned handle instead of abandoning it to GC (the
    ResourceWarning a scrub rolling restart or a cancelled hedge loser
    used to trip in tests/test_chaos.py)."""
    import threading

    gate = threading.Event()
    opened = []

    class Handle:
        closed = False

        def close(self):
            self.closed = True

    def opener():
        gate.wait(5)
        h = Handle()
        opened.append(h)
        return h

    async def main():
        task = asyncio.ensure_future(
            aio.open_in_thread(opener, lambda h: h.close()))
        await asyncio.sleep(0.05)  # park the thread on the gate
        task.cancel()
        gate.set()
        with pytest.raises(asyncio.CancelledError):
            await task
        for _ in range(200):  # the reap callback runs when the thread lands
            if opened and opened[0].closed:
                break
            await asyncio.sleep(0.01)
        assert opened and opened[0].closed

    asyncio.run(main())


def test_open_in_thread_plain_paths():
    """Uncancelled awaits hand the handle over unclosed; opener errors
    propagate (nothing to reap — a failed open owns its own cleanup)."""
    class Handle:
        closed = False

        def close(self):
            self.closed = True

    async def main():
        h = await aio.open_in_thread(Handle, lambda x: x.close())
        assert not h.closed

        def boom():
            raise FileNotFoundError("nope")

        with pytest.raises(FileNotFoundError):
            await aio.open_in_thread(boom, lambda x: x.close())

    asyncio.run(main())
