"""Unit tests for the async byte substrate (utils/aio.py) — focused on
``read_exact_into``, the zero-restage ingest primitive the writer's
staging block depends on (readinto fast path, read() fallback, partial
fills, EOF)."""

import asyncio

import numpy as np
import pytest

from chunky_bits_tpu.utils import aio


class DribbleReader:
    """read()-only reader serving at most ``step`` bytes per call."""

    def __init__(self, data: bytes, step: int):
        self._data = data
        self._off = 0
        self._step = step
        self.calls = 0

    async def read(self, n: int = -1) -> bytes:
        self.calls += 1
        if self._off >= len(self._data):
            return b""
        n = min(n if n >= 0 else self._step, self._step,
                len(self._data) - self._off)
        out = self._data[self._off:self._off + n]
        self._off += n
        return out


class DribbleIntoReader(DribbleReader):
    """readinto-capable variant with the same dribble behavior."""

    async def readinto(self, mem: memoryview) -> int:
        self.calls += 1
        if self._off >= len(self._data):
            return 0
        n = min(len(mem), self._step, len(self._data) - self._off)
        mem[:n] = self._data[self._off:self._off + n]
        self._off += n
        return n


@pytest.mark.parametrize("cls", [DribbleReader, DribbleIntoReader])
@pytest.mark.parametrize("step", [1, 7, 64, 1000])
def test_read_exact_into_fills_exactly(cls, step):
    data = bytes(range(256)) * 4  # 1024 bytes
    buf = np.zeros(600, dtype=np.uint8)

    async def main():
        reader = cls(data, step)
        got = await aio.read_exact_into(reader, memoryview(buf))
        assert got == 600
        assert buf.tobytes() == data[:600]
        # second fill continues from where the reader left off
        buf2 = np.zeros(600, dtype=np.uint8)
        got = await aio.read_exact_into(reader, memoryview(buf2))
        assert got == len(data) - 600  # EOF short fill
        assert buf2.tobytes()[:got] == data[600:]
        # at EOF: zero filled
        assert await aio.read_exact_into(reader, memoryview(buf2)) == 0

    asyncio.run(main())


def test_read_exact_into_prefers_readinto():
    data = b"x" * 100

    async def main():
        reader = DribbleIntoReader(data, 1000)
        buf = np.zeros(100, dtype=np.uint8)
        await aio.read_exact_into(reader, memoryview(buf))
        assert buf.tobytes() == data

    asyncio.run(main())


def test_builtin_readers_readinto():
    """BytesReader and FileReader expose the zero-copy path."""

    async def main():
        data = bytes(range(200))
        buf = np.zeros(200, dtype=np.uint8)
        r = aio.BytesReader(data)
        assert await aio.read_exact_into(r, memoryview(buf)) == 200
        assert buf.tobytes() == data

    asyncio.run(main())


def test_file_reader_readinto(tmp_path):
    async def main():
        data = bytes(range(256)) * 3
        path = tmp_path / "f.bin"
        path.write_bytes(data)
        r = aio.FileReader(str(path), offset=100)
        buf = np.zeros(500, dtype=np.uint8)
        assert await aio.read_exact_into(r, memoryview(buf)) == 500
        assert buf.tobytes() == data[100:600]
        await r.close()

    asyncio.run(main())
