"""Mesh erasure backend + dispatch pipeline (ISSUE 16).

The multi-device ``mesh`` backend (ops/mesh_backend.py) on conftest's
8-device virtual CPU mesh: layout planning (incl. the LANE-padding pin
for this jax build's odd-width u8 XLA quirk), byte identity against the
numpy oracle across geometries, the double-buffered feed-ahead proven
from the pipeline's own counters, and the degrade-never-hang contract
after a mid-run dispatch timeout.  The :class:`DispatchPipeline` itself
is device-agnostic and unit-tested here with plain callables.
"""

import numpy as np
import pytest

from chunky_bits_tpu.cluster import tunables
from chunky_bits_tpu.errors import DeviceDispatchTimeout
from chunky_bits_tpu.ops import matrix
from chunky_bits_tpu.ops.backend import ErasureCoder, NumpyBackend
from chunky_bits_tpu.ops.dispatch_pipeline import (
    DEFAULT_DEPTH,
    DispatchCancelled,
    DispatchPipeline,
)
from chunky_bits_tpu.ops.mesh_backend import (
    LANE,
    WIDE_STRIPE_MIN_K,
    MeshBackend,
    plan_layout,
)

rng = np.random.default_rng(16)


# ---------------------------------------------------------------- pipeline

def test_pipeline_double_buffer_window():
    """depth=2 holds at most two un-materialized dispatches: the third
    submit drains the oldest, FIFO."""
    drained = []
    pipe = DispatchPipeline(depth=2)
    entries = [pipe.submit(lambda i=i: i,
                           lambda h: drained.append(h) or h * 10)
               for i in range(4)]
    # submits 3 and 4 each forced one oldest-first materialization
    assert drained == [0, 1]
    assert pipe.inflight == 2
    assert [pipe.result(e) for e in entries] == [0, 10, 20, 30]
    assert drained == [0, 1, 2, 3]
    st = pipe.stats()
    assert st.submitted == st.completed == 4
    # the peak counts the submit being admitted (depth + 1, before the
    # drain brings the window back under the bound)
    assert st.max_inflight == 3
    assert st.submits_while_busy == 3
    assert st.cancelled == 0


def test_pipeline_depth_zero_is_serial():
    """depth=0 (the bench A/B's off leg) materializes inside submit —
    no overlap window ever exists."""
    pipe = DispatchPipeline(depth=0)
    for i in range(3):
        e = pipe.submit(lambda i=i: i, lambda h: h + 1)
        assert pipe.inflight == 0
        assert pipe.result(e) == i + 1
    st = pipe.stats()
    assert st.max_inflight <= 1
    assert st.submits_while_busy == 0


def test_pipeline_result_is_idempotent_and_out_of_order():
    pipe = DispatchPipeline(depth=4)
    a = pipe.submit(lambda: "a", lambda h: h)
    b = pipe.submit(lambda: "b", lambda h: h)
    # asking for the younger first drains the older too (FIFO bound)
    assert pipe.result(b) == "b"
    assert pipe.result(a) == "a"
    assert pipe.result(a) == "a"


def test_pipeline_cancel_drops_without_touching_handles():
    pipe = DispatchPipeline(depth=4)
    touched = []
    e = pipe.submit(lambda: "handle", lambda h: touched.append(h))
    pipe.cancel()
    assert pipe.inflight == 0
    with pytest.raises(DispatchCancelled):
        pipe.result(e)
    assert touched == []  # the dead device was never waited on
    assert pipe.stats().cancelled == 1


def test_pipeline_failure_poisons_younger_entries():
    """A failed materialization (the device died) cancels everything
    younger instead of re-paying the timeout per entry."""
    pipe = DispatchPipeline(depth=4)

    def boom(_handle):
        raise DeviceDispatchTimeout("tunnel died")

    bad = pipe.submit(lambda: None, boom)
    young = pipe.submit(lambda: None, lambda h: h)
    with pytest.raises(DeviceDispatchTimeout):
        pipe.result(bad)
    with pytest.raises(DispatchCancelled):
        pipe.result(young)
    st = pipe.stats()
    assert st.cancelled == 1 and st.completed == 0


def test_pipeline_depth_env_tunable(monkeypatch):
    monkeypatch.setenv(tunables.DISPATCH_DEPTH_ENV, "3")
    assert DispatchPipeline().depth == 3
    # 0 is a valid, meaningful setting (overlap off) — not "unset"
    monkeypatch.setenv(tunables.DISPATCH_DEPTH_ENV, "0")
    assert DispatchPipeline().depth == 0
    # malformed/negative values fall back to the default, loudly never
    for bad in ("two", "-1", "1.5"):
        monkeypatch.setenv(tunables.DISPATCH_DEPTH_ENV, bad)
        assert DispatchPipeline().depth == DEFAULT_DEPTH
    monkeypatch.delenv(tunables.DISPATCH_DEPTH_ENV)
    assert DispatchPipeline().depth == DEFAULT_DEPTH


# ------------------------------------------------------------- plan_layout

def test_plan_layout_batch_parallel_fills_dp():
    lay = plan_layout(8, 16, 10, 4096)
    assert (lay.wide, lay.dp, lay.minor, lay.pad_s) == (False, 8, 1, 0)


def test_plan_layout_dp_is_largest_divisor_at_most_batch():
    # batch 6 on 8 devices: 6 doesn't divide 8, dp falls to 4
    lay = plan_layout(8, 6, 10, 4096)
    assert lay.dp == 4 and lay.minor == 2


def test_plan_layout_wide_stripe_splits_contraction():
    lay = plan_layout(8, 2, 20, 4096)
    assert lay.wide and lay.dp == 2 and lay.minor == 4 and lay.pad_s == 0
    assert 20 % lay.minor == 0  # integral k split, no ragged psum


def test_plan_layout_narrow_stripe_never_wide():
    # k below the threshold keeps the element-wise 'sp' split even
    # when k happens to divide the minor extent
    lay = plan_layout(8, 2, 4, 4096)
    assert not lay.wide and lay.minor == 4
    assert 4 < WIDE_STRIPE_MIN_K


@pytest.mark.parametrize("s", [1, 63, 777, 4096, 4097])
def test_plan_layout_sp_slices_stay_lane_aligned(s):
    """The XLA-CPU-quirk pin (CLAUDE.md): every per-device byte slice
    of an 'sp'-sharded dispatch must be a whole multiple of LANE=64 —
    this jax build misbehaves on odd-width u8 device buffers."""
    lay = plan_layout(8, 2, 10, s)
    assert not lay.wide and lay.minor > 1
    padded = s + lay.pad_s
    assert padded % lay.minor == 0
    per_device = padded // lay.minor
    assert per_device % LANE == 0, (s, lay)
    assert lay.pad_s < lay.minor * LANE  # minimal padding only


def test_plan_layout_pure_dp_needs_no_padding():
    # when the batch covers the mesh there is no byte axis to pad
    assert plan_layout(8, 8, 10, 777).pad_s == 0


def test_plan_layout_zero_batch_is_safe():
    lay = plan_layout(8, 0, 10, 4096)
    assert lay.dp == 1


# ------------------------------------------------------------ mesh backend

@pytest.fixture(scope="module")
def mesh_be():
    return MeshBackend()


@pytest.mark.parametrize("d,p,b,s", [
    (10, 4, 16, 4096),   # batch-parallel pure 'dp'
    (10, 4, 3, 1000),    # non-divisible batch AND byte length ('sp' pad)
    (10, 4, 2, 1),       # degenerate 1-byte shards
    (20, 6, 2, 256),     # wide-stripe ('dp','tp') with the psum
    (4, 2, 5, 777),      # narrow stripe, odd everything
])
def test_mesh_identity_across_geometries(mesh_be, d, p, b, s):
    enc = matrix.build_encode_matrix(d, p)
    data = rng.integers(0, 256, (b, d, s), dtype=np.uint8)
    got = mesh_be.apply_matrix(enc[d:], data)
    want = NumpyBackend().apply_matrix(enc[d:], data)
    assert got.dtype == np.uint8 and got.shape == (b, p, s)
    assert np.array_equal(got, want)


def test_mesh_decode_with_erasures(mesh_be):
    d, p = 10, 4
    enc = matrix.build_encode_matrix(d, p)
    data = rng.integers(0, 256, (4, d, 512), dtype=np.uint8)
    parity = NumpyBackend().apply_matrix(enc[d:], data)
    present = [0, 2, 3, 4, 5, 6, 7, 8, 9, 10]
    dec = matrix.decode_matrix(enc, present, [1])
    picked = np.concatenate([data[:, :1], data[:, 2:], parity[:, :1]],
                            axis=1)
    rebuilt = mesh_be.apply_matrix(dec, picked)
    assert np.array_equal(rebuilt[:, 0], data[:, 1])


def test_mesh_encode_hash_identity(mesh_be):
    d, p = 10, 4
    data = rng.integers(0, 256, (6, d, 1024), dtype=np.uint8)
    parity, digests = ErasureCoder(d, p, mesh_be).encode_hash_batch(data)
    owant, odig = ErasureCoder(d, p, NumpyBackend()).encode_hash_batch(
        data)
    assert np.array_equal(parity, owant)
    assert np.array_equal(digests, odig)


def test_mesh_feed_ahead_counters_prove_overlap():
    """encode_hash_batches stages every batch before collecting any:
    the pipeline's own counters show >= 2 dispatches in flight."""
    be = MeshBackend(depth=2)
    d, p = 10, 4
    data = rng.integers(0, 256, (8, d, 512), dtype=np.uint8)
    coder = ErasureCoder(d, p, be)
    outs = coder.encode_hash_batches([data[:4], data[4:]])
    owant, odig = ErasureCoder(d, p, NumpyBackend()).encode_hash_batch(
        data)
    assert np.array_equal(np.concatenate([o[0] for o in outs]), owant)
    assert np.array_equal(np.concatenate([o[1] for o in outs]), odig)
    st = be.pipeline.stats()
    assert st.completed == st.submitted >= 2
    assert st.max_inflight >= 2
    assert st.submits_while_busy >= 1
    assert st.cancelled == 0


def test_mesh_depth_zero_still_identical():
    be = MeshBackend(depth=0)
    d, p = 10, 4
    enc = matrix.build_encode_matrix(d, p)
    data = rng.integers(0, 256, (4, d, 640), dtype=np.uint8)
    assert np.array_equal(be.apply_matrix(enc[d:], data),
                          NumpyBackend().apply_matrix(enc[d:], data))
    st = be.pipeline.stats()
    assert st.max_inflight <= 1 and st.submits_while_busy == 0


def test_mesh_degrade_sticky_cpu_byte_identical(monkeypatch):
    """A dispatch timeout mid-run (the tunnel dying) degrades the
    backend to the CPU fallback — loudly, once, sticky — and every
    result, including the digests of rows the block callback never
    saw, stays byte-identical."""
    be = MeshBackend()
    d, p = 10, 4
    data = rng.integers(0, 256, (4, d, 512), dtype=np.uint8)

    def dead_device(_handle):
        raise DeviceDispatchTimeout("mesh erasure dispatch timed out")

    monkeypatch.setattr(be, "_materialize", dead_device)
    owant, odig = ErasureCoder(d, p, NumpyBackend()).encode_hash_batch(
        data)
    with pytest.warns(RuntimeWarning, match="DEGRADED"):
        parity, digests = ErasureCoder(d, p, be).encode_hash_batch(data)
    assert np.array_equal(parity, owant)
    assert np.array_equal(digests, odig)  # unseen rows were reconciled
    assert be._device_dead
    # sticky: later calls go straight to CPU — the dead materializer
    # would raise again if the device were ever touched
    enc = matrix.build_encode_matrix(d, p)
    assert np.array_equal(be.apply_matrix(enc[d:], data),
                          NumpyBackend().apply_matrix(enc[d:], data))


def test_mesh_registered_backend_and_tunable():
    from chunky_bits_tpu.ops import backend as backend_mod

    be = backend_mod.get_backend("mesh")
    assert be.name == "mesh"
    assert backend_mod.get_backend("mesh") is be  # cached
    assert be.async_dispatch and be.prefers_merged_batches
    # the batching layers treat mesh as a device backend (dispatch
    # amortization on, merged groups routed through the feed-ahead)
    assert tunables.Tunables(backend="mesh").is_device_backend()
    assert not tunables.Tunables(backend="native").is_device_backend()
