"""Gateway scale-out tests: conditional GETs (ETag/304), zero-copy
sendfile streaming vs reassembly byte-identity, admission control,
the keep-alive hammer, the per-request access log, and the
multi-worker supervisor (SO_REUSEPORT fleet, respawn-on-death).

The hammer test is the sanitize leg's target: ≥200 concurrent
keep-alive clients against one worker must leak zero tasks and cross
zero planes (CI runs this file under CHUNKY_BITS_TPU_SANITIZE=1)."""

import asyncio
import os
import signal
import time

import pytest

from chunky_bits_tpu.cluster import Cluster
from chunky_bits_tpu.file.file_reference import FileReference
from chunky_bits_tpu.gateway import file_ref_etag, make_app
from chunky_bits_tpu.gateway.http import PROFILER_KEY
from chunky_bits_tpu.gateway.workers import GatewaySupervisor


def make_cluster(tmp_path, backend=None, cache_bytes=0,
                 chunk_size=16, qos=None) -> Cluster:
    dirs = []
    for i in range(5):
        d = tmp_path / f"disk{i}"
        d.mkdir(exist_ok=True)
        dirs.append(str(d))
    meta = tmp_path / "meta"
    meta.mkdir(exist_ok=True)
    tunables = {}
    if backend:
        tunables["backend"] = backend
    if cache_bytes:
        tunables["cache_bytes"] = cache_bytes
    if qos is not None:
        tunables["qos"] = qos
    return Cluster.from_obj({
        "destinations": [{"location": d} for d in dirs],
        "metadata": {"type": "path", "format": "yaml", "path": str(meta)},
        "profiles": {"default": {"data": 3, "parity": 2,
                                 "chunk_size": chunk_size}},
        "tunables": tunables,
    })


def test_etag_and_conditional_get(tmp_path):
    """ETag on GET/HEAD; If-None-Match (exact, W/-prefixed, *, lists)
    answers 304 with no body; a re-PUT changes the tag so the stale
    validator misses."""
    payload = os.urandom(100000)

    async def main():
        from aiohttp.test_utils import TestClient, TestServer

        cluster = make_cluster(tmp_path)
        app = make_app(cluster)
        async with TestClient(TestServer(app)) as client:
            assert (await client.put("/obj", data=payload)).status == 200
            resp = await client.get("/obj")
            etag = resp.headers["ETag"]
            assert etag.startswith('"') and etag.endswith('"')
            assert await resp.read() == payload
            # the tag is the file-reference hash
            ref = await cluster.get_file_ref("obj")
            assert etag == file_ref_etag(ref)
            # HEAD: same tag, Content-Length, no body
            resp = await client.head("/obj")
            assert resp.headers["ETag"] == etag
            assert int(resp.headers["Content-Length"]) == len(payload)
            # conditional hits: exact, weak-prefixed, list, wildcard
            for header in (etag, f"W/{etag}", f'"nope", {etag}', "*"):
                resp = await client.get(
                    "/obj", headers={"If-None-Match": header})
                assert resp.status == 304, header
                assert resp.headers["ETag"] == etag
                assert await resp.read() == b""
            # conditional miss streams the body
            resp = await client.get(
                "/obj", headers={"If-None-Match": '"deadbeef"'})
            assert resp.status == 200
            assert await resp.read() == payload
            # a ranged conditional hit is still 304 (RFC 9110 §13.2.2:
            # If-None-Match evaluates before Range)
            resp = await client.get(
                "/obj", headers={"If-None-Match": etag,
                                 "Range": "bytes=0-99"})
            assert resp.status == 304
            # placement changes must NOT change the tag: a resilver
            # rewrites locations for unchanged bytes, and cached
            # validators must survive it (tag = content identity only)
            from chunky_bits_tpu.file.location import Location

            moved = FileReference.from_obj(ref.to_obj())
            moved.parts[0].data[0].locations.append(
                Location.local(str(tmp_path / "disk0-replica")))
            assert file_ref_etag(moved) == etag
            # re-PUT with different bytes: new tag, old validator misses
            assert (await client.put(
                "/obj", data=os.urandom(100000))).status == 200
            resp = await client.get(
                "/obj", headers={"If-None-Match": etag})
            assert resp.status == 200
            assert resp.headers["ETag"] != etag
        await cluster.tunables.location_context().aclose()

    asyncio.run(main())


def test_416_carries_content_range(tmp_path):
    """Unsatisfiable ranges answer 416 with ``Content-Range: bytes
    */<len>`` (RFC 9110 §14.4) so clients can re-range without a probe;
    unparseable headers stay lenient (full-body 200, parse parity with
    the reference documented in gateway/http.py)."""
    payload = os.urandom(50000)

    async def main():
        from aiohttp.test_utils import TestClient, TestServer

        cluster = make_cluster(tmp_path)
        app = make_app(cluster)
        async with TestClient(TestServer(app)) as client:
            assert (await client.put("/o", data=payload)).status == 200
            for header in (f"bytes={len(payload)}-",
                           f"bytes={len(payload) + 10}-{len(payload) + 20}"):
                resp = await client.get("/o", headers={"Range": header})
                assert resp.status == 416, header
                assert resp.headers["Content-Range"] == \
                    f"bytes */{len(payload)}"
            # lenient parse parity: garbage Range is ignored, not 416
            resp = await client.get("/o", headers={"Range": "garbage"})
            assert resp.status == 200
        await cluster.tunables.location_context().aclose()

    asyncio.run(main())


@pytest.mark.parametrize("backend", ["numpy", "native", "jax"])
def test_sendfile_vs_reassembly_byte_identity(tmp_path, backend):
    """Every byte served off the sendfile fast path must equal the
    reassembly path's answer (and the original payload) for every
    backend that wrote the object — whole objects, within-chunk ranges,
    suffixes, and the padded tail chunk."""
    import numpy as np

    payload = np.random.default_rng(7).integers(
        0, 256, 3 * (1 << 16) + 12345, dtype=np.uint8).tobytes()

    async def main():
        from aiohttp.test_utils import TestClient, TestServer

        cluster = make_cluster(tmp_path, backend=backend)
        app_sf = make_app(cluster, sendfile=True)
        app_plain = make_app(cluster, sendfile=False)
        ranges = [
            None,                      # whole object (reassembly)
            "bytes=100-199",           # inside chunk 0
            "bytes=0-65535",           # exactly chunk 0
            "bytes=70000-80000",       # inside chunk 1
            f"bytes={len(payload) - 50}-",   # tail (padded chunk)
            "bytes=-77",               # suffix
        ]
        async with TestClient(TestServer(app_sf)) as client:
            assert (await client.put("/obj", data=payload)).status == 200
            got_sf = {}
            for rng in ranges:
                headers = {"Range": rng} if rng else {}
                resp = await client.get("/obj", headers=headers)
                assert resp.status in (200, 206)
                got_sf[rng] = await resp.read()
            sources = [e.source for e in
                       app_sf[PROFILER_KEY].drain_requests()
                       if e.method == "GET"]
            # at least the within-chunk ranges rode the fast path
            assert sources.count("sendfile") >= 3, sources
        async with TestClient(TestServer(app_plain)) as client:
            for rng in ranges:
                headers = {"Range": rng} if rng else {}
                resp = await client.get("/obj", headers=headers)
                assert resp.status in (200, 206)
                assert await resp.read() == got_sf[rng], rng
            assert not any(
                e.source == "sendfile" for e in
                app_plain[PROFILER_KEY].drain_requests())
        # oracle: both paths served the true bytes
        assert got_sf[None] == payload
        assert got_sf["bytes=100-199"] == payload[100:200]
        assert got_sf["bytes=70000-80000"] == payload[70000:80001]
        assert got_sf["bytes=-77"] == payload[-77:]
        await cluster.tunables.location_context().aclose()

    asyncio.run(main())


def test_sendfile_corrupt_local_chunk_falls_back(tmp_path):
    """A bit-flipped local chunk file must never be sendfile'd: the
    digest gate fails, the generic read path falls through to a healthy
    replica / reconstruction, and the client still gets true bytes."""
    payload = os.urandom(200000)

    async def main():
        from aiohttp.test_utils import TestClient, TestServer

        cluster = make_cluster(tmp_path)
        app = make_app(cluster, sendfile=True)
        async with TestClient(TestServer(app)) as client:
            assert (await client.put("/obj", data=payload)).status == 200
            ref = await cluster.get_file_ref("obj")
            victim = ref.parts[0].data[0].locations[0].target
            with open(victim, "rb") as f:
                blob = bytearray(f.read())
            blob[0] ^= 0xFF
            with open(victim, "wb") as f:
                f.write(blob)
            resp = await client.get(
                "/obj", headers={"Range": "bytes=0-999"})
            assert resp.status == 206
            assert await resp.read() == payload[:1000]
            entries = app[PROFILER_KEY].drain_requests()
            assert not any(e.source == "sendfile" for e in entries
                           if e.method == "GET")
        await cluster.tunables.location_context().aclose()

    asyncio.run(main())


def test_admission_control_sheds_excess_gets(tmp_path, monkeypatch):
    """Beyond max_concurrent_gets in-flight BODIES, full GETs get an
    immediate 503 + Retry-After — while body-free traffic (HEAD, 304
    revalidations) is still answered at the bound; slots free and the
    next read succeeds (shed, never wedge)."""
    from chunky_bits_tpu.file.reader import FileReadBuilder

    payload = os.urandom(30000)

    async def main():
        from aiohttp.test_utils import TestClient, TestServer

        # pin QoS OFF in YAML (wins over the env flag): this test
        # covers the immediate-shed admission path, which QoS-on
        # replaces with bounded per-tenant queueing
        cluster = make_cluster(tmp_path, qos={"enabled": False})
        gate = asyncio.Event()
        real_stream = FileReadBuilder.stream

        async def slow_stream(self):
            # park INSIDE the admitted body-streaming window, where a
            # slot is genuinely held
            await asyncio.wait_for(gate.wait(), timeout=10)
            async for chunk in real_stream(self):
                yield chunk

        app = make_app(cluster, max_concurrent_gets=2)
        async with TestClient(TestServer(app)) as client:
            assert (await client.put("/obj", data=payload)).status == 200
            resp = await client.get("/obj")
            etag = resp.headers["ETag"]
            assert await resp.read() == payload
            monkeypatch.setattr(FileReadBuilder, "stream", slow_stream)
            holders = [asyncio.ensure_future(client.get("/obj"))
                       for _ in range(2)]
            await asyncio.sleep(0.1)  # both slots taken, parked on gate
            shed = await client.get("/obj")
            assert shed.status == 503
            assert shed.headers["Retry-After"] == "1"
            assert "too many" in await shed.text()
            # body-free traffic is admitted even at the bound: HEAD and
            # conditional revalidation both answer, not 503
            resp = await client.head("/obj")
            assert resp.status == 200
            resp = await client.get(
                "/obj", headers={"If-None-Match": etag})
            assert resp.status == 304
            gate.set()
            resps = await asyncio.gather(*holders)
            for r in resps:
                assert r.status == 200
                assert await r.read() == payload
            # slots freed: the next read is admitted again
            monkeypatch.setattr(FileReadBuilder, "stream", real_stream)
            resp = await client.get("/obj")
            assert resp.status == 200
            assert await resp.read() == payload
        await cluster.tunables.location_context().aclose()

    asyncio.run(main())


def test_keepalive_hammer_200_clients(tmp_path):
    """≥200 concurrent keep-alive clients against ONE worker: mixed
    full/ranged/conditional traffic, every byte right, connections
    reused.  Run under CHUNKY_BITS_TPU_SANITIZE=1 (the CI leg) this
    must report 0 leaked tasks / 0 handoff violations."""
    import aiohttp

    payload = os.urandom(150000)
    n_clients = 200

    async def main():
        from aiohttp.test_utils import TestClient, TestServer

        cluster = make_cluster(tmp_path, cache_bytes=8 << 20)
        app = make_app(cluster)
        connector = aiohttp.TCPConnector(limit=n_clients)
        async with TestClient(TestServer(app),
                              connector=connector) as client:
            assert (await client.put("/hot", data=payload)).status == 200
            resp = await client.get("/hot")
            etag = resp.headers["ETag"]
            assert await resp.read() == payload

            async def one_client(i):
                # full body
                r = await client.get("/hot")
                assert r.status == 200
                assert await r.read() == payload
                # ranged
                start = (i * 613) % (len(payload) - 1000)
                r = await client.get(
                    "/hot",
                    headers={"Range": f"bytes={start}-{start + 999}"})
                assert r.status == 206
                assert await r.read() == payload[start:start + 1000]
                # conditional: zero body bytes
                r = await client.get(
                    "/hot", headers={"If-None-Match": etag})
                assert r.status == 304
                assert await r.read() == b""

            await asyncio.gather(*[one_client(i)
                                   for i in range(n_clients)])
            entries = app[PROFILER_KEY].drain_requests()
            gets = [e for e in entries if e.method == "GET"]
            assert len(gets) >= 3 * n_clients
            assert sum(1 for e in gets if e.source == "cond") \
                >= n_clients
            # the hot object is fully cached: repeat full reads are
            # cache-tagged (first-fill "store" entries allowed)
            assert any(e.source == "cache" for e in gets)
        await cluster.tunables.location_context().aclose()

    asyncio.run(main())


def test_access_log_line_and_stats(tmp_path, caplog):
    """One structured log line per request; the same records roll into
    RequestStats (the bench --config 9 percentile path)."""
    from chunky_bits_tpu.file.profiler import request_stats

    payload = os.urandom(20000)

    async def main():
        from aiohttp.test_utils import TestClient, TestServer

        cluster = make_cluster(tmp_path)
        app = make_app(cluster)
        async with TestClient(TestServer(app)) as client:
            with caplog.at_level("INFO", "chunky_bits_tpu.gateway"):
                assert (await client.put("/a", data=payload)).status \
                    == 200
                resp = await client.get("/a")
                await resp.read()
                assert (await client.get("/missing")).status == 404
                # an unroutable method is answered 405 by the router
                # (raised as HTTPException): the log must carry the
                # status the client saw, never a phantom 500
                assert (await client.post("/a", data=b"x")).status \
                    == 405
        lines = [r.message for r in caplog.records
                 if r.message.startswith("req ")]
        assert any("method=PUT" in ln and "status=200" in ln
                   for ln in lines)
        assert any("method=GET" in ln and f"bytes={len(payload)}" in ln
                   and "source=store" in ln for ln in lines)
        assert any("status=404" in ln for ln in lines)
        assert any("method=POST" in ln and "status=405" in ln
                   for ln in lines)
        assert not any("status=500" in ln for ln in lines)
        entries = app[PROFILER_KEY].drain_requests()
        assert len(entries) == 4
        stats = request_stats(entries)
        assert stats.count == 4
        assert stats.errors == 0
        assert stats.total_bytes == len(payload)
        assert stats.p50_ms <= stats.p99_ms <= stats.p999_ms
        await cluster.tunables.location_context().aclose()

    asyncio.run(main())


def test_gateway_workers_tunable(monkeypatch):
    from chunky_bits_tpu.cluster import tunables

    monkeypatch.delenv(tunables.GATEWAY_WORKERS_ENV, raising=False)
    assert tunables.gateway_workers() == 1
    for raw, want in (("4", 4), ("0", 1), ("-2", 1), ("junk", 1),
                      ("", 1)):
        monkeypatch.setenv(tunables.GATEWAY_WORKERS_ENV, raw)
        assert tunables.gateway_workers() == want, raw
    monkeypatch.delenv(tunables.GATEWAY_WORKERS_ENV)
    monkeypatch.delenv(tunables.GATEWAY_SENDFILE_ENV, raising=False)
    assert tunables.gateway_sendfile() is True
    monkeypatch.setenv(tunables.GATEWAY_SENDFILE_ENV, "0")
    assert tunables.gateway_sendfile() is False


def test_serve_honors_gateway_workers_env_default(tmp_path):
    """``serve(workers=None)`` sizes the fleet from
    ``tunables.gateway_workers`` — the CI leg that exports
    CHUNKY_BITS_TPU_GATEWAY_WORKERS=2 routes this test (and therefore
    the whole serve path) through the multi-worker supervisor; default
    legs serve single-process.  Either way one port serves PUT+GET."""
    import aiohttp

    payload = os.urandom(60000)

    async def main():
        from chunky_bits_tpu.gateway import serve

        cluster = make_cluster(tmp_path)
        ready = asyncio.Event()
        port_box = {}

        def on_ready(port):
            port_box["port"] = port
            ready.set()

        task = asyncio.ensure_future(serve(
            cluster, "127.0.0.1", 0, workers=None, on_ready=on_ready))
        try:
            await asyncio.wait_for(ready.wait(), timeout=120)
            url = f"http://127.0.0.1:{port_box['port']}"
            async with aiohttp.ClientSession() as session:
                resp = await session.put(f"{url}/obj", data=payload)
                assert resp.status == 200
                resp = await session.get(f"{url}/obj")
                assert resp.status == 200
                assert await resp.read() == payload
                assert "ETag" in resp.headers
        finally:
            task.cancel()
            await asyncio.gather(task, return_exceptions=True)
        await cluster.tunables.location_context().aclose()

    asyncio.run(main())


def test_worker_supervisor_serves_and_respawns(tmp_path):
    """The tentpole end-to-end: a 2-worker SO_REUSEPORT fleet serves
    PUT/GET through one port; SIGKILLing a worker never wedges the
    listener — the survivor keeps serving and the supervisor respawns
    the slot (new pid)."""
    import aiohttp

    payload = os.urandom(120000)

    async def main():
        cluster = make_cluster(tmp_path, cache_bytes=4 << 20)
        sup = GatewaySupervisor(cluster.to_obj(), "127.0.0.1", 0,
                                workers=2, ready_timeout=90.0)
        await sup.start()
        try:
            pids = sup.worker_pids()
            assert len(pids) == 2
            url = f"http://127.0.0.1:{sup.port}"
            async with aiohttp.ClientSession() as session:
                resp = await session.put(f"{url}/obj", data=payload)
                assert resp.status == 200
                # hit the fleet enough times that both workers serve
                for _ in range(8):
                    resp = await session.get(f"{url}/obj")
                    assert resp.status == 200
                    assert await resp.read() == payload
                etag = resp.headers["ETag"]
                resp = await session.get(
                    f"{url}/obj", headers={"If-None-Match": etag})
                assert resp.status == 304

                # kill one worker: listener must survive + slot respawn
                os.kill(pids[0], signal.SIGKILL)
                deadline = time.monotonic() + 60
                while time.monotonic() < deadline:
                    live = sup.worker_pids()
                    if len(live) == 2 and pids[0] not in live:
                        break
                    await asyncio.sleep(0.25)
                live = sup.worker_pids()
                assert len(live) == 2 and pids[0] not in live, live

                # the respawned fleet serves (a request racing the kill
                # may hit a torn connection once; retry is the client
                # contract a 503/ECONNRESET implies)
                for attempt in range(10):
                    try:
                        resp = await session.get(f"{url}/obj")
                        if resp.status == 200:
                            assert await resp.read() == payload
                            break
                    except aiohttp.ClientError:
                        pass
                    await asyncio.sleep(0.2)
                else:
                    raise AssertionError(
                        "fleet never recovered after worker death")
        finally:
            await sup.stop()
        assert sup.worker_pids() == []
        await cluster.tunables.location_context().aclose()

    asyncio.run(main())
