"""Indexed metadata plane (cluster/meta_log.py): the append-only
namespace log + compacting index behind the ``MetadataStore`` surface.

Covers the store protocol (append/read/list/tombstone/compact with the
journal-committed index), the flock'd cross-instance and cross-process
append discipline, torn-tail recovery, generation fencing against the
cluster's file-ref LRU, the index projection fast paths
(``namespace_nodes``/``namespace_hashes`` + their fall-back-on-absence
contract), and the O(index) no-dirent listing claim — asserted by
counting dirent syscalls, not by timing.  Crash-mode durability is the
crash harness's job (sim/crash.py ``meta_log_append``/
``meta_log_compact``, tests/test_crash.py); byte identity across stores
is the golden ``meta_log_placement`` fixture's job.
"""

import asyncio
import json
import os
import subprocess
import sys
import threading

import pytest

from chunky_bits_tpu.cluster import meta_log
from chunky_bits_tpu.cluster.meta_log import (
    MetadataLog,
    MetaLogStore,
    norm_name,
)
from chunky_bits_tpu.errors import MetadataReadError


def store_at(tmp_path, name="m", **kwargs) -> MetaLogStore:
    return MetaLogStore(str(tmp_path / name), **kwargs)


# ---- name canonicalization ----

def test_norm_name_strips_traversal_and_empty_components():
    assert norm_name("a/b/c") == "a/b/c"
    assert norm_name("/a//b/./../c/") == "a/b/c"
    assert norm_name(".") == ""
    assert norm_name("") == ""


# ---- store protocol ----

def test_append_read_list_roundtrip(tmp_path):
    store = store_at(tmp_path)
    store.append("dir/a", b"ref-a")
    store.append("dir/sub/b", b"ref-b")
    store.append("top", b"ref-top")

    assert store.read_bytes("dir/a") == b"ref-a"
    assert store.live_names() == ["dir/a", "dir/sub/b", "top"]
    assert store.prefix_names("dir") == ["dir/a", "dir/sub/b"]
    assert store.prefix_names("") == ["dir/a", "dir/sub/b", "top"]

    kind, children = store.list_children("")
    assert kind == "directory"
    assert children == [("directory", "dir"), ("file", "top")]
    kind, children = store.list_children("dir")
    assert children == [("file", "a"), ("directory", "sub")]
    assert store.list_children("top") == ("file", [])
    assert store.list_children("nope") is None


def test_missing_and_tombstoned_names_raise_enoent(tmp_path):
    store = store_at(tmp_path)
    with pytest.raises(FileNotFoundError):
        store.read_bytes("ghost")
    store.append("x", b"one")
    store.tombstone("x")
    with pytest.raises(FileNotFoundError):
        store.read_bytes("x")
    with pytest.raises(FileNotFoundError):
        store.tombstone("x")  # double delete = ENOENT, like os.remove
    # a republish after the tombstone is a fresh live entry
    store.append("x", b"two")
    assert store.read_bytes("x") == b"two"


def test_supersede_and_tombstone_mark_dead_bytes(tmp_path):
    store = store_at(tmp_path)
    store.append("a", b"x" * 100)
    assert store.dead_bytes() == 0
    store.append("a", b"y" * 40)
    assert store.dead_bytes() == 100
    store.tombstone("a")
    assert store.dead_bytes() == 140


def test_cold_reload_rebuilds_identical_index(tmp_path):
    store = store_at(tmp_path)
    store.append("a", b"ref-a")
    store.append("b/c", b"ref-c")
    store.tombstone("a")
    cold = MetaLogStore(store.root)
    assert cold.live_names() == ["b/c"]
    assert cold.read_bytes("b/c") == b"ref-c"
    assert cold.generation() == store.generation()


def test_rollover_past_log_max_bytes(tmp_path):
    store = store_at(tmp_path, log_max_bytes=64)
    for i in range(6):
        store.append(f"n{i}", bytes([65 + i]) * 40)
    assert len(store.log_files()) >= 3
    for i in range(6):
        assert store.read_bytes(f"n{i}") == bytes([65 + i]) * 40
    # read_many groups by log file and returns input order
    entries = store.entries_for([f"n{i}" for i in (4, 1, 3)])
    raw = store.read_many(entries)
    assert [name for name, _ in raw] == ["n4", "n1", "n3"]
    assert raw[0][1] == b"E" * 40


def test_compact_reclaims_drops_tombstones_and_keeps_generation(tmp_path):
    store = store_at(tmp_path)
    store.append("keep", b"k" * 50)
    store.append("dead", b"d" * 70)
    store.append("keep", b"K" * 30)  # supersede: 50 bytes dead
    store.tombstone("dead")
    gen = store.generation()
    old_logs = [store.log_path(log) for log in store.log_files()]

    report = store.compact()
    assert report == {"copied_bytes": 30, "reclaimed_bytes": 120,
                      "live_refs": 1}
    assert store.read_bytes("keep") == b"K" * 30
    assert store.dead_bytes() == 0
    # the {"o": "g"} floor record: the counter never runs backwards
    # across the journal swap, so a changes() cursor stays valid
    assert store.generation() == gen
    assert store.changes(gen) == []
    store.append("later", b"l")
    assert store.generation() == gen + 1
    # the dropped tombstone is gone from a cold reload too
    assert MetaLogStore(store.root).live_names() == ["keep", "later"]
    assert all(not os.path.exists(p) for p in old_logs)


def test_changes_feed_is_bounded_and_generation_ordered(tmp_path):
    store = store_at(tmp_path)
    for i in range(5):
        store.append(f"n{i}", b"x")
    store.tombstone("n2")
    rows = store.changes(0)
    assert [r.generation for r in rows] == sorted(
        r.generation for r in rows)
    # the index is compacting: n2 shows only its LATEST state
    assert [(r.name, r.tombstone) for r in rows if r.name == "n2"] \
        == [("n2", True)]
    assert len(store.changes(0, limit=2)) == 2
    cursor = rows[-1].generation
    assert store.changes(cursor) == []
    store.append("n9", b"y")
    assert [r.name for r in store.changes(cursor)] == ["n9"]


# ---- torn tails and concurrent appenders ----

def test_torn_journal_tail_ignored_and_terminated(tmp_path):
    store = store_at(tmp_path)
    store.append("a", b"ref-a")
    store.append("b", b"ref-b")
    with open(store.journal_path(), "ab") as f:
        f.write(b'{"o":"p","n":"torn","g":9')  # crashed writer, no \n

    cold = MetaLogStore(store.root)
    assert cold.live_names() == ["a", "b"]
    # the next append terminates the fragment instead of merging into
    # it, and every instance converges on the same three names
    cold.append("c", b"ref-c")
    assert cold.live_names() == ["a", "b", "c"]
    assert MetaLogStore(store.root).live_names() == ["a", "b", "c"]
    assert store.live_names() == ["a", "b", "c"]


def test_foreign_garbage_journal_line_is_skipped(tmp_path):
    store = store_at(tmp_path)
    store.append("a", b"ref-a")
    with open(store.journal_path(), "ab") as f:
        f.write(b"not json at all\n")
    store.append("b", b"ref-b")
    assert MetaLogStore(store.root).live_names() == ["a", "b"]


def test_concurrent_appends_from_two_instances(tmp_path):
    """Two store instances over one root (the cross-process shape in
    miniature): flock-serialized appends from concurrent threads all
    publish, and both indexes converge."""
    root = str(tmp_path / "m")
    a, b = MetaLogStore(root), MetaLogStore(root)
    errors = []

    def writer(store, prefix):
        try:
            for i in range(20):
                store.append(f"{prefix}/{i:02d}",
                             f"{prefix}{i}".encode() * 10)
        except Exception as err:  # noqa: BLE001 — surfaced via errors
            errors.append(err)

    threads = [threading.Thread(target=writer, args=(a, "a"), daemon=True),
               threading.Thread(target=writer, args=(b, "b"), daemon=True)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert not errors, errors
    assert len(a.live_names()) == 40
    assert len(b.live_names()) == 40
    assert a.read_bytes("b/07") == b"b7" * 10
    # generations are unique: no two publishes ever shared one
    gens = [r.generation for r in a.changes(0, limit=100)]
    assert len(gens) == len(set(gens)) == 40


def test_cross_process_append_is_flock_serialized(tmp_path):
    """A real second PROCESS appends through its own store instance;
    the parent observes the publishes on its next (refreshing) read."""
    root = str(tmp_path / "m")
    parent = MetaLogStore(root)
    parent.append("parent/0", b"from-parent")
    script = (
        "from chunky_bits_tpu.cluster.meta_log import MetaLogStore\n"
        f"store = MetaLogStore({root!r})\n"
        "for i in range(10):\n"
        "    store.append(f'child/{i}', b'from-child-%d' % i)\n"
        "print(len(store.live_names()))\n"
    )
    result = subprocess.run(
        [sys.executable, "-c", script], capture_output=True, text=True,
        timeout=60, env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert result.returncode == 0, result.stderr
    assert result.stdout.strip() == "11"
    assert len(parent.live_names()) == 11
    assert parent.read_bytes("child/7") == b"from-child-7"


# ---- the O(index) claim: syscalls, not timing ----

def test_namespace_questions_touch_no_dirents(tmp_path, monkeypatch):
    """list/prefix/index scans over a warm store must be pure index
    reads: zero listdir/scandir calls (the path store pays one dirent
    walk per directory; this store's whole point is not to)."""
    store = store_at(tmp_path)
    for i in range(50):
        store.append(f"d{i % 5}/n{i:02d}", b"x" * 20,
                     hashes=[f"sha256-{i:064d}"],
                     nodes=[["local", f"/n{i % 3}"]])
    store.generation()  # warm the index under the real functions

    calls = {"n": 0}

    def counting(real):
        def inner(*a, **kw):
            calls["n"] += 1
            return real(*a, **kw)
        return inner

    monkeypatch.setattr(os, "listdir", counting(os.listdir))
    monkeypatch.setattr(os, "scandir", counting(os.scandir))

    assert len(store.live_names()) == 50
    assert len(store.prefix_names("d3")) == 10
    assert store.list_children("d0")[0] == "directory"
    assert len(store.index_meta()) == 50
    assert store.changes(0, limit=10)
    assert calls["n"] == 0


# ---- index projection fast paths ----

def test_projection_roundtrips_append_compact_and_cold_reload(tmp_path):
    store = store_at(tmp_path)
    store.append("a", b"ref-a", hashes=["sha256-" + "0" * 64],
                 nodes=[["local", "/d0"], ["http", "node:8080"]])
    rows = store.index_meta()
    assert rows == [("a", ("sha256-" + "0" * 64,),
                     (("local", "/d0"), ("http", "node:8080")))]
    store.compact()
    assert store.index_meta() == rows
    assert MetaLogStore(store.root).index_meta() == rows


def test_extract_index_meta_from_golden_ref():
    """The publish-time extractor against a real frozen ref: every
    chunk hash in display form, every replica's health node key."""
    import yaml

    from tests.golden import generate as gen

    with open(os.path.join(gen.GOLDEN_DIR,
                           "cluster_placement.yaml")) as f:
        payload = yaml.safe_load(f)
    hashes, nodes = meta_log.extract_index_meta(payload)
    want = [f"sha256-{c['sha256']}"
            for part in payload["parts"]
            for c in part["data"] + part["parity"]]
    assert hashes == want
    assert nodes and all(kind == "local" for kind, _node in nodes)
    # anything that does not parse as a file reference projects to None
    assert meta_log.extract_index_meta({"foreign": 1}) == (None, None)
    assert meta_log.extract_index_meta(None) == (None, None)


def test_namespace_fast_paths_fall_back_on_missing_projection(tmp_path):
    """One projection-less live entry poisons the whole fast path to
    None (scoring/liveness must never be silently partial); deleting
    it restores the index answer."""
    metadata = MetadataLog(path=str(tmp_path / "m"))
    store = metadata.store
    store.append("a", b"x", hashes=["sha256-" + "1" * 64],
                 nodes=[["local", "/d0"]])

    async def scan():
        return (await metadata.namespace_nodes(),
                await metadata.namespace_hashes())

    nodes, hashes = asyncio.run(scan())
    assert nodes == [("a", (("local", "/d0"),))]
    assert hashes == [("a", ("sha256-" + "1" * 64,))]

    store.append("foreign", b"y")  # no projection
    nodes, hashes = asyncio.run(scan())
    assert nodes is None and hashes is None

    store.tombstone("foreign")
    nodes, hashes = asyncio.run(scan())
    assert nodes is not None and hashes is not None


# ---- the async MetadataStore surface ----

def test_metadata_log_store_contract(tmp_path):
    from chunky_bits_tpu.cluster.metadata import metadata_from_obj

    metadata = metadata_from_obj({"type": "meta-log", "format": "json",
                                  "path": str(tmp_path / "m")})
    assert isinstance(metadata, MetadataLog)
    assert metadata.to_obj() == {"type": "meta-log", "format": "json",
                                 "path": str(tmp_path / "m")}

    async def roundtrip():
        await metadata.write("dir/a", {"k": 1})
        await metadata.write("dir/b", {"k": 2})
        assert await metadata.read("dir/a") == {"k": 1}
        listed = await metadata.list("dir")
        assert [(e.kind, e.path) for e in listed] \
            == [("directory", "dir"), ("file", "dir/a"),
                ("file", "dir/b")]
        assert await metadata.list_files_recursive() == ["dir/a", "dir/b"]
        # read_objs: input order, unknown names skipped, one batch
        objs = await metadata.read_objs(["dir/b", "ghost", "dir/a"])
        assert objs == [("dir/b", {"k": 2}), ("dir/a", {"k": 1})]
        await metadata.delete("dir/a")
        with pytest.raises(MetadataReadError):
            await metadata.read("dir/a")
        snap = await metadata.namespace_snapshot()
        assert snap == [("dir/b", {"k": 2})]

    asyncio.run(roundtrip())


def test_env_override_rebuilds_path_stores(tmp_path, monkeypatch):
    from chunky_bits_tpu.cluster import tunables
    from chunky_bits_tpu.cluster.metadata import (
        MetadataPath,
        metadata_from_obj,
    )

    spec = {"type": "path", "path": str(tmp_path / "m")}
    monkeypatch.setenv(tunables.METADATA_KIND_ENV, "meta-log")
    assert isinstance(metadata_from_obj(dict(spec)), MetadataLog)
    # put_script stores silently stay path: the log has no write hook
    assert isinstance(
        metadata_from_obj(dict(spec, put_script="true")), MetadataPath)
    # anything but the shipped override value reads as no override
    monkeypatch.setenv(tunables.METADATA_KIND_ENV, "bogus")
    assert isinstance(metadata_from_obj(dict(spec)), MetadataPath)


def test_generation_fencing_vs_file_ref_lru(tmp_path):
    """The cluster's parsed-ref LRU (cache_bytes on) must never serve
    a ref superseded through the meta-log store: the write path's
    generation bump evicts, and a second cluster over the same root
    sees the new ref through the store's own journal refresh."""
    from chunky_bits_tpu.cluster.cluster import Cluster
    from chunky_bits_tpu.utils import aio

    for i in range(3):
        os.makedirs(tmp_path / f"ssd{i}")
    spec = {
        "destinations": {"ssd": [{"location": str(tmp_path / f"ssd{i}")}
                                 for i in range(3)]},
        "metadata": {"type": "meta-log", "format": "yaml",
                     "path": str(tmp_path / "meta")},
        "profiles": {"default": {
            "data": 2, "parity": 1, "chunk_size": 12,
            "rules": {"ssd": {"minimum": 0, "maximum": None,
                              "ideal": 3}}}},
        "tunables": {"cache_bytes": 1 << 20},
    }

    async def run():
        writer = Cluster.from_obj(spec)
        reader = Cluster.from_obj(spec)
        await writer.write_file("f", aio.BytesReader(b"one" * 400),
                                writer.get_profile())
        first = await reader.get_file_ref("f")
        assert await reader.get_file_ref("f") is first  # LRU hit
        await writer.write_file("f", aio.BytesReader(b"two" * 500),
                                writer.get_profile())
        # the writer's own cache was fenced by the generation bump
        assert await writer.get_file_ref("f") is not first
        # the reader cluster still holds the stale parse (its LRU was
        # never fenced — same behavior as the path store); dropping the
        # cached entry forces a store read, which must see the new
        # journal tail another PROCESS-shaped instance appended
        reader._file_refs.clear()
        reader._file_ref_gen += 1
        fresh = await reader.get_file_ref("f")
        assert fresh.length == 1500
        stream = await reader.read_file("f")
        chunks = []
        while True:
            piece = await stream.read(1 << 16)
            if not piece:
                break
            chunks.append(piece)
        assert b"".join(chunks) == b"two" * 500

    asyncio.run(run())
