"""Multi-tenant QoS tests (cluster/qos.py + gateway/qos.py + the
gateway wiring).

Four layers, matching the QoS plane's pieces:

* **config + resolution** — the closed tenant table: loud YAML
  validation, exact-key > longest-prefix > ``other`` resolution, the
  10k-distinct-key hammer that proves the tenant label set can never
  grow past the configured names + ``other`` (CB107 by construction);
* **the scheduler** — DRR rotation (a weighted victim interleaves with
  an antagonist backlog instead of queueing behind it), read>write
  priority gating, per-tenant rate buckets (virtual-time), queue-full
  and wait-deadline shedding, pressure, and the SLO-aware hedge
  advisor;
* **downstream hooks** — the scoreboard hedge gate (denied launches
  consume NO budget token) and the scrub bucket's pressure-scaled
  accrual with its degrade-never-hang floor;
* **the gateway** — tenant resolution into the access log and
  ``request_stats`` split, per-tenant ``cb_qos_*`` families on
  /metrics, the /stats qos stanza, the derived Retry-After, and the
  zero-overhead-off default (no qos modules imported, no qos label
  sets minted).
"""

import asyncio
import os
import sys

import pytest

from chunky_bits_tpu.cluster import tunables as tunables_mod
from chunky_bits_tpu.cluster.health import HealthScoreboard
from chunky_bits_tpu.cluster.qos import (
    MAX_TENANTS,
    OTHER,
    QosConfig,
    QosScheduler,
    QosShedError,
)
from chunky_bits_tpu.cluster.scrub import TokenBucket
from chunky_bits_tpu.errors import SerdeError
from chunky_bits_tpu.obs import metrics as obs_metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---- config + resolution ----

def test_config_parses_and_round_trips():
    obj = {
        "enabled": True,
        "tenants": {
            "gold": {"weight": 4, "keys": ["k-gold"],
                     "prefixes": ["/gold/"]},
            "bulk": {"rate_bytes_per_sec": 1e6,
                     "prefixes": ["/bulk/"]},
        },
        "other": {"weight": 2},
    }
    config = QosConfig.from_obj(obj)
    assert config.enabled is True
    assert config.other_weight == 2.0
    assert config.tenant_names() == ("gold", "bulk", OTHER)
    assert QosConfig.from_obj(config.to_obj()) == config


def test_config_validation_is_loud():
    with pytest.raises(ValueError, match="unknown keys"):
        QosConfig.from_obj({"tenats": {}})
    with pytest.raises(ValueError, match="unknown keys"):
        QosConfig.from_obj({"tenants": {"a": {"wait": 1}}})
    with pytest.raises(ValueError, match="weight"):
        QosConfig.from_obj({"tenants": {"a": {"weight": 0}}})
    with pytest.raises(ValueError, match="rate_bytes_per_sec"):
        QosConfig.from_obj(
            {"tenants": {"a": {"rate_bytes_per_sec": -1}}})
    with pytest.raises(ValueError, match="reserved"):
        QosConfig.from_obj({"tenants": {OTHER: {}}})
    with pytest.raises(ValueError, match="claimed by both"):
        QosConfig.from_obj({"tenants": {"a": {"keys": ["k"]},
                                        "b": {"keys": ["k"]}}})
    with pytest.raises(ValueError, match="MAX_TENANTS"):
        QosConfig.from_obj({"tenants": {
            f"t{i}": {} for i in range(MAX_TENANTS + 1)}})
    with pytest.raises(ValueError, match="enabled"):
        QosConfig.from_obj({"enabled": "yes"})


def test_resolution_key_beats_prefix_longest_prefix_wins():
    config = QosConfig.from_obj({"tenants": {
        "a": {"keys": ["key-a"], "prefixes": ["/data/"]},
        "b": {"prefixes": ["/data/hot/"]},
    }})
    # exact API key wins even when the path matches another tenant
    assert config.resolve("key-a", "/data/hot/x") == "a"
    # no key: longest matching prefix
    assert config.resolve(None, "/data/hot/x") == "b"
    assert config.resolve(None, "/data/cold/x") == "a"
    # missing key + unmatched path -> other; unknown key ignored
    assert config.resolve(None, "/elsewhere") == OTHER
    assert config.resolve("key-unknown", "/elsewhere") == OTHER


def test_distinct_key_hammer_never_mints_tenants():
    """10k distinct API keys all land in ``other``: the tenant label
    set stays CLOSED (the configured names + other), far under the
    registry's MAX_LABEL_SETS ceiling."""
    config = QosConfig.from_obj(
        {"tenants": {"gold": {"keys": ["k-gold"]}}})
    seen = {config.resolve(f"rotating-{i}", f"/spray/{i}")
            for i in range(10_000)}
    assert seen == {OTHER}

    async def hammer():
        sched = QosScheduler(config, read_capacity=4096,
                             write_capacity=8)
        for i in range(10_000):
            tenant = config.resolve(f"rotating-{i}", "/x")
            await sched.acquire("read", tenant, cost=10)
            sched.release("read")
        return sched.stats()

    stats = asyncio.run(hammer())
    rows = {r.tenant for r in stats.rows}
    assert rows == {"gold", OTHER}
    assert stats.to_obj()["tenants"][OTHER]["admitted"] == 10_000
    assert len(rows) <= obs_metrics.MAX_LABEL_SETS


# ---- the scheduler ----

def test_drr_interleaves_tenants_instead_of_fifo():
    """With an antagonist backlog queued first, a victim's waiters are
    granted every other rotation — never behind the whole backlog."""

    async def main():
        config = QosConfig.from_obj({"tenants": {
            "ant": {"keys": ["A"]}, "vic": {"keys": ["V"]}}})
        sched = QosScheduler(config, read_capacity=2,
                             write_capacity=1, queue_timeout_s=30)
        await sched.acquire("read", "ant", cost=100)
        await sched.acquire("read", "ant", cost=100)
        grants: list = []

        async def waiter(tenant, tag):
            await sched.acquire("read", tenant, cost=100)
            grants.append(tag)

        tasks = [asyncio.ensure_future(waiter("ant", f"a{i}"))
                 for i in range(4)]
        await asyncio.sleep(0)
        tasks += [asyncio.ensure_future(waiter("vic", f"v{i}"))
                  for i in range(2)]
        await asyncio.sleep(0)
        assert sched.queued("read") == 6
        assert sched.pressure() == 1.0
        for _ in range(6):
            sched.release("read")
            await asyncio.sleep(0)
        await asyncio.gather(*tasks)
        # FIFO would be a0 a1 a2 a3 v0 v1; DRR rotates tenants
        assert grants[:4] == ["a0", "v0", "a1", "v1"], grants

    asyncio.run(main())


def test_writes_gated_while_reads_queue():
    """Priority classes: a write grant is deferred while read waiters
    queue, and released the moment the read queue drains."""

    async def main():
        config = QosConfig.from_obj({})
        sched = QosScheduler(config, read_capacity=1,
                             write_capacity=4, queue_timeout_s=30)
        await sched.acquire("read", OTHER)

        read_granted = asyncio.Event()
        write_granted = asyncio.Event()

        async def reader():
            await sched.acquire("read", OTHER)
            read_granted.set()

        async def writer():
            await sched.acquire("write", OTHER)
            write_granted.set()

        r = asyncio.ensure_future(reader())
        await asyncio.sleep(0)
        w = asyncio.ensure_future(writer())
        for _ in range(3):
            await asyncio.sleep(0)
        # write capacity is free, but reads are queued -> gated
        assert not write_granted.is_set()
        sched.release("read")
        for _ in range(3):
            await asyncio.sleep(0)
        assert read_granted.is_set()
        assert write_granted.is_set()
        await asyncio.gather(r, w)

    asyncio.run(main())


def test_queue_full_and_deadline_shed():
    async def main():
        config = QosConfig.from_obj({})
        sched = QosScheduler(config, read_capacity=1,
                             write_capacity=1, max_queue=1,
                             queue_timeout_s=0.05)
        await sched.acquire("read", OTHER)
        waiter = asyncio.ensure_future(sched.acquire("read", OTHER))
        await asyncio.sleep(0)
        # queue full: the next arrival sheds immediately
        with pytest.raises(QosShedError, match="queue full"):
            await sched.acquire("read", OTHER)
        # the queued waiter sheds once the deadline passes (degrade,
        # never hang) — the slot is never released
        with pytest.raises(QosShedError, match="admission wait"):
            await waiter
        stats = sched.stats().to_obj()["tenants"][OTHER]
        assert stats["shed"] == 2
        assert stats["queue_peak"] == 1

    asyncio.run(main())


def test_idle_pipe_grants_oversized_waiter():
    """Work-conserving escape: a waiter whose cost out-sizes one DRR
    rotation's deficit credit must be granted the moment the pipe goes
    idle — with nothing in flight there is no future release() to run
    another grant pass, so deficit arithmetic alone would park it
    until the shed deadline (degrade-never-hang)."""
    from chunky_bits_tpu.cluster.qos import QUANTUM

    async def main():
        config = QosConfig.from_obj({})
        sched = QosScheduler(config, read_capacity=1,
                             write_capacity=1, queue_timeout_s=30.0)
        await sched.acquire("read", OTHER)
        # one rotation credits weight x QUANTUM; this cost needs ten
        waiter = asyncio.ensure_future(
            sched.acquire("read", OTHER, cost=10 * QUANTUM))
        await asyncio.sleep(0)
        sched.release("read")  # pipe now idle, waiter still queued
        await asyncio.wait_for(waiter, timeout=1.0)
        stats = sched.stats().to_obj()["tenants"][OTHER]
        assert stats["admitted"] == 2
        assert stats["shed"] == 0
        sched.release("read")

    asyncio.run(main())


def test_rate_bucket_throttles_in_virtual_time():
    """A tenant's byte-rate bucket bounds sustained throughput; the
    clock seam makes the wait virtual under the sim loop (the same
    machinery the noisy_neighbor scenario runs)."""
    from chunky_bits_tpu.sim import run as sim_run
    from chunky_bits_tpu.utils import clock as clock_mod

    async def main():
        config = QosConfig.from_obj({"tenants": {
            "bulk": {"rate_bytes_per_sec": 1000.0, "keys": ["B"]}}})
        sched = QosScheduler(config, read_capacity=64,
                             write_capacity=8)
        t0 = clock_mod.monotonic()
        # burst allowance covers the first second's worth; the rest
        # must wait for accrual: 5000 bytes at 1000 B/s >= 4 virtual s
        for _ in range(5):
            await sched.acquire("read", "bulk", cost=1000)
            sched.release("read")
        elapsed = clock_mod.monotonic() - t0
        row = sched.stats().to_obj()["tenants"]["bulk"]
        return elapsed, row

    elapsed, row = sim_run(main())
    assert elapsed >= 3.5, elapsed
    assert row["throttle_waits"] >= 3
    assert row["admitted"] == 5


def test_pressure_and_hedge_advisor():
    async def main():
        config = QosConfig.from_obj({})
        sched = QosScheduler(config, read_capacity=4,
                             write_capacity=2,
                             read_p99_objective_ms=100.0)
        assert sched.pressure() == 0.0
        assert sched.allow_hedge() is True  # no signal -> allow
        # saturate half the read capacity: pressure suppresses
        await sched.acquire("read", OTHER)
        await sched.acquire("read", OTHER)
        assert sched.pressure() == 0.5
        assert sched.allow_hedge() is False
        sched.release("read")
        sched.release("read")
        # ample p99 headroom (observed ~10ms vs 100ms objective):
        # conserve the budget
        for _ in range(32):
            sched.note_request("read", 0.010)
        assert sched.allow_hedge() is False
        # tail near the objective: spend the budget
        for _ in range(32):
            sched.note_request("read", 0.095)
        assert sched.allow_hedge() is True
        stats = sched.stats()
        assert stats.hedge_suppressed == 1
        assert stats.hedge_conserved == 1

    asyncio.run(main())


# ---- downstream hooks ----

def test_hedge_gate_denial_consumes_no_budget_token():
    board = HealthScoreboard(hedge_ms=10.0)
    # top the budget off (starts at the burst; primaries accrue it)
    for _ in range(100):
        board.note_primary()
    allowed_before = board.try_fire_hedge()
    assert allowed_before is True
    fired_before = board.stats().hedges_fired
    board.set_hedge_gate(lambda: False)
    assert board.hedge_allowed() is False
    for _ in range(10):
        assert board.try_fire_hedge() is False
    # gate-denied launches burned nothing: removing the gate fires
    # immediately from the same balance
    board.set_hedge_gate(None)
    assert board.hedge_allowed() is True
    assert board.try_fire_hedge() is True
    assert board.stats().hedges_fired == fired_before + 1


def test_token_bucket_pressure_scales_accrual():
    from chunky_bits_tpu.sim import run as sim_run
    from chunky_bits_tpu.utils import clock as clock_mod

    async def take_seconds(pressure_fn) -> float:
        bucket = TokenBucket(1000.0)
        if pressure_fn is not None:
            bucket.set_pressure(pressure_fn)
        await bucket.take(1000.0)  # burst allowance
        t0 = clock_mod.monotonic()
        await bucket.take(1000.0)  # must accrue
        return clock_mod.monotonic() - t0

    free = sim_run(take_seconds(None))
    half = sim_run(take_seconds(lambda: 0.5))
    full = sim_run(take_seconds(lambda: 1.0))
    assert 0.9 <= free <= 1.5, free
    # accrual scaled by (1 - pressure): twice as slow at 0.5
    assert 1.8 <= half <= 2.6, half
    # degrade, never hang: full pressure floors at MIN_ACCRUAL (5%),
    # it never stops accruing
    assert 18.0 <= full <= 25.0, full


# ---- tunables ----

def test_tunables_qos_mapping_round_trip_and_validation():
    obj = {"qos": {"enabled": True,
                   "tenants": {"gold": {"weight": 2}}}}
    t = tunables_mod.Tunables.from_obj(obj)
    assert t.qos["enabled"] is True
    assert t.to_obj()["qos"] == obj["qos"]
    # absent stays absent (and off by default)
    t2 = tunables_mod.Tunables.from_obj({})
    assert t2.qos == {}
    assert "qos" not in t2.to_obj()
    with pytest.raises(SerdeError, match="invalid qos mapping"):
        tunables_mod.Tunables.from_obj(
            {"qos": {"tenants": {"a": {"nope": 1}}}})


def test_qos_enabled_env_accessor(monkeypatch):
    monkeypatch.delenv(tunables_mod.QOS_ENV, raising=False)
    assert tunables_mod.qos_enabled() is False
    monkeypatch.setenv(tunables_mod.QOS_ENV, "1")
    assert tunables_mod.qos_enabled() is True
    monkeypatch.setenv(tunables_mod.QOS_ENV, "0")
    assert tunables_mod.qos_enabled() is False


# ---- the gateway ----

def _make_cluster(tmp_path, qos: dict):
    from chunky_bits_tpu.cluster import Cluster

    dirs = []
    for i in range(5):
        d = tmp_path / f"disk{i}"
        d.mkdir(exist_ok=True)
        dirs.append(str(d))
    meta = tmp_path / "meta"
    meta.mkdir(exist_ok=True)
    return Cluster.from_obj({
        "destinations": [{"location": d} for d in dirs],
        "metadata": {"type": "path", "format": "yaml",
                     "path": str(meta)},
        "profiles": {"default": {"data": 3, "parity": 2,
                                 "chunk_size": 12}},
        "tunables": {**({"qos": qos} if qos else {})},
    })


QOS_YAML = {
    "enabled": True,
    "tenants": {
        "gold": {"weight": 4, "keys": ["k-gold"]},
        "bulk": {"prefixes": ["/bulk/"]},
    },
}


def test_gateway_tenant_resolution_log_split_and_metrics(tmp_path):
    """End to end through a real app: tenants resolve from key/prefix
    into the access log, request_stats split per tenant, the /stats
    qos stanza, and the per-tenant cb_qos_* families on /metrics."""
    from aiohttp.test_utils import TestClient, TestServer

    from chunky_bits_tpu.file.profiler import (Profiler,
                                               tenant_request_stats)
    from chunky_bits_tpu.gateway import make_app

    payload = os.urandom(30000)
    profiler = Profiler()

    async def main():
        cluster = _make_cluster(tmp_path, QOS_YAML)
        app = make_app(cluster, profiler=profiler)
        async with TestClient(TestServer(app)) as client:
            r = await client.put("/bulk/obj", data=payload,
                                 headers={"X-Api-Key": "k-gold"})
            assert r.status == 200
            # key beats prefix: the PUT above was gold's
            for _ in range(2):
                r = await client.get(
                    "/bulk/obj", headers={"X-Api-Key": "k-gold"})
                assert await r.read() == payload
            r = await client.get("/bulk/obj")  # prefix -> bulk
            assert await r.read() == payload
            r = await client.get("/bulk/obj",
                                 headers={"X-Api-Key": "k-stale"})
            assert await r.read() == payload  # unknown key -> prefix
            stats = await (await client.get("/stats")).json()
            metrics = await (await client.get("/metrics")).text()
            return stats, metrics

    stats, metrics = asyncio.run(main())

    # /stats per-tenant split: same records, same percentile code
    by_tenant = stats["requests_by_tenant"]
    assert by_tenant["gold"]["count"] == 3  # 1 PUT + 2 GETs
    assert by_tenant["bulk"]["count"] == 2
    # the access-log entries themselves carry their tenant, and
    # tenant_request_stats slices them the same way
    split = tenant_request_stats(profiler.peek_requests())
    assert split["gold"].count == 3
    assert split["bulk"].count == 2
    assert OTHER in split  # the /stats+/metrics scrapes themselves
    # /stats and /metrics read the same scheduler
    qos = stats["qos"]
    assert qos["enabled"] is True
    assert set(qos["tenants"]) == {"gold", "bulk", OTHER}
    assert qos["tenants"]["gold"]["admitted"] == 3
    assert qos["tenants"]["bulk"]["admitted"] == 2
    assert 'cb_qos_admitted_total{tenant="gold"} 3' in metrics
    assert 'cb_qos_admitted_total{tenant="bulk"} 2' in metrics
    assert "cb_qos_pressure" in metrics
    assert 'qos="on"' in metrics


def test_gateway_qos_off_is_zero_overhead(tmp_path):
    """Default-off: no qos modules imported by a plain gateway, no
    qos label sets minted, /stats says enabled:false — checked in a
    clean interpreter so this suite's own qos imports cannot pollute
    the verdict."""
    import subprocess

    code = """
import asyncio, os, sys
from aiohttp.test_utils import TestClient, TestServer
from chunky_bits_tpu.cluster import Cluster
from chunky_bits_tpu.gateway import make_app

root = sys.argv[1]
dirs = []
for i in range(5):
    d = os.path.join(root, f"disk{i}")
    os.makedirs(d); dirs.append(d)
meta = os.path.join(root, "meta"); os.makedirs(meta)
cluster = Cluster.from_obj({
    "destinations": [{"location": d} for d in dirs],
    "metadata": {"type": "path", "format": "yaml", "path": meta},
    "profiles": {"default": {"data": 3, "parity": 2,
                             "chunk_size": 12}},
})

async def main():
    app = make_app(cluster)
    async with TestClient(TestServer(app)) as client:
        assert (await client.put("/x", data=b"hello")).status == 200
        r = await client.get("/x")
        assert await r.read() == b"hello"
        stats = await (await client.get("/stats")).json()
        metrics = await (await client.get("/metrics")).text()
    assert stats["qos"] == {"enabled": False}
    assert "requests_by_tenant" not in stats
    assert "cb_qos_" not in metrics
    assert 'qos="off"' in metrics

asyncio.run(main())
assert "chunky_bits_tpu.cluster.qos" not in sys.modules, "qos imported"
assert "chunky_bits_tpu.gateway.qos" not in sys.modules, "qos imported"
print("OK")
"""
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop(tunables_mod.QOS_ENV, None)
    r = subprocess.run(
        [sys.executable, "-c", code, str(tmp_path)],
        capture_output=True, timeout=120, env=env)
    assert r.returncode == 0, r.stderr.decode()[-2000:]
    assert b"OK" in r.stdout


def test_gateway_shed_has_derived_retry_after(tmp_path):
    """A shed GET's Retry-After is a positive integer (derived from
    the observed completion rate once traffic exists; the 1 s
    fallback on a cold worker)."""
    from aiohttp.test_utils import TestClient, TestServer

    from chunky_bits_tpu.gateway import make_app

    payload = os.urandom(30000)

    async def main():
        # pin QoS OFF in YAML (wins over the env flag, so the QOS=1
        # tier-1 leg still exercises the shed path this test covers)
        cluster = _make_cluster(tmp_path, {"enabled": False})
        app = make_app(cluster, max_concurrent_gets=1)
        async with TestClient(TestServer(app)) as client:
            assert (await client.put("/obj",
                                     data=payload)).status == 200
            # warm completions so the derivation has a rate window
            for _ in range(3):
                r = await client.get("/obj")
                await r.read()
            # saturate the single slot, then observe the shed
            statuses = []
            retry_after = []

            async def one():
                r = await client.get("/obj")
                statuses.append(r.status)
                if r.status == 503:
                    retry_after.append(r.headers["Retry-After"])
                await r.read()

            await asyncio.gather(*[one() for _ in range(8)])
            return statuses, retry_after

    statuses, retry_after = asyncio.run(main())
    assert 503 in statuses and 200 in statuses
    for value in retry_after:
        assert value.isdigit() and int(value) >= 1


def test_gateway_qos_write_shed_and_tenant_queueing(tmp_path):
    """With QoS on and a saturated read plane, a flood tenant's
    excess queues (bounded) while another tenant still gets served —
    the gateway-level DRR sanity check (the full isolation claim is
    sim scenario noisy_neighbor + bench --config 19)."""
    from aiohttp.test_utils import TestClient, TestServer

    from chunky_bits_tpu.gateway import make_app

    payload = os.urandom(30000)

    async def main():
        cluster = _make_cluster(tmp_path, QOS_YAML)
        app = make_app(cluster, max_concurrent_gets=2)
        async with TestClient(TestServer(app)) as client:
            assert (await client.put(
                "/bulk/obj", data=payload,
                headers={"X-Api-Key": "k-gold"})).status == 200

            async def read(key: str) -> int:
                r = await client.get(
                    "/bulk/obj",
                    headers={"X-Api-Key": key} if key else {})
                await r.read()
                return r.status

            # a burst beyond capacity: with QoS on nothing sheds (the
            # scheduler queues within its bounds) and every tenant's
            # reads land
            statuses = await asyncio.gather(
                *[read("k-gold") for _ in range(6)],
                *[read("") for _ in range(6)])
            assert statuses == [200] * 12
            stats = await (await client.get("/stats")).json()
            return stats

    stats = asyncio.run(main())
    tenants = stats["qos"]["tenants"]
    assert tenants["gold"]["admitted"] >= 6
    assert tenants["bulk"]["admitted"] >= 6
    assert tenants["gold"]["shed"] == 0
    assert tenants["bulk"]["shed"] == 0
