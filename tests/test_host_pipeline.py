"""Multi-core host pipeline (parallel/host_pipeline.py).

The load-bearing property is byte identity: slicing per-stripe encode
and per-shard SHA across worker threads must never change a single
output byte, at any worker count, on any backend — fuzzed here against
the unsliced coder across numpy/native/jax, plus the end-to-end paths
that now ride the pipeline (writer, gateway PUT round-trip, verify,
resilver).  Every explicitly created pipeline is closed so the
leak-strict tier-1 run doesn't accumulate worker threads.
"""

import asyncio
import contextlib
import os

import numpy as np
import pytest

from chunky_bits_tpu.cluster import Cluster
from chunky_bits_tpu.ops.backend import get_coder
from chunky_bits_tpu.parallel.host_pipeline import (
    HostPipeline,
    get_host_pipeline,
)
from chunky_bits_tpu.utils import aio


@contextlib.contextmanager
def pipeline(threads):
    pipe = HostPipeline(threads=threads)
    try:
        yield pipe
    finally:
        pipe.close()


# ---- unit behavior ----


def test_worker_count_honored_and_shared_clamped(monkeypatch):
    """Explicit counts are exact (sweeps/tests may oversubscribe); the
    auto-sized default resolves env then clamps to min(N, nproc)."""
    with pipeline(4) as pipe:
        assert pipe.threads == 4
    from chunky_bits_tpu.cluster import tunables

    monkeypatch.setenv(tunables.HOST_THREADS_ENV, "999")
    auto = HostPipeline()
    try:
        assert auto.threads == (os.cpu_count() or 1)
    finally:
        auto.close()
    monkeypatch.setenv(tunables.HOST_THREADS_ENV, "not-a-number")
    assert tunables.host_threads(default=3) == 3  # lenient perf knob
    monkeypatch.delenv(tunables.HOST_THREADS_ENV, raising=False)
    assert tunables.host_threads(default=0) == 0


def test_submit_wait_and_error_propagation():
    with pipeline(2) as pipe:
        assert pipe.submit("t", lambda: 41 + 1).wait() == 42

        def boom():
            raise ValueError("boom")

        job = pipe.submit("t", boom)
        with pytest.raises(ValueError, match="boom"):
            job.wait()


def test_async_run_inline_and_offloaded():
    with pipeline(2) as pipe:
        async def main():
            # small known size -> inline; large -> worker hop; both must
            # return results and propagate errors identically
            small = await pipe.run("t", lambda: "s", nbytes=10)
            big = await pipe.run(
                "t", lambda: "b", nbytes=HostPipeline.INLINE_NBYTES + 1)
            with pytest.raises(RuntimeError, match="nope"):
                await pipe.run("t", _raiser, nbytes=1 << 30)
            return small, big

        assert asyncio.run(main()) == ("s", "b")


def _raiser():
    raise RuntimeError("nope")


def test_stage_counters_and_report_format():
    with pipeline(2) as pipe:
        pipe.submit("hash", lambda: None, nbytes=1000).wait()
        pipe.submit("hash", lambda: None, nbytes=500).wait()
        pipe.submit("encode", lambda: None, nbytes=7).wait()
        stats = pipe.stats()
        assert stats.threads == 2
        by_stage = {s.stage: s for s in stats.stages}
        assert by_stage["hash"].jobs == 2
        assert by_stage["hash"].nbytes == 1500
        assert by_stage["encode"].jobs == 1
        text = str(stats)
        assert text.startswith("Pipeline<2w ")
        assert "hash: 2j/" in text and "idle " in text


def test_full_queue_and_worker_reentrancy_run_inline():
    """Backpressure and reentrancy can never deadlock: a full queue runs
    jobs on the producer, a worker-submitted job runs inline."""
    pipe = HostPipeline(threads=1, queue_depth=1)
    try:
        import threading

        gate = threading.Event()
        blocker = pipe.submit("t", gate.wait)  # occupies the worker
        jobs = [pipe.submit("t", lambda i=i: i) for i in range(16)]
        # queue depth 1: most ran inline on this thread already
        assert [j.wait() for j in jobs[:-1]] == list(range(15))
        gate.set()
        blocker.wait()
        jobs[-1].wait()

        def recursive():
            return pipe.submit("t", lambda: "inner").wait()

        assert pipe.submit("t", recursive).wait() == "inner"
    finally:
        pipe.close()


def test_closed_pipeline_degrades_never_hangs():
    """Work submitted after close() still completes (degrade, never
    hang): sync submits run inline, async runs hop to a plain thread."""
    pipe = HostPipeline(threads=2)
    pipe.close()
    assert pipe.submit("t", lambda: "sync").wait() == "sync"

    async def main():
        return await asyncio.wait_for(
            pipe.run("t", lambda: "late",
                     nbytes=HostPipeline.INLINE_NBYTES + 1),
            timeout=30)

    assert asyncio.run(main()) == "late"


def test_encode_hash_sync_validates_shape():
    from chunky_bits_tpu.errors import ErasureError

    with pipeline(2) as pipe:
        coder = get_coder(3, 2, "numpy")
        with pytest.raises(ErasureError):
            pipe.encode_hash_sync(coder,
                                  np.zeros((2, 4, 8), dtype=np.uint8))


# ---- byte-identity fuzz across worker counts and backends ----


BACKENDS = ["numpy", "native", "native:2"]


@pytest.mark.parametrize("backend", BACKENDS)
def test_encode_hash_identity_fuzz(backend):
    """N=1 vs N=4 workers vs the unsliced coder, random geometries and
    shard lengths (odd, tiny, empty, single-stripe, wide batch)."""
    rng = np.random.default_rng(1234)
    coder_cache = {}
    with pipeline(1) as p1, pipeline(4) as p4:
        for trial in range(24):
            d = int(rng.integers(1, 12))
            p = int(rng.integers(0, 5))
            b = int(rng.integers(0, 10))
            s = int(rng.choice([0, 1, 63, 64, 1000, 4096, 65537]))
            key = (d, p)
            coder = coder_cache.get(key)
            if coder is None:
                coder = coder_cache[key] = get_coder(d, p, backend)
            data = rng.integers(0, 256, (b, d, s), dtype=np.uint8)
            want_parity, want_digests = coder.encode_hash_batch(data)
            for pipe in (p1, p4):
                parity, digests = pipe.encode_hash_sync(coder, data)
                assert np.array_equal(parity, want_parity), \
                    (backend, pipe.threads, b, d, p, s)
                assert np.array_equal(digests, want_digests), \
                    (backend, pipe.threads, b, d, p, s)


@pytest.mark.parametrize("backend", BACKENDS)
def test_encode_hash_identity_pm_msr(backend):
    """pm-msr (supports_fused_ingest=False) must skip BOTH backend
    fused passes and take the decomposed path — per-shard hashing
    sliced across the workers, the coder's own stripe encode — never a
    single-threaded whole-batch delegation; bytes identical to the
    unsliced coder at every worker count."""
    rng = np.random.default_rng(4321)
    coder = get_coder(5, 4, backend, "pm-msr")
    with pipeline(1) as p1, pipeline(4) as p4:
        for b, s in [(1, 4096), (4, 8192), (3, 64)]:
            data = rng.integers(0, 256, (b, 5, s), dtype=np.uint8)
            want = coder.encode_hash_batch(data)
            for pipe in (p1, p4):
                got = pipe.encode_hash_sync(coder, data)
                assert np.array_equal(got[0], want[0]), (backend, b, s)
                assert np.array_equal(got[1], want[1]), (backend, b, s)
        stages = {st.stage: st for st in p4.stats().stages}
        # the decomposed path queues sliced "hash" jobs; the
        # delegation branch would run ONE opaque "encode" job only
        assert "hash" in stages and stages["hash"].jobs > 1


def test_encode_hash_identity_jax_backend():
    """The jax backend delegates to its own fused/overlapped path (which
    hashes on the shared pipeline internally) — output must still match
    the CPU oracle bit for bit."""
    jax = pytest.importorskip("jax")  # noqa: F841
    rng = np.random.default_rng(7)
    d, p = 5, 3
    want_coder = get_coder(d, p, "native")
    jax_coder = get_coder(d, p, "jax")
    with pipeline(1) as p1, pipeline(4) as p4:
        for b, s in [(1, 4096), (4, 8192), (3, 65537)]:
            data = rng.integers(0, 256, (b, d, s), dtype=np.uint8)
            want = want_coder.encode_hash_batch(data)
            for pipe in (p1, p4):
                got = pipe.encode_hash_sync(jax_coder, data)
                assert np.array_equal(got[0], want[0])
                assert np.array_equal(got[1], want[1])


# ---- end-to-end paths ----


def _make_cluster(root, host_threads=None, backend="native",
                  cache_bytes=0) -> Cluster:
    dirs = []
    for i in range(5):
        d = os.path.join(root, f"disk{i}")
        os.makedirs(d, exist_ok=True)
        dirs.append(d)
    meta = os.path.join(root, "meta")
    os.makedirs(meta, exist_ok=True)
    tunables = {"backend": backend}
    if host_threads is not None:
        # 0 pins "use the process-shared pipeline" even when
        # $CHUNKY_BITS_TPU_HOST_THREADS is set (YAML wins over env)
        tunables["host_threads"] = host_threads
    if cache_bytes:
        tunables["cache_bytes"] = cache_bytes
    return Cluster.from_obj({
        "destinations": [{"location": d} for d in dirs],
        "metadata": {"type": "path", "format": "yaml", "path": str(meta)},
        "profiles": {"default": {"data": 3, "parity": 2,
                                 "chunk_size": 14}},
        "tunables": tunables,
    })


def test_host_threads_tunable_serde_and_cluster_pipeline(tmp_path):
    from chunky_bits_tpu.cluster.tunables import Tunables
    from chunky_bits_tpu.errors import SerdeError

    t = Tunables.from_obj({"host_threads": 3})
    assert t.host_threads == 3
    assert t.to_obj()["host_threads"] == 3
    assert "host_threads" not in Tunables.from_obj(None).to_obj() or \
        Tunables.from_obj(None).host_threads > 0
    with pytest.raises(SerdeError):
        Tunables.from_obj({"host_threads": -1})
    with pytest.raises(SerdeError):
        Tunables.from_obj({"host_threads": "lots"})

    pinned = _make_cluster(str(tmp_path / "a"), host_threads=3)
    pipe = pinned.host_pipeline()
    try:
        assert pipe.threads == 3
        assert pinned.host_pipeline() is pipe  # cached per cluster
    finally:
        pipe.close()
    shared = _make_cluster(str(tmp_path / "b"), host_threads=0)
    assert shared.host_pipeline() is get_host_pipeline()


def test_writer_identity_across_worker_counts(tmp_path):
    """Same payload written through clusters pinned to 1 vs 4 host
    threads: identical part geometry, shard digests, and read-back
    bytes (the acceptance invariant for the parallel ingest path)."""
    payload = np.random.default_rng(3).integers(
        0, 256, 5 * 3 * (1 << 14) + 777, dtype=np.uint8).tobytes()

    def digests_of(ref):
        return [[c.hash.value.hex() for c in part.all_chunks()]
                for part in ref.parts]

    async def write_with(root, n):
        cluster = _make_cluster(str(root), host_threads=n)
        profile = cluster.get_profile(None)
        ref = await cluster.write_file(
            "obj", aio.BytesReader(payload), profile)
        got = await (await cluster.read_file("obj")).read(-1)
        pipe = cluster.host_pipeline()
        stats = pipe.stats()
        pipe.close()
        return digests_of(ref), bytes(got), stats

    async def main():
        d1, got1, _ = await write_with(tmp_path / "n1", 1)
        d4, got4, stats4 = await write_with(tmp_path / "n4", 4)
        assert got1 == payload and got4 == payload
        assert d1 == d4
        assert stats4.threads == 4
        # the ingest compute actually ran on the pipeline
        assert any(s.stage == "encode" and s.jobs > 0
                   for s in stats4.stages)

    asyncio.run(main())


def test_gateway_put_roundtrip_parallel_pipeline(tmp_path):
    """Gateway PUT through a cluster pinned to 4 host threads: byte
    identity on GET, digests identical to a 1-thread cluster's."""
    pytest.importorskip("aiohttp")
    from aiohttp.test_utils import TestClient, TestServer

    from chunky_bits_tpu.gateway import make_app

    payload = os.urandom(3 * (1 << 14) * 3 + 1234)

    async def put_and_read(root, n):
        cluster = _make_cluster(str(root), host_threads=n)
        app = make_app(cluster)
        async with TestClient(TestServer(app)) as client:
            assert (await client.put("/obj", data=payload)).status == 200
            resp = await client.get("/obj")
            body = await resp.read()
        ref = await cluster.get_file_ref("obj")
        digests = [[c.hash.value.hex() for c in part.all_chunks()]
                   for part in ref.parts]
        pipe = cluster.host_pipeline()
        pipe.close()
        return body, digests

    async def main():
        body4, digests4 = await put_and_read(tmp_path / "n4", 4)
        body1, digests1 = await put_and_read(tmp_path / "n1", 1)
        assert body4 == payload and body1 == payload
        assert digests4 == digests1

    asyncio.run(main())


def test_verify_and_resilver_on_pipeline(tmp_path):
    """verify re-hashes shards on an injected pipeline (counters prove
    it); resilver with a 4-worker pipeline restores byte identity after
    losing a destination."""
    payload = os.urandom(4 * 3 * (1 << 14) + 99)

    async def main():
        cluster = _make_cluster(str(tmp_path), host_threads=0)
        profile = cluster.get_profile(None)
        await cluster.write_file("obj", aio.BytesReader(payload), profile)
        ref = await cluster.get_file_ref("obj")

        pipe = HostPipeline(threads=4)
        try:
            report = await ref.verify(
                cluster.tunables.location_context(), pipeline=pipe)
            assert report.is_ideal()
            stats = pipe.stats()
            verify_stage = [s for s in stats.stages
                            if s.stage == "verify"]
            assert verify_stage and verify_stage[0].jobs > 0

            # destroy every shard on one destination, then resilver
            removed = 0
            for part in ref.parts:
                for chunk in part.all_chunks():
                    target = chunk.locations[0].target
                    if "disk0" in target and os.path.exists(target):
                        os.remove(target)
                        removed += 1
            destination = cluster.get_destination(profile)
            report = await ref.resilver(
                destination, cluster.tunables.location_context(),
                backend=cluster.tunables.backend, pipeline=pipe)
            assert report.is_available()
            got = await (await cluster.read_file("obj")).read(-1)
            assert bytes(got) == payload
        finally:
            pipe.close()

    asyncio.run(main())


def test_profiler_surfaces_pipeline_counters(tmp_path):
    """A write-with-report profile includes the Pipeline<...> stanza
    once verify/read work ran on the attached pipeline."""
    from chunky_bits_tpu.file.profiler import new_profiler

    payload = os.urandom(3 * (1 << 14) + 5)

    async def main():
        cluster = _make_cluster(str(tmp_path), host_threads=0)
        profile = cluster.get_profile(None)
        await cluster.write_file("obj", aio.BytesReader(payload), profile)
        ref = await cluster.get_file_ref("obj")
        profiler, reporter = new_profiler()
        cx = cluster.tunables.location_context().but_with(
            profiler=profiler)
        with pipeline(2) as pipe:
            report = await ref.verify(cx, pipeline=pipe)
            assert report.is_ideal()
            text = str(reporter.profile())
        assert "Pipeline<2w" in text and "verify:" in text

    asyncio.run(main())
