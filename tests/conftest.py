"""Test harness configuration.

JAX-dependent tests run on CPU with 8 virtual devices so multi-device
sharding tests can run without TPU hardware; this must be set before jax is
imported anywhere in the test process.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Shared recipe (also used by __graft_entry__.dryrun_multichip): drop the
# axon tunnel pinning and run on a virtual 8-device CPU backend.  The
# helper module is jax-free, so importing it here is safe.
from chunky_bits_tpu.utils.virtualmesh import provision_virtual_mesh  # noqa: E402

provision_virtual_mesh(os.environ, 8)

# The axon sitecustomize imports jax at interpreter startup (before this
# file runs), so the env vars above are read too late; force the settings
# through the live config instead.  Safe as long as no backend has been
# initialized yet (sitecustomize only registers the plugin).  jax itself is
# an optional dependency — without it the pure-host tests still run.
try:
    import jax  # noqa: E402
except ImportError:
    pass
else:
    jax.config.update("jax_platforms", "cpu")

# Runtime concurrency sanitizer (opt-in, the tier-1 sanitize leg:
# CHUNKY_BITS_TPU_SANITIZE=1 bash scripts/tier1.sh).  Installed here —
# before any test creates an event loop — so every loop the suite spins
# up is instrumented; pytest_sessionfinish below turns leaked tasks /
# swallowed task exceptions / handoff violations into a session
# failure, extending the leak-strict gate to the async plane.  Loop
# stalls are reported but advisory (shared CI boxes stall under load).
from chunky_bits_tpu.cluster.tunables import sanitize_enabled  # noqa: E402

_SANITIZER = None
if sanitize_enabled():
    from chunky_bits_tpu.analysis import sanitizer as _sanitizer_mod

    _SANITIZER = _sanitizer_mod.install()


def pytest_sessionfinish(session, exitstatus):
    if _SANITIZER is None:
        return
    report = _SANITIZER.report()
    print()  # keep the report off pytest's progress line
    print(report.render())
    if not report.ok():
        print("sanitizer: FAILING the session (leaked tasks / "
              "unretrieved exceptions / handoff violations above)")
        # only upgrade a green session: an interrupted/errored run
        # (exitstatus 2/3) tears loops down mid-test and would always
        # "leak" — overwriting would hide the real signal
        if exitstatus == 0:
            session.exitstatus = 1
