"""Test harness configuration.

JAX-dependent tests run on CPU with 8 virtual devices so multi-device
sharding tests can run without TPU hardware; this must be set before jax is
imported anywhere in the test process.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import sys  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
