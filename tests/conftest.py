"""Test harness configuration.

JAX-dependent tests run on CPU with 8 virtual devices so multi-device
sharding tests can run without TPU hardware; this must be set before jax is
imported anywhere in the test process.
"""

import os

# The axon sitecustomize registers the tunneled-TPU PJRT plugin whenever
# PALLAS_AXON_POOL_IPS is set and pins JAX_PLATFORMS=axon; drop both so the
# suite runs on the virtual 8-device CPU backend.
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon sitecustomize imports jax at interpreter startup (before this
# file runs), so the env vars above are read too late; force the settings
# through the live config instead.  Safe as long as no backend has been
# initialized yet (sitecustomize only registers the plugin).  jax itself is
# an optional dependency — without it the pure-host tests still run.
try:
    import jax  # noqa: E402
except ImportError:
    pass
else:
    jax.config.update("jax_platforms", "cpu")

import sys  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
