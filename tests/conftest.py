"""Test harness configuration.

JAX-dependent tests run on CPU with 8 virtual devices so multi-device
sharding tests can run without TPU hardware; this must be set before jax is
imported anywhere in the test process.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Shared recipe (also used by __graft_entry__.dryrun_multichip): drop the
# axon tunnel pinning and run on a virtual 8-device CPU backend.  The
# helper module is jax-free, so importing it here is safe.
from chunky_bits_tpu.utils.virtualmesh import provision_virtual_mesh  # noqa: E402

provision_virtual_mesh(os.environ, 8)

# The axon sitecustomize imports jax at interpreter startup (before this
# file runs), so the env vars above are read too late; force the settings
# through the live config instead.  Safe as long as no backend has been
# initialized yet (sitecustomize only registers the plugin).  jax itself is
# an optional dependency — without it the pure-host tests still run.
try:
    import jax  # noqa: E402
except ImportError:
    pass
else:
    jax.config.update("jax_platforms", "cpu")
