"""File codec tests (mirror of reference tests/file.rs): write-path
part/length math over the d x p grid, NotEnoughWriters, read-side profiler,
plus full write->read roundtrips and the TPU batch staging path."""

import asyncio
import hashlib
import os
import random

import pytest

from chunky_bits_tpu.errors import NotEnoughWriters
from chunky_bits_tpu.file import (
    FileReadBuilder,
    FileReference,
    FileWriteBuilder,
    Location,
    LocationContext,
    LocationsDestination,
    VoidDestination,
    new_profiler,
)
from chunky_bits_tpu.utils import aio

CHUNK_SIZE = 1 << 16
LENGTH = (1 << 18) + 7  # not divisible by any stripe size (cf. tests/file.rs)


def synthetic_bytes(n: int, seed: int = 0) -> bytes:
    rng = random.Random(seed)
    return bytes(rng.getrandbits(8) for _ in range(n))


@pytest.mark.parametrize("d", [1, 2, 3])
@pytest.mark.parametrize("p", [1, 2, 3])
def test_write_part_length_math(d, p):
    """Mirrors tests/file.rs:26-56: part count and chunk sizes over the
    void destination."""
    payload = synthetic_bytes(LENGTH, seed=d * 10 + p)

    async def main():
        builder = (FileWriteBuilder()
                   .with_destination(VoidDestination())
                   .with_chunk_size(CHUNK_SIZE)
                   .with_data_chunks(d)
                   .with_parity_chunks(p))
        ref = await builder.write(aio.BytesReader(payload))
        assert ref.length == LENGTH
        part_size = d * CHUNK_SIZE
        expected_parts = (LENGTH + part_size - 1) // part_size
        assert len(ref.parts) == expected_parts
        for part in ref.parts[:-1]:
            assert part.chunksize == CHUNK_SIZE
            assert len(part.data) == d
            assert len(part.parity) == p
        last = ref.parts[-1]
        tail = LENGTH - (expected_parts - 1) * part_size
        assert last.chunksize == (tail + d - 1) // d
        # chunks carry real hashes but no locations (void)
        for part in ref.parts:
            for chunk in part.data + part.parity:
                assert chunk.locations == []

    asyncio.run(main())


def test_not_enough_writers(tmp_path):
    """Mirrors tests/file.rs:58-111."""
    dirs = [tmp_path / f"d{i}" for i in range(3)]
    for dpath in dirs:
        dpath.mkdir()

    async def main():
        dest = LocationsDestination([Location.parse(str(d)) for d in dirs])
        builder = (FileWriteBuilder()
                   .with_destination(dest)
                   .with_chunk_size(CHUNK_SIZE)
                   .with_data_chunks(3)
                   .with_parity_chunks(2))
        with pytest.raises(NotEnoughWriters):
            await builder.write(aio.BytesReader(b"x" * 1000))

    asyncio.run(main())


@pytest.mark.parametrize("batch_parts", [1, 4])
def test_roundtrip_with_storage(tmp_path, batch_parts):
    payload = synthetic_bytes(LENGTH, seed=99)
    dirs = []
    for i in range(5):
        d = tmp_path / f"disk{i}"
        d.mkdir()
        dirs.append(Location.parse(str(d)))

    async def main():
        dest = LocationsDestination(dirs)
        builder = (FileWriteBuilder()
                   .with_destination(dest)
                   .with_chunk_size(CHUNK_SIZE)
                   .with_data_chunks(3)
                   .with_parity_chunks(2)
                   .with_batch_parts(batch_parts))
        ref = await builder.write(aio.BytesReader(payload))
        # serde roundtrip preserves everything
        ref2 = FileReference.from_obj(ref.to_obj())
        got = await FileReadBuilder(ref2).read_all()
        assert hashlib.sha256(got).hexdigest() == \
            hashlib.sha256(payload).hexdigest()
        # seek/take
        got = await FileReadBuilder(ref2).with_seek(100).with_take(
            5000).read_all()
        assert got == payload[100:5100]
        # seek across part boundaries
        offset = 3 * CHUNK_SIZE + 17
        got = await FileReadBuilder(ref2).with_seek(offset).read_all()
        assert got == payload[offset:]
        # take beyond EOF
        got = await FileReadBuilder(ref2).with_seek(LENGTH - 10).with_take(
            100).read_all()
        assert got == payload[-10:]

    asyncio.run(main())


@pytest.mark.parametrize("tail", [500, 3 * 1024 - 2, 3 * 1024])
def test_streamed_staging_roundtrip(tmp_path, tail):
    """batch_parts larger than the staging granularity streams sub-blocks
    through encode while the read loop continues; part order, lengths,
    and bytes must be exactly the serial path's.  Tail variants: short
    (repacked to a smaller shard length), near-full (same shard length
    as full parts but needing zero padding — must not drag the full
    parts off the zero-copy path), and exactly full."""
    d, p, chunk = 3, 2, 1024
    n_parts = 21
    payload = synthetic_bytes(d * chunk * (n_parts - 1) + tail, seed=41)
    dirs = []
    for i in range(5):
        dd = tmp_path / f"disk{i}"
        dd.mkdir()
        dirs.append(Location.parse(str(dd)))

    async def main():
        builder = (FileWriteBuilder()
                   .with_destination(LocationsDestination(dirs))
                   .with_chunk_size(chunk)
                   .with_data_chunks(d)
                   .with_parity_chunks(p)
                   .with_batch_parts(64)
                   .with_stage_parts(4)
                   .with_concurrency(68))
        ref = await builder.write(aio.BytesReader(payload))
        assert len(ref.parts) == n_parts
        assert ref.length == len(payload)
        got = await FileReadBuilder(ref).read_all()
        assert got == payload
        # hashes match the plain one-part-at-a-time path
        plain = await (FileWriteBuilder()
                       .with_destination(LocationsDestination(dirs))
                       .with_chunk_size(chunk)
                       .with_data_chunks(d)
                       .with_parity_chunks(p)
                       .write(aio.BytesReader(payload)))
        assert [c.hash for part in ref.parts for c in part.all_chunks()] \
            == [c.hash for part in plain.parts for c in part.all_chunks()]

    asyncio.run(main())


def test_write_fails_cleanly_on_reader_error(tmp_path):
    """A source reader erroring mid-stream must abort the write with the
    original exception, cancel in-flight batches, and not leak parts."""

    class ExplodingReader:
        def __init__(self, good_bytes: int):
            self._left = good_bytes

        async def read(self, n: int = -1) -> bytes:
            if self._left <= 0:
                raise OSError("source went away")
            n = min(n if n >= 0 else self._left, self._left)
            self._left -= n
            return b"\x5a" * n

    dirs = []
    for i in range(5):
        dd = tmp_path / f"disk{i}"
        dd.mkdir()
        dirs.append(Location.parse(str(dd)))

    async def main():
        builder = (FileWriteBuilder()
                   .with_destination(LocationsDestination(dirs))
                   .with_chunk_size(1024)
                   .with_data_chunks(3)
                   .with_parity_chunks(2)
                   .with_batch_parts(8)
                   .with_stage_parts(2)
                   .with_concurrency(12))
        with pytest.raises(OSError, match="source went away"):
            await builder.write(ExplodingReader(5 * 3 * 1024))

    asyncio.run(main())


def test_take_limited_read_ignores_trailing_parts(tmp_path):
    """A take-limited read must neither touch nor depend on parts past
    its window: destroy every chunk of the last part and the windowed
    read still succeeds — while a full read correctly fails."""
    from chunky_bits_tpu.errors import FileReadError
    from chunky_bits_tpu.file import file_part as fp_mod

    d_, p_, chunk = 3, 2, 1024
    payload = synthetic_bytes(d_ * chunk * 4, seed=47)  # exactly 4 parts
    dirs = []
    for i in range(5):
        dd = tmp_path / f"disk{i}"
        dd.mkdir()
        dirs.append(Location.parse(str(dd)))

    async def main():
        ref = await (FileWriteBuilder()
                     .with_destination(LocationsDestination(dirs))
                     .with_chunk_size(chunk)
                     .with_data_chunks(d_)
                     .with_parity_chunks(p_)
                     .write(aio.BytesReader(payload)))
        assert len(ref.parts) == 4
        for c in ref.parts[3].all_chunks():
            os.remove(c.locations[0].target)

        reads = []
        orig = fp_mod.FilePart.read_buffers

        async def counting(self, *a, **kw):
            reads.append(self)
            return await orig(self, *a, **kw)

        fp_mod.FilePart.read_buffers = counting
        try:
            part_bytes = d_ * chunk
            got = await (FileReadBuilder(ref).with_seek(100)
                         .with_take(part_bytes).read_all())
            assert got == payload[100:100 + part_bytes]
            # only the two parts overlapping the window were read
            assert len(reads) == 2
        finally:
            fp_mod.FilePart.read_buffers = orig

        with pytest.raises(FileReadError):
            await FileReadBuilder(ref).read_all()

    asyncio.run(main())


def test_writer_owns_batcher_for_merging_backend(tmp_path):
    """A merge-preferring (device) backend with no shared batcher gets a
    writer-owned EncodeHashBatcher, so streamed sub-blocks coalesce back
    into large dispatches instead of issuing one device RPC per
    sub-block."""
    from chunky_bits_tpu.ops import batching
    from chunky_bits_tpu.ops.backend import NumpyBackend, register_backend
    from chunky_bits_tpu.ops import backend as backend_mod

    class MergingNumpy(NumpyBackend):
        name = "numpy-merging"
        prefers_merged_batches = True

    created = []
    orig_init = batching.EncodeHashBatcher.__init__

    def spy_init(self, *a, **kw):
        orig_init(self, *a, **kw)
        created.append(self)

    d, p, chunk = 3, 2, 1024
    payload = synthetic_bytes(d * chunk * 20, seed=43)
    dirs = []
    for i in range(5):
        dd = tmp_path / f"disk{i}"
        dd.mkdir()
        dirs.append(Location.parse(str(dd)))

    async def main():
        builder = (FileWriteBuilder()
                   .with_destination(LocationsDestination(dirs))
                   .with_chunk_size(chunk)
                   .with_data_chunks(d)
                   .with_parity_chunks(p)
                   .with_batch_parts(64)
                   .with_stage_parts(4)
                   .with_concurrency(68)
                   .with_backend("numpy-merging"))
        ref = await builder.write(aio.BytesReader(payload))
        assert len(created) == 1, "writer should own exactly one batcher"
        # max_batch counts sub-block requests: 64 parts / 4-part blocks
        assert created[0].max_batch == 16
        # sub-blocks of 4 coalesced: far fewer dispatches than the 20
        # parts, and the content still reads back exactly
        assert created[0].dispatches < 20
        got = await FileReadBuilder(ref).read_all()
        assert got == payload

    register_backend(MergingNumpy())
    batching.EncodeHashBatcher.__init__ = spy_init
    try:
        asyncio.run(main())
    finally:
        batching.EncodeHashBatcher.__init__ = orig_init
        backend_mod._REGISTRY.pop("numpy-merging", None)


def test_read_survives_chunk_loss(tmp_path):
    payload = synthetic_bytes(200000, seed=5)
    dirs = []
    for i in range(5):
        d = tmp_path / f"disk{i}"
        d.mkdir()
        dirs.append(Location.parse(str(d)))

    async def main():
        dest = LocationsDestination(dirs)
        builder = (FileWriteBuilder()
                   .with_destination(dest)
                   .with_chunk_size(CHUNK_SIZE)
                   .with_data_chunks(3)
                   .with_parity_chunks(2))
        ref = await builder.write(aio.BytesReader(payload))
        # delete up to p chunk files per part (1 data + 1 parity)
        for part in ref.parts:
            os.remove(part.data[0].locations[0].target)
            os.remove(part.parity[0].locations[0].target)
        got = await FileReadBuilder(ref).read_all()
        assert got == payload

    asyncio.run(main())


def test_read_profiler(tmp_path):
    """Mirrors tests/file.rs:113-141."""
    payload = synthetic_bytes(100000, seed=1)
    dirs = []
    for i in range(5):
        d = tmp_path / f"disk{i}"
        d.mkdir()
        dirs.append(Location.parse(str(d)))

    async def main():
        dest = LocationsDestination(dirs)
        ref = await (FileWriteBuilder()
                     .with_destination(dest)
                     .with_chunk_size(CHUNK_SIZE)
                     .with_data_chunks(3)
                     .with_parity_chunks(2)
                     ).write(aio.BytesReader(payload))
        profiler, reporter = new_profiler()
        cx = LocationContext(profiler=profiler)
        got = await FileReadBuilder(ref).location_context(cx).read_all()
        assert got == payload
        report = reporter.profile()
        assert report.average_read_duration() is not None
        assert report.average_read_duration() < 1.0
        assert report.total_bytes() > 0

    asyncio.run(main())


def test_write_empty_file():
    async def main():
        ref = await (FileWriteBuilder()
                     .with_destination(VoidDestination())
                     ).write(aio.BytesReader(b""))
        assert ref.length == 0
        assert ref.parts == []

    asyncio.run(main())


def test_verify_fanout_is_bounded(tmp_path, monkeypatch):
    """verify keeps at most 10 parts in flight (like resilver) and at most
    VERIFY_READ_CONCURRENCY location reads per part — the reference opens
    every location of every chunk of every part at once
    (file_reference.rs:78-87, file_part.rs:228-251)."""
    from chunky_bits_tpu.file.file_part import FilePart

    payload = synthetic_bytes(40 * 3 * 1024, seed=11)  # 40 parts at S=1 KiB
    dirs = []
    for i in range(5):
        d = tmp_path / f"disk{i}"
        d.mkdir()
        dirs.append(Location.parse(str(d)))

    async def main():
        builder = (FileWriteBuilder()
                   .with_destination(LocationsDestination(dirs))
                   .with_chunk_size(1024)
                   .with_data_chunks(3)
                   .with_parity_chunks(2))
        ref = await builder.write(aio.BytesReader(payload))
        assert len(ref.parts) == 40

        in_flight = {"parts": 0, "reads": 0}
        peaks = {"parts": 0, "reads": 0}

        real_verify = FilePart.verify
        real_read = Location.read

        async def counting_verify(self, cx=None, **kwargs):
            in_flight["parts"] += 1
            peaks["parts"] = max(peaks["parts"], in_flight["parts"])
            try:
                return await real_verify(self, cx, **kwargs)
            finally:
                in_flight["parts"] -= 1

        async def counting_read(self, cx=None):
            in_flight["reads"] += 1
            peaks["reads"] = max(peaks["reads"], in_flight["reads"])
            try:
                # yield so overlapping reads actually overlap in counters
                await asyncio.sleep(0)
                return await real_read(self, cx)
            finally:
                in_flight["reads"] -= 1

        monkeypatch.setattr(FilePart, "verify", counting_verify)
        monkeypatch.setattr(Location, "read", counting_read)
        # force the generic read path: the fused local-hash shortcut
        # would bypass Location.read and leave the read cap untested
        import chunky_bits_tpu.file.file_part as fp_mod

        async def no_fused(chunk, location, cx, pipeline=None):
            return None

        monkeypatch.setattr(fp_mod, "_hash_local_fused", no_fused)
        report = await ref.verify()
        assert report.is_ideal()
        assert peaks["parts"] <= 10
        assert peaks["reads"] > 0
        assert peaks["reads"] <= 10 * FilePart.VERIFY_READ_CONCURRENCY

    asyncio.run(main())


@pytest.mark.parametrize("tail", [0, 500, 3 * 1024 - 1])
def test_mmap_source_roundtrip(tmp_path, tail, monkeypatch):
    monkeypatch.delenv("CHUNKY_BITS_TPU_NO_MMAP", raising=False)
    """A local-file source engages the writer's zero-copy view path
    (aio.FileReader.view_parts): full parts are encoded straight from
    page-cache views with no source memcpy.  The resulting reference
    must be byte-identical (every chunk hash) to the BytesReader copy
    path's, across exact-multiple, short-tail, and near-full-tail
    sizes."""
    d, p, chunk = 3, 2, 1024
    n_full = 9
    payload = synthetic_bytes(d * chunk * n_full + tail, seed=61)
    src = tmp_path / "src.bin"
    src.write_bytes(payload)
    dirs = []
    for i in range(5):
        dd = tmp_path / f"disk{i}"
        dd.mkdir()
        dirs.append(Location.parse(str(dd)))

    async def main():
        builder = (FileWriteBuilder()
                   .with_destination(LocationsDestination(dirs))
                   .with_chunk_size(chunk)
                   .with_data_chunks(d)
                   .with_parity_chunks(p)
                   .with_batch_parts(8)
                   .with_stage_parts(4)
                   .with_concurrency(12))
        reader = aio.FileReader(str(src))
        ref = await builder.write(reader)
        # the mmap path actually engaged (white-box: a real map was
        # created, not the _NO_MAP "mapping unavailable" sentinel)
        assert reader._mm is not None
        assert reader._mm is not aio.FileReader._NO_MAP
        await reader.close()
        assert ref.length == len(payload)
        got = await FileReadBuilder(ref).read_all()
        assert got == payload
        plain = await (FileWriteBuilder()
                       .with_destination(LocationsDestination(dirs))
                       .with_chunk_size(chunk)
                       .with_data_chunks(d)
                       .with_parity_chunks(p)
                       .write(aio.BytesReader(payload)))
        assert [c.hash for part in ref.parts for c in part.all_chunks()] \
            == [c.hash for part in plain.parts for c in part.all_chunks()]

    asyncio.run(main())


@pytest.mark.parametrize("backend", ["jax", "jax:dp2,sp2"])
def test_mmap_source_device_backend_identity(tmp_path, backend, monkeypatch):
    """The read-only page-cache views flow through the device backends
    (plain jax and mesh-sharded) unchanged: device_put accepts
    non-writable arrays, and the resulting chunk hashes are identical to
    the copy path's."""
    monkeypatch.delenv("CHUNKY_BITS_TPU_NO_MMAP", raising=False)
    pytest.importorskip("jax")
    d, p, chunk = 3, 2, 1024
    payload = synthetic_bytes(d * chunk * 6 + 500, seed=67)
    src = tmp_path / "src.bin"
    src.write_bytes(payload)

    async def main():
        builder = (FileWriteBuilder()
                   .with_destination(None)
                   .with_chunk_size(chunk)
                   .with_data_chunks(d)
                   .with_parity_chunks(p)
                   .with_batch_parts(4)
                   .with_stage_parts(2)
                   .with_concurrency(8)
                   .with_backend(backend))
        reader = aio.FileReader(str(src))
        ref = await builder.write(reader)
        assert reader._mm is not None
        assert reader._mm is not aio.FileReader._NO_MAP
        await reader.close()
        plain = await builder.write(aio.BytesReader(payload))
        assert [c.hash for part in ref.parts for c in part.all_chunks()] \
            == [c.hash for part in plain.parts for c in part.all_chunks()]

    asyncio.run(main())


def test_random_seek_take_sweep(tmp_path):
    """Randomized guard for the per-buffer trimming arithmetic in the
    join-free streaming reader: any (seek, take) window must yield
    exactly payload[seek:seek+take], including windows straddling part
    and chunk boundaries, zero-length windows, and past-EOF tails."""
    d, p, chunk = 3, 2, 512
    payload = synthetic_bytes(d * chunk * 7 + 313, seed=73)
    dirs = []
    for i in range(5):
        dd = tmp_path / f"disk{i}"
        dd.mkdir()
        dirs.append(Location.parse(str(dd)))

    async def main():
        ref = await (FileWriteBuilder()
                     .with_destination(LocationsDestination(dirs))
                     .with_chunk_size(chunk)
                     .with_data_chunks(d)
                     .with_parity_chunks(p)
                     .with_batch_parts(4)
                     .write(aio.BytesReader(payload)))
        rng = random.Random(73)
        n = len(payload)
        cases = [(0, 0), (0, n), (n, 10), (n - 1, 5), (chunk, chunk),
                 (d * chunk, d * chunk)]
        cases += [(rng.randrange(0, n + 20), rng.randrange(0, n + 20))
                  for _ in range(40)]
        for seek, take in cases:
            got = await (FileReadBuilder(ref).with_seek(seek)
                         .with_take(take).read_all())
            want = payload[seek:seek + take] if take else payload[seek:]
            assert got == want, (seek, take, len(got), len(want))

    asyncio.run(main())
