"""Cluster-level write failover: node invalidation, redraw, exhaustion.

The reference's per-shard retry engine (src/cluster/writer.rs:99-122,
254-276) invalidates a node on write failure, relaxes zone budgets and
draws a new node until success or NotEnoughAvailability.  The reference
repo never tests this path; these tests inject real failing HTTP nodes
(507 on every PUT) into a mixed cluster.
"""

import asyncio

import numpy as np
import pytest

from chunky_bits_tpu.cluster import Cluster
from chunky_bits_tpu.errors import FileWriteError, NotEnoughAvailability
from chunky_bits_tpu.utils import aio

from tests.http_node import FakeHttpNode


def _cluster_obj(locations, meta_path, d=3, p=2, zones=None):
    dests = []
    for i, loc in enumerate(locations):
        node = {"location": loc}
        if zones:
            node["zones"] = zones[i]
        dests.append(node)
    return {
        "destinations": dests,
        "metadata": {"type": "path", "format": "yaml",
                     "path": str(meta_path)},
        "profiles": {"default": {"data": d, "parity": p, "chunk_size": 12}},
    }


def _payload(n=30000, seed=21):
    return np.random.default_rng(seed).integers(
        0, 256, n, dtype=np.uint8).tobytes()


def test_write_fails_over_broken_node(tmp_path):
    """One dead node in a six-node cluster: writes succeed, every shard
    lands on a healthy node, and the dead node saw at least one attempt
    (proving failover, not avoidance)."""

    async def main():
        bad = await FakeHttpNode(fail_puts=True).start()
        good_dirs = []
        for i in range(5):
            d = tmp_path / f"disk{i}"
            d.mkdir()
            good_dirs.append(str(d))
        try:
            meta = tmp_path / "meta"
            meta.mkdir()
            cluster = Cluster.from_obj(
                _cluster_obj([bad.url + "/"] + good_dirs, meta))
            payload = _payload()
            ref = await cluster.write_file(
                "x", aio.BytesReader(payload), cluster.get_profile())
            assert bad.put_attempts > 0, "dead node was never attempted"
            for part in ref.parts:
                for chunk in part.data + part.parity:
                    for loc in chunk.locations:
                        assert not str(loc).startswith("http"), \
                            f"shard on dead node: {loc}"
            got = await (await cluster.get_file_ref("x")) \
                .read_builder().read_all()
            assert got == payload
        finally:
            await bad.stop()

    asyncio.run(main())


def test_write_exhaustion_raises(tmp_path):
    """d+p=5 with only 4 healthy slots: the retry loop must exhaust and
    surface an error, not hang or silently drop a shard."""

    async def main():
        bad = await FakeHttpNode(fail_puts=True).start()
        bad2 = await FakeHttpNode(fail_puts=True).start()
        good_dirs = []
        for i in range(3):
            d = tmp_path / f"disk{i}"
            d.mkdir()
            good_dirs.append(str(d))
        try:
            meta = tmp_path / "meta"
            meta.mkdir()
            cluster = Cluster.from_obj(_cluster_obj(
                [bad.url + "/", bad2.url + "/"] + good_dirs, meta))
            with pytest.raises((FileWriteError, NotEnoughAvailability)):
                await cluster.write_file(
                    "x", aio.BytesReader(_payload()),
                    cluster.get_profile())
        finally:
            await bad.stop()
            await bad2.stop()

    asyncio.run(main())


def test_failover_respects_zones_then_relaxes(tmp_path):
    """Ideal-zone budgets steer placement, but when the ideal zone's node
    dies mid-write the budget relaxes and the shard lands in the other
    zone rather than failing the write (writer.rs:99-122)."""

    async def main():
        bad = await FakeHttpNode(fail_puts=True).start()
        good_dirs = []
        for i in range(5):
            d = tmp_path / f"disk{i}"
            d.mkdir()
            good_dirs.append(str(d))
        try:
            meta = tmp_path / "meta"
            meta.mkdir()
            obj = _cluster_obj(
                [bad.url + "/"] + good_dirs, meta,
                zones=[["ssd"]] + [["hdd"]] * 5,
            )
            obj["profiles"]["default"]["rules"] = {
                "ssd": {"ideal": 1},
            }
            cluster = Cluster.from_obj(obj)
            payload = _payload(20000, seed=3)
            ref = await cluster.write_file(
                "x", aio.BytesReader(payload), cluster.get_profile())
            assert bad.put_attempts > 0, \
                "ideal-zone node was never attempted"
            got = await (await cluster.get_file_ref("x")) \
                .read_builder().read_all()
            assert got == payload
        finally:
            await bad.stop()

    asyncio.run(main())
