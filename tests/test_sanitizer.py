"""Runtime concurrency sanitizer (chunky_bits_tpu/analysis/sanitizer).

Pins the three monitors' detection behavior (leaked tasks, swallowed
task exceptions, loop stalls, handoff violations), the
degrade-never-hang watchdog contract against dead loops, and the
off-by-default zero-overhead contract: with the flag unset the
instrumentation module is never even imported.

Deliberate-violation end-to-end checks run in subprocesses: the global
sanitizer is process-wide, and recording a violation in THIS process
would fail the tier-1 sanitize leg's session report."""

from __future__ import annotations

import asyncio
import gc
import os
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

from chunky_bits_tpu.analysis.sanitizer import (
    HandoffChecker,
    LoopWatchdog,
    TaskRegistry,
)

REPO = Path(__file__).resolve().parents[1]


def _run_py(code: str, *, sanitize: str | None) -> \
        subprocess.CompletedProcess:
    env = dict(os.environ)
    env.pop("CHUNKY_BITS_TPU_SANITIZE", None)
    if sanitize is not None:
        env["CHUNKY_BITS_TPU_SANITIZE"] = sanitize
    return subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=120, cwd=str(REPO), env=env)


# ---- off-by-default: zero overhead ----

def test_flag_unset_never_imports_instrumentation():
    """The sanitize-off path must not even import the sanitizer module
    — the whole cost is one sys.modules dict lookup per job wait."""
    proc = _run_py("""
import sys
from chunky_bits_tpu.parallel.host_pipeline import HostPipeline

pipe = HostPipeline(threads=2)
jobs = [pipe.submit("t", lambda i=i: i * i) for i in range(8)]
assert [j.wait() for j in jobs] == [i * i for i in range(8)]
import asyncio


async def body():
    return await pipe.run("t", lambda: 41 + 1)


assert asyncio.run(body()) == 42
pipe.close()
assert "chunky_bits_tpu.analysis.sanitizer" not in sys.modules, \\
    "sanitizer imported with the flag unset"
print("ZERO_OVERHEAD_OK")
""", sanitize=None)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "ZERO_OVERHEAD_OK" in proc.stdout


def test_flag_set_activates_via_pipeline_construction():
    proc = _run_py("""
import sys
from chunky_bits_tpu.parallel.host_pipeline import HostPipeline

pipe = HostPipeline(threads=2)
assert "chunky_bits_tpu.analysis.sanitizer" in sys.modules
from chunky_bits_tpu.analysis import sanitizer

assert sanitizer.active() is not None
assert pipe.submit("t", lambda: 7).wait() == 7
report = sanitizer.report()
assert report.ok(), report.render()
pipe.close()
print("ACTIVATED_OK")
""", sanitize="1")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "ACTIVATED_OK" in proc.stdout


# ---- task registry ----

def test_leaked_task_detection_fires():
    reg = TaskRegistry()
    loop = asyncio.new_event_loop()
    reg.install_on_loop(loop)

    async def forever() -> None:
        await asyncio.Event().wait()

    async def spawn() -> asyncio.Task:
        task = asyncio.get_running_loop().create_task(forever())
        await asyncio.sleep(0)
        return task

    task = loop.run_until_complete(spawn())
    try:
        leaks = reg.pending_leaks()
        assert len(leaks) == 1
        # the creation site points at THIS file, not asyncio internals
        assert "test_sanitizer" in leaks[0]
    finally:
        task.cancel()
        with pytest.raises(asyncio.CancelledError):
            loop.run_until_complete(task)
        loop.close()
    assert reg.pending_leaks() == []


def test_unretrieved_task_exception_captured():
    reg = TaskRegistry()
    loop = asyncio.new_event_loop()
    reg.install_on_loop(loop)

    async def boom() -> None:
        raise RuntimeError("swallowed?")

    async def spawn_and_drop() -> None:
        asyncio.get_running_loop().create_task(boom())  # lint: task-leak-ok the leak IS the fixture
        await asyncio.sleep(0.01)

    loop.run_until_complete(spawn_and_drop())
    loop.close()
    gc.collect()
    events = reg.events()
    assert any("never retrieved" in e for e in events), events
    assert any("swallowed?" in e for e in events), events


def test_done_tasks_are_not_leaks():
    reg = TaskRegistry()
    loop = asyncio.new_event_loop()
    reg.install_on_loop(loop)

    async def work() -> int:
        return 7

    async def body() -> int:
        return await asyncio.get_running_loop().create_task(work())

    assert loop.run_until_complete(body()) == 7
    loop.close()
    assert reg.pending_leaks() == []
    assert reg.events() == []


# ---- watchdog ----

@pytest.mark.filterwarnings("ignore")
def test_watchdog_detects_blocked_loop():
    wd = LoopWatchdog(threshold=0.1, interval=0.02)

    def run() -> None:
        loop = asyncio.new_event_loop()

        async def body() -> None:
            wd.watch(asyncio.get_running_loop())
            await asyncio.sleep(0.1)  # let a heartbeat land
            time.sleep(0.5)  # block the loop: the hazard
            await asyncio.sleep(0.05)

        loop.run_until_complete(body())
        loop.close()

    thread = threading.Thread(target=run, daemon=True)
    thread.start()
    thread.join(timeout=10)
    assert not thread.is_alive()
    wd.stop()
    assert wd.stalls, "blocked loop went undetected"
    assert "unresponsive" in wd.stalls[0]


def test_watchdog_never_hangs_on_dead_or_closed_loop():
    """A loop that exists but never runs records nothing; a closed loop
    is dropped; stop() returns promptly either way (degrade, never
    hang)."""
    wd = LoopWatchdog(threshold=0.05, interval=0.02)
    dead = asyncio.new_event_loop()
    wd.watch(dead)
    time.sleep(0.3)
    assert wd.stalls == []  # not running -> not stalled
    dead.close()
    time.sleep(0.1)  # watchdog notices the close and drops it
    t0 = time.monotonic()
    wd.stop()
    assert time.monotonic() - t0 < 2.0
    assert wd.stalls == []


def test_watchdog_healthy_loop_records_nothing():
    wd = LoopWatchdog(threshold=0.25, interval=0.02)

    async def body() -> None:
        wd.watch(asyncio.get_running_loop())
        for _ in range(10):
            await asyncio.sleep(0.02)

    asyncio.run(body())
    wd.stop()
    assert wd.stalls == []


# ---- handoff checker ----

def test_sync_wait_on_loop_thread_recorded():
    hc = HandoffChecker()

    async def body() -> None:
        hc.check_sync_wait("_Job.join()")

    asyncio.run(body())
    assert len(hc.violations) == 1
    assert "event-loop thread" in hc.violations[0]
    # off-loop sync waits are the intended shape: no violation
    hc2 = HandoffChecker()
    hc2.check_sync_wait("_Job.join()")
    assert hc2.violations == []


def test_resolve_on_wrong_thread_recorded():
    hc = HandoffChecker()

    async def body() -> None:
        token = hc.submit_token()
        hc.check_resolve(token)  # same loop + thread: fine
        assert hc.violations == []
        thread = threading.Thread(target=hc.check_resolve,
                                  args=(token,), daemon=True)
        thread.start()
        await asyncio.to_thread(thread.join)

    asyncio.run(body())
    assert len(hc.violations) == 1
    assert "off the submitting side" in hc.violations[0]


# ---- end-to-end through the pipeline (subprocesses: deliberate
# violations must not land in this process's global report) ----

def test_pipeline_async_path_is_handoff_clean():
    proc = _run_py("""
import asyncio
import numpy as np
from chunky_bits_tpu.parallel.host_pipeline import HostPipeline
from chunky_bits_tpu.analysis import sanitizer

pipe = HostPipeline(threads=2)


async def body():
    big = 1 << 20  # > INLINE_NBYTES: forces the worker hop + bridge
    out = await pipe.run("t", lambda: sum(range(100)), nbytes=big)
    assert out == 4950


asyncio.run(body())

# the sync scatter APIs are for off-loop callers; with no loop running
# on this thread they record nothing
rows = np.zeros((8, 4096), dtype=np.uint8)
digests = np.empty((8, 32), dtype=np.uint8)
from chunky_bits_tpu.parallel.host_pipeline import join_jobs

join_jobs(pipe.hash_rows_jobs(rows, digests))
report = sanitizer.report()
assert report.handoff_violations == [], report.render()
assert report.leaked_tasks == [], report.render()
pipe.close()
print("CLEAN_OK")
""", sanitize="1")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "CLEAN_OK" in proc.stdout


def test_pipeline_sync_wait_on_loop_detected_end_to_end():
    proc = _run_py("""
import asyncio
import time
from chunky_bits_tpu.parallel.host_pipeline import HostPipeline
from chunky_bits_tpu.analysis import sanitizer

pipe = HostPipeline(threads=2)


async def body():
    job = pipe.submit("t", lambda: time.sleep(0.2) or 7)
    assert job.wait() == 7  # blocking the loop: the violation


asyncio.run(body())
report = sanitizer.report()
assert report.handoff_violations, "sync loop-thread wait undetected"
assert "event-loop thread" in report.handoff_violations[0]
pipe.close()
print("DETECTED_OK")
""", sanitize="1")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "DETECTED_OK" in proc.stdout
