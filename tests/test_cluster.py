"""Cluster layer tests (mirror of reference tests/cluster.rs plus coverage
for what the reference leaves untested: zone rules, profile inheritance,
metadata git)."""

import asyncio
import os
import random

import pytest
import yaml

from chunky_bits_tpu.cluster import (
    Cluster,
    ClusterNodes,
    ClusterProfiles,
    MetadataGit,
)
from chunky_bits_tpu.errors import (
    MetadataReadError,
    NotEnoughWriters,
    SerdeError,
)
from chunky_bits_tpu.file import FileIntegrity, FileReadBuilder
from chunky_bits_tpu.utils import aio

# the examples/test.yaml shape with paths rewritten into tempdirs
# (tests/cluster.rs:63-103)
TEST_CLUSTER_YAML = """
destinations:
  - location: {repo}
    repeat: 99
metadata:
  type: path
  format: yaml
  path: {metadata}
profiles:
  default:
    data: 3
    parity: 2
"""


def make_cluster(tmp_path, repeat=99) -> Cluster:
    repo = tmp_path / "repo"
    meta = tmp_path / "metadata"
    repo.mkdir(exist_ok=True)
    meta.mkdir(exist_ok=True)
    text = TEST_CLUSTER_YAML.format(repo=repo, metadata=meta)
    cluster = Cluster.from_obj(yaml.safe_load(text))
    cluster.destinations.nodes[0].repeat = repeat
    return cluster


def synthetic_reader(n: int, seed: int = 0) -> aio.BytesReader:
    rng = random.Random(seed)
    return aio.BytesReader(bytes(rng.getrandbits(8) for _ in range(n)))


def test_cluster_from_yaml_examples(tmp_path):
    """All reference example shapes must parse (CI validate-example-clusters
    analogue).  The repo's examples/ mirror the reference's five shapes
    byte-compatibly (plus tpu.yaml), so the suite stays self-contained on
    machines without the read-only reference checkout; when the checkout
    IS present, its originals are validated too."""
    repo_examples = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "examples")
    roots = [repo_examples]
    if os.path.isdir("/root/reference/examples"):
        roots.append("/root/reference/examples")
    for root in roots:
        for name in ("local", "weights", "zones", "git", "test"):
            with open(os.path.join(root, f"{name}.yaml")) as f:
                obj = yaml.safe_load(f)
            cluster = Cluster.from_obj(obj)
            assert cluster.get_profile() is not None, (root, name)


def test_zone_map_flattening():
    nodes = ClusterNodes.from_obj({
        "ssd": [{"location": "/mnt/ssd1"}, {"location": "/mnt/ssd2"}],
        "offsite": {"location": "http://remote/repo"},
    })
    assert len(nodes) == 3
    zones = {str(n.location.location): n.zones for n in nodes}
    assert zones["/mnt/ssd1"] == {"ssd"}
    assert zones["http://remote/repo"] == {"offsite"}


def test_profile_inheritance():
    profiles = ClusterProfiles.from_obj({
        "default": {
            "data": 3, "parity": 2, "chunk_size": 20,
            "rules": {"ssd": {"minimum": 1, "maximum": None, "ideal": 2}},
        },
        "lowlatency": {"parity": 0,
                       "rules": {"ssd": None}},
        "wide": {"data": 10},
    })
    low = profiles.get("lowlatency")
    assert low.data_chunks == 3  # inherited
    assert low.parity_chunks == 0  # overridden
    assert low.zone_rules == {}  # null removes inherited rule
    wide = profiles.get("wide")
    assert wide.data_chunks == 10
    assert wide.parity_chunks == 2
    assert wide.zone_rules["ssd"].ideal == 2
    assert profiles.get("DEFAULT") is profiles.get_default()
    assert profiles.get("missing") is None


def test_profiles_require_default():
    with pytest.raises(SerdeError):
        ClusterProfiles.from_obj({"custom": {"data": 1, "parity": 0}})


def test_cluster_write_read(tmp_path):
    cluster = make_cluster(tmp_path)

    async def main():
        payload_reader = synthetic_reader(1 << 20, seed=1)
        profile = cluster.get_profile()
        profile.chunk_size = 16  # 64 KiB chunks for speed
        await cluster.write_file("some-file", payload_reader, profile)
        ref = await cluster.get_file_ref("some-file")
        assert ref.length == 1 << 20
        got = await FileReadBuilder(ref).read_all()
        assert got == bytes(synthetic_bytes_list(1 << 20, seed=1))
        files = await cluster.list_files(".")
        names = {f.path for f in files if f.is_file()}
        assert "some-file" in names

    asyncio.run(main())


def synthetic_bytes_list(n, seed):
    rng = random.Random(seed)
    return bytes(rng.getrandbits(8) for _ in range(n))


def test_not_enough_writers_via_repeat(tmp_path):
    """(tests/cluster.rs:122-143)"""
    cluster = make_cluster(tmp_path, repeat=3)  # 4 slots < 5 needed

    async def main():
        profile = cluster.get_profile()
        profile.chunk_size = 16
        with pytest.raises(NotEnoughWriters):
            await cluster.write_file(
                "toobig", synthetic_reader(100000), profile)

    asyncio.run(main())


def test_delete_and_resilver(tmp_path):
    """The core conformance test (tests/cluster.rs:145-231): delete 1 data
    + 1 parity chunk per part, assert Degraded-but-available, resilver,
    assert Valid/Resilvered and new_locations == deleted count."""
    cluster = make_cluster(tmp_path)

    async def main():
        profile = cluster.get_profile()
        profile.chunk_size = 16
        await cluster.write_file(
            "resilver-me", synthetic_reader(1 << 19, seed=7), profile)
        ref = await cluster.get_file_ref("resilver-me")

        deleted = 0
        for part in ref.parts:
            os.remove(part.data[0].locations[0].target)
            os.remove(part.parity[0].locations[0].target)
            deleted += 2

        verify = await ref.verify()
        assert verify.integrity() == FileIntegrity.DEGRADED
        assert verify.is_available()
        assert not verify.is_ideal()

        dest = cluster.get_destination(profile)
        report = await ref.resilver(dest)
        assert report.integrity() == FileIntegrity.RESILVERED
        assert len(report.new_locations()) == deleted

        # after resilver everything verifies Valid again
        verify2 = await ref.verify()
        assert verify2.integrity() == FileIntegrity.VALID

        # and the file still reads back byte-identical
        got = await FileReadBuilder(ref).read_all()
        assert got == synthetic_bytes_list(1 << 19, seed=7)

    asyncio.run(main())


def test_write_profiler_bounds(tmp_path):
    """(tests/cluster.rs:233-251)"""
    cluster = make_cluster(tmp_path)

    async def main():
        profile = cluster.get_profile()
        profile.chunk_size = 16
        report, _ref = await cluster.write_file_with_report(
            "profiled", synthetic_reader(1 << 18), profile)
        avg = report.average_write_duration()
        assert avg is not None
        assert 0 < avg < 1.0
        assert report.total_bytes() > 0

    asyncio.run(main())


def test_zone_rules_ideal_and_required(tmp_path):
    """Zone placement coverage the reference lacks: ideal forces the first
    placements into a zone; minimum requires them."""
    ssd_dirs, hdd_dirs = [], []
    for i in range(5):
        d = tmp_path / f"ssd{i}"
        d.mkdir()
        ssd_dirs.append(str(d))
        d = tmp_path / f"hdd{i}"
        d.mkdir()
        hdd_dirs.append(str(d))
    meta = tmp_path / "meta"
    meta.mkdir()
    obj = {
        "destinations": {
            "ssd": [{"location": p} for p in ssd_dirs],
            "hdd": [{"location": p} for p in hdd_dirs],
        },
        "metadata": {"type": "path", "format": "yaml", "path": str(meta)},
        "profiles": {
            "default": {
                "data": 2, "parity": 1, "chunk_size": 16,
                "rules": {"ssd": {"minimum": 0, "maximum": None,
                                  "ideal": 3}},
            },
            "pinned": {
                "rules": {"ssd": {"minimum": 3, "maximum": None,
                                  "ideal": 0}},
            },
        },
    }
    cluster = Cluster.from_obj(obj)

    async def main():
        # ideal: all 3 shards of each part land on ssd
        await cluster.write_file(
            "ideal", synthetic_reader(100000, seed=2),
            cluster.get_profile())
        ref = await cluster.get_file_ref("ideal")
        for part in ref.parts:
            for chunk in part.data + part.parity:
                assert "/ssd" in chunk.locations[0].target
        # minimum: same but via the required branch
        await cluster.write_file(
            "required", synthetic_reader(100000, seed=3),
            cluster.get_profile("pinned"))
        ref = await cluster.get_file_ref("required")
        for part in ref.parts:
            for chunk in part.data + part.parity:
                assert "/ssd" in chunk.locations[0].target

    asyncio.run(main())


def test_zone_rules_maximum(tmp_path):
    """maximum budget: no more than N shards per part in a zone."""
    ssd_dirs, hdd_dirs = [], []
    for i in range(5):
        d = tmp_path / f"ssd{i}"
        d.mkdir()
        ssd_dirs.append(str(d))
        d = tmp_path / f"hdd{i}"
        d.mkdir()
        hdd_dirs.append(str(d))
    meta = tmp_path / "meta"
    meta.mkdir()
    obj = {
        "destinations": {
            "ssd": [{"location": p} for p in ssd_dirs],
            "hdd": [{"location": p} for p in hdd_dirs],
        },
        "metadata": {"type": "path", "format": "yaml", "path": str(meta)},
        "profiles": {
            "default": {
                "data": 3, "parity": 2, "chunk_size": 16,
                "rules": {"ssd": {"minimum": 0, "maximum": 1, "ideal": 0}},
            },
        },
    }
    cluster = Cluster.from_obj(obj)

    async def main():
        await cluster.write_file(
            "capped", synthetic_reader(100000, seed=4),
            cluster.get_profile())
        ref = await cluster.get_file_ref("capped")
        for part in ref.parts:
            ssd_count = sum(
                1 for chunk in part.data + part.parity
                if "/ssd" in chunk.locations[0].target
            )
            assert ssd_count <= 1, f"zone maximum violated: {ssd_count}"

    asyncio.run(main())


def test_metadata_git(tmp_path):
    """MetadataGit commits every write (untested in the reference)."""
    gitdir = tmp_path / "gitmeta"
    gitdir.mkdir()

    async def main():
        proc = await asyncio.create_subprocess_exec(
            "git", "init", "-q", cwd=str(gitdir))
        assert await proc.wait() == 0
        for args in (["config", "user.email", "test@test"],
                     ["config", "user.name", "test"]):
            proc = await asyncio.create_subprocess_exec(
                "git", *args, cwd=str(gitdir))
            assert await proc.wait() == 0
        meta = MetadataGit(str(gitdir))
        await meta.write("obj1", {"length": 1, "parts": []})
        assert (await meta.read("obj1"))["length"] == 1
        with pytest.raises(MetadataReadError):
            await meta.read(".git/config")
        proc = await asyncio.create_subprocess_exec(
            "git", "log", "--oneline", cwd=str(gitdir),
            stdout=asyncio.subprocess.PIPE)
        out, _ = await proc.communicate()
        assert b"Write obj1" in out

    asyncio.run(main())


def test_metadata_put_script(tmp_path):
    meta_dir = tmp_path / "meta"
    meta_dir.mkdir()
    marker = tmp_path / "marker"

    async def main():
        from chunky_bits_tpu.cluster import MetadataPath

        meta = MetadataPath(
            str(meta_dir), put_script=f"touch {marker}")
        await meta.write("f", {"length": 0, "parts": []})
        assert marker.exists()
        failing = MetadataPath(str(meta_dir), put_script="exit 3",
                               fail_on_script_error=True)
        with pytest.raises(MetadataReadError):
            await failing.write("g", {"length": 0, "parts": []})

    asyncio.run(main())


def test_parity_zero_profile(tmp_path):
    """data-only profile (examples/zones.yaml lowlatency shape: p=0):
    writes produce no parity chunks, reads and verify work, and chunk
    loss is unrecoverable by design."""
    dirs = []
    for i in range(4):
        d = tmp_path / f"disk{i}"
        d.mkdir()
        dirs.append(str(d))
    meta = tmp_path / "meta"
    meta.mkdir()
    cluster = Cluster.from_obj({
        "destinations": [{"location": x} for x in dirs],
        "metadata": {"type": "path", "format": "yaml", "path": str(meta)},
        "profiles": {"default": {"data": 3, "parity": 0,
                                 "chunk_size": 12}},
    })
    payload = os.urandom(30000)

    async def main():
        from chunky_bits_tpu.errors import FileReadError

        await cluster.write_file("x", aio.BytesReader(payload),
                                 cluster.get_profile())
        ref = await cluster.get_file_ref("x")
        for part in ref.parts:
            assert part.parity == []
            assert len(part.data) == 3
        got = await ref.read_builder().read_all()
        assert got == payload
        report = await ref.verify()
        assert report.integrity() == FileIntegrity.VALID
        # without parity, a lost chunk is gone
        os.remove(ref.parts[0].data[0].locations[0].target)
        with pytest.raises(FileReadError):
            await (await cluster.get_file_ref("x")) \
                .read_builder().read_all()

    asyncio.run(main())


def test_resilver_over_http_nodes(tmp_path):
    """Delete-and-resilver against real (in-process) HTTP storage nodes:
    repaired shards are re-placed over HTTP PUT, and the node already
    holding a sibling shard is excluded (destination.rs:85-94)."""
    from tests.http_node import FakeHttpNode

    async def main():
        nodes = [await FakeHttpNode().start() for _ in range(5)]
        meta = tmp_path / "meta"
        meta.mkdir()
        try:
            cluster = Cluster.from_obj({
                "destinations": [{"location": n.url + "/"} for n in nodes],
                "metadata": {"type": "path", "format": "yaml",
                             "path": str(meta)},
                "profiles": {"default": {"data": 3, "parity": 2,
                                         "chunk_size": 12}},
            })
            payload = os.urandom(40000)
            await cluster.write_file("x", aio.BytesReader(payload),
                                     cluster.get_profile())
            ref = await cluster.get_file_ref("x")
            # drop one data chunk per part from the node stores
            for part in ref.parts:
                victim = str(part.data[0].locations[0])
                for n in nodes:
                    key = victim[len(n.url) + 1:] \
                        if victim.startswith(n.url) else None
                    if key is not None:
                        assert n.store.pop(key, None) is not None
                        break
                else:
                    raise AssertionError(f"no node held {victim}")
            report = await ref.verify()
            assert report.integrity() == FileIntegrity.DEGRADED
            resilver_report = await ref.resilver(
                cluster.get_destination(cluster.get_profile()))
            assert resilver_report.new_locations()
            # updated ref must verify Valid and read back identical
            await cluster.write_file_ref("x", ref)
            ref2 = await cluster.get_file_ref("x")
            report = await ref2.verify()
            assert report.integrity() == FileIntegrity.VALID
            got = await ref2.read_builder().read_all()
            assert got == payload
        finally:
            for n in nodes:
                await n.stop()

    asyncio.run(main())


def test_placement_is_hash_seeded_deterministic(tmp_path):
    """The placement RNG is seeded from the shard hash (writer.rs:80-85):
    writing identical content twice into identical fresh clusters lands
    every shard on the same nodes."""
    def build(root):
        dirs = []
        for i in range(6):
            d = root / f"disk{i}"
            d.mkdir(parents=True)
            dirs.append(str(d))
        meta = root / "meta"
        meta.mkdir()
        return Cluster.from_obj({
            "destinations": [{"location": x} for x in dirs],
            "metadata": {"type": "path", "format": "yaml",
                         "path": str(meta)},
            "profiles": {"default": {"data": 3, "parity": 2,
                                     "chunk_size": 12}},
        }), dirs

    payload = os.urandom(30000)

    async def placements(root):
        cluster, dirs = build(root)
        await cluster.write_file("x", aio.BytesReader(payload),
                                 cluster.get_profile())
        ref = await cluster.get_file_ref("x")
        out = []
        for part in ref.parts:
            for chunk in part.data + part.parity:
                # disk index of the first location, relative to its root
                target = chunk.locations[0].target
                idx = next(i for i, d in enumerate(dirs)
                           if target.startswith(d))
                out.append(idx)
        return out

    a = asyncio.run(placements(tmp_path / "a"))
    b = asyncio.run(placements(tmp_path / "b"))
    assert a == b


def test_metadata_put_script_signal(tmp_path):
    """Signal-death is reported distinctly from a nonzero exit code
    (the reference's ExitCode/Signal variants, src/error.rs:236-253)."""
    meta_dir = tmp_path / "meta"
    meta_dir.mkdir()

    async def main():
        from chunky_bits_tpu.cluster import MetadataPath

        killed = MetadataPath(str(meta_dir), put_script="kill -TERM $$",
                              fail_on_script_error=True)
        with pytest.raises(MetadataReadError, match="signal 15"):
            await killed.write("sig", {"length": 0, "parts": []})
        coded = MetadataPath(str(meta_dir), put_script="exit 3",
                             fail_on_script_error=True)
        with pytest.raises(MetadataReadError, match="code 3"):
            await coded.write("code", {"length": 0, "parts": []})

    asyncio.run(main())
