"""Randomized conformance sweep across geometry, lengths, erasure patterns
and backends.

The reference pins behavior with a d×p grid test (tests/file.rs:26-56) and
one delete/resilver cycle (tests/cluster.rs:145-231); this sweep widens
that to seeded random geometries with adversarial lengths (stripe-aligned,
off-by-one, sub-stripe, empty tail) and random erasure patterns, asserting:

* numpy / native backends produce byte-identical parity (the jax backend's
  identity is covered on the virtual mesh in test_backends/test_parallel);
* every reconstructible erasure pattern round-trips byte-identically;
* unreconstructible patterns (> p erasures) raise, never corrupt.
"""

import numpy as np
import pytest

from chunky_bits_tpu.errors import ErasureError
from chunky_bits_tpu.ops.backend import ErasureCoder, NumpyBackend, get_backend


def _native_or_skip():
    try:
        return get_backend("native")
    except Exception as err:  # pragma: no cover - no compiler in env
        pytest.skip(f"native backend unavailable: {err}")


@pytest.mark.parametrize("seed", range(8))
def test_random_geometry_roundtrip(seed):
    rng = np.random.default_rng(seed)
    d = int(rng.integers(1, 17))
    p = int(rng.integers(0, 9))
    size = int(rng.integers(1, 3000))
    batch = int(rng.integers(1, 5))

    data = rng.integers(0, 256, (batch, d, size), dtype=np.uint8)
    numpy_coder = ErasureCoder(d, p, NumpyBackend())
    native_coder = ErasureCoder(d, p, _native_or_skip())

    parity_np = numpy_coder.encode_batch(data)
    parity_nat = native_coder.encode_batch(data)
    assert np.array_equal(parity_np, parity_nat)

    if p == 0:
        return
    full = np.concatenate([data, parity_np], axis=1)

    for _ in range(4):
        n_erase = int(rng.integers(1, p + 1))
        erased = rng.choice(d + p, size=n_erase, replace=False)
        shards = [None if i in erased else full[0, i]
                  for i in range(d + p)]
        out = numpy_coder.reconstruct(list(shards))
        for i in range(d + p):
            assert np.array_equal(out[i], full[0, i]), (d, p, erased, i)
        out = native_coder.reconstruct(list(shards))
        for i in range(d + p):
            assert np.array_equal(out[i], full[0, i])


@pytest.mark.parametrize("seed", range(6))
def test_xor_schedule_conformance(seed):
    """Scheduled-XOR leg of the sweep: random geometry / stripe-edge
    lengths / random erasure patterns, asserting the engine (numpy
    reference executor AND the native cb_xor_exec dispatch) emits
    byte-identical parity and byte-identical reconstructions — the
    same decode route the ReconstructBatcher and the RepairPlanner's
    decode plans dispatch through (reconstruct_batch_picked)."""
    from chunky_bits_tpu.ops import xor_schedule

    rng = np.random.default_rng(500 + seed)
    d = int(rng.integers(1, 17))
    p = int(rng.integers(1, 9))
    # stripe-edge but plane-eligible lengths (S % 8 == 0); the odd
    # lengths' fall-back-to-table identity is pinned in
    # tests/test_xor_schedule.py
    size = int(rng.integers(1, 300)) * 8
    batch = int(rng.integers(1, 4))

    data = rng.integers(0, 256, (batch, d, size), dtype=np.uint8)
    numpy_coder = ErasureCoder(d, p, NumpyBackend())
    try:
        from chunky_bits_tpu.ops.cpu_backend import NativeBackend

        xor_coder = ErasureCoder(d, p, NativeBackend(xor_schedule=True))
    except Exception as err:  # pragma: no cover - no compiler in env
        pytest.skip(f"native backend unavailable: {err}")

    parity_np = numpy_coder.encode_batch(data)
    assert np.array_equal(parity_np, xor_coder.encode_batch(data))
    sched = xor_schedule.get_schedule(xor_coder.parity_rows)
    assert np.array_equal(parity_np,
                          xor_schedule.apply_numpy(sched, data))

    full = np.concatenate([data, parity_np], axis=1)
    for _ in range(4):
        n_erase = int(rng.integers(1, p + 1))
        erased = rng.choice(d + p, size=n_erase, replace=False)
        shards = [None if i in erased else full[0, i]
                  for i in range(d + p)]
        out = xor_coder.reconstruct(list(shards))
        for i in range(d + p):
            assert np.array_equal(out[i], full[0, i]), (d, p, erased, i)


def _pm_geometry(rng):
    """A random geometry pm-msr supports: k >= 2, p >= k-1."""
    k = int(rng.integers(2, 7))
    p = int(rng.integers(k - 1, k + 3))
    return k, p


@pytest.mark.parametrize("seed", range(8))
def test_pm_msr_conformance(seed):
    """Product-matrix MSR leg of the sweep (ops/pm_msr.py): random
    supported geometry / alpha-divisible stripe lengths / random
    erasure patterns AND random single-chunk regenerations, asserting
    the numpy-backend coder (the oracle) and the native coder emit
    byte-identical parity, reconstructions, helper projections and
    regenerated chunks — plus round-trip against the original data."""
    from chunky_bits_tpu.ops.pm_msr import PMMSRCoder

    rng = np.random.default_rng(900 + seed)
    k, p = _pm_geometry(rng)
    alpha, dh = k - 1, 2 * (k - 1)
    size = int(rng.integers(1, 400)) * alpha
    batch = int(rng.integers(1, 4))

    data = rng.integers(0, 256, (batch, k, size), dtype=np.uint8)
    oracle = PMMSRCoder(k, p, NumpyBackend())
    native = PMMSRCoder(k, p, _native_or_skip())

    parity = oracle.encode_batch(data)
    assert np.array_equal(parity, native.encode_batch(data))
    full = np.concatenate([data, parity], axis=1)

    for _ in range(4):
        n_erase = int(rng.integers(1, p + 1))
        erased = rng.choice(k + p, size=n_erase, replace=False)
        shards = [None if i in erased else full[0, i]
                  for i in range(k + p)]
        for coder in (oracle, native):
            out = coder.reconstruct(list(shards))
            for i in range(k + p):
                assert np.array_equal(out[i], full[0, i]), \
                    (k, p, erased, i, coder.backend.name)

    for _ in range(3):
        failed = int(rng.integers(0, k + p))
        others = [i for i in range(k + p) if i != failed]
        helpers = sorted(rng.permutation(others)[:dh].tolist())
        projs = np.stack([oracle.project_batch(failed, full[:, h, :])
                          for h in helpers], axis=1)
        projs_nat = np.stack([native.project_batch(failed, full[:, h, :])
                              for h in helpers], axis=1)
        assert np.array_equal(projs, projs_nat)
        # each helper ships beta = size/alpha bytes: dh*beta = 2*size
        assert projs.shape == (batch, dh, size // alpha)
        regen = oracle.repair_batch(failed, helpers, projs)
        assert np.array_equal(regen, full[:, failed, :]), (k, p, failed)
        assert np.array_equal(
            native.repair_batch(failed, helpers, projs), regen)


@pytest.mark.parametrize("seed", range(4))
def test_pm_msr_xor_schedule_conformance(seed):
    """The engine-on leg: every pm-msr matrix apply (encode, decode,
    projection, repair combine) lowered through the scheduled-XOR
    engine must stay byte-identical to the numpy oracle — the same
    route a CHUNKY_BITS_TPU_XOR_SCHEDULE=1 host runs repair on."""
    from chunky_bits_tpu.ops.pm_msr import PMMSRCoder

    rng = np.random.default_rng(950 + seed)
    k, p = _pm_geometry(rng)
    alpha, dh = k - 1, 2 * (k - 1)
    # plane-eligible sub-stripe lengths (S/alpha % 8 == 0) so the
    # engine runs rather than falling back to the table path
    size = int(rng.integers(1, 60)) * 8 * alpha
    data = rng.integers(0, 256, (2, k, size), dtype=np.uint8)
    oracle = PMMSRCoder(k, p, NumpyBackend())
    try:
        from chunky_bits_tpu.ops.cpu_backend import NativeBackend

        xor = PMMSRCoder(k, p, NativeBackend(xor_schedule=True))
    except Exception as err:  # pragma: no cover - no compiler in env
        pytest.skip(f"native backend unavailable: {err}")

    parity = oracle.encode_batch(data)
    assert np.array_equal(parity, xor.encode_batch(data))
    full = np.concatenate([data, parity], axis=1)
    erased = rng.choice(k + p, size=p, replace=False)
    shards = [None if i in erased else full[0, i] for i in range(k + p)]
    out = xor.reconstruct(list(shards))
    for i in range(k + p):
        assert np.array_equal(out[i], full[0, i]), (k, p, erased, i)
    failed = int(rng.integers(0, k + p))
    helpers = [i for i in range(k + p) if i != failed][:dh]
    projs = np.stack([xor.project_batch(failed, full[:, h, :])
                      for h in helpers], axis=1)
    assert np.array_equal(
        projs, np.stack([oracle.project_batch(failed, full[:, h, :])
                         for h in helpers], axis=1))
    assert np.array_equal(xor.repair_batch(failed, helpers, projs),
                          full[:, failed, :])


def test_pm_msr_jax_conformance():
    """The device-backend leg (virtual CPU mesh in CI): pm-msr parity,
    reconstruction and regeneration through the jax bit-plane backend
    must match the numpy oracle byte-for-byte — the code rides the
    same apply_matrix primitive, so this pins the whole dispatch
    path, not new kernels."""
    from chunky_bits_tpu.ops.backend import get_backend
    from chunky_bits_tpu.ops.pm_msr import PMMSRCoder

    k, p = 3, 2
    alpha, dh = k - 1, 2 * (k - 1)
    rng = np.random.default_rng(1000)
    size = 64 * alpha
    data = rng.integers(0, 256, (2, k, size), dtype=np.uint8)
    oracle = PMMSRCoder(k, p, NumpyBackend())
    jax_coder = PMMSRCoder(k, p, get_backend("jax"))
    parity = oracle.encode_batch(data)
    assert np.array_equal(parity, jax_coder.encode_batch(data))
    full = np.concatenate([data, parity], axis=1)
    shards = [None if i in (0, 4) else full[0, i] for i in range(k + p)]
    out = jax_coder.reconstruct(list(shards))
    for i in range(k + p):
        assert np.array_equal(out[i], full[0, i]), i
    helpers = [1, 2, 3, 4]
    projs = np.stack([jax_coder.project_batch(0, full[:, h, :])
                      for h in helpers], axis=1)
    assert np.array_equal(jax_coder.repair_batch(0, helpers, projs),
                          full[:, 0, :])


@pytest.mark.parametrize("seed", range(6))
def test_mesh_backend_conformance(seed):
    """The multi-device leg of the sweep (8-device virtual CPU mesh in
    CI): random geometry / adversarial lengths / random erasure
    patterns through the auto-laid-out ``mesh`` backend — every
    dispatch picks its own ('dp','sp')/('dp','tp') layout and rides
    the double-buffered pipeline, and every byte must still match the
    numpy oracle (encode AND the reconstruct decode route)."""
    rng = np.random.default_rng(1600 + seed)
    d = int(rng.integers(1, 17))
    p = int(rng.integers(1, 9))
    # adversarial lengths: sub-LANE, off-by-one, and mesh-indivisible
    # sizes all exercise the 'sp' padding path
    size = int(rng.choice([1, 63, 65, int(rng.integers(1, 3000))]))
    batch = int(rng.integers(1, 6))  # incl. batches that don't divide 8

    data = rng.integers(0, 256, (batch, d, size), dtype=np.uint8)
    numpy_coder = ErasureCoder(d, p, NumpyBackend())
    mesh_coder = ErasureCoder(d, p, get_backend("mesh"))

    parity = numpy_coder.encode_batch(data)
    assert np.array_equal(parity, mesh_coder.encode_batch(data)), \
        (d, p, size, batch)
    full = np.concatenate([data, parity], axis=1)

    for _ in range(4):
        n_erase = int(rng.integers(1, p + 1))
        erased = rng.choice(d + p, size=n_erase, replace=False)
        shards = [None if i in erased else full[0, i]
                  for i in range(d + p)]
        out = mesh_coder.reconstruct(list(shards))
        for i in range(d + p):
            assert np.array_equal(out[i], full[0, i]), (d, p, erased, i)


def test_pm_msr_mesh_conformance():
    """pm-msr through the mesh backend: parity, reconstruction,
    helper projections and single-chunk regeneration all ride the
    same sharded apply_matrix primitive and must match the numpy
    oracle byte-for-byte — the repair plane's msr plans run on
    whatever backend the fleet configures, mesh included."""
    from chunky_bits_tpu.ops.pm_msr import PMMSRCoder

    k, p = 5, 4
    alpha, dh = k - 1, 2 * (k - 1)
    rng = np.random.default_rng(1700)
    size = 64 * alpha
    data = rng.integers(0, 256, (2, k, size), dtype=np.uint8)
    oracle = PMMSRCoder(k, p, NumpyBackend())
    mesh_coder = PMMSRCoder(k, p, get_backend("mesh"))
    parity = oracle.encode_batch(data)
    assert np.array_equal(parity, mesh_coder.encode_batch(data))
    full = np.concatenate([data, parity], axis=1)
    shards = [None if i in (1, 6) else full[0, i] for i in range(k + p)]
    out = mesh_coder.reconstruct(list(shards))
    for i in range(k + p):
        assert np.array_equal(out[i], full[0, i]), i
    helpers = [0, 2, 3, 4, 5, 6, 7, 8]
    projs = np.stack([mesh_coder.project_batch(1, full[:, h, :])
                      for h in helpers], axis=1)
    assert np.array_equal(
        projs, np.stack([oracle.project_batch(1, full[:, h, :])
                         for h in helpers], axis=1))
    assert np.array_equal(mesh_coder.repair_batch(1, helpers, projs),
                          full[:, 1, :])


@pytest.mark.parametrize("seed", range(3))
def test_pm_msr_rejections(seed):
    """The failure surface: unsupported geometry, unknown code names,
    too-few helpers, and non-alpha-divisible stripe lengths all raise
    ErasureError — never wrong bytes."""
    from chunky_bits_tpu.ops.backend import get_coder
    from chunky_bits_tpu.ops.pm_msr import PMMSRCoder, geometry_error

    # parity below the helper budget
    assert geometry_error(5, 3) is not None
    with pytest.raises(ErasureError):
        PMMSRCoder(5, 3, NumpyBackend())
    # k=1 has no sub-symbol structure
    with pytest.raises(ErasureError):
        PMMSRCoder(1, 2, NumpyBackend())
    with pytest.raises(ErasureError):
        get_coder(3, 2, "numpy", code="no-such-code")

    rng = np.random.default_rng(1100 + seed)
    k, p = _pm_geometry(rng)
    if k < 3:
        k, p = 3, 2  # alpha >= 2 so indivisible lengths exist
    coder = PMMSRCoder(k, p, NumpyBackend())
    bad = rng.integers(0, 256, (1, k, (k - 1) * 8 + 1), dtype=np.uint8)
    with pytest.raises(ErasureError):
        coder.encode_batch(bad)
    good = rng.integers(0, 256, (1, k, (k - 1) * 8), dtype=np.uint8)
    parity = coder.encode_batch(good)
    full = np.concatenate([good, parity], axis=1)
    with pytest.raises(ErasureError):
        coder.repair_matrix(0, list(range(1, 2 * (k - 1))))  # short
    with pytest.raises(ErasureError):
        coder.repair_matrix(0, [0] + list(range(2, 2 * (k - 1) + 1)))
    # projections stacked for the wrong helper count are refused too
    helpers = list(range(1, 2 * (k - 1) + 1))
    projs = np.stack([coder.project_batch(0, full[:, h, :])
                      for h in helpers], axis=1)
    with pytest.raises(ErasureError):
        coder.repair_batch(0, helpers, projs[:, :-1, :])


@pytest.mark.parametrize("seed", range(4))
def test_too_many_erasures_raise(seed):
    rng = np.random.default_rng(100 + seed)
    d = int(rng.integers(2, 9))
    p = int(rng.integers(1, 5))
    size = 257
    data = rng.integers(0, 256, (1, d, size), dtype=np.uint8)
    coder = ErasureCoder(d, p, NumpyBackend())
    full = np.concatenate([data, coder.encode_batch(data)], axis=1)

    erased = rng.choice(d + p, size=p + 1, replace=False)
    shards = [None if i in erased else full[0, i] for i in range(d + p)]
    with pytest.raises(ErasureError):
        coder.reconstruct(shards)


@pytest.mark.parametrize("seed", range(4))
def test_slab_vs_path_storage_roundtrip_fuzz(seed, tmp_path):
    """Storage-plane conformance: seeded random geometry/length objects
    written through a PACKED (slab:) cluster and a path cluster produce
    identical content addresses, read back byte-identically, and after
    a random reconstructible erasure of packed extents still decode to
    the same bytes — the slab store is a layout, never a codec."""
    import asyncio
    import os

    from chunky_bits_tpu.cluster import Cluster
    from chunky_bits_tpu.utils import aio

    rng = np.random.default_rng(300 + seed)
    d = int(rng.integers(2, 7))
    p = int(rng.integers(1, 4))
    chunk_log2 = int(rng.integers(10, 14))
    stripe = d * (1 << chunk_log2)
    length = int(rng.choice([1, stripe - 1, stripe, stripe + 1,
                             3 * stripe + 17]))
    payload = rng.integers(0, 256, length, dtype=np.uint8).tobytes()

    def spec(sub: str, packed: bool) -> dict:
        dirs = []
        for i in range(d + p + 1):
            path = os.path.join(str(tmp_path), sub, f"disk{i}")
            os.makedirs(path, exist_ok=True)
            dirs.append(f"slab:{path}" if packed else path)
        meta = os.path.join(str(tmp_path), sub, "meta")
        os.makedirs(meta, exist_ok=True)
        return {
            "destinations": [{"location": x} for x in dirs],
            "metadata": {"type": "path", "format": "yaml", "path": meta},
            "profiles": {"default": {"data": d, "parity": p,
                                     "chunk_size": chunk_log2}},
        }

    async def run(packed: bool):
        cluster = Cluster.from_obj(spec("slab" if packed else "files",
                                        packed))
        await cluster.write_file("obj", aio.BytesReader(payload),
                                 cluster.get_profile())
        ref = await cluster.get_file_ref("obj")
        got = await cluster.file_read_builder(ref).read_all()
        assert got == payload, (d, p, chunk_log2, length, packed)
        hashes = [str(c.hash) for part in ref.parts
                  for c in part.data + part.parity]
        if packed:
            # random reconstructible erasure: up to p extents per part
            for part in ref.parts:
                chunks = part.data + part.parity
                n_erase = int(rng.integers(1, p + 1))
                for ci in rng.choice(len(chunks), size=n_erase,
                                     replace=False):
                    await chunks[int(ci)].locations[0].delete()
            got = await cluster.file_read_builder(ref).read_all()
            assert got == payload, \
                f"post-erasure decode mismatch (d={d} p={p})"
        return hashes

    packed_hashes = asyncio.run(run(True))
    plain_hashes = asyncio.run(run(False))
    assert packed_hashes == plain_hashes


def test_adversarial_lengths():
    """Stripe-edge lengths through the part codec's split/pad math
    (reference round-up semantics, src/file/file_part.rs:150-158)."""
    from chunky_bits_tpu.file.file_part import split_into_shards

    for d in (1, 2, 3, 5, 8):
        for length in (0, 1, d - 1, d, d + 1, 2 * d, 7 * d + 3, 1024):
            if length < 0:
                continue
            buf = bytes(range(256)) * ((length // 256) + 1)
            buf = buf[:length]
            shards, shard_len = split_into_shards(buf, length, d)
            assert shard_len == (length + d - 1) // d
            joined = b"".join(bytes(s) for s in shards)
            assert joined[:length] == buf
            assert all(b == 0 for b in joined[length:])
