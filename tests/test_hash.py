"""Hash KATs (mirrors reference tests/hash.rs)."""

import asyncio

import pytest

from chunky_bits_tpu.errors import SerdeError
from chunky_bits_tpu.file.hashing import AnyHash, Sha256Hash


def test_sha256_known_answer():
    h = Sha256Hash.from_buf(b"hello world")
    assert h.hex() == (
        "b94d27b9934d3e08a52e52d7da7dabfac484efe37a5380ee9088f7ace2efcde9"
    )
    assert h.verify(b"hello world")
    assert not h.verify(b"hello worlD")


def test_any_hash_roundtrip():
    h = AnyHash.from_buf(b"data")
    s = str(h)
    assert s.startswith("sha256-")
    assert AnyHash.parse(s) == h


def test_any_hash_parse_errors():
    with pytest.raises(SerdeError):
        AnyHash.parse("md5-abcdef")
    with pytest.raises(SerdeError):
        AnyHash.parse("nodash")
    with pytest.raises(SerdeError):
        AnyHash.parse("sha256-zz")


def test_async_hashing_roundtrip():
    async def main():
        h = AnyHash.from_buf(b"stream me")
        assert await h.verify_async(b"stream me")
        assert not await h.verify_async(b"other")
        assert await h.rehash_async(b"x") == AnyHash.from_buf(b"x")

    asyncio.run(main())
