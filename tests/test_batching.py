"""ReconstructBatcher: coalesced decode dispatches on read/resilver paths.

The reference reconstructs one part per blocking-pool call
(src/file/file_part.rs:128,302-305); the batcher turns the concurrent
per-part reconstructions into grouped [B, d+p, S] dispatches.  These tests
check identity against the per-part oracle, grouping behavior, error
propagation, and the wired-in degraded read / resilver paths.
"""

import asyncio
import os

import numpy as np
import pytest

from chunky_bits_tpu.errors import ErasureError
from chunky_bits_tpu.file.collection_destination import LocationsDestination
from chunky_bits_tpu.file.location import Location
from chunky_bits_tpu.file.reader import FileReadBuilder
from chunky_bits_tpu.file.writer import FileWriteBuilder
from chunky_bits_tpu.ops.backend import ErasureCoder, NumpyBackend
from chunky_bits_tpu.ops.batching import ReconstructBatcher
from chunky_bits_tpu.utils import aio


def _make_parts(n_parts, d, p, size, seed=0):
    rng = np.random.default_rng(seed)
    coder = ErasureCoder(d, p, NumpyBackend())
    full = []
    for _ in range(n_parts):
        data = rng.integers(0, 256, (1, d, size), dtype=np.uint8)
        parity = coder.encode_batch(data)
        full.append([data[0, i] for i in range(d)]
                    + [parity[0, i] for i in range(p)])
    return full


def test_batched_identity_same_pattern():
    d, p, size = 4, 2, 512
    parts = _make_parts(8, d, p, size)

    async def main():
        batcher = ReconstructBatcher(backend="numpy")

        async def one(rows):
            punched = list(rows)
            punched[1] = None   # same erasure pattern for every part
            punched[d] = None
            return await batcher.reconstruct(d, p, punched)

        results = await asyncio.gather(*[one(r) for r in parts])
        for got, want in zip(results, parts):
            for i in range(d + p):
                assert np.array_equal(got[i], want[i]), f"shard {i}"
        # all 8 concurrent same-pattern requests shared dispatches
        assert batcher.dispatches < 8

    asyncio.run(main())


def test_batched_mixed_patterns_and_sizes():
    d, p = 3, 2
    parts_a = _make_parts(3, d, p, 256, seed=1)
    parts_b = _make_parts(3, d, p, 384, seed=2)

    async def main():
        batcher = ReconstructBatcher(backend="numpy")

        async def one(rows, missing):
            punched = list(rows)
            for i in missing:
                punched[i] = None
            got = await batcher.reconstruct(d, p, punched)
            for i in range(d + p):
                assert np.array_equal(got[i], rows[i])

        await asyncio.gather(
            *[one(r, [0]) for r in parts_a],
            *[one(r, [2, 4]) for r in parts_b],
        )

    asyncio.run(main())


def test_batched_data_only():
    d, p, size = 3, 2, 128
    (rows,) = _make_parts(1, d, p, size)

    async def main():
        batcher = ReconstructBatcher(backend="numpy")
        punched = list(rows)
        punched[0] = None
        punched[d] = None  # parity also missing
        got = await batcher.reconstruct(d, p, punched, data_only=True)
        assert np.array_equal(got[0], rows[0])
        assert got[d] is None  # parity not rebuilt in data-only mode

    asyncio.run(main())


def test_batched_too_few_shards():
    d, p, size = 3, 2, 128
    (rows,) = _make_parts(1, d, p, size)

    async def main():
        batcher = ReconstructBatcher(backend="numpy")
        punched = [rows[0], rows[1]] + [None] * 3
        with pytest.raises(ErasureError):
            await batcher.reconstruct(d, p, punched)

    asyncio.run(main())


def test_batched_mismatched_length_rejected():
    d, p = 3, 2
    (rows,) = _make_parts(1, d, p, 128)

    async def main():
        batcher = ReconstructBatcher(backend="numpy")
        punched = list(rows)
        punched[0] = None
        punched[1] = punched[1][:64]  # wrong length
        with pytest.raises(ErasureError):
            await batcher.reconstruct(d, p, punched)

    asyncio.run(main())


def test_degraded_multi_part_read_batches(tmp_path, monkeypatch):
    """A degraded read of a many-part file reconstructs through shared
    dispatches and still yields byte-identical content."""
    captured = []
    orig_init = ReconstructBatcher.__init__

    def spy_init(self, *a, **kw):
        orig_init(self, *a, **kw)
        captured.append(self)

    monkeypatch.setattr(ReconstructBatcher, "__init__", spy_init)

    payload = np.random.default_rng(7).integers(
        0, 256, 256000, dtype=np.uint8).tobytes()
    chunk_size = 4096
    dirs = []
    for i in range(5):
        droot = tmp_path / f"disk{i}"
        droot.mkdir()
        dirs.append(Location.parse(str(droot)))

    async def main():
        dest = LocationsDestination(dirs)
        ref = await (FileWriteBuilder()
                     .with_destination(dest)
                     .with_chunk_size(chunk_size)
                     .with_data_chunks(3)
                     .with_parity_chunks(2)
                     .write(aio.BytesReader(payload)))
        assert len(ref.parts) > 10
        # same loss pattern on every part: data[1] gone
        for part in ref.parts:
            os.remove(part.data[1].locations[0].target)
        got = await FileReadBuilder(ref).read_all()
        assert got == payload

    n_parts_reconstructed = 21  # ceil(len(payload) / (3 * chunk_size))
    # Coalescing depends on what is concurrently in flight, which a
    # heavily loaded 1-core host can momentarily serialize; one retry
    # squares away that scheduling flake without weakening the assertion.
    for attempt in (0, 1):
        captured.clear()
        asyncio.run(main())
        assert captured, "read path did not construct a batcher"
        batcher = captured[-1]
        assert batcher.dispatches > 0
        if batcher.dispatches < n_parts_reconstructed:
            break
    else:
        raise AssertionError(
            f"no coalescing in {n_parts_reconstructed} reconstructions "
            f"across 2 runs ({batcher.dispatches} dispatches)")


def test_encode_hash_batcher_identity_and_coalescing():
    """Concurrent small-object encodes return parity + digests identical
    to the unbatched coder; merge-preferring (device) backends coalesce
    pending requests into shared dispatches, CPU backends run them
    unmerged (the concatenate copy costs more than it saves there)."""
    from chunky_bits_tpu.ops.backend import register_backend
    from chunky_bits_tpu.ops.batching import EncodeHashBatcher

    d, p, size = 4, 2, 1024
    rng = np.random.default_rng(11)
    batches = [rng.integers(0, 256, (1, d, size), dtype=np.uint8)
               for _ in range(12)]
    coder = ErasureCoder(d, p, NumpyBackend())

    class MergingNumpy(NumpyBackend):
        """Stands in for a device backend in the merge path."""

        name = "numpy-merging"
        prefers_merged_batches = True

    async def run(backend):
        batcher = EncodeHashBatcher(backend=backend)
        results = await asyncio.gather(
            *[batcher.encode_hash(d, p, b) for b in batches])
        for stacked, (parity, digests) in zip(batches, results):
            want_par, want_dig = coder.encode_hash_batch(stacked)
            assert np.array_equal(parity, want_par)
            assert np.array_equal(digests, want_dig)
        return batcher

    async def main():
        # the merge path: concurrent requests share dispatches
        assert (await run("numpy-merging")).dispatches < len(batches)
        # the unmerged CPU path: one codec dispatch per request, same
        # results, but requests still coalesce into shared groups
        b = await run("numpy")
        assert b.dispatches == len(batches)
        assert b.groups < len(batches)

    from chunky_bits_tpu.ops import backend as backend_mod

    register_backend(MergingNumpy())
    try:
        asyncio.run(main())
    finally:
        backend_mod._REGISTRY.pop("numpy-merging", None)


def test_encode_hash_batcher_mixed_geometries():
    from chunky_bits_tpu.ops.batching import EncodeHashBatcher

    rng = np.random.default_rng(12)
    jobs = [(3, 2, 256), (3, 2, 256), (5, 1, 512), (2, 0, 128)]
    coder_cache = {}

    async def main():
        batcher = EncodeHashBatcher(backend="numpy")

        async def one(d, p, size):
            stacked = rng.integers(0, 256, (2, d, size), dtype=np.uint8)
            parity, digests = await batcher.encode_hash(d, p, stacked)
            key = (d, p)
            if key not in coder_cache:
                coder_cache[key] = ErasureCoder(d, p, NumpyBackend())
            want_par, want_dig = coder_cache[key].encode_hash_batch(stacked)
            assert np.array_equal(parity, want_par)
            assert np.array_equal(digests, want_dig)

        await asyncio.gather(*[one(*j) for j in jobs])

    asyncio.run(main())


def test_cluster_concurrent_small_writes_coalesce(tmp_path):
    """Many concurrent small-object writes into a jax-backend cluster
    share encode dispatches through the cluster's per-loop batcher, and
    every object reads back byte-identical."""
    from tests.test_tpu_cluster import make_jax_cluster

    cluster = make_jax_cluster(tmp_path, d=3, p=2)
    rng = np.random.default_rng(13)
    payloads = {f"obj{i}": rng.integers(0, 256, 40000, dtype=np.uint8)
                .tobytes() for i in range(10)}

    async def main():
        profile = cluster.get_profile()
        await asyncio.gather(*[
            cluster.write_file(name, aio.BytesReader(data), profile)
            for name, data in payloads.items()])
        batcher = cluster._encode_batchers.get(asyncio.get_running_loop())
        assert batcher is not None, "jax cluster should engage the batcher"
        assert batcher.dispatches > 0
        # 10 files x >=1 part each coalesced into fewer dispatches
        total_parts = 0
        for name in payloads:
            ref = await cluster.get_file_ref(name)
            total_parts += len(ref.parts)
        assert batcher.dispatches < total_parts
        for name, data in payloads.items():
            got = await (await cluster.get_file_ref(name)) \
                .read_builder().read_all()
            assert got == data

    asyncio.run(main())


def test_batcher_caller_cancellation():
    """Cancelling one coalesced caller must not hang or corrupt the
    others sharing its dispatch group."""
    d, p, size = 3, 2, 4096
    parts = _make_parts(6, d, p, size, seed=3)

    async def main():
        batcher = ReconstructBatcher(backend="numpy")

        async def one(rows):
            punched = list(rows)
            punched[0] = None
            return await batcher.reconstruct(d, p, punched)

        tasks = [asyncio.ensure_future(one(r)) for r in parts]
        tasks[2].cancel()
        results = await asyncio.gather(*tasks, return_exceptions=True)
        assert isinstance(results[2], asyncio.CancelledError)
        for got, want in zip(
                [r for i, r in enumerate(results) if i != 2],
                [p_ for i, p_ in enumerate(parts) if i != 2]):
            assert not isinstance(got, BaseException), got
            for i in range(d + p):
                assert np.array_equal(got[i], want[i])

    asyncio.run(main())


def test_merged_group_failure_reaches_exactly_its_waiters():
    """VERDICT r4 item 6: when sub-batches from concurrent writes merge
    into ONE dispatch and that dispatch fails, the failure must reach
    every contributing waiter — and only them: a concurrently pending
    group with a different key still encodes, and the next submission
    on the failed key works (no poisoned batcher state)."""
    from chunky_bits_tpu.ops.backend import register_backend
    from chunky_bits_tpu.ops.batching import EncodeHashBatcher

    d, p = 4, 2
    rng = np.random.default_rng(5)
    coder = ErasureCoder(d, p, NumpyBackend())

    class MergingNumpy(NumpyBackend):
        name = "numpy-merging-fail"
        prefers_merged_batches = True

    class PoisonBatcher(EncodeHashBatcher):
        """Fails any dispatch whose batch contains the poison marker."""

        def _encode(self, coder, stacked):
            if (stacked[:, 0, :2] == 0xEE).all(axis=1).any():
                raise RuntimeError("injected codec failure")
            return super()._encode(coder, stacked)

    poisoned = rng.integers(0, 256, (1, d, 512), dtype=np.uint8)
    poisoned[0, 0, :2] = 0xEE
    clean_same_key = [rng.integers(0, 256, (1, d, 512), dtype=np.uint8)
                      for _ in range(3)]
    other_key = [rng.integers(0, 256, (2, d, 1024), dtype=np.uint8)
                 for _ in range(2)]

    async def main():
        batcher = PoisonBatcher(backend="numpy-merging-fail")
        results = await asyncio.gather(
            batcher.encode_hash(d, p, poisoned),
            *[batcher.encode_hash(d, p, b) for b in clean_same_key],
            *[batcher.encode_hash(d, p, b) for b in other_key],
            return_exceptions=True)
        # the poisoned merged group: every contributing waiter fails
        for r in results[:4]:
            assert isinstance(r, RuntimeError), r
        # the other key's group is untouched
        for stacked, r in zip(other_key, results[4:]):
            assert not isinstance(r, BaseException), r
            want_par, want_dig = coder.encode_hash_batch(stacked)
            assert np.array_equal(r[0], want_par)
            assert np.array_equal(r[1], want_dig)
        # and the key itself is not poisoned: the next clean submission
        # on the same (d, p, size) encodes fine
        parity, digests = await batcher.encode_hash(
            d, p, clean_same_key[0])
        want_par, want_dig = coder.encode_hash_batch(clean_same_key[0])
        assert np.array_equal(parity, want_par)
        assert np.array_equal(digests, want_dig)

    from chunky_bits_tpu.ops import backend as backend_mod

    register_backend(MergingNumpy())
    try:
        asyncio.run(main())
    finally:
        backend_mod._REGISTRY.pop("numpy-merging-fail", None)


def test_unmerged_group_failure_is_isolated_per_batch():
    """On CPU backends the group's batches dispatch unmerged, so a
    failing batch must fail ONLY its own waiter; co-grouped clean
    batches — including ones dispatched after the failure — succeed."""
    from chunky_bits_tpu.ops.batching import EncodeHashBatcher

    d, p = 4, 2
    rng = np.random.default_rng(6)
    coder = ErasureCoder(d, p, NumpyBackend())

    class PoisonBatcher(EncodeHashBatcher):
        def _encode(self, coder, stacked):
            if (stacked[:, 0, :2] == 0xEE).all(axis=1).any():
                raise RuntimeError("injected codec failure")
            return super()._encode(coder, stacked)

    batches = [rng.integers(0, 256, (1, d, 512), dtype=np.uint8)
               for _ in range(4)]
    batches[1][0, 0, :2] = 0xEE  # second in the group fails

    async def main():
        batcher = PoisonBatcher(backend="numpy")
        results = await asyncio.gather(
            *[batcher.encode_hash(d, p, b) for b in batches],
            return_exceptions=True)
        assert isinstance(results[1], RuntimeError)
        for i in (0, 2, 3):
            assert not isinstance(results[i], BaseException), results[i]
            want_par, want_dig = coder.encode_hash_batch(batches[i])
            assert np.array_equal(results[i][0], want_par)
            assert np.array_equal(results[i][1], want_dig)
        # all four were real dispatches (unmerged), one group
        assert batcher.dispatches == 4
        assert batcher.groups == 1

    asyncio.run(main())
