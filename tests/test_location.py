"""Location substrate tests (mirror of reference tests/location.rs):
FS + HTTP read, subfile write, streaming, conflict policy, ranges,
parse/display."""

import asyncio
import os

import pytest

from chunky_bits_tpu.errors import (
    HttpStatusError,
    LocationError,
    LocationParseError,
    WriteToRangeError,
)
from chunky_bits_tpu.file.hashing import AnyHash
from chunky_bits_tpu.file.location import (
    IGNORE,
    Location,
    LocationContext,
    Range,
)
from chunky_bits_tpu.utils import aio
from tests.http_node import FakeHttpNode


def test_parse_display_roundtrip():
    cases = [
        "/tmp/some/path",
        "relative/path",
        "http://example.com/data",
        "https://example.com/data",
        "(5,10)/tmp/file",
        "(5,)/tmp/file",
        "(0,0128)/tmp/file",
        "(7,12)http://example.com/x",
    ]
    for s in cases:
        loc = Location.parse(s)
        assert str(loc) == s, s


def test_parse_range_semantics():
    loc = Location.parse("(5,10)/tmp/file")
    assert loc.range == Range(5, 10, False)
    loc = Location.parse("(5,)/tmp/file")
    assert loc.range == Range(5, None, False)
    loc = Location.parse("(0,0128)/tmp/file")
    assert loc.range == Range(0, 128, True)
    # no valid prefix -> the parens belong to the path
    loc = Location.parse("(x,y)/tmp/file")
    assert loc.target == "(x,y)/tmp/file"


def test_parse_file_url():
    loc = Location.parse("file:///tmp/abc")
    assert loc.is_local() and loc.target == "/tmp/abc"


def test_parse_errors():
    with pytest.raises(LocationParseError):
        Location.parse("")
    with pytest.raises(LocationParseError):
        Location.http("ftp://example.com/x")


def test_child_and_parent():
    base = Location.parse("/tmp/dir")
    child = base.child("abc")
    assert str(child) == "/tmp/dir/abc"
    assert child.is_child_of(base)
    assert base.is_parent_of(child)
    hbase = Location.parse("http://example.com/data")
    hchild = hbase.child("abc")
    assert str(hchild) == "http://example.com/data/abc"
    assert hchild.is_child_of(hbase)
    assert not hchild.is_child_of(base)


def test_fs_read(tmp_path):
    # the reference uses /bin/sh as an always-present file
    # (tests/location.rs:101-107); a tempfile is equivalent and hermetic
    path = tmp_path / "content"
    path.write_bytes(b"some test content")

    async def main():
        loc = Location.parse(str(path))
        assert await loc.read() == b"some test content"
        assert await loc.file_exists()
        assert await loc.file_len() == 17

    asyncio.run(main())


def test_fs_read_missing(tmp_path):
    async def main():
        loc = Location.parse(str(tmp_path / "missing"))
        with pytest.raises(LocationError):
            await loc.read()
        assert not await loc.file_exists()

    asyncio.run(main())


def test_fs_write_subfile_and_delete(tmp_path):
    async def main():
        base = Location.parse(str(tmp_path))
        hash_ = AnyHash.from_buf(b"shard bytes")
        child = await base.write_subfile(str(hash_), b"shard bytes")
        assert child.is_child_of(base)
        assert await child.read() == b"shard bytes"
        locs = await base.write_shard(hash_, b"shard bytes")
        assert locs == [child]
        await child.delete()
        assert not await child.file_exists()

    asyncio.run(main())


def test_fs_range_reads(tmp_path):
    path = tmp_path / "ranged"
    path.write_bytes(bytes(range(100)))

    async def main():
        loc = Location.local(str(path), Range(10, 20, False))
        assert await loc.read() == bytes(range(10, 30))
        # extend_zeros pads reads past EOF (location.rs:127-129)
        loc = Location.local(str(path), Range(90, 20, True))
        data = await loc.read()
        assert data == bytes(range(90, 100)) + b"\0" * 10
        # open-ended
        loc = Location.local(str(path), Range(95, None, False))
        assert await loc.read() == bytes(range(95, 100))
        # writes to ranged locations are rejected
        with pytest.raises(WriteToRangeError):
            await loc.write(b"x")

    asyncio.run(main())


def test_fs_conflict_policy(tmp_path):
    path = tmp_path / "conflict"

    async def main():
        loc = Location.parse(str(path))
        await loc.write(b"first")
        ignore_cx = LocationContext(on_conflict=IGNORE)
        await loc.write(b"second", ignore_cx)
        assert await loc.read() == b"first"  # ignored
        await loc.write(b"third")  # default overwrite
        assert await loc.read() == b"third"

    asyncio.run(main())


def test_fs_streaming(tmp_path):
    src = tmp_path / "src"
    dst = tmp_path / "dst"
    src.write_bytes(os.urandom(3 << 20))

    async def main():
        sloc = Location.parse(str(src))
        dloc = Location.parse(str(dst))
        reader = await sloc.reader()
        n = await dloc.write_from_reader(reader)
        await aio.close_reader(reader)
        assert n == 3 << 20
        assert dst.read_bytes() == src.read_bytes()

    asyncio.run(main())


def test_http_full_cycle():
    async def main():
        node = await FakeHttpNode().start()
        cx = LocationContext()
        try:
            base = Location.parse(node.url + "/data")
            hash_ = AnyHash.from_buf(b"http shard")
            child = await base.write_subfile(str(hash_), b"http shard", cx)
            assert str(child) == f"{node.url}/data%2F{hash_}" or \
                child.is_child_of(base)
            assert await child.read(cx) == b"http shard"
            assert await child.file_exists(cx)
            assert await child.file_len(cx) == len(b"http shard")
            # conflict ignore
            icx = LocationContext(on_conflict=IGNORE)
            icx._sessions = cx._sessions
            await child.write(b"changed", icx)
            assert await child.read(cx) == b"http shard"
            # overwrite
            await child.write(b"changed", cx)
            assert await child.read(cx) == b"changed"
            # range read
            rloc = child.with_range(Range(2, 3, False))
            assert await rloc.read(cx) == b"ang"
            # delete
            await child.delete(cx)
            with pytest.raises(HttpStatusError):
                await child.read(cx)
            # streaming put
            dloc = Location.parse(node.url + "/streamed")
            n = await dloc.write_from_reader(
                aio.BytesReader(b"x" * 100000), cx)
            assert n == 100000
            assert await dloc.read(cx) == b"x" * 100000
        finally:
            await cx.aclose()
            await node.stop()

    asyncio.run(main())


def test_http_put_failure_raises():
    """A failed PUT (e.g. disk full) must surface, never report success."""
    async def main():
        node = await FakeHttpNode().start()
        cx = LocationContext()
        try:
            loc = Location.parse(node.url + "/fail/x")
            with pytest.raises(HttpStatusError):
                await loc.write(b"data", cx)
            with pytest.raises(HttpStatusError):
                await loc.write_from_reader(aio.BytesReader(b"data"), cx)
        finally:
            await cx.aclose()
            await node.stop()

    asyncio.run(main())


def test_http_missing_404():
    async def main():
        node = await FakeHttpNode().start()
        cx = LocationContext()
        try:
            loc = Location.parse(node.url + "/nope")
            with pytest.raises(HttpStatusError):
                await loc.read(cx)
            assert not await loc.file_exists(cx)
        finally:
            await cx.aclose()
            await node.stop()

    asyncio.run(main())


def test_streaming_profiler_hooks(tmp_path):
    """Streaming reader/writer paths emit one profiler entry per stream —
    the hooks the reference leaves as TODO (src/file/location.rs:119,255)."""
    from chunky_bits_tpu.file.profiler import new_profiler
    from chunky_bits_tpu.utils import aio as aio_utils

    payload = os.urandom(100000)
    src = tmp_path / "src.bin"
    src.write_bytes(payload)

    async def main():
        profiler, reporter = new_profiler()
        cx = LocationContext(profiler=profiler)

        # streaming read to EOF: one successful entry, full byte count
        reader = await Location.parse(str(src)).reader(cx)
        total = 0
        while True:
            data = await reader.read(8192)
            if not data:
                break
            total += len(data)
        await aio_utils.close_reader(reader)
        assert total == len(payload)

        # early close: entry logged with partial count, not dropped
        reader = await Location.parse(str(src)).reader(cx)
        first = await reader.read(4096)
        await aio_utils.close_reader(reader)
        assert len(first) == 4096

        # streaming write: one successful write entry
        dst = Location.parse(str(tmp_path / "dst.bin"))
        await dst.write_from_reader(aio_utils.BytesReader(payload), cx)

        # open failure logs a failed read entry
        with pytest.raises(LocationError):
            await Location.parse(str(tmp_path / "missing.bin")).reader(cx)

        report = reporter.profile()
        reads = [e for e in report.entries if e.kind == "read"]
        writes = [e for e in report.entries if e.kind == "write"]
        assert len(reads) == 3
        assert [e.ok for e in reads] == [True, True, False]
        assert reads[0].length == len(payload)
        assert reads[1].length == 4096
        assert len(writes) == 1
        assert writes[0].ok and writes[0].length == len(payload)

    asyncio.run(main())


def test_https_only_refuses_plain_http():
    """With the https_only tunable set, every network verb refuses a
    plain-http location (the reference builds its whole client https-only,
    src/cluster/tunables.rs:25-32)."""
    async def main():
        node = await FakeHttpNode().start()
        open_cx = LocationContext()
        cx = LocationContext(https_only=True)
        cx._sessions = open_cx._sessions
        try:
            loc = Location.parse(node.url + "/sec")
            await loc.write(b"payload", open_cx)  # plain context still works
            for op in (
                loc.read(cx),
                loc.reader(cx),
                loc.write(b"x", cx),
                loc.write_from_reader(aio.BytesReader(b"x"), cx),
                loc.delete(cx),
                loc.file_exists(cx),
                loc.file_len(cx),
            ):
                with pytest.raises(LocationError, match="https_only"):
                    await op
            # nothing was modified through the refusing context
            assert await loc.read(open_cx) == b"payload"
            # local locations are unaffected by https_only
        finally:
            await open_cx.aclose()
            await node.stop()

    asyncio.run(main())


def test_https_only_leaves_local_alone(tmp_path):
    f = tmp_path / "f"
    f.write_bytes(b"local")

    async def main():
        cx = LocationContext(https_only=True)
        assert await Location.parse(str(f)).read(cx) == b"local"

    asyncio.run(main())


def test_https_only_refuses_redirect_hops():
    """Under https_only a redirect answer is refused (mutating verbs run
    with redirects disabled), and a GET whose hop chain touched plain
    http is refused before the body is consumed.  Stub responses stand in
    for a TLS endpoint, which the test node cannot provide."""
    from types import SimpleNamespace
    from urllib.parse import urlsplit

    cx = LocationContext(https_only=True)
    loc = Location.http("https://node.example/chunk")

    class StubUrl:
        def __init__(self, url):
            self.scheme = urlsplit(url).scheme
            self._url = url

        def __str__(self):
            return self._url

    def resp(status, url, history=()):
        return SimpleNamespace(
            status=status,
            url=StubUrl(url),
            history=tuple(
                SimpleNamespace(url=StubUrl(u)) for u in history),
            release=lambda: None,
        )

    with pytest.raises(LocationError, match="refusing redirect"):
        loc._check_redirect(cx, resp(302, "https://node.example/chunk"))
    with pytest.raises(LocationError, match="plain http"):
        loc._check_response_hops(
            cx, resp(200, "http://node.example/chunk",
                     history=["https://node.example/chunk"]))
    # all-https chains pass both checks
    loc._check_redirect(cx, resp(200, "https://node.example/chunk"))
    loc._check_response_hops(
        cx, resp(200, "https://node2.example/chunk",
                 history=["https://node.example/chunk"]))
    # without the tunable both checks are no-ops
    open_cx = LocationContext()
    loc._check_redirect(open_cx, resp(302, "https://node.example/chunk"))


def test_plain_context_follows_redirects():
    """Without https_only, redirects keep working end-to-end."""
    async def main():
        node = await FakeHttpNode().start()
        cx = LocationContext()
        try:
            real = Location.parse(node.url + "/real")
            await real.write(b"payload", cx)
            via = Location.parse(node.url + "/redir/real")
            assert await via.read(cx) == b"payload"
        finally:
            await cx.aclose()
            await node.stop()

    asyncio.run(main())


def test_read_view_semantics(tmp_path, monkeypatch):
    """read_view serves zero-copy page-cache views for local (ranged)
    reads inside the file, and declines exactly where the generic path
    owns the semantics (past-EOF ranges, profiler, opt-out env,
    non-local)."""
    monkeypatch.delenv("CHUNKY_BITS_TPU_NO_MMAP", raising=False)
    data = bytes(range(256)) * 8  # 2048 bytes
    path = tmp_path / "chunk"
    path.write_bytes(data)

    async def main():
        loc = Location.parse(str(path))
        view = await loc.read_view()
        assert view is not None and bytes(view) == data
        # ranged, fully inside the file (incl. extend_zeros interior)
        ranged = Location.parse(f"(64,128){path}")
        view = await ranged.read_view()
        assert bytes(view) == data[64:192]
        assert bytes(view) == await ranged.read()
        # range reaching past EOF: generic path owns short/zero semantics
        over = Location.parse(f"(2000,128){path}")
        assert await over.read_view() is None
        # profiler active: generic read must be observed
        from chunky_bits_tpu.file import new_profiler
        profiler, reporter = new_profiler()
        cx = LocationContext(profiler=profiler)
        assert await loc.read_view(cx) is None
        # opt-out env
        monkeypatch.setenv("CHUNKY_BITS_TPU_NO_MMAP", "1")
        assert await loc.read_view() is None
        monkeypatch.delenv("CHUNKY_BITS_TPU_NO_MMAP")
        # missing file: None, not an exception
        assert await Location.parse(
            str(tmp_path / "absent")).read_view() is None

    asyncio.run(main())


def test_atomic_write_preserves_held_views(tmp_path, monkeypatch):
    """Local writes publish via temp+rename: a view taken before an
    overwrite keeps serving the old inode's bytes (never SIGBUS, never
    torn), the path serves the new content, and no temp files leak."""
    monkeypatch.delenv("CHUNKY_BITS_TPU_NO_MMAP", raising=False)
    path = tmp_path / "chunk"
    old, new = b"A" * 4096, b"B" * 4096

    async def main():
        loc = Location.parse(str(path))
        await loc.write(old)
        view = await loc.read_view()
        assert bytes(view) == old
        await loc.write(new)  # default policy: overwrite
        # the held view still reads the old, unlinked inode
        assert bytes(view) == old
        assert await loc.read() == new
        assert [f for f in os.listdir(tmp_path) if ".tmp." in f] == []

    asyncio.run(main())


def test_atomic_write_edge_cases(tmp_path):
    """Symlinked targets are written through (link preserved), file
    modes survive replacement, negative ranges decline the view path,
    and streaming local writes publish atomically."""

    async def main():
        # symlink: write through, don't replace the link node
        real = tmp_path / "real.bin"
        real.write_bytes(b"old")
        link = tmp_path / "link.bin"
        link.symlink_to(real)
        await Location.parse(str(link)).write(b"through-the-link")
        assert link.is_symlink()
        assert real.read_bytes() == b"through-the-link"
        # mode preserved across replace
        secret = tmp_path / "secret.bin"
        secret.write_bytes(b"v1")
        os.chmod(secret, 0o600)
        await Location.parse(str(secret)).write(b"v2")
        assert os.stat(secret).st_mode & 0o777 == 0o600
        assert secret.read_bytes() == b"v2"
        # negative range: view path declines (generic read errors)
        data = bytes(range(64))
        f = tmp_path / "f.bin"
        f.write_bytes(data)
        neg = Location.parse(f"(-10,5){f}")
        assert await neg.read_view() is None
        # streaming write publishes atomically: failed stream leaves
        # the previous content intact, success leaves no temp files
        class FailingReader:
            async def read(self, n: int = -1) -> bytes:
                raise OSError("stream died")

        out = tmp_path / "out.bin"
        out.write_bytes(b"previous")
        with pytest.raises(LocationError, match="stream died"):
            await Location.parse(str(out)).write_from_reader(
                FailingReader())
        assert out.read_bytes() == b"previous"
        await Location.parse(str(out)).write_from_reader(
            aio.BytesReader(b"streamed"))
        assert out.read_bytes() == b"streamed"
        assert [x for x in os.listdir(tmp_path) if ".tmp." in x] == []

    asyncio.run(main())
