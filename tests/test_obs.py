"""Observability plane (chunky_bits_tpu/obs): metrics registry,
exposition grammar, fleet merge, loop-lag, tracing, profiler rings,
gateway endpoints, supervisor aggregation, the stats CLI, and the
CB107 label-cardinality lint rule.

Everything here runs clean under CHUNKY_BITS_TPU_SANITIZE=1 (the CI
sanitize leg): the lag monitor is a timer handle (no task to leak) and
the spool writer is cancelled AND awaited at app cleanup.
"""

from __future__ import annotations

import asyncio
import io
import json
import os
import textwrap
import threading
import time

import pytest

from chunky_bits_tpu.obs import metrics as obs_metrics
from chunky_bits_tpu.obs import tracing as obs_tracing
from chunky_bits_tpu.obs.metrics import (
    ExpositionError,
    LoopLagMonitor,
    MetricsRegistry,
    merge_snapshots,
    parse_exposition,
    render_exposition,
)


def make_cluster(tmp_path, cache_bytes=0, trace_slow_ms=0.0,
                 chunk_size=16):
    from chunky_bits_tpu.cluster import Cluster

    dirs = []
    for i in range(5):
        d = tmp_path / f"disk{i}"
        d.mkdir(exist_ok=True)
        dirs.append(str(d))
    meta = tmp_path / "meta"
    meta.mkdir(exist_ok=True)
    tunables = {}
    if cache_bytes:
        tunables["cache_bytes"] = cache_bytes
    if trace_slow_ms:
        tunables["trace_slow_ms"] = trace_slow_ms
    return Cluster.from_obj({
        "destinations": [{"location": d} for d in dirs],
        "metadata": {"type": "path", "format": "yaml", "path": str(meta)},
        "profiles": {"default": {"data": 3, "parity": 2,
                                 "chunk_size": chunk_size}},
        "tunables": tunables,
    })


# ---- registry core ----

def test_counter_gauge_histogram_basics():
    reg = MetricsRegistry()
    c = reg.counter("t_total", "t", labels=("kind",))
    c.labels(kind="a").inc()
    c.labels(kind="a").inc(2)
    g = reg.gauge("t_gauge")
    g.set(7)
    h = reg.histogram("t_seconds", buckets=(0.01, 0.1))
    h.observe(0.005)
    h.observe(0.05)
    h.observe(5.0)
    snap = reg.snapshot()
    fams = {f["name"]: f for f in snap["families"]}
    assert fams["t_total"]["samples"] == [
        {"labels": {"kind": "a"}, "value": 3.0}]
    assert fams["t_gauge"]["samples"][0]["value"] == 7.0
    hist = fams["t_seconds"]["samples"][0]
    assert hist["counts"] == [1, 1, 1]
    assert hist["count"] == 3
    assert hist["sum"] == pytest.approx(5.055)


def test_registry_rejects_bad_shapes():
    reg = MetricsRegistry()
    reg.counter("a_total", labels=("x",))
    with pytest.raises(ValueError):
        reg.gauge("a_total")  # type mismatch
    with pytest.raises(ValueError):
        reg.counter("a_total", labels=("y",))  # label mismatch
    with pytest.raises(ValueError):
        reg.counter("bad name")
    with pytest.raises(ValueError):
        reg.counter("b_total", labels=("bad-label",))
    with pytest.raises(ValueError):
        reg.counter("c_total").inc(-1)
    with pytest.raises(ValueError):
        reg.histogram("h", buckets=(0.2, 0.1))


def test_label_cardinality_ceiling_is_enforced():
    """The runtime backstop behind CB107: an open-ended label value
    set trips a hard error instead of leaking a series per value."""
    reg = MetricsRegistry()
    c = reg.counter("cap_total", labels=("k",))
    for i in range(obs_metrics.MAX_LABEL_SETS):
        c.labels(k=str(i)).inc()
    with pytest.raises(ValueError, match="CB107"):
        c.labels(k="one-too-many")


def test_concurrent_thread_and_loop_recording_is_exact():
    """8 worker threads + loop tasks hammer one counter and one
    histogram; totals come out exact — the thread-safety contract the
    two-plane runtime needs (worker threads record too)."""
    reg = MetricsRegistry()
    c = reg.counter("conc_total")
    h = reg.histogram("conc_seconds", buckets=(0.5,))
    per_thread, threads = 5000, 8

    def hammer():
        for _ in range(per_thread):
            c.inc()
            h.observe(0.1)

    ts = [threading.Thread(target=hammer) for _ in range(threads)]
    for t in ts:
        t.start()

    async def loop_side():
        for _ in range(per_thread):
            c.inc()
            h.observe(0.9)
            if _ % 500 == 0:
                await asyncio.sleep(0)

    asyncio.run(loop_side())
    for t in ts:
        t.join()
    total = per_thread * (threads + 1)
    fams = {f["name"]: f for f in reg.snapshot()["families"]}
    assert fams["conc_total"]["samples"][0]["value"] == total
    hist = fams["conc_seconds"]["samples"][0]
    assert hist["count"] == total
    assert hist["counts"] == [per_thread * threads, per_thread]


# ---- exposition grammar ----

def test_exposition_round_trip_and_grammar():
    reg = MetricsRegistry()
    reg.counter("rt_total", "a counter", labels=("k",)).labels(
        k='we"ird\\v').inc(2)
    reg.histogram("rt_seconds", "a hist", buckets=(0.1,)).observe(0.05)
    reg.gauge("rt_gauge", "a gauge").set(-3.5)
    text = render_exposition(reg.snapshot())
    parsed = parse_exposition(text)
    assert parsed["rt_total"]["type"] == "counter"
    assert parsed["rt_seconds"]["type"] == "histogram"
    (name, labels, value) = parsed["rt_total"]["samples"][0]
    assert value == 2.0
    # escaped label value survives the round trip
    assert labels["k"] == 'we\\"ird\\\\v'


@pytest.mark.parametrize("bad", [
    "orphan_metric 1\n",                       # sample without TYPE
    "# TYPE x counter\nx -1\n",                # negative counter
    "# TYPE x counter\nx{k=unquoted} 1\n",     # bad label grammar
    "# TYPE x counter\n# TYPE x counter\nx 1\n",  # duplicate TYPE
    "# TYPE h histogram\n"                     # no +Inf bucket
    'h_bucket{le="1"} 1\nh_sum 1\nh_count 1\n',
    "# TYPE h histogram\n"                     # non-cumulative buckets
    'h_bucket{le="1"} 5\nh_bucket{le="+Inf"} 3\n'
    "h_sum 1\nh_count 3\n",
    "# TYPE h histogram\n"                     # _count != +Inf bucket
    'h_bucket{le="+Inf"} 3\nh_sum 1\nh_count 4\n',
    "# WEIRD comment\n",
])
def test_exposition_grammar_rejects(bad):
    with pytest.raises(ExpositionError):
        parse_exposition(bad)


# ---- fleet merge ----

def test_merge_snapshots_sums_counters_and_histograms_labels_gauges():
    def snap(v):
        reg = MetricsRegistry()
        reg.counter("m_total", labels=("k",)).labels(k="a").inc(v)
        reg.histogram("m_seconds", buckets=(1.0,)).observe(v)
        reg.gauge("m_gauge").set(v)
        return reg.snapshot()

    merged = merge_snapshots([("w1", snap(1)), ("w2", snap(2))])
    fams = {f["name"]: f for f in merged["families"]}
    assert fams["m_total"]["samples"] == [
        {"labels": {"k": "a"}, "value": 3.0}]
    hist = fams["m_seconds"]["samples"][0]
    assert hist["count"] == 2 and hist["sum"] == 3.0
    gauges = {s["labels"]["worker"]: s["value"]
              for s in fams["m_gauge"]["samples"]}
    assert gauges == {"w1": 1.0, "w2": 2.0}
    # merged output still renders grammar-valid text
    parse_exposition(render_exposition(merged))


def test_merge_rejects_bucket_layout_mismatch():
    reg1 = MetricsRegistry()
    reg1.histogram("m_seconds", buckets=(1.0,)).observe(0.5)
    reg2 = MetricsRegistry()
    reg2.histogram("m_seconds", buckets=(2.0,)).observe(0.5)
    with pytest.raises(ValueError):
        merge_snapshots([("a", reg1.snapshot()), ("b", reg2.snapshot())])


def test_spool_write_load_fleet(tmp_path):
    spool = str(tmp_path / "spool")
    os.makedirs(spool)
    reg = MetricsRegistry()
    reg.counter("s_total").inc(5)
    obs_metrics.write_snapshot_file(
        os.path.join(spool, "worker-1.json"), reg.snapshot())
    # a torn/corrupt spool file is skipped, not fatal
    with open(os.path.join(spool, "worker-2.json"), "w") as f:
        f.write("{torn")
    reg2 = MetricsRegistry()
    reg2.counter("s_total").inc(7)
    merged = obs_metrics.fleet_snapshot(spool,
                                        own=("3", reg2.snapshot()))
    fams = {f["name"]: f for f in merged["families"]}
    assert fams["s_total"]["samples"][0]["value"] == 12.0
    # own snapshot replaces a stale spool entry for the same worker
    merged = obs_metrics.fleet_snapshot(spool,
                                        own=("1", reg2.snapshot()))
    fams = {f["name"]: f for f in merged["families"]}
    assert fams["s_total"]["samples"][0]["value"] == 7.0


def test_health_source_values_are_exact(tmp_path):
    """Polled-source adapter exactness: one scoreboard's per-node
    counters appear VERBATIM in the snapshot (regression: the first
    merge implementation double-counted the first row), and two
    scoreboards observing the same node sum."""
    from chunky_bits_tpu.cluster.health import HealthScoreboard
    from chunky_bits_tpu.file.location import Location

    loc = Location.local(str(tmp_path / "disk" / "x"))

    def scoreboard():
        sb = HealthScoreboard()
        sb.record(loc, True, 0.01)
        sb.record(loc, True, 0.02)
        sb.record(loc, False)
        return sb

    reg = MetricsRegistry()
    sb1 = scoreboard()
    reg.register_source("health", sb1)
    fams = {f["name"]: f for f in reg.snapshot()["families"]}
    assert fams["cb_node_completions_total"]["samples"][0]["value"] == 3
    assert fams["cb_node_errors_total"]["samples"][0]["value"] == 1
    sb2 = scoreboard()
    reg.register_source("health", sb2)
    fams = {f["name"]: f for f in reg.snapshot()["families"]}
    assert fams["cb_node_completions_total"]["samples"][0]["value"] == 6
    assert fams["cb_node_errors_total"]["samples"][0]["value"] == 2


def test_repair_source_families_carry_the_code_label():
    """Every cb_repair_* family splits by the CLOSED erasure-code set
    (cluster.repair.CODES): per-code counters appear verbatim under
    their code= label, cross-code samples coexist in one family, and
    the exposition stays grammar-clean."""
    from chunky_bits_tpu.cluster.repair import RepairPlanner

    planner = RepairPlanner()
    planner._bump("rs", plans_decode=2, helper_bytes_decode=4096,
                  bytes_rebuilt=1024)
    planner._bump("pm-msr", plans_msr=3, helper_bytes_msr=8192,
                  bytes_rebuilt=4096)
    reg = MetricsRegistry()
    reg.register_source("repair", planner)
    fams = {f["name"]: f for f in reg.snapshot()["families"]}

    def val(fam, **labels):
        for s in fams[fam]["samples"]:
            if all(s["labels"].get(k) == v for k, v in labels.items()):
                return s["value"]
        raise AssertionError((fam, labels, fams[fam]["samples"]))

    assert val("cb_repair_plans_total", kind="decode", code="rs") == 2
    assert val("cb_repair_plans_total", kind="msr", code="pm-msr") == 3
    assert val("cb_repair_plans_total", kind="msr", code="rs") == 0
    assert val("cb_repair_helper_bytes_total", source="decode",
               code="rs") == 4096
    assert val("cb_repair_helper_bytes_total", source="msr",
               code="pm-msr") == 8192
    assert val("cb_repair_bytes_rebuilt_total", code="rs") == 1024
    assert val("cb_repair_bytes_rebuilt_total", code="pm-msr") == 4096
    obs_metrics.parse_exposition(reg.render())


def test_xor_schedule_cache_is_a_metrics_source():
    """The scheduled-XOR program LRU surfaces its hit/miss/eviction
    counters through the registry (the PR-10 cache was observable only
    in-process): a real cache's traffic lands in cb_xor_schedule_* and
    two caches sum, per the polled-source contract."""
    from chunky_bits_tpu.ops import matrix as gf_matrix
    from chunky_bits_tpu.ops.xor_schedule import ScheduleCache

    reg = MetricsRegistry()
    cache = ScheduleCache(maxsize=1)
    # ScheduleCache self-registers with the PROCESS registry; the test
    # registry observes the same object explicitly
    reg.register_source("xor_schedule", cache)
    enc = gf_matrix.build_encode_matrix(3, 2)
    cache.get(enc[3:])           # miss
    cache.get(enc[3:])           # hit
    cache.get(enc[3:, ::-1])     # miss + eviction (maxsize=1)
    fams = {f["name"]: f for f in reg.snapshot()["families"]}
    assert fams["cb_xor_schedule_hits_total"]["samples"][0]["value"] == 1
    assert (fams["cb_xor_schedule_misses_total"]["samples"][0]["value"]
            == 2)
    assert (fams["cb_xor_schedule_evictions_total"]["samples"][0]
            ["value"] == 1)
    assert fams["cb_xor_schedule_entries"]["samples"][0]["value"] == 1
    obs_metrics.parse_exposition(reg.render())


# ---- event-loop lag ----

def test_loop_lag_monitor_observes_a_blocked_loop():
    reg = MetricsRegistry()

    async def main():
        mon = LoopLagMonitor(reg, interval=0.05)
        mon.start(asyncio.get_running_loop())
        try:
            await asyncio.sleep(0.1)   # let a clean tick land
            time.sleep(0.3)            # block the loop on purpose
            await asyncio.sleep(0.1)   # let the late tick fire
        finally:
            mon.stop()

    asyncio.run(main())
    fams = {f["name"]: f for f in reg.snapshot()["families"]}
    hist = fams["cb_eventloop_lag_seconds"]["samples"][0]
    assert hist["count"] >= 2
    # the blocked interval shows up as at least ~0.2s of recorded lag
    assert hist["sum"] >= 0.2


# ---- profiler rings ----

def test_profiler_rings_drop_oldest_and_count(tmp_path):
    from chunky_bits_tpu.file.location import Location
    from chunky_bits_tpu.file.profiler import ProfileReporter, Profiler

    p = Profiler(max_requests=4, max_entries=3, max_location_failures=2)
    loc = Location.local(str(tmp_path / "x"))
    for i in range(10):
        p.log_request("GET", f"/o{i}", 200, 1, 0.001, "store")
    for i in range(5):
        p.log_read(True, None, loc, 1, time.monotonic())
    for i in range(5):
        p.log_location_failure(loc, f"err{i}")
    drops = p.drop_counts()
    assert drops == {"requests": 6, "entries": 2,
                     "location_failures": 3}
    # the ring keeps the NEWEST entries
    assert [r.path for r in p.peek_requests()] == \
        ["/o6", "/o7", "/o8", "/o9"]
    report = ProfileReporter(p).profile()
    assert "Dropped<" in str(report)
    assert "requests=6" in str(report)
    # draining resets contents but not the drop counters
    assert p.drain_requests() == [] or True
    assert p.drop_counts()["requests"] == 6


def test_profiler_feeds_registry():
    from chunky_bits_tpu.file.profiler import Profiler

    reg = obs_metrics.get_registry()

    def req_count():
        fams = {f["name"]: f for f in reg.snapshot()["families"]}
        fam = fams.get("cb_request_total")
        if fam is None:
            return 0.0
        return sum(s["value"] for s in fam["samples"]
                   if s["labels"].get("method") == "PUT"
                   and s["labels"].get("status_class") == "2xx")

    before = req_count()
    Profiler().log_request("PUT", "/x", 200, 10, 0.001, "store")
    assert req_count() == before + 1


# ---- tracing ----

def test_trace_buffer_keeps_slowest_n():
    buf = obs_tracing.TraceBuffer(capacity=3)
    for i, d in enumerate([5.0, 1.0, 9.0, 2.0, 7.0]):
        buf.offer(d, {"trace_id": f"t{i}", "duration_ms": d})
    kept = [t["duration_ms"] for t in buf.snapshot()]
    assert kept == [9.0, 7.0, 5.0]


def test_trace_span_cap_counts_drops():
    tr = obs_tracing.Trace("t")
    t0 = time.monotonic()
    for _ in range(obs_tracing.MAX_SPANS + 10):
        tr.add("s", "host", t0, 0.001)
    obj = tr.to_obj(1.0, {})
    assert len(obj["spans"]) == obs_tracing.MAX_SPANS
    assert obj["dropped_spans"] == 10


def test_clean_id_rejects_garbage():
    assert obs_tracing.clean_id("abc-123") == "abc-123"
    for bad in (None, "", "x" * 100, 'a"b', "a\\b", "a\x00b"):
        minted = obs_tracing.clean_id(bad)
        assert minted != bad and len(minted) == 16


def test_span_recording_is_noop_without_a_trace():
    # must not raise and must not allocate a trace
    obs_tracing.record_span("x", "host", time.monotonic(), 0.001)
    assert obs_tracing.current() is None


# ---- gateway endpoints ----

def test_gateway_metrics_stats_healthz(tmp_path):
    from chunky_bits_tpu.gateway import make_app
    from chunky_bits_tpu.gateway.http import HEALTH_KEY

    payload = os.urandom(200000)

    async def main():
        from aiohttp.test_utils import TestClient, TestServer

        cluster = make_cluster(tmp_path, cache_bytes=4 << 20)
        app = make_app(cluster)
        async with TestClient(TestServer(app)) as client:
            assert (await client.put("/obj", data=payload)).status == 200
            resp = await client.get("/obj")
            assert await resp.read() == payload

            resp = await client.get("/healthz")
            assert resp.status == 200
            body = await resp.json()
            assert body["status"] == "ok" and body["uptime_s"] >= 0

            resp = await client.get("/stats")
            stats = await resp.json()
            assert stats["requests"]["count"] >= 2
            assert stats["requests"]["p50_ms"] > 0
            assert "metrics" in stats and "dropped" in stats

            resp = await client.get("/metrics")
            assert resp.status == 200
            assert resp.content_type == "text/plain"
            parsed = parse_exposition(await resp.text())
            for want in ("cb_request_seconds", "cb_request_total",
                         "cb_request_bytes_total", "cb_worker_up",
                         "cb_cache_hits_total",
                         "cb_pipeline_jobs_total",
                         "cb_node_completions_total",
                         "cb_eventloop_lag_seconds",
                         "cb_gateway_gets_in_flight"):
                assert want in parsed, f"missing {want}"

            # draining flips /healthz to 503 while other routes serve
            app[HEALTH_KEY].draining = True
            resp = await client.get("/healthz")
            assert resp.status == 503
            assert (await resp.json())["status"] == "draining"
            resp = await client.get("/obj")
            assert resp.status == 200
            await resp.read()
        await cluster.tunables.location_context().aclose()

    asyncio.run(main())


def test_trace_propagation_end_to_end(tmp_path):
    """A traced slow GET appears in /debug/traces with spans from BOTH
    planes: the async/gateway side and the host pipeline (verify jobs
    carry the captured trace across the worker-thread boundary), plus
    the network fetch spans."""
    from chunky_bits_tpu.gateway import make_app

    payload = os.urandom(300000)

    async def main():
        from aiohttp.test_utils import TestClient, TestServer

        cluster = make_cluster(tmp_path, trace_slow_ms=0.0001)
        app = make_app(cluster, sendfile=False)
        async with TestClient(TestServer(app)) as client:
            assert (await client.put("/obj", data=payload)).status == 200
            resp = await client.get(
                "/obj", headers={"X-Chunky-Trace": "e2e-trace-1"})
            assert await resp.read() == payload

            resp = await client.get("/debug/traces")
            body = await resp.json()
            assert body["enabled"] is True
            by_id = {t["trace_id"]: t for t in body["traces"]}
            assert "e2e-trace-1" in by_id, sorted(by_id)
            tr = by_id["e2e-trace-1"]
            planes = {s["plane"] for s in tr["spans"]}
            assert "gateway" in planes
            assert "host" in planes      # pipeline verify jobs
            assert "network" in planes   # chunk fetches
            names = {s["name"] for s in tr["spans"]}
            assert "request" in names and "chunk_fetch" in names
            assert any(n.startswith("pipeline.") for n in names)
            assert tr["duration_ms"] >= max(
                s["duration_ms"] for s in tr["spans"]
                if s["plane"] != "gateway")
        await cluster.tunables.location_context().aclose()

    asyncio.run(main())


def test_tracing_off_by_default(tmp_path):
    from chunky_bits_tpu.gateway import make_app

    async def main():
        from aiohttp.test_utils import TestClient, TestServer

        cluster = make_cluster(tmp_path)
        app = make_app(cluster)
        async with TestClient(TestServer(app)) as client:
            assert (await client.put("/obj", data=b"x" * 1000)
                    ).status == 200
            resp = await client.get("/debug/traces")
            body = await resp.json()
            assert body["enabled"] is False
        await cluster.tunables.location_context().aclose()

    asyncio.run(main())


def test_supervisor_fleet_metrics_aggregation(tmp_path):
    """The acceptance-criterion scrape: /metrics against a 2-worker
    SO_REUSEPORT fleet returns ONE grammar-valid exposition whose
    gauges are labeled per worker (cb_worker_up shows both pids) and
    whose counters aggregate the whole fleet's requests."""
    import aiohttp

    from chunky_bits_tpu.gateway.workers import GatewaySupervisor

    payload = os.urandom(120000)

    async def main():
        cluster = make_cluster(tmp_path, cache_bytes=4 << 20)
        sup = GatewaySupervisor(cluster.to_obj(), "127.0.0.1", 0,
                                workers=2, ready_timeout=90.0)
        await sup.start()
        try:
            url = f"http://127.0.0.1:{sup.port}"
            async with aiohttp.ClientSession() as session:
                resp = await session.put(f"{url}/obj", data=payload)
                assert resp.status == 200
                for _ in range(6):
                    resp = await session.get(f"{url}/obj")
                    assert resp.status == 200
                    await resp.read()
                # poll until the scraped worker has merged BOTH
                # workers' snapshots (the sibling publishes on its
                # spool heartbeat shortly after ready)
                deadline = time.monotonic() + 60
                workers_seen: set = set()
                parsed = {}
                while time.monotonic() < deadline:
                    resp = await session.get(f"{url}/metrics")
                    assert resp.status == 200
                    parsed = parse_exposition(await resp.text())
                    up = parsed.get("cb_worker_up",
                                    {"samples": []})["samples"]
                    workers_seen = {labels.get("worker")
                                    for _n, labels, v in up}
                    if len(workers_seen) == 2:
                        break
                    await asyncio.sleep(0.5)
                assert len(workers_seen) == 2, workers_seen
                # fleet-wide counter: every request this test issued is
                # in the merged view, whichever worker served it
                total = sum(v for _n, labels, v
                            in parsed["cb_request_total"]["samples"])
                assert total >= 7
                # request histogram merged across workers stays
                # internally consistent (grammar check enforced _count
                # == +Inf bucket already; just confirm presence)
                assert "cb_request_seconds" in parsed
                # /stats stays per-worker and says which worker
                resp = await session.get(f"{url}/stats")
                stats = await resp.json()
                assert stats["worker"] in workers_seen
            # the supervisor-side aggregation helper reads the same
            # spool (may lag the live scrape by a heartbeat)
            snap = await asyncio.to_thread(sup.fleet_snapshot)
            names = {f["name"] for f in snap["families"]}
            assert "cb_worker_up" in names
        finally:
            await sup.stop()
        assert sup.metrics_spool is None
        await cluster.tunables.location_context().aclose()

    asyncio.run(main())


# ---- stats CLI ----

def test_stats_cli_renders_summary(tmp_path, capsys):
    from chunky_bits_tpu.cli.stats import stats_command
    from chunky_bits_tpu.gateway import make_app

    async def main():
        from aiohttp.test_utils import TestServer

        cluster = make_cluster(tmp_path)
        server = TestServer(make_app(cluster))
        await server.start_server()
        try:
            import aiohttp

            url = f"http://127.0.0.1:{server.port}"
            async with aiohttp.ClientSession() as session:
                resp = await session.put(f"{url}/obj", data=b"y" * 5000)
                assert resp.status == 200
                resp = await session.get(f"{url}/obj")
                await resp.read()
            out = io.StringIO()
            assert await stats_command(url, as_json=False, out=out) == 0
            text = out.getvalue()
            assert "requests: n=" in text
            assert "status=ok" in text
            assert "scrub: disabled" in text
            out = io.StringIO()
            assert await stats_command(url, as_json=True, out=out) == 0
            blob = json.loads(out.getvalue())
            assert blob["healthz"]["status"] == "ok"
            assert blob["stats"]["requests"]["count"] >= 2
        finally:
            await server.close()
        await cluster.tunables.location_context().aclose()

    asyncio.run(main())


def test_stats_cli_unreachable_gateway_fails_loudly():
    from chunky_bits_tpu.cli.stats import stats_command
    from chunky_bits_tpu.errors import ChunkyBitsError

    async def main():
        with pytest.raises(ChunkyBitsError):
            # a port from the ephemeral range with nothing listening
            await stats_command("http://127.0.0.1:1", as_json=False,
                                out=io.StringIO())

    asyncio.run(main())


# ---- CB107 lint rule ----

def _run_cb107(tmp_path, rel, source):
    from chunky_bits_tpu.analysis import core, rules

    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source), encoding="utf-8")
    ruleset = [r for r in rules.ALL_RULES if r.id == "CB107"]
    violations, errors = core.run_analysis(tmp_path, ruleset)
    assert not errors, errors
    return violations


def test_cb107_flags_open_ended_label_values(tmp_path):
    vs = _run_cb107(tmp_path, "gateway/x.py", """
        def f(reg, request, n):
            reg.counter("x_total").labels(k=f"req-{n}").inc()
            reg.counter("y_total").labels(k=str(n)).inc()
            reg.counter("z_total").labels(k=request.path).inc()
            reg.counter("w_total").labels(k="a" + "b").inc()
    """)
    assert [v.rule for v in vs] == ["CB107"] * 4
    msgs = " ".join(v.message for v in vs)
    assert "f-string" in msgs and "request-derived" in msgs


def test_cb107_passes_closed_sets_and_suppressions(tmp_path):
    vs = _run_cb107(tmp_path, "gateway/x.py", """
        KIND = "a"

        def f(reg, kind):
            reg.counter("x_total").labels(k="literal").inc()
            reg.counter("y_total").labels(k=KIND).inc()
            reg.counter("z_total").labels(k=kind).inc()
            # lint: label-cardinality-ok enum of 3 shard classes
            reg.counter("w_total").labels(k=str(kind)).inc()
    """)
    assert vs == []


def test_tunables_trace_slow_ms_serde_and_env(monkeypatch):
    from chunky_bits_tpu.cluster.tunables import (
        TRACE_SLOW_MS_ENV,
        Tunables,
        trace_slow_ms,
    )

    monkeypatch.delenv(TRACE_SLOW_MS_ENV, raising=False)
    assert trace_slow_ms() == 0.0
    assert Tunables().trace_slow_ms == 0.0
    monkeypatch.setenv(TRACE_SLOW_MS_ENV, "12.5")
    assert trace_slow_ms() == 12.5
    assert Tunables().trace_slow_ms == 12.5
    monkeypatch.setenv(TRACE_SLOW_MS_ENV, "garbage")
    assert trace_slow_ms() == 0.0
    # YAML wins over the env default and round-trips
    t = Tunables.from_obj({"trace_slow_ms": 40})
    assert t.trace_slow_ms == 40.0
    assert Tunables.from_obj(t.to_obj()).trace_slow_ms == 40.0
    with pytest.raises(Exception):
        Tunables.from_obj({"trace_slow_ms": -1})
