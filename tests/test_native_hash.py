"""Native SHA-256 engine + fused encode+hash ingest step.

The C++ engine (native/gf256.cpp) fills the role of the reference's
``sha2`` crate on the write hot path (per-shard sha256 at
src/file/file_part.rs:185) fused with the erasure encode.  These tests
pin it byte-for-byte to hashlib and to the unfused path.
"""

import hashlib
import pathlib

import numpy as np
import pytest

from chunky_bits_tpu.ops.backend import ErasureCoder, NumpyBackend

try:
    from chunky_bits_tpu.ops.cpu_backend import NativeBackend, sha256_buf

    NativeBackend()  # the C++ build is deferred; force it so a box
    # without a working g++ skips instead of erroring mid-test
except Exception:  # pragma: no cover - no compiler on this box
    NativeBackend = None


needs_native = pytest.mark.skipif(
    NativeBackend is None, reason="native backend unavailable")


@needs_native
@pytest.mark.parametrize(
    "n", [0, 1, 3, 55, 56, 57, 63, 64, 65, 119, 120, 128, 1000, 65537])
def test_native_sha256_matches_hashlib(n):
    rng = np.random.default_rng(n)
    data = rng.integers(0, 256, n, dtype=np.uint8).tobytes()
    assert sha256_buf(data) == hashlib.sha256(data).digest()


@needs_native
def test_fused_encode_hash_matches_unfused():
    d, p = 5, 3
    rng = np.random.default_rng(7)
    data = rng.integers(0, 256, (4, d, 2048), dtype=np.uint8)
    coder = ErasureCoder(d, p, NativeBackend())
    parity, digests = coder.encode_hash_batch(data)

    oracle = ErasureCoder(d, p, NumpyBackend())
    want_parity = oracle.encode_batch(data)
    assert np.array_equal(parity, want_parity)
    assert digests.shape == (4, d + p, 32)
    for i in range(4):
        for j in range(d):
            assert digests[i, j].tobytes() == \
                hashlib.sha256(data[i, j]).digest()
        for j in range(p):
            assert digests[i, d + j].tobytes() == \
                hashlib.sha256(want_parity[i, j]).digest()


def test_generic_encode_hash_fallback():
    d, p = 3, 2
    rng = np.random.default_rng(11)
    data = rng.integers(0, 256, (2, d, 512), dtype=np.uint8)
    coder = ErasureCoder(d, p, NumpyBackend())
    parity, digests = coder.encode_hash_batch(data)
    assert np.array_equal(parity, coder.encode_batch(data))
    assert digests[0, 0].tobytes() == hashlib.sha256(data[0, 0]).digest()
    assert digests[1, d + 1].tobytes() == \
        hashlib.sha256(parity[1, 1]).digest()


def test_jax_overlapped_encode_hash_matches_hashlib():
    """The jax backend's overlapped encode+hash (device parity in flight
    while the host hashes) must equal the serial hashlib reference for
    every shard — including the multi-block dispatch path, where parity
    blocks are hashed as they land."""
    pytest.importorskip("jax")
    from chunky_bits_tpu.ops.jax_backend import JaxBackend

    d, p = 5, 3
    rng = np.random.default_rng(13)
    backend = JaxBackend()
    coder = ErasureCoder(d, p, backend)
    oracle = ErasureCoder(d, p, NumpyBackend())

    def check(data):
        parity, digests = coder.encode_hash_batch(data)
        want_parity = oracle.encode_batch(data)
        assert np.array_equal(parity, want_parity)
        b = data.shape[0]
        assert digests.shape == (b, d + p, 32)
        for i in range(b):
            for j in range(d):
                assert digests[i, j].tobytes() == \
                    hashlib.sha256(data[i, j]).digest()
            for j in range(p):
                assert digests[i, d + j].tobytes() == \
                    hashlib.sha256(want_parity[i, j]).digest()

    check(rng.integers(0, 256, (4, d, 2048), dtype=np.uint8))
    # force multi-block: shrink the per-dispatch budgets so 6 parts
    # split into 3 double-buffered blocks
    old = backend.max_block_bytes, backend.max_pallas_block_bytes
    backend.max_block_bytes = 2 * d * 2048 * 16
    backend.max_pallas_block_bytes = 2 * d * 2048 * 2
    try:
        check(rng.integers(0, 256, (6, d, 2048), dtype=np.uint8))
    finally:
        backend.max_block_bytes, backend.max_pallas_block_bytes = old
    # degenerate geometries take the serial path
    check(rng.integers(0, 256, (1, d, 128), dtype=np.uint8))
    zero_p = ErasureCoder(d, 0, backend)
    parity, digests = zero_p.encode_hash_batch(
        rng.integers(0, 256, (2, d, 256), dtype=np.uint8))
    assert parity.shape == (2, 0, 256)
    assert digests.shape == (2, d, 32)


def test_jax_encode_hash_reconciles_uncovered_rows(monkeypatch):
    """If a mid-run pallas->einsum fallback suppresses the block
    callback, encode_and_hash must still hash every parity row."""
    pytest.importorskip("jax")
    from chunky_bits_tpu.ops.jax_backend import JaxBackend

    d, p = 3, 2
    backend = JaxBackend()
    real = JaxBackend.apply_matrix

    def no_callback(self, mat, shards, on_block=None):
        # simulate the fallback: parity computed, callback never fired
        return real(self, mat, shards, on_block=None)

    monkeypatch.setattr(JaxBackend, "apply_matrix", no_callback)
    rng = np.random.default_rng(19)
    data = rng.integers(0, 256, (3, d, 512), dtype=np.uint8)
    parity, digests = backend.encode_and_hash(
        ErasureCoder(d, p, NumpyBackend()).parity_rows, data)
    for i in range(3):
        for j in range(p):
            assert digests[i, d + j].tobytes() == \
                hashlib.sha256(parity[i, j]).digest()


def test_mesh_backend_overlapped_encode_hash(request):
    """Mesh backends overlap data hashing with the sharded dispatch via
    the generic path; digests must still match hashlib exactly."""
    jax = pytest.importorskip("jax")
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from chunky_bits_tpu.ops.backend import get_backend

    d, p = 4, 2
    backend = get_backend("jax:dp4,sp2")
    assert backend.async_dispatch
    coder = ErasureCoder(d, p, backend)
    oracle = ErasureCoder(d, p, NumpyBackend())
    rng = np.random.default_rng(17)
    data = rng.integers(0, 256, (8, d, 1024), dtype=np.uint8)
    parity, digests = coder.encode_hash_batch(data)
    assert np.array_equal(parity, oracle.encode_batch(data))
    assert digests[3, 2].tobytes() == hashlib.sha256(data[3, 2]).digest()
    assert digests[5, d + 1].tobytes() == \
        hashlib.sha256(parity[5, 1]).digest()


def test_encode_hash_zero_parity():
    d = 4
    rng = np.random.default_rng(3)
    data = rng.integers(0, 256, (2, d, 256), dtype=np.uint8)
    for backend in filter(None, [NumpyBackend,
                                 NativeBackend]):
        coder = ErasureCoder(d, 0, backend())
        parity, digests = coder.encode_hash_batch(data)
        assert parity.shape == (2, 0, 256)
        assert digests.shape == (2, d, 32)
        assert digests[1, 2].tobytes() == hashlib.sha256(data[1, 2]).digest()


def test_writer_fused_refs_match_plain():
    """A file written through the batched fused path carries exactly the
    same chunk hashes as the one-part-at-a-time hashlib path."""
    import asyncio

    from chunky_bits_tpu.file.writer import FileWriteBuilder
    from chunky_bits_tpu.utils import aio

    rng = np.random.default_rng(23)
    payload = rng.integers(0, 256, 3 * 4096 * 2 + 77,
                           dtype=np.uint8).tobytes()

    async def write(batch_parts, backend):
        builder = (FileWriteBuilder()
                   .with_chunk_size(4096)
                   .with_data_chunks(3)
                   .with_parity_chunks(2)
                   .with_batch_parts(batch_parts)
                   .with_backend(backend))
        return await builder.write(aio.BytesReader(payload))

    async def main():
        plain = await write(1, "numpy")
        backends = ["numpy"] + (["native"] if NativeBackend else [])
        for backend in backends:
            fused = await write(4, backend)
            assert [c.hash for part in fused.parts
                    for c in part.all_chunks()] \
                == [c.hash for part in plain.parts
                    for c in part.all_chunks()]

    asyncio.run(main())


def test_writer_hashes_match_persisted_bytes(tmp_path):
    """Ground truth for the digest plumbing: every chunk hash in the
    written reference must be the sha256 of the bytes actually persisted
    at that chunk's location — catching any mis-zip of precomputed
    digests to shards (order, data-vs-parity) that a same-code-path
    comparison cannot see."""
    import asyncio
    import hashlib as _hl

    from chunky_bits_tpu.file.location import Location
    from chunky_bits_tpu.file.writer import FileWriteBuilder
    from chunky_bits_tpu.utils import aio

    rng = np.random.default_rng(29)
    payload = rng.integers(0, 256, 3 * 4096 * 3 + 123,
                           dtype=np.uint8).tobytes()

    async def main():
        backends = ["numpy"] + (["native"] if NativeBackend else [])
        for backend in backends:
            root = tmp_path / backend
            root.mkdir()
            builder = (FileWriteBuilder()
                       .with_chunk_size(4096)
                       .with_data_chunks(3)
                       .with_parity_chunks(2)
                       .with_batch_parts(4)
                       .with_backend(backend)
                       .with_destination([Location.parse(str(root))] * 5))
            ref = await builder.write(aio.BytesReader(payload))
            n_checked = 0
            for part in ref.parts:
                for chunk in part.all_chunks():
                    stored = await chunk.locations[0].read()
                    digest = _hl.sha256(stored).hexdigest()
                    assert str(chunk.hash) == f"sha256-{digest}"
                    n_checked += 1
            # 3 full parts + 1 short tail part, (3 data + 2 parity) each
            assert n_checked == 4 * 5

    asyncio.run(main())


def test_verify_fused_file_hash(tmp_path, monkeypatch):
    """verify hashes local chunks through the native read+hash fusion
    (no bytes surfaced to Python) and still catches corruption."""
    import asyncio

    from chunky_bits_tpu.file import file_part as fp_mod
    from chunky_bits_tpu.file.collection_destination import \
        LocationsDestination
    from chunky_bits_tpu.file.location import Location
    from chunky_bits_tpu.file.writer import FileWriteBuilder
    from chunky_bits_tpu.ops.cpu_backend import sha256_file
    from chunky_bits_tpu.utils import aio

    calls = []

    def counting(path, start=0, length=None):
        calls.append(path)
        return sha256_file(path, start, length)

    monkeypatch.setattr(fp_mod, "_FUSED_HASHER", counting)

    payload = np.random.default_rng(23).integers(
        0, 256, 60000, dtype=np.uint8).tobytes()
    dirs = []
    for i in range(5):
        d = tmp_path / f"disk{i}"
        d.mkdir()
        dirs.append(Location.parse(str(d)))

    async def main():
        ref = await (FileWriteBuilder()
                     .with_destination(LocationsDestination(dirs))
                     .with_chunk_size(4096)
                     .write(aio.BytesReader(payload)))
        report = await ref.verify()
        assert report.integrity().name == "VALID"
        assert calls, "fused hasher never engaged"
        # corrupt one chunk in place: flip a byte
        target = ref.parts[0].data[1].locations[0].target
        raw = bytearray(pathlib.Path(target).read_bytes())
        raw[0] ^= 0xFF
        pathlib.Path(target).write_bytes(bytes(raw))
        report = await ref.verify()
        assert report.integrity().name == "DEGRADED"

    asyncio.run(main())


def test_sha256_file_ranges(tmp_path):
    """Native file hasher KATs vs hashlib: full file, interior range,
    tail, empty range, short-file and missing-file errors — the range
    support that lets fused verify cover migrated (range-sliced) refs."""
    import hashlib

    from chunky_bits_tpu.ops.cpu_backend import sha256_file

    data = np.random.default_rng(31).integers(
        0, 256, 3 * (1 << 20) + 137, dtype=np.uint8).tobytes()
    path = tmp_path / "blob.bin"
    path.write_bytes(data)
    p = str(path)

    assert sha256_file(p) == hashlib.sha256(data).digest()
    assert sha256_file(p, 100, 5000) == \
        hashlib.sha256(data[100:5100]).digest()
    assert sha256_file(p, len(data) - 10) == \
        hashlib.sha256(data[-10:]).digest()
    assert sha256_file(p, 0, 0) == hashlib.sha256(b"").digest()
    # exact 64-byte-boundary lengths stress the finalize padding
    for n in (55, 56, 63, 64, 65, 119, 128):
        assert sha256_file(p, 0, n) == hashlib.sha256(data[:n]).digest()
    with pytest.raises(OSError):
        sha256_file(p, 0, len(data) + 1)  # short file
    with pytest.raises(OSError):
        sha256_file(str(tmp_path / "missing.bin"))
