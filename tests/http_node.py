"""In-process fake HTTP storage node for tests.

The analogue of the reference's warp-over-HashMap fake node
(tests/location.rs:16-99): GET/HEAD/PUT/DELETE over an in-memory dict, with
single-range GET support.  Uses an ephemeral port (the reference pins ports
64000-64005; ephemeral is race-free).

Fault injection is NOT implemented here: the node delegates every
fault decision to a ``chunky_bits_tpu.sim.fabric.FaultInjector`` — the
same composable models the deterministic cluster simulator drives at
fleet scale — so the one-shot ``put_fail_status`` / straggler
``get_delay`` scripts the tests write exercise the exact injection
logic the scenarios do.  The legacy knob attributes are properties
forwarding to ``self.faults``.
"""

from __future__ import annotations

import asyncio

from aiohttp import web

from chunky_bits_tpu.sim.fabric import FaultInjector


class FakeHttpNode:
    def __init__(self, fail_puts: bool = False) -> None:
        self.store: dict[str, bytes] = {}
        self._runner = None
        self.port: int = 0
        #: the fault model (sim/fabric.py): node-wide broken-disk mode,
        #: straggler stalls, one-shot PUT statuses
        self.faults = FaultInjector(fail_puts=fail_puts)
        self.put_attempts = 0
        self.get_attempts = 0

    # ---- legacy knob surface (forwards to the shared fault model) ----

    @property
    def fail_puts(self) -> bool:
        return self.faults.fail_puts

    @fail_puts.setter
    def fail_puts(self, value: bool) -> None:
        self.faults.fail_puts = value

    @property
    def get_delay(self) -> float:
        return self.faults.get_delay

    @get_delay.setter
    def get_delay(self, value: float) -> None:
        self.faults.get_delay = value

    @property
    def put_fail_status(self) -> int:
        return self.faults.put_fail_status

    @put_fail_status.setter
    def put_fail_status(self, value: int) -> None:
        self.faults.put_fail_status = value

    @property
    def put_fail_remaining(self) -> int:
        return self.faults.put_fail_remaining

    @put_fail_remaining.setter
    def put_fail_remaining(self, value: int) -> None:
        self.faults.put_fail_remaining = value

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    async def _get(self, request: web.Request) -> web.Response:
        key = request.match_info["key"]
        self.get_attempts += 1
        delay = self.faults.get_fault()
        if delay > 0:
            await asyncio.sleep(delay)
        if key.startswith("redir/"):
            raise web.HTTPFound(location=f"/{key[len('redir/'):]}")
        data = self.store.get(key)
        if data is None:
            return web.Response(status=404)
        range_header = request.headers.get("Range")
        if range_header and range_header.startswith("bytes="):
            spec = range_header[len("bytes="):]
            start_s, _, end_s = spec.partition("-")
            start = int(start_s) if start_s else 0
            end = int(end_s) if end_s else len(data) - 1
            if start >= len(data):
                return web.Response(status=416)
            body = data[start: end + 1]
            return web.Response(
                status=206,
                body=body,
                headers={
                    "Content-Range":
                        f"bytes {start}-{start + len(body) - 1}/{len(data)}"
                },
            )
        return web.Response(body=data)

    async def _put(self, request: web.Request) -> web.Response:
        key = request.match_info["key"]
        self.put_attempts += 1
        status = self.faults.put_fault()
        if status:
            return web.Response(status=status)
        if key.startswith("fail/"):
            # path-scripted broken disk (kept for tests addressing a
            # subtree, not a node-wide state)
            return web.Response(status=507)
        self.store[key] = await request.read()
        return web.Response()

    async def _delete(self, request: web.Request) -> web.Response:
        key = request.match_info["key"]
        self.store.pop(key, None)
        return web.Response()

    async def start(self) -> "FakeHttpNode":
        app = web.Application()
        app.router.add_get("/{key:.*}", self._get)  # also serves HEAD
        app.router.add_put("/{key:.*}", self._put)
        app.router.add_delete("/{key:.*}", self._delete)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", 0)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        return self

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None
