"""The invariant linter (chunky_bits_tpu/analysis).

Per-rule must-flag and must-pass fixture snippets, suppression-comment
parsing, baseline round-trip, CLI exit codes — and the gate itself: the
tree as shipped must be clean, which wires the analyzer into tier-1
through plain pytest (no jax import anywhere in this file; the linter
must run even when the device tunnel is down).
"""

from __future__ import annotations

import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from chunky_bits_tpu.analysis import core, rules

PKG_ROOT = Path(__file__).resolve().parents[1] / "chunky_bits_tpu"


def run_snippet(tmp_path: Path, rel: str, source: str,
                select: tuple[str, ...] = ()):
    """Lint one fixture file placed at ``rel`` under a scratch root
    (rule path scopes key off rel, e.g. 'ops/x.py')."""
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source), encoding="utf-8")
    ruleset = [r for r in rules.ALL_RULES
               if not select or r.id in select]
    violations, errors = core.run_analysis(tmp_path, ruleset)
    assert not errors, errors
    return violations


# ---- CB101 unbounded-await ----

def test_unbounded_await_flags_event_wait(tmp_path):
    vs = run_snippet(tmp_path, "ops/x.py", """
        async def f(evt):
            await evt.wait()
    """, select=("CB101",))
    assert [v.rule for v in vs] == ["CB101"]
    assert "no deadline" in vs[0].message


def test_unbounded_await_flags_bare_future(tmp_path):
    vs = run_snippet(tmp_path, "parallel/x.py", """
        async def f(fut):
            return await fut
    """, select=("CB101",))
    assert [v.rule for v in vs] == ["CB101"]
    assert "bare future" in vs[0].message


def test_unbounded_await_passes_wait_for_and_plain_calls(tmp_path):
    vs = run_snippet(tmp_path, "gateway/x.py", """
        import asyncio

        async def f(evt, reader):
            await asyncio.wait_for(evt.wait(), 5.0)
            data = await reader.read(4096)
            await asyncio.sleep(1.0)
            return data
    """, select=("CB101",))
    assert vs == []


def test_unbounded_await_out_of_scope_paths_pass(tmp_path):
    # cluster/ at large is not a device/network call path (metadata
    # subprocess waits etc. are CLI-bounded); only the I/O-scheduler
    # modules below are in scope
    vs = run_snippet(tmp_path, "cluster/x.py", """
        async def f(evt):
            await evt.wait()
    """, select=("CB101",))
    assert vs == []


def test_unbounded_await_covers_io_scheduler_paths(tmp_path):
    """The hedged-read/write-failover modules joined the CB101 scope
    with PR 5: every await the location race adds must stay reachable
    through a timeout."""
    for i, rel in enumerate(("file/file_part.py",
                             "cluster/destination.py",
                             "cluster/health.py")):
        vs = run_snippet(tmp_path / str(i), rel, """
            async def f(task):
                return await task
        """, select=("CB101",))
        assert [v.rule for v in vs] == ["CB101"], rel


# ---- CB102 env-flag-discipline ----

def test_env_read_flagged_outside_tunables(tmp_path):
    vs = run_snippet(tmp_path, "ops/x.py", """
        import os

        def f():
            return os.environ.get("CHUNKY_BITS_TPU_FOO")
    """, select=("CB102",))
    assert [v.rule for v in vs] == ["CB102"]
    assert "CHUNKY_BITS_TPU_FOO" in vs[0].message


def test_env_read_resolves_module_constants(tmp_path):
    vs = run_snippet(tmp_path, "file/x.py", """
        import os

        KNOB = "CHUNKY_BITS_TPU_BAR"

        def f():
            return os.environ[KNOB]
    """, select=("CB102",))
    assert [v.rule for v in vs] == ["CB102"]


def test_env_read_allowed_in_tunables_and_for_other_prefixes(tmp_path):
    assert run_snippet(tmp_path, "cluster/tunables.py", """
        import os

        def env_str(name):
            return os.environ.get(name, "")

        def f():
            return os.environ.get("CHUNKY_BITS_TPU_FOO")
    """, select=("CB102",)) == []
    assert run_snippet(tmp_path, "ops/y.py", """
        import os

        def f():
            return os.environ.get("JAX_PLATFORMS")
    """, select=("CB102",)) == []


def test_env_write_not_flagged(tmp_path):
    vs = run_snippet(tmp_path, "cli/x.py", """
        import os

        def f(v):
            os.environ["CHUNKY_BITS_TPU_BACKEND"] = v
    """, select=("CB102",))
    assert vs == []


# ---- CB103 non-daemon-thread ----

def test_thread_rule_flags_pool_and_nondaemon_thread(tmp_path):
    vs = run_snippet(tmp_path, "ops/x.py", """
        import threading
        from concurrent.futures import ThreadPoolExecutor

        def f(fn):
            pool = ThreadPoolExecutor(max_workers=2)
            t = threading.Thread(target=fn)
            return pool, t
    """, select=("CB103",))
    assert [v.rule for v in vs] == ["CB103", "CB103"]


def test_thread_rule_passes_daemon_thread_and_other_paths(tmp_path):
    assert run_snippet(tmp_path, "ops/x.py", """
        import threading

        def f(fn):
            return threading.Thread(target=fn, daemon=True)
    """, select=("CB103",)) == []
    # file/ (other than chunk_cache) is out of scope for CB103
    assert run_snippet(tmp_path, "file/x.py", """
        from concurrent.futures import ThreadPoolExecutor

        def f():
            return ThreadPoolExecutor()
    """, select=("CB103",)) == []


# ---- CB104 broad-except ----

def test_broad_except_flagged_without_justification(tmp_path):
    vs = run_snippet(tmp_path, "file/x.py", """
        def f():
            try:
                return 1
            except Exception:
                return None
    """, select=("CB104",))
    assert [v.rule for v in vs] == ["CB104"]


def test_broad_except_terminal_raise_passes(tmp_path):
    vs = run_snippet(tmp_path, "file/x.py", """
        def f():
            try:
                return 1
            except Exception as err:
                raise RuntimeError("wrapped") from err
    """, select=("CB104",))
    assert vs == []


def test_broad_except_narrow_type_passes(tmp_path):
    vs = run_snippet(tmp_path, "file/x.py", """
        def f():
            try:
                return 1
            except (OSError, ValueError):
                return None
    """, select=("CB104",))
    assert vs == []


def test_broad_except_bare_and_tuple_flagged(tmp_path):
    vs = run_snippet(tmp_path, "file/x.py", """
        def f():
            try:
                return 1
            except (ValueError, Exception):
                return None

        def g():
            try:
                return 1
            except:  # noqa: E722
                return None
    """, select=("CB104",))
    assert len(vs) == 2


def test_noqa_ble001_with_reason_accepted(tmp_path):
    vs = run_snippet(tmp_path, "file/x.py", """
        def f():
            try:
                return 1
            except Exception as err:  # noqa: BLE001 — surfaced upstream
                return err
    """, select=("CB104",))
    assert vs == []


# ---- CB105 jit-body hygiene ----

def test_unrolled_range_loop_in_traced_fn_flagged(tmp_path):
    vs = run_snippet(tmp_path, "ops/x.py", """
        import jax.numpy as jnp

        def compress(w):
            for i in range(64):
                w = w + jnp.tanh(w)
            return w
    """, select=("CB105",))
    assert [v.rule for v in vs] == ["CB105"]
    assert "fori_loop" in vs[0].message


def test_host_side_range_loop_passes(tmp_path):
    # no jnp/lax/pl reference in the function: host code, not a jit body
    vs = run_snippet(tmp_path, "ops/x.py", """
        def table():
            return [i * 2 for i in range(256)] + [
                j for j in range(256)]

        def small(xs):
            import jax.numpy as jnp
            for i in range(8):
                xs = jnp.roll(xs, 1)
            return xs
    """, select=("CB105",))
    assert vs == []


def test_device_concat_flagged(tmp_path):
    vs = run_snippet(tmp_path, "ops/x.py", """
        import jax.numpy as jnp

        def f(a, b):
            return jnp.concatenate([a, b], axis=1)
    """, select=("CB105",))
    assert [v.rule for v in vs] == ["CB105"]


def test_xor_schedule_module_is_in_cb101_cb105_scope(tmp_path):
    """The scheduled-XOR engine (ops/xor_schedule.py) sits on the
    CPU-fallback dispatch path: it must stay inside both the
    bounded-wait (CB101) and jit-hygiene (CB105) scopes — and the
    shipped module itself must be clean with zero baseline entries
    (test_shipped_tree_is_clean covers the latter tree-wide)."""
    for rid, src in (("CB101", """
        async def f(task):
            return await task
    """), ("CB105", """
        import jax.numpy as jnp

        def f(a, b):
            return jnp.concatenate([a, b], axis=1)
    """)):
        vs = run_snippet(tmp_path / rid, "ops/xor_schedule.py", src,
                         select=(rid,))
        assert [v.rule for v in vs] == [rid], rid


def test_mesh_modules_are_in_cb101_cb105_scope(tmp_path):
    """The mesh backend and its dispatch pipeline (ISSUE 16) ARE the
    device dispatch path: both must sit inside the bounded-wait
    (CB101) and jit-hygiene (CB105) scopes — an unbounded wait here is
    exactly the tunnel-down hang the degrade invariant forbids, and a
    device concat here is exactly the odd-width u8 XLA quirk the LANE
    padding exists for.  Must-flag fixtures per module per rule; the
    shipped modules themselves are clean with zero baseline entries
    (test_shipped_tree_is_clean pins that tree-wide)."""
    for rel in ("ops/mesh_backend.py", "ops/dispatch_pipeline.py"):
        for rid, src in (("CB101", """
            async def f(task):
                return await task
        """), ("CB105", """
            import jax.numpy as jnp

            def f(a, b):
                return jnp.concatenate([a, b], axis=1)
        """)):
            vs = run_snippet(tmp_path / rid / rel.replace("/", "_"),
                             rel, src, select=(rid,))
            assert [v.rule for v in vs] == [rid], (rel, rid)
        # and the bounded idioms the shipped modules actually use pass:
        # handle waits ride run_bounded_dispatch, window sync is a
        # plain (non-async) lock — nothing for CB101 to flag
        vs = run_snippet(tmp_path / "ok" / rel.replace("/", "_"), rel,
                         """
            import threading

            def drain(lock: threading.Lock, entries: list) -> None:
                with lock:
                    entries.clear()
        """, select=("CB101", "CB105"))
        assert vs == [], rel


# ---- CB106 public-annotations ----

def test_missing_annotations_flagged_on_strict_module(tmp_path):
    vs = run_snippet(tmp_path, "ops/backend.py", """
        class Coder:
            def encode(self, data):
                return data

        def helper(x) -> int:
            return x
    """, select=("CB106",))
    # encode: params + return; helper: params only
    assert sorted(v.message.split()[2] for v in vs) == [
        "encode()", "encode()", "helper()"]


def test_private_and_nonstrict_modules_pass(tmp_path):
    assert run_snippet(tmp_path, "ops/backend.py", """
        def _internal(x):
            return x
    """, select=("CB106",)) == []
    assert run_snippet(tmp_path, "ops/other.py", """
        def public(x):
            return x
    """, select=("CB106",)) == []


# ---- CB108 clock-seam ----

def test_clock_rule_flags_direct_monotonic_in_scope(tmp_path):
    vs = run_snippet(tmp_path, "cluster/x.py", """
        import time

        def f():
            return time.monotonic()
    """, select=("CB108",))
    assert [v.rule for v in vs] == ["CB108"]
    assert "clock seam" in vs[0].message


def test_clock_rule_flags_time_time_and_loop_time(tmp_path):
    vs = run_snippet(tmp_path, "file/x.py", """
        import asyncio
        import time

        def stamp():
            return time.time()

        async def deadline():
            loop = asyncio.get_running_loop()
            return loop.time() + 30.0
    """, select=("CB108",))
    assert [v.rule for v in vs] == ["CB108", "CB108"]


def test_clock_rule_out_of_scope_and_seam_module_pass(tmp_path):
    # the seam module itself is the one sanctioned home for direct
    # reads; ops/ outside batching.py and other planes are out of scope
    assert run_snippet(tmp_path, "cluster/clock.py", """
        import time

        def monotonic():
            return time.monotonic()
    """, select=("CB108",)) == []
    assert run_snippet(tmp_path, "ops/backend.py", """
        import time

        def f():
            return time.monotonic()
    """, select=("CB108",)) == []


def test_clock_rule_flags_alias_import_spellings(tmp_path):
    # the CB102 convention: renamed imports must not slip past the lint
    vs = run_snippet(tmp_path, "cluster/x.py", """
        import time as t
        from time import monotonic
        from time import perf_counter as pc

        def f():
            return t.monotonic() + monotonic() + pc()
    """, select=("CB108",))
    assert [v.rule for v in vs] == ["CB108", "CB108", "CB108"]


def test_clock_rule_passes_non_loop_time_methods(tmp_path):
    # a .time() on an arbitrary call result is NOT loop.time(): only
    # event-loop getters count as the call-result spelling
    assert run_snippet(tmp_path, "cluster/x.py", """
        import datetime

        def f():
            return datetime.datetime.now().time()
    """, select=("CB108",)) == []


def test_clock_rule_suppression_with_reason(tmp_path):
    assert run_snippet(tmp_path, "file/x.py", """
        import time

        def publish_stamp():
            # lint: clock-ok wall-clock stamp for humans
            return time.time()
    """, select=("CB108",)) == []


def test_clock_rule_passes_seam_reads(tmp_path):
    assert run_snippet(tmp_path, "cluster/x.py", """
        from chunky_bits_tpu.cluster import clock as _clock

        def f():
            return _clock.monotonic()
    """, select=("CB108",)) == []


# ---- CB109 fsio-seam ----

def test_fsio_rule_flags_direct_os_verbs_in_scope(tmp_path):
    vs = run_snippet(tmp_path, "file/slab.py", """
        import os

        def swap(tmp, target, root):
            os.replace(tmp, target)
            os.fsync(3)
            os.unlink(tmp)
    """, select=("CB109",))
    assert [v.rule for v in vs] == ["CB109", "CB109", "CB109"]
    assert "filesystem seam" in vs[0].message


def test_fsio_rule_flags_write_mode_open_only(tmp_path):
    vs = run_snippet(tmp_path, "cluster/metadata.py", """
        def publish(path, data):
            with open(path, "wb") as f:
                f.write(data)

        def probe(path):
            with open(path, "rb") as f:
                return f.read(1)

        def default_mode_read(path):
            with open(path) as f:
                return f.read()
    """, select=("CB109",))
    assert [v.rule for v in vs] == ["CB109"]
    assert "write-mode open" in vs[0].message


def test_fsio_rule_out_of_scope_modules_pass(tmp_path):
    # the seam applies to the storage-plane modules, not the whole tree
    assert run_snippet(tmp_path, "gateway/http.py", """
        import os

        def f(a, b):
            os.replace(a, b)
    """, select=("CB109",)) == []


def test_fsio_rule_passes_seam_calls_and_suppressions(tmp_path):
    assert run_snippet(tmp_path, "file/location.py", """
        from chunky_bits_tpu.utils import fsio as _fsio

        def publish(tmp, target):
            with _fsio.open(tmp, "wb") as f:
                f.write(b"x")
                _fsio.fsync(f)
            _fsio.replace(tmp, target)
    """, select=("CB109",)) == []
    assert run_snippet(tmp_path, "file/slab.py", """
        import os

        def lock_fd(path):
            # lint: fsio-ok the flock target carries no data
            return os.open(path, os.O_CREAT | os.O_RDWR)
    """, select=("CB109",)) == []


def test_fsio_rule_covers_repair_and_scrub(tmp_path):
    """The repair planner's in-place rewrite path joined the scope
    with ISSUE 14: any future direct disk op there must surface."""
    for i, rel in enumerate(("cluster/repair.py", "cluster/scrub.py")):
        vs = run_snippet(tmp_path / str(i), rel, """
            import os

            def rewrite(tmp, target):
                os.replace(tmp, target)
        """, select=("CB109",))
        assert [v.rule for v in vs] == ["CB109"], rel


# ---- CB201 async-blocking ----

def test_async_blocking_flags_sleep_open_subprocess(tmp_path):
    vs = run_snippet(tmp_path, "gateway/x.py", """
        import subprocess
        import time

        async def handler(path):
            time.sleep(1.0)
            with open(path) as f:
                data = f.read()
            subprocess.run(["sync"])
            return data
    """, select=("CB201",))
    assert [v.rule for v in vs] == ["CB201"] * 3
    assert "time.sleep" in vs[0].message
    assert "open" in vs[1].message
    assert "subprocess.run" in vs[2].message


def test_async_blocking_flags_eager_args_of_to_thread(tmp_path):
    # os.listdir(path) as an ARGUMENT runs on the loop before the hop
    vs = run_snippet(tmp_path, "cluster/x.py", """
        import asyncio
        import os

        async def ls(path):
            return await asyncio.to_thread(sorted, os.listdir(path))
    """, select=("CB201",))
    assert [v.rule for v in vs] == ["CB201"]
    assert "os.listdir" in vs[0].message


def test_async_blocking_passes_offloaded_and_nested_sync(tmp_path):
    vs = run_snippet(tmp_path, "file/x.py", """
        import asyncio
        import os

        async def ok(path):
            # callable passed, not called: runs on the worker
            f = await asyncio.to_thread(open, path, "rb")
            names = await asyncio.to_thread(os.listdir, path)
            return f, names

        async def nested(path, data):
            def _write():
                with open(path, "wb") as f:
                    f.write(data)
            await asyncio.to_thread(_write)

        def sync_code(path):
            return open(path).read()
    """, select=("CB201",))
    assert vs == []


# ---- CB202 lock-across-await ----

def test_lock_across_await_flagged(tmp_path):
    vs = run_snippet(tmp_path, "parallel/x.py", """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            async def bad(self, fetch):
                with self._lock:
                    return await fetch()
    """, select=("CB202",))
    assert [v.rule for v in vs] == ["CB202"]
    assert "_lock" in vs[0].message


def test_lock_across_await_resolves_bare_import(tmp_path):
    vs = run_snippet(tmp_path, "parallel/x.py", """
        from threading import Lock

        guard = Lock()

        async def bad(fetch):
            with guard:
                return await fetch()
    """, select=("CB202",))
    assert [v.rule for v in vs] == ["CB202"]


def test_lock_across_await_flags_implicit_suspensions(tmp_path):
    """async for / async with suspend without an ast.Await node; the
    lock is held across the suspension all the same."""
    vs = run_snippet(tmp_path, "file/x.py", """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            async def bad_for(self, stream):
                with self._lock:
                    async for chunk in stream:
                        self.total += len(chunk)

            async def bad_with(self, resource):
                with self._lock:
                    async with resource:
                        return self.total
    """, select=("CB202",))
    assert [v.rule for v in vs] == ["CB202", "CB202"]


def test_lock_across_await_passes_safe_shapes(tmp_path):
    vs = run_snippet(tmp_path, "parallel/x.py", """
        import asyncio
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._alock = asyncio.Lock()

            async def ok(self, fetch):
                with self._lock:
                    snapshot = self.x  # sync-only critical section
                return await fetch(snapshot)

            async def ok_async_lock(self, fetch):
                async with self._alock:
                    return await fetch()

            async def ok_nested_def(self, fetch):
                with self._lock:
                    async def later():
                        await fetch()  # runs after release
                    return later
    """, select=("CB202",))
    assert vs == []


# ---- CB203 task-leak ----

def test_fire_and_forget_task_flagged(tmp_path):
    vs = run_snippet(tmp_path, "gateway/x.py", """
        import asyncio

        async def spawny(work, loop):
            asyncio.create_task(work())
            asyncio.ensure_future(work())
            loop.create_task(work())
    """, select=("CB203",))
    assert [v.rule for v in vs] == ["CB203"] * 3


def test_stored_awaited_and_callbacked_tasks_pass(tmp_path):
    vs = run_snippet(tmp_path, "gateway/x.py", """
        import asyncio

        async def ok(work, registry):
            t = asyncio.create_task(work())
            registry.append(asyncio.ensure_future(work()))
            await asyncio.create_task(work())
            asyncio.create_task(work()).add_done_callback(print)
            return t
    """, select=("CB203",))
    assert vs == []


# ---- CB204 cross-plane (the call-graph pass) ----

def test_cross_plane_flags_event_set_via_thread_target(tmp_path):
    vs = run_snippet(tmp_path, "parallel/x.py", """
        import asyncio
        import threading

        class Pipe:
            def __init__(self):
                self.done = asyncio.Event()
                self._t = threading.Thread(
                    target=self._worker_body, daemon=True)

            def _worker_body(self):
                self.finish()

            def finish(self):
                self.done.set()
    """, select=("CB204",))
    assert [v.rule for v in vs] == ["CB204"]
    assert "asyncio.Event" in vs[0].message and "finish" in vs[0].message


def test_cross_plane_flags_loop_bound_class_via_job_lambda(tmp_path):
    # lambda handed to _Job + LOOP_BOUND tag inheritance by base name
    vs = run_snippet(tmp_path, "parallel/x.py", """
        class Batcher:
            LOOP_BOUND = True

            def poke(self):
                pass

        class SubBatcher(Batcher):
            pass

        def stage(pipe, data):
            b = SubBatcher()
            pipe.submit("encode", lambda: b.poke())
    """, select=("CB204",))
    assert [v.rule for v in vs] == ["CB204"]
    assert "LOOP_BOUND" in vs[0].message


def test_cross_plane_flags_callable_via_pipeline_run(tmp_path):
    """The async product path hands compute to workers through
    ``await pipeline.run(stage, fn)`` — those callables are roots too."""
    vs = run_snippet(tmp_path, "file/x.py", """
        import asyncio

        class Cache:
            LOOP_BOUND = True

            def get(self, key):
                return None

        async def serve(pipe, key):
            cache = Cache()
            return await pipe.run("verify", lambda: cache.get(key))
    """, select=("CB204",))
    assert [v.rule for v in vs] == ["CB204"]
    assert "cache.get" in vs[0].message


def test_cross_plane_flags_call_soon_from_decorated_to_thread_target(
        tmp_path):
    vs = run_snippet(tmp_path, "ops/x.py", """
        import asyncio
        import functools

        @functools.lru_cache(None)
        def hop(loop, fn):
            loop.call_soon(fn)

        async def go(loop, fn):
            await asyncio.to_thread(hop, loop, fn)
    """, select=("CB204",))
    assert [v.rule for v in vs] == ["CB204"]
    assert "call_soon" in vs[0].message


def test_cross_plane_passes_threadsafe_doors_and_thread_event(tmp_path):
    vs = run_snippet(tmp_path, "parallel/x.py", """
        import asyncio
        import threading

        class Pipe:
            def __init__(self):
                self._done = threading.Event()
                self._t = threading.Thread(
                    target=self._worker_body, daemon=True)

            def _worker_body(self):
                self._done.set()  # threading.Event: thread-safe

            def bridge(self, loop, fn, coro):
                loop.call_soon_threadsafe(fn)
                asyncio.run_coroutine_threadsafe(coro(), loop)

        def make(pipe):
            job = pipe.submit("hash", lambda: 1)
            job.add_done_callback(pipe.bridge)
    """, select=("CB204",))
    assert vs == []


def test_cross_plane_ignores_unreachable_loop_code(tmp_path):
    # the same loop-bound touches OFF the worker graph are fine
    vs = run_snippet(tmp_path, "parallel/x.py", """
        import asyncio

        class Pipe:
            def __init__(self):
                self.done = asyncio.Event()

            async def on_loop(self):
                self.done.set()
    """, select=("CB204",))
    assert vs == []


# ---- CB205 loop-shared ----

def test_loop_shared_flags_module_and_class_mutables(tmp_path):
    vs = run_snippet(tmp_path, "gateway/x.py", """
        import asyncio
        from collections import OrderedDict

        _registry = {}
        _queue = asyncio.Queue()

        class Handler:
            seen = OrderedDict()
    """, select=("CB205",))
    assert [v.rule for v in vs] == ["CB205"] * 3
    assert "dict literal" in vs[0].message
    assert "loop-bound" in vs[1].message
    assert "class-level" in vs[2].message


def test_loop_shared_passes_safe_and_out_of_scope(tmp_path):
    assert run_snippet(tmp_path, "parallel/x.py", """
        import threading

        _LOCK = threading.Lock()
        _NAMES = ("a", "b")
        __all__ = ["x"]
        # lint: loop-shared-ok process-wide singleton guarded by _LOCK
        _cache = {}
    """, select=("CB205",)) == []
    # ops/ and cluster/ are out of scope for CB205
    assert run_snippet(tmp_path, "ops/x.py", """
        _REGISTRY = {}
    """, select=("CB205",)) == []


# ---- suppression parsing ----

def test_suppression_same_line_and_line_above(tmp_path):
    vs = run_snippet(tmp_path, "ops/x.py", """
        async def f(evt, fut):
            # lint: unbounded-await-ok winner always sets the event
            await evt.wait()
            return await fut  # lint: unbounded-await-ok drain resolves it
    """, select=("CB101",))
    assert vs == []


def test_suppression_skips_continuation_comment_lines(tmp_path):
    vs = run_snippet(tmp_path, "ops/x.py", """
        async def f(evt):
            # lint: unbounded-await-ok a justification long enough to
            # wrap over two comment lines still covers the next code line
            await evt.wait()
    """, select=("CB101",))
    assert vs == []


def test_suppression_without_reason_does_not_suppress(tmp_path):
    vs = run_snippet(tmp_path, "ops/x.py", """
        async def f(evt):
            await evt.wait()  # lint: unbounded-await-ok
    """, select=("CB101",))
    assert len(vs) == 1


def test_suppression_wrong_slug_does_not_suppress(tmp_path):
    vs = run_snippet(tmp_path, "ops/x.py", """
        async def f(evt):
            await evt.wait()  # lint: broad-except-ok wrong rule
    """, select=("CB101",))
    assert len(vs) == 1


# ---- baseline round-trip ----

def _sample_violations(tmp_path):
    return run_snippet(tmp_path, "ops/x.py", """
        import os

        def f():
            return os.environ.get("CHUNKY_BITS_TPU_FOO")

        def g():
            try:
                return f()
            except Exception:
                return None
    """)


def test_baseline_round_trip(tmp_path):
    vs = _sample_violations(tmp_path)
    assert len(vs) == 2
    baseline_path = tmp_path / "baseline.toml"
    core.write_baseline(baseline_path, vs)
    accepted = core.load_baseline(baseline_path)
    assert accepted == {v.key() for v in vs}
    # every finding baselined -> nothing new
    assert [v for v in vs if v.key() not in accepted] == []


def test_baseline_minimal_parser_matches_tomli(tmp_path):
    vs = _sample_violations(tmp_path)
    baseline_path = tmp_path / "baseline.toml"
    core.write_baseline(baseline_path, vs)
    text = baseline_path.read_text(encoding="utf-8")
    mini = core._parse_minimal_toml(text)
    assert {(e["rule"], e["path"], e["fingerprint"])
            for e in mini["violation"]} == {v.key() for v in vs}


def test_baseline_fingerprint_survives_line_motion(tmp_path):
    before = _sample_violations(tmp_path)
    after = run_snippet(tmp_path, "ops/x.py", """
        import os

        # an unrelated comment pushed everything down


        def f():
            return os.environ.get("CHUNKY_BITS_TPU_FOO")

        def g():
            try:
                return f()
            except Exception:
                return None
    """)
    assert {v.key() for v in before} == {v.key() for v in after}
    assert [v.line for v in before] != [v.line for v in after]


def test_missing_baseline_is_empty(tmp_path):
    assert core.load_baseline(tmp_path / "nope.toml") == set()


def test_corrupt_baseline_raises_clean_diagnostic(tmp_path):
    """A hand-edit typo must fail loudly with the file named, never as
    a raw decoder traceback or a silently-shrunk accepted set."""
    bad = tmp_path / "baseline.toml"
    bad.write_text('[[violation]]\nrule = "unterminated\n',
                   encoding="utf-8")
    with pytest.raises(ValueError, match="baseline .*unparseable"):
        core.load_baseline(bad)
    proc = _run_cli("--baseline", str(bad))
    assert proc.returncode == 2
    assert "unparseable" in proc.stderr


def test_files_outside_root_are_an_error_not_a_silent_skip(tmp_path):
    """A file whose rel path can't resolve against --root would dodge
    every path-scoped rule; that's an error, not a clean scan."""
    outside = tmp_path / "backend.py"
    outside.write_text(
        "import threading\n\n\ndef f(fn):\n"
        "    return threading.Thread(target=fn)\n", encoding="utf-8")
    root = tmp_path / "pkg"
    root.mkdir()
    violations, errors = core.run_analysis(root, rules.ALL_RULES,
                                           files=[outside])
    assert violations == []
    assert len(errors) == 1 and "outside --root" in errors[0]


def test_unparseable_file_is_an_error_not_a_skip(tmp_path):
    path = tmp_path / "ops" / "bad.py"
    path.parent.mkdir(parents=True)
    path.write_text("def broken(:\n", encoding="utf-8")
    violations, errors = core.run_analysis(tmp_path, rules.ALL_RULES)
    assert violations == []
    assert len(errors) == 1 and "bad.py" in errors[0]


# ---- the gate itself (CLI contract + shipped-tree cleanliness) ----

def _run_cli(*args, cwd=None):
    return subprocess.run(
        [sys.executable, "-m", "chunky_bits_tpu.analysis", *args],
        capture_output=True, text=True, timeout=120,
        cwd=cwd or str(PKG_ROOT.parent))


@pytest.mark.filterwarnings("ignore")
def test_shipped_tree_is_clean():
    """THE acceptance gate: the analyzer exits 0 on the tree as
    shipped.  A new violation anywhere in chunky_bits_tpu/ fails
    tier-1 right here."""
    proc = _run_cli()
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "ok:" in proc.stdout


def test_cli_fails_on_introduced_violation(tmp_path):
    """End-to-end: introducing a fixture violation into a scanned tree
    turns the exit code non-zero (the ISSUE's acceptance criterion)."""
    scratch = tmp_path / "pkg"
    (scratch / "ops").mkdir(parents=True)
    (scratch / "ops" / "fresh.py").write_text(
        "import os\n\n\ndef f():\n"
        "    return os.environ.get('CHUNKY_BITS_TPU_NEW_KNOB')\n",
        encoding="utf-8")
    proc = _run_cli("--root", str(scratch), "--baseline",
                    str(tmp_path / "empty.toml"))
    assert proc.returncode == 1
    assert "CB102" in proc.stdout


def test_cli_write_baseline_refuses_restricted_scans(tmp_path):
    """A --select/path-restricted scan sees only a subset of findings;
    writing that subset out would drop every accepted entry outside it
    (and the next full run would fail on the re-surfaced findings)."""
    for args in (("--select", "CB101", "--write-baseline"),
                 (str(PKG_ROOT / "file"), "--write-baseline")):
        proc = _run_cli(*args, "--baseline", str(tmp_path / "b.toml"))
        assert proc.returncode == 2
        assert "full scan" in proc.stderr
        assert not (tmp_path / "b.toml").exists()


def test_cli_write_baseline_refuses_scan_with_file_errors(tmp_path):
    """An unparseable file's accepted findings are missing from the
    scan; writing the baseline anyway would drop them silently."""
    scratch = tmp_path / "pkg"
    (scratch / "ops").mkdir(parents=True)
    (scratch / "ops" / "bad.py").write_text("def broken(:\n",
                                            encoding="utf-8")
    proc = _run_cli("--root", str(scratch), "--write-baseline",
                    "--baseline", str(tmp_path / "b.toml"))
    assert proc.returncode == 2
    assert "file errors" in proc.stderr
    assert not (tmp_path / "b.toml").exists()


def test_cli_list_rules_names_every_rule_grouped_by_family():
    proc = _run_cli("--list-rules")
    assert proc.returncode == 0
    for rid in ("CB101", "CB102", "CB103", "CB104", "CB105", "CB106",
                "CB107", "CB108", "CB109",
                "CB201", "CB202", "CB203", "CB204", "CB205",
                "CB301", "CB302", "CB303", "CB304", "CB305",
                "CB401", "CB402", "CB403", "CB404", "CB405"):
        assert rid in proc.stdout
    # family grouping with one-line hazard descriptions
    assert "CB1xx — " in proc.stdout
    assert "CB2xx — " in proc.stdout
    assert "CB3xx — " in proc.stdout
    assert "CB4xx — " in proc.stdout
    assert proc.stdout.index("CB1xx") < proc.stdout.index("CB101")
    assert proc.stdout.index("CB2xx") < proc.stdout.index("CB201")
    assert proc.stdout.index("CB3xx") < proc.stdout.index("CB301")
    assert proc.stdout.index("CB4xx") < proc.stdout.index("CB401")


def test_cli_select_family_prefix():
    """--select CB2 selects the whole CB2xx family (the acceptance
    criterion invocation), and exits 0 on the shipped tree."""
    proc = _run_cli("--select", "CB2", "--list-rules")
    assert proc.returncode == 0
    assert "CB201" in proc.stdout and "CB205" in proc.stdout
    assert "CB101" not in proc.stdout
    proc = _run_cli("--select", "CB2")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    proc = _run_cli("--select", "CB9")
    assert proc.returncode == 2
    assert "unknown rule ids" in proc.stderr
    # empty tokens must not silently select every rule
    proc = _run_cli("--select", "CB2,", "--list-rules")
    assert proc.returncode == 0
    assert "CB101" not in proc.stdout
    proc = _run_cli("--select", ",")
    assert proc.returncode == 2


def test_cli_json_contract():
    import json

    proc = _run_cli("--json")
    assert proc.returncode == 0
    payload = json.loads(proc.stdout)
    assert payload["ok"] is True
    assert payload["new"] == []


def test_cli_json_reports_rule_family(tmp_path):
    import json

    scratch = tmp_path / "pkg"
    (scratch / "gateway").mkdir(parents=True)
    (scratch / "gateway" / "fresh.py").write_text(
        "import asyncio\n\n\nasync def f(work):\n"
        "    asyncio.create_task(work())\n", encoding="utf-8")
    proc = _run_cli("--root", str(scratch), "--baseline",
                    str(tmp_path / "empty.toml"), "--json")
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert [v["rule_family"] for v in payload["new"]] == ["CB2xx"]
    assert payload["new"][0]["rule"] == "CB203"


# ---- CB3xx whole-program reachability family ----

def run_tree(tmp_path: Path, files: dict, select: tuple = ()):
    """Lint a multi-file fixture tree (the CB3xx rules are
    interprocedural: roots and flagged sites live in different
    modules)."""
    for rel, source in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(source), encoding="utf-8")
    ruleset = [r for r in rules.ALL_RULES
               if not select or r.id in select]
    violations, errors = core.run_analysis(tmp_path, ruleset)
    assert not errors, errors
    return violations


# -- CB301 fsio-escape --

def test_fsio_escape_flags_reachable_offseam_helper(tmp_path):
    """The hole CB109 cannot see: os.replace extracted into a utils/
    helper that a durability root still reaches cross-module."""
    vs = run_tree(tmp_path, {
        "file/slab.py": """
            from utils import misc

            class SlabStore:
                def append(self, a, b):
                    misc.swap(a, b)
        """,
        "utils/misc.py": """
            import os

            def swap(a, b):
                os.replace(a, b)
        """,
    }, select=("CB301",))
    assert [(v.rule, v.path) for v in vs] == \
        [("CB301", "utils/misc.py")]
    assert "os.replace()" in vs[0].message
    assert "crash harness" in vs[0].message


def test_fsio_escape_passes_unreachable_and_governed(tmp_path):
    # same off-seam helper, but nothing on a durability path calls it
    assert run_tree(tmp_path, {
        "file/slab.py": """
            class SlabStore:
                def append(self, a, b):
                    return (a, b)
        """,
        "utils/misc.py": """
            import os

            def swap(a, b):
                os.replace(a, b)
        """,
    }, select=("CB301",)) == []
    # ops inside CB109's own path scope are CB109's findings, not a
    # second CB301 on the same line
    assert run_tree(tmp_path, {
        "file/slab.py": """
            import os

            class SlabStore:
                def append(self, a, b):
                    os.replace(a, b)
        """,
    }, select=("CB301",)) == []


def test_fsio_escape_write_mode_open_and_suppression(tmp_path):
    files = {
        "file/slab.py": """
            from utils import misc

            class SlabStore:
                def compact(self, p):
                    misc.dump(p)
                    misc.load(p)
        """,
        "utils/misc.py": """
            def dump(p):
                with open(p, "wb") as f:
                    f.write(b"x")

            def load(p):
                with open(p, "rb") as f:
                    return f.read()
        """,
    }
    vs = run_tree(tmp_path, files, select=("CB301",))
    # write-mode open flagged, read-mode open not
    assert [v.rule for v in vs] == ["CB301"]
    assert "write-mode open" in vs[0].message
    files["utils/misc.py"] = """
        def dump(p):
            # lint: fsio-escape-ok fixture-sanctioned off-seam write
            with open(p, "wb") as f:
                f.write(b"x")

        def load(p):
            with open(p, "rb") as f:
                return f.read()
    """
    assert run_tree(tmp_path / "sup", files, select=("CB301",)) == []


# -- CB302 clock-escape --

def test_clock_escape_flags_reachable_wall_clock(tmp_path):
    vs = run_tree(tmp_path, {
        "sim/scenario.py": """
            from parallel import util

            async def drive(env):
                return util.step()
        """,
        "parallel/util.py": """
            import time

            def step():
                return time.monotonic()
        """,
    }, select=("CB302",))
    assert [(v.rule, v.path) for v in vs] == \
        [("CB302", "parallel/util.py")]
    assert "time.monotonic()" in vs[0].message
    assert "virtual-time" in vs[0].message


def test_clock_escape_passes_unreachable_and_governed(tmp_path):
    # wall clock in a function no scenario reaches: not this rule's
    # business (and outside CB108's path scope, nobody else's either)
    assert run_tree(tmp_path, {
        "sim/scenario.py": """
            async def drive(env):
                return None
        """,
        "parallel/util.py": """
            import time

            def step():
                return time.monotonic()
        """,
    }, select=("CB302",)) == []
    # reachable wall clock inside CB108's path scope: CB108's finding,
    # never a double report
    assert run_tree(tmp_path, {
        "sim/scenario.py": """
            from cluster import util

            async def drive(env):
                return util.step()
        """,
        "cluster/util.py": """
            import time

            def step():
                return time.monotonic()
        """,
    }, select=("CB302",)) == []


def test_clock_escape_flags_loop_time_and_alias_imports(tmp_path):
    vs = run_tree(tmp_path, {
        "sim/scenario.py": """
            from parallel import util

            async def drive(loop):
                return util.lag(loop) + util.stamp()
        """,
        "parallel/util.py": """
            from time import monotonic as mono

            def lag(loop):
                return loop.time()

            def stamp():
                return mono()
        """,
    }, select=("CB302",))
    assert sorted(v.message.split(" in ")[0] for v in vs) == \
        ["direct loop.time() (loop.time)", "direct time.monotonic"]


# -- CB303 cancel-safety --

def test_cancel_safety_flags_swallowed_cancelled(tmp_path):
    vs = run_snippet(tmp_path, "file/x.py", """
        import asyncio

        async def run(q):
            try:
                return await q.get()
            except asyncio.CancelledError:
                return None
    """, select=("CB303",))
    assert [v.rule for v in vs] == ["CB303"]
    assert "swallows CancelledError" in vs[0].message


def test_cancel_safety_flags_bare_and_base_exception(tmp_path):
    vs = run_snippet(tmp_path, "file/x.py", """
        import asyncio

        async def a(q):
            try:
                return await q.get()
            except BaseException:
                return None

        async def b(q):
            try:
                return await q.get()
            except:
                return None
    """, select=("CB303",))
    assert len(vs) == 2
    assert "bare except" in vs[1].message


def test_cancel_safety_passes_reraise_and_child_reap(tmp_path):
    assert run_snippet(tmp_path, "file/x.py", """
        import asyncio

        async def run(q):
            try:
                return await q.get()
            except asyncio.CancelledError:
                raise

        async def stop(task):
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
    """, select=("CB303",)) == []


def test_cancel_safety_flags_cancel_without_await(tmp_path):
    vs = run_snippet(tmp_path, "gateway/x.py", """
        import asyncio

        async def abort(task):
            task.cancel()
            return True
    """, select=("CB303",))
    assert [v.rule for v in vs] == ["CB303"]
    assert "never awaited" in vs[0].message


def test_cancel_safety_herd_shape_regression(tmp_path):
    """The sim/scenario.py thundering-herd bug class: a finally that
    cancels the reader fleet but never awaits it leaves tasks
    mid-teardown when the function moves on.  Must-flag as written,
    must-pass once the reap gather is added (the shipped fix)."""
    vs = run_snippet(tmp_path, "sim/x.py", """
        import asyncio

        async def herd(make):
            tasks = [asyncio.ensure_future(make()) for _ in range(3)]
            try:
                await asyncio.gather(*tasks)
            finally:
                for t in tasks:
                    t.cancel()
    """, select=("CB303",))
    assert [v.rule for v in vs] == ["CB303"]
    assert run_snippet(tmp_path / "fixed", "sim/x.py", """
        import asyncio

        async def herd(make):
            tasks = [asyncio.ensure_future(make()) for _ in range(3)]
            try:
                await asyncio.gather(*tasks)
            finally:
                for t in tasks:
                    t.cancel()
                await asyncio.gather(*tasks, return_exceptions=True)
    """, select=("CB303",)) == []


def test_cancel_safety_passes_tuple_target_and_handles(tmp_path):
    """The fetch_hedged shape: cancel inside `for task, meta in
    d.items():` is observed by gathering the dict; TimerHandle.cancel()
    completes synchronously and needs no await."""
    assert run_snippet(tmp_path, "file/x.py", """
        import asyncio

        async def reap(pending):
            for task, (loc, t0) in pending.items():
                task.cancel()
            await asyncio.gather(*pending, return_exceptions=True)

        async def disarm(handle):
            handle.cancel()
    """, select=("CB303",)) == []


def test_cancel_safety_flags_publish_window_await(tmp_path):
    vs = run_snippet(tmp_path, "cluster/x.py", """
        import os

        async def publish(f, audit, tmp, dst):
            await f.write(b"x")
            await audit.notify()
            os.replace(tmp, dst)
    """, select=("CB303",))
    assert [v.rule for v in vs] == ["CB303"]
    assert "strands the temp file" in vs[0].message


def test_cancel_safety_passes_shielded_and_tight_windows(tmp_path):
    assert run_snippet(tmp_path, "cluster/x.py", """
        import asyncio
        import os

        async def publish(f, audit, tmp, dst):
            await f.write(b"x")
            await asyncio.shield(audit.notify())
            os.replace(tmp, dst)

        async def publish_tight(f, tmp, dst):
            await f.write(b"x")
            os.replace(tmp, dst)
    """, select=("CB303",)) == []


# -- CB304 sim-purity --

def test_sim_purity_flags_production_imports(tmp_path):
    for src in (
        "from chunky_bits_tpu.sim import fabric\n",
        "import chunky_bits_tpu.sim.fabric\n",
        "from chunky_bits_tpu import sim\n",
        # lazy in-function import: invisible to the runtime pin until
        # the branch executes, still a static finding here
        "def f():\n    from chunky_bits_tpu.sim import loop\n"
        "    return loop\n",
    ):
        vs = run_snippet(tmp_path / str(abs(hash(src)) % 997),
                         "file/x.py", src, select=("CB304",))
        assert [v.rule for v in vs] == ["CB304"], src
        assert "inverts the sim seam" in vs[0].message


def test_sim_purity_passes_sim_plane_and_lookalikes(tmp_path):
    # the simulator importing itself is the point, not a violation
    assert run_snippet(tmp_path, "sim/x.py", """
        from chunky_bits_tpu.sim import fabric
    """, select=("CB304",)) == []
    # 'sim' must match as a dotted segment, not a substring
    assert run_snippet(tmp_path / "b", "file/x.py", """
        import simpy
        from simulation import engine
    """, select=("CB304",)) == []


def test_sim_purity_suppression_on_sanctioned_inversion(tmp_path):
    assert run_snippet(tmp_path, "file/x.py", """
        def resolve(target):
            # lint: sim-purity-ok fixture-sanctioned lazy sim branch
            from chunky_bits_tpu.sim import fabric
            return fabric.resolve(target)
    """, select=("CB304",)) == []


# -- CB305 label-flow --

def test_label_flow_flags_fstring_at_call_site(tmp_path):
    vs = run_snippet(tmp_path, "obs/x.py", """
        COUNTER = object()

        def record(kind):
            COUNTER.labels(kind)

        def handler(path):
            record(f"get:{path}")
    """, select=("CB305",))
    assert [v.rule for v in vs] == ["CB305"]
    assert "'kind'" in vs[0].message
    # the finding lands at the CALL SITE, where the clamp belongs
    assert "record(" in vs[0].snippet


def test_label_flow_passes_closed_args_and_flags_kwargs(tmp_path):
    assert run_snippet(tmp_path, "obs/x.py", """
        COUNTER = object()

        def record(kind):
            COUNTER.labels(kind)

        def handler():
            record("get")
    """, select=("CB305",)) == []
    vs = run_snippet(tmp_path / "kw", "obs/x.py", """
        COUNTER = object()

        class Rec:
            def record(self, kind):
                COUNTER.labels(kind)

        def handler(rec, path):
            rec.record(kind="get:" + path)
    """, select=("CB305",))
    assert [v.rule for v in vs] == ["CB305"]


# -- call-graph precision units --

def _graph(tmp_path: Path, files: dict):
    from chunky_bits_tpu.analysis import callgraph

    sfs = []
    for rel, source in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        src = textwrap.dedent(source)
        path.write_text(src, encoding="utf-8")
        sfs.append(core.SourceFile(path, rel, src))
    return callgraph.build_call_graph(sfs)


def test_callgraph_self_methods_and_decorators(tmp_path):
    g = _graph(tmp_path, {"x.py": """
        def deco(fn):
            def wrapper():
                return fn()
            return wrapper

        class C:
            @deco
            def a(self):
                self.b()

            def b(self):
                pass
    """})
    assert ("x.py", "C.b") in g.edges[("x.py", "C.a")]
    # calling the decorated method actually runs the decorator's
    # machinery: the decorator is linked to its decoratee
    assert ("x.py", "C.a") in g.edges[("x.py", "deco")]


def test_callgraph_partial_lambda_and_to_thread_roots(tmp_path):
    g = _graph(tmp_path, {"x.py": """
        import asyncio
        import functools

        def helper(n):
            return n

        def lam_helper():
            return helper(2)

        async def spawn():
            await asyncio.to_thread(functools.partial(helper, 1))
            await asyncio.to_thread(lambda: lam_helper())
    """})
    assert ("x.py", "helper") in g.roots
    reach = g.worker_reachable()
    # the lambda is itself a root and its body's calls are followed
    assert ("x.py", "lam_helper") in reach
    assert ("x.py", "helper") in reach


def test_callgraph_counts_unknown_edges_for_dynamic_dispatch(tmp_path):
    g = _graph(tmp_path, {"x.py": """
        def f(cb, table):
            cb()
            table["k"]()
            return f()()
    """})
    assert g.unknown_edges[("x.py", "f")] == 3


def test_callgraph_cross_module_import_resolution(tmp_path):
    g = _graph(tmp_path, {
        "a/one.py": """
            from b import two

            def caller():
                two.target()
        """,
        "b/two.py": """
            def target():
                pass
        """,
    })
    assert ("b/two.py", "target") in g.edges[("a/one.py", "caller")]


def test_callgraph_async_defs_never_run_on_workers(tmp_path):
    """An async def handed to a thread only builds a coroutine object
    there — it must neither seed the worker closure nor be entered by
    it (the FilePart.read false-positive class)."""
    g = _graph(tmp_path, {"x.py": """
        import asyncio

        async def aread():
            return touched()

        def touched():
            return 1

        def sync_root():
            asyncio.to_thread(aread)

        async def spawn():
            await asyncio.to_thread(sync_root)
    """})
    assert ("x.py", "aread") not in g.roots
    reach = g.worker_reachable()
    assert ("x.py", "sync_root") in reach
    assert ("x.py", "aread") not in reach
    assert ("x.py", "touched") not in reach
    # general reachability still follows the handoff: the body DOES run
    # (on a loop), so seam rules must keep seeing it
    assert ("x.py", "aread") in g.reachable({("x.py", "sync_root")})


def test_callgraph_threadsafe_crossing_stops_worker_closure(tmp_path):
    """The HostPipeline bridge/resolve shape: a callable handed back
    through call_soon_threadsafe runs ON the loop — worker-ness must
    not flow through the sanctioned crossing, while plain reachability
    still does."""
    g = _graph(tmp_path, {"x.py": """
        import threading

        def start(loop, fut):
            def bridge():
                def resolve():
                    fut.set_result(1)
                loop.call_soon_threadsafe(resolve)
            threading.Thread(target=bridge, daemon=True).start()
    """})
    key_bridge = ("x.py", "start.bridge")
    key_resolve = ("x.py", "start.bridge.resolve")
    assert key_bridge in g.roots
    assert (key_bridge, key_resolve) in g.loop_edges
    reach = g.worker_reachable()
    assert key_bridge in reach
    assert key_resolve not in reach
    assert key_resolve in g.reachable({key_bridge})


# -- scoped fingerprints + baseline migration --

def test_fingerprint_survives_duplicate_line_churn(tmp_path):
    """The same offending line added in ANOTHER function must not shift
    the first finding's fingerprint (the failure mode of file-wide
    occurrence counting)."""
    before = run_snippet(tmp_path, "ops/x.py", """
        import os

        def f():
            return os.environ.get("CHUNKY_BITS_TPU_FOO")
    """, select=("CB102",))
    after = run_snippet(tmp_path / "b", "ops/x.py", """
        import os

        def earlier():
            return os.environ.get("CHUNKY_BITS_TPU_FOO")

        def f():
            return os.environ.get("CHUNKY_BITS_TPU_FOO")
    """, select=("CB102",))
    assert len(before) == 1 and len(after) == 2
    f_after = [v for v in after if v.scope == "f"]
    assert [v.fingerprint for v in f_after] == \
        [before[0].fingerprint]


def test_baseline_legacy_fingerprints_still_match(tmp_path):
    """One-shot migration: a pre-scope baseline entry (written before
    fingerprints carried the enclosing qualname) keeps matching through
    Violation.keys() until the next --write-baseline rewrites it."""
    vs = _sample_violations(tmp_path)
    legacy_entries = "".join(
        f'[[violation]]\nrule = "{v.rule}"\npath = "{v.path}"\n'
        f'fingerprint = "{v.legacy_fingerprint}"\n'
        for v in vs)
    baseline_path = tmp_path / "legacy.toml"
    baseline_path.write_text(legacy_entries, encoding="utf-8")
    accepted = core.load_baseline(baseline_path)
    assert all(set(v.keys()) & accepted for v in vs)
    # and the scoped spelling differs, so the dual key is load-bearing
    assert all(v.key() not in accepted for v in vs)


def test_write_baseline_records_scope(tmp_path):
    vs = _sample_violations(tmp_path)
    baseline_path = tmp_path / "b.toml"
    core.write_baseline(baseline_path, vs)
    text = baseline_path.read_text(encoding="utf-8")
    assert 'scope = "f"' in text and 'scope = "g"' in text


# -- CLI: --explain / --format github / --graph-stats --

def test_cli_explain_rule_and_family():
    proc = _run_cli("--explain", "CB303")
    assert proc.returncode == 0
    assert "cancel-safety" in proc.stdout
    assert "child-reap" in proc.stdout  # the docstring, not one line
    proc = _run_cli("--explain", "CB3")
    assert proc.returncode == 0
    for rid in ("CB301", "CB302", "CB303", "CB304", "CB305"):
        assert rid in proc.stdout
    proc = _run_cli("--explain", "fsio-escape")
    assert proc.returncode == 0 and "CB301" in proc.stdout
    proc = _run_cli("--explain", "CB999")
    assert proc.returncode == 2


def test_cli_format_github_annotations(tmp_path):
    scratch = tmp_path / "pkg"
    (scratch / "file").mkdir(parents=True)
    (scratch / "file" / "x.py").write_text(
        "import asyncio\n\n\nasync def run(q):\n    try:\n"
        "        return await q.get()\n"
        "    except asyncio.CancelledError:\n        return None\n",
        encoding="utf-8")
    proc = _run_cli("--root", str(scratch), "--no-baseline",
                    "--format", "github")
    assert proc.returncode == 1
    line = [ln for ln in proc.stdout.splitlines()
            if ln.startswith("::error")][0]
    assert "file=file/x.py" in line
    assert "title=CB303 [cancel-safety]" in line
    # messages are single annotation lines whatever they contain
    assert "\n" not in line and "%0A" not in line.split("::")[0]


def test_cli_graph_stats_text_and_json():
    import json

    proc = _run_cli("--select", "CB3", "--graph-stats")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "graph:" in proc.stdout and "worker roots" in proc.stdout
    proc = _run_cli("--select", "CB3", "--graph-stats", "--json")
    assert proc.returncode == 0
    payload = json.loads(proc.stdout)
    assert payload["graph"]["functions"] > 1000
    assert payload["graph"]["edges"] > payload["graph"]["functions"]
    assert 0 < payload["graph"]["worker_roots"] < 200


def test_cli_select_cb3_exits_zero_on_shipped_tree():
    """The ISSUE's acceptance invocation."""
    proc = _run_cli("--select", "CB3")
    assert proc.returncode == 0, proc.stdout + proc.stderr


# -- analyzer stays stdlib-only and inside the CI runtime budget --

def test_analyzer_imports_no_heavy_deps():
    """The linter must run with the device tunnel down: a full
    in-process analysis may not drag in jax/numpy/aiohttp."""
    code = (
        "import sys\n"
        "from pathlib import Path\n"
        "from chunky_bits_tpu.analysis import core, rules\n"
        "core.run_analysis(Path('chunky_bits_tpu'), rules.ALL_RULES)\n"
        "bad = [m for m in ('jax', 'numpy', 'aiohttp')\n"
        "       if m in sys.modules]\n"
        "assert not bad, bad\n")
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=120, cwd=str(PKG_ROOT.parent))
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_analyzer_runtime_budget():
    """Full run (all families, graph build included) stays under the
    CI budget — the whole-program pass must not make check.sh the slow
    leg."""
    import time as _time

    t0 = _time.monotonic()
    proc = _run_cli("--graph-stats")
    elapsed = _time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert elapsed < 15.0, f"analysis took {elapsed:.1f}s"


# ---- CB4xx resource-lifetime & deadline-propagation family ----

def _cfg_of(source: str):
    """Build the CFG of the first function in ``source``."""
    import ast

    from chunky_bits_tpu.analysis import cfg as cfgmod

    fn = ast.parse(textwrap.dedent(source)).body[0]
    return cfgmod.build_cfg(fn)


def _kinds(cfg) -> list:
    return cfg.kinds


def test_cfg_try_finally_edges():
    """Every way out of the try (fall-through, body exception, handler
    exception) runs the finally, and the finally's exits propagate the
    exceptional continuation outward."""
    from chunky_bits_tpu.analysis import cfg as cfgmod

    cfg = _cfg_of("""
        def f(a):
            try:
                a.work()
            finally:
                a.cleanup()
            return a
    """)
    assert cfgmod.K_FINPAD in cfg.kinds
    pad = cfg.kinds.index(cfgmod.K_FINPAD)
    import ast as _ast
    work = next(i for i, s in enumerate(cfg.stmts)
                if s is not None and isinstance(s, _ast.Expr)
                and "work" in _ast.dump(s))
    cleanup = next(i for i, s in enumerate(cfg.stmts)
                   if s is not None and isinstance(s, _ast.Expr)
                   and "cleanup" in _ast.dump(s))
    # body exception lands on the finally pad, not the raise exit
    assert cfg.exc[work] == {pad}
    assert pad in cfg.flow[work]  # fall-through also runs the finally
    assert cleanup in cfg.flow[pad]
    # the finally may be completing an exceptional path: its exit
    # nodes carry an exc edge outward
    assert cfg.raise_exit in cfg.exc[cleanup]


def test_cfg_with_unwind_and_await_cancellation_edges():
    """A with-body statement's exception unwinds through __exit__ (its
    exc edge), and EVERY await carries an exc edge — cancellation can
    surface at any suspension point even with nothing else to fail."""
    import ast as _ast

    cfg = _cfg_of("""
        async def f(cm, t):
            with cm:
                await t
    """)
    aw = next(i for i, s in enumerate(cfg.stmts)
              if s is not None and isinstance(s, _ast.Expr))
    assert cfg.raise_exit in cfg.exc[aw]
    # a bare await of a plain name: no call anywhere, still an exc
    # edge (the await-as-cancellation-point rule)
    cfg2 = _cfg_of("""
        async def g(t):
            await t
    """)
    aw2 = next(i for i, s in enumerate(cfg2.stmts)
               if s is not None and isinstance(s, _ast.Expr))
    assert cfg2.raise_exit in cfg2.exc[aw2]


def test_cfg_loop_orelse_break_continue():
    """break exits past the orelse, continue returns to the header,
    orelse runs only on normal loop exhaustion."""
    import ast as _ast

    cfg = _cfg_of("""
        def f(xs):
            for x in xs:
                if x:
                    break
                continue
            else:
                tail()
            return 1
    """)
    header = next(i for i, s in enumerate(cfg.stmts)
                  if isinstance(s, _ast.For))
    brk = next(i for i, s in enumerate(cfg.stmts)
               if isinstance(s, _ast.Break))
    cont = next(i for i, s in enumerate(cfg.stmts)
                if isinstance(s, _ast.Continue))
    ret = next(i for i, s in enumerate(cfg.stmts)
               if isinstance(s, _ast.Return))
    orelse = next(i for i, s in enumerate(cfg.stmts)
                  if s is not None and isinstance(s, _ast.Expr)
                  and "tail" in _ast.dump(s))
    assert cfg.flow[cont] == {header}
    assert ret in cfg.flow[brk]        # break skips the orelse
    assert orelse not in cfg.flow[brk]
    assert orelse in cfg.flow[header]  # exhaustion runs the orelse
    assert ret in cfg.flow[orelse]


def test_cfg_while_true_only_exits_via_break():
    cfg = _cfg_of("""
        def f(q):
            while True:
                if q.done():
                    break
        """)
    import ast as _ast
    brk = next(i for i, s in enumerate(cfg.stmts)
               if isinstance(s, _ast.Break))
    header = next(i for i, s in enumerate(cfg.stmts)
                  if isinstance(s, _ast.While))
    # the header has no normal exit edge to the function exit — only
    # the break reaches it
    assert cfg.exit not in cfg.flow[header]
    assert cfg.exit in cfg.flow[brk]


def test_cfg_dataflow_may_vs_must():
    """The engine's two meets on one diamond: a fact genned on one
    branch MAY reach the join but is not a MUST there."""
    import ast as _ast

    from chunky_bits_tpu.analysis.cfg import dataflow

    cfg = _cfg_of("""
        def f(c):
            if c:
                x = acquire()
            return x
    """)
    acq = next(i for i, s in enumerate(cfg.stmts)
               if isinstance(s, _ast.Assign))
    ret = next(i for i, s in enumerate(cfg.stmts)
               if isinstance(s, _ast.Return))
    gen = [frozenset()] * cfg.n_nodes
    kill = [frozenset()] * cfg.n_nodes
    gen[acq] = frozenset({"x"})
    may = dataflow(cfg, gen, kill)
    must = dataflow(cfg, gen, kill, must=True)
    assert "x" in may[ret]
    assert must[ret] is not None and "x" not in must[ret]


# -- CB401 fd-leak --

def test_fd_leak_flags_unguarded_open(tmp_path):
    """The PR 10 shape: a statement between open and the custody
    transfer can raise (or the await can be cancelled), orphaning f."""
    vs = run_tree(tmp_path, {
        "utils/u.py": """
            def f(path, n):
                f = open(path, "rb")
                f.seek(n)
                return f
        """,
    }, select=("CB401",))
    assert [v.rule for v in vs] == ["CB401"]
    assert "exception/cancellation path" in vs[0].message
    assert "f = open()" in vs[0].message


def test_fd_leak_passes_opener_guard_and_with(tmp_path):
    """The two sanctioned shapes: the try/except-BaseException opener
    guard (utils/aio.py FileReader._ensure) and plain `with`."""
    vs = run_tree(tmp_path, {
        "utils/u.py": """
            def guarded(path, n):
                f = open(path, "rb")
                try:
                    f.seek(n)
                except BaseException:
                    f.close()
                    raise
                return f

            def scoped(path):
                with open(path, "rb") as f:
                    return f.read()
        """,
    }, select=("CB401",))
    assert vs == []


def test_fd_leak_negative_control_open_in_thread_reaper(tmp_path):
    """The exact aio.open_in_thread opener contract, both ways: with
    the reaper guard the opener is clean; DELETE the guard and CB401
    must catch the orphaned handle on the cancellation path — proving
    the rule would have caught the PR 10 bug before the soak did."""
    guarded = """
        def _open(path, off):
            f = open(path, "rb")
            try:
                if off:
                    f.seek(off)
            except BaseException:
                f.close()
                raise
            return f
    """
    reaper_deleted = """
        def _open(path, off):
            f = open(path, "rb")
            if off:
                f.seek(off)
            return f
    """
    assert run_tree(tmp_path, {"utils/a.py": guarded},
                    select=("CB401",)) == []
    vs = run_tree(tmp_path, {"utils/b.py": reaper_deleted},
                  select=("CB401",))
    assert [v.rule for v in vs] == ["CB401"]


def test_fd_leak_custody_transfers_pass(tmp_path):
    """Handing the handle to a callee, storing it through an attribute
    or into a container (even inside a tuple), yielding it — all
    custody transfers, not leaks."""
    vs = run_tree(tmp_path, {
        "utils/u.py": """
            def to_callee(path, sink):
                f = open(path, "rb")
                sink(f)

            class Holder:
                def stash(self, path):
                    f = open(path, "rb")
                    self._f = f

                def index(self, path, k):
                    f = open(path, "rb")
                    self._m[k] = (path, f)

            def gen(path):
                f = open(path, "rb")
                yield f
        """,
    }, select=("CB401",))
    assert vs == []


def test_fd_leak_socket_mmap_and_fsio_open_tracked(tmp_path):
    vs = run_tree(tmp_path, {
        "utils/u.py": """
            import socket
            import mmap

            def s():
                sock = socket.socket()
                sock.connect(("h", 1))
                return sock

            def m(f):
                mm = mmap.mmap(f.fileno(), 0)
                if mm.size() == 0:
                    return None
                return mm
        """,
    }, select=("CB401",))
    assert sorted(v.message.split(" = ")[0].split()[-1] for v in vs) \
        == ["mm", "sock"]


def test_fd_leak_close_methods_exempt(tmp_path):
    """close()/__exit__ implementations ARE the release — the split
    halves must not self-flag."""
    vs = run_tree(tmp_path, {
        "utils/u.py": """
            class R:
                def close(self):
                    f = open(self._path, "rb")
                    f.flush()
        """,
    }, select=("CB401",))
    assert vs == []


# -- CB402 lock-discipline --

def test_lock_discipline_flags_unpaired_acquire(tmp_path):
    vs = run_tree(tmp_path, {
        "utils/u.py": """
            def f(lock, work):
                lock.acquire()
                work()
                lock.release()
        """,
    }, select=("CB402",))
    assert [v.rule for v in vs] == ["CB402"]
    assert "deadlock" in vs[0].message
    assert "with lock:" in vs[0].message


def test_lock_discipline_passes_finally_and_with(tmp_path):
    vs = run_tree(tmp_path, {
        "utils/u.py": """
            def paired(lock, work):
                lock.acquire()
                try:
                    work()
                finally:
                    lock.release()

            def ctx(lock, work):
                with lock:
                    work()
        """,
    }, select=("CB402",))
    assert vs == []


def test_lock_discipline_flock_pairing(tmp_path):
    """fcntl.flock: LOCK_EX without LOCK_UN on the exception path
    flags; the finally-paired shape passes (file/slab.py _Flock's
    split across __enter__/__exit__ is exempt by function name)."""
    flagged = run_tree(tmp_path, {
        "utils/u.py": """
            import fcntl

            def f(fd, work):
                fcntl.flock(fd, fcntl.LOCK_EX)
                work()
                fcntl.flock(fd, fcntl.LOCK_UN)
        """,
    }, select=("CB402",))
    assert [v.rule for v in flagged] == ["CB402"]
    clean = run_tree(tmp_path / "b", {
        "utils/u.py": """
            import fcntl

            def f(fd, work):
                fcntl.flock(fd, fcntl.LOCK_EX)
                try:
                    work()
                finally:
                    fcntl.flock(fd, fcntl.LOCK_UN)

            class _Flock:
                def __enter__(self):
                    fcntl.flock(self._fd, fcntl.LOCK_EX)

                def __exit__(self, *exc):
                    fcntl.flock(self._fd, fcntl.LOCK_UN)
        """,
    }, select=("CB402",))
    assert clean == []


# -- CB403 task-custody --

def test_task_custody_flags_assigned_then_leaked(tmp_path):
    """The shape syntactic CB203 cannot see: the task IS assigned, but
    an intervening cancellation point exits the scope without it."""
    vs = run_tree(tmp_path, {
        "utils/u.py": """
            import asyncio

            async def f(work, other):
                t = asyncio.create_task(work())
                await other()
                await t
        """,
    }, select=("CB403",))
    assert [v.rule for v in vs] == ["CB403"]
    assert "exception/cancellation path" in vs[0].message


def test_task_custody_passes_owned_shapes(tmp_path):
    vs = run_tree(tmp_path, {
        "utils/u.py": """
            import asyncio

            async def reaped(work, other):
                t = asyncio.create_task(work())
                try:
                    await other()
                finally:
                    t.cancel()
                    await t

            async def gathered(work):
                t = asyncio.ensure_future(work())
                await asyncio.gather(t)

            class S:
                def stored(self, work):
                    t = asyncio.create_task(work())
                    self._tasks.add(t)

            async def callbacked(work, reap):
                t = asyncio.ensure_future(work())
                t.add_done_callback(reap)
        """,
    }, select=("CB403",))
    assert vs == []


def test_task_custody_cancel_alone_is_not_custody(tmp_path):
    """cancel() only requests — without an await nothing observes the
    outcome (CB303's point, made path-sensitive)."""
    vs = run_tree(tmp_path, {
        "utils/u.py": """
            import asyncio

            async def f(work):
                t = asyncio.create_task(work())
                t.cancel()
        """,
    }, select=("CB403",))
    assert [v.rule for v in vs] == ["CB403"]


# -- CB404 unbounded-deadline --

def test_unbounded_deadline_flags_cross_module_bare_await(tmp_path):
    """The gap CB101's path list leaves: a bare await in a module off
    the list, reached from a gateway handler."""
    vs = run_tree(tmp_path, {
        "gateway/http.py": """
            from cluster import cluster

            async def handle(req):
                await cluster.fetch(req)
        """,
        "cluster/cluster.py": """
            async def fetch(req):
                await req.wait()
        """,
    }, select=("CB404",))
    assert [(v.rule, v.path) for v in vs] == \
        [("CB404", "cluster/cluster.py")]
    assert "no deadline at ANY frame" in vs[0].message


def test_unbounded_deadline_passes_bound_at_caller(tmp_path):
    """The converse gap: wait_for at the CALL SITE bounds everything
    beneath — the callee's bare await is fine on that path."""
    vs = run_tree(tmp_path, {
        "gateway/http.py": """
            import asyncio

            from cluster import cluster

            async def handle(req):
                await asyncio.wait_for(cluster.fetch(req), 5.0)
        """,
        "cluster/cluster.py": """
            async def fetch(req):
                await req.wait()
        """,
    }, select=("CB404",))
    assert vs == []


def test_unbounded_deadline_unreachable_and_governed_pass(tmp_path):
    """A bare await nothing serving-rooted reaches is CB101's business
    (or nobody's); modules CB101 already governs are excluded."""
    vs = run_tree(tmp_path, {
        "gateway/http.py": """
            async def handle(req):
                return req
        """,
        "cluster/cluster.py": """
            async def orphan(req):
                await req.wait()
        """,
        "ops/pipeline_helper.py": """
            async def governed(evt):
                await evt
        """,
    }, select=("CB404",))
    assert vs == []


# -- CB405 metered-io --

def test_metered_io_flags_uncharged_read(tmp_path):
    vs = run_tree(tmp_path, {
        "cluster/scrub.py": """
            class ScrubDaemon:
                async def run(self, loc):
                    await self._verify(loc)

                async def _verify(self, loc):
                    data = await loc.read()
        """,
    }, select=("CB405",))
    assert [(v.rule, v.path) for v in vs] == \
        [("CB405", "cluster/scrub.py")]
    assert "bucket.take()" in vs[0].message


def test_metered_io_passes_local_and_caller_charge(tmp_path):
    """Charge at the site passes; so does the charge-in-the-caller
    shape (entered-metered summaries composed through the graph)."""
    vs = run_tree(tmp_path, {
        "cluster/scrub.py": """
            class ScrubDaemon:
                async def run(self, loc):
                    await self._bucket.take(8)
                    data = await loc.read()
                    await self.bucket.take(8)
                    await self._helper(loc)

                async def _helper(self, loc):
                    return await loc.read()
        """,
    }, select=("CB405",))
    assert vs == []


def test_metered_io_one_charge_covers_one_io(tmp_path):
    """Exact metering: take once, read twice — the second read is
    uncharged and must flag."""
    vs = run_tree(tmp_path, {
        "cluster/repair.py": """
            async def repair_part(bucket, a, b):
                await bucket.take(8)
                x = await a.read()
                y = await b.read()
        """,
    }, select=("CB405",))
    assert len(vs) == 1
    assert vs[0].line == max(v.line for v in vs)  # the second read


def test_metered_io_metadata_plane_exempt(tmp_path):
    vs = run_tree(tmp_path, {
        "cluster/scrub.py": """
            class ScrubDaemon:
                async def run(self):
                    refs = await self.metadata.read("ns")
        """,
    }, select=("CB405",))
    assert vs == []


# -- family wiring --

def test_cb4_suppression_and_family_select(tmp_path):
    """Inline suppression works for CFG-rule findings, and --select CB4
    runs the family alone."""
    vs = run_tree(tmp_path, {
        "utils/u.py": """
            def f(path, n):
                # lint: fd-leak-ok handed to the caller's reaper registry
                f = open(path, "rb")
                f.seek(n)
                return f
        """,
    }, select=("CB401",))
    assert vs == []
    proc = _run_cli("--select", "CB4", "--list-rules")
    assert proc.returncode == 0
    for rid in ("CB401", "CB402", "CB403", "CB404", "CB405"):
        assert rid in proc.stdout
    assert "CB101" not in proc.stdout


def test_cb4_shipped_tree_clean_and_graph_stats_grow_cfg():
    """The family's acceptance criterion: --select CB4 exits 0 on the
    shipped tree, and --graph-stats reports the CFG layer's totals in
    both text and JSON."""
    import json

    proc = _run_cli("--select", "CB4", "--graph-stats", "--json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    graph = payload["graph"]
    for key in ("cfg_functions", "cfg_blocks", "cfg_edges",
                "dataflow_summaries"):
        assert graph[key] > 0, (key, graph)
    proc = _run_cli("--select", "CB4", "--graph-stats")
    assert proc.returncode == 0
    assert "cfg:" in proc.stdout and "summaries" in proc.stdout


def test_cli_prune_baseline_drops_stale_entries(tmp_path):
    """A deleted violation must not leave a dangling accept: prune
    rewrites the baseline keeping only entries that still match."""
    scratch = tmp_path / "pkg"
    (scratch / "ops").mkdir(parents=True)
    bad = ("import os\n\n\ndef f():\n"
           "    return os.environ.get('CHUNKY_BITS_TPU_KNOB')\n")
    (scratch / "ops" / "m.py").write_text(bad, encoding="utf-8")
    base = tmp_path / "b.toml"
    proc = _run_cli("--root", str(scratch), "--baseline", str(base),
                    "--write-baseline")
    assert proc.returncode == 0
    assert core.load_baseline(base)
    # fix the violation, then prune: the stale accept must vanish
    (scratch / "ops" / "m.py").write_text(
        "def f():\n    return None\n", encoding="utf-8")
    proc = _run_cli("--root", str(scratch), "--baseline", str(base),
                    "--prune-baseline")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "dropped 1" in proc.stdout
    assert core.load_baseline(base) == set()
    # and the guards mirror --write-baseline: no partial-scan prunes
    proc = _run_cli("--root", str(scratch), "--baseline", str(base),
                    "--select", "CB101", "--prune-baseline")
    assert proc.returncode == 2
    assert "full scan" in proc.stderr


def test_shipped_baseline_has_no_stale_entries():
    """CI fails on dangling accepts; this is the same check in-tree."""
    import json

    proc = _run_cli("--json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["stale_baseline_entries"] == 0
