"""Bounded device-init wait + degrade-to-CPU fallback (round 5).

The tunneled dev chip's PJRT client blocks forever when the tunnel is
down; ``backend: jax`` in cluster.yaml must degrade to the native CPU
codec, not hang a ``cp`` (VERDICT r4 item 3).  A real hang can't be
provoked on the CPU platform, so the probe seam (`_DEVICE_PROBE`) stands
in for the dead tunnel.
"""

import threading
import warnings

import numpy as np
import pytest

from chunky_bits_tpu.errors import DeviceInitTimeout
from chunky_bits_tpu.ops import backend as backend_mod
from chunky_bits_tpu.ops import jax_backend
from chunky_bits_tpu.ops.backend import ErasureCoder, NumpyBackend


@pytest.fixture
def dead_tunnel(monkeypatch):
    """Simulate a dead tunnel: the probe blocks until test teardown."""
    release = threading.Event()
    monkeypatch.setattr(jax_backend, "_DEVICE_PROBE", release.wait)
    monkeypatch.setattr(jax_backend, "_device_ready", False)
    monkeypatch.setattr(jax_backend, "_device_failed", None)
    monkeypatch.setenv(jax_backend.DEVICE_INIT_TIMEOUT_ENV, "0.05")
    # isolate the registry so cached real-jax backends don't short-circuit
    monkeypatch.setattr(backend_mod, "_REGISTRY", {})
    yield
    release.set()


def test_timeout_raises(dead_tunnel):
    with pytest.raises(DeviceInitTimeout) as exc:
        jax_backend.await_device_init()
    # the message must name the env knob so the warning is actionable
    assert jax_backend.DEVICE_INIT_TIMEOUT_ENV in str(exc.value)


def test_jax_spec_degrades_to_cpu(dead_tunnel):
    with pytest.warns(RuntimeWarning, match="DEGRADED"):
        b = backend_mod.get_backend("jax")
    assert b.name in ("native", "numpy")
    # ...and a cp-shaped encode completes on the fallback
    data = np.random.default_rng(0).integers(
        0, 256, (2, 3, 4096), dtype=np.uint8)
    got = ErasureCoder(3, 2, b).encode_batch(data)
    want = ErasureCoder(3, 2, NumpyBackend()).encode_batch(data)
    assert np.array_equal(got, want)


def test_mesh_spec_degrades_to_cpu(dead_tunnel):
    with pytest.warns(RuntimeWarning, match="DEGRADED"):
        b = backend_mod.get_backend("jax:dp2,sp2")
    assert b.name in ("native", "numpy")


def test_degraded_backend_cached_per_spec(dead_tunnel):
    with pytest.warns(RuntimeWarning):
        first = backend_mod.get_backend("jax")
    # second resolution must not re-pay the timeout (and not re-warn)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert backend_mod.get_backend("jax") is first


def test_probe_success_is_remembered(monkeypatch):
    calls = []
    monkeypatch.setattr(jax_backend, "_DEVICE_PROBE",
                        lambda: calls.append(1))
    monkeypatch.setattr(jax_backend, "_device_ready", False)
    monkeypatch.setattr(jax_backend, "_device_failed", None)
    jax_backend.await_device_init()
    jax_backend.await_device_init()
    assert calls == [1]
    assert jax_backend._device_ready


def test_bad_timeout_value_rejected(monkeypatch):
    # a config typo must fail resolution loudly, NOT read as a device
    # outage (DeviceInitTimeout would silently degrade to CPU)
    from chunky_bits_tpu.errors import ErasureError

    monkeypatch.setattr(jax_backend, "_device_ready", False)
    monkeypatch.setenv(jax_backend.DEVICE_INIT_TIMEOUT_ENV, "120s")
    with pytest.raises(ErasureError, match="120s") as exc:
        jax_backend.await_device_init()
    assert not isinstance(exc.value, DeviceInitTimeout)


def test_timeout_is_sticky_and_fails_fast(dead_tunnel, monkeypatch):
    with pytest.raises(DeviceInitTimeout) as first:
        jax_backend.await_device_init()
    # the second caller must re-raise the recorded failure without
    # starting another probe (probe calls, not wall clock, so a loaded
    # CI host can't flake this)
    calls = []
    monkeypatch.setattr(jax_backend, "_DEVICE_PROBE",
                        lambda: calls.append(1))
    with pytest.raises(DeviceInitTimeout) as second:
        jax_backend.await_device_init()
    assert second.value is first.value
    assert calls == []


class _BlockingApply:
    """Stands in for a device dispatch parked inside PJRT."""

    def __init__(self):
        self.release = threading.Event()

    def __call__(self, *a, **kw):
        self.release.wait()


def test_dispatch_timeout_degrades_jax_backend(monkeypatch):
    """A tunnel death AFTER init: the in-flight dispatch times out, the
    backend goes CPU-only for the process, output stays byte-identical,
    and later calls never touch the device again."""
    from chunky_bits_tpu.ops import jax_backend, matrix

    be = jax_backend.JaxBackend()
    blocker = _BlockingApply()
    monkeypatch.setattr(be, "_apply_matrix_device", blocker)
    monkeypatch.setenv(jax_backend.DISPATCH_TIMEOUT_ENV, "0.05")
    d, p = 3, 2
    enc = matrix.build_encode_matrix(d, p)
    data = np.random.default_rng(9).integers(
        0, 256, (2, d, 2048), dtype=np.uint8)
    want = ErasureCoder(d, p, NumpyBackend()).encode_batch(data)
    try:
        with pytest.warns(RuntimeWarning, match="DEGRADED"):
            got = be.apply_matrix(enc[d:], data)
        assert np.array_equal(got, want)
        assert be._device_dead
        # second call: straight to CPU, no bounded wait, no new warning
        calls_before = blocker.release.is_set()
        t0 = __import__("time").perf_counter()
        got2 = be.apply_matrix(enc[d:], data)
        assert __import__("time").perf_counter() - t0 < 1.0
        assert np.array_equal(got2, want)
        assert calls_before is False
    finally:
        blocker.release.set()


def test_dispatch_timeout_degrades_mesh_backend(monkeypatch):
    from chunky_bits_tpu.ops import matrix
    from chunky_bits_tpu.ops import jax_backend
    from chunky_bits_tpu.parallel.backend import MeshJaxBackend

    be = MeshJaxBackend("dp2,sp2")
    blocker = _BlockingApply()
    monkeypatch.setattr(be, "_apply", blocker)
    monkeypatch.setenv(jax_backend.DISPATCH_TIMEOUT_ENV, "0.05")
    d, p = 3, 2
    enc = matrix.build_encode_matrix(d, p)
    data = np.random.default_rng(10).integers(
        0, 256, (2, d, 2048), dtype=np.uint8)
    want = ErasureCoder(d, p, NumpyBackend()).encode_batch(data)
    try:
        with pytest.warns(RuntimeWarning, match="DEGRADED"):
            got = be.apply_matrix(enc[d:], data)
        assert np.array_equal(got, want)
        got2 = be.apply_matrix(enc[d:], data)  # sticky, no device touch
        assert np.array_equal(got2, want)
    finally:
        blocker.release.set()


def test_dispatch_bound_disabled_runs_inline(monkeypatch):
    """With the knob at 0 the dispatch runs inline on the caller's
    thread (no watchdog thread, no overhead) — the bench sets this."""
    from chunky_bits_tpu.ops import jax_backend

    monkeypatch.setenv(jax_backend.DISPATCH_TIMEOUT_ENV, "0")
    tid = []
    out = jax_backend.run_bounded_dispatch(
        lambda: tid.append(threading.get_ident()) or 42, "test")
    assert out == 42
    assert tid == [threading.get_ident()]


def test_dispatch_bad_env_value_loud(monkeypatch):
    from chunky_bits_tpu.errors import DeviceDispatchTimeout, ErasureError
    from chunky_bits_tpu.ops import jax_backend

    monkeypatch.setenv(jax_backend.DISPATCH_TIMEOUT_ENV, "10m")
    with pytest.raises(ErasureError, match="10m") as exc:
        jax_backend.run_bounded_dispatch(lambda: 1, "test")
    assert not isinstance(exc.value, DeviceDispatchTimeout)


def test_callback_gate_blocks_late_firing():
    """A dispatch thread answering AFTER the timeout degrade must not
    reach the caller's callback (digest-corruption guard)."""
    from chunky_bits_tpu.ops.jax_backend import _CallbackGate

    seen = []
    gate = _CallbackGate(lambda lo, arr: seen.append(lo))
    gate(0, None)
    gate.close()
    gate(1, None)  # the late, abandoned-attempt delivery
    assert seen == [0]
