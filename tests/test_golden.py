"""Golden file-reference conformance anchors.

Each fixture under tests/golden/ freezes bytes -> exact YAML: structure,
sha256 content addresses (so the GF(2^8) parity bytes are pinned through
their hashes), and for the cluster fixture the hash-seeded weighted
placement.  A kernel, layout, or serialization change that silently
breaks wire compatibility fails here; regenerate deliberately with
``python tests/golden/generate.py`` only for an intentional format
change.
"""

import asyncio
import os

import pytest

from tests.golden import generate as gen


def golden_text(name: str) -> str:
    with open(os.path.join(gen.GOLDEN_DIR, f"{name}.yaml")) as f:
        return f.read()


def test_fixtures_match_current_behavior():
    refs = asyncio.run(gen.build_refs())
    assert set(refs) == {"void_small", "void_wide", "cluster_placement",
                         "slab_placement", "block_digests",
                         "pm_msr_placement", "meta_log_placement"}
    for name, obj in refs.items():
        assert gen.dump(obj) == golden_text(name), (
            f"golden fixture {name} drifted — wire compatibility broken "
            "(or an intentional change: regenerate via "
            "tests/golden/generate.py and document it)")


def test_meta_log_fixture_identical_to_path_store():
    """Fixture 7 must equal fixture 3 byte-for-byte: the meta-log store
    is a metadata LAYOUT (append-only log + index), never a wire-format
    change — a ref published to the log and read back serializes
    exactly like one published file-per-ref."""
    assert golden_text("meta_log_placement") \
        == golden_text("cluster_placement")


def test_slab_fixture_mirrors_path_placement():
    """Fixture 4 differs from fixture 3 ONLY in the ``slab:`` location
    scheme: same content addresses, same hash-seeded node draw — the
    packed layout is a storage format, not a placement change."""
    import yaml

    plain = yaml.safe_load(golden_text("cluster_placement"))
    packed = yaml.safe_load(golden_text("slab_placement"))
    for p_part, s_part in zip(plain["parts"], packed["parts"]):
        for p_chunk, s_chunk in zip(p_part["data"] + p_part["parity"],
                                    s_part["data"] + s_part["parity"]):
            assert p_chunk["sha256"] == s_chunk["sha256"]
            assert [f"slab:{loc}" for loc in p_chunk["locations"]] \
                == s_chunk["locations"]


def test_block_digest_fixture_is_strictly_additive():
    """Fixture 5 differs from fixture 1 ONLY by the ``blocks`` trees:
    same content addresses, same structure — damage localization is
    metadata on top of the classic wire format, never a format fork."""
    import yaml

    plain = yaml.safe_load(golden_text("void_small"))
    treed = yaml.safe_load(golden_text("block_digests"))
    stripped = yaml.safe_load(golden_text("block_digests"))
    for part in stripped["parts"]:
        for chunk in part["data"] + part.get("parity", []):
            chunk.pop("blocks", None)
    assert stripped == plain, (
        "block_digests minus its trees must BE void_small")
    # and the trees themselves verify against the frozen chunk hashes:
    # tree blocks re-hash to the digests, digest count covers chunksize
    from chunky_bits_tpu.file.file_reference import FileReference

    ref = FileReference.from_obj(treed)
    for part in ref.parts:
        for chunk in part.data + part.parity:
            if part.chunksize <= 4096:
                assert chunk.blocks is None  # single-block: no tree
                continue
            assert chunk.blocks is not None
            assert chunk.blocks.size == 4096
            assert chunk.blocks.covers(part.chunksize)


def test_pm_msr_fixture_is_strictly_additive():
    """Fixture 6 differs from fixture 1 ONLY by the per-part ``code``
    key and the parity content addresses: the code is systematic, so
    the data chunks (and the structure around them) stay byte-identical
    — the regenerating code is a parity-math change on the same wire
    format, never a format fork."""
    import yaml

    plain = yaml.safe_load(golden_text("void_small"))
    msr = yaml.safe_load(golden_text("pm_msr_placement"))
    stripped = yaml.safe_load(golden_text("pm_msr_placement"))
    for part in stripped["parts"]:
        assert part.pop("code") == "pm-msr"
        part.pop("parity", None)
    rs_no_parity = yaml.safe_load(golden_text("void_small"))
    for part in rs_no_parity["parts"]:
        part.pop("parity", None)
    assert stripped == rs_no_parity, (
        "pm_msr_placement minus code+parity must BE void_small's data")
    # parity DOES differ — same geometry, different generator matrix;
    # identical parity would mean the pm-msr matrices silently
    # degenerated to Reed-Solomon
    for p_part, m_part in zip(plain["parts"], msr["parts"]):
        assert [c["sha256"] for c in p_part["parity"]] != \
            [c["sha256"] for c in m_part["parity"]]


def test_pm_msr_fixture_roundtrips_with_code():
    """Parse -> serialize preserves the ``code`` key byte-for-byte, and
    a ``code``-stripped document parses as a CLASSIC rs ref whose
    re-serialization is byte-identical to the stripped document (the
    key is the only delta an old writer would not produce)."""
    import yaml

    from chunky_bits_tpu.file.file_reference import FileReference

    obj = yaml.safe_load(golden_text("pm_msr_placement"))
    ref = FileReference.from_obj(obj)
    assert all(part.code == "pm-msr" for part in ref.parts)
    assert gen.dump(ref.to_obj()) == golden_text("pm_msr_placement")

    stripped = yaml.safe_load(golden_text("pm_msr_placement"))
    for part in stripped["parts"]:
        del part["code"]
    as_classic = FileReference.from_obj(stripped)
    assert all(part.code == "rs" for part in as_classic.parts)
    assert as_classic.to_obj() == stripped


def test_foreign_code_degrades_to_clean_read_error():
    """A reference declaring a code this build does not ship reads as
    a clean FileReadError (a ChunkyBitsError the CLI reports per
    file), never a crash — and resilver refuses identically."""
    import yaml

    from chunky_bits_tpu.errors import ChunkyBitsError, FileReadError
    from chunky_bits_tpu.file.file_reference import FileReference

    obj = yaml.safe_load(golden_text("pm_msr_placement"))
    for part in obj["parts"]:
        part["code"] = "lrc-12"  # a plausible FUTURE code name
    ref = FileReference.from_obj(obj)  # parsing itself must succeed
    assert all(part.code == "lrc-12" for part in ref.parts)

    async def read():
        return await ref.parts[0].read()

    with pytest.raises(FileReadError) as err:
        asyncio.run(read())
    assert "lrc-12" in str(err.value)
    assert isinstance(err.value, ChunkyBitsError)


def test_null_code_parses_as_rs():
    """An explicit ``code: null`` in a hand-edited/tool-round-tripped
    ref means unset, exactly like an absent key — it must parse as rs
    (and re-serialize without the key), never as the unreadable
    foreign code "None"."""
    import yaml

    from chunky_bits_tpu.file.file_reference import FileReference

    obj = yaml.safe_load(golden_text("pm_msr_placement"))
    for part in obj["parts"]:
        part["code"] = None
    ref = FileReference.from_obj(obj)
    assert all(part.code == "rs" for part in ref.parts)
    assert all("code" not in part for part in ref.to_obj()["parts"])


def test_foreign_code_disqualifies_sendfile_fast_path():
    """The gateway's ranged-GET zero-copy path serves raw chunk bytes,
    which is only sound for systematic shipped codes — a part carrying
    a foreign ``code:`` must fall through to the generic read (and its
    clean per-part error), never sendfile a guess."""
    import yaml

    from chunky_bits_tpu.file.file_reference import FileReference
    from chunky_bits_tpu.gateway.http import _covering_chunk

    obj = yaml.safe_load(golden_text("pm_msr_placement"))
    ref = FileReference.from_obj(obj)
    covered = _covering_chunk(ref, 0, 16)
    assert covered is not None  # pm-msr is systematic: qualifies
    assert covered[0] is ref.parts[0].data[0]

    for part in obj["parts"]:
        part["code"] = "lrc-12"
    foreign = FileReference.from_obj(obj)
    assert _covering_chunk(foreign, 0, 16) is None


def test_interop_decoder_ignores_code_key_on_rs_refs(tmp_path):
    """python/chunky-bits.py-style readers (concatenate data chunks,
    check sha256, truncate to length) must keep working on an rs ref
    even when a ``code: rs`` key is present — and, because pm-msr is
    systematic, on a pm-msr ref too."""
    import importlib.util
    import io

    import yaml

    from chunky_bits_tpu.file import FileWriteBuilder
    from chunky_bits_tpu.utils import aio

    spec = importlib.util.spec_from_file_location(
        "cb_interop", os.path.join(os.path.dirname(gen.GOLDEN_DIR),
                                   "..", "python", "chunky-bits.py"))
    interop = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(interop)

    payload = gen.payload(50_000, 9)
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        dirs = [f"d{i}" for i in range(5)]
        for name in dirs:
            os.mkdir(name)

        async def build(code):
            return await (FileWriteBuilder()
                          .with_chunk_size(1 << 12)
                          .with_data_chunks(3).with_parity_chunks(2)
                          .with_destination(list(dirs))
                          .with_code(code)
                          .write(aio.BytesReader(payload)))

        for code in ("rs", "pm-msr"):
            obj = asyncio.run(build(code)).to_obj()
            for part in obj["parts"]:
                # the rs ref never emits the key; inject it explicitly
                # to prove foreign readers skip unknown keys
                part["code"] = code
            ref_path = f"ref-{code}.yaml"
            with open(ref_path, "w") as f:
                yaml.safe_dump(obj, f, sort_keys=False)
            out = io.BytesIO()
            assert interop.decode(ref_path, out) == 0
            assert out.getvalue() == payload, code
    finally:
        os.chdir(cwd)


def test_old_reference_without_blocks_parses_and_roundtrips():
    """The compat direction: references written before the tunable
    (every other fixture) parse with ``blocks is None`` and serialize
    back WITHOUT the key — an old ref passing through this framework
    is byte-preserved, never upgraded in place."""
    import yaml

    from chunky_bits_tpu.file.file_reference import FileReference

    obj = yaml.safe_load(golden_text("void_small"))
    ref = FileReference.from_obj(obj)
    for part in ref.parts:
        for chunk in part.data + part.parity:
            assert chunk.blocks is None
    assert gen.dump(ref.to_obj()) == golden_text("void_small")


@pytest.mark.parametrize("backend", ["numpy", "native", "jax", "mesh"])
def test_wide_fixture_backend_byte_identity(backend):
    """Every erasure backend must reproduce the frozen d=10 p=4 reference
    exactly — parity hashes pin the matrix convention byte-for-byte."""
    from chunky_bits_tpu.file import FileWriteBuilder
    from chunky_bits_tpu.utils import aio

    if backend == "native":
        from chunky_bits_tpu.ops.backend import get_backend

        try:
            get_backend("native")
        except Exception as err:  # pragma: no cover - missing g++
            pytest.skip(f"native backend unavailable: {err}")

    async def build():
        return await (FileWriteBuilder()
                      .with_chunk_size(1 << 12)
                      .with_data_chunks(10).with_parity_chunks(4)
                      .with_backend(backend)
                      .with_batch_parts(2)
                      .write(aio.BytesReader(
                          gen.payload(3 * 10 * (1 << 12) + 777, 2))))

    ref = asyncio.run(build())
    assert gen.dump(ref.to_obj()) == golden_text("void_wide")


def test_wide_fixture_mesh_env_default_byte_identity():
    """$CHUNKY_BITS_TPU_BACKEND=mesh as the FLEET-WIDE default (the CI
    matrix leg's shape, no per-writer ``.with_backend()``) reproduces
    the frozen reference byte-for-byte — in a fresh interpreter so the
    env is read at first dispatch, exactly as a deployment would."""
    import subprocess
    import sys

    from chunky_bits_tpu.utils.virtualmesh import provision_virtual_mesh

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo,
               CHUNKY_BITS_TPU_BACKEND="mesh")
    provision_virtual_mesh(env, 8)
    script = (
        "import asyncio, sys\n"
        "from chunky_bits_tpu.file import FileWriteBuilder\n"
        "from chunky_bits_tpu.utils import aio\n"
        "from tests.golden import generate as gen\n"
        "ref = asyncio.run(FileWriteBuilder()\n"
        "    .with_chunk_size(1 << 12)\n"
        "    .with_data_chunks(10).with_parity_chunks(4)\n"
        "    .with_batch_parts(2)\n"
        "    .write(aio.BytesReader(\n"
        "        gen.payload(3 * 10 * (1 << 12) + 777, 2))))\n"
        "sys.stdout.write(gen.dump(ref.to_obj()))\n")
    r = subprocess.run([sys.executable, "-c", script], cwd=repo,
                       env=env, capture_output=True, timeout=300)
    assert r.returncode == 0, r.stderr.decode()[-800:]
    assert r.stdout.decode() == golden_text("void_wide")
