"""Golden file-reference conformance anchors.

Each fixture under tests/golden/ freezes bytes -> exact YAML: structure,
sha256 content addresses (so the GF(2^8) parity bytes are pinned through
their hashes), and for the cluster fixture the hash-seeded weighted
placement.  A kernel, layout, or serialization change that silently
breaks wire compatibility fails here; regenerate deliberately with
``python tests/golden/generate.py`` only for an intentional format
change.
"""

import asyncio
import os

import pytest

from tests.golden import generate as gen


def golden_text(name: str) -> str:
    with open(os.path.join(gen.GOLDEN_DIR, f"{name}.yaml")) as f:
        return f.read()


def test_fixtures_match_current_behavior():
    refs = asyncio.run(gen.build_refs())
    assert set(refs) == {"void_small", "void_wide", "cluster_placement",
                         "slab_placement", "block_digests"}
    for name, obj in refs.items():
        assert gen.dump(obj) == golden_text(name), (
            f"golden fixture {name} drifted — wire compatibility broken "
            "(or an intentional change: regenerate via "
            "tests/golden/generate.py and document it)")


def test_slab_fixture_mirrors_path_placement():
    """Fixture 4 differs from fixture 3 ONLY in the ``slab:`` location
    scheme: same content addresses, same hash-seeded node draw — the
    packed layout is a storage format, not a placement change."""
    import yaml

    plain = yaml.safe_load(golden_text("cluster_placement"))
    packed = yaml.safe_load(golden_text("slab_placement"))
    for p_part, s_part in zip(plain["parts"], packed["parts"]):
        for p_chunk, s_chunk in zip(p_part["data"] + p_part["parity"],
                                    s_part["data"] + s_part["parity"]):
            assert p_chunk["sha256"] == s_chunk["sha256"]
            assert [f"slab:{loc}" for loc in p_chunk["locations"]] \
                == s_chunk["locations"]


def test_block_digest_fixture_is_strictly_additive():
    """Fixture 5 differs from fixture 1 ONLY by the ``blocks`` trees:
    same content addresses, same structure — damage localization is
    metadata on top of the classic wire format, never a format fork."""
    import yaml

    plain = yaml.safe_load(golden_text("void_small"))
    treed = yaml.safe_load(golden_text("block_digests"))
    stripped = yaml.safe_load(golden_text("block_digests"))
    for part in stripped["parts"]:
        for chunk in part["data"] + part.get("parity", []):
            chunk.pop("blocks", None)
    assert stripped == plain, (
        "block_digests minus its trees must BE void_small")
    # and the trees themselves verify against the frozen chunk hashes:
    # tree blocks re-hash to the digests, digest count covers chunksize
    from chunky_bits_tpu.file.file_reference import FileReference

    ref = FileReference.from_obj(treed)
    for part in ref.parts:
        for chunk in part.data + part.parity:
            if part.chunksize <= 4096:
                assert chunk.blocks is None  # single-block: no tree
                continue
            assert chunk.blocks is not None
            assert chunk.blocks.size == 4096
            assert chunk.blocks.covers(part.chunksize)


def test_old_reference_without_blocks_parses_and_roundtrips():
    """The compat direction: references written before the tunable
    (every other fixture) parse with ``blocks is None`` and serialize
    back WITHOUT the key — an old ref passing through this framework
    is byte-preserved, never upgraded in place."""
    import yaml

    from chunky_bits_tpu.file.file_reference import FileReference

    obj = yaml.safe_load(golden_text("void_small"))
    ref = FileReference.from_obj(obj)
    for part in ref.parts:
        for chunk in part.data + part.parity:
            assert chunk.blocks is None
    assert gen.dump(ref.to_obj()) == golden_text("void_small")


@pytest.mark.parametrize("backend", ["numpy", "native", "jax"])
def test_wide_fixture_backend_byte_identity(backend):
    """Every erasure backend must reproduce the frozen d=10 p=4 reference
    exactly — parity hashes pin the matrix convention byte-for-byte."""
    from chunky_bits_tpu.file import FileWriteBuilder
    from chunky_bits_tpu.utils import aio

    if backend == "native":
        from chunky_bits_tpu.ops.backend import get_backend

        try:
            get_backend("native")
        except Exception as err:  # pragma: no cover - missing g++
            pytest.skip(f"native backend unavailable: {err}")

    async def build():
        return await (FileWriteBuilder()
                      .with_chunk_size(1 << 12)
                      .with_data_chunks(10).with_parity_chunks(4)
                      .with_backend(backend)
                      .with_batch_parts(2)
                      .write(aio.BytesReader(
                          gen.payload(3 * 10 * (1 << 12) + 777, 2))))

    ref = asyncio.run(build())
    assert gen.dump(ref.to_obj()) == golden_text("void_wide")
