"""External anchors for the Reed-Solomon matrix convention.

``ops/matrix.py`` claims byte-compatibility with the reference's
``reed-solomon-erasure`` crate (the Backblaze JavaReedSolomon
construction; reference src/file/file_part.rs:77, Cargo.toml:21).  Until
this module, every test of that claim was derived from ops/matrix.py
itself — a subtly wrong convention would have passed the whole suite.
Two independent anchors break the circularity:

1. **Published vectors.** The Backblaze "Erasure Coding" blog post and
   JavaReedSolomon README print the full 6x4 coding matrix for 4 data +
   2 parity shards; the QR-code standard (ISO/IEC 18004) publishes the
   GF(2^8) antilog table for polynomial 0x11D with generator 2 — the
   exact field the crate uses.  Both are transcribed here as literals.

2. **An independent implementation.** A from-scratch pure-Python
   construction of the same published recipe (Vandermonde V[r,c] = r^c,
   top-square inversion, systematic product) sharing *no* code with
   ops/matrix.py or ops/gf256.py: carry-less "Russian peasant"
   multiplication instead of log/exp tables, Fermat inversion (a^254)
   instead of table lookup, its own Gauss-Jordan over lists of ints.
   Equality is asserted across a (d, p) grid and for decode matrices.
"""

import numpy as np
import pytest

from chunky_bits_tpu.ops import matrix

# ---------------------------------------------------------------------------
# Anchor 1a: the published Backblaze 4+2 coding matrix (blog post
# "Backblaze Open-sources Reed-Solomon Erasure Coding Source Code",
# 2015; same matrix appears in the JavaReedSolomon sources).
# ---------------------------------------------------------------------------

BACKBLAZE_4_2 = [
    [1, 0, 0, 0],
    [0, 1, 0, 0],
    [0, 0, 1, 0],
    [0, 0, 0, 1],
    [27, 28, 18, 20],
    [28, 27, 20, 18],
]

# ---------------------------------------------------------------------------
# Anchor 1b: the QR-standard GF(2^8) antilog table prefix — powers of the
# generator 2 modulo 0x11D (ISO/IEC 18004; widely reprinted).  Pins both
# the reduction polynomial and the generator: the AES field (0x11B) or a
# generator-3 field diverges at index 8 and 1 respectively.
# ---------------------------------------------------------------------------

ANTILOG_0X11D_PREFIX = [1, 2, 4, 8, 16, 32, 64, 128,
                        29, 58, 116, 232, 205, 135, 19, 38]


def test_backblaze_published_matrix():
    got = matrix.build_encode_matrix(4, 2)
    assert got.tolist() == BACKBLAZE_4_2


def test_published_antilog_prefix():
    from chunky_bits_tpu.ops import gf256

    assert [gf256.gf_pow(2, i) for i in range(16)] == ANTILOG_0X11D_PREFIX
    # the generator has full order: 2^255 == 1, and no smaller
    # power-of-interest collapses (3, 5, 17 divide 255)
    assert gf256.gf_pow(2, 255) == 1
    assert all(gf256.gf_pow(2, 255 // f) != 1 for f in (3, 5, 17))


# ---------------------------------------------------------------------------
# Anchor 2: the independent implementation.  Everything below is
# deliberately self-contained — plain ints and lists, no numpy, no
# imports from chunky_bits_tpu.ops.
# ---------------------------------------------------------------------------


def _mul(a: int, b: int) -> int:
    """Carry-less multiply with on-the-fly 0x11D reduction."""
    prod = 0
    while b:
        if b & 1:
            prod ^= a
        b >>= 1
        a <<= 1
        if a & 0x100:
            a ^= 0x11D
    return prod


def _pow(base: int, exp: int) -> int:
    out = 1
    for _ in range(exp):
        out = _mul(out, base)
    return out  # 0^0 == 1, the Backblaze vandermonde convention


def _inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("no inverse of 0 in GF(2^8)")
    return _pow(a, 254)  # Fermat: a^(2^8 - 2)


def _mat_mul(a: list, b: list) -> list:
    rows, inner, cols = len(a), len(b), len(b[0])
    assert len(a[0]) == inner
    out = [[0] * cols for _ in range(rows)]
    for i in range(rows):
        for j in range(cols):
            acc = 0
            for k in range(inner):
                acc ^= _mul(a[i][k], b[k][j])
            out[i][j] = acc
    return out


def _mat_inv(m: list) -> list:
    n = len(m)
    work = [list(row) + [1 if i == j else 0 for j in range(n)]
            for i, row in enumerate(m)]
    for col in range(n):
        pivot = next((r for r in range(col, n) if work[r][col]), None)
        if pivot is None:
            raise ValueError("singular")
        work[col], work[pivot] = work[pivot], work[col]
        scale = _inv(work[col][col])
        work[col] = [_mul(scale, x) for x in work[col]]
        for r in range(n):
            if r != col and work[r][col]:
                f = work[r][col]
                work[r] = [x ^ _mul(f, y)
                           for x, y in zip(work[r], work[col])]
    return [row[n:] for row in work]


def _encode_matrix(d: int, p: int) -> list:
    vand = [[_pow(r, c) for c in range(d)] for r in range(d + p)]
    top_inv = _mat_inv([row[:d] for row in vand[:d]])
    return _mat_mul(vand, top_inv)


def test_independent_field_self_checks():
    """The independent arithmetic is itself sanity-anchored before being
    used as a judge: published antilog prefix, inverses, distributivity
    fuzz with a fixed seed."""
    assert [_pow(2, i) for i in range(16)] == ANTILOG_0X11D_PREFIX
    rng = np.random.default_rng(0)
    for _ in range(200):
        a, b, c = (int(x) for x in rng.integers(0, 256, 3))
        assert _mul(a, b) == _mul(b, a)
        assert _mul(a, b ^ c) == _mul(a, b) ^ _mul(a, c)
        if a:
            assert _mul(a, _inv(a)) == 1


@pytest.mark.parametrize("d", [1, 2, 3, 4, 5, 8, 10, 16, 20])
@pytest.mark.parametrize("p", [0, 1, 2, 4, 6])
def test_encode_matrix_matches_independent_impl(d, p):
    got = matrix.build_encode_matrix(d, p)
    want = _encode_matrix(d, p)
    assert got.tolist() == want
    # systematic: identity on top
    for i in range(d):
        assert want[i] == [1 if j == i else 0 for j in range(d)]


def test_decode_matrix_matches_independent_impl():
    """The reconstruction convention (invert the submatrix of the first d
    surviving rows, multiply by the wanted rows) re-derived
    independently."""
    d, p = 10, 4
    enc = matrix.build_encode_matrix(d, p)
    ind = _encode_matrix(d, p)
    present = [2, 3, 4, 5, 6, 7, 8, 9, 10, 12]  # 0, 1, 11, 13 lost
    wanted = [0, 1, 11, 13]
    got = matrix.decode_matrix(enc, present, wanted)
    sub_inv = _mat_inv([ind[i] for i in present[:d]])
    want = _mat_mul([ind[i] for i in wanted], sub_inv)
    assert got.tolist() == want


def test_independent_end_to_end_reconstruction():
    """Encode with the production coder, erase p shards, rebuild with
    ONLY the independent implementation — the strongest cross-check:
    production parity must be decodable by an outsider that shares no
    code with it."""
    from chunky_bits_tpu.ops.backend import ErasureCoder, NumpyBackend

    d, p, size = 5, 3, 64
    rng = np.random.default_rng(3)
    data = rng.integers(0, 256, (1, d, size), dtype=np.uint8)
    coder = ErasureCoder(d, p, NumpyBackend())
    parity = coder.encode_batch(data)
    full = [list(map(int, data[0, i])) for i in range(d)] + \
        [list(map(int, parity[0, i])) for i in range(p)]

    lost = [0, 2, 4]
    present = [i for i in range(d + p) if i not in lost]
    ind = _encode_matrix(d, p)
    sub_inv = _mat_inv([ind[i] for i in present[:d]])
    rows = _mat_mul([ind[i] for i in lost], sub_inv)
    for li, row in zip(lost, rows):
        rebuilt = [0] * size
        for coef, src in zip(row, (full[i] for i in present[:d])):
            for s in range(size):
                rebuilt[s] ^= _mul(coef, src[s])
        assert rebuilt == full[li], f"shard {li}"


def test_mds_property_sampled():
    """Any d of the d+p encode rows must be invertible (the MDS guarantee
    the crate's reconstruct relies on) — sampled subsets across
    geometries."""
    rng = np.random.default_rng(9)
    for d, p in [(3, 2), (4, 2), (10, 4), (20, 6)]:
        enc = matrix.build_encode_matrix(d, p).tolist()
        for _ in range(10):
            rows = sorted(rng.choice(d + p, size=d, replace=False).tolist())
            _mat_inv([enc[i] for i in rows])  # raises if singular
