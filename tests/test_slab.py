"""Packed slab chunk store (file/slab.py) + the ``slab:`` Location kind.

Pins the tentpole contracts: the Location surface is byte-identical to
path destinations across backends (the writer/reader/resilver/gateway
call sites change nothing), publication is journal-atomic (torn tails
never corrupt, crashed writers never publish), GC marks extents dead
and compaction reclaims them, and the gateway's zero-copy branch
streams in-slab extents via sendfile with the reassembly fallback on
corruption.
"""

import asyncio
import json
import os
import threading

import numpy as np
import pytest

from chunky_bits_tpu.cluster import Cluster
from chunky_bits_tpu.errors import LocationError
from chunky_bits_tpu.file import slab
from chunky_bits_tpu.file.location import Location, LocationContext, Range
from chunky_bits_tpu.file.weighted_location import WeightedLocation
from chunky_bits_tpu.utils import aio


def make_cluster_obj(root, packed=True, d=3, p=2, chunk_log2=12,
                     n_nodes=5, tunables=None, code=None):
    """``code`` pins the profile's erasure code in YAML (winning over
    the $CHUNKY_BITS_TPU_CODE env default the CI pm-msr matrix leg
    sets); None leaves the profile env-driven — tests that assert
    rs-specific byte accounting pass code="rs", generic behavioral
    tests stay unpinned so both codes exercise them."""
    dirs = []
    for i in range(n_nodes):
        path = os.path.join(str(root), f"disk{i}")
        os.makedirs(path, exist_ok=True)
        dirs.append(f"slab:{path}" if packed else path)
    meta = os.path.join(str(root), "meta")
    os.makedirs(meta, exist_ok=True)
    profile = {"data": d, "parity": p, "chunk_size": chunk_log2}
    if code is not None:
        profile["code"] = code
    obj = {
        "destinations": [{"location": x} for x in dirs],
        "metadata": {"type": "path", "format": "yaml", "path": meta},
        "profiles": {"default": profile},
    }
    if tunables:
        obj["tunables"] = tunables
    return obj


# ---- parsing / hierarchy ----

def test_parse_roundtrip_and_hierarchy(tmp_path):
    root = str(tmp_path / "store")
    loc = Location.parse(f"slab:{root}")
    assert loc.is_slab() and not loc.is_local() and not loc.is_http()
    assert str(loc) == f"slab:{root}"
    child = loc.child("sha256-ab")
    assert str(child) == f"slab:{root}/sha256-ab"
    assert child.is_child_of(loc) and loc.is_parent_of(child)
    assert Location.parse(str(child)) == child
    ranged = Location.parse(f"(5,10)slab:{root}/sha256-ab")
    assert ranged.range == Range(5, 10, False)
    assert ranged.target == f"{root}/sha256-ab"
    assert str(ranged) == f"(5,10)slab:{root}/sha256-ab"
    # weighted-location prefix composes
    wl = WeightedLocation.parse(f"750:slab:{root}")
    assert wl.weight == 750 and wl.location.is_slab()
    with pytest.raises(Exception):
        Location.parse("slab:")


def test_health_key_is_store_root(tmp_path):
    from chunky_bits_tpu.cluster.health import location_key

    child = Location.parse(f"slab:{tmp_path}/store/sha256-ab")
    assert location_key(child) == ("local", f"{tmp_path}/store")


# ---- store mechanics ----

def test_store_append_lookup_delete_reload(tmp_path):
    root = str(tmp_path / "s")
    store = slab.SlabStore(root)
    ext = store.append("sha256-aa", b"A" * 100)
    store.append("sha256-bb", b"B" * 50)
    assert ext.offset == 0 and ext.length == 100
    assert store.pread("sha256-aa") == b"A" * 100
    assert store.pread("sha256-bb", 10, 5) == b"B" * 5
    assert store.lookup("sha256-cc") is None
    # a second instance over the same root sees the journal
    other = slab.SlabStore(root)
    assert other.pread("sha256-bb") == b"B" * 50
    # delete marks dead; the other instance observes it on refresh
    store.mark_dead("sha256-aa")
    assert store.lookup("sha256-aa") is None
    assert store.dead_bytes() == 100
    assert other.lookup("sha256-aa") is None
    with pytest.raises(FileNotFoundError):
        store.mark_dead("sha256-aa")
    with pytest.raises(FileNotFoundError):
        store.pread("sha256-zz")


def test_supersede_marks_old_extent_dead(tmp_path):
    store = slab.SlabStore(str(tmp_path / "s"))
    store.append("sha256-aa", b"old-bytes!")
    store.append("sha256-aa", b"new")
    assert store.pread("sha256-aa") == b"new"
    assert store.dead_bytes() == 10


def test_torn_journal_tail_is_ignored_and_repaired(tmp_path):
    root = str(tmp_path / "s")
    store = slab.SlabStore(root)
    store.append("sha256-aa", b"AAAA")
    # simulate a crash mid-journal-append: a torn, newline-less tail
    with open(store.journal_path(), "ab") as f:
        f.write(b'{"o":"p","n":"sha256-torn","s":"sl')
    fresh = slab.SlabStore(root)
    assert fresh.live_names() == ["sha256-aa"]
    assert fresh.lookup("sha256-torn") is None
    # the next append terminates the fragment; nothing merges into it
    fresh.append("sha256-bb", b"BBBB")
    again = slab.SlabStore(root)
    assert sorted(again.live_names()) == ["sha256-aa", "sha256-bb"]
    assert again.pread("sha256-bb") == b"BBBB"


def test_unreferenced_slab_tail_is_invisible(tmp_path):
    """A crash between the slab append and the journal commit leaves
    tail bytes no journal line references: no reader ever sees them."""
    root = str(tmp_path / "s")
    store = slab.SlabStore(root)
    store.append("sha256-aa", b"AAAA")
    with open(store.slab_path("slab-000001.slab"), "ab") as f:
        f.write(b"CRASHED-WRITER-BYTES")
    fresh = slab.SlabStore(root)
    assert fresh.live_names() == ["sha256-aa"]
    assert fresh.pread("sha256-aa") == b"AAAA"
    # the next publication appends after the orphan bytes and reads back
    fresh.append("sha256-bb", b"BBBB")
    assert fresh.pread("sha256-bb") == b"BBBB"


def test_rollover_past_slab_max_bytes(tmp_path):
    store = slab.SlabStore(str(tmp_path / "s"), slab_max_bytes=100)
    for i in range(6):
        store.append(f"sha256-{i:02d}", bytes([i]) * 40)
    assert len(store.slab_files()) >= 2
    for i in range(6):
        assert store.pread(f"sha256-{i:02d}") == bytes([i]) * 40


def test_compact_reclaims_and_preserves(tmp_path):
    store = slab.SlabStore(str(tmp_path / "s"), slab_max_bytes=200)
    payloads = {f"sha256-{i:02d}": os.urandom(50) for i in range(8)}
    for name, data in payloads.items():
        store.append(name, data)
    # hold a zero-copy view across the compaction: the old inode must
    # stay readable for the view's lifetime (atomic-rename semantics)
    held = store.map_view("sha256-03")
    for name in ("sha256-00", "sha256-05"):
        store.mark_dead(name)
        del payloads[name]
    report = store.compact()
    assert report["reclaimed_bytes"] == 100
    assert report["live_chunks"] == len(payloads)
    for name, data in payloads.items():
        assert store.pread(name) == data
    assert store.dead_bytes() == 0
    assert bytes(held) == payloads["sha256-03"]
    # another instance reloads the swapped journal cleanly
    fresh = slab.SlabStore(str(tmp_path / "s"))
    assert sorted(fresh.live_names()) == sorted(payloads)


def test_concurrent_appends_from_two_instances(tmp_path):
    """Two store instances over one root (the cross-process shape in
    miniature): flock-serialized appends from concurrent threads all
    publish, and both indexes converge."""
    root = str(tmp_path / "s")
    a, b = slab.SlabStore(root), slab.SlabStore(root)
    errors = []

    def writer(store, prefix):
        try:
            for i in range(20):
                store.append(f"sha256-{prefix}{i:02d}",
                             f"{prefix}{i}".encode() * 10)
        except Exception as err:  # noqa: BLE001 — surfaced via errors
            errors.append(err)

    threads = [threading.Thread(target=writer, args=(a, "a"), daemon=True),
               threading.Thread(target=writer, args=(b, "b"), daemon=True)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert not errors, errors
    assert len(a.live_names()) == 40
    assert len(b.live_names()) == 40
    assert a.pread("sha256-b07") == b"b7" * 10


# ---- Location surface over the store ----

def test_location_verbs_roundtrip(tmp_path):
    loc = Location.parse(f"slab:{tmp_path}/store").child("sha256-xy")

    async def main():
        assert not await loc.file_exists()
        with pytest.raises(LocationError):
            await loc.file_len()
        with pytest.raises(LocationError):
            await loc.read()
        await loc.write(b"0123456789" * 10)
        assert await loc.file_exists()
        assert await loc.file_len() == 100
        assert await loc.read() == b"0123456789" * 10
        # ranged reads mirror local-file semantics
        assert await loc.with_range(Range(95)).read() == b"56789"
        assert await loc.with_range(Range(4, 3)).read() == b"456"
        assert await loc.with_range(Range(95, 10)).read() == b"56789"
        zext = await loc.with_range(Range(95, 10, True)).read()
        assert zext == b"56789" + b"\0" * 5
        # zero-copy view agrees
        view = await loc.read_view()
        assert bytes(view) == b"0123456789" * 10
        rview = await loc.with_range(Range(4, 3)).read_view()
        assert bytes(rview) == b"456"
        # streaming write path (write_from_reader)
        sibling = Location.parse(f"slab:{tmp_path}/store/sha256-zz")
        n = await sibling.write_from_reader(
            aio.BytesReader(b"stream-bytes"))
        assert n == 12
        assert await sibling.read() == b"stream-bytes"
        # IGNORE conflict: a second write of the same name is a no-op
        cx = LocationContext(on_conflict="ignore")
        await sibling.write(b"different", cx)
        assert await sibling.read() == b"stream-bytes"
        await sibling.delete()
        assert not await sibling.file_exists()
        with pytest.raises(LocationError):
            await sibling.delete()

    asyncio.run(main())


def test_write_shard_places_into_store(tmp_path):
    from chunky_bits_tpu.file.hashing import AnyHash

    root_loc = Location.parse(f"slab:{tmp_path}/store")

    async def main():
        data = b"shard-payload" * 9
        hash_ = AnyHash.from_buf(data)
        locations = await root_loc.write_shard(hash_, data)
        assert len(locations) == 1 and locations[0].is_slab()
        assert await locations[0].read() == data
        store = slab.get_store(f"{tmp_path}/store")
        assert store.live_names() == [str(hash_)]

    asyncio.run(main())


# ---- byte identity across backends / erasure ----

@pytest.mark.parametrize("backend", ["numpy", "native", "jax"])
def test_byte_identity_vs_path_destinations(tmp_path, backend):
    """Same payload through a slab cluster and a path cluster on each
    backend: reads match, and the content-addressed chunk digests are
    identical between layouts (the store changes placement, never
    bytes)."""
    if backend == "jax":
        pytest.importorskip("jax")
    payload = np.random.default_rng(5).integers(
        0, 256, 40000, dtype=np.uint8).tobytes()

    async def run(packed):
        cluster = Cluster.from_obj(make_cluster_obj(
            tmp_path / ("slab" if packed else "files"), packed=packed,
            tunables={"backend": backend}))
        await cluster.write_file("obj", aio.BytesReader(payload),
                                 cluster.get_profile())
        ref = await cluster.get_file_ref("obj")
        got = await cluster.file_read_builder(ref).read_all()
        assert got == payload
        return [str(c.hash) for part in ref.parts
                for c in part.data + part.parity]

    packed_hashes = asyncio.run(run(True))
    plain_hashes = asyncio.run(run(False))
    assert packed_hashes == plain_hashes


def test_reconstruct_from_erased_extents(tmp_path):
    payload = np.random.default_rng(6).integers(
        0, 256, 60000, dtype=np.uint8).tobytes()

    async def main():
        cluster = Cluster.from_obj(make_cluster_obj(tmp_path))
        await cluster.write_file("obj", aio.BytesReader(payload),
                                 cluster.get_profile())
        ref = await cluster.get_file_ref("obj")
        # erase p extents per part (the reconstructible maximum)
        for part in ref.parts:
            await part.data[0].locations[0].delete()
            await part.parity[0].locations[0].delete()
        got = await cluster.file_read_builder(ref).read_all()
        assert got == payload
        # resilver repairs in place; everything verifies Valid after
        report = await ref.resilver(
            cluster.get_destination(cluster.get_profile()))
        assert not report.failed_writes(), report.failed_writes()
        await cluster.write_file_ref("obj", ref)
        verify = await ref.verify(cluster.tunables.location_context())
        assert str(verify.integrity()) == "Valid"
        got = await cluster.file_read_builder(ref).read_all()
        assert got == payload

    asyncio.run(main())


def test_corrupt_extent_falls_through_to_replica_or_rebuild(tmp_path):
    payload = np.random.default_rng(7).integers(
        0, 256, 30000, dtype=np.uint8).tobytes()

    async def main():
        cluster = Cluster.from_obj(make_cluster_obj(tmp_path))
        await cluster.write_file("obj", aio.BytesReader(payload),
                                 cluster.get_profile())
        ref = await cluster.get_file_ref("obj")
        loc = ref.parts[0].data[1].locations[0]
        path, off, ln = loc.slab_extent()
        with open(path, "r+b") as f:
            f.seek(off + ln // 3)
            byte = f.read(1)
            f.seek(off + ln // 3)
            f.write(bytes([byte[0] ^ 0x40]))
        got = await cluster.file_read_builder(ref).read_all()
        assert got == payload

    asyncio.run(main())


# ---- gateway integration ----

def test_gateway_sendfile_over_slab_extents(tmp_path):
    """A Range inside one packed chunk streams via the zero-copy branch
    (access log source == "sendfile") with byte identity; a corrupted
    extent demotes to the reassembly fallback, still byte-identical."""
    from aiohttp import ClientSession
    from aiohttp.test_utils import TestServer

    from chunky_bits_tpu.gateway import make_app
    from chunky_bits_tpu.gateway.http import PROFILER_KEY

    payload = np.random.default_rng(8).integers(
        0, 256, 3 * 16384 + 777, dtype=np.uint8).tobytes()

    async def main():
        cluster = Cluster.from_obj(
            make_cluster_obj(tmp_path, chunk_log2=14))
        await cluster.write_file("obj", aio.BytesReader(payload),
                                 cluster.get_profile())
        app = make_app(cluster)
        server = TestServer(app)
        await server.start_server()
        profiler = app[PROFILER_KEY]
        try:
            async with ClientSession() as session:
                resp = await session.get(server.make_url("/obj"))
                assert await resp.read() == payload
                resp = await session.get(
                    server.make_url("/obj"),
                    headers={"Range": "bytes=128-2175"})
                assert resp.status == 206
                assert await resp.read() == payload[128:2176]
                # memoized second hit stays identical
                resp = await session.get(
                    server.make_url("/obj"),
                    headers={"Range": "bytes=200-300"})
                assert await resp.read() == payload[200:301]
                await asyncio.sleep(0.05)  # let access-log finallys run
                entries = profiler.drain_requests()
                sendfile = [e for e in entries
                            if e.source == "sendfile"]
                assert len(sendfile) >= 2, \
                    [(e.status, e.source) for e in entries]
                # corrupt a different chunk's extent: fallback path
                ref = await cluster.get_file_ref("obj")
                loc = ref.parts[0].data[2].locations[0]
                path, off, _ln = loc.slab_extent()
                with open(path, "r+b") as f:
                    f.seek(off + 11)
                    byte = f.read(1)
                    f.seek(off + 11)
                    f.write(bytes([byte[0] ^ 1]))
                start = 2 * 16384 + 10
                resp = await session.get(
                    server.make_url("/obj"),
                    headers={"Range": f"bytes={start}-{start + 99}"})
                assert resp.status == 206
                assert await resp.read() == payload[start:start + 100]
                await asyncio.sleep(0.05)
                entries = profiler.drain_requests()
                assert entries and entries[-1].source in ("store",
                                                          "cache")
        finally:
            await server.close()
            await cluster.tunables.location_context().aclose()

    asyncio.run(main())


# ---- GC over slab destinations ----

def test_find_unused_hashes_enumerates_index_and_marks_dead(tmp_path):
    import subprocess
    import sys

    import yaml

    REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    obj = make_cluster_obj(tmp_path)
    cluster_path = tmp_path / "cluster.yaml"
    cluster_path.write_text(yaml.safe_dump(obj))
    payload = os.urandom(20000)

    async def setup():
        cluster = Cluster.from_obj(obj)
        await cluster.write_file("keep", aio.BytesReader(payload),
                                 cluster.get_profile())
        await cluster.write_file("drop", aio.BytesReader(payload[:7000]),
                                 cluster.get_profile())
        # orphan drop's chunks: tombstone through the store surface when
        # it has one (the meta-log CI leg rebuilds plain path stores),
        # else unlink the per-name ref file of the path layout
        if hasattr(cluster.metadata, "delete"):
            await cluster.metadata.delete("drop")
        else:
            os.remove(os.path.join(str(tmp_path), "meta", "drop"))

    asyncio.run(setup())
    slab_dirs = [f"slab:{tmp_path}/disk{i}" for i in range(5)]
    env = dict(os.environ)
    env.setdefault("PYTHONPATH", REPO)
    result = subprocess.run(
        [sys.executable, "-m", "chunky_bits_tpu.cli",
         "find-unused-hashes", "--grace-seconds", "0", "-r",
         f"{cluster_path}#", "--", *slab_dirs],
        capture_output=True, env=env, cwd=REPO)
    assert result.returncode == 0, result.stderr.decode()
    collected = [ln for ln in result.stdout.decode().splitlines()
                 if ln.startswith("sha256-")]
    assert len(collected) == 5  # drop's d+p chunks
    dead = sum(slab.SlabStore(f"{tmp_path}/disk{i}").dead_bytes()
               for i in range(5))
    assert dead > 0

    async def check():
        cluster = Cluster.from_obj(obj)
        ref = await cluster.get_file_ref("keep")
        got = await cluster.file_read_builder(ref).read_all()
        assert got == payload
        # compaction reclaims the dead extents; keep still reads
        for i in range(5):
            slab.SlabStore(f"{tmp_path}/disk{i}").compact()
        got = await cluster.file_read_builder(ref).read_all()
        assert got == payload

    asyncio.run(check())


def test_gc_grace_window_spares_fresh_slab_chunks(tmp_path):
    import subprocess
    import sys

    import yaml

    REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    obj = make_cluster_obj(tmp_path)
    cluster_path = tmp_path / "cluster.yaml"
    cluster_path.write_text(yaml.safe_dump(obj))

    async def setup():
        cluster = Cluster.from_obj(obj)
        await cluster.write_file("orphan", aio.BytesReader(b"x" * 9000),
                                 cluster.get_profile())
        if hasattr(cluster.metadata, "delete"):
            await cluster.metadata.delete("orphan")
        else:
            os.remove(os.path.join(str(tmp_path), "meta", "orphan"))

    asyncio.run(setup())
    env = dict(os.environ)
    env.setdefault("PYTHONPATH", REPO)
    result = subprocess.run(
        [sys.executable, "-m", "chunky_bits_tpu.cli",
         "find-unused-hashes", "--grace-seconds", "3600", "-r",
         f"{cluster_path}#", "--",
         *[f"slab:{tmp_path}/disk{i}" for i in range(5)]],
        capture_output=True, env=env, cwd=REPO)
    assert result.returncode == 0, result.stderr.decode()
    # everything is inside the grace window: nothing collected
    assert not [ln for ln in result.stdout.decode().splitlines()
                if ln.startswith("sha256-")]
    assert all(slab.SlabStore(f"{tmp_path}/disk{i}").dead_bytes() == 0
               for i in range(5))
