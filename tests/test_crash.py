"""The crash-consistency harness (ISSUE 14): the filesystem seam, the
deterministic disk-fault injector, and the recovery verifier.

Four layers, mirroring the harness's own structure:

* seam mechanics — passthrough identity, recording op streams, the
  replayer's failure-model semantics (what survives a kill, a torn
  write, a power cut with and without directory fsync);
* the crash matrix — EVERY enumerated crash point of every
  storage-plane mutation (slab append/mark-dead/compact, chunk and
  metadata publication, the repair planner's rewrite shape) recovers
  invariant-clean, deterministically (same seed ⇒ same digest);
* scripted live faults — ENOSPC short writes truncate the slab tail
  (offset accounting never drifts), a failing fsync ABORTS compaction
  and metadata publication (never swallowed), stale publication temps
  are reaped by the next metadata write and by the GC walk;
* cluster recovery — crash images of one destination (including the
  journal-line-without-slab-bytes power-cut image slab.py documents)
  converge to Valid under ``scrub --once``.

Everything here is CPU-only and loop-local; the sanitize leg must stay
green (asyncio.run per case, no leaked tasks).
"""

from __future__ import annotations

import asyncio
import errno
import hashlib
import os
import subprocess
import sys
import time

import pytest

from chunky_bits_tpu.file.slab import SlabStore, SlabStoreError
from chunky_bits_tpu.sim import crash
from chunky_bits_tpu.utils import fsio

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _restore_provider():
    """Every test leaves the passthrough provider installed, whatever
    it broke."""
    yield
    fsio.install(None)


# ---- seam mechanics ----

def test_passthrough_provider_is_default_and_restores(tmp_path):
    assert fsio.active() is fsio.system_provider()
    recorder = fsio.RecordingFsProvider(str(tmp_path))
    previous = fsio.install(recorder)
    assert previous is fsio.system_provider()
    assert fsio.active() is recorder
    fsio.install(None)
    assert fsio.active() is fsio.system_provider()


def test_passthrough_open_is_the_builtin_file(tmp_path):
    # no wrapper on the hot path: production writes must cost one call
    path = str(tmp_path / "x")
    with fsio.open(path, "wb") as f:
        f.write(b"abc")
    with open(path, "rb") as f:
        assert f.read() == b"abc"
    import io

    with fsio.open(path, "ab") as f:
        assert isinstance(f, io.BufferedWriter)


def test_recording_captures_op_stream_and_scopes_to_root(tmp_path):
    root = tmp_path / "in"
    outside = tmp_path / "out"
    root.mkdir()
    outside.mkdir()
    recorder = fsio.RecordingFsProvider(str(root))
    fsio.install(recorder)
    try:
        with fsio.open(str(root / "a"), "wb") as f:
            f.write(b"payload")
            fsio.fsync(f)
        fsio.replace(str(root / "a"), str(root / "b"))
        fsio.fsync_dir(str(root))
        with fsio.open(str(outside / "c"), "wb") as f:
            f.write(b"elsewhere")
        with fsio.open(str(root / "b"), "rb") as f:
            assert f.read() == b"payload"  # reads not recorded
    finally:
        fsio.install(None)
    kinds = [(op.op, op.path) for op in recorder.ops]
    assert kinds == [
        ("open", "a"), ("write", "a"), ("flush", "a"), ("fsync", "a"),
        ("close", "a"), ("replace", "b"), ("fsync_dir", "."),
    ]
    assert recorder.ops[1].data == b"payload"
    assert recorder.ops[5].aux == "a"  # replace src


def _record_simple(root, body):
    os.makedirs(root, exist_ok=True)
    return crash.record_mutation(str(root), body)


def test_replayer_powercut_drops_unsynced_writes(tmp_path):
    root = tmp_path / "r"
    snap = tmp_path / "snap"
    root.mkdir()
    (root / "old").write_bytes(b"durable")
    import shutil

    shutil.copytree(root, snap)

    def body():
        with fsio.open(str(root / "new"), "wb") as f:
            f.write(b"unsynced")
            f.flush()

    ops = _record_simple(root, body)
    rep = crash.OpReplayer(str(snap))
    img = tmp_path / "img"
    # flush model: everything recorded survives
    rep.build(ops, len(ops), "flush", str(img))
    assert (img / "new").read_bytes() == b"unsynced"
    assert (img / "old").read_bytes() == b"durable"
    # powercut, keep-nothing mask: the dirent survives, the data died
    shutil.rmtree(img)
    rep.build(ops, len(ops), "powercut", str(img))
    assert (img / "new").read_bytes() == b""
    assert (img / "old").read_bytes() == b"durable"
    # powercut-meta with no fsync_dir anywhere: the file never existed
    shutil.rmtree(img)
    rep.build(ops, len(ops), "powercut-meta", str(img))
    assert not (img / "new").exists()


def test_replayer_fsync_and_dir_fsync_make_publication_durable(tmp_path):
    root = tmp_path / "r"
    snap = tmp_path / "snap"
    root.mkdir()
    (root / "t").write_bytes(b"old")
    import shutil

    shutil.copytree(root, snap)

    def body():
        with fsio.open(str(root / "t.tmp.1.00000000"), "wb") as f:
            f.write(b"new")
            fsio.fsync(f)
        fsio.replace(str(root / "t.tmp.1.00000000"), str(root / "t"))
        fsio.fsync_dir(str(root))

    ops = _record_simple(root, body)
    rep = crash.OpReplayer(str(snap))
    img = tmp_path / "img"
    # the full protocol survives the harshest model
    rep.build(ops, len(ops), "powercut-meta", str(img))
    assert (img / "t").read_bytes() == b"new"
    # crash BEFORE the dir fsync: the rename may be lost — old wins,
    # and the orphaned temp holds the fsync'd bytes
    shutil.rmtree(img)
    rep.build(ops, len(ops) - 1, "powercut-meta", str(img))
    assert (img / "t").read_bytes() == b"old"


def test_replayer_torn_write_cuts_final_write(tmp_path):
    root = tmp_path / "r"
    snap = tmp_path / "snap"
    root.mkdir()
    import shutil

    shutil.copytree(root, snap)

    def body():
        with fsio.open(str(root / "j"), "ab") as f:
            f.write(b"0123456789")
            f.flush()

    ops = _record_simple(root, body)
    write_k = next(i for i, op in enumerate(ops) if op.op == "write")
    rep = crash.OpReplayer(str(snap))
    img = tmp_path / "img"
    rep.build(ops, write_k + 1, "torn", str(img), torn=4)
    assert (img / "j").read_bytes() == b"0123"


# ---- the crash matrix: every point recovers, deterministically ----

@pytest.mark.parametrize("mutation", sorted(crash.MUTATIONS))
def test_crash_matrix_mutation_recovers_clean(tmp_path, mutation):
    result = crash.run_matrix(str(tmp_path), seed=0,
                              mutations=[mutation])
    assert result.verdicts, "no crash images enumerated"
    failed = result.failed()
    assert not failed, [v.to_obj() for v in failed[:5]]
    # the enumeration is real: multiple crash points and multiple
    # failure models per mutation
    assert result.ops_by_mutation[mutation] >= 3
    modes = {v.mode for v in result.verdicts}
    assert {"kill", "flush", "powercut", "powercut-meta"} <= modes


def test_crash_matrix_is_deterministic(tmp_path):
    picks = ["slab_append", "metadata_publish"]
    first = crash.run_matrix(str(tmp_path / "a"), seed=7,
                             mutations=picks)
    second = crash.run_matrix(str(tmp_path / "b"), seed=7,
                              mutations=picks)
    assert first.digest == second.digest
    assert [v.to_obj() for v in first.verdicts] \
        == [v.to_obj() for v in second.verdicts]


def test_crash_matrix_catches_a_dropped_dir_fsync(tmp_path, monkeypatch):
    """The harness is not vacuous: neuter the directory-fsync barrier
    (the satellite fix) and the completed-publication power-cut images
    MUST go red."""
    monkeypatch.setattr(fsio.FsProvider, "fsync_dir",
                        lambda self, path: None)
    monkeypatch.setattr(fsio.RecordingFsProvider, "fsync_dir",
                        lambda self, path: None, raising=False)
    result = crash.run_matrix(str(tmp_path), seed=0,
                              mutations=["metadata_publish"])
    failed = result.failed()
    assert failed, "neutered fsync_dir went undetected"
    assert any(v.mode == "powercut-meta" and "acknowledged" in
               " ".join(v.violations) for v in failed)


# ---- scripted live faults (the FaultyFsProvider satellite pins) ----

def _fresh_slab_with_chunks(root, n=2):
    store = SlabStore(str(root))
    expected = {}
    for i in range(n):
        payload = bytes([i]) * (300 + i)
        name = hashlib.sha256(payload).hexdigest()
        store.append(name, payload)
        expected[name] = payload
    return store, expected


def test_enospc_short_write_truncates_partial_tail(tmp_path):
    store, expected = _fresh_slab_with_chunks(tmp_path / "s")
    slab_file = os.path.join(store.root, store.slab_files()[-1])
    size_before = os.path.getsize(slab_file)
    fsio.install(fsio.FaultyFsProvider(
        "write", path_suffix=".slab", errno_code=errno.ENOSPC,
        short_bytes=17))
    try:
        with pytest.raises(OSError):
            store.append("a" * 64, b"x" * 4096)
    finally:
        fsio.install(None)
    # the partial 17-byte tail is truncated away: offsets never drift
    assert os.path.getsize(slab_file) == size_before
    # nothing journaled, store fully serviceable; the next append
    # lands exactly at the old EOF
    fresh = SlabStore(store.root)
    assert sorted(fresh.live_names()) == sorted(expected)
    payload = b"after-enospc"
    name = hashlib.sha256(payload).hexdigest()
    ext = fresh.append(name, payload)
    assert ext.offset == size_before
    assert fresh.pread(name) == payload
    for k, v in expected.items():
        assert fresh.pread(k) == v


def test_failed_fsync_aborts_compaction(tmp_path):
    store, expected = _fresh_slab_with_chunks(tmp_path / "s", n=3)
    store.mark_dead(sorted(expected)[0])
    with open(store.journal_path(), "rb") as f:
        journal_before = f.read()
    fsio.install(fsio.FaultyFsProvider("fsync"))
    try:
        with pytest.raises((OSError, SlabStoreError)):
            store.compact()
    finally:
        fsio.install(None)
    # the swap never happened: old journal authoritative, live chunks
    # all served, the dead extent still awaiting reclaim
    with open(store.journal_path(), "rb") as f:
        assert f.read() == journal_before
    fresh = SlabStore(store.root)
    for k in sorted(expected)[1:]:
        assert fresh.pread(k) == expected[k]
    assert fresh.dead_bytes() > 0
    # and with the fault gone, the same compaction succeeds
    fresh.compact()
    again = SlabStore(store.root)
    assert again.dead_bytes() == 0
    for k in sorted(expected)[1:]:
        assert again.pread(k) == expected[k]


def test_failed_dir_fsync_aborts_compaction_state_flip(tmp_path):
    store, expected = _fresh_slab_with_chunks(tmp_path / "s", n=3)
    store.mark_dead(sorted(expected)[0])
    fsio.install(fsio.FaultyFsProvider("fsync_dir"))
    try:
        with pytest.raises(OSError):
            store.compact()
    finally:
        fsio.install(None)
    # the rename may or may not be on disk — either way the cold
    # restart reads a complete journal and serves every live chunk
    fresh = SlabStore(store.root)
    for k in sorted(expected)[1:]:
        assert fresh.pread(k) == expected[k]


def test_failed_fsync_aborts_metadata_publication(tmp_path):
    from chunky_bits_tpu.cluster.metadata import MetadataPath
    from chunky_bits_tpu.errors import MetadataReadError

    meta = MetadataPath(str(tmp_path))
    asyncio.run(meta.write("obj", {"v": 1}))
    fsio.install(fsio.FaultyFsProvider("fsync"))
    try:
        with pytest.raises(MetadataReadError):
            asyncio.run(meta.write("obj", {"v": 2}))
    finally:
        fsio.install(None)
    # never swallowed-and-published: the old reference survives and
    # the staging temp was reaped on the error path
    assert asyncio.run(meta.read("obj")) == {"v": 1}
    from chunky_bits_tpu.file.location import is_publish_temp

    assert not [f for f in os.listdir(tmp_path) if is_publish_temp(f)]


def test_metadata_write_reaps_stale_temps_only(tmp_path):
    from chunky_bits_tpu.cluster.metadata import (
        STALE_TEMP_SECONDS,
        MetadataPath,
    )
    from chunky_bits_tpu.file.location import publish_temp_name

    asyncio.run(MetadataPath(str(tmp_path)).write("obj", {"v": 1}))
    stale = publish_temp_name(str(tmp_path / "obj"))
    fresh = publish_temp_name(str(tmp_path / "obj"))
    for path in (stale, fresh):
        with open(path, "w") as f:
            f.write("{}")
    old = time.time() - STALE_TEMP_SECONDS - 10
    os.utime(stale, (old, old))
    # the reap runs once per MetadataPath instance (per-write scans
    # would be O(dir) each — quadratic over a namespace); "next
    # write" means the next writer PROCESS, modeled by a new instance
    meta = MetadataPath(str(tmp_path))
    asyncio.run(meta.write("obj", {"v": 2}))
    # the crashed writer's leak is gone; the (possibly live) young
    # temp survives; the write itself landed
    assert not os.path.exists(stale)
    assert os.path.exists(fresh)
    assert asyncio.run(meta.read("obj")) == {"v": 2}
    # the same instance does not rescan: a later stale temp waits for
    # the next instance (amortized-cost contract)
    late = publish_temp_name(str(tmp_path / "obj"))
    with open(late, "w") as f:
        f.write("{}")
    os.utime(late, (old, old))
    asyncio.run(meta.write("obj", {"v": 3}))
    assert os.path.exists(late)
    asyncio.run(MetadataPath(str(tmp_path)).write("obj", {"v": 4}))
    assert not os.path.exists(late)


def test_gc_walk_reaps_stale_publish_temp(tmp_path):
    """The GC half of the stale-temp story: find-unused-hashes removes
    an aged publication temp from a hash dir (a writer killed between
    temp write and rename has no other reclamation path)."""
    import yaml

    disk = tmp_path / "disk0"
    disk.mkdir()
    (tmp_path / "metadata").mkdir()
    config = tmp_path / "cluster.yaml"
    config.write_text(yaml.safe_dump({
        "destinations": [{"location": str(disk)}],
        "metadata": {"type": "path", "format": "yaml",
                     "path": str(tmp_path / "metadata")},
        "profiles": {"default": {"data": 1, "parity": 1,
                                 "chunk_size": 12}},
    }))
    from chunky_bits_tpu.file.location import publish_temp_name

    temp = publish_temp_name(str(disk / ("sha256-" + "a" * 64)))
    with open(temp, "wb") as f:
        f.write(b"half-published")
    old = time.time() - 3600
    os.utime(temp, (old, old))
    r = subprocess.run(
        [sys.executable, "-m", "chunky_bits_tpu.cli",
         "find-unused-hashes", "--remove", f"{config}#.",
         "--", str(disk)],
        env=dict(os.environ, PYTHONPATH=REPO), cwd=REPO,
        capture_output=True, timeout=120)
    assert r.returncode == 0, r.stderr.decode()[-500:]
    assert not os.path.exists(temp)
    assert b"Stale publish temp" in r.stderr


# ---- cluster recovery: crash image + scrub --once -> Valid ----

def test_scrub_once_converges_powercut_images(tmp_path):
    """The issue's named case end to end: the journal line survives
    the power cut, the slab bytes do not — scrub --once must detect
    the damage through the content-address gate and repair the node
    in place to a Valid namespace."""
    verdicts = crash.run_cluster_recovery(str(tmp_path / "w"), seed=0,
                                          points="smoke")
    assert verdicts, "no cluster crash images"
    # the smoke selection enumerates every writeback mask of the
    # completed ingest — including journal-without-bytes
    assert len(verdicts) >= 2
    failed = [v for v in verdicts if not v.ok]
    assert not failed, [v.to_obj() for v in failed]


# ---- sim fabric disk faults ----

def test_sim_node_torn_write_budget(tmp_path):
    from chunky_bits_tpu.sim.fabric import LatencyModel, SimFabric

    fabric = SimFabric("crashtest", 1, zones=("z",), seed=0,
                       latency=LatencyModel(median_ms=0.01))
    try:
        node = fabric.nodes["n0000"]
        node.faults.torn_put_bytes = 3
        node.faults.torn_put_remaining = 1

        async def drive():
            # a payload no longer than the torn prefix cannot tear and
            # must NOT burn the one-shot budget
            await node.write("tiny", b"ab")
            assert node.faults.torn_put_remaining == 1
            await node.write("c", b"0123456789")
            first = bytes(node.store["c"])
            await node.write("c", b"0123456789")
            return first, bytes(node.store["c"])

        torn, healed = asyncio.run(drive())
        assert torn == b"012"  # acked but torn
        assert healed == b"0123456789"  # budget spent: whole write
        assert node.torn_writes == 1
        assert node.stats()["torn_writes"] == 1
    finally:
        fabric.close()


@pytest.mark.slow
def test_disk_corruption_storm_scenario(tmp_path):
    """The scenario joins the PR-12 library: run it at unit scale (the
    bench --config 14 full suite re-proves it at N=100)."""
    from chunky_bits_tpu.sim.scenario import fresh_workdir, run_scenario

    result = run_scenario("disk_corruption_storm", nodes=12, seed=0,
                          workdir=fresh_workdir(str(tmp_path / "w")),
                          objects=6)
    assert result.ok(), result.to_obj()["verdicts"]
    assert result.verdicts["torn_writes_ridden_out"]
    assert result.verdicts["corruption_detected"]
