"""Multi-process distributed e2e: real node processes, real crashes.

The in-process FakeHttpNode suites prove HTTP semantics; this file
proves the failure-domain story the reference only describes in its
README topology guidance: storage nodes as SEPARATE OS processes, a
node death as SIGKILL (TCP resets, not in-process cancellation),
degraded reads over the surviving sockets, and resilver restoring full
redundancy onto the remaining nodes.  7 nodes for a 3+2 profile so a
crash leaves shard-free survivors eligible to take the rebuilt shards
(placement excludes nodes already holding a sibling,
destination.rs:85-94).
"""

import asyncio
import os
import signal
import sys

import numpy as np

from chunky_bits_tpu.cluster.cluster import Cluster
from chunky_bits_tpu.file.file_part import FileIntegrity
from chunky_bits_tpu.utils import aio

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


async def _spawn_node():
    proc = await asyncio.create_subprocess_exec(
        sys.executable, os.path.join(REPO, "tests", "node_server.py"),
        REPO, stdout=asyncio.subprocess.PIPE)
    try:
        line = await asyncio.wait_for(proc.stdout.readline(), 30)
        assert line.startswith(b"PORT "), line
    except BaseException:
        proc.kill()
        await proc.wait()
        raise
    return proc, int(line.split()[1])


def test_node_process_crash_degraded_read_and_resilver(tmp_path):
    payload = np.random.default_rng(77).bytes(150_000)

    async def run() -> None:
        nodes = []
        try:
            for _ in range(7):
                nodes.append(await _spawn_node())
            (tmp_path / "metadata").mkdir()
            cluster = Cluster.from_obj({
                "destinations": [
                    {"location": f"http://127.0.0.1:{port}/"}
                    for _, port in nodes],
                "metadata": {"type": "path", "format": "yaml",
                             "path": str(tmp_path / "metadata")},
                "profiles": {"default": {"data": 3, "parity": 2,
                                         "chunk_size": 12}},
            })
            await cluster.write_file(
                "obj", aio.BytesReader(payload),
                cluster.get_profile(None))

            async def read_back() -> bytes:
                reader = await cluster.read_file("obj")
                out = []
                while True:
                    piece = await reader.read(1 << 16)
                    if not piece:
                        break
                    out.append(piece)
                return b"".join(out)

            assert await read_back() == payload

            # a real node crash: SIGKILL the process holding the first
            # shard of the first part
            first_loc = str(
                (await cluster.get_file_ref("obj")).parts[0]
                .data[0].locations[0])
            victim_port = int(first_loc.split(":")[2].split("/")[0])
            victim = next(pr for pr, port in nodes if port == victim_port)
            victim.send_signal(signal.SIGKILL)
            await victim.wait()

            # degraded read over the surviving sockets (TCP refused on
            # the dead node, reconstruction from the survivors)
            assert await read_back() == payload

            ref = await cluster.get_file_ref("obj")
            vrep = await ref.verify()
            assert vrep.integrity() == FileIntegrity.DEGRADED

            # resilver must place rebuilt shards on shard-free
            # survivors, and the persisted ref must verify Valid
            rrep = await ref.resilver(
                cluster.get_destination(cluster.get_profile(None)))
            assert rrep.new_locations(), "resilver placed nothing"
            assert all(f"127.0.0.1:{victim_port}" not in str(loc)
                       for loc in rrep.new_locations())
            await cluster.write_file_ref("obj", ref)
            ref2 = await cluster.get_file_ref("obj")
            assert (await ref2.verify()).integrity() == FileIntegrity.VALID
            assert await read_back() == payload
        finally:
            for proc, _ in nodes:
                if proc.returncode is None:
                    proc.kill()
                    await proc.wait()

    asyncio.run(run())
