"""Standalone HTTP storage-node process for the multi-process e2e.

Runs tests/http_node.py's FakeHttpNode in its own interpreter: child
processes give the distributed tests real failure domains — a SIGKILL
here is an actual node crash with TCP resets, not an in-process
cancellation.  Prints "PORT <n>" on stdout once listening, then serves
until killed.
"""

import asyncio
import sys


async def main() -> None:
    sys.path.insert(0, sys.argv[1])  # repo root (child has no conftest)
    from tests.http_node import FakeHttpNode

    node = FakeHttpNode()
    await node.start()
    print(f"PORT {node.port}", flush=True)
    await asyncio.Event().wait()  # serve until killed


if __name__ == "__main__":
    asyncio.run(main())
