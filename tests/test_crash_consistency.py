"""Crash consistency: a cp killed mid-write leaves no torn state.

The write protocol publishes metadata only after every shard of every
part has landed (writer.py ordered assembly; the reference has the same
order but no test for it).  So a SIGKILL mid-ingest must leave:
no metadata entry (readers see a clean not-found, never a torn object),
orphaned staged chunks that find-unused-hashes reclaims after the grace
window, and a clean retry of the same name succeeding.
"""

import hashlib
import os
import signal
import subprocess
import sys
import time

import pytest
import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def cluster(tmp_path):
    disks = []
    for i in range(5):
        d = tmp_path / f"disk{i}"
        d.mkdir()
        disks.append(str(d))
    (tmp_path / "metadata").mkdir()
    path = tmp_path / "cluster.yaml"
    path.write_text(yaml.safe_dump({
        "destinations": [{"location": d} for d in disks],
        "metadata": {"type": "path", "format": "yaml",
                     "path": str(tmp_path / "metadata")},
        # small chunks => many parts => a wide kill window
        "profiles": {"default": {"data": 3, "parity": 2,
                                 "chunk_size": 12}},
    }))
    return path, disks


def _chunks_on_disk(disks):
    return [os.path.join(d, f) for d in disks for f in os.listdir(d)]


def test_sigkill_mid_cp_leaves_no_torn_state(cluster, tmp_path):
    yaml_path, disks = cluster
    src = tmp_path / "input.bin"
    src.write_bytes(os.urandom(8 << 20))
    env = dict(os.environ, PYTHONPATH=REPO)

    proc = subprocess.Popen(
        [sys.executable, "-m", "chunky_bits_tpu.cli", "cp",
         str(src), f"{yaml_path}#obj"], env=env, cwd=REPO,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    # kill as soon as the first chunk lands (mid-ingest, pre-publish)
    deadline = time.time() + 60
    while time.time() < deadline:
        if _chunks_on_disk(disks):
            break
        if proc.poll() is not None:
            pytest.fail("cp finished before any chunk landed")
        time.sleep(0.002)
    else:
        pytest.fail("no chunk ever landed")
    proc.send_signal(signal.SIGKILL)
    proc.wait()

    # 1. no metadata entry: readers get clean not-found, never torn data
    assert not (tmp_path / "metadata" / "obj").exists()
    cat = subprocess.run(
        [sys.executable, "-m", "chunky_bits_tpu.cli", "cat",
         f"{yaml_path}#obj"], env=env, cwd=REPO, capture_output=True)
    assert cat.returncode != 0
    assert cat.stdout == b""

    # 2. the orphaned staged chunks are reclaimable once aged past the
    # grace window (simulated by aging the files)
    orphans = _chunks_on_disk(disks)
    assert orphans, "kill landed after cleanup?"
    old = time.time() - 3600
    for p in orphans:
        os.utime(p, (old, old))
    gc = subprocess.run(
        [sys.executable, "-m", "chunky_bits_tpu.cli",
         "find-unused-hashes", "--remove", f"{yaml_path}#.",
         "--", *disks], env=env, cwd=REPO, capture_output=True)
    assert gc.returncode == 0, gc.stderr
    assert not _chunks_on_disk(disks)

    # 3. a clean retry of the same name succeeds end to end
    cp2 = subprocess.run(
        [sys.executable, "-m", "chunky_bits_tpu.cli", "cp",
         str(src), f"{yaml_path}#obj"], env=env, cwd=REPO,
        capture_output=True)
    assert cp2.returncode == 0, cp2.stderr
    cat2 = subprocess.run(
        [sys.executable, "-m", "chunky_bits_tpu.cli", "cat",
         f"{yaml_path}#obj"], env=env, cwd=REPO, capture_output=True)
    assert cat2.returncode == 0
    assert hashlib.sha256(cat2.stdout).hexdigest() == \
        hashlib.sha256(src.read_bytes()).hexdigest()
