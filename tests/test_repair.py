"""Targeted repair planner (cluster/repair.py) + block-digest trees
(file/chunk.py BlockDigests).

Pins the PR's acceptance criteria: damage localizes to block ranges and
repairs move ≈damage bytes instead of d whole chunks (exact helper-byte
counts asserted); repaired replicas are byte-identical across
numpy/native/jax backends and against a whole-part rebuild oracle;
references without trees still parse, verify and repair exactly as
before; and every byte of repair I/O — victim re-reads, helper range
reads, repair writes — is observable in the scrub token bucket and the
``cb_repair_*`` counters (no unmetered helper reads).
"""

import asyncio
import os

import numpy as np
import pytest

from chunky_bits_tpu.cluster import Cluster
from chunky_bits_tpu.cluster.repair import RepairPlanner, merge_ranges
from chunky_bits_tpu.cluster.scrub import ScrubDaemon
from chunky_bits_tpu.file.chunk import BlockDigests
from chunky_bits_tpu.file.location import Location
from chunky_bits_tpu.utils import aio
from tests.test_slab import make_cluster_obj


def write_payload(cluster, name, nbytes, seed=0):
    payload = np.random.default_rng(seed).integers(
        0, 256, nbytes, dtype=np.uint8).tobytes()

    async def run():
        await cluster.write_file(name, aio.BytesReader(payload),
                                 cluster.get_profile())

    asyncio.run(run())
    return payload


def flip_byte(location, offset):
    """One-byte corruption at a chunk offset, path or slab replica."""
    if location.is_slab():
        path, base, length = location.slab_extent()
        pos = base + min(offset, length - 1)
    else:
        path = location.target
        pos = offset
    with open(path, "r+b") as f:
        f.seek(pos)
        byte = f.read(1)
        f.seek(pos)
        f.write(bytes([byte[0] ^ 0xFF]))


def meter_bucket(daemon):
    """Spy on the daemon's token bucket: every take() the pass makes
    (verification AND planner repair I/O share the one bucket) lands in
    the returned list."""
    taken = []
    orig = daemon._bucket.take

    async def spy(nbytes):
        taken.append(nbytes)
        await orig(nbytes)

    daemon._bucket.take = spy
    return taken


# ---- BlockDigests unit behavior ----

def test_block_digests_localize_and_verify():
    data = bytearray(np.random.default_rng(0).integers(
        0, 256, 10_000, dtype=np.uint8).tobytes())
    bd = BlockDigests.from_buf(data, 4096)
    assert len(bd.digests) == 3 and bd.covers(10_000)
    assert bd.damaged_ranges(data) == []
    data[5000] ^= 1
    assert bd.damaged_ranges(data) == [(4096, 4096)]
    data[5000] ^= 1  # restore; damage blocks 0 and 2 (non-adjacent)
    data[0] ^= 1
    data[9000] ^= 1
    assert bd.damaged_ranges(data) == [(0, 4096), (8192, 1808)]
    data[5000] ^= 1  # all three damaged: adjacent ranges merge
    assert bd.damaged_ranges(data) == [(0, 10_000)]
    data[0] ^= 1
    data[9000] ^= 1
    # truncated/grown replicas cannot localize
    assert bd.damaged_ranges(data[:5]) is None
    assert bd.damaged_ranges(data + b"x" * 5000) is None
    # range verification: aligned whole blocks judged, others abstain
    assert bd.verify_range(bytes(data[4096:8192]), 4096) is False
    data[5000] ^= 1  # restore block 1: data fully intact again
    assert bd.verify_range(bytes(data[4096:8192]), 4096) is True
    assert bd.verify_range(bytes(data[8192:]), 8192) is True
    data[9000] ^= 1
    assert bd.verify_range(bytes(data[8192:]), 8192) is False
    assert bd.verify_range(bytes(data[1:4097]), 1) is None
    assert bd.verify_range(b"", 0) is None


def test_block_digests_serde_and_lenient_parse():
    bd = BlockDigests.from_buf(b"hello world" * 1000, 1024)
    assert BlockDigests.from_obj(bd.to_obj()) == bd
    for garbage in (None, 7, [], {}, {"size": 0, "sha256": []},
                    {"size": 1024}, {"size": 1024, "sha256": ["zz"]},
                    {"size": "x", "sha256": []}):
        assert BlockDigests.from_obj(garbage) is None


def test_merge_ranges():
    assert merge_ranges([]) == []
    assert merge_ranges([(0, 10), (10, 5)]) == [(0, 15)]
    assert merge_ranges([(20, 5), (0, 10)]) == [(0, 10), (20, 5)]
    assert merge_ranges([(0, 10), (5, 10), (30, 2)]) == [(0, 15),
                                                         (30, 2)]
    assert merge_ranges([(0, 30), (5, 10)]) == [(0, 30)]


def test_repair_block_bytes_tunable_serde_and_env(tmp_path,
                                                  monkeypatch):
    from chunky_bits_tpu.cluster.tunables import (
        REPAIR_BLOCK_BYTES_ENV,
        Tunables,
    )

    monkeypatch.delenv(REPAIR_BLOCK_BYTES_ENV, raising=False)
    t = Tunables.from_obj({"repair_block_bytes": 1 << 20})
    assert t.repair_block_bytes == 1 << 20
    assert t.to_obj()["repair_block_bytes"] == 1 << 20
    assert "repair_block_bytes" not in Tunables.from_obj(None).to_obj()
    with pytest.raises(Exception):
        Tunables.from_obj({"repair_block_bytes": -1})
    monkeypatch.setenv(REPAIR_BLOCK_BYTES_ENV, "4096")
    assert Tunables.from_obj(None).repair_block_bytes == 4096
    monkeypatch.setenv(REPAIR_BLOCK_BYTES_ENV, "garbage")
    assert Tunables.from_obj(None).repair_block_bytes == 0
    # YAML wins over the env default
    monkeypatch.setenv(REPAIR_BLOCK_BYTES_ENV, "4096")
    assert Tunables.from_obj(
        {"repair_block_bytes": 0}).repair_block_bytes == 0


def test_encode_path_writes_trees_only_for_multiblock_chunks(tmp_path):
    cluster = Cluster.from_obj(make_cluster_obj(
        tmp_path, chunk_log2=14,
        tunables={"repair_block_bytes": 4096}))
    write_payload(cluster, "big", 3 * (1 << 14), seed=1)  # 16 KiB chunks
    write_payload(cluster, "small", 600, seed=2)  # 200 B chunks

    async def main():
        big = await cluster.get_file_ref("big")
        for chunk in big.parts[0].data + big.parts[0].parity:
            assert chunk.blocks is not None
            assert chunk.blocks.size == 4096
            assert chunk.blocks.covers(big.parts[0].chunksize)
        small = await cluster.get_file_ref("small")
        for chunk in small.parts[0].data + small.parts[0].parity:
            assert chunk.blocks is None  # one block: hash suffices

    asyncio.run(main())


# ---- the planner's plans, with exact byte accounting ----

@pytest.mark.parametrize("packed", [True, False])
def test_decode_plan_reads_d_blocks_not_d_chunks(tmp_path, packed):
    """One flipped byte in the only replica of one chunk: the planner
    reads the SAME damaged block off d helpers (3 x 4 KiB), not d whole
    chunks — and repairs in place without touching metadata."""
    cluster = Cluster.from_obj(make_cluster_obj(
        tmp_path, packed=packed, chunk_log2=14, code="rs",
        tunables={"repair_block_bytes": 4096}))
    payload = write_payload(cluster, "obj", 3 * (1 << 14), seed=3)

    async def main():
        ref = await cluster.get_file_ref("obj")
        # snapshot "was the metadata republished?" in a way that works
        # on both store layouts: raw ref-file bytes on a path store, the
        # append-only generation counter on a meta-log store (the CI
        # meta-log leg rebuilds plain path stores fleet-wide)
        meta_path = os.path.join(str(tmp_path), "meta", "obj")
        meta_before = None
        if os.path.exists(meta_path):
            with open(meta_path, "rb") as f:
                meta_before = f.read()
        gen_before = None
        if hasattr(cluster.metadata, "generation"):
            gen_before = await cluster.metadata.generation()
        flip_byte(ref.parts[0].data[1].locations[0], 5000)
        daemon = ScrubDaemon(cluster, bytes_per_sec=0)
        taken = meter_bucket(daemon)
        stats = await daemon.run_once()
        rs = stats.repair
        assert stats.corrupt == 1 and stats.repaired == 1
        assert rs["plans_decode"] == 1 and rs["plans_copy"] == 0
        assert rs["helper_bytes_decode"] == 3 * 4096
        assert rs["helper_bytes_replica"] == 0
        assert rs["bytes_localized"] == 1 << 14  # one victim re-read
        assert rs["bytes_rebuilt"] == 4096
        assert rs["bytes_written"] == 1 << 14
        # every byte of the pass is in the token-bucket accounting:
        # verification + localization + helper reads + repair writes
        assert sum(taken) == (stats.bytes_verified
                              + rs["bytes_localized"]
                              + rs["helper_bytes_decode"]
                              + rs["bytes_written"])
        # in-place repair: the stored metadata was never republished
        if meta_before is not None:
            with open(meta_path, "rb") as f:
                assert f.read() == meta_before
        if gen_before is not None:
            assert await cluster.metadata.generation() == gen_before
        got = await cluster.file_read_builder(
            await cluster.get_file_ref("obj")).read_all()
        assert got == payload
        verify = await (await cluster.get_file_ref("obj")).verify(
            cluster.tunables.location_context())
        assert str(verify.integrity()) == "Valid"
        # converged: the next pass finds nothing new
        stats2 = await daemon.run_once()
        assert stats2.corrupt == stats.corrupt

    asyncio.run(main())


def test_verify_phase_bytes_make_localization_free(tmp_path):
    """When verification runs the generic read path (here: a profiler
    rides the pass), the corrupt replica's bytes ride into the planner
    and localization costs ZERO extra I/O — repair reads are exactly
    d x damage."""
    from chunky_bits_tpu.file.profiler import new_profiler

    cluster = Cluster.from_obj(make_cluster_obj(
        tmp_path, packed=False, chunk_log2=14, code="rs",
        tunables={"repair_block_bytes": 4096}))
    payload = write_payload(cluster, "obj", 3 * (1 << 14), seed=11)

    async def main():
        ref = await cluster.get_file_ref("obj")
        flip_byte(ref.parts[0].data[0].locations[0], 7000)
        profiler, _reporter = new_profiler()
        daemon = ScrubDaemon(cluster, bytes_per_sec=0,
                             profiler=profiler)
        stats = await daemon.run_once()
        rs = stats.repair
        assert rs["bytes_localized"] == 0, rs
        assert rs["helper_bytes_decode"] == 3 * 4096, rs
        got = await cluster.file_read_builder(
            await cluster.get_file_ref("obj")).read_all()
        assert got == payload

    asyncio.run(main())


def test_copy_plan_prefers_replica_over_decode(tmp_path):
    """A corrupt replica BESIDE a healthy one: 1x ranged copy from the
    replica (one 4 KiB block), never a d x decode."""
    cluster = Cluster.from_obj(make_cluster_obj(
        tmp_path, chunk_log2=14,
        tunables={"repair_block_bytes": 4096}))
    payload = write_payload(cluster, "obj", 3 * (1 << 14), seed=4)

    async def main():
        ref = await cluster.get_file_ref("obj")
        chunk = ref.parts[0].data[0]
        data = await chunk.locations[0].read()
        victim_root = os.path.dirname(chunk.locations[0].target)
        other = next(d for d in
                     (os.path.join(str(tmp_path), f"disk{i}")
                      for i in range(5))
                     if d != victim_root)
        replica = Location.parse(f"slab:{other}/{chunk.hash}")
        await replica.write(bytes(data))
        chunk.locations.append(replica)
        await cluster.write_file_ref("obj", ref)
        flip_byte(chunk.locations[0], 9000)
        daemon = ScrubDaemon(cluster, bytes_per_sec=0)
        stats = await daemon.run_once()
        rs = stats.repair
        assert rs["plans_copy"] == 1 and rs["plans_decode"] == 0
        assert rs["helper_bytes_replica"] == 4096  # the damaged block
        assert rs["helper_bytes_decode"] == 0
        assert rs["bytes_rebuilt"] == 4096
        got = await cluster.file_read_builder(
            await cluster.get_file_ref("obj")).read_all()
        assert got == payload

    asyncio.run(main())


def test_two_lost_chunks_rebuild_in_one_decode_plan(tmp_path):
    """p chunks lost at once (the worst recoverable case): one decode
    plan rebuilds both from the same ranged helper reads."""
    cluster = Cluster.from_obj(make_cluster_obj(
        tmp_path, chunk_log2=14, code="rs",
        tunables={"repair_block_bytes": 4096}))
    payload = write_payload(cluster, "obj", 3 * (1 << 14), seed=5)

    async def main():
        ref = await cluster.get_file_ref("obj")
        flip_byte(ref.parts[0].data[0].locations[0], 100)
        flip_byte(ref.parts[0].parity[1].locations[0], 200)
        daemon = ScrubDaemon(cluster, bytes_per_sec=0)
        stats = await daemon.run_once()
        rs = stats.repair
        assert stats.corrupt == 2 and stats.repaired == 2
        assert rs["plans_decode"] == 1
        # both damaged blocks land in one range union read off d
        # helpers: 3 x (0..4096) — both flips hit block 0
        assert rs["helper_bytes_decode"] == 3 * 4096
        got = await cluster.file_read_builder(
            await cluster.get_file_ref("obj")).read_all()
        assert got == payload
        verify = await (await cluster.get_file_ref("obj")).verify(
            cluster.tunables.location_context())
        assert str(verify.integrity()) == "Valid"

    asyncio.run(main())


def test_unrecoverable_part_falls_back_and_counts_failure(tmp_path):
    """More than p chunks lost: the planner hands the part back (one
    fallback plan), the classic resilver reports the failure — the
    legacy accounting, not a silent skip."""
    cluster = Cluster.from_obj(make_cluster_obj(
        tmp_path, chunk_log2=14,
        tunables={"repair_block_bytes": 4096}))
    write_payload(cluster, "obj", 3 * (1 << 14), seed=6)

    async def main():
        ref = await cluster.get_file_ref("obj")
        for chunk in (ref.parts[0].data[0], ref.parts[0].data[1],
                      ref.parts[0].parity[0]):
            flip_byte(chunk.locations[0], 50)
        daemon = ScrubDaemon(cluster, bytes_per_sec=0)
        stats = await daemon.run_once()
        assert stats.repair["plans_fallback"] >= 1
        assert stats.repair_failures >= 1
        assert stats.repaired == 0

    asyncio.run(main())


def test_chunk_with_no_locations_falls_back_to_resilver(tmp_path):
    """A chunk stripped of every replica needs NEW placement — the
    planner hands the part to the classic resilver (which allocates a
    writer) instead of silently skipping it."""
    cluster = Cluster.from_obj(make_cluster_obj(
        tmp_path, chunk_log2=14,
        tunables={"repair_block_bytes": 4096}))
    payload = write_payload(cluster, "obj", 3 * (1 << 14), seed=8)

    async def main():
        ref = await cluster.get_file_ref("obj")
        victim = ref.parts[0].data[2]
        await victim.locations[0].delete()
        victim.locations.clear()
        await cluster.write_file_ref("obj", ref)
        daemon = ScrubDaemon(cluster, bytes_per_sec=0)
        stats = await daemon.run_once()
        assert stats.repair["plans_fallback"] >= 1
        assert stats.repaired >= 1  # resilver placed a new replica
        ref2 = await cluster.get_file_ref("obj")
        assert ref2.parts[0].data[2].locations, "no replica re-placed"
        got = await cluster.file_read_builder(ref2).read_all()
        assert got == payload
        verify = await ref2.verify(cluster.tunables.location_context())
        assert str(verify.integrity()) == "Valid"

    asyncio.run(main())


def test_old_refs_without_trees_repair_as_before(tmp_path):
    """References written with the tunable OFF (every pre-existing
    ref): no localization, whole-chunk plans, and the repaired file is
    byte-identical — the compat direction of the acceptance criteria.
    The tunable is pinned OFF in YAML (which wins) so the CI leg that
    sets $CHUNKY_BITS_TPU_REPAIR_BLOCK_BYTES suite-wide still
    exercises the tree-less path here."""
    cluster = Cluster.from_obj(make_cluster_obj(
        tmp_path, chunk_log2=14, code="rs",
        tunables={"repair_block_bytes": 0}))
    payload = write_payload(cluster, "obj", 3 * (1 << 14), seed=7)

    async def main():
        ref = await cluster.get_file_ref("obj")
        assert ref.parts[0].data[0].blocks is None
        flip_byte(ref.parts[0].data[0].locations[0], 5000)
        daemon = ScrubDaemon(cluster, bytes_per_sec=0)
        stats = await daemon.run_once()
        rs = stats.repair
        assert stats.repaired == 1
        assert rs["plans_decode"] == 1
        # whole-chunk ranged reads: d x chunksize, no localization read
        assert rs["helper_bytes_decode"] == 3 * (1 << 14)
        assert rs["bytes_localized"] == 0
        assert rs["bytes_rebuilt"] == 1 << 14
        got = await cluster.file_read_builder(
            await cluster.get_file_ref("obj")).read_all()
        assert got == payload

    asyncio.run(main())


# ---- byte-identity fuzz: partial vs full vs oracle, all backends ----

@pytest.mark.parametrize("backend", ["numpy", "native", "jax"])
def test_partial_rebuild_byte_identity_fuzz(tmp_path, backend):
    """Randomized damage repaired three ways — the planner's localized
    ranged rebuild, the planner without trees (whole-chunk), and the
    legacy full-part resilver — must all converge every replica to the
    SAME bytes the numpy-oracle content hashes pin, on every backend."""
    if backend == "native":
        from chunky_bits_tpu.ops.backend import get_backend

        try:
            get_backend("native")
        except Exception as err:  # pragma: no cover - missing g++
            pytest.skip(f"native backend unavailable: {err}")

    rng = np.random.default_rng(42)
    legs = (("treed", True, True), ("untreed", False, True),
            ("legacy", True, False))

    async def run_leg(name, trees, planner):
        # pinned in YAML either way (YAML wins over the CI leg's
        # suite-wide $CHUNKY_BITS_TPU_REPAIR_BLOCK_BYTES)
        tunables = {"backend": backend,
                    "repair_block_bytes": 1024 if trees else 0}
        cluster = Cluster.from_obj(make_cluster_obj(
            tmp_path / f"{backend}-{name}", chunk_log2=12,
            tunables=tunables))
        payload = np.random.default_rng(9).integers(
            0, 256, 3 * 4096 + 777, dtype=np.uint8).tobytes()
        await cluster.write_file("obj", aio.BytesReader(payload),
                                 cluster.get_profile())
        ref = await cluster.get_file_ref("obj")
        # identical damage pattern per leg: rng re-seeded per call
        damage_rng = np.random.default_rng(1234)
        for part in ref.parts:
            chunks = part.data + part.parity
            victims = damage_rng.choice(
                len(chunks), size=2, replace=False)
            for ci in victims:
                offset = int(damage_rng.integers(0, part.chunksize))
                flip_byte(chunks[ci].locations[0], offset)
        daemon = ScrubDaemon(cluster, bytes_per_sec=0, planner=planner)
        stats = await daemon.run_once()
        assert stats.corrupt >= 2, (name, stats)
        ref2 = await cluster.get_file_ref("obj")
        verify = await ref2.verify(cluster.tunables.location_context())
        assert str(verify.integrity()) == "Valid", (name, str(verify))
        got = await cluster.file_read_builder(ref2).read_all()
        assert got == payload, f"leg {name} not byte-identical"
        # replica bytes equal the oracle content hash by construction
        # (verify above re-hashed every replica); also pin the raw
        # bytes across legs via the chunk digests
        return sorted(str(c.hash) for p in ref2.parts
                      for c in p.data + p.parity)

    async def main():
        results = [await run_leg(*leg) for leg in legs]
        assert results[0] == results[1] == results[2]

    asyncio.run(main())


# ---- churn: scrub + planner converge under concurrent writes ----

def test_scrub_planner_converges_under_churn(tmp_path):
    """Localized corruption is repaired while a writer churns OTHER
    objects and overwrites one mid-pass: the planner converges the
    damage, never clobbers the concurrent overwrite, and every repair
    byte stays metered."""
    cluster = Cluster.from_obj(make_cluster_obj(
        tmp_path, chunk_log2=14, code="rs",
        tunables={"repair_block_bytes": 4096}))
    payloads = {
        f"o{i}": write_payload(cluster, f"o{i}", 3 * (1 << 14), seed=i)
        for i in range(4)
    }

    async def main():
        for i in (0, 2):
            ref = await cluster.get_file_ref(f"o{i}")
            flip_byte(ref.parts[0].data[i % 3].locations[0], 6000 + i)

        daemon = ScrubDaemon(cluster, bytes_per_sec=0,
                             interval_seconds=0.01)
        taken = meter_bucket(daemon)

        async def churn():
            # overwrite o3 and keep writing fresh objects while the
            # scrub pass runs
            for n in range(6):
                data = np.random.default_rng(100 + n).integers(
                    0, 256, 3 * (1 << 14), dtype=np.uint8).tobytes()
                name = "o3" if n == 0 else f"churn{n}"
                await cluster.write_file(
                    name, aio.BytesReader(data),
                    cluster.get_profile())
                payloads[name] = data
                await asyncio.sleep(0.01)

        daemon.start()
        await churn()
        for _ in range(200):
            stats = daemon.stats()
            if stats.repaired >= 2 and stats.passes >= 1:
                break
            await asyncio.sleep(0.05)
        await daemon.stop()
        stats = daemon.stats()
        assert stats.repaired >= 2, stats
        rs = stats.repair
        assert rs["plans_decode"] >= 2
        # metered: the bucket saw at least every helper/localize/write
        # byte the planner reports (verification rides the same bucket)
        assert sum(taken) >= (rs["helper_bytes_decode"]
                              + rs["helper_bytes_replica"]
                              + rs["bytes_localized"]
                              + rs["bytes_written"])
        for name, payload in payloads.items():
            ref = await cluster.get_file_ref(name)
            got = await cluster.file_read_builder(ref).read_all()
            assert got == payload, f"{name} diverged under churn"

    asyncio.run(main())


# ---- pm-msr regeneration plans (ops/pm_msr.py + the msr plan kind) ----

def _pm_cluster(tmp_path, d=5, p=4, chunk_log2=14, packed=False,
                tunables=None):
    """A pm-msr cluster with one replica per chunk (n = d + p nodes)."""
    return Cluster.from_obj(make_cluster_obj(
        tmp_path, packed=packed, d=d, p=p, chunk_log2=chunk_log2,
        n_nodes=d + p, tunables=tunables, code="pm-msr"))


def test_msr_plan_regenerates_single_loss_at_two_x(tmp_path):
    """The tentpole number: a pm-msr part losing ONE chunk regenerates
    from d' = 2(d-1) β-sized helper projections — exactly 2x chunksize
    of repair-plane bytes where the rs decode floor is d x chunksize —
    and the rebuilt object is byte-identical.  Every projection byte is
    metered through the scrub bucket, and the cb_repair_* counters
    carry the pm-msr code label."""
    d, p, chunk = 5, 4, 1 << 14
    alpha, dh = d - 1, 2 * (d - 1)
    cluster = _pm_cluster(tmp_path, d=d, p=p)
    payload = write_payload(cluster, "obj", d * chunk, seed=3)

    async def main():
        ref = await cluster.get_file_ref("obj")
        assert all(part.code == "pm-msr" for part in ref.parts)
        victim = ref.parts[0].data[2].locations[0]
        os.remove(victim.target)
        daemon = ScrubDaemon(cluster, bytes_per_sec=0, planner=True)
        taken = meter_bucket(daemon)
        stats = await daemon.run_once()
        rep = stats.repair
        assert rep["plans_msr"] == 1 and rep["plans_decode"] == 0, rep
        beta = chunk // alpha
        assert rep["helper_bytes_msr"] == dh * beta == 2 * chunk, rep
        assert rep["bytes_rebuilt"] == chunk
        by_code = rep["by_code"]
        assert by_code["pm-msr"]["plans_msr"] == 1
        assert by_code["rs"]["plans_msr"] == 0
        # the bucket meters the DISK: each helper projection reads a
        # full replica locally (only β enters the repair plane), so
        # the pass charged at least d' chunk reads + the repair write
        # (verification shares the bucket, so >=)
        assert sum(taken) >= dh * chunk + chunk
        assert sum(taken) >= rep["helper_bytes_msr"] + chunk
        got = await cluster.file_read_builder(
            await cluster.get_file_ref("obj")).read_all()
        assert got == payload
        # the regenerated replica verifies against its golden digest
        verify = await ref.parts[0].verify(
            cluster.tunables.location_context())
        assert str(verify.integrity()) == "Valid", str(verify)

    asyncio.run(main())


@pytest.mark.parametrize("packed", [False, True],
                         ids=["paths", "slabs"])
def test_msr_plan_works_on_slab_and_path_replicas(tmp_path, packed):
    """Helper projections compute from local AND slab-packed replicas
    (the is_local/is_slab gate); corruption (not just deletion) of the
    single replica also routes through the msr plan."""
    d, p, chunk = 3, 2, 1 << 13
    cluster = _pm_cluster(tmp_path, d=d, p=p, chunk_log2=13,
                          packed=packed)
    payload = write_payload(cluster, "obj", d * chunk, seed=5)

    async def main():
        ref = await cluster.get_file_ref("obj")
        flip_byte(ref.parts[0].data[1].locations[0], 100)
        daemon = ScrubDaemon(cluster, bytes_per_sec=0, planner=True)
        stats = await daemon.run_once()
        rep = stats.repair
        assert rep["plans_msr"] == 1, rep
        assert rep["helper_bytes_msr"] == 2 * (d - 1) * (chunk // (d - 1))
        got = await cluster.file_read_builder(
            await cluster.get_file_ref("obj")).read_all()
        assert got == payload

    asyncio.run(main())


def test_pm_msr_multi_loss_falls_back_to_decode_plan(tmp_path):
    """Two lost chunks exceed single-node regeneration: the planner
    falls through to the classic decode plan at whole-chunk ranges
    (the pm-msr coder through the ReconstructBatcher), still in place,
    still byte-identical."""
    d, p, chunk = 5, 4, 1 << 13
    cluster = _pm_cluster(tmp_path, d=d, p=p, chunk_log2=13)
    payload = write_payload(cluster, "obj", d * chunk, seed=7)

    async def main():
        ref = await cluster.get_file_ref("obj")
        os.remove(ref.parts[0].data[0].locations[0].target)
        os.remove(ref.parts[0].parity[1].locations[0].target)
        daemon = ScrubDaemon(cluster, bytes_per_sec=0, planner=True)
        stats = await daemon.run_once()
        rep = stats.repair
        assert rep["plans_msr"] == 0 and rep["plans_decode"] == 1, rep
        # whole-chunk decode: d helpers x chunksize, counted pm-msr
        assert rep["by_code"]["pm-msr"]["helper_bytes_decode"] \
            == d * chunk
        assert stats.repaired == 2
        got = await cluster.file_read_builder(
            await cluster.get_file_ref("obj")).read_all()
        assert got == payload

    asyncio.run(main())


def test_pm_msr_copy_plan_still_wins_with_replicas(tmp_path):
    """A damaged pm-msr replica beside a healthy one takes the 1x copy
    plan exactly like rs — regeneration only runs when NO replica of
    the chunk verifies (plan order is unchanged by the code)."""
    d, p = 3, 2
    chunk = 1 << 13
    obj = make_cluster_obj(tmp_path, packed=False, d=d, p=p,
                           chunk_log2=13, n_nodes=d + p,
                           code="pm-msr")
    cluster = Cluster.from_obj(obj)
    payload = write_payload(cluster, "obj", d * chunk, seed=11)

    async def main():
        ref = await cluster.get_file_ref("obj")
        chunk0 = ref.parts[0].data[0]
        # plant a second, healthy replica by hand (same recipe as the
        # rs copy-plan test): placement stays out of the picture
        data = await chunk0.locations[0].read()
        victim_root = os.path.dirname(chunk0.locations[0].target)
        other = next(r for r in
                     (os.path.join(str(tmp_path), f"disk{i}")
                      for i in range(d + p))
                     if r != victim_root)
        replica = Location.parse(f"{other}/{chunk0.hash}")
        await replica.write(bytes(data))
        chunk0.locations.append(replica)
        await cluster.write_file_ref("obj", ref)
        ref = await cluster.get_file_ref("obj")
        chunk0 = ref.parts[0].data[0]
        flip_byte(chunk0.locations[0], 42)
        daemon = ScrubDaemon(cluster, bytes_per_sec=0, planner=True)
        stats = await daemon.run_once()
        rep = stats.repair
        assert rep["plans_copy"] == 1 and rep["plans_msr"] == 0, rep
        assert rep["by_code"]["pm-msr"]["plans_copy"] == 1
        got = await cluster.file_read_builder(
            await cluster.get_file_ref("obj")).read_all()
        assert got == payload

    asyncio.run(main())


def test_unknown_code_part_is_hands_off_fallback(tmp_path):
    """A part declaring a foreign code is handed straight back for
    resilver (which refuses cleanly) — the planner never writes bytes
    whose semantics it does not implement, and the scrub pass survives
    to repair the rest of the namespace."""
    cluster = Cluster.from_obj(make_cluster_obj(
        tmp_path, packed=False, code="rs"))
    payload = write_payload(cluster, "obj", 3 * 4096, seed=13)
    write_payload(cluster, "ok", 3 * 4096, seed=14)

    async def main():
        # hand-edit the stored metadata to a foreign code
        obj = await cluster.metadata.read("obj")
        for part in obj["parts"]:
            part["code"] = "future-code"
        await cluster.metadata.write("obj", obj)
        ref = await cluster.get_file_ref("obj")
        flip_byte(ref.parts[0].data[0].locations[0], 10)
        daemon = ScrubDaemon(cluster, bytes_per_sec=0, planner=True)
        stats = await daemon.run_once()
        rep = stats.repair
        assert rep["plans_fallback"] >= 1
        assert rep["bytes_written"] == 0  # hands-off: nothing written
        assert stats.repair_failures >= 1  # resilver refused cleanly
        # the healthy object still scrubbed fine
        got = await cluster.file_read_builder(
            await cluster.get_file_ref("ok")).read_all()
        assert len(got) == 3 * 4096

    asyncio.run(main())


def test_msr_plan_survives_corrupt_helper(tmp_path):
    """A helper replica that rots between verify and projection fails
    its hash gate, is demerited, and the plan proceeds with the next
    healthiest helper — p > d-1 leaves spares."""
    d, p, chunk = 3, 3, 1 << 13
    cluster = _pm_cluster(tmp_path, d=d, p=p, chunk_log2=13)
    payload = write_payload(cluster, "obj", d * chunk, seed=17)

    async def main():
        ref = await cluster.get_file_ref("obj")
        os.remove(ref.parts[0].data[0].locations[0].target)
        daemon = ScrubDaemon(cluster, bytes_per_sec=0, planner=True)
        # corrupt one helper AFTER the verify phase: patch the planner
        # entry to rot it right before plans run
        planner = daemon._planner
        orig = planner.repair_part
        rotted = []

        async def rot_then_repair(part, verdicts, cx, pipe,
                                  payloads=None):
            if not rotted:
                flip_byte(part.data[1].locations[0], 99)
                rotted.append(True)
            return await orig(part, verdicts, cx, pipe,
                              payloads=payloads)

        planner.repair_part = rot_then_repair
        stats = await daemon.run_once()
        rep = stats.repair
        assert rep["plans_msr"] == 1, rep
        got = await cluster.file_read_builder(
            await cluster.get_file_ref("obj")).read_all()
        assert got == payload

    asyncio.run(main())
