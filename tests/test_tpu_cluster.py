"""End-to-end cluster lifecycle with the jax erasure backend selected via
cluster.yaml tunables — the north-star configuration: same object store,
compute plane on the accelerator (here the CPU jax backend; identical code
path on TPU)."""

import asyncio
import os
import pathlib
import random

import numpy as np
import pytest

from chunky_bits_tpu.cluster import Cluster
from chunky_bits_tpu.file import FileIntegrity
from chunky_bits_tpu.ops import matrix
from chunky_bits_tpu.ops.backend import ErasureCoder, NumpyBackend
from chunky_bits_tpu.utils import aio


def make_jax_cluster(tmp_path, d=4, p=2, backend="jax", n_dirs=None,
                     repeat=0, chunk_size=14) -> Cluster:
    dirs = []
    for i in range(n_dirs if n_dirs is not None else d + p + 1):
        dd = tmp_path / f"disk{i}"
        dd.mkdir()
        dirs.append(str(dd))
    meta = tmp_path / "meta"
    meta.mkdir()
    dest = [{"location": x, "repeat": repeat} if repeat
            else {"location": x} for x in dirs]
    return Cluster.from_obj({
        "destinations": dest,
        "metadata": {"type": "path", "format": "yaml", "path": str(meta)},
        "tunables": {"backend": backend},
        "profiles": {"default": {"data": d, "parity": p,
                                 "chunk_size": chunk_size}},
    })


async def read_all(reader) -> bytes:
    chunks = []
    while True:
        blk = await reader.read(1 << 20)
        if not blk:
            break
        chunks.append(blk)
    return b"".join(chunks)


def test_jax_backend_cluster_lifecycle(tmp_path):
    cluster = make_jax_cluster(tmp_path)
    assert cluster.tunables.backend == "jax"
    rng = random.Random(3)
    payload = bytes(rng.getrandbits(8) for _ in range(300000))

    async def main():
        profile = cluster.get_profile()
        await cluster.write_file("f", aio.BytesReader(payload), profile)
        # writer batching kicked in for the device backend
        writer = cluster.get_file_writer(profile)
        assert writer.batch_parts == 8

        ref = await cluster.get_file_ref("f")
        # shards on disk are byte-identical to the numpy oracle: re-derive
        # parity from the stored data chunks and compare hashes
        part = ref.parts[0]
        data_rows = [np.frombuffer(pathlib.Path(c.locations[0].target).read_bytes(),
                                   dtype=np.uint8) for c in part.data]
        oracle = ErasureCoder(len(part.data), len(part.parity),
                              NumpyBackend())
        parity_rows = oracle.encode_batch(np.stack(data_rows)[None])[0]
        from chunky_bits_tpu.file.hashing import AnyHash

        for row, chunk in zip(parity_rows, part.parity):
            assert AnyHash.from_buf(bytes(row)) == chunk.hash

        # degraded read + resilver through the jax reconstruct path
        os.remove(part.data[0].locations[0].target)
        os.remove(part.data[1].locations[0].target)
        reader = await cluster.read_file("f")
        assert await read_all(reader) == payload

        report = await ref.resilver(
            cluster.get_destination(profile), backend="jax")
        assert report.integrity() == FileIntegrity.RESILVERED
        verify = await ref.verify()
        assert verify.integrity() == FileIntegrity.VALID

    asyncio.run(main())


def test_wide_stripe_sharded():
    """BASELINE.md config 5: wide stripe d=20 p=6 across the 8-device
    mesh."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from chunky_bits_tpu.parallel import make_mesh, sharded_apply

    d, p = 20, 6
    enc = matrix.build_encode_matrix(d, p)
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (8, d, 512), dtype=np.uint8)
    mesh = make_mesh(8, dp=4, sp=2)
    got = np.asarray(sharded_apply(mesh, enc[d:], data))
    want = ErasureCoder(d, p, NumpyBackend()).encode_batch(data)
    assert np.array_equal(got, want)


def test_wide_stripe_mesh_cluster_lifecycle(tmp_path):
    """Full object-store lifecycle with the erasure plane on the
    wide-stripe ('dp','tp') mesh selected from cluster.yaml: ingest,
    degraded read (batched mesh reconstruct), resilver, verify."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")

    # repeat gives each dir 3 slots: 12 >= d+p = 10
    cluster = make_jax_cluster(tmp_path, d=8, p=2, backend="jax:tp4",
                               n_dirs=4, repeat=2, chunk_size=12)
    payload = np.random.default_rng(9).integers(
        0, 256, 150000, dtype=np.uint8).tobytes()

    async def main():
        await cluster.write_file("w", aio.BytesReader(payload),
                                 cluster.get_profile())
        ref = await cluster.get_file_ref("w")
        # oracle byte-identity of one part's parity
        part = ref.parts[0]
        data_rows = [np.frombuffer(pathlib.Path(c.locations[0].target).read_bytes(),
                                   dtype=np.uint8) for c in part.data]
        oracle = ErasureCoder(len(part.data), len(part.parity),
                              NumpyBackend())
        want_parity = oracle.encode_batch(np.stack(data_rows)[None])[0]
        got_parity = [pathlib.Path(c.locations[0].target).read_bytes()
                      for c in part.parity]
        for w, g in zip(want_parity, got_parity):
            assert w.tobytes() == g
        # degrade: drop 2 chunks of every part, read through tp decode
        for part in ref.parts:
            os.remove(part.data[0].locations[0].target)
            os.remove(part.parity[0].locations[0].target)
        reader = await cluster.read_file("w")  # carries backend jax:tp4
        assert await read_all(reader) == payload
        # repair through the mesh backend and verify
        rep = await ref.resilver(
            cluster.get_destination(cluster.get_profile()),
            backend=cluster.tunables.backend)
        assert rep.new_locations()
        report = await ref.verify()
        assert report.integrity() == FileIntegrity.VALID

    asyncio.run(main())


@pytest.mark.parametrize("backend", ["jax:dp4,sp2", "jax:tp4"])
def test_mesh_resilver_coalesces_parts_per_dispatch(
        tmp_path, monkeypatch, backend):
    """Degraded read + resilver end-to-end on both mesh layouts, with the
    ReconstructBatcher -> mesh path proven to coalesce: parts of one file
    degraded by the same loss pattern rebuild in strictly fewer device
    dispatches than parts (>1 parts per dispatch)."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    import chunky_bits_tpu.ops.batching as batching_mod

    d, p = 8, 2
    cluster = make_jax_cluster(tmp_path, d=d, p=p, backend=backend,
                               n_dirs=4, repeat=2, chunk_size=12)
    # exactly 8 full-size parts so every degraded part shares one
    # (geometry, erasure-pattern, size) batch key
    part_bytes = d * (1 << 12)
    payload = np.random.default_rng(21).integers(
        0, 256, 8 * part_bytes, dtype=np.uint8).tobytes()

    captured = []
    real_batcher = batching_mod.ReconstructBatcher

    class CapturingBatcher(real_batcher):
        def __init__(self, *a, **kw):
            super().__init__(*a, **kw)
            captured.append(self)

    monkeypatch.setattr(batching_mod, "ReconstructBatcher",
                        CapturingBatcher)

    async def main():
        await cluster.write_file("m", aio.BytesReader(payload),
                                 cluster.get_profile())
        ref = await cluster.get_file_ref("m")
        assert len(ref.parts) == 8
        # same loss pattern on every part: first data + first parity chunk
        for part in ref.parts:
            os.remove(part.data[0].locations[0].target)
            os.remove(part.parity[0].locations[0].target)

        # degraded read through the mesh backend: all prefetched parts
        # must share ONE batcher (coalescing is opportunistic, so the
        # dispatch count is timing-dependent — the shared-instance
        # invariant is the deterministic part)
        reader = await cluster.read_file("m")
        assert await read_all(reader) == payload
        assert len(captured) == 1, (
            "read stream no longer shares a single ReconstructBatcher")

        # resilver through the mesh backend; the shared batcher must
        # coalesce the 8 same-pattern parts into fewer dispatches
        rep = await ref.resilver(
            cluster.get_destination(cluster.get_profile()),
            backend=backend)
        assert rep.integrity() == FileIntegrity.RESILVERED
        resilver_batcher = captured[-1]
        assert resilver_batcher.dispatches >= 1
        assert resilver_batcher.dispatches < 8, (
            f"no coalescing: {resilver_batcher.dispatches} dispatches "
            f"for 8 parts")

        report = await ref.verify()
        assert report.integrity() == FileIntegrity.VALID

    asyncio.run(main())
