"""Device-side batched SHA-256 (ops/sha256_jax.py) vs hashlib.

The kernel exists to move shard hashing off the 1-core host (VERDICT r4
item 2; the reference hashes on CPU, src/file/file_part.rs:185).  Its
contract is byte-identity with hashlib for EVERY row length — FIPS
180-4 padding included — because digests feed chunk names and verify.

The shape sweep doubles as a regression net for two CPU-runtime
pathologies this jax build exhibits (either one turns an encode into an
infinite spin): odd-width u8 device concatenates, and unrolled
~2000-op compression bodies.  The kernel dodges both (host-assembled
tail block, fori_loop rounds); if a refactor reintroduces either, this
file hangs rather than fails — pytest-timeout isn't available, so the
sweep stays tiny to keep a hang obvious early in the run.
"""

import hashlib

import numpy as np
import pytest

from chunky_bits_tpu.ops.sha256_jax import (_pad_tail, _split_tail,
                                            sha256_rows_device)


def _hashlib_rows(rows: np.ndarray) -> np.ndarray:
    return np.stack([
        np.frombuffer(hashlib.sha256(r.tobytes()).digest(), dtype=np.uint8)
        for r in rows])


@pytest.mark.parametrize("n,s", [
    (1, 0),      # empty rows: digest of b""
    (1, 1),      # sub-block, odd width
    (2, 55),     # largest 1-block message
    (3, 56),     # smallest 2-block padding spill
    (2, 64),     # exactly one aligned block
    (4, 100),    # aligned head + odd remainder
    (2, 192),    # multi-block aligned
    (3, 1000),   # multi-block odd
])
def test_identical_to_hashlib(n, s):
    rows = np.random.default_rng(s).integers(0, 256, (n, s), dtype=np.uint8)
    assert np.array_equal(sha256_rows_device(rows), _hashlib_rows(rows))


def test_empty_batch():
    out = sha256_rows_device(np.empty((0, 128), dtype=np.uint8))
    assert out.shape == (0, 32)


def test_rejects_non_2d():
    with pytest.raises(ValueError):
        sha256_rows_device(np.zeros((2, 3, 4), dtype=np.uint8))


def test_pad_tail_lengths():
    # padded length must always be the next 64 multiple of s + 9
    for s in (0, 1, 54, 55, 56, 63, 64, 119, 120, 1 << 20):
        tail = _pad_tail(s)
        assert (s + tail.size) % 64 == 0
        assert tail[0] == 0x80
        assert int.from_bytes(tail[-8:].tobytes(), "big") == s * 8


def test_split_tail_alignment():
    rows = np.arange(2 * 100, dtype=np.uint8).reshape(2, 100)
    head, last = _split_tail(rows)
    assert head.shape[1] == 64 and head.shape[1] % 64 == 0
    assert last.shape[1] % 64 == 0
    # head must be a zero-copy view of the input
    assert head.base is not None and np.shares_memory(head, rows)
    # reassembled prefix equals the original row bytes
    joined = np.concatenate([head, last], axis=1)
    assert np.array_equal(joined[:, :100], rows)


def test_aligned_builder_matches_hashlib():
    """make_sha256_aligned (the traceable variant the fused device
    encode+hash path composes into its dispatch) is byte-identical to
    hashlib for 64-aligned rows."""
    import jax

    from chunky_bits_tpu.ops.sha256_jax import make_sha256_aligned

    for s in (64, 128, 1024):
        rows = np.random.default_rng(s).integers(
            0, 256, (3, s), dtype=np.uint8)
        fn = jax.jit(make_sha256_aligned(s))
        assert np.array_equal(np.asarray(fn(rows)), _hashlib_rows(rows))


def test_aligned_builder_rejects_odd_widths():
    from chunky_bits_tpu.ops.sha256_jax import make_sha256_aligned

    with pytest.raises(ValueError):
        make_sha256_aligned(100)


def test_fused_device_encode_hash_identity(monkeypatch):
    """The $CHUNKY_BITS_TPU_DEVICE_SHA path: parity AND digests from one
    fused dispatch (interpret-mode pallas on CPU) must be byte-identical
    to the numpy oracle's encode_hash_batch."""
    from chunky_bits_tpu.ops import jax_backend
    from chunky_bits_tpu.ops.backend import ErasureCoder, NumpyBackend

    d, p, s, b = 3, 2, 1024, 5
    rng = np.random.default_rng(7)
    data = rng.integers(0, 256, (b, d, s), dtype=np.uint8)
    be = jax_backend.JaxBackend()
    monkeypatch.setenv("CHUNKY_BITS_TPU_DEVICE_SHA", "1")
    monkeypatch.setattr(be, "_on_tpu", True)
    # route the fused build through interpret mode (no TPU here), and
    # force small blocks so the double-buffered block walk is exercised
    real_build = be._fused_encode_hash_fn
    monkeypatch.setattr(
        be, "_fused_encode_hash_fn",
        lambda mat, size: real_build(mat, size, interpret=True))
    monkeypatch.setattr(be, "max_pallas_block_bytes", 2 * d * s * 2)
    from chunky_bits_tpu.ops import matrix
    enc = matrix.build_encode_matrix(d, p)
    parity, digests = be.encode_and_hash(enc[d:], data)
    want_par, want_dig = ErasureCoder(
        d, p, NumpyBackend()).encode_hash_batch(data)
    assert np.array_equal(parity, want_par)
    assert np.array_equal(digests, want_dig)


def test_fused_fn_cached_and_failure_sticky(monkeypatch):
    """The fused executable is cached per (matrix, S) — no per-dispatch
    retrace — and a failing device-SHA dispatch disables the path for
    the process (host fallback thereafter, one warning)."""
    from chunky_bits_tpu.ops import jax_backend, matrix
    from chunky_bits_tpu.ops.backend import ErasureCoder, NumpyBackend

    d, p, s = 3, 2, 1024
    be = jax_backend.JaxBackend()
    enc = matrix.build_encode_matrix(d, p)
    f1 = be._fused_encode_hash_fn(enc[d:], s, interpret=True)
    f2 = be._fused_encode_hash_fn(enc[d:], s, interpret=True)
    assert f1 is f2

    monkeypatch.setenv("CHUNKY_BITS_TPU_DEVICE_SHA", "1")
    monkeypatch.setattr(be, "_on_tpu", True)
    calls = []

    def boom(mat, shards):
        calls.append(1)
        raise RuntimeError("injected device-SHA failure")

    monkeypatch.setattr(be, "_encode_and_hash_device", boom)
    data = np.random.default_rng(3).integers(
        0, 256, (2, d, s), dtype=np.uint8)
    # pallas parity path is TPU-only; drop to einsum for the fallback
    # while keeping the device-SHA gate satisfied above
    monkeypatch.setattr(
        jax_backend.JaxBackend, "_apply_pallas_blocked",
        lambda self, mat, shards, on_block=None: (_ for _ in ()).throw(
            ValueError("no pallas on cpu")))
    with pytest.warns(UserWarning) as caught:
        parity, digests = be.encode_and_hash(enc[d:], data)
    # two expected warnings: the injected device-SHA failure disables
    # that path, then the pallas-blocked monkeypatch disables the
    # pallas parity path (fallback to einsum)
    texts = [str(w.message) for w in caught]
    assert any("device SHA path disabled" in t for t in texts), texts
    assert any("pallas erasure kernel disabled" in t for t in texts), texts
    want_par, want_dig = ErasureCoder(
        d, p, NumpyBackend()).encode_hash_batch(data)
    assert np.array_equal(parity, want_par)
    assert np.array_equal(digests, want_dig)
    # second call: sticky flag set, device path never retried
    parity, digests = be.encode_and_hash(enc[d:], data)
    assert calls == [1]
    assert np.array_equal(parity, want_par)
