"""SLO engine (chunky_bits_tpu/obs/slo.py): windowed views, burn-rate
rules, the alert state machine, fleet aggregation, and the gateway
surfaces.

Four layers, matching the engine's pieces:

* **histogram_quantile edge cases** — the SLO rules made its return
  values operationally load-bearing, so the empty / all-mass-in-+Inf /
  single-sample branches are pinned here (they were untested before);
* **SnapshotRing** — windowed counter/histogram deltas, the
  young-ring insufficient-data contract, and THE worker-restart
  semantics: a cumulative series that went down restarted, and its
  windowed delta is the post-reset end value, never negative;
* **the state machine** — multi-window gating (a fast-window spike
  alone never fires), pending with ``for_s``, hold-down hysteresis on
  resolve, the bounded firing-history ring;
* **fleet + gateway** — ``fleet_alert_states`` (firing on one worker
  ⇒ firing fleet-wide; a spool-reaped dead worker contributes
  nothing), ``GET /alerts`` on and off, the ``/stats`` slo stanza,
  ``cb_build_info``, and the ``Slo<...>`` profiler stanza.

The detection-quality half (expected alerts firing inside virtual-time
bounds on the simulator) lives in tests/test_sim.py — this file is the
engine's own contract.
"""

import asyncio
import io
import math
import os

import pytest

from chunky_bits_tpu.obs import metrics as obs_metrics
from chunky_bits_tpu.obs import slo as obs_slo
from chunky_bits_tpu.obs.metrics import (
    MetricsRegistry,
    histogram_quantile,
    parse_exposition,
)
from chunky_bits_tpu.obs.slo import (
    FIRING,
    INACTIVE,
    PENDING,
    RULES,
    SloEngine,
    SloObjectives,
    SnapshotRing,
    fleet_alert_states,
)


def make_cluster(tmp_path, **tunables):
    from chunky_bits_tpu.cluster import Cluster

    dirs = []
    for i in range(5):
        d = tmp_path / f"disk{i}"
        d.mkdir(exist_ok=True)
        dirs.append(str(d))
    meta = tmp_path / "meta"
    meta.mkdir(exist_ok=True)
    return Cluster.from_obj({
        "destinations": [{"location": d} for d in dirs],
        "metadata": {"type": "path", "format": "yaml",
                     "path": str(meta)},
        "profiles": {"default": {"data": 3, "parity": 2,
                                 "chunk_size": 16}},
        "tunables": tunables,
    })


# ---- histogram_quantile edge cases (now load-bearing) ----

def test_histogram_quantile_empty_is_zero():
    assert histogram_quantile((0.1, 1.0), [0, 0, 0], 99.0) == 0.0
    assert histogram_quantile((), [], 50.0) == 0.0


def test_histogram_quantile_all_mass_in_inf_bucket():
    """Every observation above the last finite bound: the quantile
    interpolates inside the synthetic +Inf bucket [lo, 2*lo] (or
    [0, 1] when no finite bucket ever filled) — finite, monotone in q,
    never inf/NaN (an alert threshold comparison must stay sane)."""
    bounds = (0.1, 1.0)
    counts = [0, 0, 10]
    q50 = histogram_quantile(bounds, counts, 50.0)
    q99 = histogram_quantile(bounds, counts, 99.0)
    assert 1.0 <= q50 <= 2.0 and 1.0 <= q99 <= 2.0
    assert q50 <= q99
    assert math.isfinite(q99)
    # degenerate twin: nothing finite ever observed at all
    only_inf = histogram_quantile((), [5], 99.0)
    assert 0.0 <= only_inf <= 1.0 and math.isfinite(only_inf)


def test_histogram_quantile_single_sample():
    """One observation: every quantile lands inside that sample's
    bucket (linear interpolation between the bucket edges)."""
    bounds = (0.1, 1.0, 10.0)
    counts = [0, 1, 0, 0]
    for q in (1.0, 50.0, 99.9):
        v = histogram_quantile(bounds, counts, q)
        assert 0.1 <= v <= 1.0, (q, v)


# ---- SnapshotRing ----

def _counter_fam(name, *samples):
    return {"name": name, "type": "counter", "help": "",
            "samples": [{"labels": dict(labels), "value": value}
                        for labels, value in samples]}


def _gauge_fam(name, *samples):
    fam = _counter_fam(name, *samples)
    fam["type"] = "gauge"
    return fam


def _hist_fam(name, buckets, counts, labels=()):
    return {"name": name, "type": "histogram", "help": "",
            "buckets": list(buckets),
            "samples": [{"labels": dict(labels), "counts": list(counts),
                         "sum": 0.0, "count": sum(counts)}]}


def test_ring_counter_delta_and_window_selection():
    ring = SnapshotRing()
    for t, v in ((0, 100), (30, 160), (60, 220)):
        ring.append({"families": [_counter_fam(
            "c_total", ((), v))]}, now=t)
    # window 60: oldest-in-window is t=0 -> delta 120
    assert ring.counter_delta("c_total", 60) == 120
    # window 30: oldest-in-window is t=30 -> delta 60
    assert ring.counter_delta("c_total", 30) == 60
    # absent family -> None, never 0
    assert ring.counter_delta("nope_total", 60) is None


def test_ring_young_ring_reads_as_no_data():
    """A ring spanning less than half the window must answer None —
    a freshly-started worker has no burn rate, not a zero one."""
    ring = SnapshotRing()
    ring.append({"families": [_counter_fam("c_total", ((), 5))]},
                now=0)
    assert ring.counter_delta("c_total", 60) is None  # single entry
    ring.append({"families": [_counter_fam("c_total", ((), 9))]},
                now=10)
    assert ring.counter_delta("c_total", 60) is None  # span 10 < 30
    assert ring.counter_delta("c_total", 20) == 4     # span 10 >= 10


def test_ring_counter_reset_is_a_fresh_epoch_not_negative():
    """THE worker-restart contract: a cumulative counter that went
    DOWN restarted from zero; the windowed delta is the end value."""
    ring = SnapshotRing()
    ring.append({"families": [_counter_fam("c_total", ((), 1000))]},
                now=0)
    ring.append({"families": [_counter_fam("c_total", ((), 50))]},
                now=60)
    delta = ring.counter_delta("c_total", 60)
    assert delta == 50, f"restart must read as +50, got {delta}"


def test_ring_reset_is_per_label_set():
    """One worker of a fleet-merged series restarting must not poison
    the others' deltas: the clamp is per label set."""
    key_a = (("worker", "a"),)
    key_b = (("worker", "b"),)
    ring = SnapshotRing()
    ring.append({"families": [_counter_fam(
        "c_total", (key_a, 500), (key_b, 300))]}, now=0)
    ring.append({"families": [_counter_fam(
        "c_total", (key_a, 700), (key_b, 20))]}, now=60)
    # a: +200 normal; b: reset -> +20 fresh epoch
    assert ring.counter_delta("c_total", 60) == 220


def test_ring_histogram_window_and_reset():
    ring = SnapshotRing()
    ring.append({"families": [_hist_fam("h", (0.1, 1.0),
                                        [10, 5, 1])]}, now=0)
    ring.append({"families": [_hist_fam("h", (0.1, 1.0),
                                        [14, 9, 1])]}, now=60)
    bounds, counts = ring.hist_window("h", 60)
    assert bounds == [0.1, 1.0] and counts == [4, 4, 0]
    # any bucket going backwards = the series restarted: window
    # contribution is the end vector wholesale
    ring.append({"families": [_hist_fam("h", (0.1, 1.0),
                                        [2, 1, 0])]}, now=120)
    _, counts = ring.hist_window("h", 60)
    assert counts == [2, 1, 0]


def test_ring_quantile_over_window():
    ring = SnapshotRing()
    ring.append({"families": [_hist_fam("h", (0.1, 1.0),
                                        [100, 0, 0])]}, now=0)
    # all NEW mass lands in the (0.1, 1.0] bucket even though the
    # cumulative total is dominated by old fast samples — the window
    # view must see only the new mass
    ring.append({"families": [_hist_fam("h", (0.1, 1.0),
                                        [100, 50, 0])]}, now=60)
    q = ring.quantile("h", 99.0, 60)
    assert 0.1 <= q <= 1.0
    assert ring.quantile("absent", 99.0, 60) is None


def test_ring_gauge_persistence():
    ring = SnapshotRing()

    def frac(snap):
        values = ring.gauge_values(snap, "g")
        if not values:
            return None
        return sum(1 for v in values if v >= 1) / len(values)

    for t, states in ((0, (0, 0)), (30, (1, 2)), (60, (1, 2))):
        ring.append({"families": [_gauge_fam(
            "g", *(((("node", str(i)),), v)
                   for i, v in enumerate(states)))]}, now=t)
    # min over the 60s window includes the healthy t=0 entry
    assert ring.gauge_persisted(60, frac) == 0.0
    # a 30s window sees only the degraded entries
    assert ring.gauge_persisted(30, frac) == 1.0


def test_ring_prunes_by_age():
    """The memory bound that matters at fleet scale: entries older
    than max_age_s behind the newest are pruned (one boundary entry
    at/past the cutoff is kept so full-window pairs survive)."""
    ring = SnapshotRing(max_age_s=100.0)
    for t in range(0, 1000, 10):
        ring.append({"families": [_counter_fam("c_total",
                                               ((), float(t)))]},
                    now=t)
    assert len(ring) <= 13  # ~100s/10s + boundary + margin, not 100
    # windowed reads still work right up to the age bound
    assert ring.counter_delta("c_total", 100) == 100.0


def test_worker_labeled_snapshot_restart_stays_windowed():
    """THE fleet-evaluation contract: the engine's supervisor input
    is worker-LABELED, never summed — so one sibling's restart clamps
    to its own small post-reset series, not to the surviving fleet's
    lifetime total (which on a summed series would re-fire every
    ratio rule on every routine restart)."""
    from chunky_bits_tpu.obs.slo import worker_labeled_snapshot

    def fleet(a_ok, a_err, b_ok, b_err):
        return worker_labeled_snapshot([
            ("a", _requests_snap(a_ok, a_err)),
            ("b", _requests_snap(b_ok, b_err)),
        ])

    eng = SloEngine(SloObjectives(fast_s=60, slow_s=120),
                    registry=MetricsRegistry())
    # worker b carries a large OLD error history (a past outage) that
    # must never leak into a window after its restart
    t, a_ok, b_ok, b_err = 0, 10_000, 10_000, 5_000
    while t <= 120:
        eng.observe(fleet(a_ok, 0, b_ok, b_err), now=t)
        t += 30
        a_ok += 30
        b_ok += 30
    assert {x.rule: x.state for x in eng.alerts()}[
        "availability"] == INACTIVE
    # b restarts: its cumulative series drop to near zero
    b_ok, b_err = 5, 0
    for _ in range(4):
        eng.observe(fleet(a_ok, 0, b_ok, b_err), now=t)
        t += 30
        a_ok += 30
        b_ok += 30
    alerts = {x.rule: x for x in eng.alerts()}
    assert alerts["availability"].state == INACTIVE, (
        f"restart misread as a burn: {alerts['availability']}")
    assert (alerts["availability"].value_fast or 0.0) < 0.01
    # and a REAPED worker (gone from the input entirely) is silent too
    for _ in range(4):
        eng.observe(worker_labeled_snapshot(
            [("a", _requests_snap(a_ok, 0))]), now=t)
        t += 30
        a_ok += 30
    assert {x.rule: x.state for x in eng.alerts()}[
        "availability"] == INACTIVE


def test_worker_labeled_snapshot_shape():
    from chunky_bits_tpu.obs.slo import worker_labeled_snapshot

    combined = worker_labeled_snapshot([
        ("a", {"families": [_gauge_fam("cb_worker_up", ((), 1))]}),
        ("b", {"families": [_gauge_fam("cb_worker_up", ((), 1))]}),
    ])
    fam = combined["families"][0]
    assert fam["name"] == "cb_worker_up"
    assert sorted(s["labels"]["worker"] for s in fam["samples"]) \
        == ["a", "b"]
    assert sum(s["value"] for s in fam["samples"]) == 2


# ---- objectives ----

def test_objectives_loud_on_unknown_and_invalid():
    with pytest.raises(ValueError, match="unknown slo objective"):
        SloObjectives.from_obj({"tpyo": 1})
    with pytest.raises(ValueError, match="must be >= 0"):
        SloObjectives.from_obj({"fast_s": -1})
    with pytest.raises(ValueError, match="mapping"):
        SloObjectives.from_obj([1])
    obj = SloObjectives.from_obj({"fast_s": 30, "min_workers": 2})
    assert obj.fast_s == 30.0 and obj.min_workers == 2
    assert SloObjectives.from_obj(
        obj.to_obj()).to_obj() == obj.to_obj()


# ---- the state machine (driven with synthetic snapshots) ----

def _requests_snap(ok_total, err_total):
    return {"families": [_counter_fam(
        "cb_request_total",
        ((("method", "GET"), ("source", "store"),
          ("status_class", "2xx")), ok_total),
        ((("method", "GET"), ("source", "-"),
          ("status_class", "5xx")), err_total))]}


def test_fast_window_spike_alone_never_fires():
    """The multi-window burn-rate gate: a breach must hold over BOTH
    windows — a young ring (slow window unsatisfied) cannot fire."""
    eng = SloEngine(SloObjectives(fast_s=60, slow_s=300),
                    registry=MetricsRegistry())
    eng.observe(_requests_snap(100, 0), now=0)
    eng.observe(_requests_snap(150, 50), now=60)  # 33% errors, fast
    state = {a.rule: a.state for a in eng.alerts()}
    assert state["availability"] == INACTIVE


def test_availability_fires_and_resolves_with_hysteresis():
    eng = SloEngine(SloObjectives(fast_s=60, slow_s=300, clear_s=120),
                    registry=MetricsRegistry(),)
    ok, err, t = 100, 0, 0
    # sustained 10% error ratio: fires once the slow window fills
    while t <= 300:
        eng.observe(_requests_snap(ok, err), now=t)
        t += 30
        ok += 27
        err += 3
    alerts = {a.rule: a for a in eng.alerts()}
    assert alerts["availability"].state == FIRING
    assert alerts["availability"].value_fast == pytest.approx(0.1)
    fired_at = alerts["availability"].since
    # errors stop: the alert must HOLD clear_s before resolving
    clean_since = None
    while t <= 900:
        eng.observe(_requests_snap(ok, err), now=t)
        state = {a.rule: a.state for a in eng.alerts()}
        ratio = eng.alerts()[0].value_fast
        if clean_since is None and ratio is not None and ratio < 0.01:
            clean_since = t
        if state["availability"] == INACTIVE:
            break
        t += 30
        ok += 30
    assert {a.rule: a.state for a in eng.alerts()}[
        "availability"] == INACTIVE
    assert clean_since is not None
    assert t - clean_since >= 120, "resolved before the hold-down"
    history = eng.history()
    assert len(history) == 1
    assert history[0]["rule"] == "availability"
    assert history[0]["fired_at"] == pytest.approx(fired_at)
    assert history[0]["resolved_at"] is not None


def test_pending_state_with_for_s():
    eng = SloEngine(SloObjectives(fast_s=60, slow_s=60, for_s=60),
                    registry=MetricsRegistry())
    ok, err = 100, 0
    states = []
    for t in (0, 30, 60, 90, 120, 150):
        eng.observe(_requests_snap(ok, err), now=t)
        states.append({a.rule: a.state
                       for a in eng.alerts()}["availability"])
        ok += 18
        err += 2
    assert PENDING in states and states[-1] == FIRING
    assert states.index(PENDING) < states.index(FIRING)


def test_engine_publishes_closed_label_families():
    reg = MetricsRegistry()
    eng = SloEngine(registry=reg)
    eng.observe({"families": []}, now=0)
    snap = reg.snapshot()
    fams = {f["name"]: f for f in snap["families"]}
    states = fams["cb_alerts_state"]["samples"]
    assert {s["labels"]["rule"] for s in states} == set(RULES)
    assert all(s["value"] == 0 for s in states)
    assert fams["cb_slo_evaluations_total"]["samples"][0]["value"] == 1
    # and the exposition stays grammar-clean with the engine families
    parse_exposition(obs_metrics.render_exposition(snap))


def test_worker_down_rule_against_min_workers():
    eng = SloEngine(SloObjectives(fast_s=60, slow_s=60,
                                  min_workers=2, clear_s=30),
                    registry=MetricsRegistry())
    two_up = {"families": [_gauge_fam(
        "cb_worker_up", ((("worker", "a"),), 1),
        ((("worker", "b"),), 1))]}
    one_up = {"families": [_gauge_fam(
        "cb_worker_up", ((("worker", "a"),), 1))]}
    for t in (0, 30, 60):
        eng.observe(two_up, now=t)
    assert {a.rule: a.state for a in eng.alerts()}[
        "worker_down"] == INACTIVE
    for t in (90, 120, 150, 180):
        eng.observe(one_up, now=t)
    assert {a.rule: a.state for a in eng.alerts()}[
        "worker_down"] == FIRING


# ---- fleet aggregation ----

def _alerts_snap(**rule_states):
    return {"families": [_gauge_fam(
        "cb_alerts_state",
        *(((("rule", rule),), obs_slo._STATE_RANK[state])
          for rule, state in rule_states.items()))]}


def test_fleet_merge_firing_on_one_worker_is_fleet_firing():
    merged = fleet_alert_states([
        ("1001", _alerts_snap(availability=INACTIVE,
                              breaker_open=INACTIVE)),
        ("1002", _alerts_snap(availability=FIRING,
                              breaker_open=PENDING)),
    ])
    assert merged["fleet"]["availability"] == FIRING
    assert merged["fleet"]["breaker_open"] == PENDING
    assert merged["firing"] == ["availability"]
    assert merged["workers"]["1002"]["availability"] == FIRING
    assert merged["workers"]["1001"]["availability"] == INACTIVE


def test_fleet_merge_reaped_worker_contributes_nothing():
    """The supervisor unlinks a dead worker's spool snapshot; the
    merge input simply no longer contains it — its firing alert is
    gone from the fleet view on the next scrape."""
    alive = [("1001", _alerts_snap(availability=INACTIVE))]
    dead_too = alive + [("1002", _alerts_snap(availability=FIRING))]
    assert fleet_alert_states(dead_too)["fleet"][
        "availability"] == FIRING
    merged = fleet_alert_states(alive)
    assert merged["fleet"]["availability"] == INACTIVE
    assert "1002" not in merged["workers"]
    # foreign/unknown rule labels are ignored, never minted
    merged = fleet_alert_states([
        ("x", _alerts_snap(**{"not_a_rule": FIRING}))])
    assert set(merged["fleet"]) == set(RULES)
    assert merged["firing"] == []


# ---- gateway surfaces ----

def test_gateway_alerts_endpoint_off_by_default(tmp_path):
    from chunky_bits_tpu.gateway import make_app

    async def main():
        from aiohttp.test_utils import TestClient, TestServer

        cluster = make_cluster(tmp_path)
        async with TestClient(TestServer(make_app(cluster))) as client:
            resp = await client.get("/alerts")
            assert resp.status == 200
            assert await resp.json() == {"enabled": False}
            stats = await (await client.get("/stats")).json()
            assert stats["slo"] == {"enabled": False}
        await cluster.tunables.location_context().aclose()

    asyncio.run(main())


def test_gateway_alerts_endpoint_and_build_info(tmp_path):
    from chunky_bits_tpu import __version__
    from chunky_bits_tpu.gateway import make_app

    async def main():
        from aiohttp.test_utils import TestClient, TestServer

        cluster = make_cluster(tmp_path, slo_eval_s=0.05,
                               slo={"read_p99_ms": 250.0})
        async with TestClient(TestServer(make_app(cluster))) as client:
            assert (await client.put("/obj", data=b"z" * 9000)
                    ).status == 200
            await (await client.get("/obj")).read()
            await asyncio.sleep(0.2)  # a few engine ticks
            alerts = await (await client.get("/alerts")).json()
            assert alerts["enabled"] is True
            assert alerts["evaluations"] >= 1
            assert {a["rule"] for a in alerts["alerts"]} == set(RULES)
            assert alerts["objectives"]["read_p99_ms"] == 250.0
            assert alerts["firing"] == []
            stats = await (await client.get("/stats")).json()
            assert stats["slo"]["enabled"] is True
            assert stats["slo"]["evaluations"] >= 1
            parsed = parse_exposition(
                await (await client.get("/metrics")).text())
            for fam in ("cb_alerts_state", "cb_slo_evaluations_total",
                        "cb_build_info"):
                assert fam in parsed, f"missing {fam}"
            # the process-global registry may carry label sets from
            # other apps built in this process (exactly the
            # mixed-config fleet view the gauge exists for): find
            # THIS app's identity row
            rows = [labels for _n, labels, v
                    in parsed["cb_build_info"]["samples"] if v == 1]
            labels = next(r for r in rows if r["slo"] == "on")
            assert labels["version"] == __version__
            assert labels["sendfile"] in ("on", "off")
            assert labels["code"] in ("rs", "pm-msr")
        await cluster.tunables.location_context().aclose()

    asyncio.run(main())


def test_gateway_alerts_fleet_merge_via_spool(tmp_path):
    """The 2-worker supervisor shape without forking: this worker's
    live engine plus a sibling's spooled snapshot whose
    cb_alerts_state says FIRING — /alerts must report the fleet as
    firing; with the sibling's file reaped, it must not."""
    from chunky_bits_tpu.gateway import make_app

    spool = tmp_path / "spool"
    spool.mkdir()

    async def main():
        from aiohttp.test_utils import TestClient, TestServer

        cluster = make_cluster(tmp_path, slo_eval_s=0.05)
        app = make_app(cluster, metrics_spool=str(spool))
        async with TestClient(TestServer(app)) as client:
            sibling = spool / "worker-9999.json"
            obs_metrics.write_snapshot_file(
                str(sibling), _alerts_snap(breaker_open=FIRING))
            await asyncio.sleep(0.15)
            alerts = await (await client.get("/alerts")).json()
            assert alerts["enabled"] is True
            fleet = alerts["fleet"]
            assert fleet["fleet"]["breaker_open"] == FIRING
            assert "breaker_open" in fleet["firing"]
            assert fleet["workers"]["9999"]["breaker_open"] == FIRING
            # the supervisor reaps a dead worker's snapshot: its
            # firing alert must vanish from the very next fleet view
            os.unlink(sibling)
            alerts = await (await client.get("/alerts")).json()
            assert alerts["fleet"]["fleet"]["breaker_open"] == INACTIVE
            assert "9999" not in alerts["fleet"]["workers"]
        await cluster.tunables.location_context().aclose()

    asyncio.run(main())


# ---- profiler stanza + stats CLI ----

def test_profiler_slo_stanza():
    from chunky_bits_tpu.file.profiler import new_profiler

    eng = SloEngine(registry=MetricsRegistry())
    eng.observe({"families": []}, now=0)
    profiler, reporter = new_profiler()
    profiler.attach_slo(eng)
    profiler.attach_slo(eng)  # idempotent
    report = str(reporter.profile())
    assert "Slo<evals=1" in report
    assert report.count("Slo<") == 1


def test_stats_cli_renders_alert_stanza(capsys):
    from chunky_bits_tpu.cli.stats import render_summary

    stats = {"worker": "1", "requests": {}, "dropped": {},
             "metrics": {"families": []}}
    out = io.StringIO()
    render_summary(stats, {"status": "ok"}, {"enabled": False}, out)
    assert "slo: disabled" in out.getvalue()
    out = io.StringIO()
    alerts = {
        "enabled": True, "evaluations": 42,
        "firing": ["breaker_open"],
        "fleet": {"firing": ["breaker_open", "scrub_stall"]},
        "alerts": [
            {"rule": "breaker_open", "state": "firing",
             "value_fast": 0.5, "threshold": 0.3, "fired_count": 1},
            {"rule": "availability", "state": "pending",
             "value_fast": 0.02, "threshold": 0.01, "fired_count": 0},
            {"rule": "scrub_stall", "state": "inactive",
             "value_fast": None, "threshold": 1.0, "fired_count": 0},
        ]}
    render_summary(stats, {"status": "ok"}, {"enabled": False}, out,
                   alerts=alerts)
    text = out.getvalue()
    assert "slo: 1 firing (evals=42) fleet-firing=2" in text
    lines = [ln for ln in text.splitlines() if "alert " in ln]
    assert len(lines) == 2, text  # inactive rules stay off-screen
    assert "firing" in lines[0] and "breaker_open" in lines[0]
    assert "pending" in lines[1]


def test_stats_cli_watch_loops_and_fetches_alerts(tmp_path):
    """--watch N: the command redraws on the clock-seam cadence; two
    frames against a live gateway, then cancelled (the CLI's ctrl-c
    path).  Also pins that the one-shot fetch includes /alerts."""
    from chunky_bits_tpu.cli.stats import stats_command
    from chunky_bits_tpu.gateway import make_app

    async def main():
        from aiohttp.test_utils import TestServer

        cluster = make_cluster(tmp_path, slo_eval_s=0.05)
        server = TestServer(make_app(cluster))
        await server.start_server()
        try:
            url = f"http://127.0.0.1:{server.port}"
            out = io.StringIO()
            task = asyncio.ensure_future(stats_command(
                url, as_json=False, out=out, watch_s=0.1))
            for _ in range(200):
                await asyncio.sleep(0.05)
                if out.getvalue().count("--- frame") >= 2:
                    break
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
            text = out.getvalue()
            assert text.count("--- frame") >= 2
            assert "slo:" in text
            # one-shot --json carries the alerts payload
            out = io.StringIO()
            assert await stats_command(url, as_json=True,
                                       out=out) == 0
            import json as _json

            blob = _json.loads(out.getvalue())
            assert blob["alerts"]["enabled"] is True
        finally:
            await server.close()
        await cluster.tunables.location_context().aclose()

    asyncio.run(main())


# ---- tunables serde ----

def test_tunables_slo_serde_and_env(monkeypatch):
    from chunky_bits_tpu.cluster.tunables import (SLO_EVAL_S_ENV,
                                                  Tunables, slo_eval_s)
    from chunky_bits_tpu.errors import SerdeError

    t = Tunables.from_obj({"slo_eval_s": 15,
                           "slo": {"breaker_node_fraction": 0.4}})
    assert t.slo_eval_s == 15.0
    assert t.to_obj()["slo"] == {"breaker_node_fraction": 0.4}
    assert Tunables.from_obj(t.to_obj()).slo_eval_s == 15.0
    # off by default, and off stays out of to_obj
    assert Tunables.from_obj(None).slo_eval_s == 0.0
    assert "slo_eval_s" not in Tunables.from_obj(None).to_obj()
    with pytest.raises(SerdeError, match="slo_eval_s"):
        Tunables.from_obj({"slo_eval_s": -1})
    with pytest.raises(SerdeError, match="unknown slo objective"):
        Tunables.from_obj({"slo": {"tpyo": 3}})
    monkeypatch.setenv(SLO_EVAL_S_ENV, "30")
    assert slo_eval_s() == 30.0
    monkeypatch.setenv(SLO_EVAL_S_ENV, "garbage")
    assert slo_eval_s() == 0.0  # lenient: a perf knob can only tune
