"""CLI end-to-end tests, driving ``python -m chunky_bits_tpu.cli`` as a
subprocess — the analogue of the reference CI's encode-decode job
(.github/workflows/compile.yml) plus coverage of the ClusterLocation
grammar and the standalone shard codec."""

import hashlib
import os
import shlex
import subprocess
import sys

import pytest
import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_cli(*argv, check=True, pipe_to=None, **kwargs):
    """Drive ``python -m chunky_bits_tpu.cli``; ``pipe_to`` runs the CLI
    through a shell pipeline (e.g. "head -c 64 >/dev/null")."""
    env = dict(os.environ)
    env.setdefault("PYTHONPATH", REPO)
    if pipe_to is None:
        cmd = [sys.executable, "-m", "chunky_bits_tpu.cli", *argv]
        shell = False
    else:
        cmd = " ".join(
            shlex.quote(a)
            for a in (sys.executable, "-m", "chunky_bits_tpu.cli", *argv)
        ) + " | " + pipe_to
        shell = True
    result = subprocess.run(
        cmd, shell=shell, capture_output=True, env=env, cwd=REPO, **kwargs)
    if check and result.returncode != 0:
        raise AssertionError(
            f"cli failed ({result.returncode}): {result.stderr.decode()}")
    return result


def ref_obj(cluster_yaml, name):
    """Parsed file reference through the metadata surface (file-info),
    independent of the store's on-disk layout — a plain ``type: path``
    store may be running as a meta-log under
    ``$CHUNKY_BITS_TPU_METADATA_KIND`` (the CI meta-log leg)."""
    return yaml.safe_load(
        run_cli("file-info", f"{cluster_yaml}#{name}").stdout)


@pytest.fixture
def cluster_yaml(tmp_path):
    dirs = []
    for i in range(5):
        d = tmp_path / f"disk{i}"
        d.mkdir()
        dirs.append(str(d))
    meta = tmp_path / "metadata"
    meta.mkdir()
    path = tmp_path / "cluster.yaml"
    path.write_text(yaml.safe_dump({
        "destinations": [{"location": d} for d in dirs],
        "metadata": {"type": "path", "format": "yaml", "path": str(meta)},
        "profiles": {"default": {"data": 3, "parity": 2,
                                 "chunk_size": 16}},
    }))
    return path


def test_cluster_location_grammar():
    from chunky_bits_tpu.cli.cluster_location import ClusterLocation

    cases = {
        "mycluster#path/to/file": ("cluster", "mycluster", None),
        "mycluster[fast]#path": ("cluster", "mycluster", "fast"),
        "./cluster.yaml#file": ("cluster", "./cluster.yaml", None),
        "@#/tmp/ref.yaml": ("file_ref", None, None),
        "/tmp/file": ("other", None, None),
        "-": ("stdio", None, None),
    }
    for s, (kind, cluster, profile) in cases.items():
        loc = ClusterLocation.parse(s)
        assert loc.kind == kind, s
        if cluster is not None:
            assert loc.cluster == cluster
        assert loc.profile == profile
        assert str(loc) == s


def test_cp_cat_roundtrip(cluster_yaml, tmp_path):
    """50.25 MiB-style encode->decode, scaled down (256 KiB x 9 + tail)."""
    payload = os.urandom(256 * 1024 * 9 + 77)
    src = tmp_path / "input.bin"
    src.write_bytes(payload)
    run_cli("cp", str(src), f"{cluster_yaml}#files/input.bin")
    out = run_cli("cat", f"{cluster_yaml}#files/input.bin")
    assert hashlib.sha256(out.stdout).hexdigest() == \
        hashlib.sha256(payload).hexdigest()
    # read through the file-reference scheme too (cp @#ref out) — the
    # ref is exported to a standalone file so the @# grammar is
    # exercised regardless of the metadata store's on-disk layout
    meta = ref_obj(cluster_yaml, "files/input.bin")
    assert meta["length"] == len(payload)
    ref_file = tmp_path / "input.ref"
    ref_file.write_text(yaml.safe_dump(meta))
    out = run_cli("cat", f"@#{ref_file}")
    assert out.stdout == payload


def test_cp_from_stdin(cluster_yaml):
    payload = b"stdin payload" * 1000
    run_cli("cp", "-", f"{cluster_yaml}#from-stdin", input=payload)
    out = run_cli("cat", f"{cluster_yaml}#from-stdin")
    assert out.stdout == payload


def test_ls(cluster_yaml, tmp_path):
    run_cli("cp", "-", f"{cluster_yaml}#a/b/file1", input=b"x")
    run_cli("cp", "-", f"{cluster_yaml}#file2", input=b"y")
    out = run_cli("ls", f"{cluster_yaml}#.")
    listing = out.stdout.decode().splitlines()
    assert "file2" in listing and "a" in listing
    out = run_cli("ls", "-r", f"{cluster_yaml}#.")
    listing = out.stdout.decode().splitlines()
    assert "a/b/file1" in listing and "file2" in listing


def test_verify_and_resilver_cli(cluster_yaml, tmp_path):
    payload = os.urandom(200000)
    run_cli("cp", "-", f"{cluster_yaml}#victim", input=payload)
    meta = ref_obj(cluster_yaml, "victim")
    # delete one chunk file
    victim_loc = meta["parts"][0]["data"][0]["locations"][0]
    os.remove(victim_loc)
    out = run_cli("verify", f"{cluster_yaml}#victim")
    assert "Degraded" in out.stdout.decode()
    out = run_cli("resilver", f"{cluster_yaml}#victim")
    assert "Resilvered" in out.stdout.decode() or \
        "Valid" in out.stdout.decode()
    out = run_cli("verify", f"{cluster_yaml}#victim")
    assert "file\tValid" in out.stdout.decode()


def test_encode_decode_shards(tmp_path):
    payload = os.urandom(10000)
    src = tmp_path / "src.bin"
    src.write_bytes(payload)
    shard_paths = [str(tmp_path / f"shard{i}") for i in range(5)]
    run_cli("--data-chunks", "3", "--parity-chunks", "2",
            "encode-shards", str(src), *shard_paths)
    # drop one data and one parity shard; decode from the rest
    os.remove(shard_paths[0])
    os.remove(shard_paths[4])
    out = run_cli("--data-chunks", "3", "--parity-chunks", "2",
                  "decode-shards", *shard_paths, check=True)
    # decoded output is zero-padded to the stripe; trim to payload length
    assert out.stdout[:len(payload)] == payload
    assert len(out.stdout) >= len(payload)


def test_file_info_and_get_hashes(cluster_yaml):
    payload = os.urandom(70000)
    run_cli("cp", "-", f"{cluster_yaml}#hashed", input=payload)
    out = run_cli("file-info", f"{cluster_yaml}#hashed")
    info = yaml.safe_load(out.stdout)
    assert info["length"] == len(payload)
    out = run_cli("get-hashes", f"{cluster_yaml}#hashed")
    hashes = out.stdout.decode().split()
    parts = info["parts"]
    expected = sum(len(p["data"]) + len(p.get("parity", []))
                   for p in parts)
    assert len(hashes) == expected
    assert all(h.startswith("sha256-") for h in hashes)
    out_sorted = run_cli("get-hashes", "--sort", f"{cluster_yaml}#hashed")
    assert out_sorted.stdout.decode().split() == \
        sorted(set(hashes))


def test_migrate(cluster_yaml, tmp_path):
    """migrate references a file in place via range-sliced locations."""
    payload = os.urandom(150000)
    src = tmp_path / "existing.bin"
    src.write_bytes(payload)
    run_cli("migrate", str(src), f"{cluster_yaml}#migrated")
    out = run_cli("cat", f"{cluster_yaml}#migrated")
    assert out.stdout == payload
    # the data was NOT copied: chunk locations are range views of src
    meta = ref_obj(cluster_yaml, "migrated")
    first_loc = meta["parts"][0]["data"][0]["locations"][-1]
    assert str(src) in first_loc and first_loc.startswith("(")
    # a migrated ref is Degraded until resilver materializes the parity
    # chunks (the reference's migrate also writes them through the Void
    # destination: hashes recorded, no locations); verify's fused
    # range-hash path checks the in-place data chunks
    out = run_cli("verify", f"{cluster_yaml}#migrated")
    assert out.stdout.splitlines()[0].strip().endswith(b"Degraded")
    run_cli("resilver", f"{cluster_yaml}#migrated")
    out = run_cli("verify", f"{cluster_yaml}#migrated")
    assert out.stdout.splitlines()[0].strip().endswith(b"Valid")
    out = run_cli("cat", f"{cluster_yaml}#migrated")
    assert out.stdout == payload


def test_find_unused_hashes(cluster_yaml, tmp_path):
    payload = os.urandom(100000)
    run_cli("cp", "-", f"{cluster_yaml}#live", input=payload)
    # drop an orphan chunk file into disk0
    orphan_hash = "sha256-" + hashlib.sha256(b"orphan").hexdigest()
    orphan_path = tmp_path / "disk0" / orphan_hash
    orphan_path.write_bytes(b"orphan")
    # age it past the GC grace window (fresh files are shielded —
    # they look like an in-flight write's staged chunks)
    old = os.stat(orphan_path).st_mtime - 3600
    os.utime(orphan_path, (old, old))
    disks = [str(tmp_path / f"disk{i}") for i in range(5)]
    out = run_cli("find-unused-hashes", f"{cluster_yaml}#.",
                  "--", *disks)
    assert orphan_hash in out.stdout.decode()
    live_hashes = run_cli(
        "get-hashes", f"{cluster_yaml}#live").stdout.decode().split()
    assert all(h not in out.stdout.decode() for h in live_hashes)
    # --remove deletes the orphan
    run_cli("find-unused-hashes", "--remove", f"{cluster_yaml}#.",
            "--", *disks)
    assert not orphan_path.exists()
    # live data still reads back
    out = run_cli("cat", f"{cluster_yaml}#live")
    assert out.stdout == payload


def test_cluster_info_and_config_info(cluster_yaml):
    out = run_cli("cluster-info", str(cluster_yaml))
    obj = yaml.safe_load(out.stdout)
    assert len(obj["destinations"]) == 5
    out = run_cli("cluster-info", "--json", str(cluster_yaml))
    import json

    obj = json.loads(out.stdout)
    assert obj["profiles"]["default"]["data_chunks"] == 3
    out = run_cli("config-info")
    obj = yaml.safe_load(out.stdout)
    assert obj["default_destination"]["type"] == "void"


def test_error_paths(cluster_yaml):
    result = run_cli("cat", f"{cluster_yaml}#does-not-exist", check=False)
    assert result.returncode != 0
    result = run_cli("cat", "nonexistent-cluster#x", check=False)
    assert result.returncode != 0
    assert b"not defined" in result.stderr or b"Error" in result.stderr
    result = run_cli("resilver", "/tmp/just-a-file", check=False)
    assert result.returncode != 0


def test_broken_pipe_quiet(cluster_yaml, tmp_path):
    """``cat | head`` must not traceback: the CLI dies quietly on SIGPIPE
    like the reference binary (and every coreutils tool)."""
    src = tmp_path / "input.bin"
    src.write_bytes(os.urandom(1 << 20))
    run_cli("cp", str(src), f"{cluster_yaml}#objects/pipe")
    proc = run_cli("cat", f"{cluster_yaml}#objects/pipe",
                   pipe_to="head -c 64 >/dev/null")
    assert b"Traceback" not in proc.stderr


def test_python_decoder_interop(cluster_yaml, tmp_path):
    """The reference's read-only Python decoder contract: python/
    chunky-bits.py must reassemble a file from a file reference written
    by this framework (data chunks only, sha256-verified)."""
    payload = os.urandom(300000)
    src = tmp_path / "in.bin"
    src.write_bytes(payload)
    run_cli("cp", str(src), f"{cluster_yaml}#files/interop")
    # export the ref to a standalone file: the decoder's contract is
    # "a file-reference file", not any particular metadata store layout
    ref_path = tmp_path / "interop.ref"
    ref_path.write_text(yaml.safe_dump(ref_obj(cluster_yaml,
                                               "files/interop")))
    env = dict(os.environ)
    env.setdefault("PYTHONPATH", REPO)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "python", "chunky-bits.py"),
         str(ref_path)],
        capture_output=True, env=env)
    assert proc.returncode == 0, proc.stderr.decode()
    assert proc.stdout == payload


def test_cp_cluster_to_cluster(cluster_yaml, tmp_path):
    """cp cluster#a cluster2#b: read pipeline of one cluster feeding the
    write pipeline of another."""
    dirs2 = []
    for i in range(5):
        d = tmp_path / f"second{i}"
        d.mkdir()
        dirs2.append(str(d))
    meta2 = tmp_path / "metadata2"
    meta2.mkdir()
    second = tmp_path / "cluster2.yaml"
    second.write_text(yaml.safe_dump({
        "destinations": [{"location": d} for d in dirs2],
        "metadata": {"type": "path", "format": "yaml", "path": str(meta2)},
        "profiles": {"default": {"data": 4, "parity": 1,
                                 "chunk_size": 14}},
    }))
    payload = os.urandom(200000)
    run_cli("cp", "-", f"{cluster_yaml}#src-obj", input=payload)
    run_cli("cp", f"{cluster_yaml}#src-obj", f"{second}#dst-obj")
    out = run_cli("cat", f"{second}#dst-obj")
    assert out.stdout == payload
    # second cluster re-encoded with its own geometry
    meta = ref_obj(second, "dst-obj")
    assert len(meta["parts"][0]["data"]) == 4
    assert len(meta["parts"][0]["parity"]) == 1
