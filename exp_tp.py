"""On-chip: the wide-stripe tp path's acc+pack kernels, compiled (not
interpret) on ONE device.

The tp-sharded mesh encode runs `acc_m2_bitmajor` (int16 bit-plane
accumulator) per chip and packs after the psum (parallel/mesh.py:
wide_apply_sharded).  Until now that kernel pair had only interpret-mode
runs (VERDICT r4 weak item / next-round item 8); this measures it
compiled at the dryrun's wide geometry (d=20 p=6) against the fused
kernel at the same geometry, single chip, bench.py's marginal method.
Identity vs the numpy oracle gates the numbers; exits 1 on mismatch.

Usage: python exp_tp.py [--smoke]   (--smoke: CPU-sized, interpret)
"""
import sys

import numpy as np

import jax
import jax.numpy as jnp

from bench import marginal_seconds
from chunky_bits_tpu.ops import matrix
from chunky_bits_tpu.ops.backend import ErasureCoder, NumpyBackend
from chunky_bits_tpu.ops.pallas_kernels import (acc_m2_bitmajor,
                                                apply_m2_bitmajor,
                                                bit_matrix_bitmajor,
                                                pack_acc_bitmajor)

SMOKE = "--smoke" in sys.argv
d, p = 20, 6
if SMOKE:
    batch, size, iters = 2, 1 << 13, 2
else:
    batch, size, iters = 64, 1 << 20, 6

enc = matrix.build_encode_matrix(d, p)
rows = enc[d:]
m2 = jnp.asarray(bit_matrix_bitmajor(rows).astype(np.int8))
rng = np.random.default_rng(0)
data = rng.integers(0, 256, (batch, d, size), dtype=np.uint8)
x = jnp.asarray(data)

acc_then_pack = jax.jit(lambda y: pack_acc_bitmajor(
    acc_m2_bitmajor(m2, y, interpret=SMOKE)))
fused = jax.jit(lambda y: apply_m2_bitmajor(m2, y, interpret=SMOKE))

# identity gate vs the numpy oracle, both kernels
small = data[:2, :, :8192]
want = ErasureCoder(d, p, NumpyBackend()).encode_batch(small)
for name, fn in (("acc+pack", acc_then_pack), ("fused", fused)):
    got = np.asarray(fn(jnp.asarray(small)))
    if not np.array_equal(want, got):
        print(f"{name}: IDENTITY FAIL at d={d} p={p}", flush=True)
        sys.exit(1)
print(f"identity OK (d={d} p={p}, both kernels, compiled"
      f"{' interpret' if SMOKE else ''})", flush=True)

xor_cost = marginal_seconds(lambda y: y, x, iters)
if xor_cost < 0:
    if not SMOKE:
        sys.exit("xor baseline did not scale linearly; rerun")
    xor_cost = 0.0


def report(name, fn):
    t = marginal_seconds(fn, x, iters)
    if t < 0 or t <= xor_cost:
        print(f"{name}: no valid measurement", flush=True)
        return
    gib = batch * d * size / (t - xor_cost) / (1 << 30)
    print(f"{name}: {gib:6.1f} GiB/s ({(t - xor_cost) * 1e3:.2f} ms "
          f"marginal)", flush=True)


report("fused   ", fused)
report("acc+pack", acc_then_pack)
