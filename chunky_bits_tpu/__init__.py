"""chunky-bits-tpu: a TPU-native distributed erasure-coded object store.

A brand-new framework with the capabilities of MilesBreslin/Chunky-Bits
(reference: /root/reference, Rust): files are split into parts of ``d`` data +
``p`` parity chunks (Reed-Solomon over GF(2^8)), content-addressed by SHA-256
and scattered over weighted, zone-tagged destinations (local disks or dumb
HTTP endpoints), with a small YAML/JSON file reference as the only metadata.

The compute plane differs from the reference: the Reed-Solomon encode/decode
hot path (reference: src/file/file_part.rs:161,128,302) runs as batched
GF(2^8) bit-plane matmuls on TPU via JAX/XLA/Pallas, behind a pluggable
``ErasureBackend``.  A native C++ CPU backend with the identical matrix
convention is the correctness oracle.
"""

__version__ = "0.1.0"

from chunky_bits_tpu.errors import (  # noqa: F401
    ChunkyBitsError,
    ClusterError,
    FileReadError,
    FileWriteError,
    LocationError,
    LocationParseError,
    MetadataReadError,
    ShardError,
)
