"""Device-side batched SHA-256 over shard rows.

The reference hashes every shard on the host CPU (reference:
src/file/file_part.rs:185 via the ``sha2`` crate, one core per shard).
On this system the host hash is the measured end-to-end ceiling
(BASELINE.md config 2: ~0.7 GiB/s fused encode+hash on a 1-core host)
while the accelerator encodes at ~54 GiB/s and then idles — so shard
hashing is the one integrity op worth moving on-device.

TPU-first shape: SHA-256 is strictly sequential along its own message,
but every shard row is independent, so the batch axis [N = B*(d+p)]
fills the VPU's lanes while a ``fori_loop`` walks the 64-byte blocks.
Everything is 32-bit integer adds/rotates/xors — native VPU ops; no MXU
involvement, so on a mesh it can run concurrently with GF matmuls.

Layout: rows ``u8[N, S]`` are repacked once to big-endian words and
transposed to words-major ``u32[W, N]`` (one fused pass), so the block
walk reads contiguous 16-row slices; the running digest is a tuple of
eight flat ``u32[N]`` vectors, so every arithmetic op fills the VPU
lanes with zero per-round repacking.  The schedule expansion, the 64
rounds, and the block walk are all ``fori_loop``s — small loop bodies
keep the graph (and compile time) flat in S, and dodge a superlinear
compile/execute blowup this jax build's CPU backend hits on big
unrolled integer bodies (see ``compress``).

Correctness: digests are byte-identical to hashlib/SHA-NI for every row
length (FIPS 180-4 padding included) — see tests/test_sha256_jax.py.
"""

from __future__ import annotations

import functools

import numpy as np

# FIPS 180-4 round constants
_K = np.array([
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5,
    0x3956c25b, 0x59f111f1, 0x923f82a4, 0xab1c5ed5,
    0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174,
    0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
    0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7,
    0xc6e00bf3, 0xd5a79147, 0x06ca6351, 0x14292967,
    0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
    0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3,
    0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5,
    0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f, 0x682e6ff3,
    0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2,
], dtype=np.uint32)

_H0 = np.array([
    0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
    0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19,
], dtype=np.uint32)


def _pad_tail(row_bytes: int) -> np.ndarray:
    """The FIPS 180-4 suffix appended to every (equal-length) row:
    0x80, zeros to a 64-byte boundary, then the bit length as a
    big-endian u64.  Identical for all rows, so it is built once on the
    host and broadcast."""
    rem = (row_bytes + 9) % 64
    zeros = (64 - rem) % 64
    tail = bytearray()
    tail.append(0x80)
    tail.extend(b"\x00" * zeros)
    tail.extend((row_bytes * 8).to_bytes(8, "big"))
    return np.frombuffer(bytes(tail), dtype=np.uint8)


def _split_tail(rows: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Split ``u8[N, S]`` into the 64-aligned head (a zero-copy view)
    and the final block(s): the unaligned remainder plus the FIPS tail,
    assembled on the host (<= 128 bytes/row).  The device then only
    ever sees 64-aligned buffers — no odd-width device concatenate
    (which this jax build's CPU backend miscompiles into a spin; the
    head also avoids a whole-row device-side copy)."""
    n, s = rows.shape
    aligned = s - (s % 64)
    tail = _pad_tail(s)
    last = np.empty((n, s - aligned + tail.size), dtype=np.uint8)
    last[:, :s - aligned] = rows[:, aligned:]
    last[:, s - aligned:] = tail
    return rows[:, :aligned], last


def _rotr(x, r: int):
    return (x >> np.uint32(r)) | (x << np.uint32(32 - r))


def _to_words(jnp, buf):
    """``u8[N, 64k] -> u32[N, 16k]`` big-endian words."""
    b = buf.reshape(buf.shape[0], -1, 4).astype(jnp.uint32)
    return ((b[:, :, 0] << 24) | (b[:, :, 1] << 16)
            | (b[:, :, 2] << 8) | b[:, :, 3])


def _make_compress(jax, jnp, k):
    def compress(state, w16):
        """One FIPS 180-4 block; ``state`` is a tuple of eight
        ``u32[N]`` vectors, ``w16`` is ``u32[16, N]`` (words-major).

        Layout rationale: every arithmetic op runs on a full flat
        ``[N]`` vector, which XLA tiles across all VPU lanes; the
        words-major schedule makes each ``w[t]`` access a contiguous
        row slice instead of a strided per-lane column gather.

        Both phases are ``fori_loop``s, NOT unrolled: the unrolled
        64-round body (~2000 straight-line int ops) sends this jax
        build's CPU backend into a superlinear compile/execute blowup
        (8 rounds 0.5 s, 32 rounds 3.4 s, 64 rounds never returns).
        Loop bodies of ~25 ops keep compile trivial everywhere."""
        n = w16.shape[1]

        def row(w, t):
            return jax.lax.dynamic_slice(w, (t, 0), (1, n))[0]

        def sched_step(t, w):
            w15, w2 = row(w, t - 15), row(w, t - 2)
            w16_, w7 = row(w, t - 16), row(w, t - 7)
            s0 = (_rotr(w15, 7) ^ _rotr(w15, 18)
                  ^ (w15 >> np.uint32(3)))
            s1 = (_rotr(w2, 17) ^ _rotr(w2, 19)
                  ^ (w2 >> np.uint32(10)))
            return jax.lax.dynamic_update_slice(
                w, (w16_ + s0 + w7 + s1)[None, :], (t, 0))

        w = jnp.concatenate(
            [w16, jnp.zeros((48, n), jnp.uint32)], axis=0)
        w = jax.lax.fori_loop(16, 64, sched_step, w)

        def round_step(t, vs):
            a, b, c, d, e, f, g, h = vs
            wt = row(w, t)
            s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
            ch = (e & f) ^ (~e & g)
            t1 = h + s1 + ch + k[t] + wt
            s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
            maj = (a & b) ^ (a & c) ^ (b & c)
            return (t1 + s0 + maj, a, b, c, d + t1, e, f, g)

        vs = jax.lax.fori_loop(0, 64, round_step, state)
        return tuple(s + v for s, v in zip(state, vs))

    return compress


def _digest_bytes(jnp, state):
    """state tuple of eight ``u32[N]`` -> ``u8[N, 32]`` big-endian."""
    stacked = jnp.stack(state, axis=1)  # [N, 8]
    out = jnp.stack([
        (stacked >> np.uint32(s)).astype(jnp.uint8)
        for s in (24, 16, 8, 0)], axis=2)
    return out.reshape(stacked.shape[0], 32)


def _sha256_over_words(jax, jnp, words, nblocks: int, compress):
    """Run ``compress`` over ``nblocks`` 16-word blocks of
    ``u32[N, 16*nblocks]``; returns digest bytes ``u8[N, 32]``."""
    n = words.shape[0]
    # One whole-buffer transpose up front (XLA fuses it with the
    # byte->word conversion feeding this), so the hot loop's block
    # reads are contiguous row ranges instead of 16384 tiny strided
    # per-block transposes.
    words_major = words.T  # [16*nblocks, N]
    init = tuple(jnp.broadcast_to(jnp.uint32(h), (n,)) for h in _H0)

    def block_step(i, state):
        return compress(state, jax.lax.dynamic_slice(
            words_major, (i * 16, 0), (16, n)))

    state = jax.lax.fori_loop(0, nblocks, block_step, init)
    return _digest_bytes(jnp, state)


def make_sha256_aligned(row_bytes: int):
    """A TRACEABLE ``u8[N, row_bytes] -> u8[N, 32]`` for 64-aligned
    ``row_bytes``, composable inside a larger jit (the fused
    encode+hash path hashes rows that are already device-resident, so
    no host-side tail assembly is possible there).  The FIPS tail for
    equal 64-aligned rows is one constant 64-byte block, appended in
    word space."""
    if row_bytes % 64 != 0:
        raise ValueError(f"row_bytes must be 64-aligned, got {row_bytes}")
    import jax
    import jax.numpy as jnp

    tail = _pad_tail(row_bytes)
    assert tail.size == 64
    tail_words_host = (
        tail.reshape(16, 4).astype(np.uint32) @
        np.array([1 << 24, 1 << 16, 1 << 8, 1], dtype=np.uint32))
    compress = _make_compress(jax, jnp, jnp.asarray(_K))

    def fn(rows):
        n = rows.shape[0]
        words = jnp.concatenate([
            _to_words(jnp, rows),
            jnp.broadcast_to(jnp.asarray(tail_words_host), (n, 16)),
        ], axis=1)
        return _sha256_over_words(
            jax, jnp, words, row_bytes // 64 + 1, compress)

    return fn


@functools.lru_cache(maxsize=None)
def _build_sha256_fn(head_bytes: int, last_bytes: int):
    """Jit-compiled ``(u8[N, head_bytes], u8[N, last_bytes]) ->
    u8[N, 32]``.  ``head`` is the 64-aligned prefix of the rows;
    ``last`` is the host-assembled remainder + FIPS tail (64 or 128
    bytes).  One executable per (N, head, last) triple via ordinary jit
    retrace; the compression graph itself is independent of S."""
    import jax
    import jax.numpy as jnp

    compress = _make_compress(jax, jnp, jnp.asarray(_K))

    def sha256(head, last):
        # Word-space concat of two 64-aligned buffers, then ONE
        # fori_loop over every block.  Keeping the compress inside the
        # loop (rather than unrolling the tail blocks at top level)
        # matters: this jax build's CPU runtime spins forever executing
        # the unrolled variant (and the odd-width byte concat) — see
        # tests/test_sha256_jax.py for the shape sweep that pins both.
        words = jnp.concatenate(
            [_to_words(jnp, head), _to_words(jnp, last)], axis=1)
        return _sha256_over_words(
            jax, jnp, words, (head_bytes + last_bytes) // 64, compress)

    return jax.jit(sha256)


def sha256_rows_device(rows: np.ndarray):
    """SHA-256 of each row of ``u8[N, S]`` on the default JAX device;
    returns ``u8[N, 32]`` digests as a host array, byte-identical to hashlib."""
    rows = np.ascontiguousarray(rows, dtype=np.uint8)
    if rows.ndim != 2:
        raise ValueError(f"want u8[N, S], got shape {rows.shape}")
    if rows.shape[0] == 0:
        return np.empty((0, 32), dtype=np.uint8)
    head, last = _split_tail(rows)
    fn = _build_sha256_fn(head.shape[1], last.shape[1])
    return np.asarray(fn(head, last))
