"""GF(2^8) matrix algebra and the Reed-Solomon matrix convention.

The encode matrix must match the reference's ``reed-solomon-erasure`` crate
(the Backblaze JavaReedSolomon construction) so that parity shards are
byte-identical with the reference (reference: src/file/file_part.rs:77 —
``ReedSolomon::new(d, p)``):

    V = vandermonde(d + p, d)      with V[r, c] = r^c  (GF power)
    E = V @ inv(V[:d])             (systematic: E[:d] == I)

Parity rows are ``E[d:]``; reconstruction inverts the d surviving rows.

Externally anchored (tests/test_matrix_conformance.py): the published
Backblaze 4+2 coding matrix, the QR-standard (ISO/IEC 18004) antilog
table for 0x11D/generator-2, and a from-scratch independent
implementation sharing no code with this module, equality-checked over a
(d, p) grid — a convention bug here is detectable without trusting this
derivation.
"""

from __future__ import annotations

import numpy as np

from chunky_bits_tpu.errors import ErasureError
from chunky_bits_tpu.ops import gf256


def gf_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Matrix product over GF(2^8): XOR-accumulated table multiplies."""
    r, k = a.shape
    k2, c = b.shape
    assert k == k2
    out = np.zeros((r, c), dtype=np.uint8)
    for i in range(k):
        # out ^= a[:, i] ⊗ b[i, :] (outer product over GF)
        out ^= gf256.MUL_TABLE[a[:, i][:, None], b[i, :][None, :]]
    return out


def gf_identity(n: int) -> np.ndarray:
    return np.eye(n, dtype=np.uint8)


def gf_invert(mat: np.ndarray) -> np.ndarray:
    """Gauss-Jordan inversion over GF(2^8).

    Raises ErasureError on singular matrices (the reference's
    ``Error::TooFewShardsPresent`` analogue surfaces above this).
    """
    n, m = mat.shape
    if n != m:
        raise ErasureError("cannot invert a non-square matrix")
    work = np.concatenate([mat.astype(np.uint8), gf_identity(n)], axis=1)
    for col in range(n):
        pivot = None
        for row in range(col, n):
            if work[row, col] != 0:
                pivot = row
                break
        if pivot is None:
            raise ErasureError("singular matrix over GF(2^8)")
        if pivot != col:
            work[[col, pivot]] = work[[pivot, col]]
        inv_p = gf256.gf_inv(int(work[col, col]))
        work[col] = gf256.MUL_TABLE[inv_p][work[col]]
        for row in range(n):
            if row != col and work[row, col] != 0:
                factor = int(work[row, col])
                work[row] ^= gf256.MUL_TABLE[factor][work[col]]
    return work[:, n:].copy()


def vandermonde(rows: int, cols: int) -> np.ndarray:
    """V[r, c] = r^c with gf_pow's 0^0 == 1 convention (Backblaze)."""
    v = np.zeros((rows, cols), dtype=np.uint8)
    for r in range(rows):
        for c in range(cols):
            v[r, c] = gf256.gf_pow(r, c)
    return v


def build_encode_matrix(data: int, parity: int) -> np.ndarray:
    """The systematic (d+p) x d encode matrix; top d rows are the identity.

    Matches ``reed-solomon-erasure``'s ``ReedSolomon::new(data, parity)``
    internal matrix so shards interoperate with reference-written clusters.
    """
    if data < 1:
        raise ErasureError("data shard count must be >= 1")
    if parity < 0:
        raise ErasureError("parity shard count must be >= 0")
    if data + parity > 256:
        raise ErasureError("d + p must be <= 256 for GF(2^8) Vandermonde")
    v = vandermonde(data + parity, data)
    top_inv = gf_invert(v[:data])
    e = gf_matmul(v, top_inv)
    # Systematic property: the construction guarantees E[:d] == I.
    assert np.array_equal(e[:data], gf_identity(data))
    return e


def decode_matrix(
    encode: np.ndarray, present: list[int], wanted: list[int]
) -> np.ndarray:
    """Rows that rebuild ``wanted`` shards from the first-d ``present`` ones.

    ``present`` — indices (into the d+p shard list) of >= d intact shards;
    only the first d are used, mirroring the reference codec's reconstruction
    (it inverts the submatrix of d surviving rows).  ``wanted`` — indices of
    shards to reproduce.  Returns [len(wanted), d] over GF(2^8).
    """
    d = encode.shape[1]
    if len(present) < d:
        raise ErasureError(
            f"need at least {d} present shards, have {len(present)}"
        )
    sub = encode[np.array(present[:d], dtype=np.intp)]
    sub_inv = gf_invert(sub)  # maps surviving shard bytes -> data bytes
    rows = encode[np.array(wanted, dtype=np.intp)]
    return gf_matmul(rows, sub_inv)
