"""Multi-chip ``mesh`` erasure backend: auto-laid-out sharded dispatch.

``backend: mesh`` (or ``$CHUNKY_BITS_TPU_BACKEND=mesh``) puts the
erasure plane on EVERY visible device with per-dispatch layout
selection, where ``jax:dp4,sp2`` (parallel/backend.py) pins one
explicit mesh for the whole process.  The staged ``[B, d, S]``
encode/decode batches from the batching layer (ops/batching.py) are
sharded per call:

* batch-parallel ``('dp', 'sp')`` by default — the part-batch axis over
  ``dp`` (parts are independent stripes) and, when the batch alone
  cannot fill the mesh, shard bytes over the leftover ``sp`` axis;
* wide-stripe ``('dp', 'tp')`` when the stripe is wide enough that a
  single-stripe matmul saturates one core (``k >=
  WIDE_STRIPE_MIN_K``) and the batch cannot cover the devices: the
  GF contraction axis splits over ``tp`` with an integer psum over ICI
  (parallel/mesh.py, the ``dryrun_multichip`` layout).

The per-chip transform is the existing bit-plane kernel, unchanged,
under ``jit`` + shard_map (``parallel/mesh.py`` — einsum on CPU
meshes, the fused Pallas kernel on TPU chips); on TPU meshes the
staged device buffers are donated back to the allocator
(``donate=True``), never on CPU where XLA may alias host numpy memory.

Dispatch rides the shared :class:`DispatchPipeline`
(ops/dispatch_pipeline.py): block k+1's H2D and the host hash stage
overlap block k's compute and block k-1's D2H, bounded at
``tunables.dispatch_depth()`` in-flight dispatches (default 2, the
double buffer).  ``submit_apply`` exposes the feed-ahead surface the
ingest path uses to stage whole batches ahead of dispatch
(ops/backend.py ``encode_hash_batches``).

XLA CPU quirks stay out of this path by construction (CLAUDE.md
"Environment quirks"): byte-sharded dispatches are padded so every
per-device slice is a multiple of ``LANE`` = 64 bytes, jit bodies are
the existing small kernels (no unrolled loops, no device concats —
blocks concatenate on the host).  Padding is sliced back after
materialization; GF transforms are columnwise, so padding never leaks
into real output and every backend stays byte-identical (conformance
fuzz + golden fixtures pin it).

Degrade-never-hang (CLAUDE.md invariant): construction waits behind
``await_device_init`` (bounded, sticky), every materialization runs
under ``run_bounded_dispatch``, and a dispatch timeout cancels the
pipeline and marks the mesh dead — all further work recomputes on the
CPU fallback, byte-identically.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

from chunky_bits_tpu.ops.backend import ErasureBackend
from chunky_bits_tpu.ops.dispatch_pipeline import (
    DispatchCancelled,
    DispatchPipeline,
)

#: contraction-split threshold: stripes at least this wide take the
#: ('dp', 'tp') wide-stripe layout when the batch alone cannot fill the
#: mesh (BASELINE.md config 5's regime — d=20 saturates one core)
WIDE_STRIPE_MIN_K = 16

#: per-device byte-slice alignment for the 'sp' axis — this jax build's
#: XLA CPU backend misbehaves on odd-width u8 device buffers, and real
#: chips want lane-aligned slices anyway (CLAUDE.md)
LANE = 64


@dataclass(frozen=True)
class Layout:
    """One dispatch's mesh layout: ``('dp', 'tp')`` when ``wide`` else
    ``('dp', 'sp')``; ``minor`` is the tp/sp extent and ``pad_s`` the
    byte padding keeping per-device slices LANE-aligned."""

    wide: bool
    dp: int
    minor: int
    pad_s: int


def _divisors_desc(n: int) -> list[int]:
    return [d for d in range(n, 0, -1) if n % d == 0]


def plan_layout(n: int, b: int, k: int, s: int) -> Layout:
    """Pick the mesh layout for one ``[b, k, s]`` dispatch over ``n``
    devices.  Batch-parallel wants ``dp`` as large as the batch allows
    (parts shard with zero collectives); the leftover axis goes to the
    stripe (``tp``, wide stripes only — one integer psum) or to shard
    bytes (``sp``, element-wise, padded to ``minor * LANE``)."""
    b = max(b, 1)
    dp = next(d for d in _divisors_desc(n) if d <= b)
    minor = n // dp
    if minor == 1:
        return Layout(False, dp, 1, 0)
    if k >= WIDE_STRIPE_MIN_K and k % minor == 0:
        return Layout(True, dp, minor, 0)
    return Layout(False, dp, minor, (-s) % (minor * LANE))


class _MeshTicket:
    """One ``submit_apply`` call's handle: the un-materialized sharded
    dispatches of a ``[B, k, S]`` batch.  ``result()`` drains them
    FIFO through the owning backend's pipeline, fires ``on_block`` per
    materialized block, and recomputes on the CPU fallback if the mesh
    died (cancel-safe — collected blocks keep their valid bytes;
    callers reconcile rows their callback never saw)."""

    __slots__ = ("_backend", "_mat", "_shards", "_entries", "_spans",
                 "_on_block", "_b", "_s", "_value", "_done")

    def __init__(self, backend: "MeshBackend", mat: np.ndarray,
                 shards: np.ndarray, entries: list, spans: list,
                 on_block: Optional[Callable[[int, np.ndarray], None]],
                 b: int, s: int,
                 value: Optional[np.ndarray] = None) -> None:
        self._backend = backend
        self._mat = mat
        self._shards = shards
        self._entries = entries
        self._spans = spans
        self._on_block = on_block
        self._b = b
        self._s = s
        self._value = value
        self._done = value is not None

    def result(self) -> np.ndarray:
        if self._done:
            return self._value  # type: ignore[return-value]
        from chunky_bits_tpu.errors import DeviceDispatchTimeout

        be = self._backend
        outs: list[np.ndarray] = []
        failure: Optional[BaseException] = None
        for (lo, rows), entry in zip(self._spans, self._entries):
            try:
                arr = be.pipeline.result(entry)
            except (DispatchCancelled, DeviceDispatchTimeout) as err:
                failure = err
                break
            arr = np.ascontiguousarray(arr[:rows, :, :self._s])
            if self._on_block is not None:
                # lint: clock-escape-ok times REAL host-side work for
                # the overlap-proof counters (bench config 17); real
                # work completes at zero virtual width under sim
                t0 = time.perf_counter()
                self._on_block(lo, arr)
                if be.pipeline.inflight:
                    # lint: clock-escape-ok same real host interval
                    dt = time.perf_counter() - t0
                    be.pipeline.note_host_overlap(dt)
            outs.append(arr)
        if failure is not None:
            be._degrade(failure)
            # blocks already delivered through on_block keep their
            # (valid) bytes — a timeout invalidates the DEVICE, not
            # results it already returned; the callback is NOT fired
            # for the CPU recompute, callers reconcile never-seen rows
            out = be._cpu_fallback().apply_matrix(self._mat, self._shards)
        else:
            out = outs[0] if len(outs) == 1 else np.concatenate(outs,
                                                                axis=0)
        self._value, self._done = out, True
        self._entries = self._spans = None  # type: ignore[assignment]
        return out


class MeshBackend(ErasureBackend):
    """Erasure math sharded over every visible device, fed through a
    bounded double-buffered dispatch window."""

    name = "mesh"

    #: the generic ingest path overlaps host hashing with the sharded
    #: device dispatch (ops/backend.py encode_hash_batch)
    async_dispatch = True

    #: batcher groups route through the feed-ahead submit surface
    #: (ops/batching.py), which supersedes the merged-concat copy
    prefers_merged_batches = True

    #: cap device memory per in-flight dispatch: bits blow bytes up 16x
    #: as bf16 on the einsum impl (same budget as JaxBackend)
    max_block_bytes = 64 << 20

    def __init__(self, depth: Optional[int] = None) -> None:
        from chunky_bits_tpu.ops.jax_backend import await_device_init

        await_device_init()
        import jax

        devices = jax.devices()
        self.n_devices = len(devices)
        try:
            self._on_tpu = devices[0].platform == "tpu"
        # lint: broad-except-ok platform probe only; a failure routes
        # to the no-donation path, which computes the same bytes
        except Exception:
            self._on_tpu = False
        self.pipeline = DispatchPipeline(depth=depth, name="mesh dispatch")
        self._meshes: dict[tuple[bool, int, int], object] = {}
        self._mesh_lock = threading.Lock()
        self._device_dead = False
        self._fallback: Optional[ErasureBackend] = None

    # ---- dispatch plane ----

    def _mesh_for(self, lay: Layout):
        key = (lay.wide, lay.dp, lay.minor)
        with self._mesh_lock:
            mesh = self._meshes.get(key)
            if mesh is None:
                from chunky_bits_tpu.parallel import mesh as mesh_mod

                n = lay.dp * lay.minor
                if lay.wide:
                    mesh = mesh_mod.make_stripe_mesh(n, dp=lay.dp,
                                                     tp=lay.minor)
                else:
                    mesh = mesh_mod.make_mesh(n, dp=lay.dp, sp=lay.minor)
                self._meshes[key] = mesh
            return mesh

    def _materialize(self, handle: object) -> np.ndarray:
        from chunky_bits_tpu.ops.jax_backend import run_bounded_dispatch

        return run_bounded_dispatch(lambda: np.asarray(handle),
                                    "mesh erasure dispatch")

    def submit_apply(self, mat: np.ndarray, shards: np.ndarray,
                     on_block: Optional[Callable[[int, np.ndarray],
                                                 None]] = None
                     ) -> _MeshTicket:
        """Stage one ``[B, k, S]`` matrix apply into the dispatch
        window and return a ticket; the device starts on it while the
        caller stages more work (the feed-ahead surface
        ``encode_hash_batches`` and the batching layer ride).
        ``on_block(lo, arr)`` fires per materialized block during
        ``result()``, on the collecting thread."""
        from chunky_bits_tpu.errors import DeviceDispatchTimeout
        from chunky_bits_tpu.parallel import mesh as mesh_mod

        mat = np.ascontiguousarray(mat, dtype=np.uint8)
        shards = np.asarray(shards, dtype=np.uint8)
        b, k, s = shards.shape
        r = mat.shape[0]
        if r == 0 or b == 0 or s == 0:
            out = np.zeros((b, r, s), dtype=np.uint8)
            if on_block is not None and b:
                on_block(0, out)
            return _MeshTicket(self, mat, shards, [], [], None, b, s,
                               value=out)
        if self._device_dead:
            out = self._cpu_fallback().apply_matrix(mat, shards)
            if on_block is not None:
                on_block(0, out)
            return _MeshTicket(self, mat, shards, [], [], None, b, s,
                               value=out)
        lay = plan_layout(self.n_devices, b, k, s)
        mesh = self._mesh_for(lay)
        apply_fn = (mesh_mod.wide_apply_sharded if lay.wide
                    else mesh_mod.sharded_apply)
        padded = (np.pad(shards, ((0, 0), (0, 0), (0, lay.pad_s)))
                  if lay.pad_s else shards)
        per_item = k * (s + lay.pad_s) * 16
        budget = self.max_block_bytes // max(self.pipeline.depth, 1)
        block = max(lay.dp, budget // max(per_item, 1) // lay.dp * lay.dp)
        donate = self._on_tpu
        entries: list = []
        spans: list[tuple[int, int]] = []
        try:
            for lo in range(0, b, block):
                rows = min(block, b - lo)
                blk = padded[lo:lo + rows]
                pad_b = (-rows) % lay.dp
                if pad_b:
                    blk = np.pad(blk, ((0, pad_b), (0, 0), (0, 0)))
                else:
                    blk = np.ascontiguousarray(blk)
                entries.append(self.pipeline.submit(
                    lambda blk=blk: apply_fn(mesh, mat, blk,
                                             donate=donate),
                    self._materialize))
                spans.append((lo, rows))
        except (DispatchCancelled, DeviceDispatchTimeout) as err:
            # the window drained into a dead device mid-submit: degrade
            # and satisfy this call on the CPU (no on_block — callers
            # reconcile rows their callback never saw)
            self._degrade(err)
            out = self._cpu_fallback().apply_matrix(mat, shards)
            return _MeshTicket(self, mat, shards, [], [], None, b, s,
                               value=out)
        return _MeshTicket(self, mat, shards, entries, spans, on_block,
                           b, s)

    def apply_matrix(self, mat: np.ndarray, shards: np.ndarray,
                     on_block: Optional[Callable[[int, np.ndarray],
                                                 None]] = None
                     ) -> np.ndarray:
        """Sharded dispatch, blocking: stage through the pipeline and
        collect.  Byte-identical to every other backend; bounded by
        the per-materialization dispatch deadline."""
        return self.submit_apply(mat, shards, on_block=on_block).result()

    # ---- degrade plane ----

    def _degrade(self, err: BaseException) -> None:
        with self._mesh_lock:
            first = not self._device_dead
            self._device_dead = True
        if first:
            import warnings

            warnings.warn(
                f"{err}; DEGRADED to the native CPU codec for the rest "
                f"of this process (output stays byte-identical)",
                RuntimeWarning)
        self.pipeline.cancel()

    def _cpu_fallback(self) -> ErasureBackend:
        """The backend used once the mesh is marked dead mid-run."""
        if self._fallback is None:
            from chunky_bits_tpu.ops.backend import cpu_fallback_backend

            self._fallback = cpu_fallback_backend()
        return self._fallback

    # ---- ingest plane ----

    def encode_and_hash(self, mat: np.ndarray, shards: np.ndarray
                        ) -> tuple[np.ndarray, np.ndarray]:
        """Overlapped ingest, the jax backend's host-overlap shape
        (ops/jax_backend.py encode_and_hash) on the sharded dispatch:
        the mesh computes parity while the shared HostPipeline hashes
        the data rows, and each parity block is hashed as it lands
        while later blocks are still in flight.  Output is identical
        to the fused native engine's, bit for bit."""
        from chunky_bits_tpu.ops.backend import row_hasher
        from chunky_bits_tpu.parallel.host_pipeline import (
            get_host_pipeline,
            join_jobs,
        )

        shards = np.ascontiguousarray(shards, dtype=np.uint8)
        b, k, s = shards.shape
        r = mat.shape[0]
        hash_rows = row_hasher()
        data_digests = np.empty((b, k, 32), dtype=np.uint8)
        parity_digests = np.empty((b, r, 32), dtype=np.uint8)
        if b == 0 or s == 0 or r == 0:
            parity = np.zeros((b, r, s), dtype=np.uint8)
            hash_rows(shards, data_digests)
            hash_rows(parity, parity_digests)
            return parity, np.concatenate(
                [data_digests, parity_digests], axis=1)
        pipe = get_host_pipeline()
        jobs = list(pipe.hash_rows_jobs(shards, data_digests))
        covered = np.zeros(b, dtype=bool)

        def on_block(lo: int, arr: np.ndarray) -> None:
            # axis-0 slices of the C-contiguous digest array are
            # contiguous, so the hasher can write in place
            covered[lo:lo + arr.shape[0]] = True
            jobs.extend(pipe.hash_rows_jobs(
                arr, parity_digests[lo:lo + arr.shape[0]]))

        parity = self.apply_matrix(mat, shards, on_block=on_block)
        join_jobs(jobs)
        if not covered.all():
            # rows the callback never saw (a mid-run degrade's CPU
            # recompute) are hashed from the parity actually returned
            idx = np.flatnonzero(~covered)
            rest = np.empty((len(idx), r, 32), dtype=np.uint8)
            hash_rows(np.ascontiguousarray(parity[idx]), rest)
            parity_digests[idx] = rest
        return parity, np.concatenate([data_digests, parity_digests],
                                      axis=1)
