"""Double-buffered async device-dispatch window.

JAX dispatch is asynchronous: calling a jitted function enqueues the
H2D transfer and the kernel and returns a future-like device array;
the host only blocks when it materializes the result (``np.asarray``).
``JaxBackend._pipelined_blocks`` exploits that locally for one
``apply_matrix`` call; this module lifts the same discipline into a
standalone, thread-safe window so a backend can keep it warm ACROSS
calls — block k+1's H2D and the host hash stage run while block k
computes and block k-1 drains D2H (the classic double buffer, depth 2).

The pipeline is deliberately device-agnostic: ``submit`` takes an
``issue`` thunk (non-blocking enqueue — ``device_put`` + jitted call)
and a ``materialize`` function (the blocking D2H wait, which callers
wrap in ``jax_backend.run_bounded_dispatch`` so the degrade-never-hang
deadline applies per materialization).  That keeps this module free of
jax imports and unit-testable with plain callables.

Ordering is FIFO: materializations happen oldest-first, so the window
never holds more than ``depth`` un-materialized dispatches and device
memory stays bounded (each in-flight bit-plane dispatch costs ~16x its
byte size).  ``cancel()`` is the degrade path: it drops every pending
device reference without blocking on the (presumed dead) device;
cancelled entries raise :class:`DispatchCancelled` from ``result`` so
callers recompute on the CPU fallback — cancel is safe at any point,
including with a materialization parked on a watchdog thread.

Overlap is counted, not assumed: ``stats()`` exposes how many submits
found the window busy (``submits_while_busy`` — the feed-ahead events)
and the deepest window (``max_inflight``), plus host seconds spent in
callbacks while dispatches were in flight (``host_overlap_s``, fed by
the mesh backend's block callbacks).  bench --config 17 asserts these
in-run as the platform-independent overlap proof.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Optional

#: classic double buffer: one dispatch computing, one staging
DEFAULT_DEPTH = 2

_PENDING, _DONE, _FAILED, _CANCELLED = range(4)


class DispatchCancelled(RuntimeError):
    """Raised by ``result`` for entries dropped by ``cancel()`` — the
    caller's signal to recompute that work on the CPU fallback."""


@dataclass
class DispatchStats:
    """Counter snapshot; see module docstring for field semantics."""

    submitted: int = 0
    completed: int = 0
    cancelled: int = 0
    max_inflight: int = 0
    submits_while_busy: int = 0
    host_overlap_s: float = 0.0


class _Entry:
    __slots__ = ("handle", "materialize", "state", "value", "error")

    def __init__(self, materialize: Callable[[object], object]) -> None:
        self.handle: object = None
        self.materialize = materialize
        self.state = _PENDING
        self.value: object = None
        self.error: Optional[BaseException] = None


class DispatchPipeline:
    """Bounded FIFO window of in-flight device dispatches.

    ``depth`` is the number of un-materialized dispatches the window
    may hold after a submit returns: 2 (default) is the double buffer,
    1 keeps a single dispatch in flight, 0 disables overlap entirely
    (every submit materializes synchronously — the bench A/B's "off"
    leg).  ``None`` reads ``tunables.dispatch_depth()``
    ($CHUNKY_BITS_TPU_DISPATCH_DEPTH) at construction.

    Thread-safe via one coarse lock: a materialization holds the lock,
    so concurrent submitters queue behind it — acceptable because the
    device is the serial resource anyway, and required for the FIFO
    memory bound.  NOT loop-bound: batcher worker threads
    (asyncio.to_thread) and sync callers share one instance.
    """

    def __init__(self, depth: Optional[int] = None,
                 name: str = "dispatch") -> None:
        if depth is None:
            from chunky_bits_tpu.cluster.tunables import dispatch_depth

            depth = dispatch_depth(default=DEFAULT_DEPTH)
        self.depth = max(0, int(depth))
        self.name = name
        self._lock = threading.Lock()
        self._window: list[_Entry] = []
        self._stats = DispatchStats()

    def submit(self, issue: Callable[[], object],
               materialize: Callable[[object], object]) -> _Entry:
        """Issue a dispatch and admit it to the window, materializing
        the oldest entries first if the window would exceed ``depth``.
        ``issue`` must be a non-blocking enqueue; its return value is
        the handle later passed to ``materialize``."""
        with self._lock:
            st = self._stats
            st.submitted += 1
            if self._window:
                st.submits_while_busy += 1
            entry = _Entry(materialize)
            entry.handle = issue()
            self._window.append(entry)
            st.max_inflight = max(st.max_inflight, len(self._window))
            while len(self._window) > self.depth:
                self._materialize_oldest_locked()
            return entry

    def result(self, entry: _Entry) -> object:
        """Block until ``entry`` is materialized (draining everything
        older first) and return its value; re-raises a stored
        materialization error, :class:`DispatchCancelled` for dropped
        entries."""
        with self._lock:
            while entry.state == _PENDING:
                self._materialize_oldest_locked()
            if entry.state == _CANCELLED:
                raise DispatchCancelled(
                    f"{self.name}: dispatch cancelled before completion")
            if entry.state == _FAILED:
                raise entry.error  # type: ignore[misc]
            return entry.value

    def drain(self) -> None:
        """Materialize every pending entry (oldest first).  The flush
        used by tests and shutdown paths; errors propagate like
        ``result``'s."""
        with self._lock:
            while self._window:
                self._materialize_oldest_locked()

    def cancel(self) -> None:
        """Drop every pending entry without touching the device — the
        degrade path after a dispatch timeout.  Never blocks."""
        with self._lock:
            for e in self._window:
                e.state = _CANCELLED
                e.handle = None
            self._stats.cancelled += len(self._window)
            self._window.clear()

    @property
    def inflight(self) -> int:
        with self._lock:
            return len(self._window)

    def note_host_overlap(self, seconds: float) -> None:
        """Record host-side staging/callback time spent while at least
        one dispatch was in flight (the bench overlap span)."""
        with self._lock:
            self._stats.host_overlap_s += seconds

    def stats(self) -> DispatchStats:
        with self._lock:
            return DispatchStats(**vars(self._stats))

    def _materialize_oldest_locked(self) -> None:
        e = self._window.pop(0)
        try:
            e.value = e.materialize(e.handle)
            e.state = _DONE
            self._stats.completed += 1
        except BaseException as err:
            # A failed materialization (DeviceDispatchTimeout: the
            # device died mid-run) poisons the whole window — younger
            # dispatches sit behind the same dead device, and blocking
            # on them would re-pay the timeout each.  Cancel them and
            # surface the error to whoever is driving the drain; their
            # owners recompute on CPU via DispatchCancelled.
            e.state = _FAILED
            e.error = err
            for rest in self._window:
                rest.state = _CANCELLED
                rest.handle = None
            self._stats.cancelled += len(self._window)
            self._window.clear()
            raise
