"""The core GF(2^8) bit-plane transform, shared by every JAX path.

``apply_bitplane(m2, shards)`` computes
``out[b, r, s] = pack((m2 @ unpack(shards)) mod 2)`` where ``m2`` is a
0/1 bf16 matrix from ``gf256.expand_to_bit_matrix``.  Used by the
single-device einsum path (ops/jax_backend.py), the mesh-sharded path
(parallel/mesh.py) and the driver entry; the Pallas kernel
(ops/pallas_kernels.py) is the fused equivalent of this exact function.
"""

from __future__ import annotations


def bitplane_acc(m2, shards):
    """Raw bit-plane accumulation: int32 [B, r8, S] of popcounts, *before*
    the mod-2.  Split out so the wide-stripe mesh path (parallel/mesh.py)
    can ``psum`` partial accumulations across chips — GF(2^8) addition is
    XOR, so summing integer popcounts over chips and taking mod-2 once at
    the end is exact."""
    import jax.numpy as jnp

    b, k, s = shards.shape
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (shards[:, :, None, :] >> shifts[None, None, :, None]) & 1
    bits = bits.reshape(b, k * 8, s).astype(jnp.bfloat16)
    acc = jnp.einsum("rk,bks->brs", m2, bits,
                     preferred_element_type=jnp.float32)
    return acc.astype(jnp.int32)


def pack_acc(acc):
    """Pack int32 popcounts [B, r8, S] into bytes [B, r, S] via mod-2."""
    import jax.numpy as jnp

    shifts = jnp.arange(8, dtype=jnp.uint8)
    b, r8, s = acc.shape
    out_bits = acc & 1
    out_bits = out_bits.reshape(b, r8 // 8, 8, s)
    packed = jnp.sum(out_bits << shifts[None, None, :, None], axis=2)
    return packed.astype(jnp.uint8)


def apply_bitplane(m2, shards):
    """m2: bf16 [r8, k8] of 0/1; shards: uint8 [B, k, S] -> uint8 [B, r, S].

    Products are 0/1 and the contraction length is <= 2048, so bf16 inputs
    with f32 accumulation are exact; the mod-2 keeps only the XOR parity.
    """
    return pack_acc(bitplane_acc(m2, shards))
