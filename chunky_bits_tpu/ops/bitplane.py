"""The core GF(2^8) bit-plane transform, shared by every JAX path.

``apply_bitplane(m2, shards)`` computes
``out[b, r, s] = pack((m2 @ unpack(shards)) mod 2)`` where ``m2`` is a
0/1 bf16 matrix from ``gf256.expand_to_bit_matrix``.  Used by the
single-device einsum path (ops/jax_backend.py), the mesh-sharded path
(parallel/mesh.py) and the driver entry; the Pallas kernel
(ops/pallas_kernels.py) is the fused equivalent of this exact function.
"""

from __future__ import annotations


def apply_bitplane(m2, shards):
    """m2: bf16 [r8, k8] of 0/1; shards: uint8 [B, k, S] -> uint8 [B, r, S].

    Products are 0/1 and the contraction length is <= 2048, so bf16 inputs
    with f32 accumulation are exact; the mod-2 keeps only the XOR parity.
    """
    import jax.numpy as jnp

    b, k, s = shards.shape
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (shards[:, :, None, :] >> shifts[None, None, :, None]) & 1
    bits = bits.reshape(b, k * 8, s).astype(jnp.bfloat16)
    acc = jnp.einsum("rk,bks->brs", m2, bits,
                     preferred_element_type=jnp.float32)
    out_bits = acc.astype(jnp.int32) & 1
    out_bits = out_bits.reshape(b, m2.shape[0] // 8, 8, s)
    packed = jnp.sum(out_bits << shifts[None, None, :, None], axis=2)
    return packed.astype(jnp.uint8)
